# %% [markdown]
# # ONNX inference + image featurization pipeline
#
# Reference notebooks: `notebooks/features/onnx/` and
# `notebooks/features/opencv/` — import an ONNX model, transform images
# through XLA kernels, featurize with a headless CNN, and train a
# classifier on the features (the ImageFeaturizer -> LightGBM demo).

# %%
import numpy as np

from synapseml_tpu import Pipeline, Table
from synapseml_tpu.dl import ImageFeaturizer
from synapseml_tpu.gbdt import LightGBMClassifier
from synapseml_tpu.image import ImageTransformer
from synapseml_tpu.models import build_model_bytes
from synapseml_tpu.onnx import OnnxFunction

# %% raw ONNX execution: the importer turns bytes into a jittable function
fn = OnnxFunction(build_model_bytes("ResNet18", num_classes=10))
imgs = np.random.default_rng(0).normal(size=(4, 3, 224, 224)).astype(np.float32)
out = fn({"data": imgs})
print("logits:", np.asarray(out["logits"]).shape,
      "features:", np.asarray(out["features"]).shape)

# %% image preprocessing as a pipeline stage (resize/crop/flip on XLA)
rng = np.random.default_rng(1)
n = 16
raw = np.empty(n, dtype=object)
labels = np.zeros(n)
for i in range(n):
    base = rng.integers(0, 255, size=(48, 64, 3)).astype(np.uint8)
    if i % 2:  # class 1: bright center square
        base[16:32, 24:40] = 250
        labels[i] = 1.0
    raw[i] = base
t = Table({"image": raw, "label": labels})

pre = ImageTransformer(input_col="image", output_col="image", stages=[
    {"action": "resize", "height": 32, "width": 32},
    {"action": "centercrop", "width": 28, "height": 28},
])
print("stages:", pre.stages)

# %% featurize -> classify, end to end
pipe = Pipeline(stages=[
    pre,
    ImageFeaturizer(model_bytes=build_model_bytes("ResNet18", num_classes=4),
                    input_col="image", output_col="features"),
    LightGBMClassifier(num_iterations=5, num_leaves=4, min_data_in_leaf=2),
])
model = pipe.fit(t)
scored = model.transform(t)
train_acc = (np.asarray(scored["prediction"]) == labels).mean()
print("train accuracy:", train_acc)
