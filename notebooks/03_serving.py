# %% [markdown]
# # Model serving: any pipeline as a low-latency web service
#
# Reference notebook: `notebooks/features/spark_serving/` — the same
# drain -> transform -> reply contract, with a micro-batch engine and a
# push-mode continuous engine (sub-millisecond p50 at idle).

# %%
import json
import urllib.request

import numpy as np

from synapseml_tpu import Table
from synapseml_tpu.core import Transformer
from synapseml_tpu.gbdt import LightGBMClassifier
from synapseml_tpu.io.serving import ServingServer, serve, string_to_response
from synapseml_tpu.io.serving_v2 import ContinuousServingEngine

# %% train something worth serving
rng = np.random.default_rng(0)
x = rng.normal(size=(2000, 4))
y = (x[:, 0] > 0).astype(float)
model = LightGBMClassifier(num_iterations=10, num_leaves=7).fit(
    Table({"features": x, "label": y}))


class ScoreReply(Transformer):
    """JSON {features: [...]} in -> JSON {probability} out."""

    def _transform(self, table):
        reqs = table["request"]
        feats = np.array([json.loads(r.entity)["features"] for r in reqs])
        scored = model.transform(Table({"features": feats}))
        out = np.empty(len(reqs), dtype=object)
        for i in range(len(reqs)):
            out[i] = {"probability": float(scored["probability"][i, 1])}
        return table.with_column("reply", out)


# %% continuous (push-mode) serving
srv = ServingServer(port=0)
engine = ContinuousServingEngine(srv, ScoreReply()).start()
req = urllib.request.Request(
    srv.address, data=json.dumps({"features": [2.0, 0.0, 0.0, 0.0]}).encode(),
    method="POST")
with urllib.request.urlopen(req, timeout=10) as resp:
    body = json.loads(resp.read())
print("served probability:", body["probability"])
assert body["probability"] > 0.5
print("p50 latency so far:", engine.latency_p50())
engine.stop()

# %% micro-batch engine via the one-liner
engine = serve(ScoreReply(), port=0)
req = urllib.request.Request(
    engine.server.address,
    data=json.dumps({"features": [-2.0, 0.0, 0.0, 0.0]}).encode(),
    method="POST")
with urllib.request.urlopen(req, timeout=10) as resp:
    print("microbatch:", json.loads(resp.read()))
engine.stop()
