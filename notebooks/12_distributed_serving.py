# %% [markdown]
# # Distributed serving: a real OS-process fleet with failover
#
# The serving tier at its full depth (reference: Spark Serving's
# load-balanced continuous server + the `HTTPv2Suite` fault contract —
# kill a worker mid-stream and the service keeps answering): a trained
# pipeline is saved, N worker PROCESSES each load a copy and serve it, and
# a routing front door round-robins requests, evicting dead workers and
# failing requests over.
#
# Delivery contract (r5): timeouts never re-send non-idempotent requests
# (a slow worker may still finish — re-sending a POST would double its side
# effects); worker DEATH fails over, the reference's kill-a-worker
# behavior.

# %%
import json
import urllib.request

import numpy as np

from synapseml_tpu import Table
from synapseml_tpu.core.stage import Transformer
from synapseml_tpu.gbdt import LightGBMClassifier
from synapseml_tpu.io.serving import string_to_response

rng = np.random.default_rng(0)
x = rng.normal(size=(2000, 6))
y = (x[:, 0] - 0.5 * x[:, 3] > 0).astype(np.float64)
model = LightGBMClassifier(num_iterations=15, num_leaves=15).fit(
    Table({"features": x, "label": y}))


class Score(Transformer):
    """request JSON {"features": [...]} -> {"probability": p}"""

    def _transform(self, table):
        reqs = table["request"]
        feats = np.array([json.loads(r.entity)["features"] for r in reqs])
        scored = model.transform(Table({"features": feats}))
        out = np.empty(len(reqs), dtype=object)
        for i in range(len(reqs)):
            out[i] = {"probability": float(scored["probability"][i, 1])}
        return table.with_column("reply", out)


# %% single-process continuous serving first (sub-ms p50)
from synapseml_tpu.io.serving import ServingServer
from synapseml_tpu.io.serving_v2 import ContinuousServingEngine

srv = ServingServer(port=0)
eng = ContinuousServingEngine(srv, Score()).start()


def hit(addr, row):
    req = urllib.request.Request(
        addr, data=json.dumps({"features": list(map(float, row))}).encode(),
        method="POST", headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=15) as r:
        return json.loads(r.read())


print("continuous:", hit(srv.address, x[0]))
eng.stop()

# %% a REAL process fleet behind the routing front door
# (workers are `python -m synapseml_tpu.io.serving_worker` subprocesses,
# each serving a saved copy of the pipeline)
import os
import sys

repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, repo)

from synapseml_tpu.io.serving_v2 import ProcessServingFleet

# ProcessServingFleet needs the stage importable by module path IN THE
# WORKER PROCESS (the fleet puts the repo root on the workers' PYTHONPATH);
# use the pid-echo stage shipped with the repo's tests
from tests.serving_fault_stage import PidEchoReply

fleet = ProcessServingFleet(PidEchoReply(), n_workers=3,
                            import_modules=["tests.serving_fault_stage"],
                            reply_timeout=20.0)
try:
    def raw_hit(addr):
        req = urllib.request.Request(addr + "/", data=b"ping", method="POST")
        with urllib.request.urlopen(req, timeout=20) as r:
            return r.read().decode()

    pids = {raw_hit(fleet.address) for _ in range(9)}
    print("requests served by", len(pids), "distinct worker processes")
    assert len(pids) == 3

    # %% kill a worker mid-service: the router evicts it and the service
    # keeps answering (reference HTTPv2Suite kill-a-worker contract)
    dead = fleet.kill_worker(0)
    answers = [raw_hit(fleet.address) for _ in range(9)]
    print("after kill:", len(set(answers)), "workers still answering;",
          "evicted:", fleet.router.workers_evicted)
    assert len(set(answers)) == 2
    assert dead not in fleet.routing_table()["default"]
finally:
    fleet.stop()
print("fleet stopped cleanly")
