# %% [markdown]
# # Cognitive services on pipelines
#
# Reference notebooks: `notebooks/features/cognitive_services/`. Service
# transformers compose into ordinary pipelines: pack per-row params, call
# the service with bounded concurrency and retries, parse JSON, split
# errors into their own column. This demo runs against an in-notebook stub
# service (the environment is zero-egress); swap `url=` for a real
# endpoint + key to run live.

# %% stand up a local stub that answers like the text-analytics API
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from synapseml_tpu import Table


class Stub(BaseHTTPRequestHandler):
    def do_POST(self):
        body = json.loads(self.rfile.read(
            int(self.headers.get("Content-Length", 0)) or 0) or b"{}")
        text = body["documents"][0]["text"]
        score = 0.9 if "love" in text else 0.1
        out = json.dumps({"documents": [{
            "id": "0", "sentiment": "positive" if score > 0.5 else "negative",
            "confidenceScores": {"positive": score, "negative": 1 - score},
        }]}).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(out)))
        self.end_headers()
        self.wfile.write(out)

    def log_message(self, *a):
        pass


httpd = ThreadingHTTPServer(("127.0.0.1", 0), Stub)
threading.Thread(target=httpd.serve_forever, daemon=True).start()
url = f"http://127.0.0.1:{httpd.server_address[1]}/sentiment"

# %% sentiment over a column of reviews
from synapseml_tpu.cognitive import TextSentiment

reviews = Table({"text": np.array(
    ["I love this framework", "terrible latency", "love the mesh API"],
    dtype=object)})
ts = TextSentiment(url=url, subscription_key="key", output_col="sentiment")
out = ts.transform(reviews)
labels = [d["documents"][0]["sentiment"] for d in out["sentiment"]]
print("sentiments:", labels)
assert labels == ["positive", "negative", "positive"]
assert all(e is None for e in out["errors"])

# %% error columns: a dead endpoint lands in `errors`, rows keep flowing
dead = TextSentiment(url="http://127.0.0.1:1/nope", subscription_key="key",
                     backoffs=[], output_col="sentiment")
bad = dead.transform(reviews)
print("error rows:", sum(e is not None for e in bad["errors"]))
assert all(v is None for v in bad["sentiment"])

# %% pipe the parsed service output into downstream ML
from synapseml_tpu.gbdt import LightGBMClassifier

scored = out.with_column(
    "features",
    np.array([[d["documents"][0]["confidenceScores"]["positive"]]
              for d in out["sentiment"]]))
scored = scored.with_column("label",
                            np.array([1.0, 0.0, 1.0]))
model = LightGBMClassifier(num_iterations=5, min_data_in_leaf=1).fit(scored)
print("downstream predictions:",
      np.asarray(model.transform(scored)["prediction"]))

httpd.shutdown()
