# %% [markdown]
# # Online linear learning (Vowpal-Wabbit-equivalent)
#
# Reference notebooks: `notebooks/features/vw/` — classification with the
# VW featurizer, quadratic interactions, quantile regression, and a
# contextual bandit. The engine is a jitted AdaGrad-SGD learner over
# murmur-hashed sparse features; under a mesh, weights `pmean`-average at
# pass boundaries (the reference's spanning-tree AllReduce as an XLA
# collective).

# %%
import numpy as np

from synapseml_tpu import Pipeline, Table
from synapseml_tpu.vw import (VowpalWabbitClassifier,
                              VowpalWabbitContextualBandit,
                              VowpalWabbitFeaturizer,
                              VowpalWabbitInteractions,
                              VowpalWabbitRegressor)

rng = np.random.default_rng(0)
n = 4000

# %% adult-income-style classification from mixed columns
age = rng.uniform(18, 80, n)
hours = rng.uniform(5, 60, n)
city = rng.choice(["nyc", "sf", "chi"], n).astype(object)
y = ((age * 0.03 + hours * 0.05 + (city == "sf") * 1.0
      + rng.normal(0, 0.5, n)) > 3.2).astype(float)
t = Table({"age": age, "hours": hours, "city": city, "label": y})

feat = VowpalWabbitFeaturizer(input_cols=["age", "hours", "city"],
                              output_col="features")
model = Pipeline([feat, VowpalWabbitClassifier(
    num_passes=5, pass_through_args="--loss_function logistic -l 0.8")]).fit(t)
pred = model.transform(t)
acc = float((np.asarray(pred["prediction"]) == y).mean())
print("train accuracy:", round(acc, 3))
assert acc > 0.8

# %% quadratic interactions (VW -q): an XOR-style target no linear model
# over the raw namespaces can fit — the cross features make it linear
a = rng.choice(["u", "v"], n).astype(object)
b = rng.choice(["u", "v"], n).astype(object)
y_xor = (a == b).astype(float)
tx = Table({"a": a, "b": b, "label": y_xor})
fa = VowpalWabbitFeaturizer(input_cols=["a"], output_col="fa")
fb = VowpalWabbitFeaturizer(input_cols=["b"], output_col="fb")
crossed = VowpalWabbitInteractions(input_cols=["fa", "fb"],
                                   output_col="features")
xor_model = Pipeline([fa, fb, crossed,
                      VowpalWabbitClassifier(num_passes=8)]).fit(tx)
xor_acc = float((np.asarray(xor_model.transform(tx)["prediction"])
                 == y_xor).mean())
print("xor accuracy with interactions:", round(xor_acc, 3))
assert xor_acc > 0.95

# %% quantile regression (VW --quantile_tau)
yr = age * 0.02 + rng.exponential(1.0, n)
tr = Table({"age": age, "hours": hours, "label": yr})
reg = Pipeline([
    VowpalWabbitFeaturizer(input_cols=["age", "hours"], output_col="features"),
    VowpalWabbitRegressor(
        num_passes=30,
        pass_through_args="--loss_function quantile --quantile_tau 0.9 -l 1.0"),
]).fit(tr)
q90 = np.asarray(reg.transform(tr)["prediction"])
cover = float((yr <= q90).mean())
print("fraction of labels under the q90 prediction:", round(cover, 3))
assert 0.8 < cover < 0.99

# %% contextual bandit: learn which action is cheapest per context.
# Per-action features cross context x action (VW users add -q sa for
# this); the model outputs an exploration distribution over actions.
n_cb, n_actions = 1500, 3
ctx = rng.integers(0, n_actions, n_cb)  # best action == context id
shared = np.empty(n_cb, dtype=object)
action_feats = np.empty(n_cb, dtype=object)
for i in range(n_cb):
    shared[i] = (np.array([100 + ctx[i]], np.uint32), np.ones(1, np.float32))
    action_feats[i] = [
        (np.array([200 + a, 1000 + 10 * ctx[i] + a], np.uint32),
         np.ones(2, np.float32)) for a in range(n_actions)]
chosen = rng.integers(1, n_actions + 1, n_cb)          # 1-based, logged uniform
cost = (chosen - 1 != ctx).astype(np.float32)          # wrong action costs 1
cb_table = Table({"shared": shared, "features": action_feats,
                  "chosenAction": chosen, "label": cost,
                  "probability": np.full(n_cb, 1 / n_actions)})
cb = VowpalWabbitContextualBandit(num_passes=5).fit(cb_table)
picked = np.array([int(np.argmax(p))
                   for p in cb.transform(cb_table)["prediction"]])
cb_acc = float((picked == ctx).mean())
print("bandit picks the best action:", round(cb_acc, 3))
assert cb_acc > 0.9
