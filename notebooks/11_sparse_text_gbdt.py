# %% [markdown]
# # Hashed-text GBDT: sparse training, SHAP, and boosting variants
#
# The canonical sparse workload (reference: sparse vector columns flowing
# from text featurization into LightGBM — `DatasetAggregator.scala` builds
# CSR native datasets; `LightGBMBooster.predictForCSR` scores them): hash
# raw text with the VW featurizer, train the GBDT engine STRAIGHT FROM CSR
# (no densify — the bin matrix at 2^14 hashed slots would be ~gigabytes),
# then explain predictions with exact TreeSHAP computed from the sparse
# rows.
#
# TPU design notes: the sparse engine stores the binned matrix as a
# (feature, bin)-sorted entry triple on device; histograms are scatter-free
# (panel gather + chunked cumsum + prefix diffs) because TPU scatter-adds
# collision-serialize.

# %%
import numpy as np

from synapseml_tpu import Pipeline, Table
from synapseml_tpu.gbdt import LightGBMClassifier
from synapseml_tpu.vw.featurizer import VowpalWabbitFeaturizer

rng = np.random.default_rng(0)
pos_words = ["great", "excellent", "wonderful", "superb"]
neg_words = ["awful", "terrible", "poor", "dreadful"]
filler = [f"word{i}" for i in range(300)]
texts, labels = [], []
for _ in range(1500):
    y = int(rng.random() < 0.5)
    words = list(rng.choice(pos_words if y else neg_words, size=2)) + \
        list(rng.choice(filler, size=8))
    rng.shuffle(words)
    texts.append(" ".join(words))
    labels.append(float(y))
t = Table({"text": np.array(texts, object), "label": np.array(labels)})

# %% hashed featurization -> sparse GBDT, one pipeline
pipe = Pipeline(stages=[
    VowpalWabbitFeaturizer(input_cols=["text"], string_split_cols=["text"]),
    LightGBMClassifier(num_iterations=30, num_leaves=15, min_data_in_leaf=5,
                       sparse_num_bits=14),
])
model = pipe.fit(t)
p = np.asarray(model.transform(t)["probability"])[:, 1]
auc_rank = np.argsort(np.argsort(p))
print("train accuracy:", ((p > 0.5) == (np.array(labels) > 0.5)).mean())
booster = model.stages[-1].booster
print("hashed feature space:", booster.mapper.n_features)

# %% exact TreeSHAP straight from the sparse rows (r5)
# contributions come back SPARSE — per-row (indices, values) over the used
# features + the expected-value slot — because a dense (n, 2^14+1) panel is
# exactly what the sparse path exists to avoid
clf = model.stages[-1]
clf.features_shap_col = "shap"
shap_col = model.transform(t)["shap"]
idx0, val0 = shap_col[0]
print("row 0 touches", len(idx0), "features; sum(contrib) =",
      round(float(val0.sum()), 4))

# %% boosting variants run sparse too: dart (device drop/re-add replay)
from synapseml_tpu.gbdt.boost import train
from synapseml_tpu.gbdt.sparse import CSRMatrix

feats = model.stages[0].transform(t)["features"]
X = CSRMatrix.from_pairs(feats, num_bits=14)
b_dart = train({"objective": "binary", "boosting": "dart",
                "num_iterations": 15, "num_leaves": 15,
                "min_data_in_leaf": 5, "drop_rate": 0.3}, X,
               np.array(labels))
print("dart trees:", b_dart.num_trees,
      "distinct scales:", len(set(np.round(b_dart.tree_scale, 6))))

# %% distributed: the SAME sparse fit over an 8-device mesh
# (per-shard entry blocks, psum'd child histograms)
import jax
from jax.sharding import Mesh

if len(jax.devices()) >= 8:
    mesh = Mesh(np.array(jax.devices()[:8]), ("data",))
    b_mesh = train({"objective": "binary", "num_iterations": 10,
                    "num_leaves": 15, "min_data_in_leaf": 5},
                   X, np.array(labels), mesh=mesh)
    b_one = train({"objective": "binary", "num_iterations": 10,
                   "num_leaves": 15, "min_data_in_leaf": 5},
                  X, np.array(labels))
    diff = np.abs(b_mesh.predict(X) - b_one.predict(X)).max()
    print("mesh vs single-replica max prediction diff:", float(diff))
    assert diff < 1e-4
