# %% [markdown]
# # HyperparameterTuning: TuneHyperparameters + FindBestModel
#
# Reference notebook: `notebooks/features/other/HyperParameterTuning -
# Fighting Breast Cancer` — build a search space over an estimator's
# params, run parallel random search, and keep the winning model; then pick
# among several FITTED models with FindBestModel.

# %%
import numpy as np

from synapseml_tpu import Table
from synapseml_tpu.automl import (DiscreteHyperParam, FindBestModel,
                                  HyperparamBuilder, RangeHyperParam,
                                  TuneHyperparameters)
from synapseml_tpu.gbdt import LightGBMClassifier

# %% a tabular diagnosis-style dataset (nonlinear decision surface)
rng = np.random.default_rng(0)
n = 3000
x = rng.normal(size=(n, 8))
y = ((x[:, 0] * x[:, 1] > 0.2) | (x[:, 2] ** 2 > 1.5)).astype(np.float64)
tr = Table({"features": x[:2400], "label": y[:2400]})
te = Table({"features": x[2400:], "label": y[2400:]})

# %% the search space (reference HyperparamBuilder)
space = (HyperparamBuilder()
         .add_hyperparam("num_leaves", DiscreteHyperParam([7, 15, 31]))
         .add_hyperparam("learning_rate", RangeHyperParam(0.05, 0.3))
         .add_hyperparam("num_iterations", DiscreteHyperParam([20, 40]))
         .build())

# %% parallel random search, AUC on an internal validation split
tuner = TuneHyperparameters(
    models=LightGBMClassifier(min_data_in_leaf=5), hyperparams=space,
    search_mode="random", number_of_runs=8, parallelism=4,
    evaluation_metric="auc", seed=7)
tuned = tuner.fit(tr)
print("best params:", {k: round(v, 3) if isinstance(v, float) else v
                       for k, v in tuned.best_params.items()})
print("best validation AUC:", round(tuned.best_metric, 4))
assert tuned.best_metric > 0.85
assert len(tuned.history) == 8  # every evaluation recorded

# %% the tuned model is a drop-in transformer
pred = np.asarray(tuned.transform(te)["probability"])[:, 1]
acc = float(((pred > 0.5) == y[2400:]).mean())
print("held-out accuracy:", round(acc, 4))
assert acc > 0.85

# %% FindBestModel across separately-fitted candidates
candidates = [
    LightGBMClassifier(num_iterations=5, num_leaves=4).fit(tr),
    LightGBMClassifier(num_iterations=40, num_leaves=15,
                       min_data_in_leaf=5).fit(tr),
]
best = FindBestModel(models=candidates, evaluation_metric="auc").fit(te)
print("winner metric:", round(best.best_metric, 4))
# the stronger candidate must win
assert best.best_model is candidates[1]
