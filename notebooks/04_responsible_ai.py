# %% [markdown]
# # Responsible AI: explainers + data balance analysis
#
# Reference notebooks: `notebooks/features/responsible_ai/` — model-agnostic
# LIME/KernelSHAP explanations, ICE plots, and dataset balance measures.

# %%
import numpy as np

from synapseml_tpu import Table
from synapseml_tpu.explainers import VectorSHAP
from synapseml_tpu.exploratory import (
    AggregateBalanceMeasure,
    DistributionBalanceMeasure,
    FeatureBalanceMeasure,
)
from synapseml_tpu.gbdt import LightGBMClassifier

# %% train a model whose decisions we want to explain
rng = np.random.default_rng(0)
n = 3000
x = rng.normal(size=(n, 5))
y = (2 * x[:, 0] - x[:, 1] > 0).astype(float)  # features 0 and 1 matter
t = Table({"features": x, "label": y})
model = LightGBMClassifier(num_iterations=20, num_leaves=15).fit(t)

# %% KernelSHAP attributions: features 0/1 should dominate
shap = VectorSHAP(
    model=model, input_col="features", output_col="shap",
    target_col="probability", target_classes=[1],
    background_data=Table({"features": x[:100]}), seed=7)
explained = shap.transform(Table({"features": x[:20]}))
mean_abs = np.abs(np.stack(
    [np.asarray(v, dtype=np.float64)[0, 1:] for v in explained["shap"]]
)).mean(0)
print("mean |shap| per feature:", np.round(mean_abs, 4))
assert mean_abs[0] > mean_abs[2] and mean_abs[1] > mean_abs[3]

# %% dataset balance measures over a sensitive column
gender = np.where(rng.random(n) < 0.7, "M", "F").astype(object)
bt = Table({"gender": gender, "label": y})
fbm = FeatureBalanceMeasure(sensitive_cols=["gender"]).transform(bt)
print("feature balance (M vs F):", fbm["FeatureBalanceMeasure"][0]["dp"])
dbm = DistributionBalanceMeasure(sensitive_cols=["gender"]).transform(bt)
print("distribution vs uniform:", dbm["DistributionBalanceMeasure"][0])
abm = AggregateBalanceMeasure(sensitive_cols=["gender"]).transform(bt)
print("aggregate:", abm["AggregateBalanceMeasure"][0])
