# %% [markdown]
# # Explaining image model decisions: ImageLIME + ImageSHAP
#
# Reference notebooks: `notebooks/features/responsible_ai/` (Image
# Explainers) — superpixel the image, perturb superpixels on/off, and fit a
# local surrogate to attribute the model's output to image regions.

# %%
import numpy as np

from synapseml_tpu import Table, Transformer
from synapseml_tpu.explainers import ImageLIME, ImageSHAP
from synapseml_tpu.explainers.superpixel import slic_superpixels

# %% a toy "classifier" whose decision comes from one image region:
# score = mean brightness of the top-left quadrant. The explainers don't
# know that; the attributions must rediscover it.
H = W = 48


class TopLeftBrightness(Transformer):
    input_col = "image"

    def _transform(self, table):
        scores = np.array([
            [float(np.mean(img[: H // 2, : W // 2]))]
            for img in table["image"]])
        return table.with_column("probability", scores)


rng = np.random.default_rng(0)
img = rng.uniform(0.4, 0.6, size=(H, W, 3))
img[: H // 2, : W // 2] += 0.35  # the bright region that drives the model
t = Table({"image": np.array([img], dtype=object)})
model = TopLeftBrightness()

# %% superpixels: the attribution units (SLIC, reference LIMEImageSampler)
spd = slic_superpixels(img, cell_size=12.0, modifier=20.0)
print("superpixels:", len(spd))

# %% LIME attributions per superpixel
lime = ImageLIME(model=model, input_col="image", output_col="weights",
                 target_col="probability", target_classes=[0],
                 cell_size=12.0, modifier=20.0, num_samples=150, seed=3)
w_lime = np.asarray(lime.transform(t)["weights"][0], dtype=np.float64)[0]

# %% SHAP attributions per superpixel
shap = ImageSHAP(model=model, input_col="image", output_col="shap",
                 target_col="probability", target_classes=[0],
                 cell_size=12.0, modifier=20.0, num_samples=150, seed=3)
w_shap = np.asarray(shap.transform(t)["shap"][0], dtype=np.float64)[0][1:]

# %% both must put their mass on superpixels inside the bright quadrant
centers = np.array([c.mean(axis=0) for c in spd.clusters])
in_region = (centers[:, 0] < H / 2) & (centers[:, 1] < W / 2)
for name, w in [("lime", w_lime), ("shap", w_shap)]:
    top = np.argsort(-np.abs(w))[: int(in_region.sum())]
    frac = in_region[top].mean()
    print(f"{name}: top-attribution superpixels in the true region: "
          f"{frac:.2f}")
    assert frac >= 0.7, (name, frac)
