# %% [markdown]
# # Recommendation, anomaly detection, and hyperparameter tuning
#
# Reference notebooks: `notebooks/features/other/` — SAR recommendations,
# isolation-forest anomaly scores, CyberML access anomalies, and
# TuneHyperparameters.

# %%
import numpy as np

from synapseml_tpu import Table
from synapseml_tpu.recommendation import (RankingAdapter, RankingEvaluator,
                                          SAR)

rng = np.random.default_rng(0)

# %% SAR: two taste groups; recommendations should stay in-group
users, items, ratings = [], [], []
for u in range(40):
    pool = range(0, 15) if u % 2 == 0 else range(15, 30)
    for it in rng.choice(list(pool), size=8, replace=False):
        users.append(u)
        items.append(int(it))
        ratings.append(float(rng.integers(3, 6)))
t = Table({"user": np.array(users, np.int64),
           "item": np.array(items, np.int64),
           "rating": np.array(ratings)})
model = SAR(support_threshold=1).fit(t)
recs = model.recommend_for_all_users(5, remove_seen=True)
print("user 0 recs:", recs["recommendations"][0])

ranked = RankingAdapter(k=5, recommender=SAR(support_threshold=1)).fit(t).transform(t)
print("ndcg@5:", RankingEvaluator(k=5, n_items=30).evaluate(ranked))

# %% isolation forest: score a contaminated cluster
from synapseml_tpu.isolationforest import IsolationForest

inliers = rng.normal(size=(500, 4))
outliers = rng.normal(size=(20, 4)) + 7.0
iso = IsolationForest(num_estimators=50, contamination=20 / 520,
                      random_seed=1).fit(Table({"features": np.vstack([inliers, outliers])}))
scored = iso.transform(Table({"features": np.vstack([inliers, outliers])}))
flagged = np.asarray(scored["predictedLabel"])[-20:]
print("outliers flagged:", int(flagged.sum()), "/ 20")

# %% CyberML: cross-group resource access is anomalous
from synapseml_tpu.cyber import AccessAnomaly

tenants, ausers, res = [], [], []
for u in range(12):
    pool = range(0, 5) if u < 6 else range(5, 10)
    for _ in range(15):
        tenants.append("t0")
        ausers.append(f"user{u}")
        res.append(f"res{rng.choice(list(pool))}")
tenants += ["t0", "t0"]
ausers += ["bridge", "bridge"]
res += ["res0", "res9"]
at = Table({"tenant": np.array(tenants, dtype=object),
            "user": np.array(ausers, dtype=object),
            "res": np.array(res, dtype=object)})
aa = AccessAnomaly(max_iter=10, rank_param=8).fit(at)
probe = Table({"tenant": np.array(["t0", "t0"], dtype=object),
               "user": np.array(["user0", "user0"], dtype=object),
               "res": np.array(["res1", "res8"], dtype=object)})
scores = np.asarray(aa.transform(probe)["anomaly_score"])
print("in-group score:", scores[0], " cross-group score:", scores[1])

# %% hyperparameter tuning
from synapseml_tpu.automl import TuneHyperparameters
from synapseml_tpu.gbdt import LightGBMClassifier

x = rng.normal(size=(2000, 6))
y = ((x[:, 0] > 0) ^ (x[:, 1] > 0)).astype(float)
tuner = TuneHyperparameters(
    models=LightGBMClassifier(),
    hyperparams={"num_leaves": [7, 31], "num_iterations": [10, 40]},
    search_mode="grid", evaluation_metric="auc", seed=0)
best = tuner.fit(Table({"features": x, "label": y}))
print("best auc:", best.best_metric, "params:", best.best_params)
