# %% [markdown]
# # LightGBM-style classification on TPU
#
# The flagship training path (reference notebook:
# `notebooks/features/lightgbm/LightGBM - Overview.ipynb`): fit a
# histogram-GBDT classifier, inspect eval metrics and feature importances,
# save/load, and run distributed over a device mesh.
#
# Notebooks in this repo are plain Python files with `# %%` cell markers —
# runnable end-to-end by the test suite (the reference runs its notebooks
# as E2E tests on Databricks; here `tests/test_notebooks.py` executes them).

# %%
import numpy as np

from synapseml_tpu import Table
from synapseml_tpu.gbdt import LightGBMClassifier

rng = np.random.default_rng(0)
n = 20_000
x = rng.normal(size=(n, 10))
y = (x[:, 0] + 0.5 * x[:, 1] * x[:, 2] > 0).astype(np.float64)
train_t = Table({"features": x[: n // 2], "label": y[: n // 2]})
test_t = Table({"features": x[n // 2:], "label": y[n // 2:]})

# %% train with validation-driven early stopping
clf = LightGBMClassifier(
    num_iterations=100, num_leaves=31, learning_rate=0.1,
    early_stopping_round=10, metric="auc",
    validation_indicator_col="is_val",
)
val_mask = np.zeros(n // 2, dtype=bool)
val_mask[-2000:] = True
model = clf.fit(train_t.with_column("is_val", val_mask))
print("best iteration:", model.booster.best_iteration)
print("last eval auc:", model.booster.evals_result[-1]["eval0_auc"])

# %% predict + evaluate
out = model.transform(test_t)
acc = (np.asarray(out["prediction"]) == y[n // 2:]).mean()
print("test accuracy:", round(float(acc), 4))
assert acc > 0.9

# %% feature importances + save/load
print("split importances:", model.get_feature_importances("split")[:5])
import tempfile, os

from synapseml_tpu import load_stage

path = os.path.join(tempfile.mkdtemp(), "model")
model.save(path)
reloaded = load_stage(path)
np.testing.assert_allclose(
    np.asarray(reloaded.transform(test_t)["probability"]),
    np.asarray(out["probability"]))

# %% distributed: shard rows over every visible device
import jax
from jax.sharding import Mesh

mesh = Mesh(np.array(jax.devices()), ("data",))
dist = LightGBMClassifier(num_iterations=30, num_leaves=31, mesh=mesh)
dist_model = dist.fit(train_t)
dist_acc = (np.asarray(dist_model.transform(test_t)["prediction"])
            == y[n // 2:]).mean()
print(f"distributed over {len(jax.devices())} devices, accuracy:",
      round(float(dist_acc), 4))
