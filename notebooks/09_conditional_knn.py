# %% [markdown]
# # Conditional KNN: cross-join retrieval with per-query conditions
#
# Reference notebook: `notebooks/features/other/ConditionalKNN on art` —
# index a gallery of embeddings, then for each query retrieve the k nearest
# neighbors whose LABEL is in the query's admissible set (e.g. "only match
# art from these cultures"). The TPU redesign is a brute-force MXU matmul:
# exact, batched, no tree traversal.

# %%
import numpy as np

from synapseml_tpu import Table
from synapseml_tpu.nn import KNN, ConditionalKNN

# %% a gallery of 4 "cultures", each a cluster in embedding space
rng = np.random.default_rng(0)
cultures = ["roman", "greek", "egyptian", "mayan"]
centers = rng.normal(size=(4, 16)) * 3
n_per = 250
keys = np.concatenate([
    centers[i] + rng.normal(size=(n_per, 16)) * 0.5 for i in range(4)])
labels = np.repeat(cultures, n_per).astype(object)
ids = np.array([f"item{i}" for i in range(len(keys))], dtype=object)
gallery = Table({"features": keys, "values": ids, "labels": labels})

# %% plain KNN: nearest items regardless of culture
knn = KNN(k=3).fit(gallery)
q = Table({"features": centers[0][None] + 0.1})
matches = knn.transform(q)["output"][0]
print("unconditional:", [m["value"] for m in matches])

# %% conditional: the SAME query, restricted to non-roman cultures
cknn = ConditionalKNN(k=3).fit(gallery)
cond = np.empty(2, dtype=object)
cond[0] = ["greek", "mayan"]      # query 0: only these cultures admissible
cond[1] = ["egyptian"]
cq = Table({"features": np.stack([centers[0] + 0.1, centers[2] + 0.1]),
            "conditioner": cond})
out = cknn.transform(cq)["output"]
got0 = {m["value"] for m in out[0]}
got1 = {m["value"] for m in out[1]}

# %% every conditional match respects its query's admissible set
label_of = dict(zip(ids, labels))
assert all(label_of[v] in ("greek", "mayan") for v in got0), got0
assert all(label_of[v] == "egyptian" for v in got1), got1
print("query 0 matched cultures:", {label_of[v] for v in got0})
print("query 1 matched cultures:", {label_of[v] for v in got1})

# %% distances are exact inner products (MXU brute force, no approximation)
best = max(out[1], key=lambda m: m["distance"])
egy = labels == "egyptian"
expected = float((keys[egy] @ (centers[2] + 0.1)).max())
assert abs(best["distance"] - expected) < 1e-3
print("top conditional distance matches the exact inner product")
