"""Grouping / repartitioning / balancing stages.

Rebuilds of ``core/.../stages/StratifiedRepartition.scala``, ``EnsembleByKey.scala``,
``ClassBalancer.scala`` and ``SummarizeData.scala``.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..core import Estimator, Model, Param, Table, Transformer
from ..core.params import ParamValidators

__all__ = [
    "StratifiedRepartition",
    "EnsembleByKey",
    "ClassBalancer",
    "ClassBalancerModel",
    "SummarizeData",
]


class StratifiedRepartition(Transformer):
    """Repartition so every partition sees every label
    (``StratifiedRepartition.scala``; needed e.g. so each GBDT worker has at least one
    instance of each class — same constraint our distributed GBDT has per mesh shard).

    Modes (reference ``SPConstants``): ``equal`` — resample (with replacement) so labels
    are equally represented; ``original`` — keep original ratios; ``mixed`` — heuristic
    between the two (labels rarer than the equal share are upsampled to it).
    """

    label_col = Param("label column", str, default="label")
    mode = Param("equal | original | mixed", str, default="mixed",
                 validator=ParamValidators.in_list(["equal", "original", "mixed"]))
    seed = Param("rng seed", int, default=0)

    def _transform(self, table: Table) -> Table:
        self._validate_input(table, self.label_col)
        labels = np.asarray(table[self.label_col])
        uniq, counts = np.unique(labels, return_counts=True)
        n, k = table.num_rows, len(uniq)
        rng = np.random.default_rng(self.seed)
        if self.mode == "original":
            fracs = {u: 1.0 for u in uniq}
        elif self.mode == "equal":
            share = n / k
            fracs = {u: share / c for u, c in zip(uniq, counts)}
        else:  # mixed: upsample only labels below the equal share
            share = n / k
            fracs = {u: max(1.0, share / c) for u, c in zip(uniq, counts)}
        # Per-label cyclic dealing: each label's rows are spread round-robin over
        # partitions (with rotating offsets), so every partition sees every label that
        # has >= 1 row per partition's share — the stage's contract.
        P = table.npartitions
        idx_parts: List[np.ndarray] = []
        for u, c in zip(uniq, counts):
            rows = np.nonzero(labels == u)[0]
            want = int(round(fracs[u] * c))
            if want <= c:
                take = rng.choice(rows, size=want, replace=False)
            else:
                take = np.concatenate([rows, rng.choice(rows, size=want - c, replace=True)])
            idx_parts.append(take)
        idx = np.concatenate(idx_parts)
        order = np.argsort(np.arange(len(idx)) % P, kind="stable")
        return table.take(idx[order])


class EnsembleByKey(Transformer):
    """Group rows by key column(s) and aggregate score columns
    (``EnsembleByKey.scala``): strategy ``mean`` over scalars or fixed-dim vectors;
    ``collapse_group=True`` emits one row per key, else broadcasts the aggregate back
    onto every row of the group."""

    keys = Param("key columns", list, validator=ParamValidators.non_empty())
    cols = Param("columns to aggregate", list, validator=ParamValidators.non_empty())
    new_col_names = Param("output names (defaults to '<strategy>(col)')", list, default=None)
    strategy = Param("aggregation strategy", str, default="mean",
                     validator=ParamValidators.in_list(["mean"]))
    collapse_group = Param("collapse each group to one row", bool, default=True)

    def _transform(self, table: Table) -> Table:
        self._validate_input(table, *self.keys, *self.cols)
        out_names = self.new_col_names or [f"{self.strategy}({c})" for c in self.cols]
        if len(out_names) != len(self.cols):
            raise ValueError(
                f"EnsembleByKey({self.uid}): new_col_names has {len(out_names)} entries "
                f"for {len(self.cols)} cols"
            )
        key_arrays = [table[k] for k in self.keys]
        key_tuples = list(zip(*[a.tolist() for a in key_arrays]))
        uniq: Dict[tuple, int] = {}
        group_of = np.empty(table.num_rows, dtype=np.int64)
        for i, kt in enumerate(key_tuples):
            group_of[i] = uniq.setdefault(kt, len(uniq))
        n_groups = len(uniq)
        agg_cols: Dict[str, np.ndarray] = {}
        for col, out_name in zip(self.cols, out_names):
            v = np.asarray(table[col], dtype=np.float64)
            if v.ndim == 1:
                sums = np.zeros(n_groups)
                np.add.at(sums, group_of, v)
            else:
                sums = np.zeros((n_groups,) + v.shape[1:])
                np.add.at(sums, group_of, v)
            cnt = np.bincount(group_of, minlength=n_groups).astype(np.float64)
            agg = sums / cnt.reshape((-1,) + (1,) * (sums.ndim - 1))
            agg_cols[out_name] = agg
        if self.collapse_group:
            first_row = np.zeros(n_groups, dtype=np.int64)
            seen = np.zeros(n_groups, dtype=bool)
            for i in range(table.num_rows):
                g = group_of[i]
                if not seen[g]:
                    first_row[g] = i
                    seen[g] = True
            base = table.select(*self.keys).take(first_row)
            for name, v in agg_cols.items():
                base = base.with_column(name, v)
            return base
        out = table
        for name, v in agg_cols.items():
            out = out.with_column(name, v[group_of])
        return out


class ClassBalancerModel(Model):
    """Adds a per-row weight column from the fitted label->weight map."""

    input_col = Param("label column", str, default="label")
    output_col = Param("weight column", str, default="weight")
    values = Param("label values (as strings)", list, default=[])
    weights = Param("weight per label value", list, default=[])

    def _transform(self, table: Table) -> Table:
        self._validate_input(table, self.input_col)
        table_vals = table[self.input_col]
        lut = dict(zip(self.values, self.weights))
        w = np.empty(len(table_vals), dtype=np.float64)
        for i, v in enumerate(table_vals):
            try:
                w[i] = lut[str(v)]
            except KeyError:
                raise ValueError(
                    f"ClassBalancerModel({self.uid}): label {v!r} in column "
                    f"{self.input_col!r} was not seen during fit (known: {self.values})"
                ) from None
        return table.with_column(self.output_col, w)


class ClassBalancer(Estimator):
    """Compute inverse-frequency class weights (``ClassBalancer.scala``):
    weight(label) = max_class_count / count(label)."""

    input_col = Param("label column", str, default="label")
    output_col = Param("weight column", str, default="weight")

    def _fit(self, table: Table) -> ClassBalancerModel:
        self._validate_input(table, self.input_col)
        uniq, counts = np.unique(np.asarray(table[self.input_col]), return_counts=True)
        top = counts.max()
        return ClassBalancerModel(
            input_col=self.input_col,
            output_col=self.output_col,
            values=[str(u) for u in uniq],
            weights=(top / counts).tolist(),
        )


class SummarizeData(Transformer):
    """Per-numeric-column summary statistics table (``SummarizeData.scala``):
    counts (rows, unique, missing/NaN), basic (mean/std/min/max), percentiles
    (P0.5, P1, P5, P25, P50, P75, P95, P99, P99.5)."""

    counts = Param("include count block", bool, default=True)
    basic = Param("include basic stats block", bool, default=True)
    percentiles = Param("include percentiles block", bool, default=True)
    error_threshold = Param("percentile approximation error (API parity; exact here)",
                            float, default=0.0)

    _PCTS = [0.5, 1, 5, 25, 50, 75, 95, 99, 99.5]

    def _transform(self, table: Table) -> Table:
        cols: Dict[str, List] = {"Feature": []}
        rows: List[Dict[str, float]] = []
        for name in table.column_names:
            v = table[name]
            if v.dtype == object or v.ndim != 1 or not np.issubdtype(v.dtype, np.number):
                continue
            x = v.astype(np.float64)
            finite = x[np.isfinite(x)]
            rec: Dict[str, float] = {}
            if self.counts:
                rec["Count"] = float(len(x))
                rec["Unique Value Count"] = float(len(np.unique(finite)))
                rec["Missing Value Count"] = float(len(x) - len(finite))
            if self.basic:
                rec["Mean"] = float(finite.mean()) if len(finite) else np.nan
                rec["Standard Deviation"] = float(finite.std(ddof=1)) if len(finite) > 1 else np.nan
                rec["Min"] = float(finite.min()) if len(finite) else np.nan
                rec["Max"] = float(finite.max()) if len(finite) else np.nan
            if self.percentiles:
                qs = np.percentile(finite, self._PCTS) if len(finite) else [np.nan] * len(self._PCTS)
                for p, q in zip(self._PCTS, qs):
                    rec[f"P{p}"] = float(q)
            cols["Feature"].append(name)
            rows.append(rec)
        if rows:
            for key in rows[0]:
                cols[key] = [r[key] for r in rows]
        return Table(cols)
