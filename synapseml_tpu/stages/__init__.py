"""Utility pipeline stages (reference: ``core/src/main/scala/.../stages/``)."""

from .basic import (
    Cacher,
    DropColumns,
    Explode,
    Lambda,
    RenameColumn,
    Repartition,
    SelectColumns,
    Timer,
    TimerModel,
    UDFTransformer,
)
from .batching import (
    DynamicMiniBatchTransformer,
    FixedMiniBatchTransformer,
    FlattenBatch,
    PartitionConsolidator,
    TimeIntervalMiniBatchTransformer,
)
from .grouping import (
    ClassBalancer,
    ClassBalancerModel,
    EnsembleByKey,
    StratifiedRepartition,
    SummarizeData,
)
from .text import MultiColumnAdapter, TextPreprocessor, UnicodeNormalize

__all__ = [
    "Cacher",
    "DropColumns",
    "Explode",
    "Lambda",
    "RenameColumn",
    "Repartition",
    "SelectColumns",
    "Timer",
    "TimerModel",
    "UDFTransformer",
    "DynamicMiniBatchTransformer",
    "FixedMiniBatchTransformer",
    "FlattenBatch",
    "PartitionConsolidator",
    "TimeIntervalMiniBatchTransformer",
    "ClassBalancer",
    "ClassBalancerModel",
    "EnsembleByKey",
    "StratifiedRepartition",
    "SummarizeData",
    "MultiColumnAdapter",
    "TextPreprocessor",
    "UnicodeNormalize",
]
