"""Text utility stages.

Rebuilds of ``core/.../stages/TextPreprocessor.scala`` (trie-driven find/replace with
case normalization), ``UnicodeNormalize.scala`` and ``MultiColumnAdapter.scala``.
"""

from __future__ import annotations

import unicodedata
from typing import Dict, List, Optional

import numpy as np

from ..core import ComplexParam, Param, Pipeline, PipelineModel, Table, Transformer, Estimator
from ..core.params import ParamValidators

__all__ = ["TextPreprocessor", "UnicodeNormalize", "MultiColumnAdapter"]


class _Trie:
    """Longest-match replacement trie (reference builds the same structure,
    ``TextPreprocessor.scala``)."""

    __slots__ = ("children", "value")

    def __init__(self):
        self.children: Dict[str, "_Trie"] = {}
        self.value: Optional[str] = None

    def put(self, key: str, value: str) -> None:
        node = self
        for ch in key:
            node = node.children.setdefault(ch, _Trie())
        node.value = value

    def longest_match(self, s: str, start: int):
        node, best = self, None
        i = start
        while i < len(s) and s[i] in node.children:
            node = node.children[s[i]]
            i += 1
            if node.value is not None:
                best = (i, node.value)
        return best


class TextPreprocessor(Transformer):
    """Map-driven text normalization: longest-match substring replacement via a trie,
    with optional case normalization before matching."""

    input_col = Param("input text column", str, default="text")
    output_col = Param("output column", str, default="processed")
    map = Param("substring -> replacement map", dict, default={})
    normalize_case = Param("lowercase before matching", bool, default=True)

    def _transform(self, table: Table) -> Table:
        self._validate_input(table, self.input_col)
        trie = _Trie()
        for k, v in self.map.items():
            trie.put(k.lower() if self.normalize_case else k, v)
        out = []
        for s in table[self.input_col]:
            s = str(s)
            if self.normalize_case:
                s = s.lower()
            parts, i = [], 0
            while i < len(s):
                m = trie.longest_match(s, i)
                if m is None:
                    parts.append(s[i])
                    i += 1
                else:
                    parts.append(m[1])
                    i = m[0]
            out.append("".join(parts))
        return table.with_column(self.output_col, out)


class UnicodeNormalize(Transformer):
    """Unicode normalization (``UnicodeNormalize.scala``): NFC/NFD/NFKC/NFKD + optional
    lowercasing."""

    input_col = Param("input text column", str, default="text")
    output_col = Param("output column", str, default="normalized")
    form = Param("normalization form", str, default="NFKD",
                 validator=ParamValidators.in_list(["NFC", "NFD", "NFKC", "NFKD"]))
    lower = Param("lowercase output", bool, default=True)

    def _transform(self, table: Table) -> Table:
        self._validate_input(table, self.input_col)
        out = []
        for s in table[self.input_col]:
            t = unicodedata.normalize(self.form, str(s))
            out.append(t.lower() if self.lower else t)
        return table.with_column(self.output_col, out)


class MultiColumnAdapter(Estimator):
    """Apply a single-column stage to many columns (``MultiColumnAdapter.scala``):
    clones ``base_stage`` per (input, output) pair and chains them into a pipeline."""

    base_stage = ComplexParam("unary stage to replicate (uses input_col/output_col params)",
                              object, default=None)
    input_cols = Param("input columns", list, validator=ParamValidators.non_empty())
    output_cols = Param("output columns", list, validator=ParamValidators.non_empty())

    def _chain(self):
        if len(self.input_cols) != len(self.output_cols):
            raise ValueError("input_cols and output_cols must have equal length")
        stages = []
        for i, o in zip(self.input_cols, self.output_cols):
            clone = self.base_stage.copy({"input_col": i, "output_col": o})
            clone.uid = f"{self.base_stage.uid}_{i}"
            stages.append(clone)
        return stages

    def _fit(self, table: Table) -> PipelineModel:
        return Pipeline(stages=self._chain()).fit(table)
