"""Minibatching stages — the bridge between row-oriented tables and tensor-oriented
engines.

Rebuild of ``core/.../stages/MiniBatchTransformer.scala`` (``FixedMiniBatchTransformer``
:151, ``DynamicMiniBatchTransformer``:53, ``TimeIntervalMiniBatchTransformer``:77,
``FlattenBatch``:187) and ``PartitionConsolidator.scala:21-48``. In the reference these
convert row streams into rows-of-arrays so native engines see contiguous buffers
(``ONNXModel.transform`` inserts a FixedMiniBatchTransformer before inference,
``ONNXModel.scala:499``). Here a *batched* table is one whose columns are object arrays
holding per-batch numpy arrays; ``FlattenBatch`` inverts losslessly.

On TPU the batch dimension is what feeds the MXU — minibatch size should be chosen to
keep matmuls large and shapes static (pad-to-bucket helpers live in the ONNX engine).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..core import Param, Table, Transformer, concat_tables
from ..core.params import ParamValidators

__all__ = [
    "FixedMiniBatchTransformer",
    "DynamicMiniBatchTransformer",
    "TimeIntervalMiniBatchTransformer",
    "FlattenBatch",
    "PartitionConsolidator",
]


def _batch_table(table: Table, bounds: List[tuple]) -> Table:
    cols = {}
    for name in table.column_names:
        src = table[name]
        out = np.empty(len(bounds), dtype=object)
        for i, (lo, hi) in enumerate(bounds):
            out[i] = src[lo:hi]
        cols[name] = out
    return Table(cols, npartitions=min(table.npartitions, max(1, len(bounds))), meta=table.meta)


class FixedMiniBatchTransformer(Transformer):
    """Group consecutive rows into fixed-size batches
    (``MiniBatchTransformer.scala:151``). Batching is per-partition, so batches never
    straddle a partition boundary (a Spark task == a partition here)."""

    batch_size = Param("rows per batch", int, default=32, validator=ParamValidators.gt(0))
    max_buffer_size = Param("buffering bound (API parity; eager substrate ignores)", int, default=2147483647)

    def _transform(self, table: Table) -> Table:
        def per_part(part: Table, _i: int) -> Table:
            b = self.batch_size
            bounds = [(lo, min(lo + b, part.num_rows)) for lo in range(0, part.num_rows, b)]
            return _batch_table(part, bounds)

        return table.map_partitions(per_part)


class DynamicMiniBatchTransformer(Transformer):
    """Batch whatever is available, capped at ``max_batch_size``
    (``MiniBatchTransformer.scala:53``). In the eager substrate the whole partition is
    'available', so this emits one batch per partition (or several when capped)."""

    max_batch_size = Param("max rows per batch", int, default=2147483647,
                           validator=ParamValidators.gt(0))

    def _transform(self, table: Table) -> Table:
        def per_part(part: Table, _i: int) -> Table:
            b = min(self.max_batch_size, max(1, part.num_rows))
            bounds = [(lo, min(lo + b, part.num_rows)) for lo in range(0, part.num_rows, b)]
            return _batch_table(part, bounds)

        return table.map_partitions(per_part)


class TimeIntervalMiniBatchTransformer(DynamicMiniBatchTransformer):
    """Time-window batching (``MiniBatchTransformer.scala:77``). Meaningful for
    streaming sources (serving); over an eager table it degenerates to dynamic
    batching — the interval param is kept for API parity and used by the serving layer."""

    millis_to_wait = Param("batch window in milliseconds", int, default=1000,
                           validator=ParamValidators.gt(0))


class FlattenBatch(Transformer):
    """Invert minibatching: explode every batched column in lockstep
    (``MiniBatchTransformer.scala:187``)."""

    def _transform(self, table: Table) -> Table:
        if table.num_rows == 0:
            return table
        names = table.column_names
        first = table[names[0]]
        lengths = np.array([len(v) for v in first], dtype=np.int64)
        cols = {}
        for name in names:
            src = table[name]
            parts = []
            for i, v in enumerate(src):
                arr = np.asarray(v)
                if len(arr) != lengths[i]:
                    raise ValueError(
                        f"FlattenBatch: column {name!r} batch {i} has {len(arr)} rows, "
                        f"expected {lengths[i]}"
                    )
                parts.append(arr)
            if any(p.dtype == object for p in parts):
                total = int(lengths.sum())
                out = np.empty(total, dtype=object)
                k = 0
                for p in parts:
                    out[k : k + len(p)] = p
                    k += len(p)
                cols[name] = out
            else:
                cols[name] = np.concatenate(parts, axis=0)
        return Table(cols, npartitions=table.npartitions, meta=table.meta)


class PartitionConsolidator(Transformer):
    """Funnel all rows into one partition per host
    (``PartitionConsolidator.scala:21-48``; reference funnels an executor's rows to one
    task so rate-limited HTTP clients share a single connection pool). Here: coalesce the
    table to a single logical partition."""

    def _transform(self, table: Table) -> Table:
        return table.repartition(1)
