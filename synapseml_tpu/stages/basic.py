"""Basic utility transformers.

TPU-native rebuilds of the small stages in ``core/src/main/scala/.../stages/``:
``DropColumns.scala``, ``SelectColumns.scala``, ``RenameColumn.scala``,
``Repartition.scala``, ``Cacher.scala``, ``Lambda.scala``, ``UDFTransformer.scala``,
``Explode.scala``, ``Timer.scala``.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, List, Optional

import numpy as np

from ..core import ComplexParam, Param, Table, Transformer, Estimator, Model, PipelineStage
from ..core.clock import StopWatch
from ..core.params import ParamValidators

__all__ = [
    "DropColumns",
    "SelectColumns",
    "RenameColumn",
    "Repartition",
    "Cacher",
    "Lambda",
    "UDFTransformer",
    "Explode",
    "Timer",
    "TimerModel",
]

_logger = logging.getLogger("synapseml_tpu.stages")


class DropColumns(Transformer):
    """Drop the listed columns (``DropColumns.scala``)."""

    cols = Param("columns to drop", list, default=[])

    def _transform(self, table: Table) -> Table:
        self._validate_input(table, *self.cols)
        return table.drop(*self.cols)


class SelectColumns(Transformer):
    """Keep only the listed columns (``SelectColumns.scala``)."""

    cols = Param("columns to keep", list, validator=ParamValidators.non_empty())

    def _transform(self, table: Table) -> Table:
        self._validate_input(table, *self.cols)
        return table.select(*self.cols)


class RenameColumn(Transformer):
    """Rename one column (``RenameColumn.scala``)."""

    input_col = Param("existing column name", str)
    output_col = Param("new column name", str)

    def _transform(self, table: Table) -> Table:
        self._validate_input(table, self.input_col)
        return table.rename({self.input_col: self.output_col})


class Repartition(Transformer):
    """Change the logical partition count (``Repartition.scala``).

    ``disable=True`` passes through unchanged, mirroring the reference param.
    """

    n = Param("target partition count", int, validator=ParamValidators.gt(0))
    disable = Param("if true, pass through unchanged", bool, default=False)

    def _transform(self, table: Table) -> Table:
        if self.disable:
            return table
        return table.repartition(self.n)


class Cacher(Transformer):
    """Materialization hint (``Cacher.scala``). The eager columnar substrate is always
    materialized, so this is API-parity no-op (``disable`` kept for compatibility)."""

    disable = Param("if true, do nothing", bool, default=False)

    def _transform(self, table: Table) -> Table:
        return table.cache() if not self.disable else table


class Lambda(Transformer):
    """Arbitrary ``Table -> Table`` function stage (``Lambda.scala``).

    The reference warns these don't serialize their closures; same here — save/load
    persists only metadata, and loading yields an identity lambda with a warning.
    """

    transform_func = ComplexParam("function Table -> Table", object, default=None)

    def _transform(self, table: Table) -> Table:
        fn = self.transform_func
        if fn is None:
            _logger.warning("Lambda(%s): no transform_func (deserialized?); passing through", self.uid)
            return table
        return fn(table)


class UDFTransformer(Transformer):
    """Apply a python function to column(s) producing a new column
    (``UDFTransformer.scala``; ``UDFUtils.oldUdf`` injection).

    ``vectorized=True`` hands the whole column array(s) to ``udf`` (preferred — lets the
    udf be a jitted jax function over the full batch); otherwise applies per row.
    """

    input_col = Param("single input column", str, default=None)
    input_cols = Param("multiple input columns", list, default=None)
    output_col = Param("output column", str, default="output")
    udf = ComplexParam("python callable", object, default=None)
    vectorized = Param("call udf on whole columns instead of per-row", bool, default=False)

    def _transform(self, table: Table) -> Table:
        if self.udf is None:
            raise ValueError(f"UDFTransformer({self.uid}): udf is not set")
        cols = self.input_cols if self.input_cols else [self.input_col]
        if cols == [None]:
            raise ValueError("set input_col or input_cols")
        self._validate_input(table, *cols)
        arrays = [table[c] for c in cols]
        if self.vectorized:
            out = self.udf(*arrays)
        else:
            vals = [self.udf(*row) for row in zip(*arrays)]
            out = vals
        return table.with_column(self.output_col, out)


class Explode(Transformer):
    """One row per element of a sequence column, other columns replicated
    (``Explode.scala``)."""

    input_col = Param("sequence column to explode", str)
    output_col = Param("output column (defaults to input)", str, default=None)

    def _transform(self, table: Table) -> Table:
        self._validate_input(table, self.input_col)
        col = table[self.input_col]
        lengths = np.array([len(v) for v in col], dtype=np.int64)
        idx = np.repeat(np.arange(table.num_rows), lengths)
        flat: List[Any] = [x for v in col for x in v]
        out_name = self.output_col or self.input_col
        base = table.drop(self.input_col).take(idx) if out_name == self.input_col else table.take(idx)
        return base.with_column(out_name, flat)


class TimerModel(Model):
    """Fitted Timer: times the wrapped fitted stage's transform.

    ``profile_dir`` additionally captures a ``jax.profiler`` trace of the
    transform (per-HLO device timeline — SURVEY §5's prescription for
    debugging where a stage's device time actually goes)."""

    inner_model = ComplexParam("wrapped fitted transformer", object, default=None)
    log_to_logger = Param("emit timing to logger", bool, default=True)
    profile_dir = Param("capture a jax profiler trace into this directory",
                        str, default=None)

    def _transform(self, table: Table) -> Table:
        import contextlib

        from ..core.telemetry import profile_trace

        sw = StopWatch()
        ctx = (profile_trace(self.profile_dir) if self.profile_dir
               else contextlib.nullcontext())
        with ctx, sw.measure():
            out = self.inner_model.transform(table)
        self._last_elapsed_s = sw.elapsed_s
        if self.log_to_logger:
            _logger.info("%s.transform took %.4fs", type(self.inner_model).__name__, sw.elapsed_s)
        return out


class Timer(Estimator):
    """Time fit/transform of a wrapped stage (``Timer.scala``)."""

    stage = ComplexParam("wrapped stage", object, default=None)
    log_to_logger = Param("emit timing to logger", bool, default=True)

    def _fit(self, table: Table) -> TimerModel:
        st = self.stage
        sw = StopWatch()
        if isinstance(st, Estimator):
            with sw.measure():
                inner = st.fit(table)
        else:
            inner = st
        if self.log_to_logger and sw.elapsed_ns:
            _logger.info("%s.fit took %.4fs", type(st).__name__, sw.elapsed_s)
        m = TimerModel(inner_model=inner, log_to_logger=self.log_to_logger)
        m._last_fit_s = sw.elapsed_s
        return m
