"""Image ops and stages (reference: ``opencv`` module + ``core/.../image/``)."""

from . import ops
from .stages import (ImageSetAugmenter, ImageTransformer,
                     ResizeImageTransformer, UnrollBinaryImage, UnrollImage)

__all__ = ["ops", "ImageTransformer", "ResizeImageTransformer", "UnrollImage", "UnrollBinaryImage", "ImageSetAugmenter"]
