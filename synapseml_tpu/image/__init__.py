"""Image ops and stages (reference: ``opencv`` module + ``core/.../image/``)."""

from ..core.lazyimport import lazy_module

# PEP 562 lazy exports (lint SMT008): attribute access imports the owning
# submodule on demand (`image.ops` resolves as a submodule), keeping
# `import synapseml_tpu.image` jax-free
__getattr__, __dir__, __all__ = lazy_module(__name__, {
    "ops": [],
    "stages": ["ImageSetAugmenter", "ImageTransformer",
               "ResizeImageTransformer", "UnrollBinaryImage", "UnrollImage"],
})
