"""Image kernels on JAX — the OpenCV-equivalent op library.

Rebuild of the native image ops behind ``opencv/.../ImageTransformer.scala:28-671``
(resize, crop, center-crop, color format, blur, threshold, gaussian kernel, flip) as
batched JAX functions over ``(N, H, W, C)`` float32/uint8 arrays. Where the reference
calls OpenCV C++ per image per task, these run whole batches as XLA programs (separable
convolutions for blurs ride the MXU/VPU; resize is ``jax.image.resize``).

Channel convention: images are HWC; color images default BGR to stay bit-compatible
with the reference's OpenCV convention (``ImageSchema`` stores BGR).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional, Sequence, Tuple

import numpy as np

from ..core.lazyimport import lazy_import

# resolved on first attribute access inside a kernel — importing this
# module (or synapseml_tpu.image) stays jax-free (lint rule SMT001)
jax = lazy_import("jax")
jnp = lazy_import("jax.numpy")

__all__ = [
    "resize",
    "resize_shorter",
    "crop",
    "center_crop",
    "flip",
    "gaussian_kernel_2d",
    "gaussian_blur",
    "box_blur",
    "threshold",
    "color_convert",
    "normalize",
]


def resize(images: jnp.ndarray, height: int, width: int, method: str = "linear") -> jnp.ndarray:
    """Batched resize to (height, width). images: (N,H,W,C)."""
    n, _, _, c = images.shape
    return jax.image.resize(images.astype(jnp.float32), (n, height, width, c), method=method)


def resize_shorter(image: np.ndarray, size: int, method: str = "linear") -> np.ndarray:
    """Single-image aspect-preserving resize: shorter side -> ``size``
    (reference ``ResizeImage.size`` + ``keepAspectRatio``, ``ImageTransformer.scala:71-82``)."""
    h, w = image.shape[:2]
    ratio = size / min(h, w)
    th, tw = int(round(ratio * h)), int(round(ratio * w))
    out = jax.image.resize(jnp.asarray(image, jnp.float32), (th, tw, image.shape[2]), method=method)
    return np.asarray(out)


def crop(images: jnp.ndarray, x: int, y: int, width: int, height: int) -> jnp.ndarray:
    """Rectangle crop at (x, y) (reference ``CropImage``). x is column, y is row."""
    return images[:, y : y + height, x : x + width, :]


def center_crop(images: jnp.ndarray, width: int, height: int) -> jnp.ndarray:
    """Center crop (reference ``CenterCropImage.scala:142-147``)."""
    h, w = images.shape[1:3]
    cw, ch = min(width, w), min(height, h)
    mx, my = w // 2, h // 2
    x0, y0 = mx - cw // 2, my - ch // 2
    return images[:, y0 : y0 + ch, x0 : x0 + cw, :]


def flip(images: jnp.ndarray, flip_code: int = 1) -> jnp.ndarray:
    """OpenCV flip codes: 0 vertical (around x-axis), >0 horizontal, <0 both
    (reference ``Flip`` stage)."""
    if flip_code == 0:
        return images[:, ::-1, :, :]
    if flip_code > 0:
        return images[:, :, ::-1, :]
    return images[:, ::-1, ::-1, :]


def gaussian_kernel_2d(aperture: int, sigma: float) -> np.ndarray:
    """2-D Gaussian kernel matching OpenCV ``getGaussianKernel`` semantics
    (reference ``GaussianKernel`` stage)."""
    if sigma <= 0:
        sigma = 0.3 * ((aperture - 1) * 0.5 - 1) + 0.8
    half = (aperture - 1) / 2.0
    xs = np.arange(aperture) - half
    k1 = np.exp(-(xs**2) / (2.0 * sigma**2))
    k1 /= k1.sum()
    return np.outer(k1, k1)


def _separable_blur(images: jnp.ndarray, kx: jnp.ndarray, ky: jnp.ndarray) -> jnp.ndarray:
    """Depthwise separable 2-D filter with edge ('replicate') padding, per channel."""
    n, h, w, c = images.shape
    x = images.astype(jnp.float32)
    px = (len(ky) - 1) // 2, len(ky) - 1 - (len(ky) - 1) // 2
    py = (len(kx) - 1) // 2, len(kx) - 1 - (len(kx) - 1) // 2
    x = jnp.pad(x, ((0, 0), px, (0, 0), (0, 0)), mode="edge")
    x = jnp.pad(x, ((0, 0), (0, 0), py, (0, 0)), mode="edge")
    # NHWC depthwise conv: feature_group_count = C
    kv = jnp.asarray(ky, jnp.float32).reshape(len(ky), 1, 1, 1) * jnp.ones((1, 1, 1, c), jnp.float32)
    kh = jnp.asarray(kx, jnp.float32).reshape(1, len(kx), 1, 1) * jnp.ones((1, 1, 1, c), jnp.float32)
    dn = jax.lax.conv_dimension_numbers(x.shape, kv.shape, ("NHWC", "HWIO", "NHWC"))
    x = jax.lax.conv_general_dilated(x, kv, (1, 1), "VALID", dimension_numbers=dn,
                                     feature_group_count=c)
    x = jax.lax.conv_general_dilated(x, kh, (1, 1), "VALID", dimension_numbers=dn,
                                     feature_group_count=c)
    return x


def gaussian_blur(images: jnp.ndarray, aperture: int, sigma: float) -> jnp.ndarray:
    """Gaussian blur (reference ``Blur``/GaussianBlur path)."""
    if sigma <= 0:
        sigma = 0.3 * ((aperture - 1) * 0.5 - 1) + 0.8
    half = (aperture - 1) / 2.0
    xs = np.arange(aperture) - half
    k1 = np.exp(-(xs**2) / (2.0 * sigma**2))
    k1 = k1 / k1.sum()
    return _separable_blur(images, k1, k1)


def box_blur(images: jnp.ndarray, height: int, width: int) -> jnp.ndarray:
    """Normalized box filter (reference ``Blur`` stage with (h,w) aperture)."""
    kx = np.full(width, 1.0 / width)
    ky = np.full(height, 1.0 / height)
    return _separable_blur(images, kx, ky)


def threshold(images: jnp.ndarray, thresh: float, max_val: float, kind: str = "binary") -> jnp.ndarray:
    """OpenCV-style thresholding (reference ``Threshold`` stage)."""
    x = images.astype(jnp.float32)
    if kind == "binary":
        return jnp.where(x > thresh, max_val, 0.0)
    if kind == "binary_inv":
        return jnp.where(x > thresh, 0.0, max_val)
    if kind == "trunc":
        return jnp.minimum(x, thresh)
    if kind == "tozero":
        return jnp.where(x > thresh, x, 0.0)
    if kind == "tozero_inv":
        return jnp.where(x > thresh, 0.0, x)
    raise ValueError(f"unknown threshold kind {kind!r}")


_BGR2GRAY = np.array([0.114, 0.587, 0.299], dtype=np.float32)  # OpenCV luma, BGR order


def color_convert(images: jnp.ndarray, code: str) -> jnp.ndarray:
    """Color-format conversion (reference ``ColorFormat`` stage). Supported codes:
    'bgr2rgb', 'rgb2bgr', 'bgr2gray', 'rgb2gray', 'gray2bgr', 'gray2rgb'."""
    code = code.lower()
    if code in ("bgr2rgb", "rgb2bgr"):
        return images[..., ::-1]
    if code in ("bgr2gray", "rgb2gray"):
        w = _BGR2GRAY if code.startswith("bgr") else _BGR2GRAY[::-1].copy()
        gray = jnp.tensordot(images.astype(jnp.float32), jnp.asarray(w), axes=[[-1], [0]])
        return gray[..., None]
    if code in ("gray2bgr", "gray2rgb"):
        return jnp.repeat(images, 3, axis=-1)
    raise ValueError(f"unknown color conversion {code!r}")


def normalize(images: jnp.ndarray, mean: Sequence[float], std: Sequence[float],
              scale: float = 1.0) -> jnp.ndarray:
    """(x*scale - mean)/std per channel — the standard CNN input normalization
    (the reference leaves this to CNTK model internals; explicit here)."""
    x = images.astype(jnp.float32) * scale
    m = jnp.asarray(mean, jnp.float32).reshape(1, 1, 1, -1)
    s = jnp.asarray(std, jnp.float32).reshape(1, 1, 1, -1)
    return (x - m) / s
