"""Image pipeline stages.

Rebuilds of ``opencv/.../ImageTransformer.scala`` (stage-list driven image pipeline),
``ImageSetAugmenter.scala``, and core's opencv-free ``ResizeImageTransformer`` /
``UnrollImage`` (``core/.../image/``). Image columns are either object columns of HWC
uint8/float arrays (ragged sizes) or uniform ``(N,H,W,C)`` tensor columns; stages
normalize to tensor columns as soon as sizes become uniform so downstream ops run
batched on the accelerator.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ..core import (ColumnSpec, Param, Table, TableSchema, Transformer,
                    concat_tables)
from ..core.params import ParamValidators
from . import ops as iops

__all__ = ["ImageTransformer", "ResizeImageTransformer", "UnrollImage", "ImageSetAugmenter"]


def _to_batch(col) -> Optional[np.ndarray]:
    """Object column of uniform HWC arrays -> (N,H,W,C); None if ragged."""
    if isinstance(col, np.ndarray) and col.dtype != object:
        return col if col.ndim == 4 else None
    shapes = {np.asarray(v).shape for v in col}
    if len(shapes) == 1:
        return np.stack([np.asarray(v) for v in col])
    return None


def _from_batch(batch: np.ndarray):
    return np.asarray(batch)


class ImageTransformer(Transformer):
    """Sequential image-op pipeline encoded as a list of ``{"action": ..., params}``
    dicts — same contract as the reference's stage list (``ImageTransformerStage.apply``,
    ``ImageTransformer.scala:34-48``). Supported actions: ``resize``, ``crop``,
    ``centercrop``, ``colorformat``, ``blur``, ``gaussiankernel``, ``threshold``,
    ``flip``, ``normalize``."""

    input_col = Param("input image column", str, default="image")
    output_col = Param("output image column", str, default="image")
    stages = Param("list of image op dicts with 'action' key", list, default=[])

    def input_schema(self):
        # tensor image columns OR ragged object columns of HWC arrays
        return TableSchema({self.input_col: ColumnSpec("any", "any")})

    def transform_schema(self, schema):
        self._check_schema(schema, self.input_schema())
        return schema.with_column(self.output_col,
                                  ColumnSpec("any", "image"))

    # -- single-stage helpers, batched ------------------------------------------

    def _apply_stage(self, batch: np.ndarray, stage: Dict[str, Any]) -> np.ndarray:
        action = stage["action"].lower()
        if action == "resize":
            if "size" in stage:  # aspect-preserving shorter-side resize is per-image
                raise ValueError("resize with 'size' must be applied pre-batch (ragged)")
            return np.asarray(iops.resize(batch, int(stage["height"]), int(stage["width"])))
        if action == "crop":
            return np.asarray(iops.crop(batch, int(stage["x"]), int(stage["y"]),
                                        int(stage["width"]), int(stage["height"])))
        if action == "centercrop":
            return np.asarray(iops.center_crop(batch, int(stage["width"]), int(stage["height"])))
        if action == "colorformat":
            return np.asarray(iops.color_convert(batch, stage["format"]))
        if action == "blur":
            return np.asarray(iops.box_blur(batch, int(stage["height"]), int(stage["width"])))
        if action == "gaussiankernel":
            return np.asarray(iops.gaussian_blur(batch, int(stage["aperturesize"]),
                                                 float(stage.get("sigma", -1.0))))
        if action == "threshold":
            return np.asarray(iops.threshold(batch, float(stage["threshold"]),
                                             float(stage["maxval"]),
                                             stage.get("thresholdtype", "binary")))
        if action == "flip":
            return np.asarray(iops.flip(batch, int(stage.get("flipcode", 1))))
        if action == "normalize":
            return np.asarray(iops.normalize(batch, stage["mean"], stage["std"],
                                             float(stage.get("scale", 1.0))))
        raise ValueError(f"unknown image action {action!r}")

    def _transform(self, table: Table) -> Table:
        self._validate_input(table, self.input_col)
        col = table[self.input_col]
        batch = _to_batch(col)
        stages = list(self.stages)
        if batch is None:
            # Ragged: resolve per-image until a uniform-size op (resize) appears.
            imgs = [np.asarray(v) for v in col]
            while stages:
                st = dict(stages[0])
                action = st["action"].lower()
                if action == "resize" and "size" in st:
                    imgs = [iops.resize_shorter(im, int(st["size"])) for im in imgs]
                    stages.pop(0)
                    continue
                if action == "resize":
                    h, w = int(st["height"]), int(st["width"])
                    imgs = [
                        np.asarray(iops.resize(im[None], h, w))[0] for im in imgs
                    ]
                    stages.pop(0)
                    batch = np.stack(imgs)
                    break
                # apply per-image with batch dim 1
                imgs = [self._apply_stage(im[None], st)[0] for im in imgs]
                stages.pop(0)
            if batch is None:
                try:
                    batch = np.stack(imgs)
                except ValueError:
                    out = np.empty(len(imgs), dtype=object)
                    for i, im in enumerate(imgs):
                        out[i] = im
                    return table.with_column(self.output_col, out, meta={"type": "image"})
        for st in stages:
            batch = self._apply_stage(batch, st)
        return table.with_column(self.output_col, _from_batch(batch), meta={"type": "image"})


class ResizeImageTransformer(Transformer):
    """Opencv-free resize (reference ``core/.../image/ResizeImageTransformer.scala``)."""

    input_col = Param("input image column", str, default="image")
    output_col = Param("output image column", str, default="image")
    height = Param("target height", int, default=224, validator=ParamValidators.gt(0))
    width = Param("target width", int, default=224, validator=ParamValidators.gt(0))

    def input_schema(self):
        return TableSchema({self.input_col: ColumnSpec("any", "any")})

    def transform_schema(self, schema):
        self._check_schema(schema, self.input_schema())
        return schema.with_column(self.output_col,
                                  ColumnSpec("any", "image"))

    def _transform(self, table: Table) -> Table:
        self._validate_input(table, self.input_col)
        col = table[self.input_col]
        batch = _to_batch(col)
        if batch is not None:
            out = np.asarray(iops.resize(batch, self.height, self.width))
        else:
            out = np.stack(
                [np.asarray(iops.resize(np.asarray(v)[None], self.height, self.width))[0]
                 for v in col]
            )
        return table.with_column(self.output_col, out, meta={"type": "image"})


class UnrollImage(Transformer):
    """Flatten image column into a feature vector column
    (reference ``core/.../image/UnrollImage.scala``; CNTK convention unrolls per
    channel-plane, i.e. CHW order)."""

    input_col = Param("input image column", str, default="image")
    output_col = Param("output vector column", str, default="features")

    def input_schema(self):
        return TableSchema({self.input_col: ColumnSpec("any", "image")})

    def transform_schema(self, schema):
        self._check_schema(schema, self.input_schema())
        return schema.with_column(self.output_col,
                                  ColumnSpec("float", "vector"))

    def _transform(self, table: Table) -> Table:
        self._validate_input(table, self.input_col)
        col = table[self.input_col]
        batch = _to_batch(col)
        if batch is None:
            raise ValueError(
                f"UnrollImage({self.uid}): images must be uniform size (resize first)"
            )
        n = batch.shape[0]
        chw = np.transpose(batch, (0, 3, 1, 2))
        return table.with_column(self.output_col, chw.reshape(n, -1).astype(np.float32))


class ImageSetAugmenter(Transformer):
    """Dataset augmentation by mirroring (reference ``ImageSetAugmenter.scala``):
    emits original rows plus flipped copies, multiplying the row count."""

    input_col = Param("image column", str, default="image")
    output_col = Param("output image column", str, default="image")
    flip_left_right = Param("add horizontal mirrors", bool, default=True)
    flip_up_down = Param("add vertical mirrors", bool, default=False)

    def input_schema(self):
        return TableSchema({self.input_col: ColumnSpec("any", "image")})

    def transform_schema(self, schema):
        self._check_schema(schema, self.input_schema())
        out = schema.with_column(self.output_col, ColumnSpec("any", "image"))
        if self.output_col != self.input_col:
            out = out.drop(self.input_col)
        return out

    def _transform(self, table: Table) -> Table:
        self._validate_input(table, self.input_col)
        col = table[self.input_col]
        batch = _to_batch(col)
        if batch is None:
            raise ValueError(f"ImageSetAugmenter({self.uid}): resize images first")
        tables = [table.with_column(self.output_col, batch, meta={"type": "image"})]
        if self.flip_left_right:
            tables.append(table.with_column(self.output_col, np.asarray(iops.flip(batch, 1)),
                                            meta={"type": "image"}))
        if self.flip_up_down:
            tables.append(table.with_column(self.output_col, np.asarray(iops.flip(batch, 0)),
                                            meta={"type": "image"}))
        if self.output_col != self.input_col:
            tables = [t.drop(self.input_col) if self.input_col in t else t for t in tables]
        return concat_tables(tables)


class UnrollBinaryImage(Transformer):
    """Decode a binary (bytes) image column and unroll to a CHW vector.

    Reference ``core/.../image/UnrollImage.scala:187`` (``UnrollBinaryImage``):
    same unroll order as :class:`UnrollImage`, but fed raw encoded bytes;
    optional ``width``/``height`` resize to a uniform target (required when
    source sizes vary). Undecodable/None rows yield None."""

    input_col = Param("binary image column", str, default="image")
    output_col = Param("output vector column", str, default="features")
    width = Param("target width (resize when set)", int, default=None)
    height = Param("target height (resize when set)", int, default=None)
    n_channels = Param("target channel count", int, default=None)

    def input_schema(self):
        return TableSchema({self.input_col: ColumnSpec("object", "scalar")})

    def transform_schema(self, schema):
        self._check_schema(schema, self.input_schema())
        # object column of per-row f32 vectors (None for undecodable rows)
        return schema.with_column(self.output_col,
                                  ColumnSpec("float", "vector"))

    def _transform(self, table: Table) -> Table:
        from ..io.binary import decode_image

        if (self.width is None) != (self.height is None):
            raise ValueError(
                f"UnrollBinaryImage({self.uid}): width and height must be "
                "set together to resize (got width="
                f"{self.width}, height={self.height})")
        if self.width is not None and (self.width <= 0 or self.height <= 0):
            raise ValueError(
                f"UnrollBinaryImage({self.uid}): width/height must be "
                f"positive (got {self.width}x{self.height})")
        self._validate_input(table, self.input_col)
        col = table[self.input_col]
        n = table.num_rows
        decoded: List[Optional[np.ndarray]] = []
        for r in range(n):
            v = col[r]
            if v is None:
                decoded.append(None)
                continue
            try:
                img = decode_image(bytes(v))
            except Exception:
                decoded.append(None)
                continue
            if self.width is not None:
                img = np.asarray(iops.resize(
                    np.asarray(img, np.float32)[None], self.height,
                    self.width))[0]
            if self.n_channels:
                c = img.shape[-1]
                if c == 1 and self.n_channels == 3:
                    img = np.repeat(img, 3, axis=-1)
                elif c != self.n_channels:
                    img = img[..., : self.n_channels]
            decoded.append(np.asarray(img, np.float32))
        shapes = {d.shape for d in decoded if d is not None}
        if len(shapes) > 1:
            raise ValueError(
                f"UnrollBinaryImage({self.uid}): decoded sizes differ "
                f"({sorted(shapes)}); set width/height to resize")
        out = np.empty(n, dtype=object)
        for r, img in enumerate(decoded):
            if img is not None:
                out[r] = np.transpose(img, (2, 0, 1)).ravel().astype(np.float32)
        return table.with_column(self.output_col, out)


__all__.append("UnrollBinaryImage")
