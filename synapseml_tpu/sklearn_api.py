"""sklearn-compatible estimator surface — GENERATED, do not edit.

Regenerate with ``python -m synapseml_tpu.codegen --sklearn``. Every
registered Estimator is wrapped in the sklearn protocol:

    from synapseml_tpu.sklearn_api import SkLightGBMClassifier
    clf = SkLightGBMClassifier(num_iterations=50).fit(X, y)
    proba = clf.predict_proba(X_test)

``fit(X, y=None, **columns)`` builds the native Table (``X`` -> the
estimator's features column, ``y`` -> its label column, extra arrays by
column name — e.g. ``group=`` for the ranker); ``predict`` returns the
model's prediction column, ``predict_proba`` the probability column where
one exists. ``get_params``/``set_params`` follow the sklearn clone
protocol, so these wrappers drop into sklearn model selection utilities.
"""

# fmt: off
# flake8: noqa

import numpy as np

try:  # BaseEstimator supplies __sklearn_tags__ etc. for sklearn >= 1.6
    from sklearn.base import BaseEstimator as _SkParent
except ImportError:  # sklearn absent: the protocol still works standalone
    class _SkParent:  # type: ignore[no-redef]
        pass


class _SkBase(_SkParent):
    """Shared sklearn-protocol plumbing over a native estimator class."""

    _native_module = None
    _native_class = None
    _features_col = None
    _label_col = None
    _prediction_col = None
    _probability_col = None

    def __init__(self, **params):
        self._validate(params)
        for name in self._param_names:
            if name in params:
                # user values stored UNMODIFIED: sklearn clone() checks
                # identity of constructor params
                value = params[name]
            else:
                value = self._param_defaults[name]
                if isinstance(value, (list, dict, set)):
                    # never alias the shared class-level mutable default
                    value = value.copy()
            setattr(self, name, value)
        self.model_ = None

    def _validate(self, params):
        unknown = set(params) - set(self._param_names)
        if unknown:
            raise TypeError(
                f"{type(self).__name__}: unknown params {sorted(unknown)}")
        for k, v in params.items():
            if v is None and self._param_defaults[k] is not None:
                # silently mapping None back to the default would make
                # get_params() disagree with the fitted native estimator
                raise TypeError(
                    f"{type(self).__name__}: {k}=None is not valid "
                    f"(omit it for the default {self._param_defaults[k]!r})")

    # -- sklearn clone protocol ------------------------------------------------

    def get_params(self, deep: bool = True):
        return {n: getattr(self, n) for n in self._param_names}

    def set_params(self, **params):
        self._validate(params)
        for k, v in params.items():
            setattr(self, k, v)  # as-is: sklearn set_params/clone semantics
        return self

    def __sklearn_tags__(self):
        tags = super().__sklearn_tags__()  # needs sklearn >= 1.6
        est_type = getattr(self, "_estimator_type", None)
        if est_type is not None:
            tags.estimator_type = est_type
        return tags

    def score(self, X, y, **columns):
        """Accuracy for classifiers, R^2 for regressors (the sklearn
        default-scoring contract model selection relies on)."""
        pred = self.predict(X, **columns)
        y = np.asarray(y)
        if getattr(self, "_estimator_type", None) == "classifier":
            return float((pred == y).mean())
        ss_res = float(((y - pred) ** 2).sum())
        ss_tot = float(((y - y.mean()) ** 2).sum())
        return 1.0 - ss_res / ss_tot if ss_tot else 0.0

    # -- native bridge ---------------------------------------------------------

    def _native(self):
        import importlib

        cls = getattr(importlib.import_module(self._native_module),
                      self._native_class)
        # None only ever means "the native default" here (_validate rejects
        # explicit None for non-None defaults), so omit those args
        kw = {n: getattr(self, n) for n in self._param_names
              if getattr(self, n) is not None}
        return cls(**kw)

    def _table(self, X, y=None, **columns):
        from synapseml_tpu.core import Table

        cols = {}
        if X is not None:
            cols[getattr(self, self._features_col)
                 if self._features_col else "features"] = np.asarray(X)
        if y is not None:
            cols[getattr(self, self._label_col)
                 if self._label_col else "label"] = np.asarray(y)
        for name, arr in columns.items():
            cols[name] = np.asarray(arr)
        return Table(cols)

    def fit(self, X, y=None, **columns):
        self.model_ = self._native().fit(self._table(X, y, **columns))
        if y is not None and                 getattr(self, "_estimator_type", None) == "classifier":
            # sklearn scorers resolve predict_proba columns via classes_
            self.classes_ = np.unique(np.asarray(y))
        return self

    def _check_fitted(self):
        if self.model_ is None:
            raise RuntimeError(
                f"{type(self).__name__} is not fitted; call fit first")

    def transform(self, X, **columns):
        """The fitted model's full output Table (every output column)."""
        self._check_fitted()
        return self.model_.transform(self._table(X, **columns))

    def predict(self, X, **columns):
        self._check_fitted()
        out = self.transform(X, **columns)
        col = (getattr(self, self._prediction_col)
               if self._prediction_col else "prediction")
        return np.asarray(out[col])

    def predict_proba(self, X, **columns):
        if self._probability_col is None:
            raise AttributeError(
                f"{type(self).__name__} has no probability output")
        self._check_fitted()
        out = self.transform(X, **columns)
        return np.asarray(out[getattr(self, self._probability_col)])

    def __repr__(self):
        def differs(v, d):
            try:
                return bool(v != d)
            except Exception:  # e.g. numpy array vs list comparison
                return True

        changed = {n: v for n, v in self.get_params().items()
                   if differs(v, self._param_defaults[n])}
        args = ", ".join(f"{k}={v!r}" for k, v in sorted(changed.items()))
        return f"{type(self).__name__}({args})"


class SkAccessAnomaly(_SkBase):
    """Reference ``AccessAnomaly:472``; param names snake_cased from the"""

    _native_module = 'synapseml_tpu.cyber.anomaly'
    _native_class = 'AccessAnomaly'
    _param_names = ('alpha_param', 'apply_implicit_cf', 'complementset_factor', 'high_value', 'likelihood_col', 'low_value', 'max_iter', 'neg_score', 'output_col', 'rank_param', 'reg_param', 'res_col', 'seed', 'tenant_col', 'user_col')
    _param_defaults = {'alpha_param': 1.0, 'apply_implicit_cf': True, 'complementset_factor': 2, 'high_value': 10.0, 'likelihood_col': 'likelihood', 'low_value': 5.0, 'max_iter': 25, 'neg_score': 1.0, 'output_col': 'anomaly_score', 'rank_param': 10, 'reg_param': 1.0, 'res_col': 'res', 'seed': 0, 'tenant_col': 'tenant', 'user_col': 'user'}


class SkClassBalancer(_SkBase):
    """Compute inverse-frequency class weights (``ClassBalancer.scala``):"""

    _native_module = 'synapseml_tpu.stages.grouping'
    _native_class = 'ClassBalancer'
    _param_names = ('input_col', 'output_col')
    _param_defaults = {'input_col': 'label', 'output_col': 'weight'}


class SkCleanMissingData(_SkBase):
    """Impute NaN/None in numeric columns (reference ``CleanMissingData.scala``;"""

    _native_module = 'synapseml_tpu.featurize.stages'
    _native_class = 'CleanMissingData'
    _param_names = ('cleaning_mode', 'custom_value', 'input_cols', 'output_cols')
    _param_defaults = {'cleaning_mode': 'Mean', 'custom_value': 0.0, 'input_cols': [], 'output_cols': []}


class SkConditionalKNN(_SkBase):
    """Reference ``ConditionalKNN.scala:31``: like KNN but each query carries"""

    _native_module = 'synapseml_tpu.nn.knn'
    _native_class = 'ConditionalKNN'
    _features_col = 'features_col'
    _label_col = 'label_col'
    _param_names = ('conditioner_col', 'features_col', 'k', 'label_col', 'leaf_size', 'output_col', 'values_col')
    _param_defaults = {'conditioner_col': 'conditioner', 'features_col': 'features', 'k': 5, 'label_col': 'labels', 'leaf_size': 50, 'output_col': 'output', 'values_col': 'values'}


class SkCountSelector(_SkBase):
    """Drop all-zero / constant vector slots (reference ``CountSelector.scala``"""

    _native_module = 'synapseml_tpu.featurize.stages'
    _native_class = 'CountSelector'
    _param_names = ('input_col', 'output_col')
    _param_defaults = {'input_col': 'features', 'output_col': 'features'}


class SkFeaturize(_SkBase):
    """Auto-featurize arbitrary columns into one numeric vector"""

    _native_module = 'synapseml_tpu.featurize.stages'
    _native_class = 'Featurize'
    _param_names = ('input_cols', 'max_one_hot', 'num_features', 'one_hot_encode_categoricals', 'output_col')
    _param_defaults = {'input_cols': [], 'max_one_hot': 64, 'num_features': 262144, 'one_hot_encode_categoricals': True, 'output_col': 'features'}


class SkFindBestModel(_SkBase):
    """Pick the best of several FITTED models on an evaluation table"""

    _native_module = 'synapseml_tpu.automl.stages'
    _native_class = 'FindBestModel'
    _label_col = 'label_col'
    _param_names = ('evaluation_metric', 'label_col')
    _param_defaults = {'evaluation_metric': 'auc', 'label_col': 'label'}


class SkFitMultivariateAnomaly(_SkBase):
    """Reference ``FitMultivariateAnomaly`` (``MultivariateAnomalyDetection.scala:304``):"""

    _native_module = 'synapseml_tpu.cognitive.extended'
    _native_class = 'FitMultivariateAnomaly'
    _param_names = ('align_mode', 'backoffs', 'concurrency', 'display_name', 'end_time', 'error_col', 'fill_na_method', 'location', 'max_polling_retries', 'output_col', 'padding_value', 'polling_delay', 'sliding_window', 'source', 'start_time', 'subscription_key', 'subscription_key_col', 'timeout', 'url')
    _param_defaults = {'align_mode': 'Outer', 'backoffs': [100, 500, 1000], 'concurrency': 4, 'display_name': '', 'end_time': '', 'error_col': 'errors', 'fill_na_method': 'Linear', 'location': '', 'max_polling_retries': 100, 'output_col': 'output', 'padding_value': 0.0, 'polling_delay': 0.3, 'sliding_window': 300, 'source': '', 'start_time': '', 'subscription_key': None, 'subscription_key_col': None, 'timeout': 60.0, 'url': ''}


class SkFormOntologyLearner(_SkBase):
    """Reference ``FormOntologyLearner`` (``FormOntologyLearner.scala:42``):"""

    _native_module = 'synapseml_tpu.cognitive.extended'
    _native_class = 'FormOntologyLearner'
    _param_names = ('input_col', 'output_col')
    _param_defaults = {'input_col': 'form', 'output_col': 'out'}


class SkIdIndexer(_SkBase):
    """IdIndexer"""

    _native_module = 'synapseml_tpu.cyber.indexers'
    _native_class = 'IdIndexer'
    _param_names = ('input_col', 'output_col', 'partition_key', 'reset_per_partition')
    _param_defaults = {'input_col': 'input', 'output_col': 'output', 'partition_key': 'tenant', 'reset_per_partition': False}


class SkIsolationForest(_SkBase):
    """Reference param surface (LinkedIn ``IsolationForestParams`` via"""

    _native_module = 'synapseml_tpu.isolationforest.forest'
    _native_class = 'IsolationForest'
    _features_col = 'features_col'
    _prediction_col = 'prediction_col'
    _param_names = ('bootstrap', 'contamination', 'features_col', 'max_features', 'max_samples', 'num_estimators', 'prediction_col', 'random_seed', 'score_col')
    _param_defaults = {'bootstrap': False, 'contamination': 0.0, 'features_col': 'features', 'max_features': 1.0, 'max_samples': 256, 'num_estimators': 100, 'prediction_col': 'predictedLabel', 'random_seed': 1, 'score_col': 'outlierScore'}


class SkKNN(_SkBase):
    """Reference ``KNN.scala:48``: indexes (features, values); queries return"""

    _native_module = 'synapseml_tpu.nn.knn'
    _native_class = 'KNN'
    _features_col = 'features_col'
    _param_names = ('features_col', 'k', 'leaf_size', 'output_col', 'values_col')
    _param_defaults = {'features_col': 'features', 'k': 5, 'leaf_size': 50, 'output_col': 'output', 'values_col': 'values'}


class SkLightGBMClassifier(_SkBase):
    """Reference: ``LightGBMClassifier.scala:26``. Auto-selects binary vs multiclass"""

    _native_module = 'synapseml_tpu.gbdt.estimators'
    _native_class = 'LightGBMClassifier'
    _features_col = 'features_col'
    _label_col = 'label_col'
    _prediction_col = 'prediction_col'
    _probability_col = 'probability_col'
    _estimator_type = 'classifier'
    _param_names = ('bagging_fraction', 'bagging_freq', 'bagging_seed', 'bin_sample_count', 'boost_from_average', 'boosting_type', 'cat_smooth', 'categorical_slot_indexes', 'categorical_slot_names', 'drop_rate', 'early_stopping_round', 'feature_fraction', 'features_col', 'features_shap_col', 'improvement_tolerance', 'init_score_col', 'is_unbalance', 'label_col', 'lambda_l1', 'lambda_l2', 'leaf_prediction_col', 'learning_rate', 'max_bin', 'max_bin_by_feature', 'max_cat_threshold', 'max_delta_step', 'max_depth', 'max_drop', 'metric', 'min_data_in_leaf', 'min_gain_to_split', 'min_sum_hessian_in_leaf', 'neg_bagging_fraction', 'num_batches', 'num_iterations', 'num_leaves', 'objective', 'other_rate', 'parallelism', 'pos_bagging_fraction', 'prediction_col', 'probability_col', 'raw_prediction_col', 'seed', 'skip_drop', 'sparse_num_bits', 'top_k', 'top_rate', 'uniform_drop', 'use_barrier_execution_mode', 'validation_indicator_col', 'verbosity', 'weight_col', 'xgboost_dart_mode')
    _param_defaults = {'bagging_fraction': 1.0, 'bagging_freq': 0, 'bagging_seed': 3, 'bin_sample_count': 200000, 'boost_from_average': True, 'boosting_type': 'gbdt', 'cat_smooth': 10.0, 'categorical_slot_indexes': [], 'categorical_slot_names': [], 'drop_rate': 0.1, 'early_stopping_round': 0, 'feature_fraction': 1.0, 'features_col': 'features', 'features_shap_col': None, 'improvement_tolerance': 0.0, 'init_score_col': None, 'is_unbalance': False, 'label_col': 'label', 'lambda_l1': 0.0, 'lambda_l2': 0.0, 'leaf_prediction_col': None, 'learning_rate': 0.1, 'max_bin': 255, 'max_bin_by_feature': [], 'max_cat_threshold': 32, 'max_delta_step': 0.0, 'max_depth': -1, 'max_drop': 50, 'metric': '', 'min_data_in_leaf': 20, 'min_gain_to_split': 0.0, 'min_sum_hessian_in_leaf': 0.001, 'neg_bagging_fraction': 1.0, 'num_batches': 0, 'num_iterations': 100, 'num_leaves': 31, 'objective': '', 'other_rate': 0.1, 'parallelism': 'data_parallel', 'pos_bagging_fraction': 1.0, 'prediction_col': 'prediction', 'probability_col': 'probability', 'raw_prediction_col': 'rawPrediction', 'seed': 0, 'skip_drop': 0.5, 'sparse_num_bits': 18, 'top_k': 20, 'top_rate': 0.2, 'uniform_drop': False, 'use_barrier_execution_mode': False, 'validation_indicator_col': None, 'verbosity': -1, 'weight_col': None, 'xgboost_dart_mode': False}


class SkLightGBMRanker(_SkBase):
    """Reference: ``LightGBMRanker.scala:25`` — lambdarank over ``group_col``."""

    _native_module = 'synapseml_tpu.gbdt.estimators'
    _native_class = 'LightGBMRanker'
    _features_col = 'features_col'
    _label_col = 'label_col'
    _prediction_col = 'prediction_col'
    _estimator_type = 'regressor'
    _param_names = ('bagging_fraction', 'bagging_freq', 'bagging_seed', 'bin_sample_count', 'boost_from_average', 'boosting_type', 'cat_smooth', 'categorical_slot_indexes', 'categorical_slot_names', 'drop_rate', 'early_stopping_round', 'feature_fraction', 'features_col', 'features_shap_col', 'group_col', 'improvement_tolerance', 'init_score_col', 'label_col', 'lambda_l1', 'lambda_l2', 'lambdarank_truncation_level', 'leaf_prediction_col', 'learning_rate', 'max_bin', 'max_bin_by_feature', 'max_cat_threshold', 'max_delta_step', 'max_depth', 'max_drop', 'max_position', 'metric', 'min_data_in_leaf', 'min_gain_to_split', 'min_sum_hessian_in_leaf', 'ndcg_at', 'neg_bagging_fraction', 'num_batches', 'num_iterations', 'num_leaves', 'objective', 'other_rate', 'parallelism', 'pos_bagging_fraction', 'prediction_col', 'seed', 'skip_drop', 'sparse_num_bits', 'top_k', 'top_rate', 'uniform_drop', 'use_barrier_execution_mode', 'validation_indicator_col', 'verbosity', 'weight_col', 'xgboost_dart_mode')
    _param_defaults = {'bagging_fraction': 1.0, 'bagging_freq': 0, 'bagging_seed': 3, 'bin_sample_count': 200000, 'boost_from_average': True, 'boosting_type': 'gbdt', 'cat_smooth': 10.0, 'categorical_slot_indexes': [], 'categorical_slot_names': [], 'drop_rate': 0.1, 'early_stopping_round': 0, 'feature_fraction': 1.0, 'features_col': 'features', 'features_shap_col': None, 'group_col': 'group', 'improvement_tolerance': 0.0, 'init_score_col': None, 'label_col': 'label', 'lambda_l1': 0.0, 'lambda_l2': 0.0, 'lambdarank_truncation_level': 30, 'leaf_prediction_col': None, 'learning_rate': 0.1, 'max_bin': 255, 'max_bin_by_feature': [], 'max_cat_threshold': 32, 'max_delta_step': 0.0, 'max_depth': -1, 'max_drop': 50, 'max_position': 20, 'metric': '', 'min_data_in_leaf': 20, 'min_gain_to_split': 0.0, 'min_sum_hessian_in_leaf': 0.001, 'ndcg_at': 10, 'neg_bagging_fraction': 1.0, 'num_batches': 0, 'num_iterations': 100, 'num_leaves': 31, 'objective': 'lambdarank', 'other_rate': 0.1, 'parallelism': 'data_parallel', 'pos_bagging_fraction': 1.0, 'prediction_col': 'prediction', 'seed': 0, 'skip_drop': 0.5, 'sparse_num_bits': 18, 'top_k': 20, 'top_rate': 0.2, 'uniform_drop': False, 'use_barrier_execution_mode': False, 'validation_indicator_col': None, 'verbosity': -1, 'weight_col': None, 'xgboost_dart_mode': False}


class SkLightGBMRegressor(_SkBase):
    """Reference: ``LightGBMRegressor.scala:38`` (objectives regression/l1/huber/"""

    _native_module = 'synapseml_tpu.gbdt.estimators'
    _native_class = 'LightGBMRegressor'
    _features_col = 'features_col'
    _label_col = 'label_col'
    _prediction_col = 'prediction_col'
    _estimator_type = 'regressor'
    _param_names = ('alpha', 'bagging_fraction', 'bagging_freq', 'bagging_seed', 'bin_sample_count', 'boost_from_average', 'boosting_type', 'cat_smooth', 'categorical_slot_indexes', 'categorical_slot_names', 'drop_rate', 'early_stopping_round', 'feature_fraction', 'features_col', 'features_shap_col', 'improvement_tolerance', 'init_score_col', 'label_col', 'lambda_l1', 'lambda_l2', 'leaf_prediction_col', 'learning_rate', 'max_bin', 'max_bin_by_feature', 'max_cat_threshold', 'max_delta_step', 'max_depth', 'max_drop', 'metric', 'min_data_in_leaf', 'min_gain_to_split', 'min_sum_hessian_in_leaf', 'neg_bagging_fraction', 'num_batches', 'num_iterations', 'num_leaves', 'objective', 'other_rate', 'parallelism', 'pos_bagging_fraction', 'prediction_col', 'seed', 'skip_drop', 'sparse_num_bits', 'top_k', 'top_rate', 'tweedie_variance_power', 'uniform_drop', 'use_barrier_execution_mode', 'validation_indicator_col', 'verbosity', 'weight_col', 'xgboost_dart_mode')
    _param_defaults = {'alpha': 0.9, 'bagging_fraction': 1.0, 'bagging_freq': 0, 'bagging_seed': 3, 'bin_sample_count': 200000, 'boost_from_average': True, 'boosting_type': 'gbdt', 'cat_smooth': 10.0, 'categorical_slot_indexes': [], 'categorical_slot_names': [], 'drop_rate': 0.1, 'early_stopping_round': 0, 'feature_fraction': 1.0, 'features_col': 'features', 'features_shap_col': None, 'improvement_tolerance': 0.0, 'init_score_col': None, 'label_col': 'label', 'lambda_l1': 0.0, 'lambda_l2': 0.0, 'leaf_prediction_col': None, 'learning_rate': 0.1, 'max_bin': 255, 'max_bin_by_feature': [], 'max_cat_threshold': 32, 'max_delta_step': 0.0, 'max_depth': -1, 'max_drop': 50, 'metric': '', 'min_data_in_leaf': 20, 'min_gain_to_split': 0.0, 'min_sum_hessian_in_leaf': 0.001, 'neg_bagging_fraction': 1.0, 'num_batches': 0, 'num_iterations': 100, 'num_leaves': 31, 'objective': 'regression', 'other_rate': 0.1, 'parallelism': 'data_parallel', 'pos_bagging_fraction': 1.0, 'prediction_col': 'prediction', 'seed': 0, 'skip_drop': 0.5, 'sparse_num_bits': 18, 'top_k': 20, 'top_rate': 0.2, 'tweedie_variance_power': 1.5, 'uniform_drop': False, 'use_barrier_execution_mode': False, 'validation_indicator_col': None, 'verbosity': -1, 'weight_col': None, 'xgboost_dart_mode': False}


class SkLinearScalarScaler(_SkBase):
    """LinearScalarScaler"""

    _native_module = 'synapseml_tpu.cyber.scalers'
    _native_class = 'LinearScalarScaler'
    _param_names = ('input_col', 'max_required_value', 'min_required_value', 'output_col', 'partition_key')
    _param_defaults = {'input_col': 'input', 'max_required_value': 1.0, 'min_required_value': 0.0, 'output_col': 'output', 'partition_key': None}


class SkMultiColumnAdapter(_SkBase):
    """Apply a single-column stage to many columns (``MultiColumnAdapter.scala``):"""

    _native_module = 'synapseml_tpu.stages.text'
    _native_class = 'MultiColumnAdapter'
    _param_names = ('input_cols', 'output_cols')
    _param_defaults = {'input_cols': None, 'output_cols': None}


class SkMultiIndexer(_SkBase):
    """Fits several IdIndexers on one pass (reference ``MultiIndexer:130``)."""

    _native_module = 'synapseml_tpu.cyber.indexers'
    _native_class = 'MultiIndexer'
    _param_names = ()
    _param_defaults = {}


class SkRankingAdapter(_SkBase):
    """Wraps a recommender estimator so classic evaluators see"""

    _native_module = 'synapseml_tpu.recommendation.ranking'
    _native_class = 'RankingAdapter'
    _label_col = 'label_col'
    _param_names = ('k', 'label_col', 'min_ratings_per_item', 'min_ratings_per_user', 'mode')
    _param_defaults = {'k': 10, 'label_col': 'label', 'min_ratings_per_item': 1, 'min_ratings_per_user': 1, 'mode': 'allUsers'}


class SkRankingTrainValidationSplit(_SkBase):
    """Per-user stratified train/validation split + param-map search over a"""

    _native_module = 'synapseml_tpu.recommendation.ranking'
    _native_class = 'RankingTrainValidationSplit'
    _param_names = ('item_col', 'min_ratings_i', 'min_ratings_u', 'parallelism', 'rating_col', 'seed', 'train_ratio', 'user_col')
    _param_defaults = {'item_col': 'item', 'min_ratings_i': 1, 'min_ratings_u': 1, 'parallelism': 1, 'rating_col': 'rating', 'seed': 0, 'train_ratio': 0.75, 'user_col': 'user'}


class SkRecommendationIndexer(_SkBase):
    """Raw user/item ids (strings or sparse ints) -> dense indices"""

    _native_module = 'synapseml_tpu.recommendation.ranking'
    _native_class = 'RecommendationIndexer'
    _param_names = ('item_input_col', 'item_output_col', 'rating_col', 'user_input_col', 'user_output_col')
    _param_defaults = {'item_input_col': 'item', 'item_output_col': 'item_idx', 'rating_col': 'rating', 'user_input_col': 'user', 'user_output_col': 'user_idx'}


class SkSAR(_SkBase):
    """Reference ``SAR.scala:36``. Ids must be non-negative integers (use"""

    _native_module = 'synapseml_tpu.recommendation.sar'
    _native_class = 'SAR'
    _param_names = ('activity_time_format', 'item_col', 'rating_col', 'similarity_function', 'start_time', 'start_time_format', 'support_threshold', 'time_col', 'time_decay_coeff', 'user_col')
    _param_defaults = {'activity_time_format': '%Y/%m/%dT%H:%M:%S', 'item_col': 'item', 'rating_col': 'rating', 'similarity_function': 'jaccard', 'start_time': None, 'start_time_format': '%a %b %d %H:%M:%S %z %Y', 'support_threshold': 4, 'time_col': 'time', 'time_decay_coeff': 30, 'user_col': 'user'}


class SkStandardScalarScaler(_SkBase):
    """StandardScalarScaler"""

    _native_module = 'synapseml_tpu.cyber.scalers'
    _native_class = 'StandardScalarScaler'
    _param_names = ('coefficient_factor', 'input_col', 'output_col', 'partition_key')
    _param_defaults = {'coefficient_factor': 1.0, 'input_col': 'input', 'output_col': 'output', 'partition_key': None}


class SkTextFeaturizer(_SkBase):
    """Tokenize -> n-grams -> hashing TF -> IDF vector"""

    _native_module = 'synapseml_tpu.featurize.text'
    _native_class = 'TextFeaturizer'
    _param_names = ('binary', 'input_col', 'n_gram_length', 'num_features', 'output_col', 'to_lowercase', 'use_idf')
    _param_defaults = {'binary': False, 'input_col': 'text', 'n_gram_length': 1, 'num_features': 4096, 'output_col': 'features', 'to_lowercase': True, 'use_idf': True}


class SkTimer(_SkBase):
    """Time fit/transform of a wrapped stage (``Timer.scala``)."""

    _native_module = 'synapseml_tpu.stages.basic'
    _native_class = 'Timer'
    _param_names = ('log_to_logger',)
    _param_defaults = {'log_to_logger': True}


class SkTrainClassifier(_SkBase):
    """Featurize + index labels + fit (reference ``TrainClassifier.scala:50``)."""

    _native_module = 'synapseml_tpu.train.stages'
    _native_class = 'TrainClassifier'
    _features_col = 'features_col'
    _label_col = 'label_col'
    _param_names = ('features_col', 'input_cols', 'label_col', 'number_of_features')
    _param_defaults = {'features_col': 'features', 'input_cols': [], 'label_col': 'label', 'number_of_features': 262144}


class SkTrainRegressor(_SkBase):
    """Reference ``TrainRegressor``. Default learner: LightGBMRegressor."""

    _native_module = 'synapseml_tpu.train.stages'
    _native_class = 'TrainRegressor'
    _features_col = 'features_col'
    _label_col = 'label_col'
    _param_names = ('features_col', 'input_cols', 'label_col', 'number_of_features')
    _param_defaults = {'features_col': 'features', 'input_cols': [], 'label_col': 'label', 'number_of_features': 262144}


class SkTuneHyperparameters(_SkBase):
    """Parallel hyperparameter search over estimator param spaces"""

    _native_module = 'synapseml_tpu.automl.stages'
    _native_class = 'TuneHyperparameters'
    _label_col = 'label_col'
    _param_names = ('budget', 'evaluation_metric', 'executor', 'journal_path', 'label_col', 'min_resource', 'number_of_runs', 'parallelism', 'search_mode', 'seed', 'train_ratio')
    _param_defaults = {'budget': 0, 'evaluation_metric': 'auc', 'executor': 'threads', 'journal_path': None, 'label_col': 'label', 'min_resource': 0, 'number_of_runs': 10, 'parallelism': 4, 'search_mode': 'random', 'seed': 0, 'train_ratio': 0.75}


class SkValueIndexer(_SkBase):
    """Categorical value -> dense index (reference ``ValueIndexer.scala``)."""

    _native_module = 'synapseml_tpu.featurize.stages'
    _native_class = 'ValueIndexer'
    _param_names = ('input_col', 'output_col')
    _param_defaults = {'input_col': 'input', 'output_col': 'output'}


class SkVowpalWabbitClassifier(_SkBase):
    """Binary classifier (reference ``VowpalWabbitClassifier``; VW logistic loss,"""

    _native_module = 'synapseml_tpu.vw.estimators'
    _native_class = 'VowpalWabbitClassifier'
    _features_col = 'features_col'
    _label_col = 'label_col'
    _prediction_col = 'prediction_col'
    _probability_col = 'probability_col'
    _estimator_type = 'classifier'
    _param_names = ('additional_features', 'batch_size', 'features_col', 'hash_seed', 'l1', 'l2', 'label_col', 'learning_rate', 'loss_function', 'num_bits', 'num_passes', 'pass_through_args', 'power_t', 'prediction_col', 'probability_col', 'raw_prediction_col', 'use_barrier_execution_mode', 'weight_col')
    _param_defaults = {'additional_features': [], 'batch_size': 256, 'features_col': 'features', 'hash_seed': 0, 'l1': 0.0, 'l2': 0.0, 'label_col': 'label', 'learning_rate': 0.5, 'loss_function': 'logistic', 'num_bits': 18, 'num_passes': 1, 'pass_through_args': '', 'power_t': 0.5, 'prediction_col': 'prediction', 'probability_col': 'probability', 'raw_prediction_col': 'rawPrediction', 'use_barrier_execution_mode': False, 'weight_col': None}


class SkVowpalWabbitContextualBandit(_SkBase):
    """Contextual bandit with per-action features (reference"""

    _native_module = 'synapseml_tpu.vw.estimators'
    _native_class = 'VowpalWabbitContextualBandit'
    _features_col = 'features_col'
    _label_col = 'label_col'
    _prediction_col = 'prediction_col'
    _probability_col = 'probability_col'
    _estimator_type = 'classifier'
    _param_names = ('additional_features', 'batch_size', 'chosen_action_col', 'epsilon', 'features_col', 'hash_seed', 'l1', 'l2', 'label_col', 'learning_rate', 'num_bits', 'num_passes', 'pass_through_args', 'power_t', 'prediction_col', 'probability_col', 'shared_col', 'use_barrier_execution_mode', 'weight_col')
    _param_defaults = {'additional_features': [], 'batch_size': 256, 'chosen_action_col': 'chosenAction', 'epsilon': 0.05, 'features_col': 'features', 'hash_seed': 0, 'l1': 0.0, 'l2': 0.0, 'label_col': 'label', 'learning_rate': 0.5, 'num_bits': 18, 'num_passes': 1, 'pass_through_args': '', 'power_t': 0.5, 'prediction_col': 'prediction', 'probability_col': 'probability', 'shared_col': 'shared', 'use_barrier_execution_mode': False, 'weight_col': None}


class SkVowpalWabbitRegressor(_SkBase):
    """Reference ``VowpalWabbitRegressor`` (squared / quantile loss)."""

    _native_module = 'synapseml_tpu.vw.estimators'
    _native_class = 'VowpalWabbitRegressor'
    _features_col = 'features_col'
    _label_col = 'label_col'
    _prediction_col = 'prediction_col'
    _estimator_type = 'regressor'
    _param_names = ('additional_features', 'batch_size', 'features_col', 'hash_seed', 'l1', 'l2', 'label_col', 'learning_rate', 'loss_function', 'num_bits', 'num_passes', 'pass_through_args', 'power_t', 'prediction_col', 'quantile_tau', 'use_barrier_execution_mode', 'weight_col')
    _param_defaults = {'additional_features': [], 'batch_size': 256, 'features_col': 'features', 'hash_seed': 0, 'l1': 0.0, 'l2': 0.0, 'label_col': 'label', 'learning_rate': 0.5, 'loss_function': 'squared', 'num_bits': 18, 'num_passes': 1, 'pass_through_args': '', 'power_t': 0.5, 'prediction_col': 'prediction', 'quantile_tau': 0.5, 'use_barrier_execution_mode': False, 'weight_col': None}


__all__ = ["SkAccessAnomaly", "SkClassBalancer", "SkCleanMissingData", "SkConditionalKNN", "SkCountSelector", "SkFeaturize", "SkFindBestModel", "SkFitMultivariateAnomaly", "SkFormOntologyLearner", "SkIdIndexer", "SkIsolationForest", "SkKNN", "SkLightGBMClassifier", "SkLightGBMRanker", "SkLightGBMRegressor", "SkLinearScalarScaler", "SkMultiColumnAdapter", "SkMultiIndexer", "SkRankingAdapter", "SkRankingTrainValidationSplit", "SkRecommendationIndexer", "SkSAR", "SkStandardScalarScaler", "SkTextFeaturizer", "SkTimer", "SkTrainClassifier", "SkTrainRegressor", "SkTuneHyperparameters", "SkValueIndexer", "SkVowpalWabbitClassifier", "SkVowpalWabbitContextualBandit", "SkVowpalWabbitRegressor"]
