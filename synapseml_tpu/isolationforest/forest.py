"""Isolation forest: host-built random trees, device-scored path lengths.

Reference behavior: LinkedIn isolation-forest as wrapped by
``isolationforest/IsolationForest.scala:18-65`` — params ``numEstimators``,
``maxSamples``, ``maxFeatures``, ``contamination``, ``bootstrap``,
``randomSeed``; outputs ``outlierScore`` (2^(-E[h(x)]/c(m))) and
``predictedLabel`` (score >= threshold from the train-score contamination
quantile).

TPU-first: trees are complete heap arrays (feature, threshold, leaf path
length); scoring is ``vmap`` over trees of a ``fori_loop`` heap descent —
(T, n) path lengths in one jit, no per-row Python.
"""

from __future__ import annotations

import math
from functools import lru_cache
from typing import Tuple

import numpy as np

from ..core import ComplexParam, Estimator, Model, Param, Table
from ..core.params import ParamValidators
from ..core.table import features_matrix

__all__ = ["IsolationForest", "IsolationForestModel"]

_EULER = 0.5772156649015329


def _avg_path_length(n) -> float:
    """c(n): expected unsuccessful-search path length in a BST of n points."""
    if n <= 1:
        return 0.0
    if n == 2:
        return 1.0
    h = math.log(n - 1.0) + _EULER
    return 2.0 * h - 2.0 * (n - 1.0) / n


def _build_tree(x: np.ndarray, feat_subset: np.ndarray, depth_limit: int,
                rng) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """One isolation tree over subsample ``x`` as heap arrays.

    Returns (feature, threshold, path_len) each sized 2^(depth_limit+1)-1.
    Internal nodes: feature >= 0, route by value > threshold. Leaves:
    feature = -1 and path_len = depth + c(n_node)."""
    n_nodes = 2 ** (depth_limit + 1) - 1
    feature = np.full(n_nodes, -1, dtype=np.int32)
    threshold = np.zeros(n_nodes, dtype=np.float32)
    path_len = np.zeros(n_nodes, dtype=np.float32)

    # iterative (node, row-indices, depth) worklist; heap child = 2i+1 / 2i+2
    work = [(0, np.arange(len(x)), 0)]
    while work:
        node, idx, depth = work.pop()
        rows = x[idx]
        if depth >= depth_limit or len(idx) <= 1:
            path_len[node] = depth + _avg_path_length(len(idx))
            continue
        # random feature among those with spread, random split in (min, max)
        spread = rows[:, feat_subset].max(0) - rows[:, feat_subset].min(0)
        candidates = feat_subset[spread > 0]
        if len(candidates) == 0:
            path_len[node] = depth + _avg_path_length(len(idx))
            continue
        f = int(candidates[rng.integers(len(candidates))])
        lo, hi = rows[:, f].min(), rows[:, f].max()
        t = float(rng.uniform(lo, hi))
        go_right = rows[:, f] > t
        feature[node] = f
        threshold[node] = t
        work.append((2 * node + 1, idx[~go_right], depth + 1))
        work.append((2 * node + 2, idx[go_right], depth + 1))
    return feature, threshold, path_len


@lru_cache(maxsize=32)
def _score_fn(depth_limit: int):
    import jax
    import jax.numpy as jnp

    @jax.jit
    def score(x, feature, threshold, path_len, c_norm):
        """x (n, d); tree arrays (T, nodes). Returns (n,) outlier scores."""

        def one_tree(feat_t, thr_t, pl_t):
            def step(_, idx):
                f = feat_t[idx]
                go = jnp.take_along_axis(x, jnp.maximum(f, 0)[:, None],
                                         axis=1)[:, 0] > thr_t[idx]
                nxt = 2 * idx + 1 + go.astype(jnp.int32)
                return jnp.where(f < 0, idx, nxt)

            idx = jax.lax.fori_loop(0, depth_limit, step,
                                    jnp.zeros(x.shape[0], jnp.int32))
            return pl_t[idx]

        pl = jax.vmap(one_tree)(feature, threshold, path_len)  # (T, n)
        return jnp.power(2.0, -pl.mean(0) / c_norm)

    return score


class IsolationForest(Estimator):
    """Reference param surface (LinkedIn ``IsolationForestParams`` via
    ``IsolationForest.scala``), snake_cased."""

    features_col = Param("features column (vector)", str, default="features")
    prediction_col = Param("0/1 outlier prediction column", str,
                           default="predictedLabel")
    score_col = Param("outlier score column", str, default="outlierScore")
    num_estimators = Param("number of isolation trees", int, default=100,
                           validator=ParamValidators.gt(0))
    max_samples = Param("subsample size per tree", int, default=256,
                        validator=ParamValidators.gt(1))
    max_features = Param("fraction of features per tree", float, default=1.0,
                         validator=ParamValidators.in_range(0.0, 1.0,
                                                            low_inclusive=False))
    contamination = Param("expected outlier fraction; 0 disables the "
                          "prediction threshold", float, default=0.0,
                          validator=ParamValidators.in_range(0.0, 0.5))
    bootstrap = Param("sample with replacement", bool, default=False)
    random_seed = Param("seed", int, default=1)

    def _fit(self, table: Table) -> "IsolationForestModel":
        self._validate_input(table, self.features_col)
        x = features_matrix(table[self.features_col])
        n, d = x.shape
        m = min(self.max_samples, n)
        depth_limit = max(1, int(math.ceil(math.log2(max(m, 2)))))
        n_feat = max(1, int(round(self.max_features * d)))
        rng = np.random.default_rng(self.random_seed)

        feats, thrs, pls = [], [], []
        for _ in range(self.num_estimators):
            idx = (rng.integers(0, n, size=m) if self.bootstrap
                   else rng.permutation(n)[:m])
            feat_subset = rng.permutation(d)[:n_feat]
            f, t, p = _build_tree(x[idx], feat_subset, depth_limit, rng)
            feats.append(f)
            thrs.append(t)
            pls.append(p)

        model = IsolationForestModel(
            features_col=self.features_col, prediction_col=self.prediction_col,
            score_col=self.score_col, contamination=self.contamination,
            depth_limit=depth_limit, c_norm=float(_avg_path_length(m)),
            tree_features=np.stack(feats), tree_thresholds=np.stack(thrs),
            tree_path_lens=np.stack(pls), score_threshold=2.0)
        if self.contamination > 0:
            scores = model._scores(x)
            model.set_params(score_threshold=float(
                np.quantile(scores, 1.0 - self.contamination)))
        return model


class IsolationForestModel(Model):
    features_col = Param("features column", str, default="features")
    prediction_col = Param("0/1 outlier prediction column", str,
                           default="predictedLabel")
    score_col = Param("outlier score column", str, default="outlierScore")
    contamination = Param("outlier fraction used at fit", float, default=0.0)
    depth_limit = Param("tree depth limit", int, default=8)
    c_norm = Param("c(max_samples) score normalizer", float, default=1.0)
    score_threshold = Param("score >= threshold -> outlier (2.0 = never, "
                            "used when contamination = 0)", float, default=2.0)
    tree_features = ComplexParam("(T, nodes) split features", object,
                                 default=None)
    tree_thresholds = ComplexParam("(T, nodes) split thresholds", object,
                                   default=None)
    tree_path_lens = ComplexParam("(T, nodes) leaf path lengths", object,
                                  default=None)

    def _scores(self, x: np.ndarray) -> np.ndarray:
        import jax.numpy as jnp

        fn = _score_fn(self.depth_limit)
        return np.asarray(fn(
            jnp.asarray(x, jnp.float32),
            jnp.asarray(np.asarray(self.tree_features)),
            jnp.asarray(np.asarray(self.tree_thresholds)),
            jnp.asarray(np.asarray(self.tree_path_lens)),
            jnp.float32(self.c_norm)))

    def _transform(self, table: Table) -> Table:
        self._validate_input(table, self.features_col)
        x = features_matrix(table[self.features_col])
        scores = self._scores(x)
        pred = (scores >= self.score_threshold).astype(np.float64)
        return (table.with_column(self.score_col, scores.astype(np.float64))
                .with_column(self.prediction_col, pred))
