"""Isolation Forest anomaly detection.

Reference: ``core/src/main/scala/.../isolationforest/IsolationForest.scala:18-65``
(a wrapper over ``com.linkedin.relevance.isolationforest``). Here the
algorithm itself is implemented: random-split isolation trees built on host
(cheap, tiny subsamples) and scored on device as a fixed-depth vectorized
heap-array traversal (same design as the GBDT device predictor).
"""

from .forest import IsolationForest, IsolationForestModel

__all__ = ["IsolationForest", "IsolationForestModel"]
