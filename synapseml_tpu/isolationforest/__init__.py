"""Isolation Forest anomaly detection.

Reference: ``core/src/main/scala/.../isolationforest/IsolationForest.scala:18-65``
(a wrapper over ``com.linkedin.relevance.isolationforest``). Here the
algorithm itself is implemented: random-split isolation trees built on host
(cheap, tiny subsamples) and scored on device as a fixed-depth vectorized
heap-array traversal (same design as the GBDT device predictor).
"""

from ..core.lazyimport import lazy_module

# PEP 562 lazy exports (lint SMT008): keeps the package import jax-free
__getattr__, __dir__, __all__ = lazy_module(__name__, {
    "forest": ["IsolationForest", "IsolationForestModel"],
})
