"""ONNX operator implementations on JAX.

Each entry maps an ONNX op_type to ``fn(inputs, attrs, ctx) -> output | tuple``.
``inputs`` holds jnp arrays (traced under jit), numpy arrays (graph constants —
initializers, Constant nodes, and anything derived only from them or from *shapes*),
or None for omitted optional inputs. Numpy-ness is significant: ops that *need* static
values (Reshape target, Slice bounds, ...) require numpy inputs, which the executor
guarantees by constant-folding shape arithmetic during tracing (under ``jit`` shapes are
static, so ``Shape`` always yields numpy — this is how dynamic-shape chains in BERT-style
exports compile to static XLA programs; reference pins only dim 0 instead,
``ONNXModel.scala:357-362``).

Opset notes: handles both attribute-style (<13) and input-style (>=13) axes for
Squeeze/Unsqueeze/Reduce*, Clip min/max attrs (<11) vs inputs, Pad attrs (<11) vs
inputs, Slice attrs (<10) vs inputs.

TPU notes: convs/matmuls go through ``lax.conv_general_dilated``/``jnp.matmul`` and land
on the MXU; XLA picks layouts (NCHW semantics preserved from ONNX). bf16 execution is
applied at the executor level by dtype policy, not per-op.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.lazyimport import lazy_import

# resolved on first attribute access inside an op body — importing the
# 129-op registry (or synapseml_tpu.onnx) stays jax-free (lint SMT001)
jax = lazy_import("jax")
jnp = lazy_import("jax.numpy")
lax = lazy_import("jax.lax")

OPS: Dict[str, Callable] = {}


def _lazy_fn(spec: str) -> Callable:
    """Resolve a dotted ``jnp.add`` / ``jax.nn.relu`` spec at *call* time
    (attribute access on the lazy proxies), so building the op tables
    below never imports jax."""
    root, _, rest = spec.partition(".")
    base = {"jax": jax, "jnp": jnp, "lax": lax}[root]

    def call(*args, **kw):
        fn = base
        for part in rest.split("."):
            fn = getattr(fn, part)
        return fn(*args, **kw)

    return call


def op(*names: str):
    def deco(fn):
        for n in names:
            OPS[n] = fn
        return fn

    return deco


def _static(v, what: str) -> np.ndarray:
    """Require a graph-constant (numpy) value; informative error otherwise."""
    if v is None:
        raise ValueError(f"{what}: missing required static input")
    if isinstance(v, np.ndarray) or np.isscalar(v):
        return np.asarray(v)
    raise ValueError(
        f"{what} must be a graph constant (initializer / shape-derived), got a traced "
        f"array; this graph has genuinely data-dependent shapes, which XLA cannot compile"
    )


def _ints(v, what: str) -> List[int]:
    return [int(x) for x in np.atleast_1d(_static(v, what))]


def _axis_list(attrs, inputs, idx, what, default=None):
    """axes from attrs (opset<13) or inputs[idx] (>=13)."""
    if attrs.get("axes") is not None:
        return [int(a) for a in attrs["axes"]]
    if len(inputs) > idx and inputs[idx] is not None:
        return _ints(inputs[idx], what)
    return default


# ---------------------------------------------------------------------------------
# elementwise math
# ---------------------------------------------------------------------------------

_BINOPS = {
    "Add": "jnp.add", "Sub": "jnp.subtract", "Mul": "jnp.multiply",
    "Div": "jnp.divide",
    "Pow": "jnp.power", "Mod": "jnp.mod",
    "PRelu": lambda x, s: jnp.where(x >= 0, x, x * s),
    "And": "jnp.logical_and", "Or": "jnp.logical_or", "Xor": "jnp.logical_xor",
    "BitwiseAnd": "jnp.bitwise_and", "BitwiseOr": "jnp.bitwise_or",
    "BitwiseXor": "jnp.bitwise_xor",
}
for _name, _fn in _BINOPS.items():
    _fn = _fn if callable(_fn) else _lazy_fn(_fn)
    OPS[_name] = (lambda f: lambda inputs, attrs, ctx: f(inputs[0], inputs[1]))(_fn)

_UNOPS = {
    "Sqrt": "jnp.sqrt", "Exp": "jnp.exp", "Log": "jnp.log", "Abs": "jnp.abs",
    "Neg": "jnp.negative",
    "Floor": "jnp.floor", "Ceil": "jnp.ceil", "Reciprocal": lambda x: 1.0 / x,
    "Sign": "jnp.sign", "Erf": "jax.scipy.special.erf",
    "Not": "jnp.logical_not",
    "Relu": "jax.nn.relu", "Sigmoid": "jax.nn.sigmoid", "Tanh": "jnp.tanh",
    "Softplus": "jax.nn.softplus", "Softsign": "jax.nn.soft_sign",
    "Identity": lambda x: x,
    "IsNaN": "jnp.isnan", "Sin": "jnp.sin", "Cos": "jnp.cos", "Tan": "jnp.tan",
    "Asin": "jnp.arcsin", "Acos": "jnp.arccos", "Atan": "jnp.arctan",
    "Sinh": "jnp.sinh", "Cosh": "jnp.cosh", "Asinh": "jnp.arcsinh",
    "Acosh": "jnp.arccosh",
    "Atanh": "jnp.arctanh", "BitwiseNot": "jnp.bitwise_not",
}
for _name, _fn in _UNOPS.items():
    _fn = _fn if callable(_fn) else _lazy_fn(_fn)
    OPS[_name] = (lambda f: lambda inputs, attrs, ctx: f(inputs[0]))(_fn)


@op("Round")
def _round(inputs, attrs, ctx):
    return jnp.round(inputs[0])  # banker's rounding matches ONNX spec


_COMPARE = {"Equal": _lazy_fn("jnp.equal"), "Greater": _lazy_fn("jnp.greater"),
            "GreaterOrEqual": _lazy_fn("jnp.greater_equal"),
            "Less": _lazy_fn("jnp.less"),
            "LessOrEqual": _lazy_fn("jnp.less_equal")}


@op("Equal", "Greater", "GreaterOrEqual", "Less", "LessOrEqual")
def _compare(inputs, attrs, ctx):
    return _COMPARE[ctx["op_type"]](inputs[0], inputs[1])


@op("Min", "Max", "Sum", "Mean")
def _variadic(inputs, attrs, ctx):
    vals = [v for v in inputs if v is not None]
    red = {"Min": jnp.minimum, "Max": jnp.maximum}.get(ctx["op_type"])
    if red is not None:
        return functools.reduce(red, vals)
    s = functools.reduce(jnp.add, vals)
    return s / len(vals) if ctx["op_type"] == "Mean" else s


@op("Clip")
def _clip(inputs, attrs, ctx):
    lo = attrs.get("min") if attrs.get("min") is not None else (inputs[1] if len(inputs) > 1 else None)
    hi = attrs.get("max") if attrs.get("max") is not None else (inputs[2] if len(inputs) > 2 else None)
    return jnp.clip(inputs[0], lo, hi)


@op("LeakyRelu")
def _leaky(inputs, attrs, ctx):
    return jax.nn.leaky_relu(inputs[0], attrs.get("alpha", 0.01))


@op("Elu")
def _elu(inputs, attrs, ctx):
    return jax.nn.elu(inputs[0], attrs.get("alpha", 1.0))


@op("Selu")
def _selu(inputs, attrs, ctx):
    a = attrs.get("alpha", 1.6732632423543772)
    g = attrs.get("gamma", 1.0507009873554805)
    x = inputs[0]
    return g * jnp.where(x > 0, x, a * (jnp.exp(x) - 1.0))


@op("Celu")
def _celu(inputs, attrs, ctx):
    return jax.nn.celu(inputs[0], attrs.get("alpha", 1.0))


@op("HardSigmoid")
def _hard_sigmoid(inputs, attrs, ctx):
    a, b = attrs.get("alpha", 0.2), attrs.get("beta", 0.5)
    return jnp.clip(a * inputs[0] + b, 0.0, 1.0)


@op("HardSwish")
def _hard_swish(inputs, attrs, ctx):
    x = inputs[0]
    return x * jnp.clip(x / 6.0 + 0.5, 0.0, 1.0)


@op("Mish")
def _mish(inputs, attrs, ctx):
    x = inputs[0]
    return x * jnp.tanh(jax.nn.softplus(x))


@op("Gelu")
def _gelu(inputs, attrs, ctx):
    approx = attrs.get("approximate", "none") == "tanh"
    return jax.nn.gelu(inputs[0], approximate=approx)


@op("Softmax")
def _softmax(inputs, attrs, ctx):
    axis = attrs.get("axis", -1 if ctx["opset"] >= 13 else 1)
    if ctx["opset"] >= 13:
        return jax.nn.softmax(inputs[0], axis=axis)
    # pre-13: flatten trailing dims from axis, softmax over the flattened tail
    x = inputs[0]
    shape = x.shape
    axis = axis % x.ndim  # spec coerces negative axis to axis + rank
    lead = int(np.prod(shape[:axis])) if axis > 0 else 1
    flat = x.reshape(lead, -1)
    return jax.nn.softmax(flat, axis=-1).reshape(shape)


@op("LogSoftmax")
def _log_softmax(inputs, attrs, ctx):
    axis = attrs.get("axis", -1 if ctx["opset"] >= 13 else 1)
    return jax.nn.log_softmax(inputs[0], axis=axis)


@op("Einsum")
def _einsum(inputs, attrs, ctx):
    return jnp.einsum(attrs["equation"], *[v for v in inputs if v is not None])


@op("CumSum")
def _cumsum(inputs, attrs, ctx):
    axis = int(_static(inputs[1], "CumSum.axis"))
    x = inputs[0]
    if attrs.get("reverse", 0):
        x = jnp.flip(x, axis)
    out = jnp.cumsum(x, axis=axis)
    if attrs.get("exclusive", 0):
        out = jnp.roll(out, 1, axis)
        idx = [slice(None)] * out.ndim
        idx[axis] = 0
        out = out.at[tuple(idx)].set(0)
    if attrs.get("reverse", 0):
        out = jnp.flip(out, axis)
    return out


# ---------------------------------------------------------------------------------
# matmul / gemm
# ---------------------------------------------------------------------------------

@op("MatMul")
def _matmul(inputs, attrs, ctx):
    return jnp.matmul(inputs[0], inputs[1], preferred_element_type=ctx.get("accum_dtype"))


@op("Gemm")
def _gemm(inputs, attrs, ctx):
    a, b = inputs[0], inputs[1]
    # transA/transB fold into the contraction dims: no transposed copy is
    # ever materialized, so a device-resident (sharded/fsdp-stored) B
    # traces identically to a host-constant B
    ca = 0 if attrs.get("transA", 0) else 1
    cb = 1 if attrs.get("transB", 0) else 0
    out = attrs.get("alpha", 1.0) * lax.dot_general(
        a, b, (((ca,), (cb,)), ((), ())),
        preferred_element_type=ctx.get("accum_dtype"))
    if len(inputs) > 2 and inputs[2] is not None:
        out = out + attrs.get("beta", 1.0) * inputs[2]
    return out.astype(a.dtype) if out.dtype != a.dtype else out


# ---------------------------------------------------------------------------------
# convolution / pooling
# ---------------------------------------------------------------------------------

def _resolve_pads(attrs, spatial_rank: int, x_shape, k_shape, strides, dilations):
    """ONNX pads [x1b,x2b,...,x1e,x2e,...] or auto_pad."""
    auto = attrs.get("auto_pad", "NOTSET")
    if auto in ("NOTSET", ""):
        pads = attrs.get("pads") or [0] * (2 * spatial_rank)
        return [(int(pads[i]), int(pads[i + spatial_rank])) for i in range(spatial_rank)]
    if auto == "VALID":
        return [(0, 0)] * spatial_rank
    # SAME_UPPER / SAME_LOWER
    out = []
    for i in range(spatial_rank):
        in_dim = x_shape[2 + i]
        eff_k = (k_shape[i] - 1) * dilations[i] + 1
        out_dim = -(-in_dim // strides[i])
        total = max(0, (out_dim - 1) * strides[i] + eff_k - in_dim)
        lo = total // 2 if auto == "SAME_UPPER" else (total + 1) // 2
        out.append((lo, total - lo))
    return out


@op("Conv")
def _conv(inputs, attrs, ctx):
    x, w = inputs[0], inputs[1]
    b = inputs[2] if len(inputs) > 2 else None
    rank = x.ndim - 2
    strides = [int(s) for s in attrs.get("strides", [1] * rank)]
    dilations = [int(d) for d in attrs.get("dilations", [1] * rank)]
    groups = int(attrs.get("group", 1))
    kernel_spatial = w.shape[2:]
    pads = _resolve_pads(attrs, rank, x.shape, kernel_spatial, strides, dilations)
    dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                    ("NCHW"[: rank + 2], "OIHW"[: rank + 2], "NCHW"[: rank + 2])
                                    if rank <= 2 else
                                    ("NCDHW", "OIDHW", "NCDHW"))
    out = lax.conv_general_dilated(
        x, w, window_strides=strides, padding=pads, rhs_dilation=dilations,
        dimension_numbers=dn, feature_group_count=groups,
        preferred_element_type=ctx.get("accum_dtype"),
    )
    if out.dtype != x.dtype:
        out = out.astype(x.dtype)
    if b is not None:
        out = out + b.reshape((1, -1) + (1,) * rank)
    return out


@op("ConvTranspose")
def _conv_transpose(inputs, attrs, ctx):
    x, w = inputs[0], inputs[1]
    b = inputs[2] if len(inputs) > 2 else None
    rank = x.ndim - 2
    strides = [int(s) for s in attrs.get("strides", [1] * rank)]
    dilations = [int(d) for d in attrs.get("dilations", [1] * rank)]
    groups = int(attrs.get("group", 1))
    if groups != 1:
        raise NotImplementedError("grouped ConvTranspose not supported yet")
    kernel_spatial = w.shape[2:]
    pads = _resolve_pads(attrs, rank, x.shape, kernel_spatial, strides, dilations)
    out_pads = [int(p) for p in attrs.get("output_padding", [0] * rank)]
    # ONNX W layout for ConvTranspose is (C_in, C_out/groups, *k); transpose to OIHW.
    w_t = jnp.swapaxes(w, 0, 1)
    w_t = jnp.flip(w_t, axis=tuple(range(2, 2 + rank)))
    # conv_transpose via input dilation
    padding = []
    for i in range(rank):
        eff_k = (kernel_spatial[i] - 1) * dilations[i] + 1
        padding.append((eff_k - 1 - pads[i][0], eff_k - 1 - pads[i][1] + out_pads[i]))
    dn = lax.conv_dimension_numbers(x.shape, w_t.shape,
                                    ("NCHW"[: rank + 2], "OIHW"[: rank + 2], "NCHW"[: rank + 2]))
    out = lax.conv_general_dilated(
        x, w_t, window_strides=[1] * rank, padding=padding, lhs_dilation=strides,
        rhs_dilation=dilations, dimension_numbers=dn,
        preferred_element_type=ctx.get("accum_dtype"),
    )
    if out.dtype != x.dtype:
        out = out.astype(x.dtype)
    if b is not None:
        out = out + b.reshape((1, -1) + (1,) * rank)
    return out


def _pool(x, kernel, strides, pads, reducer, init, count_include_pad, ceil_mode=0):
    rank = len(kernel)
    if ceil_mode:
        # extend end-padding so ceil-division windows fit
        new_pads = []
        for i in range(rank):
            in_dim = x.shape[2 + i] + pads[i][0] + pads[i][1]
            rem = (in_dim - kernel[i]) % strides[i]
            extra = (strides[i] - rem) % strides[i] if rem else 0
            new_pads.append((pads[i][0], pads[i][1] + extra))
        pads = new_pads
    window = (1, 1) + tuple(kernel)
    strides_full = (1, 1) + tuple(strides)
    pads_full = ((0, 0), (0, 0)) + tuple(pads)
    out = lax.reduce_window(x, init, reducer, window, strides_full, pads_full)
    return out, pads


@op("MaxPool")
def _maxpool(inputs, attrs, ctx):
    x = inputs[0]
    kernel = [int(k) for k in attrs["kernel_shape"]]
    rank = len(kernel)
    strides = [int(s) for s in attrs.get("strides", [1] * rank)]
    dil = [int(d) for d in attrs.get("dilations", [1] * rank)]
    if any(d != 1 for d in dil):
        raise NotImplementedError("dilated MaxPool not supported")
    pads = _resolve_pads(attrs, rank, x.shape, kernel, strides, [1] * rank)
    neg_inf = jnp.array(-jnp.inf if jnp.issubdtype(x.dtype, jnp.floating)
                        else jnp.iinfo(x.dtype).min, dtype=x.dtype)
    out, _ = _pool(x, kernel, strides, pads, lax.max, neg_inf, False,
                   attrs.get("ceil_mode", 0))
    return out


@op("AveragePool")
def _avgpool(inputs, attrs, ctx):
    x = inputs[0]
    kernel = [int(k) for k in attrs["kernel_shape"]]
    rank = len(kernel)
    strides = [int(s) for s in attrs.get("strides", [1] * rank)]
    pads = _resolve_pads(attrs, rank, x.shape, kernel, strides, [1] * rank)
    include_pad = attrs.get("count_include_pad", 0)
    out, eff_pads = _pool(x, kernel, strides, pads, lax.add, jnp.array(0, x.dtype),
                          include_pad, attrs.get("ceil_mode", 0))
    if include_pad:
        return out / float(np.prod(kernel))
    ones = jnp.ones(x.shape[2:], dtype=x.dtype)[None, None]
    counts, _ = _pool(ones, kernel, strides, eff_pads, lax.add, jnp.array(0, x.dtype), True)
    return out / counts


@op("GlobalAveragePool")
def _gap(inputs, attrs, ctx):
    x = inputs[0]
    return jnp.mean(x, axis=tuple(range(2, x.ndim)), keepdims=True)


@op("GlobalMaxPool")
def _gmp(inputs, attrs, ctx):
    x = inputs[0]
    return jnp.max(x, axis=tuple(range(2, x.ndim)), keepdims=True)


@op("LRN")
def _lrn(inputs, attrs, ctx):
    x = inputs[0]
    size = int(attrs["size"])
    alpha, beta, bias = attrs.get("alpha", 1e-4), attrs.get("beta", 0.75), attrs.get("bias", 1.0)
    sq = x * x
    half = size // 2
    pads = ((0, 0), (half, size - 1 - half)) + ((0, 0),) * (x.ndim - 2)
    window = (1, size) + (1,) * (x.ndim - 2)
    summed = lax.reduce_window(sq, jnp.array(0, x.dtype), lax.add, window, (1,) * x.ndim, pads)
    return x / jnp.power(bias + (alpha / size) * summed, beta)


# ---------------------------------------------------------------------------------
# normalization
# ---------------------------------------------------------------------------------

@op("BatchNormalization")
def _batchnorm(inputs, attrs, ctx):
    x, scale, bias, mean, var = inputs[:5]
    eps = attrs.get("epsilon", 1e-5)
    shape = (1, -1) + (1,) * (x.ndim - 2)
    inv = lax.rsqrt(var.astype(jnp.float32) + eps).astype(x.dtype)
    return (x - mean.reshape(shape)) * (scale * inv).reshape(shape) + bias.reshape(shape)


@op("InstanceNormalization")
def _instancenorm(inputs, attrs, ctx):
    x, scale, bias = inputs[:3]
    eps = attrs.get("epsilon", 1e-5)
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    shape = (1, -1) + (1,) * (x.ndim - 2)
    return (x - mean) * lax.rsqrt(var + eps) * scale.reshape(shape) + bias.reshape(shape)


@op("LayerNormalization")
def _layernorm(inputs, attrs, ctx):
    x = inputs[0]
    scale = inputs[1] if len(inputs) > 1 else None
    bias = inputs[2] if len(inputs) > 2 else None
    axis = attrs.get("axis", -1)
    eps = attrs.get("epsilon", 1e-5)
    axes = tuple(range(axis % x.ndim, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    out = (x - mean) * lax.rsqrt(var + eps)
    if scale is not None:
        out = out * scale
    if bias is not None:
        out = out + bias
    return out


@op("GroupNormalization")
def _groupnorm(inputs, attrs, ctx):
    x, scale, bias = inputs[:3]
    g = int(attrs["num_groups"])
    eps = attrs.get("epsilon", 1e-5)
    n, c = x.shape[:2]
    xg = x.reshape((n, g, c // g) + x.shape[2:])
    axes = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=axes, keepdims=True)
    var = jnp.var(xg, axis=axes, keepdims=True)
    out = ((xg - mean) * lax.rsqrt(var + eps)).reshape(x.shape)
    shape = (1, -1) + (1,) * (x.ndim - 2)
    return out * scale.reshape(shape) + bias.reshape(shape)


@op("Dropout")
def _dropout(inputs, attrs, ctx):
    # inference-mode: identity (+ all-true mask as optional second output)
    x = inputs[0]
    return (x, jnp.ones(x.shape, dtype=bool))


# ---------------------------------------------------------------------------------
# shape / data movement  (static-shape discipline: see module docstring)
# ---------------------------------------------------------------------------------

@op("Shape")
def _shape(inputs, attrs, ctx):
    shp = np.asarray(np.shape(inputs[0]), dtype=np.int64)
    start = attrs.get("start", 0)
    end = attrs.get("end")
    return shp[start:end]


@op("Size")
def _size(inputs, attrs, ctx):
    return np.asarray(int(np.prod(np.shape(inputs[0]))), dtype=np.int64)


@op("Reshape")
def _reshape(inputs, attrs, ctx):
    if attrs.get("shape") is not None:  # opset<5 attribute form
        target = [int(s) for s in attrs["shape"]]
    else:
        target = _ints(inputs[1], "Reshape.shape")
    x = inputs[0]
    if attrs.get("allowzero", 0) == 0:
        target = [x.shape[i] if s == 0 else s for i, s in enumerate(target)]
    return jnp.reshape(x, target)


@op("Flatten")
def _flatten(inputs, attrs, ctx):
    x = inputs[0]
    axis = attrs.get("axis", 1)
    if axis < 0:
        axis += x.ndim
    lead = int(np.prod(x.shape[:axis])) if axis else 1
    return jnp.reshape(x, (lead, -1))


@op("Transpose")
def _transpose(inputs, attrs, ctx):
    perm = attrs.get("perm")
    x = inputs[0]
    return jnp.transpose(x, perm if perm is not None else tuple(reversed(range(x.ndim))))


@op("Concat")
def _concat(inputs, attrs, ctx):
    vals = [v for v in inputs if v is not None]
    if all(isinstance(v, np.ndarray) for v in vals):
        return np.concatenate([np.atleast_1d(v) for v in vals], axis=attrs.get("axis", 0))
    return jnp.concatenate([jnp.atleast_1d(v) for v in vals], axis=attrs.get("axis", 0))


@op("Split")
def _split(inputs, attrs, ctx):
    x = inputs[0]
    axis = attrs.get("axis", 0)
    splits = attrs.get("split")
    if splits is None and len(inputs) > 1 and inputs[1] is not None:
        splits = _ints(inputs[1], "Split.split")
    n_out = ctx["n_outputs"]
    if splits is None:
        dim = x.shape[axis]
        base = -(-dim // n_out) if attrs.get("num_outputs") else dim // n_out
        splits = [base] * (n_out - 1) + [dim - base * (n_out - 1)]
    idx = np.cumsum(splits)[:-1]
    return tuple(jnp.split(x, idx, axis=axis))


@op("Slice")
def _slice(inputs, attrs, ctx):
    x = inputs[0]
    if attrs.get("starts") is not None:  # opset<10 attribute form
        starts, ends = list(attrs["starts"]), list(attrs["ends"])
        axes = list(attrs.get("axes", range(len(starts))))
        steps = [1] * len(starts)
    else:
        starts = _ints(inputs[1], "Slice.starts")
        ends = _ints(inputs[2], "Slice.ends")
        axes = _ints(inputs[3], "Slice.axes") if len(inputs) > 3 and inputs[3] is not None \
            else list(range(len(starts)))
        steps = _ints(inputs[4], "Slice.steps") if len(inputs) > 4 and inputs[4] is not None \
            else [1] * len(starts)
    idx = [slice(None)] * x.ndim
    for s, e, a, st in zip(starts, ends, axes, steps):
        a = a % x.ndim
        idx[a] = slice(s if s > -(1 << 62) else None,
                       e if -(1 << 62) < e < (1 << 62) else None, st)
    return x[tuple(idx)]


@op("Gather")
def _gather(inputs, attrs, ctx):
    x, idx = inputs[0], inputs[1]
    axis = attrs.get("axis", 0)
    if isinstance(x, np.ndarray) and isinstance(idx, np.ndarray):
        return np.take(x, idx.astype(np.int64), axis=axis)
    return jnp.take(x, jnp.asarray(idx), axis=axis)


@op("GatherElements")
def _gather_elements(inputs, attrs, ctx):
    x, idx = inputs[0], jnp.asarray(inputs[1])
    axis = attrs.get("axis", 0)
    return jnp.take_along_axis(x, idx, axis=axis)


@op("GatherND")
def _gather_nd(inputs, attrs, ctx):
    x, idx = inputs[0], inputs[1]
    batch_dims = attrs.get("batch_dims", 0)
    if batch_dims:
        raise NotImplementedError("GatherND batch_dims>0")
    idx = jnp.asarray(idx)
    return x[tuple(jnp.moveaxis(idx, -1, 0))]


@op("ScatterND")
def _scatter_nd(inputs, attrs, ctx):
    data, indices, updates = inputs[:3]
    indices = jnp.asarray(indices)
    out = jnp.asarray(data)
    red = attrs.get("reduction", "none")
    at = out.at[tuple(jnp.moveaxis(indices, -1, 0))]
    if red == "add":
        return at.add(updates)
    if red == "mul":
        return at.multiply(updates)
    return at.set(updates)


@op("Squeeze")
def _squeeze(inputs, attrs, ctx):
    x = inputs[0]
    axes = _axis_list(attrs, inputs, 1, "Squeeze.axes")
    if axes is None:
        axes = [i for i, d in enumerate(np.shape(x)) if d == 1]
    if isinstance(x, np.ndarray):
        return np.squeeze(x, axis=tuple(a % x.ndim for a in axes))
    return jnp.squeeze(x, axis=tuple(a % x.ndim for a in axes))


@op("Unsqueeze")
def _unsqueeze(inputs, attrs, ctx):
    x = inputs[0]
    axes = _axis_list(attrs, inputs, 1, "Unsqueeze.axes")
    out_rank = np.ndim(x) + len(axes)
    axes = sorted(a % out_rank for a in axes)
    if isinstance(x, np.ndarray):
        return np.expand_dims(x, tuple(axes))
    return jnp.expand_dims(x, tuple(axes))


@op("Expand")
def _expand(inputs, attrs, ctx):
    target = _ints(inputs[1], "Expand.shape")
    x = inputs[0]
    # ONNX Expand uses bidirectional broadcast; jnp.broadcast_to needs exact target.
    in_shape = list(np.shape(x))
    rank = max(len(in_shape), len(target))
    in_shape = [1] * (rank - len(in_shape)) + in_shape
    target = [1] * (rank - len(target)) + list(target)
    final = [max(a, b) for a, b in zip(in_shape, target)]
    return jnp.broadcast_to(x, final)


@op("Tile")
def _tile(inputs, attrs, ctx):
    reps = _ints(inputs[1], "Tile.repeats")
    return jnp.tile(inputs[0], reps)


@op("Pad")
def _pad(inputs, attrs, ctx):
    x = inputs[0]
    mode = attrs.get("mode", "constant")
    if attrs.get("pads") is not None:  # opset<11
        pads = [int(p) for p in attrs["pads"]]
        cval = attrs.get("value", 0.0)
    else:
        pads = _ints(inputs[1], "Pad.pads")
        cval = inputs[2] if len(inputs) > 2 and inputs[2] is not None else 0.0
    rank = x.ndim
    axes = _ints(inputs[3], "Pad.axes") if len(inputs) > 3 and inputs[3] is not None \
        else list(range(rank))
    width = [(0, 0)] * rank
    half = len(pads) // 2
    for i, a in enumerate(axes):
        width[a % rank] = (pads[i], pads[i + half])
    if mode == "constant":
        cval_scalar = cval if np.isscalar(cval) else jnp.reshape(cval, ())
        return jnp.pad(x, width, constant_values=cval_scalar)
    jmode = {"reflect": "reflect", "edge": "edge", "wrap": "wrap"}[mode]
    return jnp.pad(x, width, mode=jmode)


@op("Cast", "CastLike")
def _cast(inputs, attrs, ctx):
    from .wire import DataType

    if ctx["op_type"] == "CastLike":
        dtype = np.asarray(inputs[1]).dtype if isinstance(inputs[1], np.ndarray) else inputs[1].dtype
    else:
        dtype = DataType.to_numpy(int(attrs["to"]))
    return inputs[0].astype(dtype)


def _qbroadcast(x, scale, zp, axis: int):
    """Per-axis quantization params broadcast against ``x``: a 1-D
    scale/zero_point lies along ``axis`` (ONNX per-channel form); scalars
    broadcast as-is. Returns jnp views ready for arithmetic."""
    scale = jnp.asarray(scale)
    if zp is not None:
        zp = jnp.asarray(zp)
    nd = jnp.ndim(x)
    if scale.ndim == 1 and nd > 1:
        shape = [1] * nd
        shape[axis % nd] = -1
        scale = scale.reshape(shape)
        if zp is not None and zp.ndim == 1:
            zp = zp.reshape(shape)
    return scale, zp


@op("QuantizeLinear")
def _quantize_linear(inputs, attrs, ctx):
    # y = saturate(round(x / y_scale) + y_zero_point), round half to even
    # (jnp.round IS banker's rounding); output dtype follows the
    # zero_point (uint8 when omitted, per spec)
    x, scale = inputs[0], inputs[1]
    zp = inputs[2] if len(inputs) > 2 else None
    qdtype = (np.dtype(np.uint8) if zp is None
              else np.asarray(zp).dtype if isinstance(zp, np.ndarray)
              else np.dtype(zp.dtype))
    scale, zp = _qbroadcast(x, scale, zp, int(attrs.get("axis", 1)))
    y = jnp.round(x / scale)
    if zp is not None:
        y = y + zp.astype(y.dtype)
    info = np.iinfo(qdtype)
    return jnp.clip(y, info.min, info.max).astype(qdtype)


@op("DequantizeLinear")
def _dequantize_linear(inputs, attrs, ctx):
    # y = (x - x_zero_point) * x_scale, in the scale's float dtype
    x, scale = inputs[0], inputs[1]
    zp = inputs[2] if len(inputs) > 2 else None
    scale, zp = _qbroadcast(x, scale, zp, int(attrs.get("axis", 1)))
    xf = jnp.asarray(x).astype(scale.dtype)
    if zp is not None:
        xf = xf - zp.astype(scale.dtype)
    return xf * scale


@op("DynamicQuantizeLinear")
def _dynamic_quantize_linear(inputs, attrs, ctx):
    # uint8 affine quantization with the data's own range (the range is
    # widened to include 0 so zero_point is always representable);
    # returns (y, y_scale, y_zero_point) exactly per spec
    x = jnp.asarray(inputs[0])
    xmax = jnp.maximum(jnp.max(x), 0.0)
    xmin = jnp.minimum(jnp.min(x), 0.0)
    scale = ((xmax - xmin) / 255.0).astype(jnp.float32)
    # all-zero input: the spec's scale is 0 — quantize against 1.0 to
    # keep the kernel finite (y and zero_point are all zero either way)
    safe = jnp.where(scale == 0, jnp.float32(1.0), scale)
    zp = jnp.clip(jnp.round(-xmin / safe), 0, 255)
    y = jnp.clip(jnp.round(x / safe) + zp, 0, 255).astype(jnp.uint8)
    return y, scale, zp.astype(jnp.uint8)


def _zp_shift(q, zp, axis: int):
    """Zero-centre a quantized (u)int8 operand in int32: widening BEFORE
    the zero_point subtraction keeps the accumulation exact (uint8 - 255
    underflows in-dtype). A 1-D zero_point lies along ``axis``."""
    q = jnp.asarray(q).astype(jnp.int32)
    if zp is None:
        return q
    zp = jnp.asarray(zp).astype(jnp.int32)
    if zp.ndim == 1 and q.ndim > 1:
        shape = [1] * q.ndim
        shape[axis % q.ndim] = -1
        zp = zp.reshape(shape)
    return q - zp


@op("MatMulInteger")
def _matmul_integer(inputs, attrs, ctx):
    # int32 accumulation over zero-centred operands; per spec a 1-D
    # a_zero_point is per-row (M axis of A), a 1-D b_zero_point is
    # per-column (N axis of B). Output is always int32.
    a = _zp_shift(inputs[0], inputs[2] if len(inputs) > 2 else None, -2)
    b = _zp_shift(inputs[1], inputs[3] if len(inputs) > 3 else None, -1)
    return jnp.matmul(a, b, preferred_element_type=jnp.int32)


@op("ConvInteger")
def _conv_integer(inputs, attrs, ctx):
    # Conv over zero-centred int32 operands (implicit padding therefore
    # represents x_zero_point, i.e. real zero — onnxruntime semantics);
    # w_zero_point may be per-output-channel (axis 0 of OIHW)
    x = _zp_shift(inputs[0], inputs[2] if len(inputs) > 2 else None, 0)
    w = _zp_shift(inputs[1], inputs[3] if len(inputs) > 3 else None, 0)
    rank = x.ndim - 2
    strides = [int(s) for s in attrs.get("strides", [1] * rank)]
    dilations = [int(d) for d in attrs.get("dilations", [1] * rank)]
    groups = int(attrs.get("group", 1))
    pads = _resolve_pads(attrs, rank, x.shape, w.shape[2:], strides, dilations)
    dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                    ("NCHW"[: rank + 2], "OIHW"[: rank + 2], "NCHW"[: rank + 2])
                                    if rank <= 2 else
                                    ("NCDHW", "OIDHW", "NCDHW"))
    return lax.conv_general_dilated(
        x, w, window_strides=strides, padding=pads, rhs_dilation=dilations,
        dimension_numbers=dn, feature_group_count=groups,
        preferred_element_type=jnp.int32,
    )


@op("QLinearConv")
def _qlinear_conv(inputs, attrs, ctx):
    # full requantizing Conv: ConvInteger's zero-centred int32
    # accumulation, an optional int32 bias (per spec already quantized
    # with scale x_scale*w_scale, zero_point 0 — added into the
    # accumulator), then rescale by x_scale*w_scale/y_scale, round half
    # to even, re-centre on y_zero_point and saturate to its dtype.
    # w_scale/w_zero_point may be per-output-channel (OIHW axis 0).
    x, x_scale, x_zp, w, w_scale, w_zp, y_scale, y_zp = inputs[:8]
    bias = inputs[8] if len(inputs) > 8 and inputs[8] is not None else None
    acc = _conv_integer([x, w, x_zp, w_zp], attrs, ctx)
    nd = acc.ndim

    def _chan(s):  # per-channel params lie along the output-channel axis
        s = jnp.asarray(s).astype(jnp.float32)
        return s.reshape((1, -1) + (1,) * (nd - 2)) if s.ndim == 1 else s

    if bias is not None:
        acc = acc + jnp.asarray(bias).astype(jnp.int32).reshape(
            (1, -1) + (1,) * (nd - 2))
    scale = jnp.asarray(x_scale).astype(jnp.float32) * _chan(w_scale) \
        / jnp.asarray(y_scale).astype(jnp.float32)
    qdtype = (np.asarray(y_zp).dtype if isinstance(y_zp, np.ndarray)
              else np.dtype(y_zp.dtype))
    y = jnp.round(acc.astype(jnp.float32) * scale) + _chan(y_zp)
    info = np.iinfo(qdtype)
    return jnp.clip(y, info.min, info.max).astype(qdtype)


@op("QLinearMatMul")
def _qlinear_matmul(inputs, attrs, ctx):
    # full requantizing matmul: int32 accumulate, rescale by
    # a_scale*b_scale/y_scale, round half to even, re-centre on
    # y_zero_point and saturate to its dtype. 1-D scales/zero_points are
    # per-row for a and y, per-column for b (same layout rule as
    # MatMulInteger).
    a, a_scale, a_zp, b, b_scale, b_zp, y_scale, y_zp = inputs[:8]
    acc = jnp.matmul(_zp_shift(a, a_zp, -2), _zp_shift(b, b_zp, -1),
                     preferred_element_type=jnp.int32)

    def _row(s):  # per-row params broadcast down the output's M axis
        s = jnp.asarray(s).astype(jnp.float32)
        return s.reshape(-1, 1) if s.ndim == 1 else s

    scale = _row(a_scale) * jnp.asarray(b_scale).astype(jnp.float32) \
        / _row(y_scale)
    qdtype = (np.asarray(y_zp).dtype if isinstance(y_zp, np.ndarray)
              else np.dtype(y_zp.dtype))
    y = jnp.round(acc.astype(jnp.float32) * scale) + _row(y_zp)
    info = np.iinfo(qdtype)
    return jnp.clip(y, info.min, info.max).astype(qdtype)


@op("Where")
def _where(inputs, attrs, ctx):
    c, a, b = inputs[:3]
    if all(isinstance(v, np.ndarray) for v in (c, a, b)):
        return np.where(c, a, b)
    return jnp.where(c, a, b)


@op("OneHot")
def _onehot(inputs, attrs, ctx):
    indices, depth, values = inputs[:3]
    axis = attrs.get("axis", -1)
    d = int(_static(depth, "OneHot.depth"))
    off_val, on_val = values[0], values[1]
    idx = jnp.asarray(indices)
    # spec: negative indices in [-depth, -1] wrap; anything else is all-off
    valid = (idx >= -d) & (idx <= d - 1)
    idx = jnp.where(valid, jnp.where(idx < 0, idx + d, idx), -1)
    oh = jax.nn.one_hot(idx, d, axis=axis)  # one_hot(-1) -> all zeros
    return oh * (on_val - off_val) + off_val


@op("Range")
def _range(inputs, attrs, ctx):
    start, limit, delta = (_static(v, "Range") for v in inputs[:3])
    return np.arange(start.item(), limit.item(), delta.item(),
                     dtype=np.asarray(start).dtype)


@op("ConstantOfShape")
def _constant_of_shape(inputs, attrs, ctx):
    from .wire import tensor_to_numpy

    shape = _ints(inputs[0], "ConstantOfShape.shape")
    t = attrs.get("value")
    if t is None:
        return np.zeros(shape, dtype=np.float32)
    v = tensor_to_numpy(t, external_dir=ctx.get("external_dir"))
    return np.full(shape, v.reshape(-1)[0], dtype=v.dtype)


@op("Constant")
def _constant(inputs, attrs, ctx):
    from .wire import tensor_to_numpy

    if attrs.get("value") is not None:
        return tensor_to_numpy(attrs["value"],
                               external_dir=ctx.get("external_dir"))
    for k in ("value_float", "value_int"):
        if attrs.get(k) is not None:
            return np.asarray(attrs[k])
    for k in ("value_floats", "value_ints"):
        if attrs.get(k) is not None:
            return np.asarray(attrs[k])
    raise ValueError("Constant node with no value attribute")


@op("DepthToSpace")
def _depth_to_space(inputs, attrs, ctx):
    x = inputs[0]
    b = int(attrs["blocksize"])
    n, c, h, w = x.shape
    if attrs.get("mode", "DCR") == "DCR":
        t = x.reshape(n, b, b, c // (b * b), h, w).transpose(0, 3, 4, 1, 5, 2)
    else:
        t = x.reshape(n, c // (b * b), b, b, h, w).transpose(0, 1, 4, 2, 5, 3)
    return t.reshape(n, c // (b * b), h * b, w * b)


@op("SpaceToDepth")
def _space_to_depth(inputs, attrs, ctx):
    x = inputs[0]
    b = int(attrs["blocksize"])
    n, c, h, w = x.shape
    t = x.reshape(n, c, h // b, b, w // b, b).transpose(0, 3, 5, 1, 2, 4)
    return t.reshape(n, c * b * b, h // b, w // b)


@op("Resize")
def _resize(inputs, attrs, ctx):
    x = inputs[0]
    mode = attrs.get("mode", "nearest")
    sizes = None
    if len(inputs) > 3 and inputs[3] is not None:
        sizes = _ints(inputs[3], "Resize.sizes")
    elif len(inputs) > 2 and inputs[2] is not None:
        scales = np.asarray(_static(inputs[2], "Resize.scales"), dtype=np.float64)
        if scales.size:
            sizes = [int(np.floor(s * d)) for s, d in zip(scales, x.shape)]
    if sizes is None:
        raise ValueError("Resize needs scales or sizes")
    method = {"nearest": "nearest", "linear": "linear", "cubic": "cubic"}[mode]
    return jax.image.resize(x, sizes, method=method)


@op("ArgMax", "ArgMin")
def _argminmax(inputs, attrs, ctx):
    axis = attrs.get("axis", 0)
    keepdims = attrs.get("keepdims", 1)
    fn = jnp.argmax if ctx["op_type"] == "ArgMax" else jnp.argmin
    x = inputs[0]
    if attrs.get("select_last_index", 0):
        x = jnp.flip(x, axis)
        out = x.shape[axis] - 1 - fn(x, axis=axis)
    else:
        out = fn(x, axis=axis)
    out = out.astype(jnp.int64 if jax.config.jax_enable_x64 else jnp.int32)
    return jnp.expand_dims(out, axis) if keepdims else out


@op("TopK")
def _topk(inputs, attrs, ctx):
    x = inputs[0]
    k = int(_static(inputs[1], "TopK.k")) if len(inputs) > 1 else int(attrs["k"])
    axis = attrs.get("axis", -1)
    largest = attrs.get("largest", 1)
    xm = jnp.moveaxis(x, axis, -1)
    vals, idx = lax.top_k(xm if largest else -xm, k)
    if not largest:
        vals = -vals
    return (jnp.moveaxis(vals, -1, axis), jnp.moveaxis(idx, -1, axis))


@op("Trilu")
def _trilu(inputs, attrs, ctx):
    x = inputs[0]
    k = int(_static(inputs[1], "Trilu.k")) if len(inputs) > 1 and inputs[1] is not None else 0
    return jnp.triu(x, k) if attrs.get("upper", 1) else jnp.tril(x, k)


@op("IsInf")
def _isinf(inputs, attrs, ctx):
    x = inputs[0]
    pos = attrs.get("detect_positive", 1)
    neg = attrs.get("detect_negative", 1)
    out = jnp.zeros(jnp.shape(x), dtype=bool)
    if pos:
        out = out | (x == jnp.inf)
    if neg:
        out = out | (x == -jnp.inf)
    return out


# ---------------------------------------------------------------------------------
# reductions
# ---------------------------------------------------------------------------------

def _reduce(fn_np, fn_jnp, axes_from_input_opset: int):
    def impl(inputs, attrs, ctx):
        x = inputs[0]
        if ctx["opset"] >= axes_from_input_opset:
            axes = _axis_list({"axes": attrs.get("axes")}, inputs, 1, "Reduce.axes")
        else:
            axes = attrs.get("axes")
        keepdims = bool(attrs.get("keepdims", 1))
        if axes is None:
            if attrs.get("noop_with_empty_axes", 0):
                return x
            ax = None
        else:
            ax = tuple(int(a) for a in np.atleast_1d(axes))
        if isinstance(x, np.ndarray):
            return fn_np(x, axis=ax, keepdims=keepdims)
        return fn_jnp(x, axis=ax, keepdims=keepdims)

    return impl


OPS["ReduceSum"] = _reduce(np.sum, _lazy_fn("jnp.sum"), 13)
OPS["ReduceMean"] = _reduce(np.mean, _lazy_fn("jnp.mean"), 18)
OPS["ReduceMax"] = _reduce(np.max, _lazy_fn("jnp.max"), 18)
OPS["ReduceMin"] = _reduce(np.min, _lazy_fn("jnp.min"), 18)
OPS["ReduceProd"] = _reduce(np.prod, _lazy_fn("jnp.prod"), 18)
OPS["ReduceL1"] = _reduce(lambda x, axis, keepdims: np.sum(np.abs(x), axis=axis, keepdims=keepdims),
                          lambda x, axis, keepdims: jnp.sum(jnp.abs(x), axis=axis, keepdims=keepdims), 18)
OPS["ReduceL2"] = _reduce(lambda x, axis, keepdims: np.sqrt(np.sum(x * x, axis=axis, keepdims=keepdims)),
                          lambda x, axis, keepdims: jnp.sqrt(jnp.sum(x * x, axis=axis, keepdims=keepdims)), 18)
OPS["ReduceSumSquare"] = _reduce(lambda x, axis, keepdims: np.sum(x * x, axis=axis, keepdims=keepdims),
                                 lambda x, axis, keepdims: jnp.sum(x * x, axis=axis, keepdims=keepdims), 18)
OPS["ReduceLogSum"] = _reduce(lambda x, axis, keepdims: np.log(np.sum(x, axis=axis, keepdims=keepdims)),
                              lambda x, axis, keepdims: jnp.log(jnp.sum(x, axis=axis, keepdims=keepdims)), 18)
OPS["ReduceLogSumExp"] = _reduce(
    lambda x, axis, keepdims: np.log(np.sum(np.exp(x), axis=axis, keepdims=keepdims)),
    lambda x, axis, keepdims: jax.scipy.special.logsumexp(x, axis=axis, keepdims=keepdims), 18)


@op("If")
def _if(inputs, attrs, ctx):
    cond = inputs[0]
    then_fn, else_fn = ctx["subgraph_runner"](attrs["then_branch"]), ctx["subgraph_runner"](attrs["else_branch"])
    if isinstance(cond, np.ndarray):  # constant condition: fold at trace time
        return then_fn() if bool(cond) else else_fn()
    raise NotImplementedError(
        "If with traced condition not supported (branches may differ in shape); "
        "most exported models have constant conditions after shape specialization"
    )


# ---------------------------------------------------------------------------------
# recurrent (LSTM / GRU)
# ---------------------------------------------------------------------------------

def _rnn_act(name: str) -> Callable:
    try:
        return {"Sigmoid": _lazy_fn("jax.nn.sigmoid"),
                "Tanh": _lazy_fn("jnp.tanh"),
                "Relu": _lazy_fn("jax.nn.relu")}[name]
    except KeyError:
        raise NotImplementedError(f"RNN activation {name!r}") from None


def _rnn_common(op_type: str, inputs, attrs, n_gates: int):
    """Shared LSTM/GRU front end: forward single-direction slices,
    combined bias, initial hidden state, optional pre-activation clip."""
    if attrs.get("layout", 0) != 0:
        raise NotImplementedError(f"{op_type} layout=1")
    direction = attrs.get("direction", "forward")
    if direction != "forward":
        raise NotImplementedError(f"{op_type} direction={direction!r}")
    x, w, r = inputs[0], inputs[1], inputs[2]
    seq_lens = inputs[4] if len(inputs) > 4 else None
    if seq_lens is not None and not (
            isinstance(seq_lens, np.ndarray) and np.all(seq_lens == x.shape[0])):
        raise NotImplementedError(f"{op_type} with ragged sequence_lens")
    hidden = int(r.shape[-1])
    w2, r2 = jnp.asarray(w[0]), jnp.asarray(r[0])  # (n_gates*H, I), (n_gates*H, H)
    b = inputs[3] if len(inputs) > 3 else None
    if b is not None:
        wb, rb = jnp.split(jnp.asarray(b[0]), 2)
    else:
        wb = rb = jnp.zeros((n_gates * hidden,), x.dtype)
    init_h = inputs[5] if len(inputs) > 5 else None
    h0 = (jnp.zeros((x.shape[1], hidden), x.dtype) if init_h is None
          else jnp.asarray(init_h[0]))
    clip = attrs.get("clip")
    squash = ((lambda v: jnp.clip(v, -clip, clip)) if clip is not None
              else (lambda v: v))
    return x, w2, r2, wb, rb, h0, hidden, squash


@op("LSTM")
def _lstm(inputs, attrs, ctx):
    """Single-layer forward LSTM via ``lax.scan``; gate order iofc, optional
    peepholes, outputs ``Y (S,1,B,H)``, ``Y_h (1,B,H)``, ``Y_c (1,B,H)``."""
    x, w2, r2, wb, rb, h0, hidden, squash = _rnn_common("LSTM", inputs, attrs, 4)
    acts = attrs.get("activations") or ["Sigmoid", "Tanh", "Tanh"]
    f, g, h_act = (_rnn_act(a) for a in acts[:3])
    init_c = inputs[6] if len(inputs) > 6 else None
    c0 = (jnp.zeros_like(h0) if init_c is None else jnp.asarray(init_c[0]))
    p = inputs[7] if len(inputs) > 7 else None
    if p is not None:
        pi, po, pf = jnp.split(jnp.asarray(p[0]), 3)
    else:
        pi = po = pf = jnp.zeros((hidden,), x.dtype)
    # the input projection has no step dependence: one batched matmul
    # outside the scan, only the H-recurrence stays sequential
    gx = jnp.matmul(x, w2.T) + wb + rb  # (S, B, 4H)

    def step(carry, xt):
        h, c = carry
        zi, zo, zf, zc = jnp.split(xt + jnp.matmul(h, r2.T), 4, axis=-1)
        i = f(squash(zi + pi * c))
        ft = f(squash(zf + pf * c))
        c_new = ft * c + i * g(squash(zc))
        o = f(squash(zo + po * c_new))
        return (o * h_act(c_new), c_new), o * h_act(c_new)

    (h_t, c_t), ys = lax.scan(step, (h0, c0), gx)
    return ys[:, None], h_t[None], c_t[None]


@op("GRU")
def _gru(inputs, attrs, ctx):
    """Single-layer forward GRU via ``lax.scan``; gate order zrh, both
    ``linear_before_reset`` modes, outputs ``Y (S,1,B,H)``, ``Y_h (1,B,H)``."""
    x, w2, r2, wb, rb, h0, hidden, squash = _rnn_common("GRU", inputs, attrs, 3)
    acts = attrs.get("activations") or ["Sigmoid", "Tanh"]
    f, g = _rnn_act(acts[0]), _rnn_act(acts[1])
    lbr = int(attrs.get("linear_before_reset", 0))
    rz, rr, rh = jnp.split(r2, 3)
    rbz, rbr, rbh = jnp.split(rb, 3)
    gx = jnp.matmul(x, w2.T) + wb  # (S, B, 3H)

    def step(h, xt):
        xz, xr, xh = jnp.split(xt, 3, axis=-1)
        z = f(squash(xz + jnp.matmul(h, rz.T) + rbz))
        r = f(squash(xr + jnp.matmul(h, rr.T) + rbr))
        if lbr:  # reset gate applied to the already-projected hidden state
            hh = g(squash(xh + r * (jnp.matmul(h, rh.T) + rbh)))
        else:
            hh = g(squash(xh + jnp.matmul(r * h, rh.T) + rbh))
        h_new = (1.0 - z) * hh + z * h
        return h_new, h_new

    h_t, ys = lax.scan(step, h0, gx)
    return ys[:, None], h_t[None]
