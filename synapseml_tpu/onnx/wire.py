"""Self-contained ONNX protobuf wire-format codec (no ``onnx`` package dependency).

The reference consumes ONNX models through ONNX Runtime's JNI
(``deep-learning/.../onnx/ONNXModel.scala:173-193``). This rebuild lowers ONNX graphs to
JAX/XLA instead, and therefore needs to *read* ``ModelProto`` bytes itself. Rather than
depending on the ``onnx`` python package (not in the image), this module implements the
protobuf wire format directly for the ONNX schema subset that matters:

    ModelProto / GraphProto / NodeProto / AttributeProto / TensorProto /
    ValueInfoProto / TypeProto / TensorShapeProto / OperatorSetIdProto

Field numbers follow onnx/onnx.proto (onnx upstream, stable since IR v3). A writer for
the same subset lets tests and benchmarks construct real ``.onnx`` files (builder.py).
"""

from __future__ import annotations

import dataclasses
import struct
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

__all__ = [
    "TensorProto",
    "AttributeProto",
    "NodeProto",
    "ValueInfo",
    "GraphProto",
    "ModelProto",
    "parse_model",
    "serialize_model",
    "tensor_to_numpy",
    "numpy_to_tensor",
    "DataType",
]


# ---------------------------------------------------------------------------------
# low-level varint / wire primitives
# ---------------------------------------------------------------------------------

def _read_varint(buf: memoryview, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("varint too long (corrupt protobuf)")


def _write_varint(out: bytearray, value: int) -> None:
    if value < 0:
        value &= (1 << 64) - 1  # two's-complement 64-bit, proto convention
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _iter_fields(data: memoryview) -> Iterator[Tuple[int, int, Any]]:
    """Yield (field_number, wire_type, value) over a message buffer.

    wire types: 0 varint, 1 fixed64, 2 length-delimited (memoryview), 5 fixed32.
    """
    pos, end = 0, len(data)
    while pos < end:
        key, pos = _read_varint(data, pos)
        field, wt = key >> 3, key & 7
        if wt == 0:
            v, pos = _read_varint(data, pos)
        elif wt == 1:
            v = bytes(data[pos : pos + 8])
            pos += 8
        elif wt == 2:
            ln, pos = _read_varint(data, pos)
            v = data[pos : pos + ln]
            pos += ln
        elif wt == 5:
            v = bytes(data[pos : pos + 4])
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wt} for field {field}")
        yield field, wt, v


def _signed64(v: int) -> int:
    return v - (1 << 64) if v >= (1 << 63) else v


def _packed_varints(v: memoryview) -> List[int]:
    out, pos = [], 0
    while pos < len(v):
        x, pos = _read_varint(v, pos)
        out.append(_signed64(x))
    return out


def _tag(out: bytearray, field: int, wt: int) -> None:
    _write_varint(out, (field << 3) | wt)


def _put_bytes(out: bytearray, field: int, b: bytes) -> None:
    _tag(out, field, 2)
    _write_varint(out, len(b))
    out += b


def _put_str(out: bytearray, field: int, s: str) -> None:
    _put_bytes(out, field, s.encode("utf-8"))


def _put_varint_field(out: bytearray, field: int, v: int) -> None:
    _tag(out, field, 0)
    _write_varint(out, v)


# ---------------------------------------------------------------------------------
# ONNX data model (plain dataclasses)
# ---------------------------------------------------------------------------------

class DataType:
    """onnx.TensorProto.DataType enum values."""

    FLOAT = 1
    UINT8 = 2
    INT8 = 3
    UINT16 = 4
    INT16 = 5
    INT32 = 6
    INT64 = 7
    STRING = 8
    BOOL = 9
    FLOAT16 = 10
    DOUBLE = 11
    UINT32 = 12
    UINT64 = 13
    BFLOAT16 = 16

    _TO_NUMPY = {
        FLOAT: np.float32,
        UINT8: np.uint8,
        INT8: np.int8,
        UINT16: np.uint16,
        INT16: np.int16,
        INT32: np.int32,
        INT64: np.int64,
        BOOL: np.bool_,
        FLOAT16: np.float16,
        DOUBLE: np.float64,
        UINT32: np.uint32,
        UINT64: np.uint64,
    }

    @classmethod
    def to_numpy(cls, dt: int):
        if dt == cls.BFLOAT16:
            import ml_dtypes

            return ml_dtypes.bfloat16
        try:
            return cls._TO_NUMPY[dt]
        except KeyError:
            raise ValueError(f"unsupported ONNX data_type {dt}") from None

    @classmethod
    def from_numpy(cls, dtype) -> int:
        dtype = np.dtype(dtype)
        if dtype.name == "bfloat16":
            return cls.BFLOAT16
        for k, v in cls._TO_NUMPY.items():
            if np.dtype(v) == dtype:
                return k
        raise ValueError(f"unsupported numpy dtype {dtype}")


@dataclasses.dataclass
class TensorProto:
    name: str = ""
    dims: List[int] = dataclasses.field(default_factory=list)
    data_type: int = DataType.FLOAT
    raw_data: bytes = b""
    float_data: List[float] = dataclasses.field(default_factory=list)
    int32_data: List[int] = dataclasses.field(default_factory=list)
    int64_data: List[int] = dataclasses.field(default_factory=list)
    double_data: List[float] = dataclasses.field(default_factory=list)
    uint64_data: List[int] = dataclasses.field(default_factory=list)
    string_data: List[bytes] = dataclasses.field(default_factory=list)
    # data_location 1 = EXTERNAL: bytes live in a side file described by the
    # external_data entries (location / offset / length), the format real
    # exporters use past protobuf's 2GB limit
    data_location: int = 0
    external_data: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class AttributeProto:
    name: str = ""
    type: int = 0  # 1 FLOAT, 2 INT, 3 STRING, 4 TENSOR, 5 GRAPH, 6 FLOATS, 7 INTS, 8 STRINGS
    f: float = 0.0
    i: int = 0
    s: bytes = b""
    t: Optional[TensorProto] = None
    g: Optional["GraphProto"] = None
    floats: List[float] = dataclasses.field(default_factory=list)
    ints: List[int] = dataclasses.field(default_factory=list)
    strings: List[bytes] = dataclasses.field(default_factory=list)
    graphs: List["GraphProto"] = dataclasses.field(default_factory=list)
    # inside a FunctionProto body: take the value of the CALL node's
    # attribute with this name instead of a literal
    ref_attr_name: str = ""

    def value(self):
        return {
            1: self.f, 2: self.i, 3: self.s.decode("utf-8", "replace"),
            4: self.t, 5: self.g, 6: list(self.floats), 7: list(self.ints),
            8: [b.decode("utf-8", "replace") for b in self.strings], 10: list(self.graphs),
        }.get(self.type)


@dataclasses.dataclass
class NodeProto:
    op_type: str = ""
    name: str = ""
    domain: str = ""
    input: List[str] = dataclasses.field(default_factory=list)
    output: List[str] = dataclasses.field(default_factory=list)
    attribute: List[AttributeProto] = dataclasses.field(default_factory=list)

    def attrs(self) -> Dict[str, Any]:
        return {a.name: a.value() for a in self.attribute}


@dataclasses.dataclass
class ValueInfo:
    name: str = ""
    elem_type: int = 0
    # each dim: int (static), str (symbolic), or None (unknown)
    shape: Optional[List[Any]] = None


@dataclasses.dataclass
class GraphProto:
    name: str = ""
    node: List[NodeProto] = dataclasses.field(default_factory=list)
    initializer: List[TensorProto] = dataclasses.field(default_factory=list)
    input: List[ValueInfo] = dataclasses.field(default_factory=list)
    output: List[ValueInfo] = dataclasses.field(default_factory=list)
    value_info: List[ValueInfo] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class FunctionProto:
    """Model-local operator definition (ONNX functions, IR >= 8): nodes
    calling (domain, name) expand to the body with inputs bound and
    ``ref_attr_name`` attributes substituted from the call site."""

    name: str = ""
    domain: str = ""
    input: List[str] = dataclasses.field(default_factory=list)
    output: List[str] = dataclasses.field(default_factory=list)
    attribute: List[str] = dataclasses.field(default_factory=list)  # param names
    attribute_proto: List[AttributeProto] = dataclasses.field(
        default_factory=list)  # params with defaults
    node: List[NodeProto] = dataclasses.field(default_factory=list)
    opset_imports: Dict[str, int] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class ModelProto:
    ir_version: int = 8
    producer_name: str = ""
    graph: GraphProto = dataclasses.field(default_factory=GraphProto)
    opset_imports: Dict[str, int] = dataclasses.field(default_factory=dict)  # domain -> version
    functions: List[FunctionProto] = dataclasses.field(default_factory=list)

    @property
    def opset_version(self) -> int:
        return self.opset_imports.get("", 13)


# ---------------------------------------------------------------------------------
# parsing
# ---------------------------------------------------------------------------------

def _parse_tensor(data: memoryview) -> TensorProto:
    t = TensorProto()
    for field, wt, v in _iter_fields(data):
        if field == 1 and wt == 0:
            t.dims.append(_signed64(v))
        elif field == 1 and wt == 2:
            t.dims.extend(_packed_varints(v))
        elif field == 2:
            t.data_type = v
        elif field == 4:
            if wt == 2:
                t.float_data.extend(struct.unpack(f"<{len(v)//4}f", bytes(v)))
            else:
                t.float_data.append(struct.unpack("<f", v)[0])
        elif field == 5:
            if wt == 2:
                t.int32_data.extend(_packed_varints(v))
            else:
                t.int32_data.append(_signed64(v))
        elif field == 6:
            t.string_data.append(bytes(v))
        elif field == 7:
            if wt == 2:
                t.int64_data.extend(_packed_varints(v))
            else:
                t.int64_data.append(_signed64(v))
        elif field == 8:
            t.name = bytes(v).decode("utf-8")
        elif field == 9:
            t.raw_data = bytes(v)
        elif field == 10:
            if wt == 2:
                t.double_data.extend(struct.unpack(f"<{len(v)//8}d", bytes(v)))
            else:
                t.double_data.append(struct.unpack("<d", v)[0])
        elif field == 11:
            if wt == 2:
                t.uint64_data.extend(_packed_varints(v))
            else:
                t.uint64_data.append(v)
        elif field == 13:
            # StringStringEntryProto {key=1, value=2}
            k = val = ""
            for f2, wt2, v2 in _iter_fields(v):
                if f2 == 1:
                    k = bytes(v2).decode("utf-8")
                elif f2 == 2:
                    val = bytes(v2).decode("utf-8")
            if k:
                t.external_data[k] = val
        elif field == 14:
            t.data_location = v
    return t


def _parse_attribute(data: memoryview) -> AttributeProto:
    a = AttributeProto()
    for field, wt, v in _iter_fields(data):
        if field == 1:
            a.name = bytes(v).decode("utf-8")
        elif field == 2:
            a.f = struct.unpack("<f", v)[0]
        elif field == 3:
            a.i = _signed64(v)
        elif field == 4:
            a.s = bytes(v)
        elif field == 5:
            a.t = _parse_tensor(v)
        elif field == 6:
            a.g = _parse_graph(v)
        elif field == 7:
            if wt == 2:
                a.floats.extend(struct.unpack(f"<{len(v)//4}f", bytes(v)))
            else:
                a.floats.append(struct.unpack("<f", v)[0])
        elif field == 8:
            if wt == 2:
                a.ints.extend(_packed_varints(v))
            else:
                a.ints.append(_signed64(v))
        elif field == 9:
            a.strings.append(bytes(v))
        elif field == 11:
            a.graphs.append(_parse_graph(v))
        elif field == 20:
            a.type = v
        elif field == 21:
            a.ref_attr_name = bytes(v).decode("utf-8")
    if a.type == 0:
        # Older exporters omit type; infer from which field is populated.
        if a.t is not None:
            a.type = 4
        elif a.g is not None:
            a.type = 5
        elif a.floats:
            a.type = 6
        elif a.ints:
            a.type = 7
        elif a.strings:
            a.type = 8
        elif a.s:
            a.type = 3
        elif a.f:
            a.type = 1
        else:
            a.type = 2
    return a


def _parse_node(data: memoryview) -> NodeProto:
    n = NodeProto()
    for field, wt, v in _iter_fields(data):
        if field == 1:
            n.input.append(bytes(v).decode("utf-8"))
        elif field == 2:
            n.output.append(bytes(v).decode("utf-8"))
        elif field == 3:
            n.name = bytes(v).decode("utf-8")
        elif field == 4:
            n.op_type = bytes(v).decode("utf-8")
        elif field == 5:
            n.attribute.append(_parse_attribute(v))
        elif field == 7:
            n.domain = bytes(v).decode("utf-8")
    return n


def _parse_value_info(data: memoryview) -> ValueInfo:
    vi = ValueInfo()
    for field, wt, v in _iter_fields(data):
        if field == 1:
            vi.name = bytes(v).decode("utf-8")
        elif field == 2:
            # TypeProto { tensor_type = 1 { elem_type = 1; shape = 2 } }
            for f2, _w2, v2 in _iter_fields(v):
                if f2 == 1:
                    for f3, _w3, v3 in _iter_fields(v2):
                        if f3 == 1:
                            vi.elem_type = v3
                        elif f3 == 2:
                            dims: List[Any] = []
                            for f4, _w4, v4 in _iter_fields(v3):
                                if f4 == 1:  # Dimension
                                    dv: Any = None
                                    for f5, _w5, v5 in _iter_fields(v4):
                                        if f5 == 1:
                                            dv = _signed64(v5)
                                        elif f5 == 2:
                                            dv = bytes(v5).decode("utf-8")
                                    dims.append(dv)
                            vi.shape = dims
    return vi


def _parse_graph(data: memoryview) -> GraphProto:
    g = GraphProto()
    for field, wt, v in _iter_fields(data):
        if field == 1:
            g.node.append(_parse_node(v))
        elif field == 2:
            g.name = bytes(v).decode("utf-8")
        elif field == 5:
            g.initializer.append(_parse_tensor(v))
        elif field == 11:
            g.input.append(_parse_value_info(v))
        elif field == 12:
            g.output.append(_parse_value_info(v))
        elif field == 13:
            g.value_info.append(_parse_value_info(v))
    return g


def parse_model(data: bytes) -> ModelProto:
    m = ModelProto()
    mv = memoryview(data)
    for field, wt, v in _iter_fields(mv):
        if field == 1:
            m.ir_version = v
        elif field == 2:
            m.producer_name = bytes(v).decode("utf-8")
        elif field == 7:
            m.graph = _parse_graph(v)
        elif field == 8:
            domain, version = "", 0
            for f2, _w2, v2 in _iter_fields(v):
                if f2 == 1:
                    domain = bytes(v2).decode("utf-8")
                elif f2 == 2:
                    version = v2
            m.opset_imports[domain] = version
        elif field == 25:
            m.functions.append(_parse_function(v))
    return m


def _parse_function(data: memoryview) -> FunctionProto:
    f = FunctionProto()
    for field, wt, v in _iter_fields(data):
        if field == 1:
            f.name = bytes(v).decode("utf-8")
        elif field == 4:
            f.input.append(bytes(v).decode("utf-8"))
        elif field == 5:
            f.output.append(bytes(v).decode("utf-8"))
        elif field == 6:
            f.attribute.append(bytes(v).decode("utf-8"))
        elif field == 7:
            f.node.append(_parse_node(v))
        elif field == 9:
            domain, version = "", 0
            for f2, _w2, v2 in _iter_fields(v):
                if f2 == 1:
                    domain = bytes(v2).decode("utf-8")
                elif f2 == 2:
                    version = v2
            f.opset_imports[domain] = version
        elif field == 10:
            f.domain = bytes(v).decode("utf-8")
        elif field == 11:
            f.attribute_proto.append(_parse_attribute(v))
    return f


# ---------------------------------------------------------------------------------
# tensor <-> numpy
# ---------------------------------------------------------------------------------

def tensor_to_numpy(t: TensorProto, external_dir: Optional[str] = None) -> np.ndarray:
    np_dtype = DataType.to_numpy(t.data_type)
    shape = tuple(t.dims)
    if t.data_location == 1:  # EXTERNAL
        import os

        if external_dir is None:
            raise ValueError(
                f"tensor {t.name!r} stores its data externally "
                f"({t.external_data.get('location')!r}); load the model by "
                "path (load_model) or pass external_data_dir")
        loc = t.external_data.get("location", "")
        if not loc:
            raise ValueError(f"external tensor {t.name!r} has no 'location' "
                             "entry in external_data")
        base = os.path.realpath(external_dir)
        path = os.path.realpath(os.path.join(base, loc))
        if not path.startswith(base + os.sep):
            raise ValueError(f"external data location {loc!r} escapes the "
                             "model directory")
        offset = int(t.external_data.get("offset", 0) or 0)
        length = t.external_data.get("length")
        with open(path, "rb") as f:
            f.seek(offset)
            buf = f.read(int(length)) if length else f.read()
        return np.frombuffer(buf, dtype=np_dtype).reshape(shape)
    if t.raw_data:
        arr = np.frombuffer(t.raw_data, dtype=np_dtype)
    elif t.data_type == DataType.FLOAT and t.float_data:
        arr = np.asarray(t.float_data, dtype=np.float32)
    elif t.data_type == DataType.DOUBLE and t.double_data:
        arr = np.asarray(t.double_data, dtype=np.float64)
    elif t.data_type == DataType.INT64 and t.int64_data:
        arr = np.asarray(t.int64_data, dtype=np.int64)
    elif t.data_type in (DataType.INT32, DataType.INT16, DataType.INT8, DataType.UINT16,
                         DataType.UINT8, DataType.BOOL, DataType.FLOAT16) and t.int32_data:
        if t.data_type == DataType.FLOAT16:
            arr = np.asarray(t.int32_data, dtype=np.uint16).view(np.float16)
        else:
            arr = np.asarray(t.int32_data).astype(np_dtype)
    elif t.data_type in (DataType.UINT64, DataType.UINT32) and t.uint64_data:
        arr = np.asarray(t.uint64_data, dtype=np_dtype)
    else:
        arr = np.zeros(int(np.prod(shape)) if shape else 0, dtype=np_dtype)
    return arr.reshape(shape)


def numpy_to_tensor(name: str, arr: np.ndarray) -> TensorProto:
    # NB: np.ascontiguousarray would promote 0-d to 1-d, corrupting scalar tensors.
    arr = np.asarray(arr, order="C")
    return TensorProto(
        name=name,
        dims=list(arr.shape),
        data_type=DataType.from_numpy(arr.dtype),
        raw_data=arr.tobytes(),
    )


# ---------------------------------------------------------------------------------
# serialization (writer)
# ---------------------------------------------------------------------------------

def _ser_tensor(t: TensorProto) -> bytes:
    out = bytearray()
    for d in t.dims:
        _put_varint_field(out, 1, d)
    _put_varint_field(out, 2, t.data_type)
    if t.name:
        _put_str(out, 8, t.name)
    if t.raw_data:
        _put_bytes(out, 9, t.raw_data)
    if t.float_data:
        _put_bytes(out, 4, struct.pack(f"<{len(t.float_data)}f", *t.float_data))
    if t.int64_data:
        packed = bytearray()
        for x in t.int64_data:
            _write_varint(packed, x)
        _put_bytes(out, 7, bytes(packed))
    for k, v in t.external_data.items():  # round-trip external references
        entry = bytearray()
        _put_str(entry, 1, k)
        _put_str(entry, 2, v)
        _put_bytes(out, 13, bytes(entry))
    if t.data_location:
        _put_varint_field(out, 14, t.data_location)
    return bytes(out)


def _ser_attribute(a: AttributeProto) -> bytes:
    out = bytearray()
    _put_str(out, 1, a.name)
    if a.type == 1:
        _tag(out, 2, 5)
        out += struct.pack("<f", a.f)
    elif a.type == 2:
        _tag(out, 3, 0)
        _write_varint(out, a.i)
    elif a.type == 3:
        _put_bytes(out, 4, a.s)
    elif a.type == 4:
        _put_bytes(out, 5, _ser_tensor(a.t))
    elif a.type == 5:
        _put_bytes(out, 6, _ser_graph(a.g))
    elif a.type == 6:
        _put_bytes(out, 7, struct.pack(f"<{len(a.floats)}f", *a.floats))
    elif a.type == 7:
        packed = bytearray()
        for x in a.ints:
            _write_varint(packed, x)
        _put_bytes(out, 8, bytes(packed))
    elif a.type == 8:
        for s in a.strings:
            _put_bytes(out, 9, s)
    _put_varint_field(out, 20, a.type)
    return bytes(out)


def _ser_node(n: NodeProto) -> bytes:
    out = bytearray()
    for s in n.input:
        _put_str(out, 1, s)
    for s in n.output:
        _put_str(out, 2, s)
    if n.name:
        _put_str(out, 3, n.name)
    _put_str(out, 4, n.op_type)
    for a in n.attribute:
        _put_bytes(out, 5, _ser_attribute(a))
    if n.domain:
        _put_str(out, 7, n.domain)
    return bytes(out)


def _ser_value_info(vi: ValueInfo) -> bytes:
    shape_buf = bytearray()
    for d in vi.shape or []:
        dim = bytearray()
        if isinstance(d, int):
            _put_varint_field(dim, 1, d)
        elif isinstance(d, str):
            _put_str(dim, 2, d)
        _put_bytes(shape_buf, 1, bytes(dim))
    tensor_type = bytearray()
    _put_varint_field(tensor_type, 1, vi.elem_type)
    if vi.shape is not None:
        _put_bytes(tensor_type, 2, bytes(shape_buf))
    type_proto = bytearray()
    _put_bytes(type_proto, 1, bytes(tensor_type))
    out = bytearray()
    _put_str(out, 1, vi.name)
    _put_bytes(out, 2, bytes(type_proto))
    return bytes(out)


def _ser_graph(g: GraphProto) -> bytes:
    out = bytearray()
    for n in g.node:
        _put_bytes(out, 1, _ser_node(n))
    if g.name:
        _put_str(out, 2, g.name)
    for t in g.initializer:
        _put_bytes(out, 5, _ser_tensor(t))
    for vi in g.input:
        _put_bytes(out, 11, _ser_value_info(vi))
    for vi in g.output:
        _put_bytes(out, 12, _ser_value_info(vi))
    for vi in g.value_info:
        _put_bytes(out, 13, _ser_value_info(vi))
    return bytes(out)


def serialize_model(m: ModelProto) -> bytes:
    out = bytearray()
    _put_varint_field(out, 1, m.ir_version)
    if m.producer_name:
        _put_str(out, 2, m.producer_name)
    _put_bytes(out, 7, _ser_graph(m.graph))
    opsets = m.opset_imports or {"": 13}
    for domain, version in opsets.items():
        op = bytearray()
        if domain:
            _put_str(op, 1, domain)
        _put_varint_field(op, 2, version)
        _put_bytes(out, 8, bytes(op))
    return bytes(out)
