"""ONNX graph → XLA executable.

Replaces the reference's ONNX Runtime JNI session
(``deep-learning/.../onnx/ONNXModel.scala:173-193`` ``initializeOrt`` /
``applyModel:305-355``) with a direct lowering: the graph is *interpreted once under
``jax.jit`` tracing*, emitting one fused XLA program per input-shape signature. There is
no per-op dispatch at run time and no JVM↔native tensor copies — feeds go device-side
once, the whole graph runs as a single compiled computation.

Static-shape discipline (TPU requirement): ``Shape``/shape arithmetic is constant-folded
during tracing (any node whose inputs are all graph-constants is evaluated eagerly and
pinned as numpy), so BERT-style dynamic-reshape chains compile to static programs. Each
distinct input shape triggers one retrace — callers batch with fixed bucket sizes
(``ONNXModel`` pads minibatches for exactly this reason; the reference instead pins
shape(0)=batch at ``ONNXModel.scala:357-362``).

``dtype_policy='bfloat16'`` runs floating-point compute in bf16 (inputs/weights cast,
matmul/conv accumulate in f32 via ``preferred_element_type``, outputs returned f32) —
the MXU-native mode.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .ops import OPS
from .wire import GraphProto, ModelProto, ValueInfo, parse_model, tensor_to_numpy

__all__ = ["OnnxFunction", "load_model"]

_logger = logging.getLogger("synapseml_tpu.onnx")


def _is_const(v) -> bool:
    return isinstance(v, np.ndarray) or np.isscalar(v)


class OnnxFunction:
    """Callable wrapper: ``fn(feeds: dict[str, array]) -> dict[str, array]``.

    jit-compiled per input-shape signature; signatures are cached by jax.jit itself.
    """

    def __init__(self, model: "ModelProto | bytes", dtype_policy: str = "float32"):
        import jax

        if isinstance(model, (bytes, bytearray, memoryview)):
            model = parse_model(bytes(model))
        self.model = model
        self.graph = model.graph
        self.opset = model.opset_version
        if dtype_policy not in ("float32", "bfloat16"):
            raise ValueError(f"unknown dtype_policy {dtype_policy!r}")
        self.dtype_policy = dtype_policy
        self.constants: Dict[str, np.ndarray] = {
            t.name: tensor_to_numpy(t) for t in self.graph.initializer
        }
        init_names = set(self.constants)
        # Graph inputs that are not initializers are the real feeds.
        self.input_infos: List[ValueInfo] = [
            vi for vi in self.graph.input if vi.name not in init_names
        ]
        self.input_names: List[str] = [vi.name for vi in self.input_infos]
        self.output_names: List[str] = [vi.name for vi in self.graph.output]
        self._validate_ops(self.graph)
        self._jit = jax.jit(self._run_positional)

    # -- public ------------------------------------------------------------------

    def __call__(self, feeds: Dict[str, Any]) -> Dict[str, Any]:
        missing = [n for n in self.input_names if n not in feeds]
        if missing:
            raise ValueError(f"missing feeds {missing}; expected {self.input_names}")
        import jax

        # Leave device-resident jax arrays in place; only materialize host data.
        args = [
            feeds[n] if isinstance(feeds[n], jax.Array) else np.asarray(feeds[n])
            for n in self.input_names
        ]
        outs = self._jit(*args)
        return dict(zip(self.output_names, outs))

    def input_shapes(self) -> Dict[str, Optional[List[Any]]]:
        return {vi.name: vi.shape for vi in self.input_infos}

    # -- execution ---------------------------------------------------------------

    def _validate_ops(self, graph: GraphProto) -> None:
        missing = sorted({n.op_type for n in graph.node if n.op_type not in OPS})
        if missing:
            raise NotImplementedError(
                f"ONNX ops not supported by the importer: {missing}. "
                f"Supported: {len(OPS)} ops; extend synapseml_tpu/onnx/ops.py."
            )

    def _cast_policy_in(self, x):
        import jax.numpy as jnp

        dtype = getattr(x, "dtype", None)
        if self.dtype_policy == "bfloat16" and dtype is not None and jnp.issubdtype(dtype, jnp.floating):
            return jnp.asarray(x, dtype=jnp.bfloat16)
        return x

    def _run_positional(self, *arrays):
        import jax.numpy as jnp

        env: Dict[str, Any] = {"": None}
        for name, const in self.constants.items():
            env[name] = (
                const.astype(np.dtype("bfloat16"))
                if self.dtype_policy == "bfloat16" and np.issubdtype(const.dtype, np.floating)
                else const
            )
        for name, arr in zip(self.input_names, arrays):
            env[name] = self._cast_policy_in(arr)
        self._run_graph(self.graph, env)
        outs = []
        for name in self.output_names:
            v = env[name]
            if self.dtype_policy == "bfloat16" and hasattr(v, "dtype") and v.dtype == jnp.bfloat16:
                v = v.astype(jnp.float32)
            outs.append(jnp.asarray(v))
        return tuple(outs)

    def _run_graph(self, graph: GraphProto, env: Dict[str, Any]) -> None:
        import jax.numpy as jnp

        accum = jnp.float32 if self.dtype_policy == "bfloat16" else None

        def subgraph_runner(sub: GraphProto):
            def run():
                sub_env = dict(env)
                self._run_graph(sub, sub_env)
                vals = [sub_env[o.name] for o in sub.output]
                return vals[0] if len(vals) == 1 else tuple(vals)

            return run

        for node in graph.node:
            try:
                fn = OPS[node.op_type]
            except KeyError:
                raise NotImplementedError(f"unsupported ONNX op {node.op_type}") from None
            inputs = [env[i] if i else None for i in node.input]
            ctx = {
                "op_type": node.op_type,
                "opset": self.opset,
                "n_outputs": len(node.output),
                "accum_dtype": accum,
                "subgraph_runner": subgraph_runner,
            }
            try:
                out = fn(inputs, node.attrs(), ctx)
            except Exception as e:
                raise type(e)(
                    f"while executing node {node.name or '?'} ({node.op_type}) "
                    f"inputs={node.input}: {e}"
                ) from e
            outs = out if isinstance(out, tuple) else (out,)
            # Constant folding: all-constant inputs => pin outputs as numpy so shape
            # chains (Shape -> Gather -> Concat -> Reshape) stay static under tracing.
            if all(v is None or _is_const(v) for v in inputs) and node.op_type != "Dropout":
                pinned = []
                for o in outs:
                    try:
                        pinned.append(np.asarray(o))
                    except Exception:
                        pinned.append(o)  # traced despite const inputs (shouldn't happen)
                outs = tuple(pinned)
            for name, val in zip(node.output, outs):
                if name:
                    env[name] = val


def load_model(path_or_bytes, dtype_policy: str = "float32") -> OnnxFunction:
    """Load an ``.onnx`` file (path or bytes) into an executable function."""
    if isinstance(path_or_bytes, (bytes, bytearray, memoryview)):
        data = bytes(path_or_bytes)
    else:
        with open(path_or_bytes, "rb") as f:
            data = f.read()
    return OnnxFunction(data, dtype_policy=dtype_policy)
