"""ONNX graph → XLA executable.

Replaces the reference's ONNX Runtime JNI session
(``deep-learning/.../onnx/ONNXModel.scala:173-193`` ``initializeOrt`` /
``applyModel:305-355``) with a direct lowering: the graph is *interpreted once under
``jax.jit`` tracing*, emitting one fused XLA program per input-shape signature. There is
no per-op dispatch at run time and no JVM↔native tensor copies — feeds go device-side
once, the whole graph runs as a single compiled computation.

Static-shape discipline (TPU requirement): ``Shape``/shape arithmetic is constant-folded
during tracing (any node whose inputs are all graph-constants is evaluated eagerly and
pinned as numpy), so BERT-style dynamic-reshape chains compile to static programs. Each
distinct input shape triggers one retrace — callers batch with fixed bucket sizes
(``ONNXModel`` pads minibatches for exactly this reason; the reference instead pins
shape(0)=batch at ``ONNXModel.scala:357-362``).

``dtype_policy='bfloat16'`` runs floating-point compute in bf16 (inputs/weights cast,
matmul/conv accumulate in f32 via ``preferred_element_type``, outputs returned f32) —
the MXU-native mode.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .ops import OPS
from .wire import (DataType, GraphProto, ModelProto, ValueInfo, parse_model,
                   tensor_to_numpy)

__all__ = ["OnnxFunction", "load_model", "model_io_specs"]

_logger = logging.getLogger("synapseml_tpu.onnx")


def _is_const(v) -> bool:
    return isinstance(v, np.ndarray) or np.isscalar(v)


def _value_info_spec(vi: ValueInfo):
    """(dtype_class, shape_role) of a graph ``value_info`` entry, in
    :mod:`synapseml_tpu.core.schema` vocabulary. The leading dim is the
    batch axis, so a rank-2 graph tensor is a per-row *vector* column, a
    rank-3+ one a *tensor* column, rank-0/1 a *scalar* column. Unknown
    element types / shapes degrade to ``any``."""
    np_dtype = DataType._TO_NUMPY.get(vi.elem_type)
    if np_dtype is None:
        dtype_class = "any"
    else:
        from ..core.schema import dtype_class_of

        dtype_class = dtype_class_of(np_dtype)
    if vi.shape is None:
        role = "any"
    elif len(vi.shape) <= 1:
        role = "scalar"
    elif len(vi.shape) == 2:
        role = "vector"
    else:
        role = "tensor"
    return (dtype_class, role)


def model_io_specs(model: "ModelProto | bytes"):
    """Static (input specs, output specs) of an ONNX model, derived from
    the graph's ``value_info`` — ``{name: (dtype_class, shape_role)}``
    per side, initializers excluded from inputs.

    Pure wire-format work: parses the protobuf only, NEVER imports jax —
    this is what ``ONNXModel.transform_schema`` and ``Pipeline.validate``
    run at plan time, and what serving admission derives its request
    schema from."""
    if isinstance(model, (bytes, bytearray, memoryview)):
        model = parse_model(bytes(model))
    graph = model.graph
    init_names = {t.name for t in graph.initializer}
    inputs = {vi.name: _value_info_spec(vi) for vi in graph.input
              if vi.name not in init_names}
    outputs = {vi.name: _value_info_spec(vi) for vi in graph.output}
    return inputs, outputs


class OnnxFunction:
    """Callable wrapper: ``fn(feeds: dict[str, array]) -> dict[str, array]``.

    jit-compiled per input-shape signature; signatures are cached by jax.jit itself.
    """

    def __init__(self, model: "ModelProto | bytes", dtype_policy: str = "float32",
                 channels_last: bool = False,
                 external_data_dir: "str | None" = None,
                 layout=None):
        import jax

        if isinstance(model, (bytes, bytearray, memoryview)):
            model = parse_model(bytes(model))
        self.model = model
        self.graph = model.graph
        self.opset = model.opset_version
        if dtype_policy not in ("float32", "bfloat16"):
            raise ValueError(f"unknown dtype_policy {dtype_policy!r}")
        self.dtype_policy = dtype_policy
        # NHWC layout propagation (opt-in): Conv/BatchNorm/elementwise chains
        # execute channels-last; other consumers transpose back on demand.
        # An ISOLATED mid-network conv measures ~1.6x faster NHWC on v5e,
        # but on the full ResNet-50 graph XLA's layout assignment already
        # picks optimal physical layouts for the logical-NCHW program and the
        # pass's edge transposes cost more than they save (measured 12.9 vs
        # 16.4 ms/fwd at batch 128) — hence default OFF; kept for backends
        # whose layout assignment is weaker.
        self.channels_last = bool(channels_last)
        self._external_dir = external_data_dir
        # model-local functions: nodes whose (domain, op_type) matches expand
        # to the function body (real exporters emit e.g. LayerNormalization
        # or custom ops this way from IR 8 on)
        self.functions = {(f.domain, f.name): f
                          for f in getattr(model, "functions", [])}
        self.constants: Dict[str, np.ndarray] = {
            t.name: tensor_to_numpy(t, external_dir=external_data_dir)
            for t in self.graph.initializer
        }
        init_names = set(self.constants)
        # Graph inputs that are not initializers are the real feeds.
        self.input_infos: List[ValueInfo] = [
            vi for vi in self.graph.input if vi.name not in init_names
        ]
        self.input_names: List[str] = [vi.name for vi in self.input_infos]
        self.output_names: List[str] = [vi.name for vi in self.graph.output]
        self._validate_ops(self.graph)
        # -- model-parallel weight sharding (runtime/layout.py SpecLayout) ----
        # MatMul/Gemm RHS weights partition COLUMN-wise over the layout's
        # 'model' axis and Conv kernels over output channels; jax.jit's GSPMD
        # pass inserts the collectives. Each chip then holds 1/m of every
        # big weight — models larger than one chip's HBM serve at all, and
        # the matmuls themselves run tensor-parallel. Weights keep their
        # sharded placement from __init__ (device_put) and the traced program
        # re-pins it (with_sharding_constraint), so the intent survives
        # however jit stages the closure constants.
        self.layout = layout
        self._const_plan: List[Dict[str, Any]] = []
        self._const_specs: Dict[str, Any] = (
            self._plan_const_specs() if layout is not None
            and (getattr(layout, "model_size", 1) > 1
                 or getattr(layout, "fsdp_size", 1) > 1) else {})
        for name, spec in self._const_specs.items():
            const = self.constants[name]
            if self.dtype_policy == "bfloat16":
                # cast BEFORE placement: the executable only ever consumes
                # the bf16 view, and the whole point of tp-sharding is HBM
                # headroom — a resident f32 master copy would triple it
                const = const.astype(np.dtype("bfloat16"))
            self.constants[name] = layout.put(const, spec)
        # profiled jit entry point: every XLA compile of this model is
        # timed into smt_compile_seconds{fn=...}, its cost_analysis FLOPs
        # cached, and warm calls attribute achieved MFU to the enclosing
        # stage span (observability/profiling.py)
        from ..observability.profiling import profiled_jit

        graph_name = getattr(self.graph, "name", "") or "graph"
        # the persisted-AOT digest must see the weight placement: the
        # same graph under a replicated, (1,2)-tp or (2,2,2)-fsdp layout
        # compiles three different executables behind identical input
        # avals, and loading the wrong one raises (at best)
        closure_key = f"dtype={self.dtype_policy}"
        if self._const_specs:
            closure_key += ";layout=" + str(layout.describe()) + ";" + \
                ",".join(f"{n}:{self._const_specs[n]}"
                         for n in sorted(self._const_specs))
        self._jit = profiled_jit(self._run_positional,
                                 name=f"onnx.{graph_name}",
                                 closure_key=closure_key)

    # -- public ------------------------------------------------------------------

    def __call__(self, feeds: Dict[str, Any]) -> Dict[str, Any]:
        missing = [n for n in self.input_names if n not in feeds]
        if missing:
            raise ValueError(f"missing feeds {missing}; expected {self.input_names}")
        import jax

        # Leave device-resident jax arrays in place; only materialize host data.
        args = [
            feeds[n] if isinstance(feeds[n], jax.Array) else np.asarray(feeds[n])
            for n in self.input_names
        ]
        outs = self._jit(*args)
        return dict(zip(self.output_names, outs))

    def input_shapes(self) -> Dict[str, Optional[List[Any]]]:
        return {vi.name: vi.shape for vi in self.input_infos}

    # -- model-parallel spec planning (pure graph analysis, no jax) --------------

    def _plan_const_specs(self) -> Dict[str, Any]:
        """Per-initializer PartitionSpec for tensor-parallel serving.

        A weight is sharded only when EVERY consumer agrees on one role:
        - ``MatMul`` input 1, rank 2  -> columns (output features) over
          ``model``;
        - ``Gemm`` input 1, rank 2    -> the output-feature dim (respects
          ``transB``);
        - ``Conv`` input 1, rank 4    -> output channels (OIHW dim 0).
        Anything else (biases, norm params, shape operands, multi-role
        weights) replicates — GSPMD still partitions the surrounding
        compute. Shape arithmetic never involves these tensors, so
        constant folding is unaffected.

        Under a 3-D layout (``fsdp_size > 1``) the planner knows a THIRD
        decision besides shard-over-model/replicate: store-over-fsdp +
        gather-at-consumer. Weights are *stored* row-sharded over the
        fsdp axis (stacked on top of any model sharding) and all-gathered
        transiently at the point of use (``gather_for_use`` re-pin inside
        the jit). This finally gives multi-role weights a correct answer:
        a tied tensor consumed as both a MatMul RHS and a transposed Gemm
        RHS cannot pick one resident sharded form, but it CAN store
        row-sharded and hand each consumer its own transient gathered
        copy — at-rest HBM drops by 1/fsdp instead of paying full
        replication."""
        roles: Dict[str, set] = {}

        def scan(graph):
            for node in graph.node:
                attrs = node.attrs()
                for slot, name in enumerate(node.input):
                    if not name or name not in self.constants:
                        continue
                    const = self.constants[name]
                    role = None
                    if slot == 1 and node.op_type == "MatMul" \
                            and const.ndim == 2:
                        role = ("col", 1)
                    elif slot == 1 and node.op_type == "Gemm" \
                            and const.ndim == 2:
                        role = ("col", 0 if int(attrs.get("transB", 0))
                                else 1)
                    elif slot == 1 and node.op_type == "Conv" \
                            and const.ndim == 4:
                        role = ("conv", 0)
                    roles.setdefault(name, set()).add(role)
                for a in node.attribute:
                    if a.g is not None:
                        scan(a.g)
                    for g in a.graphs:
                        scan(g)

        scan(self.graph)
        for f in self.functions.values():
            scan(f)
        layout = self.layout
        m = layout.model_size
        f = getattr(layout, "fsdp_size", 1)
        specs: Dict[str, Any] = {}

        def record(name: str, decision: str, reason: str) -> None:
            # residency ledger for placement_report(): shape/bytes are
            # captured NOW, while the constant is still a host array
            # (after __init__ the sharded ones are device-resident)
            const = self.constants[name]
            self._const_plan.append({
                "tensor": name, "shape": tuple(const.shape),
                "nbytes": int(const.nbytes),
                "decision": decision, "reason": reason})

        def fsdp_store_dim(const, avoid: Optional[int]) -> Optional[int]:
            # first dim (skipping any model-sharded one) whose size splits
            # over the fsdp axis — the row dim the weight is STORED over
            if f <= 1:
                return None
            for sd in range(const.ndim):
                if sd != avoid and const.shape[sd] % f == 0:
                    return sd
            return None

        for name, rs in roles.items():
            const = self.constants[name]
            is_float = np.issubdtype(const.dtype, np.floating)
            if len(rs) != 1 or None in rs:
                kinds = sorted(str(r) for r in rs)
                conflict = (f"consumer-role conflict ({', '.join(kinds)}) — "
                            f"no single shardable role; tied/multi-use "
                            f"weight")
                # store-over-fsdp only pays for real WEIGHTS (some consumer
                # wanted it sharded); pure-elementwise operands (biases,
                # norm params: roles == {None}) stay replicated as before
                sd = (fsdp_store_dim(const, None)
                      if is_float and rs != {None} else None)
                if sd is None:
                    record(name, "replicated", conflict)
                    continue
                # THE fsdp decision: no resident sharded form satisfies
                # every consumer, but row-sharded STORAGE + a transient
                # gathered copy per consumer satisfies all of them
                specs[name] = layout.fsdp_weight(rank=const.ndim, dim=sd)
                record(name, "fsdp",
                       f"stored over fsdp={f} on dim {sd}, all-gathered at "
                       f"each consumer — resolves {conflict}")
                continue
            kind, dim = next(iter(rs))
            if not is_float:
                record(name, "replicated",
                       f"non-float dtype {const.dtype} (shape operand / "
                       f"index table)")
                continue
            if m > 1 and const.shape[dim] % m == 0:
                use = (layout.conv_weight(rank=const.ndim)
                       if kind == "conv"
                       else layout.col_weight(rank=const.ndim, dim=dim))
                sd = fsdp_store_dim(const, avoid=dim)
                if sd is None:
                    specs[name] = use
                    record(name, "sharded",
                           f"{kind} weight: dim {dim} over model={m}")
                else:
                    # SNIPPETS [3] embeddings layout: use-sharded over
                    # model AND stored row-sharded over fsdp — at rest
                    # each device holds 1/(f*m) of the tensor
                    specs[name] = layout.fsdp_weight(
                        rank=const.ndim, dim=sd, use_spec=use)
                    record(name, "fsdp",
                           f"{kind} weight: dim {dim} over model={m}, "
                           f"stored over fsdp={f} on dim {sd}; fsdp axis "
                           f"all-gathered on use")
                continue
            if m > 1:
                record(name, "replicated",
                       f"{kind} dim {dim} size {const.shape[dim]} not "
                       f"divisible by model={m}")
                continue
            # model axis unpopulated (fsdp-only layout): storage sharding
            # is still worth it for weight-role tensors
            sd = fsdp_store_dim(const, None)
            if sd is None:
                record(name, "replicated",
                       f"{kind} weight: no dim divisible by fsdp={f}")
                continue
            specs[name] = layout.fsdp_weight(rank=const.ndim, dim=sd)
            record(name, "fsdp",
                   f"{kind} weight: stored over fsdp={f} on dim {sd}, "
                   f"all-gathered on use")
        for name in self.constants:
            if name not in roles:
                record(name, "replicated",
                       "no weight-role consumer (bias / norm param / "
                       "unconsumed initializer)")
        return specs

    def placement_report(self) -> List[Dict[str, Any]]:
        """Per-initializer residency decisions under the tensor-parallel
        layout, largest tensor first — each row names the tensor, its
        host-side footprint, and WHY the planner sharded or replicated it.
        Empty without a populated model or fsdp axis (nothing to shard
        across). The SPMD lint pack (``analysis/rules_spmd.py`` SMT110)
        turns every large replicated row into a finding, so the planner's
        silent "replicate on conflict" choices surface before they cost
        HBM; ``fsdp`` rows document the store-over-fsdp +
        gather-at-consumer placements (reason strings carry the stored
        dim and axis sizes)."""
        return sorted((dict(r) for r in self._const_plan),
                      key=lambda r: (-r["nbytes"], r["tensor"]))

    # -- execution ---------------------------------------------------------------

    def _validate_ops(self, graph: GraphProto) -> None:
        missing = sorted({n.op_type for n in graph.node
                          if n.op_type not in OPS
                          and (n.domain, n.op_type) not in self.functions})
        for f in self.functions.values():
            missing += [n.op_type for n in f.node
                        if n.op_type not in OPS
                        and (n.domain, n.op_type) not in self.functions]
        if missing:
            raise NotImplementedError(
                f"ONNX ops not supported by the importer: {sorted(set(missing))}. "
                f"Supported: {len(OPS)} ops; extend synapseml_tpu/onnx/ops.py."
            )

    def _cast_policy_in(self, x):
        import jax.numpy as jnp

        dtype = getattr(x, "dtype", None)
        if self.dtype_policy == "bfloat16" and dtype is not None and jnp.issubdtype(dtype, jnp.floating):
            return jnp.asarray(x, dtype=jnp.bfloat16)
        return x

    def _run_positional(self, *arrays):
        import jax.numpy as jnp

        env: Dict[str, Any] = {"": None}
        for name, const in self.constants.items():
            v = (
                const.astype(np.dtype("bfloat16"))
                if self.dtype_policy == "bfloat16" and np.issubdtype(const.dtype, np.floating)
                else const
            )
            if name in self._const_specs:
                # re-pin the tensor-parallel placement inside the traced
                # program so GSPMD partitions the consuming matmul however
                # jit chose to stage the closure constant
                spec = self._const_specs[name]
                v = self.layout.constraint(jnp.asarray(v), spec)
                use = self.layout.use_spec(spec) \
                    if hasattr(self.layout, "use_spec") else spec
                if use != spec:
                    # stored-over-fsdp weight: all-gather-on-use. The
                    # re-pin to the use spec makes GSPMD insert the
                    # all-gather here, so the gathered copy is a transient
                    # of this step — at rest only the row shards persist.
                    v = self.layout.gather_for_use(v, spec)
            env[name] = v
        for name, arr in zip(self.input_names, arrays):
            env[name] = self._cast_policy_in(arr)
        self._run_graph(self.graph, env)
        outs = []
        for name in self.output_names:
            v = env[name]
            if self.dtype_policy == "bfloat16" and hasattr(v, "dtype") and v.dtype == jnp.bfloat16:
                v = v.astype(jnp.float32)
            outs.append(jnp.asarray(v))
        return tuple(outs)

    # unary ops that are layout-agnostic: run them directly on an NHWC array
    _NHWC_UNARY = frozenset({
        "Relu", "LeakyRelu", "Sigmoid", "Tanh", "Elu", "Selu", "Softplus",
        "HardSigmoid", "Identity", "Neg", "Abs", "Sqrt", "Exp", "Log",
        "Floor", "Ceil", "Erf", "Clip", "Cast",
    })
    _NHWC_BINARY = frozenset({"Add", "Sub", "Mul", "Div", "Min", "Max",
                              "Pow", "PRelu"})

    def _try_nhwc(self, node, env: Dict[str, Any], nhwc: set) -> bool:
        """Execute ``node`` channels-last when profitable. Returns True when
        the node was handled (outputs written to env, layout recorded)."""
        import jax.numpy as jnp
        from jax import lax

        op_type = node.op_type
        ins = [env.get(i) if i else None for i in node.input]
        if all(v is None or _is_const(v) for v in ins):
            return False  # leave constant folding to the generic path

        def as_nhwc(name):
            v = env[name]
            return v if name in nhwc else jnp.transpose(v, (0, 2, 3, 1))

        accum = jnp.float32 if self.dtype_policy == "bfloat16" else None

        if op_type == "Conv" and ins[0] is not None and ins[0].ndim == 4 \
                and ins[1] is not None and ins[1].ndim == 4:
            attrs = node.attrs()
            x = as_nhwc(node.input[0])
            w = ins[1]  # OIHW
            strides = [int(s) for s in attrs.get("strides", [1, 1])]
            dils = [int(d) for d in attrs.get("dilations", [1, 1])]
            groups = int(attrs.get("group", 1))
            from .ops import _resolve_pads

            # _resolve_pads reads spatial dims at x_shape[2+i]; feed std dims
            std_shape = (x.shape[0], x.shape[3], x.shape[1], x.shape[2])
            pads = _resolve_pads(attrs, 2, std_shape, w.shape[2:], strides,
                                 dils)
            hwio = (w.shape[2], w.shape[3], w.shape[1], w.shape[0])
            dn = lax.conv_dimension_numbers(x.shape, hwio,
                                            ("NHWC", "HWIO", "NHWC"))
            out = lax.conv_general_dilated(
                x, jnp.transpose(w, (2, 3, 1, 0)), window_strides=strides,
                padding=pads, rhs_dilation=dils, dimension_numbers=dn,
                feature_group_count=groups, preferred_element_type=accum)
            if out.dtype != x.dtype:
                out = out.astype(x.dtype)
            if len(ins) > 2 and ins[2] is not None:
                out = out + ins[2].reshape((1, 1, 1, -1))
            env[node.output[0]] = out
            nhwc.add(node.output[0])
            return True

        if op_type == "BatchNormalization" and len(node.output) == 1 \
                and node.input[0] in nhwc:
            attrs = node.attrs()
            x = env[node.input[0]]
            scale, bias, mean, var = ins[1:5]
            eps = attrs.get("epsilon", 1e-5)
            inv = lax.rsqrt(jnp.asarray(var, jnp.float32) + eps).astype(x.dtype)
            env[node.output[0]] = (x - mean) * (scale * inv) + bias
            nhwc.add(node.output[0])
            return True

        if op_type in self._NHWC_UNARY and node.input and \
                node.input[0] in nhwc and len(node.output) == 1:
            inputs = [env[i] if i else None for i in node.input]
            ctx = {"op_type": op_type, "opset": self.opset, "n_outputs": 1,
                   "accum_dtype": accum, "subgraph_runner": None,
                   "external_dir": self._external_dir}
            env[node.output[0]] = OPS[op_type](inputs, node.attrs(), ctx)
            nhwc.add(node.output[0])
            return True

        if op_type in self._NHWC_BINARY and len(node.input) >= 2 \
                and len(node.output) == 1:
            a_name, b_name = node.input[0], node.input[1]
            va, vb = env.get(a_name), env.get(b_name)
            if va is None or vb is None:
                return False
            na, nb = a_name in nhwc, b_name in nhwc

            def compatible(other, other_is_nhwc):
                """Rewritten operand broadcastable against NHWC, or None."""
                if other_is_nhwc:
                    return other
                if np.isscalar(other) or getattr(other, "ndim", None) == 0 \
                        or getattr(other, "size", None) == 1:
                    return other
                # NCHW-broadcast constants (1, C, 1, 1) / (C, 1, 1) -> last-axis
                shp = getattr(other, "shape", None)
                if shp is not None and len(shp) == 4 and shp[2] == shp[3] == 1:
                    return jnp.transpose(jnp.asarray(other), (0, 2, 3, 1))
                if shp is not None and len(shp) == 3 and shp[1] == shp[2] == 1:
                    return jnp.asarray(other).reshape(1, 1, 1, -1)
                return None

            if na and nb:
                if getattr(va, "shape", None) != getattr(vb, "shape", None):
                    return False
                xa, xb = va, vb
            elif na:
                xb = compatible(vb, False)
                if xb is None:
                    return False
                xa = va
            elif nb:
                xa = compatible(va, False)
                if xa is None:
                    return False
                xb = vb
            else:
                return False
            ctx = {"op_type": op_type, "opset": self.opset, "n_outputs": 1,
                   "accum_dtype": accum, "subgraph_runner": None,
                   "external_dir": self._external_dir}
            env[node.output[0]] = OPS[op_type](
                [xa, xb] + [env[i] if i else None for i in node.input[2:]],
                node.attrs(), ctx)
            nhwc.add(node.output[0])
            return True

        return False

    def _run_function(self, fdef, call, env: Dict[str, Any], to_std) -> None:
        """Inline-expand a model-local function call: bind formal inputs,
        substitute ``ref_attr_name`` attributes from the call site (falling
        back to ``attribute_proto`` defaults, recursing into subgraph
        attributes), run the body in a private scope under the function's
        own opset, and export the formal outputs."""
        import dataclasses

        for i in call.input:
            to_std(i)
        call_attrs = {a.name: a for a in call.attribute}
        for a in fdef.attribute_proto:  # declared params with defaults
            call_attrs.setdefault(a.name, a)

        def resolve_node(node):
            changed = False
            resolved = []
            for a in node.attribute:
                if a.ref_attr_name:
                    src = call_attrs.get(a.ref_attr_name)
                    if src is not None:
                        resolved.append(dataclasses.replace(src, name=a.name))
                    # absent optional attr: drop (ONNX function semantics)
                    changed = True
                elif a.g is not None or a.graphs:
                    # refs are legal inside If/Loop bodies of the function
                    a2 = dataclasses.replace(
                        a,
                        g=resolve_graph(a.g) if a.g is not None else None,
                        graphs=[resolve_graph(g) for g in a.graphs])
                    resolved.append(a2)
                    changed = True
                else:
                    resolved.append(a)
            return dataclasses.replace(node, attribute=resolved) if changed \
                else node

        def resolve_graph(g):
            return dataclasses.replace(g, node=[resolve_node(n)
                                                for n in g.node])

        fenv: Dict[str, Any] = {"": None}
        for formal in fdef.input:  # trailing optionals may be uncalled
            fenv[formal] = None
        for formal, actual in zip(fdef.input, call.input):
            fenv[formal] = env[actual] if actual else None
        body = GraphProto(
            node=[resolve_node(n) for n in fdef.node],
            output=[ValueInfo(name=o) for o in fdef.output],
        )
        # the body executes under ITS opset (pre-13 bodies keep e.g.
        # attribute-form Unsqueeze even inside an opset-13+ model)
        self._run_graph(body, fenv,
                        opset=fdef.opset_imports.get("") or None)
        for formal, actual in zip(fdef.output, call.output):
            if actual:
                env[actual] = fenv[formal]

    def _run_graph(self, graph: GraphProto, env: Dict[str, Any],
                   opset: "int | None" = None) -> None:
        import jax.numpy as jnp

        opset = self.opset if opset is None else opset
        accum = jnp.float32 if self.dtype_policy == "bfloat16" else None
        nhwc: set = set()  # value names currently stored channels-last

        def to_std(name: str) -> None:
            if name in nhwc:
                env[name] = jnp.transpose(env[name], (0, 3, 1, 2))
                nhwc.discard(name)

        def subgraph_runner(sub: GraphProto):
            def run():
                for name in list(nhwc):  # subgraphs see standard layout
                    to_std(name)
                sub_env = dict(env)
                self._run_graph(sub, sub_env, opset=opset)
                vals = [sub_env[o.name] for o in sub.output]
                return vals[0] if len(vals) == 1 else tuple(vals)

            return run

        for node in graph.node:
            fdef = self.functions.get((node.domain, node.op_type))
            # builtins win only in the standard domains; a custom-domain
            # function whose name collides with a builtin must still expand
            if fdef is not None and (node.domain not in ("", "ai.onnx")
                                     or node.op_type not in OPS):
                self._run_function(fdef, node, env, to_std)
                continue
            try:
                fn = OPS[node.op_type]
            except KeyError:
                raise NotImplementedError(f"unsupported ONNX op {node.op_type}") from None
            if self.channels_last and self._try_nhwc(node, env, nhwc):
                continue
            for i in node.input:  # fallback consumers get standard layout
                to_std(i)
            inputs = [env[i] if i else None for i in node.input]
            ctx = {
                "op_type": node.op_type,
                "opset": opset,
                "n_outputs": len(node.output),
                "accum_dtype": accum,
                "subgraph_runner": subgraph_runner,
                "external_dir": self._external_dir,
            }
            # Constant folding: all-constant inputs => evaluate OUTSIDE the
            # trace (omnistaging would otherwise stage jnp ops on concrete
            # values into tracers) and pin outputs as numpy, so shape chains
            # (Shape -> Gather/Mod/Add -> Reshape -> Slice.ends) stay static.
            const_in = (all(v is None or _is_const(v) for v in inputs)
                        and node.op_type != "Dropout")
            try:
                if const_in:
                    import jax

                    with jax.ensure_compile_time_eval():
                        out = fn(inputs, node.attrs(), ctx)
                else:
                    out = fn(inputs, node.attrs(), ctx)
            except Exception as e:
                raise type(e)(
                    f"while executing node {node.name or '?'} ({node.op_type}) "
                    f"inputs={node.input}: {e}"
                ) from e
            outs = out if isinstance(out, tuple) else (out,)
            if const_in:
                pinned = []
                for o in outs:
                    try:
                        pinned.append(np.asarray(o))
                    except Exception:
                        pinned.append(o)  # traced despite const inputs (subgraph capture)
                outs = tuple(pinned)
            for name, val in zip(node.output, outs):
                if name:
                    env[name] = val
        for vi in graph.output:  # graph outputs leave in standard layout
            to_std(vi.name)


def load_model(path_or_bytes, dtype_policy: str = "float32") -> OnnxFunction:
    """Load an ``.onnx`` file (path or bytes) into an executable function.

    Loading by PATH resolves external-data tensors (``data_location=EXTERNAL``,
    the real-exporter format past protobuf's 2GB limit) relative to the
    model's directory; from raw bytes pass ``external_data_dir`` to
    :class:`OnnxFunction` directly."""
    if isinstance(path_or_bytes, (bytes, bytearray, memoryview)):
        data = bytes(path_or_bytes)
        ext_dir = None
    else:
        import os

        with open(path_or_bytes, "rb") as f:
            data = f.read()
        ext_dir = os.path.dirname(os.path.abspath(path_or_bytes))
    return OnnxFunction(data, dtype_policy=dtype_policy,
                        external_data_dir=ext_dir)
