"""``ONNXModel`` — generic ONNX inference transformer.

Rebuild of ``deep-learning/src/main/scala/.../onnx/ONNXModel.scala`` (685 LoC): feed/
fetch dicts, minibatch→tensor coercion, post-processing (softmax/argmax). Where the
reference opens an ORT session per partition and pays JVM↔native copies per batch
(``applyModel:305-355``), this version compiles the graph once per batch shape and runs
whole batches as single XLA programs on the TPU.

Batching: rows are processed in fixed-size buckets (``batch_size``); the final partial
batch is padded to the bucket and the padding sliced off after — so exactly ONE compiled
executable serves the whole table (the reference pins dim 0 for the same reason,
``ONNXModel.scala:357-362``).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ..core import (ColumnSpec, ComplexParam, Param, Table, TableSchema,
                    Transformer)
from ..core.params import ParamValidators
from .importer import OnnxFunction, model_io_specs

__all__ = ["ONNXModel"]


class ONNXModel(Transformer):
    """Run an ONNX graph over table columns.

    - ``feed_dict``: onnx input name -> table column name
      (reference ``setFeedDict``, ``ONNXModel.scala:122``)
    - ``fetch_dict``: output column name -> onnx output name (``setFetchDict``)
    - ``softmax_dict`` / ``argmax_dict``: output col -> new col post-ops
      (``softMaxDict``/``argMaxDict``, ``ONNXModel.scala:516-562``)
    """

    model_bytes = ComplexParam("serialized ONNX ModelProto", bytes, default=None)
    feed_dict = Param("onnx input name -> table column", dict, default={})
    fetch_dict = Param("output column -> onnx output name", dict, default={})
    batch_size = Param("inference bucket size (pad-to-bucket)", int, default=64,
                       validator=ParamValidators.gt(0))
    dtype_policy = Param("float32 | bfloat16 (MXU-native)", str, default="float32",
                         validator=ParamValidators.in_list(["float32", "bfloat16"]))
    softmax_dict = Param("col -> softmax(col) output col", dict, default={})
    argmax_dict = Param("col -> argmax(col) output col", dict, default={})
    sharding_layout = ComplexParam(
        "optional runtime.layout.SpecLayout: shard MatMul/Gemm/Conv weights "
        "over the layout's 'model' axis (tensor-parallel serving — models "
        "bigger than one chip's HBM)", object, default=None)

    def __init__(self, uid=None, **kw):
        super().__init__(uid=uid, **kw)
        self._fn: Optional[OnnxFunction] = None
        self._io_specs_cache = None

    def _post_load(self):
        self._fn = None
        self._io_specs_cache = None

    def set_model(self, model_bytes: bytes) -> "ONNXModel":
        self.set("model_bytes", bytes(model_bytes))
        self._fn = None
        self._io_specs_cache = None
        return self

    @property
    def fn(self) -> OnnxFunction:
        if self._fn is None:
            if self.model_bytes is None:
                raise ValueError(f"ONNXModel({self.uid}): model_bytes not set")
            self._fn = OnnxFunction(self.model_bytes,
                                    dtype_policy=self.dtype_policy,
                                    layout=self.sharding_layout)
        return self._fn

    # -- static schema (derived from the graph's value_info; NO jax) --------------

    def _io_specs(self):
        """Graph input/output specs via :func:`model_io_specs` — protobuf
        parsing only (so ``Pipeline.validate`` stays jax-free), cached:
        real models carry hundreds of MB of initializers and must not be
        re-parsed per validate() call. The cache is keyed on the current
        ``model_bytes`` OBJECT, so replacing the model through the generic
        ``Params.set`` path (not just :meth:`set_model`) invalidates it."""
        mb = self.model_bytes
        if mb is None:
            raise ValueError(f"ONNXModel({self.uid}): model_bytes not set")
        cache = self._io_specs_cache
        if cache is None or cache[0] is not mb:
            self._io_specs_cache = cache = (mb, model_io_specs(mb))
        return cache[1]

    def _input_schema_from(self, ins) -> TableSchema:
        cols = {}
        for onnx_in, col in self.feed_dict.items():
            dc, role = ins.get(onnx_in, ("any", "any"))
            # a rank-k graph tensor feeds from a per-row rank-(k-1) column,
            # which may also arrive as an object column of arrays — keep
            # the dtype class, relax the role (stacking is _gather_feed's
            # job, the static contract is "this column exists & is dc")
            cols[col] = ColumnSpec(dc, "any" if role == "tensor" else role)
        return TableSchema(cols)

    def input_schema(self) -> "TableSchema | None":
        if not self.feed_dict or self.model_bytes is None:
            return None
        return self._input_schema_from(self._io_specs()[0])

    def transform_schema(self, schema: TableSchema) -> "TableSchema | None":
        # mis-wiring raises SchemaError so Pipeline.validate wraps it into
        # its documented PipelineSchemaError (naming this stage) instead
        # of letting a bare ValueError escape the plan-time gate
        from ..core.schema import SchemaError

        if self.model_bytes is None or not self.feed_dict \
                or not self.fetch_dict:
            raise SchemaError(
                f"ONNXModel({self.uid}): model_bytes, feed_dict and "
                f"fetch_dict must be set")
        ins, outs = self._io_specs()
        unknown = [k for k in self.feed_dict if k not in ins]
        if unknown:
            raise SchemaError(
                f"ONNXModel({self.uid}): feed_dict keys {unknown} are not "
                f"graph inputs; graph expects {sorted(ins)}")
        missing_out = [n for n in self.fetch_dict.values() if n not in outs]
        if missing_out:
            raise SchemaError(
                f"ONNXModel({self.uid}): fetch_dict outputs {missing_out} "
                f"are not graph outputs; graph produces {sorted(outs)}")
        self._check_schema(schema, self._input_schema_from(ins))
        out = schema
        for col, onnx_name in self.fetch_dict.items():
            dc, role = outs.get(onnx_name, ("any", "any"))
            out = out.with_column(col, ColumnSpec(dc, role))
        for src, dst in self.softmax_dict.items():
            out = out.with_column(dst, ColumnSpec(
                "float", out[src].role if src in out else "any"))
        for src, dst in self.argmax_dict.items():
            out = out.with_column(dst, ColumnSpec("int", "any"))
        return out

    # -- helpers -------------------------------------------------------------------

    def _gather_feed(self, table: Table, col: str) -> np.ndarray:
        arr = table[col]
        if arr.dtype == object:  # ragged/list column -> stack (must be uniform)
            if len(arr) == 0:
                return np.zeros((0,), dtype=np.float32)
            try:
                arr = np.stack([np.asarray(v) for v in arr])
            except ValueError as e:
                raise ValueError(
                    f"ONNXModel({self.uid}): column {col!r} has non-uniform shapes; "
                    f"resize/pad upstream (e.g. ResizeImageTransformer)"
                ) from e
        return arr

    def transform_arrays(self, feeds: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        """Batched execution with pad-to-bucket; returns full-length outputs."""
        fn = self.fn
        n = len(next(iter(feeds.values())))
        if n == 0:  # empty partitions are normal in a partitioned pipeline
            dummy = {}
            shapes = fn.input_shapes()
            for k, v in feeds.items():
                shp = v.shape[1:]
                if not shp and shapes.get(k) and len(shapes[k]) > 1:
                    shp = tuple(s if isinstance(s, int) else 1 for s in shapes[k][1:])
                dt = v.dtype if v.dtype != object else np.float32
                dummy[k] = np.zeros((1,) + tuple(shp), dtype=dt)
            result = fn(dummy)
            out0 = {}
            for col, name in self.fetch_dict.items():
                if name not in result:  # same error as the non-empty path
                    raise ValueError(
                        f"ONNXModel({self.uid}): graph has no output {name!r}; "
                        f"outputs: {list(result)}"
                    )
                out0[col] = np.asarray(result[name])[:0]
            return out0
        b = min(self.batch_size, max(1, n))
        out_parts: Dict[str, List[np.ndarray]] = {k: [] for k in self.fetch_dict}
        for lo in range(0, n, b):
            hi = min(lo + b, n)
            batch = {k: v[lo:hi] for k, v in feeds.items()}
            pad = b - (hi - lo)
            if pad:
                batch = {
                    k: np.concatenate([v, np.repeat(v[-1:], pad, axis=0)]) for k, v in batch.items()
                }
            result = fn(batch)
            for out_col, onnx_name in self.fetch_dict.items():
                if onnx_name not in result:
                    raise ValueError(
                        f"ONNXModel({self.uid}): graph has no output {onnx_name!r}; "
                        f"outputs: {list(result)}"
                    )
                r = np.asarray(result[onnx_name])
                out_parts[out_col].append(r[: hi - lo] if pad else r)
        return {k: np.concatenate(v, axis=0) for k, v in out_parts.items()}

    # -- transform -----------------------------------------------------------------

    def _transform(self, table: Table) -> Table:
        if not self.feed_dict or not self.fetch_dict:
            raise ValueError(f"ONNXModel({self.uid}): feed_dict and fetch_dict must be set")
        unknown = [k for k in self.feed_dict if k not in self.fn.input_names]
        if unknown:
            raise ValueError(
                f"ONNXModel({self.uid}): feed_dict keys {unknown} are not graph inputs; "
                f"graph expects {self.fn.input_names}"
            )
        for onnx_in, col in self.feed_dict.items():
            self._validate_input(table, col)
        feeds = {onnx_in: self._gather_feed(table, col) for onnx_in, col in self.feed_dict.items()}
        outputs = self.transform_arrays(feeds)
        out = table
        for col, arr in outputs.items():
            out = out.with_column(col, arr)
        for src, dst in self.softmax_dict.items():
            x = np.asarray(out[src], dtype=np.float64)
            x = x - x.max(axis=-1, keepdims=True)
            e = np.exp(x)
            out = out.with_column(dst, (e / e.sum(axis=-1, keepdims=True)).astype(np.float32))
        for src, dst in self.argmax_dict.items():
            out = out.with_column(dst, np.argmax(np.asarray(out[src]), axis=-1).astype(np.int64))
        return out
