"""Programmatic ONNX graph construction.

Test/bench-side counterpart of the wire codec: build ``ModelProto`` structures in python
(nodes, initializers, value infos) and serialize them to real ``.onnx`` bytes. Used by
the unit tests (which cross-check the importer against torch reference outputs) and by
the model zoo (``synapseml_tpu.models``) to materialize ResNet/BERT-class graphs without
network access. API shape is deliberately close to ``onnx.helper`` so models written
against it port trivially.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from .wire import (
    AttributeProto,
    DataType,
    GraphProto,
    ModelProto,
    NodeProto,
    TensorProto,
    ValueInfo,
    numpy_to_tensor,
    serialize_model,
)

__all__ = ["node", "make_graph", "make_model", "value_info", "constant_node", "save_model"]


def _attr(name: str, v: Any) -> AttributeProto:
    a = AttributeProto(name=name)
    if isinstance(v, TensorProto):
        a.type, a.t = 4, v
    elif isinstance(v, GraphProto):
        a.type, a.g = 5, v
    elif isinstance(v, bool):
        a.type, a.i = 2, int(v)
    elif isinstance(v, (int, np.integer)):
        a.type, a.i = 2, int(v)
    elif isinstance(v, (float, np.floating)):
        a.type, a.f = 1, float(v)
    elif isinstance(v, str):
        a.type, a.s = 3, v.encode("utf-8")
    elif isinstance(v, (list, tuple, np.ndarray)):
        seq = list(v)
        if all(isinstance(x, (int, np.integer)) for x in seq):
            a.type, a.ints = 7, [int(x) for x in seq]
        elif all(isinstance(x, (float, np.floating, int, np.integer)) for x in seq):
            a.type, a.floats = 6, [float(x) for x in seq]
        elif all(isinstance(x, str) for x in seq):
            a.type, a.strings = 8, [x.encode("utf-8") for x in seq]
        else:
            raise TypeError(f"attribute {name}: unsupported sequence {seq[:3]}")
    else:
        raise TypeError(f"attribute {name}: unsupported type {type(v)}")
    return a


def node(op_type: str, inputs: Sequence[str], outputs: Sequence[str],
         name: str = "", **attrs) -> NodeProto:
    return NodeProto(
        op_type=op_type,
        name=name or f"{op_type}_{outputs[0] if outputs else ''}",
        input=list(inputs),
        output=list(outputs),
        attribute=[_attr(k, v) for k, v in attrs.items() if v is not None],
    )


def value_info(name: str, dtype=np.float32, shape: Optional[Sequence[Any]] = None) -> ValueInfo:
    return ValueInfo(name=name, elem_type=DataType.from_numpy(dtype),
                     shape=list(shape) if shape is not None else None)


def constant_node(output: str, arr: np.ndarray) -> NodeProto:
    return node("Constant", [], [output], value=numpy_to_tensor(output, np.asarray(arr)))


def make_graph(nodes: Sequence[NodeProto], name: str,
               inputs: Sequence[ValueInfo], outputs: Sequence[ValueInfo],
               initializers: Optional[Dict[str, np.ndarray]] = None) -> GraphProto:
    return GraphProto(
        name=name,
        node=list(nodes),
        input=list(inputs),
        output=list(outputs),
        initializer=[numpy_to_tensor(k, np.asarray(v)) for k, v in (initializers or {}).items()],
    )


def make_model(graph: GraphProto, opset: int = 17, producer: str = "synapseml_tpu") -> ModelProto:
    return ModelProto(ir_version=8, producer_name=producer, graph=graph,
                      opset_imports={"": opset})


def save_model(model: ModelProto, path: str) -> None:
    with open(path, "wb") as f:
        f.write(serialize_model(model))
