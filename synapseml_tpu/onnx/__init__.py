"""ONNX engine: wire codec, builder, JAX importer/executor, ONNXModel transformer."""

from ..core.lazyimport import lazy_module

# PEP 562 lazy exports (lint SMT008): attribute access imports the owning
# submodule on demand, keeping `import synapseml_tpu.onnx` jax-free
__getattr__, __dir__, __all__ = lazy_module(__name__, {
    "builder": ["constant_node", "make_graph", "make_model", "node",
                "save_model", "value_info"],
    "importer": ["OnnxFunction", "load_model"],
    "model": ["ONNXModel"],
    "wire": ["DataType", "ModelProto", "parse_model", "serialize_model",
             "tensor_to_numpy"],
})
