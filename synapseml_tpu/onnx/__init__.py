"""ONNX engine: wire codec, builder, JAX importer/executor, ONNXModel transformer."""

from .builder import constant_node, make_graph, make_model, node, save_model, value_info
from .importer import OnnxFunction, load_model
from .model import ONNXModel
from .wire import DataType, ModelProto, parse_model, serialize_model, tensor_to_numpy

__all__ = [
    "OnnxFunction",
    "load_model",
    "ONNXModel",
    "DataType",
    "ModelProto",
    "parse_model",
    "serialize_model",
    "tensor_to_numpy",
    "node",
    "make_graph",
    "make_model",
    "value_info",
    "constant_node",
    "save_model",
]
