"""Native (C++) kernel library + loader.

Rebuild of the reference's native-library mechanism: ``NativeLoader``
(``core/src/main/java/.../core/env/NativeLoader.java`` extracts ``.so`` files from
the jar and ``System.load``s them). Here the shared object is built once per
machine from the checked-in C++ sources (``python -m synapseml_tpu.native.build``)
and loaded with ctypes; every consumer has a pure-numpy fallback so the framework
works (slower) without the toolchain.
"""

from .loader import NativeLib, get_lib, murmur3_32, murmur3_32_batch

__all__ = ["NativeLib", "get_lib", "murmur3_32", "murmur3_32_batch"]
