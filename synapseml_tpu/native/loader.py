"""ctypes loader + numpy fallbacks for the native kernels.

The loader auto-builds the ``.so`` on first use when a toolchain is present
(reference ``NativeLoader`` extracts-and-loads per JVM; here it is build-and-load
per machine, cached on disk). All entry points are also implemented in pure
numpy so the package never hard-requires the native path — parity between the two
is asserted by tests.
"""

from __future__ import annotations

import ctypes
import logging
import os
import threading
from typing import List, Optional, Sequence

import numpy as np

_logger = logging.getLogger("synapseml_tpu.native")

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO_PATH = os.path.join(_HERE, "_smt_native.so")


class NativeLib:
    """Lazily built+loaded handle to ``_smt_native.so``."""

    _instance: Optional["NativeLib"] = None
    _load_failed = False  # cache failures: never retry the compile per call
    _lock = threading.Lock()

    def __init__(self, cdll):
        self.cdll = cdll
        self.cdll.smt_murmur3_32.restype = ctypes.c_uint32
        self.cdll.smt_murmur3_32.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_uint32,
        ]

    @classmethod
    def load(cls) -> Optional["NativeLib"]:
        with cls._lock:
            if cls._instance is not None:
                return cls._instance
            if cls._load_failed:
                return None
            if not os.path.exists(_SO_PATH):
                try:
                    from .build import build

                    build(verbose=False)
                except Exception as e:  # no toolchain / build failure -> fallback
                    _logger.info("native build unavailable (%s); using numpy fallback", e)
                    cls._load_failed = True
                    return None
            try:
                cls._instance = NativeLib(ctypes.CDLL(_SO_PATH))
            except OSError as e:
                _logger.warning("failed to load %s (%s); using numpy fallback", _SO_PATH, e)
                cls._load_failed = True
                return None
            return cls._instance


def get_lib() -> Optional[NativeLib]:
    return NativeLib.load()


# -- murmur3 -----------------------------------------------------------------------

def _murmur3_32_py(data: bytes, seed: int) -> int:
    """Pure-python MurmurHash3 x86/32 (bit-exact with the C++ kernel)."""
    c1, c2 = 0xCC9E2D51, 0x1B873593
    h = seed & 0xFFFFFFFF
    length = len(data)
    rounded = length & ~3
    for i in range(0, rounded, 4):
        k = int.from_bytes(data[i:i + 4], "little")
        k = (k * c1) & 0xFFFFFFFF
        k = ((k << 15) | (k >> 17)) & 0xFFFFFFFF
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
        h = ((h << 13) | (h >> 19)) & 0xFFFFFFFF
        h = (h * 5 + 0xE6546B64) & 0xFFFFFFFF
    k = 0
    tail = length & 3
    if tail >= 3:
        k ^= data[rounded + 2] << 16
    if tail >= 2:
        k ^= data[rounded + 1] << 8
    if tail >= 1:
        k ^= data[rounded]
        k = (k * c1) & 0xFFFFFFFF
        k = ((k << 15) | (k >> 17)) & 0xFFFFFFFF
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
    h ^= length
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & 0xFFFFFFFF
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & 0xFFFFFFFF
    h ^= h >> 16
    return h


def murmur3_32(data, seed: int = 0) -> int:
    """Hash one string/bytes value."""
    if isinstance(data, str):
        data = data.encode("utf-8")
    lib = get_lib()
    if lib is not None:
        return int(lib.cdll.smt_murmur3_32(data, len(data), seed & 0xFFFFFFFF))
    return _murmur3_32_py(data, seed)


def murmur3_32_batch(strings: Sequence, seeds=0) -> np.ndarray:
    """Hash a sequence of strings -> uint32 array. ``seeds``: scalar or per-string."""
    enc: List[bytes] = [
        s if isinstance(s, bytes) else str(s).encode("utf-8") for s in strings
    ]
    n = len(enc)
    per_seed = not np.isscalar(seeds)
    lib = get_lib()
    if lib is None:
        if per_seed:
            return np.array(
                [_murmur3_32_py(b, int(s)) for b, s in zip(enc, seeds)], dtype=np.uint32
            )
        return np.array([_murmur3_32_py(b, int(seeds)) for b in enc], dtype=np.uint32)
    buf = b"".join(enc)
    offsets = np.zeros(n + 1, dtype=np.int64)
    np.cumsum([len(b) for b in enc], out=offsets[1:])
    out = np.empty(n, dtype=np.uint32)
    if per_seed:
        seed_arr = np.asarray(seeds, dtype=np.uint32)
        lib.cdll.smt_murmur3_32_batch_seeded(
            buf, offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), n,
            seed_arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        )
    else:
        lib.cdll.smt_murmur3_32_batch(
            buf, offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), n,
            ctypes.c_uint32(int(seeds) & 0xFFFFFFFF),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        )
    return out
