"""Build the native shared library: ``python -m synapseml_tpu.native.build``.

Compiles ``src/*.cpp`` into ``_smt_native.so`` next to this file with g++ (the
image's baked-in toolchain; no pybind11 — the ABI is plain C via ctypes).
"""

from __future__ import annotations

import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
SRC_DIR = os.path.join(HERE, "src")
OUT = os.path.join(HERE, "_smt_native.so")


def build(verbose: bool = True) -> str:
    sources = sorted(
        os.path.join(SRC_DIR, f) for f in os.listdir(SRC_DIR) if f.endswith(".cpp")
    )
    cmd = [
        "g++", "-O3", "-fPIC", "-shared", "-std=c++17", "-march=native",
        *sources, "-o", OUT,
    ]
    if verbose:
        print(" ".join(cmd), file=sys.stderr)
    subprocess.run(cmd, check=True)
    return OUT


if __name__ == "__main__":
    build()
