// MurmurHash3 x86 32-bit + batch entry points for the VW-style featurizer.
//
// The reference ships VW's C++ core (vw-jni) whose feature hashing is murmur3
// (`vw/.../VowpalWabbitMurmurWithPrefix.scala` wraps it on the Scala side). This is
// a from-scratch implementation of the public MurmurHash3 algorithm (Austin Appleby,
// public domain) with a batch API: one contiguous UTF-8 buffer + offsets in, uint32
// hashes out. Loaded via ctypes (no pybind11 in the image).

#include <cstdint>
#include <cstring>

static inline uint32_t rotl32(uint32_t x, int8_t r) {
  return (x << r) | (x >> (32 - r));
}

static inline uint32_t fmix32(uint32_t h) {
  h ^= h >> 16;
  h *= 0x85ebca6b;
  h ^= h >> 13;
  h *= 0xc2b2ae35;
  h ^= h >> 16;
  return h;
}

extern "C" {

uint32_t smt_murmur3_32(const uint8_t* data, int64_t len, uint32_t seed) {
  const int64_t nblocks = len / 4;
  uint32_t h1 = seed;
  const uint32_t c1 = 0xcc9e2d51;
  const uint32_t c2 = 0x1b873593;

  for (int64_t i = 0; i < nblocks; i++) {
    uint32_t k1;
    std::memcpy(&k1, data + i * 4, 4);
    k1 *= c1;
    k1 = rotl32(k1, 15);
    k1 *= c2;
    h1 ^= k1;
    h1 = rotl32(h1, 13);
    h1 = h1 * 5 + 0xe6546b64;
  }

  const uint8_t* tail = data + nblocks * 4;
  uint32_t k1 = 0;
  switch (len & 3) {
    case 3: k1 ^= tail[2] << 16; [[fallthrough]];
    case 2: k1 ^= tail[1] << 8; [[fallthrough]];
    case 1:
      k1 ^= tail[0];
      k1 *= c1;
      k1 = rotl32(k1, 15);
      k1 *= c2;
      h1 ^= k1;
  }

  h1 ^= (uint32_t)len;
  return fmix32(h1);
}

// Batch: buf holds n concatenated byte strings; offsets has n+1 entries.
void smt_murmur3_32_batch(const uint8_t* buf, const int64_t* offsets, int64_t n,
                          uint32_t seed, uint32_t* out) {
  for (int64_t i = 0; i < n; i++) {
    out[i] = smt_murmur3_32(buf + offsets[i], offsets[i + 1] - offsets[i], seed);
  }
}

// Batch with per-string seeds (namespace-seeded hashing).
void smt_murmur3_32_batch_seeded(const uint8_t* buf, const int64_t* offsets,
                                 int64_t n, const uint32_t* seeds, uint32_t* out) {
  for (int64_t i = 0; i < n; i++) {
    out[i] = smt_murmur3_32(buf + offsets[i], offsets[i + 1] - offsets[i], seeds[i]);
  }
}

}  // extern "C"
