"""Pallas flash attention — the TPU kernel for long-sequence inference.

SURVEY.md §5 marks long-context support as net-new; ``ring.py`` provides the
cross-chip recipes (ppermute ring / all-to-all). This module provides the
ON-CHIP kernel: blockwise attention with online softmax running entirely in
VMEM, so the (S_q, S_k) score matrix never materializes in HBM. XLA's dense
attention allocates the full score tensor per head — at S=8k, H=12 that is
B * 12 * 8k * 8k * 4 bytes = 3 GB HBM traffic per batch element; the flash
kernel streams K/V blocks through VMEM instead (the standard
memory-bound-to-compute-bound move).

Layout: inputs (B, S, H, D) like ``ring.py``; the kernel runs per (batch,
head) over query blocks, with a ``lax.fori_loop`` over key blocks carrying
the (m, l, acc) online-softmax state as register values. Masking uses a
finite ``-1e30`` (an actual ``-inf`` makes ``exp(m - m_new)`` produce NaN
for fully-masked leading causal rows).

``interpret=True`` runs the same kernel through the Pallas interpreter on
CPU — the parity tests exercise the kernel logic without TPU hardware.
"""

from __future__ import annotations

import functools
import math

__all__ = ["flash_attention", "dense_attention"]

_NEG = -1e30


def dense_attention(q, k, v, causal: bool = False, pv_dtype=None):
    """Reference dense attention, (B, S, H, D) layout, f32 accumulation.

    ``pv_dtype`` casts the probabilities for the P@V matmul (e.g. bf16 —
    the performant-XLA baseline bench.py compares flash against; the flash
    kernel makes the same cast). Default keeps everything f32 (the exact
    parity reference the tests use)."""
    import jax.numpy as jnp

    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bqhk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        S_q, S_k = s.shape[1], s.shape[3]
        mask = (jnp.arange(S_q)[:, None] + (S_k - S_q)
                >= jnp.arange(S_k)[None, :])
        s = jnp.where(mask[None, :, None, :], s, _NEG)
    p = jnp.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    if pv_dtype is not None:
        p = p.astype(pv_dtype)
        out = jnp.einsum("bqhk,bkhd->bqhd", p, v.astype(pv_dtype))
    else:
        out = jnp.einsum("bqhk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


def flash_attention(q, k, v, causal: bool = False, block_q: int = 512,
                    block_k: int = 512, interpret: bool = False):
    """Blockwise-online-softmax attention as ONE Pallas kernel.

    ``q`` (B, S_q, H, D), ``k``/``v`` (B, S_k, H, D) -> (B, S_q, H, D).
    ``causal`` aligns the diagonal to the END of the key sequence (queries
    are the LAST S_q positions), matching decode/ring conventions. Block
    sizes must divide the respective sequence lengths.

    ``bench.py``'s ``flash_attention_32k`` config records throughput on the
    round's TPU; at short S the kernel is dispatch-bound and roughly ties
    XLA's dense attention, so it is the long-sequence path (dense attention
    at S=32k would need ~34 GB for the score tensor alone).
    """
    import jax.numpy as jnp

    b, s_q, h, d = q.shape
    s_k = k.shape[1]
    if k.shape != (b, s_k, h, d) or v.shape != (b, s_k, h, d):
        raise ValueError(f"shape mismatch: q {q.shape}, k {k.shape}, "
                         f"v {v.shape}")
    if causal and s_q > s_k:
        # queries are the LAST s_q positions of the key sequence; more
        # queries than keys would leave leading rows with no visible key
        # (and silently all-zero outputs)
        raise ValueError(f"causal flash attention needs s_q <= s_k, got "
                         f"s_q={s_q} > s_k={s_k}")
    block_q = min(block_q, s_q)
    block_k = min(block_k, s_k)
    if s_q % block_q or s_k % block_k:
        raise ValueError(f"block sizes ({block_q}, {block_k}) must divide "
                         f"sequence lengths ({s_q}, {s_k})")

    # (B, S, H, D) -> (B*H, S, D): batch*head is the embarrassing grid axis
    def to_bh(x):
        return jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, x.shape[1], d)

    out = _flash_bh(to_bh(q), to_bh(k), to_bh(v), bool(causal), int(block_q),
                    int(block_k), bool(interpret))
    return (out.reshape(b, h, s_q, d).transpose(0, 2, 1, 3)).astype(q.dtype)


@functools.lru_cache(maxsize=1)
def _flash_bh_jit():
    """jax.jit applied lazily so importing the package never imports jax."""
    import jax

    return jax.jit(_flash_bh_impl,
                   static_argnames=("causal", "block_q", "block_k",
                                    "interpret"))


def _flash_bh(q, k, v, causal, block_q, block_k, interpret):
    return _flash_bh_jit()(q, k, v, causal=causal, block_q=block_q,
                           block_k=block_k, interpret=interpret)


def _flash_bh_impl(q, k, v, causal, block_q, block_k, interpret):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, s_q, d = q.shape
    s_k = k.shape[1]
    scale = 1.0 / math.sqrt(d)
    nk = s_k // block_k
    # causal diagonal sits at the END of the key axis (ring/decode layout)
    diag_off = s_k - s_q

    # bf16 inputs run the two dots at the MXU's native rate with f32
    # accumulation (p is cast to the value dtype for the PV dot — the
    # standard flash-kernel precision tradeoff); f32 inputs stay exact
    in_dt = q.dtype

    def kernel(q_ref, k_ref, v_ref, o_ref, ml_s, acc_s):
        # one (block_q, 128) scratch holds BOTH online-softmax carries (m in
        # lane 0, l in lane 1): each needs a single lane, and the saved
        # block_q x 128 f32 buffer is what lets 2k-wide blocks fit scoped
        # VMEM
        iq = pl.program_id(1)
        jk = pl.program_id(2)

        @pl.when(jk == 0)
        def _():
            ml_s[:, 0:1] = jnp.full((block_q, 1), _NEG, jnp.float32)
            ml_s[:, 1:2] = jnp.zeros((block_q, 1), jnp.float32)
            acc_s[:] = jnp.zeros_like(acc_s)

        def compute():
            qb = q_ref[0]                                    # (bq, d)
            kb = k_ref[0]
            vb = v_ref[0]
            s = jax.lax.dot_general(qb, kb, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32)
            s = s * scale
            if causal:
                qpos = iq * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0) + diag_off
                kpos = jk * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1)
                s = jnp.where(qpos >= kpos, s, _NEG)
            m = ml_s[:, 0:1]
            m_new = jnp.maximum(m, s.max(-1, keepdims=True))
            p = jnp.exp(s - m_new)
            corr = jnp.exp(m - m_new)
            ml_s[:, 1:2] = ml_s[:, 1:2] * corr + p.sum(-1, keepdims=True)
            acc_s[:] = acc_s[:] * corr + jax.lax.dot_general(
                p.astype(in_dt), vb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            ml_s[:, 0:1] = m_new

        if causal:
            # key blocks strictly above the diagonal contribute nothing
            first_masked = ((iq + 1) * block_q + diag_off
                            + block_k - 1) // block_k
            pl.when(jk < first_masked)(compute)
        else:
            compute()

        @pl.when(jk == pl.num_programs(2) - 1)
        def _():
            o_ref[0] = (acc_s[:] / jnp.maximum(ml_s[:, 1:2], 1e-30)
                        ).astype(o_ref.dtype)

    grid = (bh, s_q // block_q, nk)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bhi, i, j: (bhi, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda bhi, i, j: (bhi, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda bhi, i, j: (bhi, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d),
                               lambda bhi, i, j: (bhi, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s_q, d), jnp.float32),
        scratch_shapes=[
            # running max (lane 0) + denominator (lane 1)
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),    # running numerator
        ],
        compiler_params=None if interpret else pltpu.CompilerParams(
            # bh/q-block steps are independent; only the key-block walk
            # carries state -> Mosaic can pipeline block DMAs across steps
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v)
