"""Pallas flash attention — the TPU kernel for long-sequence inference.

SURVEY.md §5 marks long-context support as net-new; ``ring.py`` provides the
cross-chip recipes (ppermute ring / all-to-all). This module provides the
ON-CHIP kernel: blockwise attention with online softmax running entirely in
VMEM, so the (S_q, S_k) score matrix never materializes in HBM. XLA's dense
attention allocates the full score tensor per head — at S=8k, H=12 that is
B * 12 * 8k * 8k * 4 bytes = 3 GB HBM traffic per batch element; the flash
kernel streams K/V blocks through VMEM instead (the standard
memory-bound-to-compute-bound move).

Layout: inputs (B, S, H, D) like ``ring.py``; the kernel runs per (batch,
head) over query blocks, with a ``lax.fori_loop`` over key blocks carrying
the (m, l, acc) online-softmax state as register values. Masking uses a
finite ``-1e30`` (an actual ``-inf`` makes ``exp(m - m_new)`` produce NaN
for fully-masked leading causal rows).

``interpret=True`` runs the same kernel through the Pallas interpreter on
CPU — the parity tests exercise the kernel logic without TPU hardware.
"""

from __future__ import annotations

import functools
import math

__all__ = ["flash_attention", "dense_attention"]

_NEG = -1e30


def dense_attention(q, k, v, causal: bool = False, pv_dtype=None):
    """Reference dense attention, (B, S, H, D) layout, f32 accumulation.

    ``pv_dtype`` casts the probabilities for the P@V matmul (e.g. bf16 —
    the performant-XLA baseline bench.py compares flash against; the flash
    kernel makes the same cast). Default keeps everything f32 (the exact
    parity reference the tests use)."""
    import jax.numpy as jnp

    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bqhk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        S_q, S_k = s.shape[1], s.shape[3]
        mask = (jnp.arange(S_q)[:, None] + (S_k - S_q)
                >= jnp.arange(S_k)[None, :])
        s = jnp.where(mask[None, :, None, :], s, _NEG)
    p = jnp.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    if pv_dtype is not None:
        p = p.astype(pv_dtype)
        out = jnp.einsum("bqhk,bkhd->bqhd", p, v.astype(pv_dtype))
    else:
        out = jnp.einsum("bqhk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


def _pow2_divisor(s: int, cap: int) -> int:
    """Largest power-of-2 divisor of ``s`` that is <= cap."""
    b = cap
    while b > 1 and s % b:
        b //= 2
    return b


def _pick_blocks(bh: int, s_q: int, s_k: int):
    """Block sizes tuned from the r5 TPU v5e sweep (bench.py harness,
    single-dispatch timing):

    - small grids (bh < 32) at long S are latency-bound per grid step —
      wide (2048, 1024) q/k blocks win (S=32k, B=1, H=8: 31.5 ms / 0.354
      MFU vs 41 ms at (2048, 512));
    - bigger grids (serving batches, B*H >= 32) saturate with (1024, 1024)
      AND must stay there: (2048, 512) at bh=64 exceeds the 16 MB scoped
      VMEM limit (B=8, S=8k OOM'd in the sweep);
    - everything clamps to power-of-2 divisors of the sequence lengths.
    """
    bq_target = 2048 if (bh < 32 and s_q >= 16384) else 1024
    return (_pow2_divisor(s_q, bq_target), _pow2_divisor(s_k, 1024))


def flash_attention(q, k, v, causal: bool = False, block_q: int = None,
                    block_k: int = None, interpret: bool = False):
    """Blockwise-online-softmax attention as ONE Pallas kernel.

    ``q`` (B, S_q, H, D), ``k``/``v`` (B, S_k, H_kv, D) -> (B, S_q, H, D).
    ``H_kv`` may divide ``H`` (grouped-query attention): the kernel maps
    each query head's grid step onto its K/V group IN-KERNEL via the block
    index map, so grouped K/V are never expanded in HBM (Llama/Mistral
    checkpoints pay 1/group of the K/V bandwidth).

    ``causal`` aligns the diagonal to the END of the key sequence (queries
    are the LAST S_q positions), matching decode/ring conventions. Block
    sizes default to the r5 sweep's auto-pick (:func:`_pick_blocks`);
    explicit values must divide the sequence lengths.

    ``bench.py``'s ``flash_attention_32k`` config records throughput on the
    round's TPU; at short S the kernel is dispatch-bound and roughly ties
    XLA's dense attention, so it is the long-sequence path (dense attention
    at S=32k would need ~34 GB for the score tensor alone).
    """
    import jax.numpy as jnp

    b, s_q, h, d = q.shape
    s_k = k.shape[1]
    h_kv = k.shape[2]
    if k.shape != (b, s_k, h_kv, d) or v.shape != (b, s_k, h_kv, d):
        raise ValueError(f"shape mismatch: q {q.shape}, k {k.shape}, "
                         f"v {v.shape}")
    if h % h_kv:
        raise ValueError(f"query heads {h} must be a multiple of kv heads "
                         f"{h_kv} (GQA groups)")
    rep = h // h_kv
    if causal and s_q > s_k:
        # queries are the LAST s_q positions of the key sequence; more
        # queries than keys would leave leading rows with no visible key
        # (and silently all-zero outputs)
        raise ValueError(f"causal flash attention needs s_q <= s_k, got "
                         f"s_q={s_q} > s_k={s_k}")
    req_q, req_k = block_q, block_k  # the USER's values, pre-clamp
    auto_bq, auto_bk = _pick_blocks(b * h, s_q, s_k)
    block_q = min(block_q or auto_bq, s_q)  # each side auto-fills alone
    block_k = min(block_k or auto_bk, s_k)
    if s_q % block_q or s_k % block_k:
        raise ValueError(f"block sizes ({block_q}, {block_k}) must divide "
                         f"sequence lengths ({s_q}, {s_k})")
    # Mosaic tile minimum: the (block_q, block_k) score tile needs >= 8
    # sublanes and >= 128 lanes. Awkward sequence lengths with few
    # power-of-2 factors (S=1200 -> 16, odd S -> 1) used to auto-pick
    # sub-tile blocks and die inside Mosaic (or crawl); the Pallas
    # interpreter has no such minimum, so CPU parity tests keep passing
    # small explicit blocks with interpret=True.
    if not interpret and (block_q < 8 or block_k < 128):
        # raise only for blocks the USER requested below the minimum; a
        # legal explicit block that a short sequence clamped down
        # (block_k=1024 at s_k=64) takes the dense fallback like the auto
        # path — "pass bigger blocks" would be unsatisfiable advice there
        if (req_q is not None and req_q < 8) or \
                (req_k is not None and req_k < 128):
            raise ValueError(
                f"flash_attention blocks ({block_q}, {block_k}) are below "
                f"Mosaic's (8, 128) tile minimum; pass blocks that divide "
                f"the sequence lengths ({s_q}, {s_k}) and meet the minimum, "
                f"or use interpret=True / dense_attention")
        # auto-picked sub-tile (the sequence length simply has no legal
        # block): fall back to dense attention when its score tensor is
        # affordable — a long ODD sequence would OOM in dense with an
        # equally opaque error, so that case raises with the fix named
        if 4 * b * h * s_q * s_k > 2e9:
            raise ValueError(
                f"flash_attention cannot tile sequence lengths ({s_q}, "
                f"{s_k}): the largest power-of-2 block divisors "
                f"({block_q}, {block_k}) are below Mosaic's (8, 128) tile "
                f"minimum, and the lengths are too large for the dense "
                f"fallback. Pad the sequences to a multiple of 128.")
        if rep != 1:  # dense needs matching head counts: expand GQA K/V
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        return dense_attention(q, k, v, causal=causal)

    # (B, S, H, D) -> (B*H, S, D): batch*head is the embarrassing grid axis.
    # K/V keep their GROUPED head count; the kernel's index map divides.
    def to_bh(x):
        return jnp.transpose(x, (0, 2, 1, 3)).reshape(
            b * x.shape[2], x.shape[1], d)

    out = _flash_bh(to_bh(q), to_bh(k), to_bh(v), bool(causal), int(block_q),
                    int(block_k), int(rep), bool(interpret))
    return (out.reshape(b, h, s_q, d).transpose(0, 2, 1, 3)).astype(q.dtype)


@functools.lru_cache(maxsize=1)
def _flash_bh_jit():
    """Profiled jit entry point, applied lazily so importing the package
    never imports jax. ``observability.profiling`` times every compile
    (``smt_compile_seconds{fn="flash.attention"}``), counts recompiles by
    the signature change that caused them (block-size churn shows up as
    ``cause="static"``), and caches cost_analysis FLOPs so serving spans
    report the kernel's achieved MFU."""
    from ..observability.profiling import profiled_jit

    return profiled_jit(_flash_bh_impl, name="flash.attention",
                        static_argnames=("causal", "block_q", "block_k",
                                         "rep", "interpret"))


def _flash_bh(q, k, v, causal, block_q, block_k, rep, interpret):
    return _flash_bh_jit()(q, k, v, causal=causal, block_q=block_q,
                           block_k=block_k, rep=rep, interpret=interpret)


def _flash_bh_impl(q, k, v, causal, block_q, block_k, rep, interpret):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, s_q, d = q.shape
    s_k = k.shape[1]
    scale = 1.0 / math.sqrt(d)
    nk = s_k // block_k
    # causal diagonal sits at the END of the key axis (ring/decode layout)
    diag_off = s_k - s_q

    # bf16 inputs run the two dots at the MXU's native rate with f32
    # accumulation (p is cast to the value dtype for the PV dot — the
    # standard flash-kernel precision tradeoff); f32 inputs stay exact
    in_dt = q.dtype

    def kernel(q_ref, k_ref, v_ref, o_ref, ml_s, acc_s):
        # one (block_q, 128) scratch holds BOTH online-softmax carries (m in
        # lane 0, l in lane 1): each needs a single lane, and the saved
        # block_q x 128 f32 buffer is what lets 2k-wide blocks fit scoped
        # VMEM
        iq = pl.program_id(1)
        jk = pl.program_id(2)

        @pl.when(jk == 0)
        def _():
            ml_s[:, 0:1] = jnp.full((block_q, 1), _NEG, jnp.float32)
            ml_s[:, 1:2] = jnp.zeros((block_q, 1), jnp.float32)
            acc_s[:] = jnp.zeros_like(acc_s)

        def compute():
            qb = q_ref[0]                                    # (bq, d)
            kb = k_ref[0]
            vb = v_ref[0]
            s = jax.lax.dot_general(qb, kb, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32)
            s = s * scale
            if causal:
                qpos = iq * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0) + diag_off
                kpos = jk * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1)
                s = jnp.where(qpos >= kpos, s, _NEG)
            m = ml_s[:, 0:1]
            m_new = jnp.maximum(m, s.max(-1, keepdims=True))
            p = jnp.exp(s - m_new)
            corr = jnp.exp(m - m_new)
            ml_s[:, 1:2] = ml_s[:, 1:2] * corr + p.sum(-1, keepdims=True)
            acc_s[:] = acc_s[:] * corr + jax.lax.dot_general(
                p.astype(in_dt), vb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            ml_s[:, 0:1] = m_new

        if causal:
            # key blocks strictly above the diagonal contribute nothing
            first_masked = ((iq + 1) * block_q + diag_off
                            + block_k - 1) // block_k
            pl.when(jk < first_masked)(compute)
        else:
            compute()

        @pl.when(jk == pl.num_programs(2) - 1)
        def _():
            o_ref[0] = (acc_s[:] / jnp.maximum(ml_s[:, 1:2], 1e-30)
                        ).astype(o_ref.dtype)

    grid = (bh, s_q // block_q, nk)
    # GQA: query-head grid step bhi reads K/V group bhi // rep — since
    # h = rep * h_kv, (batch*h + head) // rep == batch*h_kv + head//rep,
    # so one integer divide maps flattened (b, h) onto flattened (b, h_kv);
    # the grouped K/V are never expanded in HBM. rep == 1 keeps the plain
    # identity map (a division in the index map can pessimize Mosaic's
    # block-revisit analysis).
    if rep == 1:
        kv_map = lambda bhi, i, j: (bhi, j, 0)
    else:
        kv_map = lambda bhi, i, j: (bhi // rep, j, 0)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bhi, i, j: (bhi, i, 0)),
            pl.BlockSpec((1, block_k, d), kv_map),
            pl.BlockSpec((1, block_k, d), kv_map),
        ],
        out_specs=pl.BlockSpec((1, block_q, d),
                               lambda bhi, i, j: (bhi, i, 0)),
        # output in the INPUT dtype: the caller casts to q.dtype anyway, and
        # the f32 out block was what pushed (2048, 1024) past the 16 MB
        # scoped-VMEM limit when operands arrive as arguments (r5)
        out_shape=jax.ShapeDtypeStruct((bh, s_q, d), q.dtype),
        scratch_shapes=[
            # running max (lane 0) + denominator (lane 1)
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),    # running numerator
        ],
        compiler_params=None if interpret else pltpu.CompilerParams(
            # bh/q-block steps are independent; only the key-block walk
            # carries state -> Mosaic can pipeline block DMAs across steps
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v)
