"""Ring + Ulysses (all-to-all) sequence-parallel attention.

Design (the standard TPU recipe — mesh axis over the sequence dimension,
collectives over ICI):

- **Ring attention**: each shard keeps its query block resident and passes
  K/V blocks around the ring with ``lax.ppermute`` while accumulating
  flash-style online softmax (running max ``m``, denominator ``l``,
  numerator ``acc``). Peak memory per chip is one K/V block — sequence
  length scales with the number of chips. Communication: n-1 block
  rotations riding neighbor links.
- **Ulysses attention**: ``lax.all_to_all`` re-shards sequence-sharded
  projections into head-sharded full sequences, runs exact local attention
  per head group, and re-shards back. One collective each way; head counts
  that don't divide the axis are zero-padded through the collective, and
  GQA (fewer K/V heads) re-shards the GROUPED heads, expanding locally.

Both are exact (parity-tested against dense attention on the virtual mesh).
All tensors are (batch, seq, heads, head_dim).
"""

from __future__ import annotations

import math
from functools import lru_cache, partial
from typing import Optional

import numpy as np

__all__ = ["ring_attention", "ulysses_attention",
           "sequence_sharded_attention"]


def _expand_gqa(q, k, v):
    """Grouped-query attention: replicate each K/V head over its query-head
    group (what real GQA checkpoints — Llama/Mistral-style — need before a
    head-count-symmetric attention path). No-op when head counts match."""
    import jax.numpy as jnp

    h, h_kv = q.shape[2], k.shape[2]
    if h_kv == h:
        return k, v
    if h % h_kv:
        raise ValueError(f"query heads {h} must be a multiple of kv heads "
                         f"{h_kv} (GQA groups)")
    rep = h // h_kv
    return jnp.repeat(k, rep, axis=2), jnp.repeat(v, rep, axis=2)


def _auto_block(s: int, cap: int = 512) -> int:
    """Largest power-of-2 block <= cap dividing ``s`` (flash blocks must
    divide the sequence; gathered Ulysses sequences are rarely multiples of
    the kernel defaults). Shares the divisor rule with the kernel's own
    auto-pick so the two cannot drift."""
    from .flash import _pow2_divisor

    return _pow2_divisor(s, cap)


def ring_attention(q, k, v, axis_name: str, causal: bool = False):
    """Flash-style ring attention over sequence shards.

    Call INSIDE ``shard_map``: ``q``/``k``/``v`` are the LOCAL sequence
    blocks (B, s_local, H, D); shard i holds global positions
    ``[i*s_local, (i+1)*s_local)``. K/V may carry fewer (grouped) heads —
    GQA rotates the GROUPED blocks around the ring (group-size-times less
    ICI traffic per hop) and expands to the query head count locally at each
    step. Returns the local output block.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    h, h_kv = q.shape[2], k.shape[2]
    if h % h_kv:
        raise ValueError(f"query heads {h} must be a multiple of kv heads "
                         f"{h_kv} (GQA groups)")
    rep = h // h_kv
    b, s_local, h, d = q.shape
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    scale = 1.0 / math.sqrt(d)
    q32 = q.astype(jnp.float32)

    qpos = my * s_local + jnp.arange(s_local)  # global query positions

    def step(i, carry):
        m, l, acc, k_blk, v_blk = carry
        # the block currently held started at shard (my - i) mod n
        src = (my - i) % n
        kpos = src * s_local + jnp.arange(s_local)
        mask = (qpos[:, None] >= kpos[None, :]) if causal else None
        # GQA: expand the grouped K block locally (free VMEM copy) — only
        # grouped heads ride the ring
        k_full = (jnp.repeat(k_blk, rep, axis=2) if rep > 1 else k_blk)
        s = jnp.einsum("bqhd,bkhd->bqhk", q32,
                       k_full.astype(jnp.float32)) * scale
        if mask is not None:
            s = jnp.where(mask[None, :, None, :], s, -jnp.inf)
        m_new = jnp.maximum(m, s.max(-1))
        # exp(-inf - -inf) guard: rows with no visible keys keep m=-inf
        safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - safe_m[..., None])
        if mask is not None:
            p = jnp.where(mask[None, :, None, :], p, 0.0)
        corr = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
        l = l * corr + p.sum(-1)
        v_full = (jnp.repeat(v_blk, rep, axis=2) if rep > 1 else v_blk)
        acc = acc * corr[..., None] + jnp.einsum(
            "bqhk,bkhd->bqhd", p, v_full.astype(jnp.float32))
        m = m_new
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return m, l, acc, k_blk, v_blk

    m0 = jnp.full((b, s_local, h), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, s_local, h), jnp.float32)
    acc0 = jnp.zeros((b, s_local, h, d), jnp.float32)
    m, l, acc, _, _ = lax.fori_loop(0, n, step, (m0, l0, acc0, k, v))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def ulysses_attention(q, k, v, axis_name: str, causal: bool = False,
                      local: str = "dense", interpret: bool = False,
                      block_q: Optional[int] = None,
                      block_k: Optional[int] = None):
    """All-to-all sequence parallelism (DeepSpeed-Ulysses style).

    Call INSIDE ``shard_map`` with (B, s_local, H, D) blocks. Re-shards to
    (B, S_global, H/n, D), runs local attention over the full gathered
    sequence, re-shards back. Heads that don't divide the axis size are
    zero-padded through the all-to-all and sliced off after (padded heads
    attend zeros -> produce zeros); K/V may carry fewer (grouped) heads —
    GQA expands first. ``local='flash'`` runs the local attention as the
    Pallas flash kernel (``flash.py``) — at long S the head-sharded score
    tensor is exactly the HBM blow-up flash avoids; ``'dense'`` stays
    exact-XLA. ``block_q``/``block_k`` override the flash block sizes
    (default: largest power-of-2 divisor of the gathered length, <= 512)."""
    import jax.numpy as jnp
    from jax import lax

    b, s_local, h, d = q.shape
    n = lax.psum(1, axis_name)  # axis sizes are static: this is a Python int
    h_kv = k.shape[2]
    rep = 1
    if h_kv != h:
        if h % h_kv:
            raise ValueError(f"query heads {h} must be a multiple of kv "
                             f"heads {h_kv} (GQA groups)")
        if h % n == 0 and h_kv % n == 0:
            # grouped re-shard: shard s's q-head slice [s*h/n, (s+1)*h/n)
            # covers exactly kv groups [s*h_kv/n, (s+1)*h_kv/n), so K/V ride
            # the all-to-all at group width and expand locally after —
            # group-size-times less collective traffic
            rep = h // h_kv
        else:
            k, v = _expand_gqa(q, k, v)
            h_kv = h
    pad_h = (-h) % n
    if pad_h:
        def zpad(x):
            return jnp.concatenate(
                [x, jnp.zeros((b, s_local, pad_h, d), x.dtype)], axis=2)
        q, k, v = zpad(q), zpad(k), zpad(v)

    # sequence-sharded -> head-sharded: split heads, concat sequence
    def to_heads(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)  # (B, S, H_pad/n, D)
    if rep > 1 and local != "flash":
        # dense local path: expand grouped K/V after the collective; the
        # flash kernel instead resolves GQA in-kernel via its BlockSpec
        # index map, so the expanded K/V never materialize in HBM (r5)
        kh = jnp.repeat(kh, rep, axis=2)
        vh = jnp.repeat(vh, rep, axis=2)
    if local == "flash":
        S = qh.shape[1]
        any_auto = block_q is None or block_k is None
        bq = block_q or _auto_block(S)
        bk = block_k or _auto_block(S)
        if any_auto and min(bq, bk) < 128:
            # an odd / small-power-of-2-factor gathered length auto-blocks
            # below the (8, 128) Mosaic tile minimum — the kernel would be
            # rejected or crawl at sub-tile grids; dense local attention is
            # both correct and faster at these sizes. Explicit blocks are
            # honored (interpret-mode tests and expert tuning).
            pass  # falls through to the dense path below
        else:
            from .flash import flash_attention

            out = flash_attention(qh, kh, vh, causal=causal,
                                  block_q=bq, block_k=bk,
                                  interpret=interpret)
            out = to_seq(out.astype(q.dtype))
            return out[:, :, :h] if pad_h else out
    if kh.shape[2] != qh.shape[2]:
        # reached via the flash sub-tile fallback with grouped K/V intact
        kh = jnp.repeat(kh, qh.shape[2] // kh.shape[2], axis=2)
        vh = jnp.repeat(vh, qh.shape[2] // vh.shape[2], axis=2)
    scale = 1.0 / math.sqrt(d)
    s = jnp.einsum("bqhd,bkhd->bqhk", qh.astype(jnp.float32),
                   kh.astype(jnp.float32)) * scale
    if causal:
        S = s.shape[1]
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask[None, :, None, :], s, -jnp.inf)
    p = jnp.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    out = jnp.einsum("bqhk,bkhd->bqhd", p, vh.astype(jnp.float32))
    out = to_seq(out.astype(q.dtype))
    return out[:, :, :h] if pad_h else out


def sequence_sharded_attention(q, k, v, mesh, axis: str = "seq",
                               strategy: str = "ring",
                               causal: bool = False,
                               local: str = "dense",
                               interpret: bool = False,
                               block_q: Optional[int] = None,
                               block_k: Optional[int] = None):
    """Host-level entry: GLOBAL (B, S, H, D) arrays -> attention output,
    with S sharded over ``mesh`` axis ``axis`` and the chosen strategy's
    collectives over the ICI ring. K/V may carry fewer (grouped/GQA) heads;
    ``block_q``/``block_k`` tune the ``local='flash'`` kernel (default:
    auto-picked to divide the gathered sequence)."""
    from ..runtime.layout import as_layout

    if strategy not in ("ring", "ulysses"):
        raise ValueError(f"unknown strategy {strategy!r}")
    # canonical sharding layout (runtime/layout.py): the sequence axis is
    # the layout's row axis; accepts a raw Mesh (back-compat) or SpecLayout
    layout = as_layout(mesh, data_axis=axis)
    n = layout.data_size
    S = q.shape[1]
    if S % n:
        raise ValueError(f"sequence length {S} must be divisible by the "
                         f"{layout.data_axis!r} axis size {n}")
    if local not in ("dense", "flash"):
        raise ValueError(f"unknown local attention {local!r}")
    run = _sharded_attn_fn(layout, strategy, causal, local, interpret,
                           block_q, block_k)
    spec = layout.batch(rank=4, dim=1)
    return run(layout.put(q, spec), layout.put(k, spec),
               layout.put(v, spec))


@lru_cache(maxsize=64)
def _sharded_attn_fn(layout, strategy: str, causal: bool,
                     local: str = "dense", interpret: bool = False,
                     block_q: Optional[int] = None,
                     block_k: Optional[int] = None):
    # cached per (layout, strategy, causal): a fresh jit closure per call
    # would retrace + recompile on every invocation (per layer / per step);
    # SpecLayout is frozen/hashable exactly so it can key this cache
    import jax

    axis = layout.data_axis
    if strategy == "ring":
        fn = partial(ring_attention, axis_name=axis, causal=causal)
    else:
        fn = partial(ulysses_attention, axis_name=axis, causal=causal,
                     local=local, interpret=interpret,
                     block_q=block_q, block_k=block_k)
    spec = layout.batch(rank=4, dim=1)
    return jax.jit(layout.shard_map(
        fn,
        in_specs=(spec, spec, spec), out_specs=spec,
        check=False,
    ))
