"""Sequence/context parallelism for long-sequence inference.

SURVEY.md §5 marks this NET-NEW: the reference predates LLM-scale sequence
lengths (its only long-input handling is audio chunking,
``SpeechToTextSDK.scala:232-339``). This package fills the capability gap
the TPU-first way: attention over sequences sharded across the ICI mesh,
with XLA collectives (``ppermute`` ring / ``all_to_all`` head exchange)
doing the communication.
"""

from .flash import dense_attention, flash_attention
from .ring import (
    ring_attention,
    sequence_sharded_attention,
    ulysses_attention,
)

__all__ = ["ring_attention", "ulysses_attention",
           "sequence_sharded_attention",
           "flash_attention", "dense_attention"]
