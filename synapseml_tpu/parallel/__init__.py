"""Sequence/context parallelism for long-sequence inference.

SURVEY.md §5 marks this NET-NEW: the reference predates LLM-scale sequence
lengths (its only long-input handling is audio chunking,
``SpeechToTextSDK.scala:232-339``). This package fills the capability gap
the TPU-first way: attention over sequences sharded across the ICI mesh,
with XLA collectives (``ppermute`` ring / ``all_to_all`` head exchange)
doing the communication.
"""

from ..core.lazyimport import lazy_module

# PEP 562 lazy exports (lint SMT008): attribute access imports the owning
# submodule on demand, keeping `import synapseml_tpu.parallel` jax-free
__getattr__, __dir__, __all__ = lazy_module(__name__, {
    "flash": ["dense_attention", "flash_attention"],
    "ring": ["ring_attention", "sequence_sharded_attention",
             "ulysses_attention"],
})
