"""Complement-set sampling (reference ``cyber/anomaly/complement_access.py``).

For each observed row, draw ``complementset_factor`` uniform random tuples
from the per-partition index ranges, then anti-join the observed tuples —
yielding a sample of access patterns that did NOT occur.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..core import Param, Table, Transformer
from .scalers import _partition_values

__all__ = ["ComplementAccessTransformer"]


class ComplementAccessTransformer(Transformer):
    partition_key = Param("partition column (None = global)", str, default=None)
    indexed_col_names = Param("indexed columns to sample over", list,
                              default=[])
    complementset_factor = Param("candidate draws per observed row", int,
                                 default=2)
    seed = Param("sampling seed", int, default=0)

    def _transform(self, table: Table) -> Table:
        cols = list(self.indexed_col_names)
        if not cols:
            raise ValueError(f"{type(self).__name__}({self.uid}): "
                             "indexed_col_names must be set")
        self._validate_input(table, *cols)
        factor = self.complementset_factor
        pk = self.partition_key
        if factor == 0:
            empty = {c: np.array([], dtype=np.int64) for c in cols}
            if pk is not None:
                empty[pk] = np.array([], dtype=object)
            return Table(empty)
        if pk is not None:
            self._validate_input(table, pk)
        parts = _partition_values(table, pk, table.num_rows)
        rng = np.random.default_rng(self.seed)
        vals = {c: np.asarray(table[c], dtype=np.int64) for c in cols}

        out_parts, out_vals = [], {c: [] for c in cols}
        for p in np.unique(parts):
            m = parts == p
            seen = set(zip(*[vals[c][m] for c in cols]))
            lims = [(int(vals[c][m].min()), int(vals[c][m].max()))
                    for c in cols]
            n_draw = int(m.sum()) * factor
            cand = np.stack([rng.integers(lo, hi + 1, size=n_draw)
                             for lo, hi in lims], axis=1)
            cand = np.unique(cand, axis=0)
            keep = [tuple(row) not in seen for row in cand]
            cand = cand[np.asarray(keep, dtype=bool)] if len(cand) else cand
            out_parts.extend([p] * len(cand))
            for j, c in enumerate(cols):
                out_vals[c].extend(cand[:, j].tolist())

        data = {c: np.array(out_vals[c], dtype=np.int64) for c in cols}
        if pk is not None:
            data[pk] = np.array(out_parts, dtype=object)
        return Table(data)
