"""AccessAnomaly: collaborative-filtering anomaly scores, TPU-first.

Reference: ``cyber/anomaly/collaborative_filtering.py`` —
``AccessAnomaly:472`` (Spark ALS per tenant, likelihood scaling, optional
explicit-CF complement sampling), ``ModelNormalizeTransformer:886`` (append
bias terms so the final dot product is the NEGATED per-tenant z-score of the
CF likelihood: unusual access scores high), ``ConnectedComponents:415``
(bipartite user/resource components; cross-component access scores +inf),
``AccessAnomalyModel:161`` (seen pairs from history score 0, unknown
user/resource scores NaN).

TPU-first redesign: Spark's blocked ALS becomes a dense batched JAX ALS —
both half-steps are einsum-built (B, k, k) normal matrices solved with one
batched ``jnp.linalg.solve`` (MXU work), with nonnegative projection like the
reference's ``nonnegative=True``. The iterative Spark-join connected
components becomes a union-find per tenant.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..core import ComplexParam, Estimator, Model, Param, Table
from ..core.params import ParamValidators
from .complement import ComplementAccessTransformer
from .indexers import IdIndexer
from .scalers import LinearScalarScaler, _partition_values

__all__ = ["AccessAnomaly", "AccessAnomalyModel", "ConnectedComponents"]


def _nest(flat: Dict) -> Dict[str, Dict[str, int]]:
    """{(tenant, name): v} -> {tenant: {name: v}} (JSON-persistable keys)."""
    out: Dict[str, Dict[str, int]] = {}
    for (tenant, name), v in flat.items():
        out.setdefault(tenant, {})[name] = v
    return out


def _als(ratings: np.ndarray, rank: int, iters: int, reg: float,
         implicit: bool, alpha: float, seed: int) -> Tuple[np.ndarray,
                                                           np.ndarray]:
    """Dense ALS. ``ratings`` (n_u, n_i) with 0 = unobserved.

    Implicit (Hu-Koren-Volinsky, the reference's default): confidence
    c = 1 + alpha*r toward preference 1. Explicit: squared error on observed
    entries. Both half-steps are batched normal-equation solves; factors are
    projected to >= 0 (reference sets ``nonnegative=True``)."""
    import jax
    import jax.numpy as jnp

    n_u, n_i = ratings.shape
    key = jax.random.PRNGKey(seed)
    ku, ki = jax.random.split(key)
    u = jax.random.uniform(ku, (n_u, rank), dtype=jnp.float32) * 0.1
    v = jax.random.uniform(ki, (n_i, rank), dtype=jnp.float32) * 0.1
    r = jnp.asarray(ratings, jnp.float32)
    p = (r > 0).astype(jnp.float32)
    eye = jnp.eye(rank, dtype=jnp.float32) * reg

    def solve_implicit(fixed, rows, alpha_r):
        # A_b = F^T F + reg I + sum_j alpha*r_bj f_j f_j^T ; b_b = F^T c_b p_b
        ftf = fixed.T @ fixed
        a = ftf[None] + eye[None] + jnp.einsum(
            "bj,jk,jl->bkl", alpha_r, fixed, fixed)
        b = ((1.0 + alpha_r) * rows) @ fixed
        return jnp.maximum(jnp.linalg.solve(a, b[..., None])[..., 0], 0.0)

    def solve_explicit(fixed, r_rows, w_rows):
        a = jnp.einsum("bj,jk,jl->bkl", w_rows, fixed, fixed) + eye[None]
        b = (w_rows * r_rows) @ fixed
        return jnp.maximum(jnp.linalg.solve(a, b[..., None])[..., 0], 0.0)

    @jax.jit
    def run(u, v):
        def step(_, uv):
            u, v = uv
            if implicit:
                u = solve_implicit(v, p, alpha * r)
                v = solve_implicit(u, p.T, alpha * r.T)
            else:
                u = solve_explicit(v, r, p)
                v = solve_explicit(u, r.T, p.T)
            return u, v

        return jax.lax.fori_loop(0, iters, step, (u, v))

    u, v = run(u, v)
    return np.asarray(u), np.asarray(v)


class ConnectedComponents:
    """Bipartite user/resource connected components per tenant (reference
    ``ConnectedComponents:415`` — the iterative min-propagation joins are a
    union-find here)."""

    def __init__(self, tenant_col: str, user_col: str, res_col: str):
        self.tenant_col = tenant_col
        self.user_col = user_col
        self.res_col = res_col

    def compute(self, table: Table) -> Tuple[Dict, Dict]:
        """Returns ({(tenant, user): comp}, {(tenant, res): comp})."""
        parent: Dict = {}

        def find(x):
            while parent[x] != x:
                parent[x] = parent[parent[x]]
                x = parent[x]
            return x

        def union(a, b):
            parent.setdefault(a, a)
            parent.setdefault(b, b)
            ra, rb = find(a), find(b)
            if ra != rb:
                parent[max(ra, rb)] = min(ra, rb)

        for i in range(table.num_rows):
            t = str(table[self.tenant_col][i])
            union((t, "u", str(table[self.user_col][i])),
                  (t, "r", str(table[self.res_col][i])))
        users, resources = {}, {}
        labels: Dict = {}
        for node in parent:
            root = find(node)
            comp = labels.setdefault(root, len(labels))
            tenant, kind, name = node
            (users if kind == "u" else resources)[(tenant, name)] = comp
        return users, resources


class AccessAnomaly(Estimator):
    """Reference ``AccessAnomaly:472``; param names snake_cased from the
    reference's ``AccessAnomalyConfig`` defaults."""

    tenant_col = Param("tenant partition column", str, default="tenant")
    user_col = Param("user column", str, default="user")
    res_col = Param("resource column", str, default="res")
    likelihood_col = Param("access likelihood column (e.g. counts per time "
                           "unit)", str, default="likelihood")
    output_col = Param("anomaly score output (mean ~0, std ~1 per tenant)",
                       str, default="anomaly_score")
    rank_param = Param("latent factors", int, default=10,
                       validator=ParamValidators.gt(0))
    max_iter = Param("ALS iterations", int, default=25,
                     validator=ParamValidators.gt(0))
    reg_param = Param("ALS regularization", float, default=1.0)
    apply_implicit_cf = Param("implicit-feedback ALS (True, default) vs "
                              "explicit with complement sampling", bool,
                              default=True)
    alpha_param = Param("implicit: confidence slope", float, default=1.0)
    complementset_factor = Param("explicit: complement samples per row", int,
                                 default=2)
    neg_score = Param("explicit: rating assigned to complement rows", float,
                      default=1.0)
    low_value = Param("scale likelihood to [low_value, high_value] "
                      "(None = no scaling)", float, default=5.0)
    high_value = Param("scale likelihood upper bound", float, default=10.0)
    seed = Param("random seed", int, default=0)
    history_access_df = ComplexParam(
        "optional Table of seen (tenant, user, res) scoring 0", object,
        default=None)

    def _fit(self, table: Table) -> "AccessAnomalyModel":
        self._validate_input(table, self.tenant_col, self.user_col,
                             self.res_col)
        if (self.low_value is None) != (self.high_value is None):
            raise ValueError("low_value and high_value must be set together")
        tenant_col, user_col, res_col = (self.tenant_col, self.user_col,
                                         self.res_col)

        # per-tenant consecutive ids from 1 (unknown -> 0 at transform)
        user_ix = IdIndexer(input_col=user_col, partition_key=tenant_col,
                            output_col="__uidx__",
                            reset_per_partition=True).fit(table)
        res_ix = IdIndexer(input_col=res_col, partition_key=tenant_col,
                           output_col="__ridx__",
                           reset_per_partition=True).fit(table)
        indexed = res_ix.transform(user_ix.transform(table))

        # likelihood: scale to [low, high] per tenant; default 1.0 when absent
        if self.likelihood_col in table:
            if self.low_value is not None:
                indexed = LinearScalarScaler(
                    input_col=self.likelihood_col, partition_key=tenant_col,
                    output_col="__lik__",
                    min_required_value=self.low_value,
                    max_required_value=self.high_value,
                ).fit(indexed).transform(indexed)
            else:
                indexed = indexed.with_column(
                    "__lik__", np.asarray(indexed[self.likelihood_col],
                                          np.float64))
        else:
            default_lik = 1.0 if self.high_value is None else self.high_value
            indexed = indexed.with_column("__lik__",
                                          np.full(indexed.num_rows,
                                                  default_lik))

        tenants = sorted({str(v) for v in table[tenant_col].tolist()})
        user_vecs: Dict[str, Dict[str, list]] = {}
        res_vecs: Dict[str, Dict[str, list]] = {}
        parts = _partition_values(indexed, tenant_col, indexed.num_rows)
        k = self.rank_param
        for tenant in tenants:
            m = parts == tenant
            uidx = np.asarray(indexed["__uidx__"], np.int64)[m] - 1
            ridx = np.asarray(indexed["__ridx__"], np.int64)[m] - 1
            lik = np.asarray(indexed["__lik__"], np.float64)[m]
            n_u, n_i = int(uidx.max()) + 1, int(ridx.max()) + 1
            ratings = np.zeros((n_u, n_i), dtype=np.float64)
            np.add.at(ratings, (uidx, ridx), lik)
            if not self.apply_implicit_cf:
                # explicit CF: unseen sampled pairs get neg_score
                comp = ComplementAccessTransformer(
                    partition_key=None,
                    indexed_col_names=["u", "r"],
                    complementset_factor=self.complementset_factor,
                    seed=self.seed,
                ).transform(Table({"u": uidx, "r": ridx}))
                if comp.num_rows:
                    cu = np.asarray(comp["u"], np.int64)
                    cr = np.asarray(comp["r"], np.int64)
                    ratings[cu, cr] = self.neg_score
            u, v = _als(ratings, k, self.max_iter, self.reg_param,
                        self.apply_implicit_cf, self.alpha_param, self.seed)
            # normalization (reference ModelNormalizeTransformer:886): compute
            # train-pair dots, per-tenant mean/std_pop, then fold the z-score
            # and negation into appended bias dims:
            #   user' = -1/std * [u, -mean, 1] ; res' = [v, 1, 0]
            #   => user'.res' = -(u.v - mean)/std
            dots = np.einsum("rk,rk->r", u[uidx], v[ridx])
            mean, std = float(dots.mean()), float(dots.std())
            std = std if std != 0.0 else 1.0
            u_aug = np.concatenate(
                [u, np.full((n_u, 1), -mean), np.ones((n_u, 1))], axis=1)
            u_aug *= -1.0 / std
            v_aug = np.concatenate(
                [v, np.ones((n_i, 1)), np.zeros((n_i, 1))], axis=1)
            inv_u = {ix - 1: name for name, ix
                     in user_ix.vocab[tenant].items()}
            inv_r = {ix - 1: name for name, ix in res_ix.vocab[tenant].items()}
            user_vecs[tenant] = {inv_u[i]: u_aug[i].tolist()
                                 for i in range(n_u) if i in inv_u}
            res_vecs[tenant] = {inv_r[i]: v_aug[i].tolist()
                                for i in range(n_i) if i in inv_r}

        history = self.history_access_df
        access = history if history is not None else table
        users_comp, res_comp = ConnectedComponents(
            tenant_col, user_col, res_col).compute(access)
        history_list = None
        if history is not None:
            history_list = [
                [str(history[tenant_col][i]), str(history[user_col][i]),
                 str(history[res_col][i])]
                for i in range(history.num_rows)]
        return AccessAnomalyModel(
            tenant_col=tenant_col, user_col=user_col, res_col=res_col,
            output_col=self.output_col,
            user_vectors=user_vecs, res_vectors=res_vecs,
            user_components=_nest(users_comp),
            res_components=_nest(res_comp),
            history=history_list)


class AccessAnomalyModel(Model):
    """Reference ``AccessAnomalyModel:161``. Scores (tenant, user, res) rows:
    NaN for unknown user/resource, +inf for cross-component access, 0 for
    pairs present in the history set, else the normalized CF score."""

    tenant_col = Param("tenant partition column", str, default="tenant")
    user_col = Param("user column", str, default="user")
    res_col = Param("resource column", str, default="res")
    output_col = Param("anomaly score output column", str,
                       default="anomaly_score")
    user_vectors = ComplexParam("tenant -> {user -> augmented latent vector}",
                                dict, default=None)
    res_vectors = ComplexParam("tenant -> {res -> augmented latent vector}",
                               dict, default=None)
    user_components = ComplexParam("tenant -> {user -> component id}", dict,
                                   default=None)
    res_components = ComplexParam("tenant -> {res -> component id}", dict,
                                  default=None)
    history = ComplexParam("list of seen [tenant, user, res] scoring 0",
                           object, default=None)

    def _transform(self, table: Table) -> Table:
        self._validate_input(table, self.tenant_col, self.user_col,
                             self.res_col)
        n = table.num_rows
        out = np.empty(n, dtype=np.float64)
        seen = ({tuple(t) for t in self.history}
                if self.history is not None else None)
        for i in range(n):
            tenant = str(table[self.tenant_col][i])
            user = str(table[self.user_col][i])
            res = str(table[self.res_col][i])
            if seen is not None and (tenant, user, res) in seen:
                out[i] = 0.0
                continue
            uv = self.user_vectors.get(tenant, {}).get(user)
            rv = self.res_vectors.get(tenant, {}).get(res)
            if uv is None or rv is None:
                out[i] = np.nan
                continue
            uc = self.user_components.get(tenant, {}).get(user)
            rc = self.res_components.get(tenant, {}).get(res)
            if uc is not None and rc is not None and uc != rc:
                out[i] = np.inf
                continue
            out[i] = float(np.dot(uv, rv))
        return table.with_column(self.output_col, out)
