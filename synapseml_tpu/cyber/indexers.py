"""Per-partition id indexers (reference ``cyber/feature/indexers.py``).

``IdIndexer``: (partition, value) -> consecutive index from 1; unseen values
map to 0 at transform (reference ``IdIndexerModel._transform:31-43``).
``reset_per_partition=True`` restarts 1..n within each partition.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

from ..core import ComplexParam, Estimator, Model, Param, Table, Transformer

__all__ = ["IdIndexer", "IdIndexerModel", "MultiIndexer", "MultiIndexerModel"]


class IdIndexer(Estimator):
    input_col = Param("column to index", str, default="input")
    output_col = Param("index output column", str, default="output")
    partition_key = Param("partition column", str, default="tenant")
    reset_per_partition = Param("restart numbering per partition", bool,
                                default=False)

    def _fit(self, table: Table) -> "IdIndexerModel":
        self._validate_input(table, self.input_col, self.partition_key)
        pairs = sorted({(str(table[self.partition_key][i]),
                         str(table[self.input_col][i]))
                        for i in range(table.num_rows)})
        vocab: Dict[str, Dict[str, int]] = {}
        if self.reset_per_partition:
            for part, val in pairs:
                d = vocab.setdefault(part, {})
                d[val] = len(d) + 1
        else:
            for i, (part, val) in enumerate(pairs):
                vocab.setdefault(part, {})[val] = i + 1
        return IdIndexerModel(
            input_col=self.input_col, output_col=self.output_col,
            partition_key=self.partition_key, vocab=vocab)


class IdIndexerModel(Model):
    input_col = Param("column to index", str, default="input")
    output_col = Param("index output column", str, default="output")
    partition_key = Param("partition column", str, default="tenant")
    vocab = ComplexParam("partition -> {value -> index from 1}", dict,
                         default=None)

    def _transform(self, table: Table) -> Table:
        self._validate_input(table, self.input_col, self.partition_key)
        out = np.empty(table.num_rows, dtype=np.int64)
        for i in range(table.num_rows):
            part = str(table[self.partition_key][i])
            out[i] = self.vocab.get(part, {}).get(
                str(table[self.input_col][i]), 0)  # unseen -> 0
        return table.with_column(self.output_col, out)

    def undo_map(self) -> Dict[Tuple[str, int], str]:
        """(partition, index) -> original value (reference ``undo_transform``)."""
        return {(part, idx): val
                for part, d in self.vocab.items() for val, idx in d.items()}


class MultiIndexer(Estimator):
    """Fits several IdIndexers on one pass (reference ``MultiIndexer:130``)."""

    indexers = ComplexParam("list of IdIndexer stages", list, default=[])

    def _fit(self, table: Table) -> "MultiIndexerModel":
        return MultiIndexerModel(
            models=[ix.fit(table) for ix in self.indexers])


class MultiIndexerModel(Model):
    models = ComplexParam("list of fitted IdIndexerModels", list, default=[])

    def get_model_by_input_col(self, input_col: str):
        for m in self.models:
            if m.input_col == input_col:
                return m
        return None

    def get_model_by_output_col(self, output_col: str):
        for m in self.models:
            if m.output_col == output_col:
                return m
        return None

    def _transform(self, table: Table) -> Table:
        for m in self.models:
            table = m.transform(table)
        return table
