"""CyberML: access-anomaly detection via collaborative filtering.

Reference package: ``core/src/main/python/synapse/ml/cyber/`` (1,787 LoC) —
``anomaly/collaborative_filtering.py`` (``AccessAnomaly:472``,
``AccessAnomalyModel:161``, ``ConnectedComponents:415``,
``ModelNormalizeTransformer:886``), ``anomaly/complement_access.py``,
``feature/indexers.py``, ``feature/scalers.py``.
"""

from ..core.lazyimport import lazy_module

# PEP 562 lazy exports (lint SMT008): attribute access imports the owning
# submodule on demand, keeping `import synapseml_tpu.cyber` jax-free
__getattr__, __dir__, __all__ = lazy_module(__name__, {
    "anomaly": ["AccessAnomaly", "AccessAnomalyModel", "ConnectedComponents"],
    "complement": ["ComplementAccessTransformer"],
    "indexers": ["IdIndexer", "IdIndexerModel", "MultiIndexer",
                 "MultiIndexerModel"],
    "scalers": ["LinearScalarScaler", "LinearScalarScalerModel",
                "StandardScalarScaler", "StandardScalarScalerModel"],
})
