"""CyberML: access-anomaly detection via collaborative filtering.

Reference package: ``core/src/main/python/synapse/ml/cyber/`` (1,787 LoC) —
``anomaly/collaborative_filtering.py`` (``AccessAnomaly:472``,
``AccessAnomalyModel:161``, ``ConnectedComponents:415``,
``ModelNormalizeTransformer:886``), ``anomaly/complement_access.py``,
``feature/indexers.py``, ``feature/scalers.py``.
"""

from .anomaly import AccessAnomaly, AccessAnomalyModel, ConnectedComponents
from .complement import ComplementAccessTransformer
from .indexers import IdIndexer, IdIndexerModel, MultiIndexer, MultiIndexerModel
from .scalers import (
    LinearScalarScaler,
    LinearScalarScalerModel,
    StandardScalarScaler,
    StandardScalarScalerModel,
)

__all__ = [
    "AccessAnomaly", "AccessAnomalyModel", "ConnectedComponents",
    "ComplementAccessTransformer",
    "IdIndexer", "IdIndexerModel", "MultiIndexer", "MultiIndexerModel",
    "LinearScalarScaler", "LinearScalarScalerModel",
    "StandardScalarScaler", "StandardScalarScalerModel",
]
