"""Per-partition scalers (reference ``cyber/feature/scalers.py``).

``StandardScalarScaler``: per-partition (x - mean) / std_pop, falling back to
x - mean when std == 0 (reference ``StandardScalarScalerModel:156-183``).
``LinearScalarScaler``: per-partition linear map onto
[min_required, max_required]; degenerate partitions (min == max) map to the
midpoint (reference ``LinearScalarScalerModel:241-280``).

Stats are keyed by the partition value (``partition_key=None`` = one global
partition), stored as a plain dict so models persist via the JSON path.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from ..core import ComplexParam, Estimator, Model, Param, Table

__all__ = ["StandardScalarScaler", "StandardScalarScalerModel",
           "LinearScalarScaler", "LinearScalarScalerModel"]

_GLOBAL = "__global__"


def _partition_values(table: Table, partition_key: Optional[str], n: int):
    if partition_key is None:
        return np.array([_GLOBAL] * n, dtype=object)
    return np.array([str(v) for v in table[partition_key].tolist()],
                    dtype=object)


class _ScalerBase(Estimator):
    _abstract_stage = True

    input_col = Param("column to scale", str, default="input")
    output_col = Param("scaled output column", str, default="output")
    partition_key = Param("partition column (None = global)", str, default=None)

    def _group_stats(self, table: Table, stat_fn) -> Dict[str, list]:
        self._validate_input(table, self.input_col)
        if self.partition_key is not None:
            self._validate_input(table, self.partition_key)
        x = np.asarray(table[self.input_col], dtype=np.float64)
        parts = _partition_values(table, self.partition_key, len(x))
        return {p: stat_fn(x[parts == p]) for p in np.unique(parts)}


class StandardScalarScaler(_ScalerBase):
    coefficient_factor = Param("multiply scaled output by this", float,
                               default=1.0)

    def _fit(self, table: Table) -> "StandardScalarScalerModel":
        stats = self._group_stats(
            table, lambda v: [float(v.mean()), float(v.std())])
        return StandardScalarScalerModel(
            input_col=self.input_col, output_col=self.output_col,
            partition_key=self.partition_key,
            coefficient_factor=self.coefficient_factor,
            per_group_stats=stats)


class StandardScalarScalerModel(Model):
    input_col = Param("column to scale", str, default="input")
    output_col = Param("scaled output column", str, default="output")
    partition_key = Param("partition column", str, default=None)
    coefficient_factor = Param("output multiplier", float, default=1.0)
    per_group_stats = ComplexParam("partition -> [mean, std_pop]", dict,
                                   default=None)

    def _transform(self, table: Table) -> Table:
        self._validate_input(table, self.input_col)
        x = np.asarray(table[self.input_col], dtype=np.float64)
        parts = _partition_values(table, self.partition_key, len(x))
        out = np.empty(len(x))
        for p in np.unique(parts):
            m = parts == p
            mean, std = self.per_group_stats.get(str(p), [0.0, 1.0])
            if std != 0.0:
                out[m] = self.coefficient_factor * (x[m] - mean) / std
            else:
                out[m] = x[m] - mean
        return table.with_column(self.output_col, out)


class LinearScalarScaler(_ScalerBase):
    min_required_value = Param("target range lower bound", float, default=0.0)
    max_required_value = Param("target range upper bound", float, default=1.0)

    def _fit(self, table: Table) -> "LinearScalarScalerModel":
        stats = self._group_stats(
            table, lambda v: [float(v.min()), float(v.max())])
        return LinearScalarScalerModel(
            input_col=self.input_col, output_col=self.output_col,
            partition_key=self.partition_key,
            min_required_value=self.min_required_value,
            max_required_value=self.max_required_value,
            per_group_stats=stats)


class LinearScalarScalerModel(Model):
    input_col = Param("column to scale", str, default="input")
    output_col = Param("scaled output column", str, default="output")
    partition_key = Param("partition column", str, default=None)
    min_required_value = Param("target range lower bound", float, default=0.0)
    max_required_value = Param("target range upper bound", float, default=1.0)
    per_group_stats = ComplexParam("partition -> [min, max]", dict,
                                   default=None)

    def _transform(self, table: Table) -> Table:
        self._validate_input(table, self.input_col)
        x = np.asarray(table[self.input_col], dtype=np.float64)
        parts = _partition_values(table, self.partition_key, len(x))
        out = np.empty(len(x))
        for p in np.unique(parts):
            m = parts == p
            lo, hi = self.per_group_stats.get(str(p), [0.0, 0.0])
            delta = hi - lo
            if delta != 0.0:
                a = (self.max_required_value - self.min_required_value) / delta
                b = self.max_required_value - a * hi
                out[m] = a * x[m] + b
            else:
                out[m] = (self.min_required_value
                          + self.max_required_value) / 2.0
        return table.with_column(self.output_col, out)
