"""Exact (conditional) KNN by maximum inner product, TPU-first.

Reference: ``core/src/main/scala/.../nn/KNN.scala:48``,
``ConditionalKNN.scala:31``, backed by a serialized ball tree
(``BallTree.scala:109``, ``ConditionalBallTree`` at ``:202``) whose
``findMaximumInnerProducts`` walks tree nodes with a bounded priority queue
per query.

TPU-first redesign: a pointer ball tree is the wrong shape for the MXU — the
index here is the raw (N, d) key matrix, a query batch scores ALL keys with
ONE matmul ``Q @ K.T`` (bf16/f32 on the systolic array), conditional search
masks disallowed labels with ``-inf``, and ``jax.lax.top_k`` returns the
result. Exact (no approximation), like the reference; brute force on the MXU
beats tree pointer-chasing for any N that fits in HBM.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Optional

import numpy as np

from ..core import ComplexParam, Estimator, Model, Param, Table
from ..core.params import ParamValidators
from ..core.table import features_matrix as _matrix

__all__ = ["KNN", "KNNModel", "ConditionalKNN", "ConditionalKNNModel"]


@lru_cache(maxsize=64)
def _topk_kernel(k: int, has_mask: bool):
    import jax
    import jax.numpy as jnp

    @jax.jit
    def run(q, kk, m):
        # HIGHEST precision: the contract is EXACT inner products (the
        # reference's BLAS brute force); TPU default matmul precision
        # rounds f32 operands through bf16 passes, which shifts distances
        # by ~1e-3 relative and can flip near-tie rankings
        scores = jnp.matmul(q, kk.T,
                            precision=jax.lax.Precision.HIGHEST)  # (nq, N)
        if has_mask:
            scores = jnp.where(m, scores, -jnp.inf)
        return jax.lax.top_k(scores, k)

    return run


def _topk_inner_products(keys: np.ndarray, queries: np.ndarray, k: int,
                         mask: Optional[np.ndarray] = None):
    """(scores, indices) of the k largest inner products per query row.

    One jitted matmul over the whole batch (replaces the reference's per-row
    ``findMaximumInnerProducts`` tree walk)."""
    import jax.numpy as jnp

    k = min(k, keys.shape[0])
    run = _topk_kernel(k, mask is not None)
    vals, idx = run(jnp.asarray(queries, jnp.float32),
                    jnp.asarray(keys, jnp.float32),
                    jnp.zeros((), jnp.bool_) if mask is None
                    else jnp.asarray(mask))
    return np.asarray(vals), np.asarray(idx)


class KNN(Estimator):
    """Reference ``KNN.scala:48``: indexes (features, values); queries return
    the k best matches as ``[{value, distance}]`` where distance is the inner
    product (the reference's ``BestMatch``)."""

    features_col = Param("key vector column", str, default="features")
    values_col = Param("payload column returned for matches", str,
                       default="values")
    output_col = Param("output column of match lists", str, default="output")
    k = Param("number of matches", int, default=5,
              validator=ParamValidators.gt(0))
    leaf_size = Param("accepted for reference API parity (the MXU index has "
                      "no tree leaves)", int, default=50)

    def _fit(self, table: Table) -> "KNNModel":
        self._validate_input(table, self.features_col, self.values_col)
        return KNNModel(
            features_col=self.features_col, values_col=self.values_col,
            output_col=self.output_col, k=self.k,
            keys=_matrix(table[self.features_col]).astype(np.float32),
            values=np.asarray(table[self.values_col], dtype=object))


class KNNModel(Model):
    features_col = Param("query vector column", str, default="features")
    values_col = Param("payload column", str, default="values")
    output_col = Param("output column", str, default="output")
    k = Param("number of matches", int, default=5)
    keys = ComplexParam("(N, d) indexed key matrix", object, default=None)
    values = ComplexParam("(N,) payload array", object, default=None)

    def _transform(self, table: Table) -> Table:
        self._validate_input(table, self.features_col)
        queries = _matrix(table[self.features_col])
        vals, idx = _topk_inner_products(np.asarray(self.keys), queries, self.k)
        values = np.asarray(self.values, dtype=object)
        out = np.empty(len(queries), dtype=object)
        for r in range(len(queries)):
            out[r] = [{"value": values[idx[r, j]],
                       "distance": float(vals[r, j])}
                      for j in range(idx.shape[1])]
        return table.with_column(self.output_col, out)


class ConditionalKNN(Estimator):
    """Reference ``ConditionalKNN.scala:31``: like KNN but each query carries
    a conditioner set; only keys whose label is in the set may match."""

    features_col = Param("key vector column", str, default="features")
    values_col = Param("payload column returned for matches", str,
                       default="values")
    label_col = Param("per-key label used for conditioning", str,
                      default="labels")
    conditioner_col = Param("per-query collection of admissible labels", str,
                            default="conditioner")
    output_col = Param("output column of match lists", str, default="output")
    k = Param("number of matches", int, default=5,
              validator=ParamValidators.gt(0))
    leaf_size = Param("accepted for reference API parity", int, default=50)

    def _fit(self, table: Table) -> "ConditionalKNNModel":
        self._validate_input(table, self.features_col, self.values_col,
                             self.label_col)
        labels = np.asarray(table[self.label_col], dtype=object)
        levels = sorted({l for l in labels.tolist()}, key=repr)
        lut = {l: i for i, l in enumerate(levels)}
        codes = np.array([lut[l] for l in labels.tolist()], dtype=np.int32)
        return ConditionalKNNModel(
            features_col=self.features_col, values_col=self.values_col,
            label_col=self.label_col, conditioner_col=self.conditioner_col,
            output_col=self.output_col, k=self.k,
            keys=_matrix(table[self.features_col]).astype(np.float32),
            values=np.asarray(table[self.values_col], dtype=object),
            labels=labels, label_codes=codes,
            label_levels=np.array(levels, dtype=object))


class ConditionalKNNModel(Model):
    features_col = Param("query vector column", str, default="features")
    values_col = Param("payload column", str, default="values")
    label_col = Param("per-key label column", str, default="labels")
    conditioner_col = Param("per-query admissible-label collection", str,
                            default="conditioner")
    output_col = Param("output column", str, default="output")
    k = Param("number of matches", int, default=5)
    keys = ComplexParam("(N, d) indexed key matrix", object, default=None)
    values = ComplexParam("(N,) payload array", object, default=None)
    labels = ComplexParam("(N,) label array", object, default=None)
    label_codes = ComplexParam("(N,) int codes of labels", object, default=None)
    label_levels = ComplexParam("code -> label", object, default=None)

    def _transform(self, table: Table) -> Table:
        self._validate_input(table, self.features_col, self.conditioner_col)
        queries = _matrix(table[self.features_col])
        levels = list(self.label_levels)
        lut = {l: i for i, l in enumerate(levels)}
        codes = np.asarray(self.label_codes)
        # (nq, L) admissible matrix -> (nq, N) mask by code gather; labels
        # unseen at fit time simply admit nothing.
        allowed = np.zeros((len(queries), len(levels)), dtype=bool)
        for r, cond in enumerate(table[self.conditioner_col]):
            for l in (cond if isinstance(cond, (list, tuple, set, np.ndarray))
                      else [cond]):
                i = lut.get(l)
                if i is not None:
                    allowed[r, i] = True
        mask = allowed[:, codes]
        vals, idx = _topk_inner_products(np.asarray(self.keys), queries,
                                         self.k, mask=mask)
        values = np.asarray(self.values, dtype=object)
        labels = np.asarray(self.labels, dtype=object)
        out = np.empty(len(queries), dtype=object)
        for r in range(len(queries)):
            # drop -inf entries (fewer than k admissible keys)
            out[r] = [{"value": values[idx[r, j]],
                       "distance": float(vals[r, j]),
                       "label": labels[idx[r, j]]}
                      for j in range(idx.shape[1]) if np.isfinite(vals[r, j])]
        return table.with_column(self.output_col, out)
