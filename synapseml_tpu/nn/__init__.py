"""Nearest neighbors: exact KNN + conditional (label-filtered) KNN.

Reference package: ``core/src/main/scala/.../nn/`` (616 LoC —
``BallTree.scala``, ``ConditionalKNN.scala``, ``KNN.scala``,
``BoundedPriorityQueue.scala``).
"""

from ..core.lazyimport import lazy_module

# PEP 562 lazy exports (lint SMT008): keeps the package import jax-free
__getattr__, __dir__, __all__ = lazy_module(__name__, {
    "knn": ["KNN", "KNNModel", "ConditionalKNN", "ConditionalKNNModel"],
})
