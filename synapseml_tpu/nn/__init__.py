"""Nearest neighbors: exact KNN + conditional (label-filtered) KNN.

Reference package: ``core/src/main/scala/.../nn/`` (616 LoC —
``BallTree.scala``, ``ConditionalKNN.scala``, ``KNN.scala``,
``BoundedPriorityQueue.scala``).
"""

from .knn import KNN, KNNModel, ConditionalKNN, ConditionalKNNModel

__all__ = ["KNN", "KNNModel", "ConditionalKNN", "ConditionalKNNModel"]
