"""Deep-learning stages: ONNX-backed featurization + model repository
(reference: ``deep-learning`` module)."""

from .downloader import (LocalRepository, ModelDownloader, ModelSchema,
                         RemoteRepository, Repository, ZooRepository)
from .featurizer import ImageFeaturizer

__all__ = [
    "ImageFeaturizer",
    "ModelDownloader",
    "RemoteRepository",
    "ModelSchema",
    "Repository",
    "LocalRepository",
    "ZooRepository",
]
