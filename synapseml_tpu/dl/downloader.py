"""Model repository / downloader.

Rebuild of ``deep-learning/.../downloader/ModelDownloader.scala:26-263`` (+
``Schema.scala``): a ``Repository`` abstraction with schema metadata and content-hash
verification, a local filesystem repo, and a "remote" default repo. The reference's
default repo is an Azure blob; this environment is zero-egress, so the default repo is
backed by the builder zoo (``synapseml_tpu.models.zoo``) — same contract (list, schema,
fetch-with-hash-check, local caching), different origin. A real HTTP repo can be added
by implementing ``Repository.read_bytes``.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Dict, Iterator, List, Optional

__all__ = ["ModelSchema", "Repository", "LocalRepository", "ZooRepository",
           "RemoteRepository", "ModelDownloader"]


@dataclasses.dataclass
class ModelSchema:
    """Reference: ``Schema.scala`` (name, uri, hash, size, inputNode, numLayers...)."""

    name: str
    path: str = ""
    sha256: str = ""
    size: int = 0
    input_name: str = "data"
    feature_output: str = "features"
    logits_output: str = "logits"
    input_shape: Optional[List[int]] = None
    extra: Dict = dataclasses.field(default_factory=dict)

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2, sort_keys=True)

    @staticmethod
    def from_json(s: str) -> "ModelSchema":
        return ModelSchema(**json.loads(s))


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


class Repository:
    """Abstract model repository (reference ``Repository[S]`` trait)."""

    def list_schemas(self) -> Iterator[ModelSchema]:  # pragma: no cover - interface
        raise NotImplementedError

    def read_bytes(self, schema: ModelSchema) -> bytes:  # pragma: no cover - interface
        raise NotImplementedError

    def get_schema(self, name: str) -> ModelSchema:
        for s in self.list_schemas():
            if s.name == name:
                return s
        raise KeyError(f"model {name!r} not found in {type(self).__name__}")


class LocalRepository(Repository):
    """Directory of ``<name>.json`` schemas + model payload files
    (reference ``LocalRepo``). Verifies sha256 on read."""

    def __init__(self, base_dir: str):
        self.base_dir = base_dir

    def list_schemas(self) -> Iterator[ModelSchema]:
        if not os.path.isdir(self.base_dir):
            return
        for fn in sorted(os.listdir(self.base_dir)):
            if fn.endswith(".json"):
                with open(os.path.join(self.base_dir, fn)) as f:
                    yield ModelSchema.from_json(f.read())

    def read_bytes(self, schema: ModelSchema) -> bytes:
        path = schema.path
        if not os.path.isabs(path):
            path = os.path.join(self.base_dir, path)
        with open(path, "rb") as f:
            data = f.read()
        if schema.sha256 and _sha256(data) != schema.sha256:
            raise IOError(
                f"hash mismatch for model {schema.name}: expected {schema.sha256[:12]}..., "
                f"got {_sha256(data)[:12]}... (corrupt download?)"
            )
        return data

    def add(self, schema: ModelSchema, data: bytes) -> ModelSchema:
        os.makedirs(self.base_dir, exist_ok=True)
        payload = f"{schema.name}.onnx"
        with open(os.path.join(self.base_dir, payload), "wb") as f:
            f.write(data)
        schema = dataclasses.replace(schema, path=payload, sha256=_sha256(data), size=len(data))
        with open(os.path.join(self.base_dir, f"{schema.name}.json"), "w") as f:
            f.write(schema.to_json())
        return schema


class ZooRepository(Repository):
    """Default 'remote' repo backed by the builder zoo (reference ``DefaultModelRepo``)."""

    _INPUT_SHAPES = {
        "ResNet18": [1, 3, 224, 224],
        "ResNet50": [1, 3, 224, 224],
        "ResNet101": [1, 3, 224, 224],
        "ViTB16": [1, 3, 224, 224],
        "BERTBase": None,
        "BERTTiny": None,
    }

    def list_schemas(self) -> Iterator[ModelSchema]:
        from ..models.zoo import MODEL_BUILDERS

        for name in sorted(MODEL_BUILDERS):
            input_name = "input_ids" if name.startswith("BERT") else "data"
            feature = "pooled" if name.startswith("BERT") else "features"
            yield ModelSchema(name=name, input_name=input_name, feature_output=feature,
                              input_shape=self._INPUT_SHAPES.get(name))

    def read_bytes(self, schema: ModelSchema) -> bytes:
        from ..models.zoo import build_model_bytes

        return build_model_bytes(schema.name)


class RemoteRepository(Repository):
    """HTTP(S) model repository with hash verification (reference
    ``ModelDownloader.scala:26-263`` — the Azure-blob default repo's
    contract over any static file host).

    Layout: ``<base_url>/index.json`` is a JSON LIST of model schemas
    (:class:`ModelSchema` dicts); each schema's ``path`` is resolved
    relative to ``base_url``. ``read_bytes`` verifies the schema's sha256
    against the fetched payload — the reference's corrupt-download guard.
    Retries ride :func:`synapseml_tpu.io.clients.send_with_retries`, which
    retries ONLY transient statuses (429/5xx/connection errors) — a 404
    fails fast instead of backing off toward an outcome that cannot change.
    """

    def __init__(self, base_url: str, timeout: float = 60.0,
                 backoffs_ms=(200, 400, 800)):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self.backoffs_ms = tuple(backoffs_ms)
        self._index: Optional[List[ModelSchema]] = None

    def _fetch(self, url: str) -> bytes:
        from ..io.clients import send_with_retries
        from ..io.http_schema import HTTPRequestData

        resp = send_with_retries(HTTPRequestData(url=url, method="GET"),
                                 timeout=self.timeout,
                                 backoffs_ms=self.backoffs_ms)
        if resp.status_code != 200:
            raise IOError(f"GET {url} -> {resp.status_code} {resp.reason}")
        return resp.entity or b""

    def list_schemas(self) -> Iterator[ModelSchema]:
        if self._index is None:
            raw = json.loads(self._fetch(self.base_url + "/index.json"))
            self._index = [ModelSchema(**d) for d in raw]
        return iter(self._index)

    def read_bytes(self, schema: ModelSchema) -> bytes:
        url = (schema.path if schema.path.startswith(("http://", "https://"))
               else f"{self.base_url}/{schema.path}")
        data = self._fetch(url)
        if schema.sha256 and _sha256(data) != schema.sha256:
            raise IOError(
                f"hash mismatch for model {schema.name} from {url}: expected "
                f"{schema.sha256[:12]}..., got {_sha256(data)[:12]}... "
                "(corrupt download?)")
        return data


class ModelDownloader:
    """Fetch models from a remote repo into a local one, with caching
    (reference ``ModelDownloader.downloadModel`` / ``downloadByName``)."""

    def __init__(self, local_path: str, remote: Optional[Repository] = None):
        self.local = LocalRepository(local_path)
        self.remote = remote if remote is not None else ZooRepository()

    def remote_models(self) -> List[ModelSchema]:
        return list(self.remote.list_schemas())

    def local_models(self) -> List[ModelSchema]:
        return list(self.local.list_schemas())

    def download_model(self, schema: ModelSchema, always_download: bool = False) -> ModelSchema:
        if not always_download:
            try:
                cached = self.local.get_schema(schema.name)
                self.local.read_bytes(cached)  # hash check
                return cached
            except (KeyError, IOError):
                pass
        data = self.remote.read_bytes(schema)
        return self.local.add(schema, data)

    def download_by_name(self, name: str, always_download: bool = False) -> ModelSchema:
        return self.download_model(self.remote.get_schema(name), always_download)

    def read_bytes(self, name: str) -> bytes:
        try:  # cached: single read + hash check
            return self.local.read_bytes(self.local.get_schema(name))
        except (KeyError, IOError):
            pass
        schema = self.remote.get_schema(name)
        data = self.remote.read_bytes(schema)
        self.local.add(schema, data)
        return data
