"""``ImageFeaturizer`` — headless-CNN image featurization.

Rebuild of ``deep-learning/.../cntk/ImageFeaturizer.scala:40-197``: resize/normalize an
image column, run a vision model, and emit either the penultimate features
(``cut_output_layers=1``, the reference's "headless" mode) or the logits
(``cut_output_layers=0``). The reference chains ResizeImageTransformer → UnrollImage →
CNTKModel; here the backbone is an ONNX graph executed by the XLA importer, and zoo
models expose the feature layer as a named output so no graph surgery is needed.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..core import ComplexParam, Param, Table, Transformer
from ..core.params import ParamValidators
from ..image.stages import ResizeImageTransformer, _to_batch
from ..onnx.model import ONNXModel

__all__ = ["ImageFeaturizer"]

_IMAGENET_MEAN = [0.485, 0.456, 0.406]
_IMAGENET_STD = [0.229, 0.224, 0.225]


class ImageFeaturizer(Transformer):
    input_col = Param("image column", str, default="image")
    output_col = Param("output features column", str, default="features")
    model_name = Param("zoo model name (e.g. ResNet50); ignored if model_bytes set",
                       str, default="ResNet50")
    model_bytes = ComplexParam("explicit ONNX model bytes", bytes, default=None)
    model_dir = Param("local cache dir for downloaded models", str, default="/tmp/synapseml_tpu_models")
    cut_output_layers = Param("1 = penultimate features (headless), 0 = logits", int,
                              default=1, validator=ParamValidators.in_range(0, 1))
    image_height = Param("input height", int, default=224)
    image_width = Param("input width", int, default=224)
    channel_order = Param("channel order of incoming images", str, default="bgr",
                          validator=ParamValidators.in_list(["bgr", "rgb"]))
    scale = Param("pixel pre-scale (1/255 for uint8 input)", float, default=1.0 / 255.0)
    mean = Param("per-channel normalization mean (rgb order)", list, default=_IMAGENET_MEAN)
    std = Param("per-channel normalization std (rgb order)", list, default=_IMAGENET_STD)
    batch_size = Param("inference bucket size", int, default=32, validator=ParamValidators.gt(0))
    dtype_policy = Param("float32 | bfloat16", str, default="float32",
                         validator=ParamValidators.in_list(["float32", "bfloat16"]))

    def __init__(self, uid=None, **kw):
        super().__init__(uid=uid, **kw)
        self._onnx: Optional[ONNXModel] = None

    def _post_load(self):
        self._onnx = None

    def _resolve_model(self):
        if self._onnx is not None:
            return self._onnx
        if self.model_bytes is not None:
            data = self.model_bytes
            input_name, feat, logits = "data", "features", "logits"
        else:
            from .downloader import ModelDownloader

            dl = ModelDownloader(self.model_dir)
            schema = dl.download_by_name(self.model_name)
            data = dl.local.read_bytes(schema)
            input_name, feat, logits = schema.input_name, schema.feature_output, schema.logits_output
        fetch = feat if self.cut_output_layers >= 1 else logits
        self._onnx = ONNXModel(
            feed_dict={input_name: "__img_nchw"},
            fetch_dict={self.output_col: fetch},
            batch_size=self.batch_size,
            dtype_policy=self.dtype_policy,
        ).set_model(data)
        return self._onnx

    def _transform(self, table: Table) -> Table:
        self._validate_input(table, self.input_col)
        resized = ResizeImageTransformer(
            input_col=self.input_col, output_col="__img_r",
            height=self.image_height, width=self.image_width,
        ).transform(table)
        batch = _to_batch(resized["__img_r"]).astype(np.float32)
        if self.channel_order == "bgr":  # zoo models expect RGB
            batch = batch[..., ::-1]
        x = batch * self.scale
        x = (x - np.asarray(self.mean, np.float32)) / np.asarray(self.std, np.float32)
        nchw = np.transpose(x, (0, 3, 1, 2))
        onnx = self._resolve_model()
        with_feed = resized.drop("__img_r").with_column("__img_nchw", nchw)
        out = onnx.transform(with_feed)
        return out.drop("__img_nchw")
