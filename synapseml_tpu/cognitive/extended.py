"""Cognitive tail: geospatial, search writer, multivariate anomaly,
document translation, form ontology, streaming speech.

Reference files (``cognitive/src/main/scala/.../``):
- ``geospatial/AzureMapsSearch.scala`` (``AddressGeocoder``,
  ``ReverseAddressGeocoder``) + ``AzureMapsHelpers.scala`` (``MapsAsyncReply``
  — 202 + Location polling);
- ``cognitive/AzureSearch.scala:85`` (``AddDocuments``) and ``:141-356``
  (``AzureSearchWriter``: batched index upload, filterNulls, actionCol);
- ``cognitive/MultivariateAnomalyDetection.scala:304`` (``FitMultivariateAnomaly``
  estimator -> ``DetectMultivariateAnomaly`` model, train/poll protocol);
- ``cognitive/DocumentTranslator.scala:50`` (batch submission + async reply);
- ``cognitive/FormOntologyLearner.scala:42`` (``combineDataTypes`` ontology
  merge over AnalyzeResponse fields -> ``FormOntologyTransformer``);
- ``cognitive/SpeechToTextSDK.scala:232-339`` (chunked audio streaming; the
  reference drives the native Speech SDK + ffmpeg — here the chunking and
  result merging are explicit and the wire format is the REST endpoint).
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..core import ComplexParam, Estimator, Model, Param, Table, Transformer
from ..core.params import ParamValidators
from ..io.clients import send_with_retries
from ..io.http_schema import HTTPRequestData, HTTPResponseData
from ..core.table import jsonable_value
from .base import CognitiveServiceBase
from .services import _TextAnalyticsBase, _VisionBase

__all__ = [
    "AddressGeocoder", "ReverseAddressGeocoder",
    "AddDocuments", "AzureSearchWriter",
    "FitMultivariateAnomaly", "DetectMultivariateAnomaly",
    "DocumentTranslator",
    "FormOntologyLearner", "FormOntologyTransformer",
    "SpeechToTextSDK",
]


class AsyncPollError(RuntimeError):
    """A 202 poll failed; ``status`` is the failing poll's status code."""

    def __init__(self, message: str, status: Optional[int] = None):
        super().__init__(message)
        self.status = status


class _AsyncReplyMixin:
    """202-Accepted + Location polling (reference ``HasAsyncReply`` /
    ``MapsAsyncReply``, ``AzureMapsHelpers.scala``)."""

    polling_delay = Param("seconds between result polls", float, default=0.3)
    max_polling_retries = Param("max result polls", int, default=100,
                                validator=ParamValidators.gt(0))

    def await_result(self, resp: HTTPResponseData,
                     headers: Optional[Dict[str, str]] = None,
                     location_suffix: str = "") -> HTTPResponseData:
        if resp.status_code != 202:
            return resp
        location = None
        for k, v in (resp.headers or {}).items():
            if k.lower() in ("location", "operation-location"):
                location = v
        if not location:
            raise AsyncPollError("202 reply without a Location header",
                                 status=resp.status_code)
        if location_suffix:
            location += ("&" if "?" in location else "?") + location_suffix
        for _ in range(self.max_polling_retries):
            poll = send_with_retries(HTTPRequestData(
                url=location, method="GET", headers=headers or {}),
                timeout=self.timeout, backoffs_ms=self.backoffs)
            if poll.status_code == 200:
                return poll
            if poll.status_code != 202:
                raise AsyncPollError(
                    f"async poll got status {poll.status_code}: {poll.text!r}",
                    status=poll.status_code)
            time.sleep(self.polling_delay)
        raise TimeoutError(f"async result not ready after "
                           f"{self.max_polling_retries} polls")


class _PerRowAsyncBase(_AsyncReplyMixin, CognitiveServiceBase):
    """Per-row request -> 202 -> poll Operation-Location (the reference's
    ``BasicAsyncReply`` contract). Hooks: ``_poll_suffix`` appends to the
    poll URL's query (e.g. maps re-signing), ``_unwrap`` post-processes the
    parsed success body."""

    _abstract_stage = True

    def _poll_suffix(self, table: Table, row: int) -> str:
        return ""

    def _unwrap(self, parsed):
        return parsed

    def _transform(self, table: Table) -> Table:
        n = table.num_rows
        out = np.empty(n, dtype=object)
        errors = np.empty(n, dtype=object)
        for i in range(n):
            req = self.build_request(table, i)
            if req is None:
                out[i] = errors[i] = None
                continue
            resp = send_with_retries(req, timeout=self.timeout,
                                     backoffs_ms=self.backoffs)
            try:
                resp = self.await_result(
                    resp, headers=req.headers,
                    location_suffix=self._poll_suffix(table, i))
            except (RuntimeError, TimeoutError) as e:
                out[i] = None
                errors[i] = {"statusCode": getattr(e, "status", None),
                             "reason": str(e)}
                continue
            if 200 <= resp.status_code < 300:
                out[i] = self._unwrap(self.parse_response(resp))
                errors[i] = None
            else:
                out[i] = None
                errors[i] = resp.to_dict()
        return (table.with_column(self.output_col, out)
                .with_column(self.error_col, errors))


# ---------------------------------------------------------------------------------
# Geospatial (reference geospatial/AzureMapsSearch.scala)
# ---------------------------------------------------------------------------------

class _AzureMapsBase(_PerRowAsyncBase):
    _abstract_stage = True

    api_version = Param("maps API version", str, default="1.0")

    def build_url(self, table, row):
        if self.url:
            return self.url
        return f"https://atlas.microsoft.com{self.url_path}"

    def build_headers(self, table, row):
        h = super().build_headers(table, row)
        h.pop("Ocp-Apim-Subscription-Key", None)  # maps auth is a query param
        return h

    def build_request(self, table, row):
        req = super().build_request(table, row)
        if req is None:
            return None
        key = self.svc_value(table, row, "subscription_key")
        sep = "&" if "?" in req.url else "?"
        url = f"{req.url}{sep}api-version={self.api_version}"
        if key:
            url += f"&subscription-key={key}"
        return HTTPRequestData(url=url, method=req.method,
                               headers=req.headers, entity=req.entity)

    def _poll_suffix(self, table, row):
        # maps auth rides the query string, on polls too (reference
        # MapsAsyncReply re-signs the status GET)
        key = self.svc_value(table, row, "subscription_key")
        suffix = f"api-version={self.api_version}"
        if key:
            suffix += f"&subscription-key={key}"
        return suffix

    def _unwrap(self, parsed):
        return ((parsed or {}).get("batchItems", parsed)
                if isinstance(parsed, dict) else parsed)


class AddressGeocoder(_AzureMapsBase):
    """Reference ``AddressGeocoder`` (``AzureMapsSearch.scala:22``): batch
    forward geocoding; output column carries the batchItems array."""

    url_path = "/search/address/batch/json"
    address = Param("addresses (static list)", object, default=None)
    address_col = Param("addresses column (list of strings per row)", str,
                        default=None)

    def build_payload(self, table, row):
        addresses = self.svc_value(table, row, "address")
        if addresses is None:
            return None
        from urllib.parse import quote

        items = [{"query": f"?query={quote(str(a))}&limit=1"}
                 for a in addresses]
        return {"batchItems": items}


class ReverseAddressGeocoder(_AzureMapsBase):
    """Reference ``ReverseAddressGeocoder``: (lat, lon) pairs -> addresses."""

    url_path = "/search/address/reverse/batch/json"
    coordinates = Param("list of (lat, lon) pairs (static)", object,
                        default=None)
    coordinates_col = Param("coordinates column", str, default=None)

    def build_payload(self, table, row):
        coords = self.svc_value(table, row, "coordinates")
        if coords is None:
            return None
        items = [{"query": f"?query={lat},{lon}"} for lat, lon in coords]
        return {"batchItems": items}


# ---------------------------------------------------------------------------------
# Azure Search (reference AzureSearch.scala)
# ---------------------------------------------------------------------------------

class AddDocuments(CognitiveServiceBase):
    """Reference ``AddDocuments`` (``AzureSearch.scala:85``): each row's
    document batch posts to the index's docs/index endpoint."""

    service_name = Param("search service name", str, default="")
    index_name = Param("target index", str, default="")
    action_col = Param("per-document action field (reference actionCol)", str,
                       default="@search.action")
    batch_col = Param("column holding a list of document dicts", str,
                      default="documents")
    api_version = Param("search API version", str, default="2019-05-06")

    def build_url(self, table, row):
        if self.url:
            return self.url
        return (f"https://{self.service_name}.search.windows.net/indexes/"
                f"{self.index_name}/docs/index?api-version={self.api_version}")

    def build_headers(self, table, row):
        h = super().build_headers(table, row)
        key = self.svc_value(table, row, "subscription_key")
        if key:
            h["api-key"] = str(key)
            h.pop("Ocp-Apim-Subscription-Key", None)
        return h

    def build_payload(self, table, row):
        docs = table[self.batch_col][row]
        if docs is None:
            return None
        value = []
        for d in docs:
            doc = dict(d)
            doc.setdefault(self.action_col, "upload")
            value.append(doc)
        return {"value": value}


class AzureSearchWriter:
    """Reference ``AzureSearchWriter`` (``AzureSearch.scala:141-356``):
    batches table rows into AddDocuments calls."""

    @staticmethod
    def write(table: Table, *, subscription_key: str, service_name: str = "",
              index_name: str = "", url: str = "", batch_size: int = 100,
              action: str = "upload", filter_nulls: bool = False,
              key_col: Optional[str] = None) -> Table:
        """Upload every row as a document; columns become fields. ``key_col``
        names the index key field — every document must carry it (the
        reference's keyCol validation). Returns a Table of per-batch
        responses."""
        if batch_size <= 0:
            raise ValueError("batch_size must be positive")
        if key_col is not None and key_col not in table:
            raise ValueError(f"key_col {key_col!r} missing from table; "
                             f"available: {table.column_names}")
        cols = table.column_names
        docs: List[Dict[str, Any]] = []
        for i in range(table.num_rows):
            doc = {}
            for c in cols:
                v = jsonable_value(table[c][i])
                if filter_nulls and v is None:
                    continue
                doc[c] = v
            if key_col is not None and doc.get(key_col) is None:
                raise ValueError(
                    f"document {i} has a null index key ({key_col!r})")
            doc["@search.action"] = action
            docs.append(doc)
        batches = [docs[i:i + batch_size]
                   for i in range(0, len(docs), batch_size)]
        batch_col = np.empty(len(batches), dtype=object)
        batch_col[:] = batches
        stage = AddDocuments(subscription_key=subscription_key,
                             service_name=service_name, index_name=index_name,
                             url=url)
        return stage.transform(Table({"documents": batch_col}))


# ---------------------------------------------------------------------------------
# Multivariate anomaly detection (reference MultivariateAnomalyDetection.scala)
# ---------------------------------------------------------------------------------

class _MADBase(_AsyncReplyMixin, CognitiveServiceBase):
    _abstract_stage = True

    start_time = Param("series start (ISO8601)", str, default="")
    end_time = Param("series end (ISO8601)", str, default="")

    def _headers(self):
        h = {"Content-Type": "application/json"}
        if self.subscription_key:
            h["Ocp-Apim-Subscription-Key"] = str(self.subscription_key)
        return h

    def _base_url(self):
        if self.url:
            return self.url.rstrip("/")
        if not self.location:
            raise ValueError(f"{type(self).__name__}({self.uid}): "
                             "set url or location")
        return (f"https://{self.location}.api.cognitive.microsoft.com"
                "/anomalydetector/v1.1-preview/multivariate")


class FitMultivariateAnomaly(_MADBase, Estimator):
    """Reference ``FitMultivariateAnomaly`` (``MultivariateAnomalyDetection.scala:304``):
    submits a training request for a multivariate model, polls the model
    status until ready, and yields :class:`DetectMultivariateAnomaly`."""

    source = Param("blob/data source URI the service trains from", str,
                   default="")
    sliding_window = Param("model sliding window (28-2880)", int, default=300)
    align_mode = Param("Inner | Outer timestamp alignment", str,
                       default="Outer",
                       validator=ParamValidators.in_list(["Inner", "Outer"]))
    fill_na_method = Param("Previous|Subsequent|Linear|Zero|Fixed|NotFill",
                           str, default="Linear")
    padding_value = Param("fill value when fill_na_method=Fixed", float,
                          default=0.0)
    display_name = Param("model display name", str, default="")

    def _fit(self, table: Table) -> "DetectMultivariateAnomaly":
        payload = {
            "source": self.source,
            "startTime": self.start_time,
            "endTime": self.end_time,
            "slidingWindow": self.sliding_window,
            "alignPolicy": {"alignMode": self.align_mode,
                            "fillNAMethod": self.fill_na_method,
                            "paddingValue": self.padding_value},
        }
        if self.display_name:
            payload["displayName"] = self.display_name
        resp = send_with_retries(HTTPRequestData(
            url=self._base_url() + "/models", method="POST",
            headers=self._headers(),
            entity=json.dumps(payload).encode()),
            timeout=self.timeout, backoffs_ms=self.backoffs)
        if resp.status_code not in (200, 201, 202):
            raise RuntimeError(f"model submission failed: {resp.status_code} "
                               f"{resp.text!r}")
        location = None
        for k, v in (resp.headers or {}).items():
            if k.lower() == "location":
                location = v
        model_id = (location or "").rstrip("/").rsplit("/", 1)[-1]
        # poll modelInfo until ready (reference blocks in fit the same way)
        for _ in range(self.max_polling_retries):
            info = send_with_retries(HTTPRequestData(
                url=self._base_url() + f"/models/{model_id}", method="GET",
                headers=self._headers()),
                timeout=self.timeout, backoffs_ms=self.backoffs)
            body = json.loads(info.text or "{}")
            status = (body.get("modelInfo") or {}).get("status", "")
            if status.upper() == "READY":
                break
            if status.upper() == "FAILED":
                raise RuntimeError(f"model training failed: {body}")
            time.sleep(self.polling_delay)
        else:
            raise TimeoutError("model not READY after max_polling_retries")
        return DetectMultivariateAnomaly(
            model_id=model_id, url=self.url, location=self.location,
            subscription_key=self.subscription_key,
            start_time=self.start_time, end_time=self.end_time,
            output_col=self.output_col, error_col=self.error_col,
            polling_delay=self.polling_delay,
            max_polling_retries=self.max_polling_retries)


class DetectMultivariateAnomaly(_MADBase, Model):
    """Reference ``DetectMultivariateAnomaly`` (``MultivariateAnomalyDetection.scala:431``):
    submits inference against a trained model id and polls for results."""

    model_id = Param("trained model uuid", str, default="")
    source = Param("blob/data source URI to score", str, default="")

    def _transform(self, table: Table) -> Table:
        payload = {"source": self.source or None,
                   "startTime": self.start_time, "endTime": self.end_time}
        resp = send_with_retries(HTTPRequestData(
            url=self._base_url() + f"/models/{self.model_id}/detect",
            method="POST", headers=self._headers(),
            entity=json.dumps(payload).encode()),
            timeout=self.timeout, backoffs_ms=self.backoffs)
        resp = self.await_result(resp, headers=self._headers())
        body = json.loads(resp.text or "{}")
        results = (body.get("results")
                   or body.get("result", {}).get("results") or [])
        by_ts = {r.get("timestamp"): r for r in results}
        n = table.num_rows
        out = np.empty(n, dtype=object)
        ts_col = "timestamp" if "timestamp" in table else None
        for i in range(n):
            out[i] = (by_ts.get(str(table[ts_col][i])) if ts_col
                      else (results[i] if i < len(results) else None))
        return table.with_column(self.output_col, out)


# ---------------------------------------------------------------------------------
# Document translation (reference DocumentTranslator.scala)
# ---------------------------------------------------------------------------------

class DocumentTranslator(_AsyncReplyMixin, CognitiveServiceBase):
    """Reference ``DocumentTranslator`` (``DocumentTranslator.scala:50``):
    batch document translation — submit source/target containers, 202-poll
    the batch operation until done."""

    service_name = Param("translator resource name", str, default="")
    source_url = Param("source container URL (static)", object, default=None)
    source_url_col = Param("source container URL column", str, default=None)
    source_language = Param("source language (None = autodetect)", object,
                            default=None)
    filter_prefix = Param("only translate blobs with this prefix", object,
                          default=None)
    targets = Param("list of {targetUrl, language} dicts (static)", object,
                    default=None)
    targets_col = Param("targets column", str, default=None)

    def build_url(self, table, row):
        if self.url:
            return self.url
        return (f"https://{self.service_name}.cognitiveservices.azure.com"
                "/translator/text/batch/v1.0/batches")

    def build_payload(self, table, row):
        source_url = self.svc_value(table, row, "source_url")
        targets = self.svc_value(table, row, "targets")
        if source_url is None or not targets:
            return None
        source: Dict[str, Any] = {"sourceUrl": source_url}
        if self.source_language:
            source["language"] = self.source_language
        if self.filter_prefix:
            source["filter"] = {"prefix": self.filter_prefix}
        return {"inputs": [{
            "source": source,
            "targets": [{"targetUrl": t["targetUrl"],
                         "language": t["language"]} for t in targets],
        }]}

    def _transform(self, table: Table) -> Table:
        n = table.num_rows
        out = np.empty(n, dtype=object)
        errors = np.empty(n, dtype=object)
        for i in range(n):
            req = self.build_request(table, i)
            if req is None:
                out[i] = errors[i] = None
                continue
            resp = send_with_retries(req, timeout=self.timeout,
                                     backoffs_ms=self.backoffs)
            try:
                resp = self.await_result(resp,
                                         headers=self.build_headers(table, i))
                out[i] = self.parse_response(resp)
                errors[i] = None
            except (RuntimeError, TimeoutError) as e:
                out[i] = None
                errors[i] = {"statusCode": getattr(e, "status", None),
                             "reason": str(e)}
        return (table.with_column(self.output_col, out)
                .with_column(self.error_col, errors))


# ---------------------------------------------------------------------------------
# Form ontology (reference FormOntologyLearner.scala)
# ---------------------------------------------------------------------------------

def _combine_types(a, b):
    """Merge two observed field 'types' (reference ``combineDataTypes``):
    scalars widen to their union; dicts merge recursively; lists merge
    element types."""
    if a is None:
        return b
    if b is None:
        return a
    if isinstance(a, dict) and isinstance(b, dict):
        return {k: _combine_types(a.get(k), b.get(k))
                for k in {*a, *b}}
    if isinstance(a, list) and isinstance(b, list):
        ea = a[0] if a else None
        eb = b[0] if b else None
        merged = _combine_types(ea, eb)
        return [merged] if merged is not None else []
    if isinstance(a, (dict, list)) or isinstance(b, (dict, list)):
        return "string"  # structured vs scalar across documents: widen
    if a == b:
        return a
    if {a, b} <= {"integer", "number"}:
        return "number"
    return "string"  # incompatible scalars widen to string


def _field_type(value):
    if isinstance(value, dict):
        if "valueObject" in value:
            return {k: _field_type(v)
                    for k, v in value["valueObject"].items()}
        if "valueArray" in value:
            elems = [_field_type(v) for v in value["valueArray"]]
            merged = None
            for e in elems:
                merged = _combine_types(merged, e)
            return [merged] if merged is not None else []
        for k in ("valueNumber", "valueInteger", "valueDate", "valueTime",
                  "valueString", "valuePhoneNumber", "text"):
            if k in value:
                return {"valueNumber": "number", "valueInteger": "integer",
                        }.get(k, "string")
        return "string"
    if isinstance(value, bool):
        return "string"
    if isinstance(value, (int, np.integer)):
        return "integer"
    if isinstance(value, (float, np.floating)):
        return "number"
    return "string"


def _field_value(value):
    if isinstance(value, dict):
        if "valueObject" in value:
            return {k: _field_value(v)
                    for k, v in value["valueObject"].items()}
        if "valueArray" in value:
            return [_field_value(v) for v in value["valueArray"]]
        for k in ("valueNumber", "valueInteger", "valueDate", "valueTime",
                  "valueString", "valuePhoneNumber", "text"):
            if k in value:
                return value[k]
        return None
    return value


class FormOntologyLearner(Estimator):
    """Reference ``FormOntologyLearner`` (``FormOntologyLearner.scala:42``):
    aggregates the per-document field schemas of FormRecognizer analyze
    responses into one merged ontology; the fitted transformer projects each
    document onto it."""

    input_col = Param("column of AnalyzeResponse dicts", str, default="form")
    output_col = Param("extracted ontology-struct column", str, default="out")

    @staticmethod
    def _doc_fields(response) -> Dict[str, Any]:
        if not isinstance(response, dict):
            return {}
        ar = response.get("analyzeResult") or {}
        fields: Dict[str, Any] = {}
        for doc in ar.get("documentResults") or ar.get("documents") or []:
            fields.update(doc.get("fields") or {})
        return fields

    def _fit(self, table: Table) -> "FormOntologyTransformer":
        self._validate_input(table, self.input_col)
        ontology: Optional[Dict[str, Any]] = None
        for i in range(table.num_rows):
            fields = self._doc_fields(table[self.input_col][i])
            doc_type = {k: _field_type(v) for k, v in fields.items()}
            ontology = _combine_types(ontology, doc_type)
        return FormOntologyTransformer(
            input_col=self.input_col, output_col=self.output_col,
            ontology=ontology or {})


class FormOntologyTransformer(Model):
    """Reference ``FormOntologyTransformer`` (``FormOntologyLearner.scala:84``)."""

    input_col = Param("column of AnalyzeResponse dicts", str, default="form")
    output_col = Param("extracted ontology-struct column", str, default="out")
    ontology = ComplexParam("merged field-name -> type tree", dict,
                            default=None)

    def _transform(self, table: Table) -> Table:
        self._validate_input(table, self.input_col)
        n = table.num_rows
        out = np.empty(n, dtype=object)
        for i in range(n):
            fields = FormOntologyLearner._doc_fields(table[self.input_col][i])
            out[i] = {name: _field_value(fields.get(name))
                      for name in (self.ontology or {})}
        return table.with_column(self.output_col, out)


# ---------------------------------------------------------------------------------
# Streaming speech (reference SpeechToTextSDK.scala)
# ---------------------------------------------------------------------------------

class SpeechToTextSDK(CognitiveServiceBase):
    """Chunked-streaming speech transcription.

    Reference ``SpeechToTextSDK.scala:232-339`` pulls fixed-size audio chunks
    (``PullAudioInputStream``) through the native SDK — converting arbitrary
    input streams with an ffmpeg subprocess first (``:232-269``) — and
    concatenates per-utterance results. Here each audio column value is
    transcoded to canonical 16 kHz mono PCM (``cognitive.audio``: ffmpeg
    pipes for compressed formats, a built-in numpy path for WAV) and then
    streams to the REST endpoint in ``chunk_size`` pieces (sequential
    requests sharing one connection id); the per-chunk DisplayText results
    merge in order."""

    audio_col = Param("audio bytes column", str, default="audio")
    language = Param("recognition language", str, default="en-US")
    format = Param("simple | detailed", str, default="simple",
                   validator=ParamValidators.in_list(["simple", "detailed"]))
    chunk_size = Param("streaming chunk bytes", int, default=32768,
                       validator=ParamValidators.gt(0))
    audio_format = Param("input audio format: auto (sniff WAV, ffmpeg for "
                         "the rest) | wav | mp3 | ogg | flac | ... "
                         "(reference fileType / ffmpeg path)", str,
                         default="auto")
    transcode = Param("convert input to 16 kHz mono 16-bit WAV before "
                      "streaming (reference's ffmpeg conversion; off sends "
                      "raw bytes)", bool, default=True)

    url_path = "/speech/recognition/conversation/cognitiveservices/v1"
    _service_domain = "stt.speech.microsoft.com"

    def build_url(self, table, row):
        base = super().build_url(table, row)
        return f"{base}?language={self.language}&format={self.format}"

    def build_headers(self, table, row):
        h = super().build_headers(table, row)
        h["Content-Type"] = "audio/wav"
        return h

    def _transform(self, table: Table) -> Table:
        self._validate_input(table, self.audio_col)
        n = table.num_rows
        out = np.empty(n, dtype=object)
        errors = np.empty(n, dtype=object)
        for i in range(n):
            audio = table[self.audio_col][i]
            if audio is None:
                out[i] = errors[i] = None
                continue
            audio = bytes(audio)
            if self.transcode:
                from .audio import transcode_to_wav

                try:
                    audio = transcode_to_wav(audio,
                                             src_format=self.audio_format)
                except Exception as e:
                    # a bad row lands in the errors column like a failed
                    # HTTP chunk does — it must not abort the whole batch
                    out[i] = None
                    errors[i] = {"status_code": 0,
                                 "reason": f"transcode failed: {e}"}
                    continue
            chunks = [audio[o:o + self.chunk_size]
                      for o in range(0, len(audio), self.chunk_size)] or [b""]
            texts: List[str] = []
            err = None
            headers = self.build_headers(table, i)
            headers["X-ConnectionId"] = f"{self.uid}-{i}"
            for ci, chunk in enumerate(chunks):
                headers["X-Chunk-Index"] = str(ci)
                headers["X-Chunk-Count"] = str(len(chunks))
                resp = send_with_retries(HTTPRequestData(
                    url=self.build_url(table, i), method="POST",
                    headers=dict(headers), entity=chunk),
                    timeout=self.timeout, backoffs_ms=self.backoffs)
                if not 200 <= resp.status_code < 300:
                    err = resp.to_dict()
                    break
                body = self.parse_response(resp) or {}
                text = (body.get("DisplayText")
                        if isinstance(body, dict) else None)
                if text:
                    texts.append(text)
            out[i] = None if err else {"DisplayText": " ".join(texts)}
            errors[i] = err
        return (table.with_column(self.output_col, out)
                .with_column(self.error_col, errors))


# ---------------------------------------------------------------------------------
# Async text analytics / vision (reference TextAnalytics.scala:482,
# ComputerVision.scala:358 — BasicAsyncReply services)
# ---------------------------------------------------------------------------------

class TextAnalyze(_PerRowAsyncBase, _TextAnalyticsBase):
    """Multi-task text analysis in one call (reference ``TextAnalyze``,
    ``TextAnalytics.scala:482``): entity recognition / linking / PII / key
    phrases / sentiment tasks over the async /analyze endpoint. Document
    construction (text/language params) comes from ``_TextAnalyticsBase``."""

    url_path = "/text/analytics/v3.1/analyze"
    entity_recognition_tasks = Param("task params list", list, default=[{}])
    entity_linking_tasks = Param("task params list", list, default=[])
    entity_recognition_pii_tasks = Param("task params list", list, default=[])
    key_phrase_extraction_tasks = Param("task params list", list, default=[])
    sentiment_analysis_tasks = Param("task params list", list, default=[])

    def build_payload(self, table: Table, row: int):
        docs = _TextAnalyticsBase.build_payload(self, table, row)
        if docs is None:
            return None
        tasks = {}
        for key, plist in [
            ("entityRecognitionTasks", self.entity_recognition_tasks),
            ("entityLinkingTasks", self.entity_linking_tasks),
            ("entityRecognitionPiiTasks", self.entity_recognition_pii_tasks),
            ("keyPhraseExtractionTasks", self.key_phrase_extraction_tasks),
            ("sentimentAnalysisTasks", self.sentiment_analysis_tasks),
        ]:
            if plist:
                tasks[key] = [{"parameters": dict(p)} for p in plist]
        return {"displayName": self.uid, "analysisInput": docs,
                "tasks": tasks}


class RecognizeText(_PerRowAsyncBase, _VisionBase):
    """Async printed/handwritten text recognition (reference
    ``RecognizeText``, ``ComputerVision.scala:358``). Image input handling
    (url/bytes params, octet-stream header) comes from ``_VisionBase``."""

    url_path = "/vision/v2.0/recognizeText"
    mode = Param("'Printed' | 'Handwritten'", str, default="Printed",
                 validator=ParamValidators.in_list(["Printed", "Handwritten"]))

    def build_url(self, table, row):
        return super().build_url(table, row) + f"?mode={self.mode}"


class ConversationTranscription(SpeechToTextSDK):
    """Multi-speaker conversation transcription (reference
    ``ConversationTranscription``, ``SpeechToTextSDK.scala`` — the second
    SDK-streaming class, adding speaker diarization over the same chunked
    audio path)."""

    url_path = "/speech/recognition/conversation/cognitiveservices/v1"

    def build_url(self, table, row):
        return super().build_url(table, row) + "&diarizationEnabled=true"


__all__ += ["TextAnalyze", "RecognizeText", "ConversationTranscription",
            "AsyncPollError"]
