"""The cognitive-service transformer family.

Reference: ``cognitive/src/main/scala/.../cognitive/`` — ~40 transformers over
HTTP-on-Spark (SURVEY.md §2.4): ``TextAnalytics.scala`` (622 LoC),
``ComputerVision.scala`` (630), ``Face.scala`` (351), ``TextTranslator.scala``
(550), ``AnomalyDetection.scala`` (249), ``FormRecognizer.scala`` (353),
``BingImageSearch.scala`` (309), ``SpeechToText.scala``. Each stage is a thin
payload/URL builder on :class:`CognitiveServiceBase`; value-or-column service
params mirror the reference's ``setX``/``setXCol`` pairs.
"""

from __future__ import annotations

import base64 as _b64
import json
import urllib.parse
from typing import Any, Dict, Optional

from ..core import Param, Table
from ..core.params import ParamValidators
from ..io.http_schema import HTTPRequestData
from .base import CognitiveServiceBase

__all__ = [
    # text analytics
    "TextSentiment", "LanguageDetector", "EntityDetector", "KeyPhraseExtractor",
    "NER", "PII",
    # translator
    "Translate", "Transliterate", "DetectLanguage", "BreakSentence",
    "DictionaryLookup",
    # vision
    "AnalyzeImage", "DescribeImage", "OCR", "ReadImage", "TagImage",
    "GenerateThumbnails", "RecognizeDomainSpecificContent",
    # face
    "DetectFace", "FindSimilarFace", "GroupFaces", "IdentifyFaces", "VerifyFaces",
    # anomaly
    "DetectLastAnomaly", "DetectAnomalies", "SimpleDetectAnomalies",
    # speech / search / form
    "SpeechToText", "TextToSpeech", "BingImageSearch",
    "AnalyzeLayout", "AnalyzeReceipts", "AnalyzeBusinessCards",
    "AnalyzeInvoices", "AnalyzeIDDocuments",
]


# ---------------------------------------------------------------------------------
# Text analytics (reference TextAnalytics.scala; v3.1 documents API)
# ---------------------------------------------------------------------------------

class _TextAnalyticsBase(CognitiveServiceBase):
    _abstract_stage = True

    text = Param("text (static value)", object, default=None)
    text_col = Param("text column", str, default="text")
    language = Param("document language (static)", object, default="en")
    language_col = Param("language column", str, default=None)

    def build_payload(self, table: Table, row: int):
        text = self.svc_value(table, row, "text")
        if text is None:
            return None
        lang = self.svc_value(table, row, "language")
        doc: Dict[str, Any] = {"id": "0", "text": str(text)}
        if lang:
            doc["language"] = str(lang)
        return {"documents": [doc]}


class TextSentiment(_TextAnalyticsBase):
    """Reference ``TextSentiment`` (``TextAnalytics.scala``)."""

    url_path = "/text/analytics/v3.1/sentiment"
    opinion_mining = Param("include opinion mining", bool, default=False)

    def build_url(self, table, row):
        u = super().build_url(table, row)
        return u + ("?opinionMining=true" if self.opinion_mining else "")


class LanguageDetector(_TextAnalyticsBase):
    url_path = "/text/analytics/v3.1/languages"

    def build_payload(self, table: Table, row: int):
        text = self.svc_value(table, row, "text")
        if text is None:
            return None
        return {"documents": [{"id": "0", "text": str(text)}]}


class EntityDetector(_TextAnalyticsBase):
    url_path = "/text/analytics/v3.1/entities/linking"


class KeyPhraseExtractor(_TextAnalyticsBase):
    url_path = "/text/analytics/v3.1/keyPhrases"


class NER(_TextAnalyticsBase):
    url_path = "/text/analytics/v3.1/entities/recognition/general"


class PII(_TextAnalyticsBase):
    url_path = "/text/analytics/v3.1/entities/recognition/pii"


# ---------------------------------------------------------------------------------
# Translator (reference TextTranslator.scala; api.cognitive.microsofttranslator.com)
# ---------------------------------------------------------------------------------

class _TranslatorBase(CognitiveServiceBase):
    _abstract_stage = True
    _service_domain = "api.cognitive.microsofttranslator.com"

    text = Param("text (static)", object, default=None)
    text_col = Param("text column", str, default="text")
    api_version = Param("API version", str, default="3.0")

    def _query(self, table: Table, row: int) -> Dict[str, str]:
        return {"api-version": self.api_version}

    def build_url(self, table, row):
        if self.url:
            base = self.url
        else:
            base = f"https://{self._service_domain}{self.url_path}"
        return base + "?" + urllib.parse.urlencode(self._query(table, row),
                                                   doseq=True)

    def build_headers(self, table, row):
        h = super().build_headers(table, row)
        if self.location:  # translator wants the region as its own header
            h["Ocp-Apim-Subscription-Region"] = self.location
        return h

    def build_payload(self, table: Table, row: int):
        text = self.svc_value(table, row, "text")
        if text is None:
            return None
        texts = text if isinstance(text, (list, tuple)) else [text]
        return [{"Text": str(t)} for t in texts]


class Translate(_TranslatorBase):
    url_path = "/translate"
    to_language = Param("target language(s)", object, default=["en"])
    from_language = Param("source language (autodetect if unset)", object,
                          default=None)

    def _query(self, table, row):
        q = super()._query(table, row)
        to = self.to_language
        q["to"] = list(to) if isinstance(to, (list, tuple)) else [to]
        if self.from_language:
            q["from"] = self.from_language
        return q


class Transliterate(_TranslatorBase):
    url_path = "/transliterate"
    language = Param("language of the text", object, default="ja")
    from_script = Param("source script", object, default="Jpan")
    to_script = Param("target script", object, default="Latn")

    def _query(self, table, row):
        q = super()._query(table, row)
        q.update({"language": self.language, "fromScript": self.from_script,
                  "toScript": self.to_script})
        return q


class DetectLanguage(_TranslatorBase):
    url_path = "/detect"


class BreakSentence(_TranslatorBase):
    url_path = "/breaksentence"


class DictionaryLookup(_TranslatorBase):
    url_path = "/dictionary/lookup"
    from_language = Param("source language", object, default="en")
    to_language = Param("target language", object, default="es")

    def _query(self, table, row):
        q = super()._query(table, row)
        q.update({"from": self.from_language, "to": self.to_language})
        return q


# ---------------------------------------------------------------------------------
# Computer vision (reference ComputerVision.scala; v3.2)
# ---------------------------------------------------------------------------------

class _VisionBase(CognitiveServiceBase):
    _abstract_stage = True

    image_url = Param("image URL (static)", object, default=None)
    image_url_col = Param("image URL column", str, default=None)
    image_bytes = Param("image bytes (static)", object, default=None)
    image_bytes_col = Param("image bytes column", str, default=None)

    def build_payload(self, table: Table, row: int):
        img = self.svc_value(table, row, "image_bytes")
        if img is not None:
            return bytes(img)
        url = self.svc_value(table, row, "image_url")
        if url is None:
            return None
        return {"url": str(url)}

    def build_headers(self, table, row):
        h = super().build_headers(table, row)
        if self.svc_value(table, row, "image_bytes") is not None:
            h["Content-Type"] = "application/octet-stream"
        return h


class AnalyzeImage(_VisionBase):
    url_path = "/vision/v3.2/analyze"
    visual_features = Param("features: Categories,Tags,Description,Faces,...",
                            list, default=["Categories"])
    details = Param("details: Celebrities,Landmarks", list, default=[])
    language = Param("result language", object, default="en")

    def build_url(self, table, row):
        q = {"visualFeatures": ",".join(self.visual_features),
             "language": self.language}
        if self.details:
            q["details"] = ",".join(self.details)
        return super().build_url(table, row) + "?" + urllib.parse.urlencode(q)


class DescribeImage(_VisionBase):
    url_path = "/vision/v3.2/describe"
    max_candidates = Param("caption candidates", int, default=1)

    def build_url(self, table, row):
        return (super().build_url(table, row)
                + f"?maxCandidates={self.max_candidates}")


class OCR(_VisionBase):
    url_path = "/vision/v3.2/ocr"
    detect_orientation = Param("detect orientation", bool, default=True)

    def build_url(self, table, row):
        return (super().build_url(table, row)
                + f"?detectOrientation={str(self.detect_orientation).lower()}")


class ReadImage(_VisionBase):
    url_path = "/vision/v3.2/read/analyze"


class TagImage(_VisionBase):
    url_path = "/vision/v3.2/tag"


class GenerateThumbnails(_VisionBase):
    url_path = "/vision/v3.2/generateThumbnail"
    width = Param("thumbnail width", int, default=64)
    height = Param("thumbnail height", int, default=64)
    smart_cropping = Param("smart cropping", bool, default=True)

    def build_url(self, table, row):
        q = {"width": self.width, "height": self.height,
             "smartCropping": str(self.smart_cropping).lower()}
        return super().build_url(table, row) + "?" + urllib.parse.urlencode(q)

    def parse_response(self, resp):
        return resp.entity  # binary thumbnail


class RecognizeDomainSpecificContent(_VisionBase):
    model = Param("domain model, e.g. celebrities", str, default="celebrities")

    def build_url(self, table, row):
        self_url = self.url
        if self_url:
            return self_url
        return (f"https://{self.location}.{self._service_domain}"
                f"/vision/v3.2/models/{self.model}/analyze")


# ---------------------------------------------------------------------------------
# Face (reference Face.scala; v1.0)
# ---------------------------------------------------------------------------------

class DetectFace(_VisionBase):
    url_path = "/face/v1.0/detect"
    return_face_id = Param("return face ids", bool, default=True)
    return_face_landmarks = Param("return landmarks", bool, default=False)
    return_face_attributes = Param("attribute list", list, default=[])

    def build_url(self, table, row):
        q = {"returnFaceId": str(self.return_face_id).lower(),
             "returnFaceLandmarks": str(self.return_face_landmarks).lower()}
        if self.return_face_attributes:
            q["returnFaceAttributes"] = ",".join(self.return_face_attributes)
        return super().build_url(table, row) + "?" + urllib.parse.urlencode(q)


class _FaceJSONBase(CognitiveServiceBase):
    _abstract_stage = True

    def _payload_from_params(self, table, row, names) -> Optional[dict]:
        out = {}
        for snake, wire in names.items():
            v = self.svc_value(table, row, snake)
            if v is not None:
                out[wire] = v.tolist() if hasattr(v, "tolist") else v
        return out or None


class FindSimilarFace(_FaceJSONBase):
    url_path = "/face/v1.0/findsimilars"
    face_id = Param("query face id (static)", object, default=None)
    face_id_col = Param("query face id column", str, default=None)
    face_ids = Param("candidate face ids (static)", object, default=None)
    face_ids_col = Param("candidate ids column", str, default=None)
    max_num_of_candidates = Param("max candidates returned", int, default=20)

    def build_payload(self, table, row):
        p = self._payload_from_params(
            table, row, {"face_id": "faceId", "face_ids": "faceIds"})
        if p:
            p["maxNumOfCandidatesReturned"] = self.max_num_of_candidates
        return p


class GroupFaces(_FaceJSONBase):
    url_path = "/face/v1.0/group"
    face_ids = Param("face ids (static)", object, default=None)
    face_ids_col = Param("face ids column", str, default=None)

    def build_payload(self, table, row):
        return self._payload_from_params(table, row, {"face_ids": "faceIds"})


class IdentifyFaces(_FaceJSONBase):
    url_path = "/face/v1.0/identify"
    face_ids = Param("face ids (static)", object, default=None)
    face_ids_col = Param("face ids column", str, default=None)
    person_group_id = Param("person group", object, default=None)

    def build_payload(self, table, row):
        p = self._payload_from_params(table, row, {"face_ids": "faceIds"})
        if p and self.person_group_id:
            p["personGroupId"] = self.person_group_id
        return p


class VerifyFaces(_FaceJSONBase):
    url_path = "/face/v1.0/verify"
    face_id1 = Param("first face id (static)", object, default=None)
    face_id1_col = Param("first face id column", str, default=None)
    face_id2 = Param("second face id (static)", object, default=None)
    face_id2_col = Param("second face id column", str, default=None)

    def build_payload(self, table, row):
        return self._payload_from_params(
            table, row, {"face_id1": "faceId1", "face_id2": "faceId2"})


# ---------------------------------------------------------------------------------
# Anomaly detection (reference AnomalyDetection.scala; v1.0 series API)
# ---------------------------------------------------------------------------------

class _AnomalyBase(CognitiveServiceBase):
    _abstract_stage = True

    series = Param("time series [{timestamp, value}, ...] (static)", object,
                   default=None)
    series_col = Param("series column", str, default="series")
    granularity = Param("granularity: yearly|monthly|weekly|daily|hourly|"
                        "minutely", str, default="monthly")
    max_anomaly_ratio = Param("max anomaly ratio", float, default=0.25)
    sensitivity = Param("sensitivity 0-99", int, default=95)

    def build_payload(self, table: Table, row: int):
        series = self.svc_value(table, row, "series")
        if series is None:
            return None
        pts = [dict(p) for p in series]
        return {"series": pts, "granularity": self.granularity,
                "maxAnomalyRatio": self.max_anomaly_ratio,
                "sensitivity": self.sensitivity}


class DetectLastAnomaly(_AnomalyBase):
    url_path = "/anomalydetector/v1.0/timeseries/last/detect"


class DetectAnomalies(_AnomalyBase):
    url_path = "/anomalydetector/v1.0/timeseries/entire/detect"


class SimpleDetectAnomalies(_AnomalyBase):
    """Reference ``SimpleDetectAnomalies``: rows hold (timestamp, value, group);
    series are assembled per group and the per-point verdict is joined back."""

    url_path = "/anomalydetector/v1.0/timeseries/entire/detect"
    timestamp_col = Param("timestamp column", str, default="timestamp")
    value_col = Param("value column", str, default="value")
    group_col = Param("series grouping column", str, default="group")

    def _transform(self, table: Table) -> Table:
        import numpy as np

        self._validate_input(table, self.timestamp_col, self.value_col,
                             self.group_col)
        groups = np.asarray(table[self.group_col])
        ts = table[self.timestamp_col]
        vals = table[self.value_col]
        out = np.empty(table.num_rows, dtype=object)
        errors = np.empty(table.num_rows, dtype=object)
        from ..io.clients import send_with_retries

        for g in np.unique(groups):
            rows = np.nonzero(groups == g)[0]
            order = rows[np.argsort(np.asarray(ts, dtype=object)[rows])]
            series = [{"timestamp": str(ts[i]), "value": float(vals[i])}
                      for i in order]
            payload = {"series": series, "granularity": self.granularity,
                       "maxAnomalyRatio": self.max_anomaly_ratio,
                       "sensitivity": self.sensitivity}
            from ..io.http_schema import HTTPRequestData

            req = HTTPRequestData(
                url=self.build_url(table, int(order[0])), method="POST",
                headers=self.build_headers(table, int(order[0])),
                entity=json.dumps(payload).encode())
            resp = send_with_retries(req, self.timeout, self.backoffs)
            if 200 <= resp.status_code < 300:
                parsed = self.parse_response(resp)
                if not isinstance(parsed, dict):  # non-JSON 2xx body
                    for i in order:
                        out[i] = None
                        errors[i] = resp.to_dict()
                    continue
                flags = parsed.get("isAnomaly", [])
                for k, i in enumerate(order):
                    out[i] = {"isAnomaly": flags[k] if k < len(flags) else None}
                    errors[i] = None
            else:
                for i in order:
                    out[i] = None
                    errors[i] = resp.to_dict()
        return (table.with_column(self.output_col, out)
                .with_column(self.error_col, errors))


# ---------------------------------------------------------------------------------
# Speech (reference SpeechToText.scala / TextToSpeech.scala; REST short-audio API)
# ---------------------------------------------------------------------------------

class SpeechToText(CognitiveServiceBase):
    _service_domain = "stt.speech.microsoft.com"
    url_path = "/speech/recognition/conversation/cognitiveservices/v1"

    audio_data = Param("audio bytes (static)", object, default=None)
    audio_data_col = Param("audio bytes column", str, default="audio")
    audio_format = Param("Content-Type of the audio", str,
                         default="audio/wav; codecs=audio/pcm; samplerate=16000")
    language = Param("recognition language", object, default="en-US")

    def build_url(self, table, row):
        base = self.url or (f"https://{self.location}.{self._service_domain}"
                            f"{self.url_path}")
        return base + "?" + urllib.parse.urlencode({"language": self.language})

    def build_headers(self, table, row):
        h = super().build_headers(table, row)
        h["Content-Type"] = self.audio_format
        h["Accept"] = "application/json"
        return h

    def build_payload(self, table: Table, row: int):
        audio = self.svc_value(table, row, "audio_data")
        return bytes(audio) if audio is not None else None


class TextToSpeech(CognitiveServiceBase):
    _service_domain = "tts.speech.microsoft.com"
    url_path = "/cognitiveservices/v1"

    text = Param("text to speak (static)", object, default=None)
    text_col = Param("text column", str, default="text")
    voice_name = Param("voice", str, default="en-US-JennyNeural")
    language = Param("language", str, default="en-US")
    output_format = Param("X-Microsoft-OutputFormat", str,
                          default="riff-16khz-16bit-mono-pcm")

    def build_headers(self, table, row):
        h = super().build_headers(table, row)
        h["Content-Type"] = "application/ssml+xml"
        h["X-Microsoft-OutputFormat"] = self.output_format
        return h

    def build_payload(self, table: Table, row: int):
        from xml.sax.saxutils import escape, quoteattr

        text = self.svc_value(table, row, "text")
        if text is None:
            return None
        ssml = (f"<speak version='1.0' xml:lang={quoteattr(str(self.language))}>"
                f"<voice name={quoteattr(str(self.voice_name))}>"
                f"{escape(str(text))}</voice></speak>")
        return ssml.encode()

    def parse_response(self, resp):
        return resp.entity  # audio bytes


# ---------------------------------------------------------------------------------
# Bing image search (reference BingImageSearch.scala)
# ---------------------------------------------------------------------------------

class BingImageSearch(CognitiveServiceBase):
    _service_domain = "api.bing.microsoft.com"
    url_path = "/v7.0/images/search"

    query = Param("search query (static)", object, default=None)
    query_col = Param("query column", str, default=None)
    count = Param("results per query", int, default=10)
    offset = Param("result offset", int, default=0)

    def build_url(self, table, row):
        base = self.url or f"https://{self._service_domain}{self.url_path}"
        q = self.svc_value(table, row, "query")
        return base + "?" + urllib.parse.urlencode(
            {"q": q, "count": self.count, "offset": self.offset})

    def build_request(self, table, row):
        from ..io.http_schema import HTTPRequestData

        q = self.svc_value(table, row, "query")
        if q is None:
            return None
        headers = self.build_headers(table, row)
        headers.pop("Content-Type", None)
        return HTTPRequestData(url=self.build_url(table, row), method="GET",
                               headers=headers)

    def build_payload(self, table, row):  # GET carries no body
        return None

    @staticmethod
    def download_from_urls(table: Table, url_col: str, out_col: str = "image",
                           concurrency: int = 8) -> Table:
        """Reference helper ``BingImageSearch.downloadFromUrls``."""
        import numpy as np

        from ..io.clients import AsyncHTTPClient
        from ..io.http_schema import HTTPRequestData

        urls = table[url_col]
        reqs = [None if u is None else HTTPRequestData(url=str(u), method="GET")
                for u in urls]
        resps = AsyncHTTPClient(concurrency=concurrency).send_all(reqs)
        out = np.empty(len(urls), dtype=object)
        for i, r in enumerate(resps):
            out[i] = r.entity if (r is not None and r.status_code == 200) else None
        return table.with_column(out_col, out)


# ---------------------------------------------------------------------------------
# Form recognizer (reference FormRecognizer.scala; v2.1 analyze APIs)
# ---------------------------------------------------------------------------------

class _FormRecognizerBase(_VisionBase):
    _abstract_stage = True


class AnalyzeLayout(_FormRecognizerBase):
    url_path = "/formrecognizer/v2.1/layout/analyze"


class AnalyzeReceipts(_FormRecognizerBase):
    url_path = "/formrecognizer/v2.1/prebuilt/receipt/analyze"


class AnalyzeBusinessCards(_FormRecognizerBase):
    url_path = "/formrecognizer/v2.1/prebuilt/businessCard/analyze"


class AnalyzeInvoices(_FormRecognizerBase):
    url_path = "/formrecognizer/v2.1/prebuilt/invoice/analyze"


class AnalyzeIDDocuments(_FormRecognizerBase):
    url_path = "/formrecognizer/v2.1/prebuilt/idDocument/analyze"


# ---------------------------------------------------------------------------------
# Legacy v2 text analytics (reference TextAnalytics.scala:224-276 — kept for
# API parity with the reference's *V2 classes over the /v2.x endpoints)
# ---------------------------------------------------------------------------------

class TextSentimentV2(_TextAnalyticsBase):
    url_path = "/text/analytics/v2.0/sentiment"


class LanguageDetectorV2(_TextAnalyticsBase):
    url_path = "/text/analytics/v2.0/languages"


class EntityDetectorV2(_TextAnalyticsBase):
    url_path = "/text/analytics/v2.0/entities"


class NERV2(_TextAnalyticsBase):
    url_path = "/text/analytics/v2.1/entities"


class KeyPhraseExtractorV2(_TextAnalyticsBase):
    url_path = "/text/analytics/v2.0/keyPhrases"


# ---------------------------------------------------------------------------------
# Remaining translator endpoints (reference TextTranslator.scala:414,487)
# ---------------------------------------------------------------------------------

class Detect(DetectLanguage):
    """The reference's name for translator /detect (``TextTranslator.scala:414``)
    — same endpoint and behavior as :class:`DetectLanguage`, registered under
    both names for API parity."""


class DictionaryExamples(_TranslatorBase):
    """Contextual usage examples for (text, translation) pairs (reference
    ``DictionaryExamples``, ``TextTranslator.scala:487``)."""

    url_path = "/dictionary/examples"
    from_language = Param("source language", object, default="en")
    to_language = Param("target language", object, default="es")
    text_and_translation = Param("(text, translation) pair or list of pairs "
                                 "(static)", object, default=None)
    text_and_translation_col = Param("(text, translation) pairs column", str,
                                     default=None)

    def _query(self, table, row):
        q = super()._query(table, row)
        q["from"] = self.from_language
        q["to"] = self.to_language
        return q

    def build_payload(self, table: Table, row: int):
        pairs = self.svc_value(table, row, "text_and_translation")
        if pairs is None:
            return None
        if pairs and not isinstance(pairs[0], (list, tuple)):
            pairs = [pairs]
        return [{"Text": str(t), "Translation": str(tr)} for t, tr in pairs]


# ---------------------------------------------------------------------------------
# Form-recognizer custom models (reference FormRecognizer.scala:259-334)
# ---------------------------------------------------------------------------------

class ListCustomModels(CognitiveServiceBase):
    """GET the trained custom models (reference ``ListCustomModels``,
    ``FormRecognizer.scala:259``)."""

    url_path = "/formrecognizer/v2.1/custom/models"
    op = Param("'full' | 'summary'", str, default="full",
               validator=ParamValidators.in_list(["full", "summary"]))

    def build_request(self, table, row):
        url = self.build_url(table, row) + f"?op={self.op}"
        return HTTPRequestData(url=url, method="GET",
                               headers=self.build_headers(table, row))


class GetCustomModel(CognitiveServiceBase):
    """GET one custom model's detail (reference ``GetCustomModel``,
    ``FormRecognizer.scala:284``)."""

    url_path = "/formrecognizer/v2.1/custom/models"
    model_id = Param("custom model id (static)", object, default=None)
    model_id_col = Param("custom model id column", str, default=None)
    include_keys = Param("include extracted keys", bool, default=True)

    def build_request(self, table, row):
        mid = self.svc_value(table, row, "model_id")
        if mid is None:
            return None
        url = (self.build_url(table, row) + f"/{mid}"
               + ("?includeKeys=true" if self.include_keys else ""))
        return HTTPRequestData(url=url, method="GET",
                               headers=self.build_headers(table, row))


class AnalyzeCustomModel(_FormRecognizerBase):
    """Analyze a document with a trained custom model (reference
    ``AnalyzeCustomModel``, ``FormRecognizer.scala:326``)."""

    model_id = Param("custom model id (static)", object, default=None)
    model_id_col = Param("custom model id column", str, default=None)
    include_text_details = Param("include text lines/elements", bool,
                                 default=False)

    url_path = "/formrecognizer/v2.1/custom/models"

    def build_request(self, table, row):
        if self.svc_value(table, row, "model_id") is None:
            return None  # skip like sibling GetCustomModel, not POST .../None
        return super().build_request(table, row)

    def build_url(self, table, row):
        mid = self.svc_value(table, row, "model_id")
        base = super().build_url(table, row)
        url = f"{base}/{mid}/analyze"
        if self.include_text_details:
            url += "?includeTextDetails=true"
        return url


__all__ += [
    "TextSentimentV2", "LanguageDetectorV2", "EntityDetectorV2", "NERV2",
    "KeyPhraseExtractorV2", "Detect", "DictionaryExamples",
    "ListCustomModels", "GetCustomModel", "AnalyzeCustomModel",
]
