"""Cognitive-service transformer base.

Reference: ``cognitive/.../CognitiveServiceBase.scala:271-335`` — every service
stage assembles a pipeline of [Lambda (pack dynamic params into a struct) ->
SimpleHTTPTransformer -> DropColumns], with ``ServiceParam``s that hold either a
static value or a column reference (``setX`` / ``setXCol`` in the reference's
codegen), subscription-key headers, URL from location+path, and an error column.

``ServiceParam`` here is a light descriptor over two underlying Params
(``<name>`` and ``<name>_col``): ``svc_value(row)`` resolves per row.
"""

from __future__ import annotations

import json
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..core import Param, Table, Transformer
from ..io.clients import AsyncHTTPClient
from ..io.http_schema import HTTPRequestData, HTTPResponseData

__all__ = ["ServiceParamMixin", "CognitiveServiceBase", "service_param"]


def service_param(owner_attrs: Dict[str, Any], name: str, doc: str,
                  default=None) -> None:
    """Declare a value-or-column service param pair on a class body dict."""
    owner_attrs[name] = Param(doc + " (static value)", object, default=default)
    owner_attrs[f"{name}_col"] = Param(doc + " (column name)", str, default=None)


class ServiceParamMixin:
    """Resolution helper for value-or-column params."""

    def svc_value(self, table: Optional[Table], row: Optional[int], name: str):
        col_name = getattr(self, f"{name}_col", None)
        if col_name:
            if table is None or col_name not in table:
                raise ValueError(
                    f"{type(self).__name__}({self.uid}): column {col_name!r} "
                    f"(for service param {name!r}) missing from input")
            return table[col_name][row]
        return getattr(self, name, None)


class CognitiveServiceBase(Transformer, ServiceParamMixin):
    """Build per-row requests, post with bounded concurrency, parse, error-split.

    Subclasses define ``url_path``, override ``build_payload(table, row)`` (and
    optionally ``build_url``/``build_headers``/``parse_response``)."""

    _abstract_stage = True

    subscription_key = Param("service key (static)", object, default=None)
    subscription_key_col = Param("service key column", str, default=None)
    url = Param("full endpoint URL (overrides location+path)", str, default="")
    location = Param("service region, e.g. eastus (reference setLocation)", str,
                     default="")
    output_col = Param("parsed output column", str, default="output")
    error_col = Param("error column", str, default="errors")
    concurrency = Param("max in-flight requests", int, default=4)
    timeout = Param("request timeout seconds", float, default=60.0)
    backoffs = Param("retry backoffs ms", list, default=[100, 500, 1000])

    url_path: str = ""  # subclass service path
    _service_domain = "api.cognitive.microsoft.com"

    # -- request assembly ----------------------------------------------------------

    def build_url(self, table: Table, row: int) -> str:
        if self.url:
            return self.url
        if not self.location:
            raise ValueError(
                f"{type(self).__name__}({self.uid}): set url or location")
        return f"https://{self.location}.{self._service_domain}{self.url_path}"

    def build_headers(self, table: Table, row: int) -> Dict[str, str]:
        headers = {"Content-Type": "application/json"}
        key = self.svc_value(table, row, "subscription_key")
        if key:
            headers["Ocp-Apim-Subscription-Key"] = str(key)
        return headers

    def build_payload(self, table: Table, row: int) -> Optional[Any]:
        raise NotImplementedError

    def build_request(self, table: Table, row: int) -> Optional[HTTPRequestData]:
        payload = self.build_payload(table, row)
        if payload is None:
            return None
        if isinstance(payload, bytes):
            body = payload
        else:
            body = json.dumps(payload, default=_np_jsonable).encode()
        return HTTPRequestData(url=self.build_url(table, row), method="POST",
                               headers=self.build_headers(table, row), entity=body)

    def parse_response(self, resp: HTTPResponseData) -> Any:
        if not resp.text:
            return None
        try:
            return json.loads(resp.text)
        except json.JSONDecodeError:
            return resp.text

    # -- transform -----------------------------------------------------------------

    def _transform(self, table: Table) -> Table:
        n = table.num_rows
        reqs: List[Optional[HTTPRequestData]] = [
            self.build_request(table, r) for r in range(n)
        ]
        client = AsyncHTTPClient(self.concurrency, self.timeout, self.backoffs)
        responses = client.send_all(reqs)
        out = np.empty(n, dtype=object)
        errors = np.empty(n, dtype=object)
        for i, resp in enumerate(responses):
            if resp is None:
                out[i] = None
                errors[i] = None
            elif 200 <= resp.status_code < 300:
                out[i] = self.parse_response(resp)
                errors[i] = None
            else:
                out[i] = None
                errors[i] = resp.to_dict()
        return (table.with_column(self.output_col, out)
                .with_column(self.error_col, errors))


def _np_jsonable(v):
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, np.generic):
        return v.item()
    raise TypeError(f"not JSON-serializable: {type(v)}")


from ..core.table import jsonable_value  # noqa: E402  (shared coercer)
