"""Cognitive-service transformers (reference ``cognitive/`` module, SURVEY.md §2.4)."""

from .base import CognitiveServiceBase
from .services import *  # noqa: F401,F403
from .services import __all__ as _service_all

__all__ = ["CognitiveServiceBase", *_service_all]
