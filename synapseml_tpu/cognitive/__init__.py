"""Cognitive-service transformers (reference ``cognitive/`` module, SURVEY.md §2.4)."""

from .base import CognitiveServiceBase
from .extended import *  # noqa: F401,F403
from .extended import __all__ as _extended_all
from .services import *  # noqa: F401,F403
from .services import __all__ as _service_all

__all__ = ["CognitiveServiceBase", *_service_all, *_extended_all]
