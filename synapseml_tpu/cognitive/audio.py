"""Audio transcoding for the streaming speech stages.

Reference: ``SpeechToTextSDK.scala:232-269,339`` spawns an **ffmpeg
subprocess** with piped stdio to convert arbitrary input streams (mp3,
ogg, flac, m4a, webm...) into the PCM the speech service wants, and feeds
the converted stream through the chunked recognizer. Same design here:

- :func:`transcode_to_wav` pipes the payload through ``ffmpeg -i pipe:0
  ... -f wav pipe:1`` when an ffmpeg binary exists (any compressed format
  ffmpeg understands);
- WAV input falls back to a pure-numpy resample/downmix path (stdlib
  ``wave`` + linear interpolation) so the canonical
  resample-to-16k-mono-16bit case needs no external binary at all;
- COMPRESSED WAV codecs — G.711 µ-law (format 7), G.711 A-law (format 6),
  and IMA ADPCM (format 0x11) — decode in pure numpy (r5: the compressed
  branch is CI-testable without vendoring an ffmpeg binary; these are the
  telephony/container codecs, while mp3/ogg/flac still take the ffmpeg
  subprocess);
- anything else without ffmpeg raises with an actionable message.

The target profile is the speech service's canonical PCM: 16 kHz, mono,
16-bit little-endian WAV.
"""

from __future__ import annotations

import io
import shutil
import subprocess
import wave
from typing import Optional

import numpy as np

__all__ = ["transcode_to_wav", "ffmpeg_available", "wav_info"]

_TARGET_RATE = 16000


def ffmpeg_available() -> Optional[str]:
    """Path of the ffmpeg binary, or None."""
    return shutil.which("ffmpeg")


def wav_info(data: bytes) -> dict:
    """(rate, channels, sample width, frames) of a WAV payload."""
    with wave.open(io.BytesIO(data)) as w:
        return {"rate": w.getframerate(), "channels": w.getnchannels(),
                "sample_width": w.getsampwidth(), "frames": w.getnframes()}


def _ffmpeg_transcode(data: bytes, rate: int) -> bytes:
    """Pipe the payload through ffmpeg (the reference's subprocess design:
    stdin/stdout pipes, no temp files)."""
    proc = subprocess.run(
        [ffmpeg_available(), "-hide_banner", "-loglevel", "error",
         "-i", "pipe:0", "-ac", "1", "-ar", str(rate),
         "-acodec", "pcm_s16le", "-f", "wav", "pipe:1"],
        input=data, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        timeout=300)
    if proc.returncode != 0:
        raise RuntimeError(
            f"ffmpeg transcode failed: {proc.stderr.decode()[-500:]}")
    return proc.stdout


# WAVE format tags with built-in pure-numpy decoders
_FMT_PCM = 0x0001
_FMT_ALAW = 0x0006
_FMT_ULAW = 0x0007
_FMT_IMA_ADPCM = 0x0011

# IMA ADPCM tables (public spec: IMA Digital Audio Focus Group, 1992)
_IMA_STEPS = np.array([
    7, 8, 9, 10, 11, 12, 13, 14, 16, 17, 19, 21, 23, 25, 28, 31, 34, 37,
    41, 45, 50, 55, 60, 66, 73, 80, 88, 97, 107, 118, 130, 143, 157, 173,
    190, 209, 230, 253, 279, 307, 337, 371, 408, 449, 494, 544, 598, 658,
    724, 796, 876, 963, 1060, 1166, 1282, 1411, 1552, 1707, 1878, 2066,
    2272, 2499, 2749, 3024, 3327, 3660, 4026, 4428, 4871, 5358, 5894,
    6484, 7132, 7845, 8630, 9493, 10442, 11487, 12635, 13899, 15289,
    16818, 18500, 20350, 22385, 24623, 27086, 29794, 32767], np.int32)
_IMA_INDEX_ADJ = np.array([-1, -1, -1, -1, 2, 4, 6, 8], np.int32)


def _riff_chunks(data: bytes):
    """Yield (fourcc, payload) for each top-level RIFF/WAVE chunk."""
    if data[:4] != b"RIFF" or data[8:12] != b"WAVE":
        raise ValueError("not a RIFF/WAVE payload")
    pos = 12
    while pos + 8 <= len(data):
        cc = data[pos:pos + 4]
        size = int.from_bytes(data[pos + 4:pos + 8], "little")
        yield cc, data[pos + 8:pos + 8 + size]
        pos += 8 + size + (size & 1)  # chunks are word-aligned


def _ulaw_decode(b: np.ndarray) -> np.ndarray:
    """G.711 µ-law byte -> float in [-1, 1)."""
    u = (~b.astype(np.uint8)).astype(np.int32)
    sign = u >> 7
    exp = (u >> 4) & 7
    mant = u & 15
    mag = ((mant << 3) + 0x84 << exp) - 0x84
    pcm = np.where(sign, -mag, mag)
    return pcm.astype(np.float32) / 32768.0


def _alaw_decode(b: np.ndarray) -> np.ndarray:
    """G.711 A-law byte -> float in [-1, 1)."""
    a = (b.astype(np.uint8) ^ 0x55).astype(np.int32)
    sign = a >> 7  # after the XOR, a SET sign bit means POSITIVE (G.711)
    exp = (a >> 4) & 7
    mant = a & 15
    mag = np.where(exp == 0, (mant << 4) + 8,
                   ((mant << 4) + 0x108) << np.maximum(exp - 1, 0))
    pcm = np.where(sign, mag, -mag)
    return pcm.astype(np.float32) / 32768.0


def _ima_adpcm_decode(raw: bytes, channels: int, block_align: int) -> np.ndarray:
    """IMA ADPCM (WAVE format 0x11) -> float mono-interleavable array.

    Block layout per channel: 4-byte header (s16 predictor, u8 step index,
    reserved), then 4-bit nibbles in 4-byte words interleaved per channel.
    The sequential predictor recurrence is per-block, so blocks decode
    independently (vectorization happens across blocks via the outer loop —
    payloads here are seconds of speech, not hours)."""
    n_blocks, rem = divmod(len(raw), block_align)
    if rem:
        raw = raw[: n_blocks * block_align]
    out = []
    for bi in range(n_blocks):
        blk = raw[bi * block_align:(bi + 1) * block_align]
        preds = np.empty(channels, np.int32)
        idxs = np.empty(channels, np.int32)
        chans = [[] for _ in range(channels)]
        for c in range(channels):
            h = blk[c * 4:(c + 1) * 4]
            preds[c] = int.from_bytes(h[0:2], "little", signed=True)
            idxs[c] = min(max(h[2], 0), 88)
            chans[c].append(preds[c])
        body = blk[channels * 4:]
        # nibble stream: 4-byte words per channel, channels interleaved
        words = [body[i:i + 4] for i in range(0, len(body) - 3, 4)]
        for wi, word in enumerate(words):
            c = wi % channels
            pred, idx = int(preds[c]), int(idxs[c])
            for byte in word:
                for nib in (byte & 0xF, byte >> 4):
                    step = int(_IMA_STEPS[idx])
                    diff = step >> 3
                    if nib & 1:
                        diff += step >> 2
                    if nib & 2:
                        diff += step >> 1
                    if nib & 4:
                        diff += step
                    pred = pred - diff if nib & 8 else pred + diff
                    pred = min(max(pred, -32768), 32767)
                    idx = min(max(idx + int(_IMA_INDEX_ADJ[nib & 7]), 0), 88)
                    chans[c].append(pred)
            preds[c], idxs[c] = pred, idx
        n_samp = min(len(ch) for ch in chans)
        inter = np.empty(n_samp * channels, np.float32)
        for c in range(channels):
            inter[c::channels] = np.asarray(chans[c][:n_samp],
                                            np.float32) / 32768.0
        out.append(inter)
    return np.concatenate(out) if out else np.empty(0, np.float32)


def _compressed_wav_decode(data: bytes):
    """Decode a compressed-codec WAV (µ-law / A-law / IMA ADPCM) to
    (float samples interleaved, rate, channels); ValueError when the codec
    has no built-in decoder (caller falls through to ffmpeg)."""
    fmt = None
    body = None
    for cc, payload in _riff_chunks(data):
        if cc == b"fmt ":
            fmt = payload
        elif cc == b"data":
            body = payload
    if fmt is None or body is None:
        raise ValueError("WAV missing fmt/data chunks")
    tag = int.from_bytes(fmt[0:2], "little")
    channels = int.from_bytes(fmt[2:4], "little") or 1
    rate = int.from_bytes(fmt[4:8], "little")
    if rate <= 0:
        # fuzzed/corrupt header: fall through to the ffmpeg/error chain
        # rather than dividing by zero in the resampler
        raise ValueError("compressed WAV declares sample rate 0")
    block_align = int.from_bytes(fmt[12:14], "little")
    if tag == _FMT_ULAW:
        x = _ulaw_decode(np.frombuffer(body, np.uint8))
    elif tag == _FMT_ALAW:
        x = _alaw_decode(np.frombuffer(body, np.uint8))
    elif tag == _FMT_IMA_ADPCM:
        x = _ima_adpcm_decode(body, channels, max(block_align, channels * 4))
    else:
        raise ValueError(f"no built-in decoder for WAVE format 0x{tag:04x}")
    return x, rate, channels


def _float_to_wav(x: np.ndarray, src_rate: int, channels: int,
                  rate: int) -> bytes:
    """Interleaved float samples -> canonical 16 kHz mono s16 WAV."""
    if channels > 1:
        x = x[: len(x) // channels * channels].reshape(-1, channels).mean(1)
    if src_rate != rate and len(x):
        n_out = max(int(round(len(x) * rate / src_rate)), 1)
        x = np.interp(np.linspace(0, len(x) - 1, n_out), np.arange(len(x)), x)
    pcm = np.clip(np.round(x * 32767.0), -32768, 32767).astype("<i2")
    buf = io.BytesIO()
    with wave.open(buf, "wb") as w:
        w.setnchannels(1)
        w.setsampwidth(2)
        w.setframerate(rate)
        w.writeframes(pcm.tobytes())
    return buf.getvalue()


def _wav_transcode(data: bytes, rate: int) -> bytes:
    """Pure-numpy WAV -> 16 kHz mono s16 WAV (no external binary)."""
    with wave.open(io.BytesIO(data)) as w:
        src_rate = w.getframerate()
        channels = w.getnchannels()
        width = w.getsampwidth()
        raw = w.readframes(w.getnframes())
    if width == 2:
        x = np.frombuffer(raw, dtype="<i2").astype(np.float32) / 32768.0
    elif width == 1:  # unsigned 8-bit
        x = (np.frombuffer(raw, dtype=np.uint8).astype(np.float32)
             - 128.0) / 128.0
    elif width == 4:
        x = np.frombuffer(raw, dtype="<i4").astype(np.float32) / 2147483648.0
    else:
        raise ValueError(f"unsupported WAV sample width {width}")
    return _float_to_wav(x, src_rate, channels, rate)


def transcode_to_wav(data: bytes, src_format: str = "auto",
                     rate: int = _TARGET_RATE) -> bytes:
    """Any audio payload -> 16 kHz mono 16-bit WAV bytes.

    ``src_format='auto'`` sniffs WAV by its RIFF header; everything else
    needs ffmpeg (the reference's subprocess path).
    """
    data = bytes(data)
    is_wav = (src_format == "wav"
              or (src_format == "auto" and data[:4] == b"RIFF"))
    if is_wav:
        try:
            info = wav_info(data)
            if (info["rate"] == rate and info["channels"] == 1
                    and info["sample_width"] == 2):
                return data  # already canonical: no copy, no subprocess
            return _wav_transcode(data, rate)
        except (wave.Error, ValueError, EOFError):
            # non-PCM codec, malformed header, or a width the plain path
            # doesn't speak: try the built-in compressed decoders
            try:
                x, src_rate, channels = _compressed_wav_decode(data)
                return _float_to_wav(x, src_rate, channels, rate)
            except ValueError:
                pass  # codec without a built-in decoder: let ffmpeg try
    if ffmpeg_available():
        return _ffmpeg_transcode(data, rate)
    raise RuntimeError(
        f"transcoding {src_format!r} audio needs an ffmpeg binary on PATH "
        "(8/16/32-bit PCM, mu-law/A-law, and IMA ADPCM WAV have built-in "
        "converters); install ffmpeg or pre-convert to 16 kHz mono 16-bit "
        "WAV")
