"""Audio transcoding for the streaming speech stages.

Reference: ``SpeechToTextSDK.scala:232-269,339`` spawns an **ffmpeg
subprocess** with piped stdio to convert arbitrary input streams (mp3,
ogg, flac, m4a, webm...) into the PCM the speech service wants, and feeds
the converted stream through the chunked recognizer. Same design here:

- :func:`transcode_to_wav` pipes the payload through ``ffmpeg -i pipe:0
  ... -f wav pipe:1`` when an ffmpeg binary exists (any compressed format
  ffmpeg understands);
- WAV input falls back to a pure-numpy resample/downmix path (stdlib
  ``wave`` + linear interpolation) so the canonical
  resample-to-16k-mono-16bit case needs no external binary at all;
- anything else without ffmpeg raises with an actionable message.

The target profile is the speech service's canonical PCM: 16 kHz, mono,
16-bit little-endian WAV.
"""

from __future__ import annotations

import io
import shutil
import subprocess
import wave
from typing import Optional

import numpy as np

__all__ = ["transcode_to_wav", "ffmpeg_available", "wav_info"]

_TARGET_RATE = 16000


def ffmpeg_available() -> Optional[str]:
    """Path of the ffmpeg binary, or None."""
    return shutil.which("ffmpeg")


def wav_info(data: bytes) -> dict:
    """(rate, channels, sample width, frames) of a WAV payload."""
    with wave.open(io.BytesIO(data)) as w:
        return {"rate": w.getframerate(), "channels": w.getnchannels(),
                "sample_width": w.getsampwidth(), "frames": w.getnframes()}


def _ffmpeg_transcode(data: bytes, rate: int) -> bytes:
    """Pipe the payload through ffmpeg (the reference's subprocess design:
    stdin/stdout pipes, no temp files)."""
    proc = subprocess.run(
        [ffmpeg_available(), "-hide_banner", "-loglevel", "error",
         "-i", "pipe:0", "-ac", "1", "-ar", str(rate),
         "-acodec", "pcm_s16le", "-f", "wav", "pipe:1"],
        input=data, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        timeout=300)
    if proc.returncode != 0:
        raise RuntimeError(
            f"ffmpeg transcode failed: {proc.stderr.decode()[-500:]}")
    return proc.stdout


def _wav_transcode(data: bytes, rate: int) -> bytes:
    """Pure-numpy WAV -> 16 kHz mono s16 WAV (no external binary)."""
    with wave.open(io.BytesIO(data)) as w:
        src_rate = w.getframerate()
        channels = w.getnchannels()
        width = w.getsampwidth()
        raw = w.readframes(w.getnframes())
    if width == 2:
        x = np.frombuffer(raw, dtype="<i2").astype(np.float32) / 32768.0
    elif width == 1:  # unsigned 8-bit
        x = (np.frombuffer(raw, dtype=np.uint8).astype(np.float32)
             - 128.0) / 128.0
    elif width == 4:
        x = np.frombuffer(raw, dtype="<i4").astype(np.float32) / 2147483648.0
    else:
        raise ValueError(f"unsupported WAV sample width {width}")
    if channels > 1:
        x = x.reshape(-1, channels).mean(axis=1)  # downmix
    if src_rate != rate and len(x):
        n_out = max(int(round(len(x) * rate / src_rate)), 1)
        x = np.interp(np.linspace(0, len(x) - 1, n_out),
                      np.arange(len(x)), x)
    pcm = np.clip(np.round(x * 32767.0), -32768, 32767).astype("<i2")
    buf = io.BytesIO()
    with wave.open(buf, "wb") as w:
        w.setnchannels(1)
        w.setsampwidth(2)
        w.setframerate(rate)
        w.writeframes(pcm.tobytes())
    return buf.getvalue()


def transcode_to_wav(data: bytes, src_format: str = "auto",
                     rate: int = _TARGET_RATE) -> bytes:
    """Any audio payload -> 16 kHz mono 16-bit WAV bytes.

    ``src_format='auto'`` sniffs WAV by its RIFF header; everything else
    needs ffmpeg (the reference's subprocess path).
    """
    data = bytes(data)
    is_wav = (src_format == "wav"
              or (src_format == "auto" and data[:4] == b"RIFF"))
    if is_wav:
        try:
            info = wav_info(data)
            if (info["rate"] == rate and info["channels"] == 1
                    and info["sample_width"] == 2):
                return data  # already canonical: no copy, no subprocess
            return _wav_transcode(data, rate)
        except (wave.Error, ValueError):
            # malformed header or a width the numpy path doesn't speak
            # (e.g. 24-bit studio PCM): let ffmpeg try
            pass
    if ffmpeg_available():
        return _ffmpeg_transcode(data, rate)
    raise RuntimeError(
        f"transcoding {src_format!r} audio needs an ffmpeg binary on PATH "
        "(only 8/16/32-bit WAV has a built-in converter); install ffmpeg or "
        "pre-convert to 16 kHz mono 16-bit WAV")
