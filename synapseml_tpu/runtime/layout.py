"""Canonical GSPMD sharding layout — one named mesh + PartitionSpec module.

Every distributed path in this repo used to hand-roll its own 1-D
``jax.sharding.Mesh`` and ad-hoc ``PartitionSpec`` plumbing (gbdt/boost,
vw/learner, parallel/ring). That caps the framework at pure data
parallelism: a model bigger than one chip's HBM cannot serve at all, and
GBDT histograms cannot split work over features. This module is the one
place mesh construction and tensor placement live:

- **Named 2-D/3-D meshes** ``(data, model)`` / ``(data, fsdp, model)``
  built on :func:`~synapseml_tpu.runtime.topology.make_mesh`, degrading
  gracefully to ``(1, 1)`` on a single chip and to 1-D when only one axis
  is populated (``model_axis=None``). The same code runs from 1 chip to a
  pod — axis sizes change, programs don't.
- **Canonical PartitionSpecs per tensor role**: :meth:`SpecLayout.batch`
  (rows over ``data``), :meth:`SpecLayout.replicated` (params),
  :meth:`SpecLayout.col_weight` (column-sharded weight matrices over
  ``model`` — tensor-parallel MatMul/Gemm), :meth:`SpecLayout.conv_weight`
  (output channels over ``model``), :meth:`SpecLayout.feature_blocks`
  (GBDT histogram feature blocks: rows over ``data`` x features over
  ``model``).
- **Beyond-HBM storage specs** (ROADMAP item 4, SNIPPETS [3] pattern): an
  optional third ``fsdp`` mesh axis over which parameters are *stored*
  row-sharded (:meth:`SpecLayout.fsdp_weight`,
  :meth:`SpecLayout.embed_weight`) and all-gathered only at the point of
  use (:meth:`SpecLayout.gather_for_use` — a ``with_sharding_constraint``
  re-pin inside jit, so GSPMD inserts the collective and the gathered
  copy is a transient of the step, never resident). Per-device at-rest
  HBM for an fsdp-stored tensor is ``nbytes / (fsdp * model)`` of the
  replicated cost, bought with one all-gather per use.
- **Placement helpers**: :meth:`SpecLayout.sharding` /
  :meth:`SpecLayout.put` / :meth:`SpecLayout.constraint`, plus a thin
  :meth:`SpecLayout.shard_map` that wraps
  :func:`~synapseml_tpu.runtime.topology.shard_map_compat` with the
  layout's mesh bound — engines never touch ``jax.sharding`` directly
  (lint rule SMT013 enforces this for new code).

Import discipline: stdlib-only at import (jax reached lazily inside
methods), like the rest of ``runtime``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence, Tuple

__all__ = ["SpecLayout", "as_layout", "representative_layouts"]

_UNSET = object()


@dataclasses.dataclass(frozen=True)
class SpecLayout:
    """A named mesh plus the canonical PartitionSpecs every engine shares.

    Frozen and hashable (``jax.sharding.Mesh`` hashes by device assignment
    and axis names) so layouts key ``lru_cache``'d compiled-program caches
    the same way raw meshes did.
    """

    mesh: Any                               # jax.sharding.Mesh
    data_axis: str = "data"
    model_axis: Optional[str] = "model"
    # optional third axis for row-sharded parameter STORAGE (weights live
    # sharded over it, all-gathered at point of use). None -> the 2-D
    # layout every pre-fsdp caller built; nothing changes for them.
    fsdp_axis: Optional[str] = None

    # -- constructors -----------------------------------------------------------

    @classmethod
    def build(cls, data: Optional[int] = None, model: Optional[int] = None,
              *, fsdp: Optional[int] = None,
              devices: Optional[Sequence] = None,
              data_axis: str = "data",
              model_axis: Optional[str] = "model",
              fsdp_axis: str = "fsdp") -> "SpecLayout":
        """Build a layout over the available devices.

        ``model=m`` populates the model axis with ``m`` devices and the
        data axis with the rest (``n // m``); ``data=d`` with ``model``
        unset leaves the model axis at 1. Neither given: all devices on
        ``data`` (pure data parallelism, the safe default). On one chip
        every variant degrades to a ``(1, 1)`` mesh — specs still resolve,
        collectives become no-ops. ``model_axis=None`` builds a 1-D mesh
        over ``data_axis`` only (e.g. the sequence-parallel ``seq`` axis).

        ``fsdp=f`` inserts a third axis between ``data`` and ``model``
        (mesh ``(data, fsdp, model)``) over which parameters are *stored*
        row-sharded; omitting it keeps the 2-D mesh bit-for-bit.
        """
        from .topology import make_mesh

        if devices is None:
            import jax

            devices = jax.devices()
        n = len(devices)
        if model_axis is None:
            if fsdp:
                raise ValueError("fsdp axis requires a model_axis mesh "
                                 "(1-D layouts have nowhere to insert it)")
            shape: Tuple[int, ...] = (int(data) if data else n,)
            mesh = make_mesh((data_axis,), shape=shape, devices=devices)
            return cls(mesh=mesh, data_axis=data_axis, model_axis=None)
        f2 = int(fsdp) if fsdp else 1
        if f2 < 1:
            raise ValueError(f"fsdp axis size must be >= 1, got {f2}")
        if model is None and data is None:
            if n % f2:
                raise ValueError(
                    f"fsdp axis size {f2} must divide the {n} available "
                    f"devices (pass data= explicitly for a partial mesh)")
            d2, m2 = n // f2, 1
        elif model is None:
            d2, m2 = int(data), 1
        elif data is None:
            m2 = int(model)
            if m2 < 1 or n % (m2 * f2):
                raise ValueError(
                    f"model x fsdp axis sizes {m2} x {f2} must divide the "
                    f"{n} available devices (pass data= explicitly for a "
                    f"partial mesh)")
            d2 = n // (m2 * f2)
        else:
            d2, m2 = int(data), int(model)
        if fsdp:
            mesh = make_mesh((data_axis, fsdp_axis, model_axis),
                             shape=(d2, f2, m2), devices=devices)
            return cls(mesh=mesh, data_axis=data_axis,
                       model_axis=model_axis, fsdp_axis=fsdp_axis)
        mesh = make_mesh((data_axis, model_axis), shape=(d2, m2),
                         devices=devices)
        return cls(mesh=mesh, data_axis=data_axis, model_axis=model_axis)

    @classmethod
    def from_mesh(cls, mesh, data_axis: Optional[str] = None,
                  model_axis=_UNSET, fsdp_axis=_UNSET) -> "SpecLayout":
        """Wrap an existing mesh. ``data_axis`` defaults to ``'data'`` when
        the mesh has it, else the mesh's first axis; ``model_axis`` to
        ``'model'`` and ``fsdp_axis`` to ``'fsdp'`` when present (else
        None — 2-D/1-D degradation)."""
        names = tuple(mesh.axis_names)
        if data_axis is None:
            data_axis = "data" if "data" in names else names[0]
        if data_axis not in names:
            raise ValueError(f"mesh axes {names} have no {data_axis!r} axis")
        if model_axis is _UNSET:
            model_axis = "model" if ("model" in names
                                     and data_axis != "model") else None
        if model_axis is not None and model_axis not in names:
            raise ValueError(f"mesh axes {names} have no {model_axis!r} axis")
        if fsdp_axis is _UNSET:
            fsdp_axis = "fsdp" if ("fsdp" in names
                                   and data_axis != "fsdp"
                                   and model_axis != "fsdp") else None
        if fsdp_axis is not None and fsdp_axis not in names:
            raise ValueError(f"mesh axes {names} have no {fsdp_axis!r} axis")
        return cls(mesh=mesh, data_axis=data_axis, model_axis=model_axis,
                   fsdp_axis=fsdp_axis)

    # -- sizes ------------------------------------------------------------------

    @property
    def data_size(self) -> int:
        return int(self.mesh.shape[self.data_axis])

    @property
    def model_size(self) -> int:
        if self.model_axis is None:
            return 1
        return int(self.mesh.shape[self.model_axis])

    @property
    def fsdp_size(self) -> int:
        if self.fsdp_axis is None:
            return 1
        return int(self.mesh.shape[self.fsdp_axis])

    @property
    def n_devices(self) -> int:
        return self.data_size * self.fsdp_size * self.model_size

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return tuple(self.mesh.axis_names)

    @property
    def is_single_device(self) -> bool:
        return self.n_devices == 1

    def describe(self) -> dict:
        """JSON-able mesh summary (stamped into MULTICHIP artifacts)."""
        out = {self.data_axis: self.data_size}
        if self.fsdp_axis is not None:
            out[self.fsdp_axis] = self.fsdp_size
        if self.model_axis is not None:
            out[self.model_axis] = self.model_size
        return out

    # -- canonical specs per tensor role ---------------------------------------

    def batch(self, rank: int = 1, dim: int = 0):
        """Batch rows sharded over ``data`` at position ``dim`` of a
        rank-``rank`` tensor (everything else replicated) — the spec for
        feature matrices, labels, weights, activations."""
        from jax.sharding import PartitionSpec as P

        axes = [None] * rank
        axes[dim] = self.data_axis
        return P(*axes)

    def replicated(self):
        """Fully replicated (scalars, RNG keys, small parameters)."""
        from jax.sharding import PartitionSpec as P

        return P()

    def col_weight(self, rank: int = 2, dim: Optional[int] = None):
        """Column-sharded weight matrix: output-feature dim (default: last)
        over ``model`` — the tensor-parallel MatMul/Gemm layout. Degrades
        to replicated on a 1-D layout."""
        from jax.sharding import PartitionSpec as P

        axes: list = [None] * rank
        if self.model_axis is not None:
            axes[rank - 1 if dim is None else dim] = self.model_axis
        return P(*axes)

    def conv_weight(self, rank: int = 4):
        """Conv kernel (OIHW): output channels over ``model``."""
        return self.col_weight(rank=rank, dim=0)

    def feature_blocks(self):
        """GBDT histogram layout: rows over ``data`` x feature blocks over
        ``model`` (stats ``psum`` per axis)."""
        from jax.sharding import PartitionSpec as P

        if self.model_axis is None:
            return P(self.data_axis)
        return P(self.data_axis, self.model_axis)

    # -- fsdp storage specs (row-sharded at rest, all-gathered on use) ----------

    def fsdp_weight(self, rank: int = 1, dim: int = 0, use_spec=None):
        """STORAGE spec of a parameter row-sharded over ``fsdp`` at ``dim``.

        ``use_spec`` is the tensor's point-of-use spec (default replicated);
        the storage spec stacks the fsdp axis on top of it (a dim already
        sharded over ``model`` stores over ``(fsdp, model)``). Degrades to
        ``use_spec`` itself when the layout has no fsdp axis, so adopting
        call sites stay correct on 2-D and 1-D meshes.
        """
        from jax.sharding import PartitionSpec as P

        base: list = list(use_spec) if use_spec is not None else []
        base += [None] * (rank - len(base))
        if self.fsdp_axis is not None:
            cur = base[dim]
            if cur is None:
                base[dim] = self.fsdp_axis
            elif isinstance(cur, tuple):
                base[dim] = (self.fsdp_axis,) + cur
            else:
                base[dim] = (self.fsdp_axis, cur)
        return P(*base)

    def embed_weight(self, rank: int = 2):
        """Embedding-table STORAGE: rows (vocab dim 0) sharded over
        ``fsdp x model`` jointly (the SNIPPETS [3] ``embeddings`` layout) —
        at rest each device holds ``1 / (fsdp * model)`` of the table."""
        from jax.sharding import PartitionSpec as P

        row = tuple(a for a in (self.fsdp_axis, self.model_axis)
                    if a is not None)
        axes: list = [None] * rank
        if row:
            axes[0] = row if len(row) > 1 else row[0]
        return P(*axes)

    def use_spec(self, stored_spec):
        """Point-of-use spec of a stored-over-fsdp tensor: the storage spec
        with the fsdp axis stripped (what the consumer math wants resident —
        replicated, or still ``model``-sharded for a tensor-parallel dim)."""
        from jax.sharding import PartitionSpec as P

        if self.fsdp_axis is None:
            return stored_spec

        def strip(entry):
            if entry == self.fsdp_axis:
                return None
            if isinstance(entry, tuple):
                kept = tuple(a for a in entry if a != self.fsdp_axis)
                return kept if len(kept) > 1 else (kept[0] if kept else None)
            return entry

        return P(*[strip(e) for e in stored_spec])

    def gather_for_use(self, x, stored_spec):
        """All-gather-on-use re-pin INSIDE a traced program.

        ``with_sharding_constraint`` to :meth:`use_spec` makes GSPMD insert
        the all-gather over ``fsdp`` right where the value is consumed; the
        gathered copy is a transient of the jitted step (freed with the
        step's temporaries), while the bound argument stays row-sharded at
        rest. No-op (identity constraint) on layouts without an fsdp axis.
        """
        return self.constraint(x, self.use_spec(stored_spec))

    def donated_gather(self, stored_spec):
        """Explicit eager gather for hot loops that dispatch many steps per
        stored tensor: a jitted identity with ``out_shardings`` pinned to
        :meth:`use_spec`. The caller runs it per batch of uses and lets the
        returned (gathered) buffer die — or donates it into the consumer's
        jit — so the full copy is alive only across those dispatches. The
        stored argument is deliberately NOT donated: storage persists.
        """
        import jax

        out = self.sharding(self.use_spec(stored_spec))
        return jax.jit(lambda t: t, out_shardings=out)

    # -- placement --------------------------------------------------------------

    def sharding(self, spec):
        from jax.sharding import NamedSharding

        return NamedSharding(self.mesh, spec)

    def put(self, x, spec):
        """``device_put`` onto the layout (host->device or resharding)."""
        import jax

        return jax.device_put(x, self.sharding(spec))

    def constraint(self, x, spec):
        """``with_sharding_constraint`` inside a traced program — pins the
        placement GSPMD must honor (jit inserts the collectives)."""
        import jax

        return jax.lax.with_sharding_constraint(x, self.sharding(spec))

    def shard_map(self, f, in_specs, out_specs, check: bool = True):
        """``shard_map`` over the layout's mesh, axis names resolved from
        the layout (drift-proof through ``shard_map_compat``)."""
        from .topology import shard_map_compat

        return shard_map_compat(f, mesh=self.mesh, in_specs=in_specs,
                                out_specs=out_specs, check=check)

    # -- persistence (core/serialization state_dict protocol) -------------------

    def state_dict(self) -> dict:
        """Axis names + sizes only — a Mesh is bound to live devices and
        cannot travel; the loading process rebuilds it over ITS devices.
        The fsdp keys are only written for 3-D layouts, so artifacts saved
        by 2-D trainers stay byte-identical to the pre-fsdp format."""
        out = {"data_axis": self.data_axis,
               "model_axis": self.model_axis or "",
               "data": self.data_size,
               "model": self.model_size}
        if self.fsdp_axis is not None:
            out["fsdp_axis"] = self.fsdp_axis
            out["fsdp"] = self.fsdp_size
        return out

    @staticmethod
    def from_state_dict(d: dict) -> "SpecLayout":
        """Rebuild on the loading process's devices. A layout never changes
        results (placement only — parity-tested), so when this process has
        fewer devices than the saved shape the layout degrades to what fits
        (ultimately ``(1, 1)``) instead of failing the load — a 1-chip
        serving worker can load a pipeline saved on an 8-chip
        ``(2, 2, 2)`` trainer. Degradation collapses ``fsdp`` first (it
        only changes at-rest storage), then ``model``, then ``data``."""
        import jax

        data_axis = str(d["data_axis"])
        model_axis = str(d.get("model_axis") or "") or None
        fsdp_axis = str(d.get("fsdp_axis") or "") or None
        want_data, want_model = int(d["data"]), int(d.get("model", 1))
        want_fsdp = int(d.get("fsdp", 1)) if fsdp_axis else 1
        n = len(jax.devices())
        if model_axis is None:
            return SpecLayout.build(data=min(want_data, n),
                                    data_axis=data_axis, model_axis=None)
        if want_data * want_fsdp * want_model > n:
            import logging

            saved = f"{data_axis}={want_data}"
            if fsdp_axis:
                saved += f", {fsdp_axis}={want_fsdp}"
            saved += f", {model_axis}={want_model}"
            logging.getLogger("synapseml_tpu.layout").warning(
                "saved layout (%s) needs %d devices, have %d; degrading",
                saved, want_data * want_fsdp * want_model, n)
            want_model = max(1, min(want_model, n))
            want_fsdp = max(1, min(want_fsdp, n // want_model))
            want_data = max(1, min(want_data,
                                   n // (want_model * want_fsdp)))
        if fsdp_axis and want_fsdp > 1:
            return SpecLayout.build(data=want_data, model=want_model,
                                    fsdp=want_fsdp, data_axis=data_axis,
                                    model_axis=model_axis,
                                    fsdp_axis=fsdp_axis)
        return SpecLayout.build(data=want_data, model=want_model,
                                data_axis=data_axis, model_axis=model_axis)


from ..core.serialization import register_state_class

register_state_class(SpecLayout)


def representative_layouts(devices=None) -> dict:
    """The canonical layout matrix static analysis traces under.

    The SPMD lint pack (``analysis/rules_spmd.py``) and ``tools/
    spmd_diff.py`` need REPRESENTATIVE layouts, not whatever this host
    happens to have: ``(1,1)`` (the degenerate single-chip mesh every
    program must tolerate), ``(1,2)-tp`` (tensor-parallel serving — the
    model axis populated, SMT110's replication hazard live), ``(4,2)-fp``
    (the 2-D feature-parallel GBDT shape), and ``(1,2,2)`` (the 3-D
    fsdp storage mesh — store-over-fsdp plans and their
    all-gather-on-use re-pins get re-traced). Each degrades gracefully to
    the devices actually present (a 1-chip host still traces everything,
    with axis sizes collapsed to 1) so the pack runs identically on a
    laptop and an 8-chip pod slice.
    """
    if devices is None:
        import jax

        devices = jax.devices()
    devices = list(devices)
    n = len(devices)
    m = 2 if n >= 2 else 1
    d = 4 if n >= 4 * m else max(1, n // m)
    f = 2 if n >= 2 * m else 1
    fsdp_kw = {"fsdp": f} if f > 1 else {}
    return {
        "(1,1)": SpecLayout.build(data=1, model=1, devices=devices[:1]),
        "(1,2)-tp": SpecLayout.build(data=1, model=m, devices=devices[:m]),
        "(4,2)-fp": SpecLayout.build(data=d, model=m,
                                     devices=devices[:d * m]),
        "(1,2,2)": SpecLayout.build(data=1, model=m, devices=devices[:f * m],
                                    **fsdp_kw),
    }


def as_layout(mesh_or_layout, data_axis: str = "data") -> SpecLayout:
    """Coerce an engine's ``mesh=`` argument (a raw ``jax.sharding.Mesh``
    — back-compat — or a :class:`SpecLayout`) into a layout. ``data_axis``
    is the caller's row axis name and is honored when the mesh has it."""
    if isinstance(mesh_or_layout, SpecLayout):
        return mesh_or_layout
    names = tuple(getattr(mesh_or_layout, "axis_names", ()))
    return SpecLayout.from_mesh(
        mesh_or_layout,
        data_axis=data_axis if data_axis in names else None)
