"""Canonical GSPMD sharding layout — one named mesh + PartitionSpec module.

Every distributed path in this repo used to hand-roll its own 1-D
``jax.sharding.Mesh`` and ad-hoc ``PartitionSpec`` plumbing (gbdt/boost,
vw/learner, parallel/ring). That caps the framework at pure data
parallelism: a model bigger than one chip's HBM cannot serve at all, and
GBDT histograms cannot split work over features. This module is the one
place mesh construction and tensor placement live:

- **Named 2-D meshes** ``(data, model)`` built on
  :func:`~synapseml_tpu.runtime.topology.make_mesh`, degrading gracefully
  to ``(1, 1)`` on a single chip and to 1-D when only one axis is
  populated (``model_axis=None``). The same code runs from 1 chip to a
  pod — axis sizes change, programs don't.
- **Canonical PartitionSpecs per tensor role**: :meth:`SpecLayout.batch`
  (rows over ``data``), :meth:`SpecLayout.replicated` (params),
  :meth:`SpecLayout.col_weight` (column-sharded weight matrices over
  ``model`` — tensor-parallel MatMul/Gemm), :meth:`SpecLayout.conv_weight`
  (output channels over ``model``), :meth:`SpecLayout.feature_blocks`
  (GBDT histogram feature blocks: rows over ``data`` x features over
  ``model``).
- **Placement helpers**: :meth:`SpecLayout.sharding` /
  :meth:`SpecLayout.put` / :meth:`SpecLayout.constraint`, plus a thin
  :meth:`SpecLayout.shard_map` that wraps
  :func:`~synapseml_tpu.runtime.topology.shard_map_compat` with the
  layout's mesh bound — engines never touch ``jax.sharding`` directly
  (lint rule SMT013 enforces this for new code).

Import discipline: stdlib-only at import (jax reached lazily inside
methods), like the rest of ``runtime``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence, Tuple

__all__ = ["SpecLayout", "as_layout", "representative_layouts"]

_UNSET = object()


@dataclasses.dataclass(frozen=True)
class SpecLayout:
    """A named mesh plus the canonical PartitionSpecs every engine shares.

    Frozen and hashable (``jax.sharding.Mesh`` hashes by device assignment
    and axis names) so layouts key ``lru_cache``'d compiled-program caches
    the same way raw meshes did.
    """

    mesh: Any                               # jax.sharding.Mesh
    data_axis: str = "data"
    model_axis: Optional[str] = "model"

    # -- constructors -----------------------------------------------------------

    @classmethod
    def build(cls, data: Optional[int] = None, model: Optional[int] = None,
              *, devices: Optional[Sequence] = None,
              data_axis: str = "data",
              model_axis: Optional[str] = "model") -> "SpecLayout":
        """Build a layout over the available devices.

        ``model=m`` populates the model axis with ``m`` devices and the
        data axis with the rest (``n // m``); ``data=d`` with ``model``
        unset leaves the model axis at 1. Neither given: all devices on
        ``data`` (pure data parallelism, the safe default). On one chip
        every variant degrades to a ``(1, 1)`` mesh — specs still resolve,
        collectives become no-ops. ``model_axis=None`` builds a 1-D mesh
        over ``data_axis`` only (e.g. the sequence-parallel ``seq`` axis).
        """
        from .topology import make_mesh

        if devices is None:
            import jax

            devices = jax.devices()
        n = len(devices)
        if model_axis is None:
            shape: Tuple[int, ...] = (int(data) if data else n,)
            mesh = make_mesh((data_axis,), shape=shape, devices=devices)
            return cls(mesh=mesh, data_axis=data_axis, model_axis=None)
        if model is None and data is None:
            d2, m2 = n, 1
        elif model is None:
            d2, m2 = int(data), 1
        elif data is None:
            m2 = int(model)
            if m2 < 1 or n % m2:
                raise ValueError(
                    f"model axis size {m2} must divide the {n} available "
                    f"devices (pass data= explicitly for a partial mesh)")
            d2 = n // m2
        else:
            d2, m2 = int(data), int(model)
        mesh = make_mesh((data_axis, model_axis), shape=(d2, m2),
                         devices=devices)
        return cls(mesh=mesh, data_axis=data_axis, model_axis=model_axis)

    @classmethod
    def from_mesh(cls, mesh, data_axis: Optional[str] = None,
                  model_axis=_UNSET) -> "SpecLayout":
        """Wrap an existing mesh. ``data_axis`` defaults to ``'data'`` when
        the mesh has it, else the mesh's first axis; ``model_axis`` to
        ``'model'`` when present (else None — 1-D degradation)."""
        names = tuple(mesh.axis_names)
        if data_axis is None:
            data_axis = "data" if "data" in names else names[0]
        if data_axis not in names:
            raise ValueError(f"mesh axes {names} have no {data_axis!r} axis")
        if model_axis is _UNSET:
            model_axis = "model" if ("model" in names
                                     and data_axis != "model") else None
        if model_axis is not None and model_axis not in names:
            raise ValueError(f"mesh axes {names} have no {model_axis!r} axis")
        return cls(mesh=mesh, data_axis=data_axis, model_axis=model_axis)

    # -- sizes ------------------------------------------------------------------

    @property
    def data_size(self) -> int:
        return int(self.mesh.shape[self.data_axis])

    @property
    def model_size(self) -> int:
        if self.model_axis is None:
            return 1
        return int(self.mesh.shape[self.model_axis])

    @property
    def n_devices(self) -> int:
        return self.data_size * self.model_size

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return tuple(self.mesh.axis_names)

    @property
    def is_single_device(self) -> bool:
        return self.n_devices == 1

    def describe(self) -> dict:
        """JSON-able mesh summary (stamped into MULTICHIP artifacts)."""
        out = {self.data_axis: self.data_size}
        if self.model_axis is not None:
            out[self.model_axis] = self.model_size
        return out

    # -- canonical specs per tensor role ---------------------------------------

    def batch(self, rank: int = 1, dim: int = 0):
        """Batch rows sharded over ``data`` at position ``dim`` of a
        rank-``rank`` tensor (everything else replicated) — the spec for
        feature matrices, labels, weights, activations."""
        from jax.sharding import PartitionSpec as P

        axes = [None] * rank
        axes[dim] = self.data_axis
        return P(*axes)

    def replicated(self):
        """Fully replicated (scalars, RNG keys, small parameters)."""
        from jax.sharding import PartitionSpec as P

        return P()

    def col_weight(self, rank: int = 2, dim: Optional[int] = None):
        """Column-sharded weight matrix: output-feature dim (default: last)
        over ``model`` — the tensor-parallel MatMul/Gemm layout. Degrades
        to replicated on a 1-D layout."""
        from jax.sharding import PartitionSpec as P

        axes: list = [None] * rank
        if self.model_axis is not None:
            axes[rank - 1 if dim is None else dim] = self.model_axis
        return P(*axes)

    def conv_weight(self, rank: int = 4):
        """Conv kernel (OIHW): output channels over ``model``."""
        return self.col_weight(rank=rank, dim=0)

    def feature_blocks(self):
        """GBDT histogram layout: rows over ``data`` x feature blocks over
        ``model`` (stats ``psum`` per axis)."""
        from jax.sharding import PartitionSpec as P

        if self.model_axis is None:
            return P(self.data_axis)
        return P(self.data_axis, self.model_axis)

    # -- placement --------------------------------------------------------------

    def sharding(self, spec):
        from jax.sharding import NamedSharding

        return NamedSharding(self.mesh, spec)

    def put(self, x, spec):
        """``device_put`` onto the layout (host->device or resharding)."""
        import jax

        return jax.device_put(x, self.sharding(spec))

    def constraint(self, x, spec):
        """``with_sharding_constraint`` inside a traced program — pins the
        placement GSPMD must honor (jit inserts the collectives)."""
        import jax

        return jax.lax.with_sharding_constraint(x, self.sharding(spec))

    def shard_map(self, f, in_specs, out_specs, check: bool = True):
        """``shard_map`` over the layout's mesh, axis names resolved from
        the layout (drift-proof through ``shard_map_compat``)."""
        from .topology import shard_map_compat

        return shard_map_compat(f, mesh=self.mesh, in_specs=in_specs,
                                out_specs=out_specs, check=check)

    # -- persistence (core/serialization state_dict protocol) -------------------

    def state_dict(self) -> dict:
        """Axis names + sizes only — a Mesh is bound to live devices and
        cannot travel; the loading process rebuilds it over ITS devices."""
        return {"data_axis": self.data_axis,
                "model_axis": self.model_axis or "",
                "data": self.data_size,
                "model": self.model_size}

    @staticmethod
    def from_state_dict(d: dict) -> "SpecLayout":
        """Rebuild on the loading process's devices. A layout never changes
        results (placement only — parity-tested), so when this process has
        fewer devices than the saved shape the layout degrades to what fits
        (ultimately ``(1, 1)``) instead of failing the load — a 1-chip
        serving worker can load a pipeline saved on an 8-chip trainer."""
        import jax

        data_axis = str(d["data_axis"])
        model_axis = str(d.get("model_axis") or "") or None
        want_data, want_model = int(d["data"]), int(d.get("model", 1))
        n = len(jax.devices())
        if model_axis is None:
            return SpecLayout.build(data=min(want_data, n),
                                    data_axis=data_axis, model_axis=None)
        if want_data * want_model > n:
            import logging

            logging.getLogger("synapseml_tpu.layout").warning(
                "saved layout (%s=%d, %s=%d) needs %d devices, have %d; "
                "degrading", data_axis, want_data, model_axis, want_model,
                want_data * want_model, n)
            want_model = max(1, min(want_model, n))
            want_data = max(1, min(want_data, n // want_model))
        return SpecLayout.build(data=want_data, model=want_model,
                                data_axis=data_axis, model_axis=model_axis)


from ..core.serialization import register_state_class

register_state_class(SpecLayout)


def representative_layouts(devices=None) -> dict:
    """The canonical layout matrix static analysis traces under.

    The SPMD lint pack (``analysis/rules_spmd.py``) and ``tools/
    spmd_diff.py`` need REPRESENTATIVE layouts, not whatever this host
    happens to have: ``(1,1)`` (the degenerate single-chip mesh every
    program must tolerate), ``(1,2)-tp`` (tensor-parallel serving — the
    model axis populated, SMT110's replication hazard live), and
    ``(4,2)-fp`` (the 2-D feature-parallel GBDT shape). Each degrades
    gracefully to the devices actually present (a 1-chip host still
    traces everything, with axis sizes collapsed to 1) so the pack runs
    identically on a laptop and an 8-chip pod slice.
    """
    if devices is None:
        import jax

        devices = jax.devices()
    devices = list(devices)
    n = len(devices)
    m = 2 if n >= 2 else 1
    d = 4 if n >= 4 * m else max(1, n // m)
    return {
        "(1,1)": SpecLayout.build(data=1, model=1, devices=devices[:1]),
        "(1,2)-tp": SpecLayout.build(data=1, model=m, devices=devices[:m]),
        "(4,2)-fp": SpecLayout.build(data=d, model=m,
                                     devices=devices[:d * m]),
    }


def as_layout(mesh_or_layout, data_axis: str = "data") -> SpecLayout:
    """Coerce an engine's ``mesh=`` argument (a raw ``jax.sharding.Mesh``
    — back-compat — or a :class:`SpecLayout`) into a layout. ``data_axis``
    is the caller's row axis name and is honored when the mesh has it."""
    if isinstance(mesh_or_layout, SpecLayout):
        return mesh_or_layout
    names = tuple(getattr(mesh_or_layout, "axis_names", ()))
    return SpecLayout.from_mesh(
        mesh_or_layout,
        data_axis=data_axis if data_axis in names else None)
