"""Process-wide shared state — the ``SharedSingleton``/``SharedVariable`` equivalent.

Reference: ``core/.../io/http/SharedVariable.scala:18-58`` — a JVM-wide pool keyed by
UUID so every task running in one executor JVM shares a single object (used for LightGBM
``SharedState``, serving servers, ``PartitionConsolidator``). Here the unit of sharing is
the Python process (one process per TPU host); partition-parallel threads of one host get
one shared instance, guarded by per-key locks.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Generic, Optional, TypeVar

__all__ = ["SharedVariable", "shared_singleton", "clear_shared_pool"]

T = TypeVar("T")

_pool: Dict[str, Any] = {}
_pool_lock = threading.Lock()
_key_locks: Dict[str, threading.Lock] = {}


def _key_lock(key: str) -> threading.Lock:
    with _pool_lock:
        if key not in _key_locks:
            _key_locks[key] = threading.Lock()
        return _key_locks[key]


def shared_singleton(key: str, factory: Callable[[], T]) -> T:
    """Get-or-create the process-wide instance for ``key``.

    The factory runs at most once per process per key, even under concurrent access
    (double-checked under the per-key lock).
    """
    if key in _pool:
        return _pool[key]
    with _key_lock(key):
        if key not in _pool:
            value = factory()
            with _pool_lock:
                _pool[key] = value
        return _pool[key]


def clear_shared_pool(prefix: str = "") -> None:
    """Clear cached values. Per-key locks are deliberately retained: deleting a lock
    another thread currently holds would let two factories race for the same key."""
    with _pool_lock:
        for k in [k for k in _pool if k.startswith(prefix)]:
            del _pool[k]


class SharedVariable(Generic[T]):
    """A handle whose value is shared per-process, lazily constructed.

    >>> sv = SharedVariable(lambda: [])
    >>> sv.get() is sv.get()
    True
    """

    def __init__(self, factory: Callable[[], T], key: Optional[str] = None):
        import uuid

        self._factory = factory
        self._key = key or f"sharedvar-{uuid.uuid4().hex}"

    def get(self) -> T:
        return shared_singleton(self._key, self._factory)

    @property
    def key(self) -> str:
        return self._key
