"""Device / host topology discovery — the ``ClusterUtil`` equivalent.

The reference discovers Spark executors and tasks-per-executor to size its native
process groups (``core/.../core/utils/ClusterUtil.scala:20-176``: ``getNumTasksPerExec``,
``getExecutors``, ``getDriverHost``). On TPU the analogous facts come from the JAX
runtime and pod-slice metadata: local/global device counts, process (host) index/count,
and the ICI mesh shape. This module centralizes them and builds ``jax.sharding.Mesh``
objects that the distributed trainers (GBDT histogram ``psum``, linear ``pmean``) and
serving layer consume.

Multi-host bring-up (the reference's driver-socket rendezvous,
``LightGBMBase.scala:399-437``) maps to ``jax.distributed.initialize`` — coordinator
address instead of driver ServerSocket, with the same retry-with-backoff semantics.
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "ClusterInfo",
    "cluster_info",
    "make_mesh",
    "best_mesh_shape",
    "initialize_distributed",
    "device_kind",
    "is_tpu",
    "shard_map_compat",
]


def shard_map_compat(f, mesh, in_specs, out_specs, check: bool = True):
    """``shard_map`` across jax version drift.

    Newer jax exposes ``jax.shard_map`` with a ``check_vma`` kwarg; 0.4.x
    has ``jax.experimental.shard_map.shard_map`` with ``check_rep``. Every
    mesh-distributed code path in this repo goes through this wrapper —
    never ``from jax import shard_map`` directly — so an interpreter's jax
    picks the right spelling at call time (jax stays lazily imported).

    ``check`` defaults to True, matching jax's own replication checking
    default; the trainers pass ``check=False`` explicitly where the body's
    collectives are known-good and the check costs tracing time."""
    import inspect

    import jax

    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm
    params = inspect.signature(sm).parameters
    if "check_vma" in params:
        kw = {"check_vma": check}
    elif "check_rep" in params:
        kw = {"check_rep": check}
    else:
        kw = {}
    return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)

_logger = logging.getLogger("synapseml_tpu.topology")


@dataclasses.dataclass(frozen=True)
class ClusterInfo:
    """Snapshot of the accelerator topology (ClusterUtil.getExecutors analogue)."""

    num_devices: int
    local_num_devices: int
    num_hosts: int
    host_index: int
    platform: str
    device_kinds: Tuple[str, ...]

    @property
    def devices_per_host(self) -> int:
        return self.local_num_devices


def cluster_info() -> ClusterInfo:
    import jax

    devs = jax.devices()
    return ClusterInfo(
        num_devices=jax.device_count(),
        local_num_devices=jax.local_device_count(),
        num_hosts=jax.process_count(),
        host_index=jax.process_index(),
        platform=devs[0].platform if devs else "cpu",
        device_kinds=tuple(sorted({d.device_kind for d in devs})),
    )


def device_kind() -> str:
    import jax

    return jax.devices()[0].device_kind


def is_tpu() -> bool:
    import jax

    return jax.devices()[0].platform == "tpu"


def require_backend(want: Optional[str] = None, *,
                    allow_cpu: bool = False) -> "ClusterInfo":
    """Assert the resolved jax backend is an accelerator — LOUDLY.

    jax falls back to CPU silently when the TPU runtime is absent,
    unclaimed, or shadowed by ``JAX_PLATFORMS`` — and every benchmark,
    SLO probe, and training job downstream then measures the wrong
    machine while reporting success. This is the fail-fast gate: call it
    once at process start (bench refuses CPU rounds through it) and a
    mis-provisioned environment dies with a diagnostic naming what was
    found and which knobs select the backend, instead of publishing
    CPU numbers.

    ``want`` pins a specific platform (``"tpu"``, ``"gpu"``); the default
    accepts any non-CPU accelerator. ``allow_cpu=True`` turns the check
    into a pass-through (the explicit opt-in path — tests, laptops).
    Returns the :class:`ClusterInfo` snapshot so callers can stamp it.
    """
    info = cluster_info()
    if allow_cpu:
        return info
    plat = info.platform
    if plat == "cpu" or (want is not None and plat != want):
        wanted = want or "an accelerator (tpu/gpu)"
        raise RuntimeError(
            f"resolved jax backend is {plat!r} "
            f"(kinds={list(info.device_kinds)}, "
            f"devices={info.num_devices}) but {wanted} is required.\n"
            f"  JAX_PLATFORMS={os.environ.get('JAX_PLATFORMS', '<unset>')}\n"
            f"  XLA_FLAGS={os.environ.get('XLA_FLAGS', '<unset>')}\n"
            f"likely causes: TPU runtime not installed / already claimed "
            f"by another process / JAX_PLATFORMS pinning cpu. Probe with "
            f"`python tools/check_device.py`; pass allow_cpu=True (bench: "
            f"--allow-cpu) only to deliberately measure the host.")
    return info


def best_mesh_shape(n_devices: int, n_axes: int) -> Tuple[int, ...]:
    """Factor ``n_devices`` into ``n_axes`` balanced axes, sorted largest-first.

    Greedy prime-factor packing: factors (largest first) go to the axis with the
    smallest current product, so e.g. 12 over 3 axes -> (3, 2, 2), 8 over 3 -> (2, 2, 2).
    Used when the caller asks for e.g. a ('data','model') mesh without specifying the
    split; mirrors how the reference derives numTasksPerExec from cores/taskCpus
    (``ClusterUtil.scala:20-105``) — sensible defaults, overridable.
    """
    factors: List[int] = []
    rem = n_devices
    d = 2
    while d * d <= rem:
        while rem % d == 0:
            factors.append(d)
            rem //= d
        d += 1
    if rem > 1:
        factors.append(rem)
    shape = [1] * n_axes
    for f in sorted(factors, reverse=True):
        shape[int(np.argmin(shape))] *= f
    return tuple(sorted(shape, reverse=True))


def make_mesh(
    axis_names: Sequence[str] = ("data",),
    shape: Optional[Sequence[int]] = None,
    devices: Optional[Sequence] = None,
):
    """Build a ``jax.sharding.Mesh`` over available devices.

    ``shape=None`` puts all devices on the first axis (pure data parallelism — the only
    parallelism the reference's trainers use, SURVEY.md §2.1) and 1 on the rest.
    """
    import jax
    from jax.sharding import Mesh

    devs = list(devices) if devices is not None else list(jax.devices())
    axis_names = tuple(axis_names)
    if shape is None:
        shape = (len(devs),) + (1,) * (len(axis_names) - 1)
    shape = tuple(int(s) for s in shape)
    total = int(np.prod(shape))
    if total > len(devs):
        raise ValueError(f"mesh shape {shape} needs {total} devices, have {len(devs)}")
    arr = np.array(devs[:total]).reshape(shape)
    return Mesh(arr, axis_names)


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    retries: int = 5,
) -> None:
    """Multi-host rendezvous: ``jax.distributed.initialize`` with backoff retry.

    Replaces the reference's driver-socket rendezvous + exponential-backoff native
    network init (``TrainUtils.scala:237-296``). No-ops when single-host and no
    coordinator is configured.
    """
    import jax

    from ..core.fault import retry_with_backoff

    if coordinator_address is None and "JAX_COORDINATOR_ADDRESS" not in os.environ:
        if num_processes in (None, 1):
            _logger.debug("single-host: skipping jax.distributed.initialize")
            return

    def _init():
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )

    retry_with_backoff(_init, retries=retries, initial_delay_s=1.0, max_delay_s=30.0)
