"""Runtime: topology discovery, shared per-host state, distributed bring-up."""

from .shared import SharedVariable, clear_shared_pool, shared_singleton
from .topology import (
    ClusterInfo,
    best_mesh_shape,
    cluster_info,
    device_kind,
    initialize_distributed,
    is_tpu,
    make_mesh,
)

__all__ = [
    "SharedVariable",
    "shared_singleton",
    "clear_shared_pool",
    "ClusterInfo",
    "cluster_info",
    "make_mesh",
    "best_mesh_shape",
    "initialize_distributed",
    "device_kind",
    "is_tpu",
]
