"""Runtime: topology discovery, shared per-host state, distributed bring-up."""

from ..core.lazyimport import lazy_module

# PEP 562 lazy exports (lint SMT008): attribute access imports the owning
# submodule on demand, keeping `import synapseml_tpu.runtime` jax-free
__getattr__, __dir__, __all__ = lazy_module(__name__, {
    "layout": ["SpecLayout", "as_layout"],
    "shared": ["SharedVariable", "clear_shared_pool", "shared_singleton"],
    "topology": ["ClusterInfo", "best_mesh_shape", "cluster_info",
                 "device_kind", "initialize_distributed", "is_tpu",
                 "make_mesh", "shard_map_compat"],
})
