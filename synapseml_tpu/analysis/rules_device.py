"""Jaxpr-level device lint: the SMT1xx rule pack.

The AST pack (SMT001–009) stops at the Python source; the class of defect
that actually costs TPU cycles lives one layer down, in the traced
program: f64 leaks that double every matmul's bandwidth, host callbacks
that stall the device per step, transfers staged inside jit, collectives
over axis names no mesh declares, closure constants bloating every
executable's HBM footprint, and weak-typed scalar args churning the
``profiled_jit`` AOT cache (``smt_recompiles_total{cause="weak_type"}``).

This pack **abstract-evals** the repo's ``profiled_jit``-registered hot
entry points under canonical bench-lane-shaped signatures
(``jax.make_jaxpr`` — tracing only, no device execution, runs on any
backend) and walks the resulting jaxprs. Tracing happens under
``jax.experimental.enable_x64`` so *latent* f64 leaks — dtype-less
``jnp.zeros(...)``/numpy-f64 constants that today only stay f32 by the
grace of the global x64 flag — surface as findings instead of shipping.

Import discipline (enforced by ``tests/test_import_hygiene.py``): this
module is stdlib-only at import — jax is reached exclusively inside
:func:`run_device_pack` / the entry builders, so the default lint CLI and
``--list-rules`` stay jax-free; only ``--device`` pays for a trace.

Findings flow through the ordinary engine plumbing: rule codes register
in ``engine.RULES`` (so ``--select SMT101`` and ``--list-rules`` work),
findings anchor at the entry point's defining ``file:line`` and are
subject to the same ``LINT_ACKS.md`` waiver rows and the zero-unwaived
gate as the AST pack.
"""

from __future__ import annotations

import dataclasses
import inspect
import os
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .engine import Finding, Module, Rule, register

__all__ = [
    "DeviceEntry",
    "DeviceRule",
    "DEVICE_RULES",
    "default_device_entries",
    "trace_entry",
    "run_device_pack",
]

# closure constants above this footprint flag SMT105 unless the entry
# overrides (ONNX serving deliberately bakes model weights into the
# executable — entries carrying real models raise their own limit)
DEFAULT_CONST_BYTES = 256 << 20


@dataclasses.dataclass
class DeviceEntry:
    """One hot entry point to abstract-eval.

    ``build()`` runs under jax (lazily) and returns a dict with:

    - ``fn``: the callable to trace (statics already bound);
    - ``args`` / ``kwargs``: the canonical bench-lane-shaped example
      arguments (arrays stay abstract — tracing only);
    - optionally ``anchor``: ``(path, line)`` overriding the source
      anchor derived from ``fn`` (needed for shard_map-wrapped fns).
    """

    name: str
    build: Callable[[], Dict[str, Any]]
    policy: str = "float32"          # declared dtype policy (f64 never OK)
    mesh_axes: Tuple[str, ...] = ()  # declared mesh axis names
    const_bytes_limit: int = DEFAULT_CONST_BYTES
    hot: bool = True                 # host callbacks are findings only here


class TracedEntry:
    """A :class:`DeviceEntry` plus its traced ClosedJaxpr and anchor.

    ``x64_error`` is set when the entry could only trace with x64 OFF —
    SMT101's latent-leak visibility is lost for it, which is itself a
    (waivable) SMT101 finding, never a silent downgrade."""

    def __init__(self, entry: DeviceEntry, closed, anchor: Tuple[str, int],
                 x64_error: Optional[str] = None):
        self.entry = entry
        self.closed = closed         # jax ClosedJaxpr
        self.anchor = anchor         # (path, line) findings anchor
        self.x64_error = x64_error


# ---------------------------------------------------------------------------
# jaxpr traversal helpers (duck-typed: no jax import needed at call time
# beyond the objects already in hand)
# ---------------------------------------------------------------------------

def _sub_jaxprs(value) -> Iterable[Any]:
    """Jaxpr objects hiding inside one eqn param value (pjit carries a
    ClosedJaxpr, cond a tuple of branches, shard_map a bare Jaxpr)."""
    if value is None:
        return
    if hasattr(value, "eqns"):               # bare Jaxpr
        yield value
    elif hasattr(value, "jaxpr") and hasattr(getattr(value, "jaxpr"),
                                             "eqns"):  # ClosedJaxpr
        yield value.jaxpr
    elif isinstance(value, (list, tuple)):
        for v in value:
            yield from _sub_jaxprs(v)


def iter_eqns(jaxpr) -> Iterable[Any]:
    """Every eqn in ``jaxpr`` and, recursively, in any sub-jaxpr carried
    by an eqn's params (pjit / scan / cond / while / shard_map / pallas)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for value in eqn.params.values():
            for sub in _sub_jaxprs(value):
                yield from iter_eqns(sub)


def _aval_dtype_name(aval) -> Optional[str]:
    dtype = getattr(aval, "dtype", None)
    return getattr(dtype, "name", None)


# ---------------------------------------------------------------------------
# the rules
# ---------------------------------------------------------------------------

DEVICE_RULES: Dict[str, "DeviceRule"] = {}


def register_device(cls):
    """Register in BOTH the engine registry (``--select``/listing/waivers)
    and the device-pack registry (what :func:`run_device_pack` runs)."""
    register(cls)
    inst = DEVICE_RULES[cls.code] = cls()
    return cls


class DeviceRule(Rule):
    """A rule over traced entry points instead of source modules. The AST
    hook is inert — device rules only produce findings when the device
    pass runs (``--device``). ``ast_active = False`` tells the engine an
    AST-only run cannot judge these rules' waiver rows stale."""

    ast_active = False

    def check(self, module: Module) -> Iterable[Finding]:
        return []

    def check_entry(self, traced: TracedEntry) -> Iterable[Finding]:
        raise NotImplementedError  # pragma: no cover

    def entry_finding(self, traced: TracedEntry, message: str) -> Finding:
        path, line = traced.anchor
        return Finding(path=path, line=line, col=1, code=self.code,
                       message=f"[{traced.entry.name}] {message}")


@register_device
class F64Leak(DeviceRule):
    """SMT101 — float64 values in a hot entry point's traced program.

    TPUs have no f64 ALUs: every f64 op emulates at a many-x slowdown and
    doubles bandwidth, silently defeating the bf16/f32 policy. Entries are
    traced under ``enable_x64`` so the LATENT leaks (dtype-less
    ``jnp.zeros``, numpy-f64 closure constants) that the global x64=off
    flag currently papers over are caught before someone runs with x64 on.
    Fix: pin dtypes explicitly (``jnp.zeros(..., jnp.float32)``).
    """

    code = "SMT101"
    name = "device-f64-leak"
    rationale = ("f64 in a jitted hot path emulates on TPU and defeats "
                 "the bf16/f32 dtype policy")
    _MAX_REPORTS = 3

    def check_entry(self, traced: TracedEntry) -> Iterable[Finding]:
        findings: List[Finding] = []
        if traced.x64_error:
            # the x64 trace failing is USUALLY a latent dtype conflict —
            # exactly what this rule hunts; surface it as a waivable
            # finding instead of silently losing x64 visibility
            findings.append(self.entry_finding(
                traced,
                f"entry could not trace under enable_x64 (latent-f64 "
                f"visibility lost; the failure is often itself a dtype "
                f"conflict): {traced.x64_error}"))
        seen: Set[str] = set()
        hits = 0
        for i, const in enumerate(getattr(traced.closed, "consts", ())):
            if getattr(getattr(const, "dtype", None), "name", "") == "float64":
                hits += 1
                if len(findings) < self._MAX_REPORTS:
                    findings.append(self.entry_finding(
                        traced,
                        f"closure constant #{i} (shape "
                        f"{getattr(const, 'shape', '?')}) is float64; pin "
                        f"it to float32/bfloat16"))
        for eqn in iter_eqns(traced.closed.jaxpr):
            for var in eqn.outvars:
                if _aval_dtype_name(getattr(var, "aval", None)) == "float64":
                    hits += 1
                    prim = getattr(eqn.primitive, "name", "?")
                    if prim not in seen and len(findings) < self._MAX_REPORTS:
                        seen.add(prim)
                        findings.append(self.entry_finding(
                            traced,
                            f"primitive '{prim}' produces float64 under "
                            f"x64 (policy {traced.entry.policy}); pin the "
                            f"dtype explicitly (e.g. jnp.zeros(..., "
                            f"jnp.float32))"))
                    break
        if hits > len(findings) and findings:
            findings[-1] = dataclasses.replace(
                findings[-1],
                message=findings[-1].message
                + f" ({hits} f64 sites total in this entry)")
        return findings


@register_device
class HostCallbackInJit(DeviceRule):
    """SMT102 — host callbacks staged into a hot jitted program.

    ``pure_callback`` / ``io_callback`` / ``jax.debug.print`` /
    ``debug_callback`` round-trip device->host->device EVERY step; one
    stray debug print in a scan body serializes the whole pipeline behind
    the host. Debug-only uses belong outside the jitted path or behind a
    flag that drops them from the traced program.
    """

    code = "SMT102"
    name = "host-callback-in-jit"
    rationale = ("host callbacks in a jitted hot path stall the device on "
                 "a host round-trip every step")

    _CALLBACK_PRIMS = {"pure_callback", "io_callback", "debug_callback",
                       "debug_print", "outside_call", "host_callback_call"}

    def check_entry(self, traced: TracedEntry) -> Iterable[Finding]:
        if not traced.entry.hot:
            return []
        findings: List[Finding] = []
        seen: Set[str] = set()
        for eqn in iter_eqns(traced.closed.jaxpr):
            prim = getattr(eqn.primitive, "name", "?")
            if prim in self._CALLBACK_PRIMS and prim not in seen:
                seen.add(prim)
                findings.append(self.entry_finding(
                    traced,
                    f"host callback '{prim}' staged inside the jitted hot "
                    f"path; move it outside the traced program"))
        return findings


@register_device
class TransferInsideJit(DeviceRule):
    """SMT103 — explicit device transfers staged inside jit.

    ``jax.device_put`` under an active trace records a transfer/placement
    op in the compiled program — the placement should happen once at the
    call boundary (as every trainer here does before its step loop), not
    per executed step where it defeats XLA's layout freedom.
    """

    code = "SMT103"
    name = "transfer-inside-jit"
    rationale = ("device_put inside a jitted program re-stages placement "
                 "per step; place once at the call boundary")

    _TRANSFER_PRIMS = {"device_put", "copy"}

    def check_entry(self, traced: TracedEntry) -> Iterable[Finding]:
        findings: List[Finding] = []
        count = 0
        for eqn in iter_eqns(traced.closed.jaxpr):
            prim = getattr(eqn.primitive, "name", "?")
            if prim in self._TRANSFER_PRIMS:
                count += 1
                if count == 1:
                    findings.append(self.entry_finding(
                        traced,
                        f"'{prim}' staged inside the jitted program; move "
                        f"placement outside the traced fn"))
        if count > 1 and findings:
            findings[0] = dataclasses.replace(
                findings[0],
                message=findings[0].message + f" ({count} sites)")
        return findings


@register_device
class CollectiveAxisMismatch(DeviceRule):
    """SMT104 — a collective over an axis name the entry does not declare.

    ``psum``/``ppermute``/``all_to_all`` bind an axis NAME resolved at run
    time against the enclosing mesh; a typo'd or stale name is invisible
    until a pod run dies (or worse, silently reduces over the wrong
    axis when meshes nest). Every entry declares its mesh axes
    (``DeviceEntry.mesh_axes``); collectives must stay inside them.
    """

    code = "SMT104"
    name = "collective-axis-mismatch"
    rationale = ("collectives over undeclared axis names fail (or reduce "
                 "wrongly) only once a real mesh is attached")

    _COLLECTIVE_PRIMS = {"psum", "pmax", "pmin", "ppermute", "pbroadcast",
                         "all_gather", "all_to_all", "reduce_scatter",
                         "axis_index"}

    @staticmethod
    def _axis_names(eqn) -> List[str]:
        names: List[str] = []
        for key in ("axes", "axis_name"):
            v = eqn.params.get(key)
            if v is None:
                continue
            for name in v if isinstance(v, (tuple, list)) else (v,):
                if isinstance(name, str):
                    names.append(name)
        return names

    def check_entry(self, traced: TracedEntry) -> Iterable[Finding]:
        declared = set(traced.entry.mesh_axes)
        findings: List[Finding] = []
        seen: Set[Tuple[str, str]] = set()
        for eqn in iter_eqns(traced.closed.jaxpr):
            prim = getattr(eqn.primitive, "name", "?")
            if prim not in self._COLLECTIVE_PRIMS:
                continue
            for axis in self._axis_names(eqn):
                if axis in declared or (prim, axis) in seen:
                    continue
                seen.add((prim, axis))
                findings.append(self.entry_finding(
                    traced,
                    f"collective '{prim}' binds axis name {axis!r} but the "
                    f"entry declares mesh axes "
                    f"{sorted(declared) if declared else 'NONE'}"))
        return findings


@register_device
class HbmBloatConstant(DeviceRule):
    """SMT105 — closure constants above the HBM-bloat threshold.

    Arrays captured by closure are baked into EVERY compiled executable of
    the entry (one copy per shape signature) and live in HBM for the
    executable's lifetime — ``smt_device_hbm_peak_bytes`` pays for them
    whether or not the entry runs. Big operands belong in the argument
    list (donated or sharded); only genuine model weights (ONNX) get a
    raised per-entry limit.
    """

    code = "SMT105"
    name = "hbm-bloat-constant"
    rationale = ("closure constants replicate into every compiled "
                 "executable and squat in HBM for its lifetime")

    def check_entry(self, traced: TracedEntry) -> Iterable[Finding]:
        limit = traced.entry.const_bytes_limit
        findings: List[Finding] = []
        for i, const in enumerate(getattr(traced.closed, "consts", ())):
            nbytes = getattr(const, "nbytes", 0) or 0
            if nbytes > limit:
                findings.append(self.entry_finding(
                    traced,
                    f"closure constant #{i} (shape "
                    f"{getattr(const, 'shape', '?')}, "
                    f"{nbytes / (1 << 20):.1f} MiB) exceeds the "
                    f"{limit / (1 << 20):.0f} MiB HBM-bloat threshold; "
                    f"pass it as an argument instead"))
        return findings


@register_device
class WeakTypeChurn(DeviceRule):
    """SMT106 — weak-typed scalar arguments in a hot entry's signature.

    A python scalar argument traces as a WEAK-typed aval; the same call
    site passing a numpy scalar (or a jax array) later produces a
    different abstract signature and recompiles — exactly what
    ``smt_recompiles_total{cause="weak_type"}`` counts in production.
    When the live registry has recorded such churn for the entry, the
    finding says so; either way the fix is the same: coerce scalars at
    the boundary (``jnp.float32(x)`` / ``np.asarray(x, np.float32)``) or
    make the argument static.
    """

    code = "SMT106"
    name = "weak-type-churn"
    rationale = ("weak-typed scalar args flip the abstract signature "
                 "between python/numpy callers and churn the AOT cache")

    @staticmethod
    def _live_weak_type_recompiles() -> Dict[str, float]:
        """fn -> recorded weak_type recompiles from the process registry
        (``observability`` is stdlib-only; absence of data is fine)."""
        try:
            from ..observability import get_registry

            fam = get_registry().snapshot()["families"].get(
                "smt_recompiles_total")
            if not fam:
                return {}
            li = {n: i for i, n in enumerate(fam["labelnames"])}
            out: Dict[str, float] = {}
            for s in fam["series"]:
                if s["labels"][li["cause"]] == "weak_type":
                    fn = s["labels"][li["fn"]]
                    out[fn] = out.get(fn, 0.0) + float(s["value"])
            return out
        except Exception:
            return {}

    def check_entry(self, traced: TracedEntry) -> Iterable[Finding]:
        findings: List[Finding] = []
        churn = self._live_weak_type_recompiles().get(traced.entry.name)
        for i, aval in enumerate(getattr(traced.closed, "in_avals", ())):
            if getattr(aval, "weak_type", False):
                extra = (f"; profiling has recorded {churn:.0f} weak_type "
                         f"recompile(s) for this entry" if churn else "")
                findings.append(self.entry_finding(
                    traced,
                    f"argument #{i} ({aval}) is weak-typed — a python "
                    f"scalar here recompiles against numpy/array callers; "
                    f"coerce at the boundary or make it static{extra}"))
        return findings


# ---------------------------------------------------------------------------
# canonical entry points
# ---------------------------------------------------------------------------

def _build_flash_entry() -> Dict[str, Any]:
    """``flash.attention`` (``parallel/flash._flash_bh_impl``) under a
    shrunk ``flash_attention_gqa`` bench-lane signature: (B*H, S, D) bf16
    with the statics bound the way ``flash_attention`` binds them."""
    import functools

    import numpy as np

    from ..parallel import flash

    q = np.zeros((4, 256, 64), np.dtype("bfloat16"))
    k = np.zeros((4, 256, 64), np.dtype("bfloat16"))
    v = np.zeros((4, 256, 64), np.dtype("bfloat16"))
    # interpret=True: the kernel body traces identically, and the Mosaic
    # compiler-params path needs TPU plugin versions the lint host may
    # not have — tracing is the point here, not lowering
    fn = functools.partial(flash._flash_bh_impl, causal=True, block_q=128,
                           block_k=128, rep=1, interpret=True)
    return {"fn": fn, "args": (q, k, v),
            "anchor_obj": flash._flash_bh_impl}


def _tiny_mlp_bytes():
    """A small MatMul+Add+Relu+MatMul graph (the shape of the codegen /
    test_onnx models) through the repo's own builder — jax-free."""
    import numpy as np

    from ..onnx import builder
    from ..onnx.wire import serialize_model

    rng = np.random.default_rng(0)
    w1 = rng.normal(size=(16, 32)).astype(np.float32)
    b1 = rng.normal(size=(32,)).astype(np.float32)
    w2 = rng.normal(size=(32, 8)).astype(np.float32)
    g = builder.make_graph(
        [builder.constant_node("w1", w1),
         builder.constant_node("b1", b1),
         builder.constant_node("w2", w2),
         builder.node("MatMul", ["x", "w1"], ["h0"]),
         builder.node("Add", ["h0", "b1"], ["h1"]),
         builder.node("Relu", ["h1"], ["h2"]),
         builder.node("MatMul", ["h2", "w2"], ["y"])],
        "mlp",
        [builder.value_info("x", np.float32, [None, 16])],
        [builder.value_info("y", np.float32, [None, 8])])
    return serialize_model(builder.make_model(g))


def _build_onnx_entry(policy: str) -> Callable[[], Dict[str, Any]]:
    def build() -> Dict[str, Any]:
        import numpy as np

        from ..onnx.importer import OnnxFunction

        of = OnnxFunction(_tiny_mlp_bytes(), dtype_policy=policy)
        x = np.zeros((8, 16), np.float32)
        return {"fn": of._run_positional, "args": (x,)}

    return build


def _gbdt_grow_inputs():
    import numpy as np

    from ..gbdt.grow import TreeConfig

    rng = np.random.default_rng(0)
    n, d, B = 64, 4, 8
    binned = rng.integers(0, B, size=(n, d)).astype(np.int32)
    g = rng.normal(size=n).astype(np.float32)
    h = np.ones(n, np.float32)
    w = np.ones(n, np.float32)
    fmask = np.ones(d, np.float32)
    return binned, g, h, w, fmask, TreeConfig, B


def _build_gbdt_grow_entry() -> Dict[str, Any]:
    """``gbdt.iter``'s kernel (``grow.grow_tree``) in single-chip
    data-parallel shape — the Adult-scale bench lane shrunk."""
    from ..gbdt import grow

    binned, g, h, w, fmask, TreeConfig, B = _gbdt_grow_inputs()
    cfg = TreeConfig(n_bins=B, num_leaves=4)

    def fn(b, gg, hh, ww, fm):
        return grow.grow_tree(b, gg, hh, ww, fm, cfg)

    return {"fn": fn, "args": (binned, g, h, w, fmask),
            "anchor_obj": grow.grow_tree}


def _build_gbdt_voting_entry() -> Dict[str, Any]:
    """``gbdt.iter_sharded`` in voting-parallel mode over a 1-device mesh
    (the PV-tree vote path: per-shard top-k vote, psum'd candidates) —
    the distributed configuration SMT104/SMT101 most need to see."""
    import jax

    from ..gbdt import grow
    from ..runtime.layout import SpecLayout

    binned, g, h, w, fmask, TreeConfig, B = _gbdt_grow_inputs()
    cfg = TreeConfig(n_bins=B, num_leaves=4, parallelism="voting", top_k=2)
    layout = SpecLayout.build(devices=jax.devices("cpu")[:1],
                              model_axis=None)
    data, rep = layout.batch(), layout.replicated()

    def body(b, gg, hh, ww, fm):
        return grow.grow_tree(b, gg, hh, ww, fm, cfg,
                              axis_name=layout.data_axis)

    fn = layout.shard_map(body,
                          in_specs=(data, data, data, data, rep),
                          out_specs=(rep, data), check=False)
    return {"fn": fn, "args": (binned, g, h, w, fmask),
            "anchor_obj": grow.grow_tree}


def _build_gbdt_feature_parallel_entry() -> Dict[str, Any]:
    """``gbdt.iter_sharded`` over a 2-D ``(data, model)`` ``SpecLayout``
    mesh — the feature-parallel histogram path (features over ``model``,
    stats ``psum``'d per axis). The jaxpr binds BOTH axis names, so
    SMT104 verifies collectives against a 2-D declaration."""
    import jax

    from ..gbdt import grow
    from ..runtime.layout import SpecLayout

    binned, g, h, w, fmask, TreeConfig, B = _gbdt_grow_inputs()
    cfg = TreeConfig(n_bins=B, num_leaves=4)
    layout = SpecLayout.build(data=1, model=1,
                              devices=jax.devices("cpu")[:1])
    data, rep = layout.batch(), layout.replicated()

    def body(b, gg, hh, ww, fm):
        return grow.grow_tree(b, gg, hh, ww, fm, cfg,
                              axis_name=layout.data_axis,
                              model_axis_name=layout.model_axis)

    fn = layout.shard_map(body,
                          in_specs=(data, data, data, data, rep),
                          out_specs=(rep, data), check=False)
    return {"fn": fn, "args": (binned, g, h, w, fmask),
            "anchor_obj": grow.grow_tree}


def default_device_entries() -> List[DeviceEntry]:
    """The canonical hot entry points, one per ``profiled_jit`` family the
    bench lanes exercise (docs/analysis.md lists the mapping)."""
    return [
        DeviceEntry("flash.attention", _build_flash_entry,
                    policy="bfloat16"),
        DeviceEntry("onnx.mlp", _build_onnx_entry("float32"),
                    policy="float32"),
        DeviceEntry("onnx.mlp[bf16]", _build_onnx_entry("bfloat16"),
                    policy="bfloat16"),
        DeviceEntry("gbdt.grow", _build_gbdt_grow_entry,
                    policy="float32"),
        DeviceEntry("gbdt.grow[voting,sharded]", _build_gbdt_voting_entry,
                    policy="float32", mesh_axes=("data",)),
        DeviceEntry("gbdt.grow[feature-parallel,2d]",
                    _build_gbdt_feature_parallel_entry,
                    policy="float32", mesh_axes=("data", "model")),
    ]


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def _repo_root() -> str:
    """The checkout root (three levels above this file) — the same anchor
    LINT_ACKS.md lives at, so device findings stay waiver-matchable even
    when the caller passes no root (e.g. ``--no-acks`` CLI runs)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _anchor_of(built: Dict[str, Any], root: Optional[str]
               ) -> Tuple[str, int]:
    obj = built.get("anchor_obj") or built.get("fn")
    if "anchor" in built:
        path, line = built["anchor"]
    else:
        try:
            while hasattr(obj, "func"):  # unwrap functools.partial
                obj = obj.func
            obj = inspect.unwrap(obj)
            path = inspect.getsourcefile(obj) or "<unknown>"
            line = inspect.getsourcelines(obj)[1]
        except (TypeError, OSError):
            path, line = "<unknown>", 1
    if os.path.isabs(path) or os.path.exists(path):
        path = os.path.abspath(path)
    root_abs = os.path.abspath(root) if root else _repo_root()
    if path.startswith(root_abs + os.sep):
        path = os.path.relpath(path, root_abs)
    return path.replace(os.sep, "/"), int(line)


def trace_entry(entry: DeviceEntry, root: Optional[str] = None
                ) -> TracedEntry:
    """Abstract-eval one entry: build its fn + canonical args, trace with
    ``jax.make_jaxpr`` under ``enable_x64`` (latent-f64 visibility).
    When the x64 trace fails but a plain trace works, the failure is
    recorded on the TracedEntry — SMT101 reports it as a finding instead
    of a silent visibility downgrade. Tracing only — no compile, no
    device execution."""
    import jax

    built = entry.build()
    fn = built["fn"]
    args = built.get("args", ())
    kwargs = built.get("kwargs", {})
    x64_error = None
    try:
        from jax.experimental import enable_x64

        with enable_x64():
            closed = jax.make_jaxpr(fn)(*args, **kwargs)
    except Exception as e:
        x64_error = f"{type(e).__name__}: {e}"
        closed = jax.make_jaxpr(fn)(*args, **kwargs)
    return TracedEntry(entry, closed, _anchor_of(built, root),
                       x64_error=x64_error)


def run_device_pack(entries: Optional[Sequence[DeviceEntry]] = None,
                    select: Optional[Sequence[str]] = None,
                    root: Optional[str] = None
                    ) -> Tuple[List[Finding], List[str]]:
    """Trace every entry and run the (selected) device rules over the
    jaxprs. Returns ``(findings, errors)`` — an entry whose trace fails
    is an ERROR (the gate must see it), not a silent skip."""
    codes = [c for c in (select or sorted(DEVICE_RULES))
             if c in DEVICE_RULES]
    if not codes:
        # selection excludes every device rule: don't pay for (or fail
        # on) traces that cannot produce a finding
        return [], []
    if entries is None:
        entries = default_device_entries()
    findings: List[Finding] = []
    errors: List[str] = []
    for entry in entries:
        try:
            traced = trace_entry(entry, root=root)
        except Exception as e:
            errors.append(f"device entry {entry.name!r} failed to trace: "
                          f"{type(e).__name__}: {e}")
            continue
        for code in codes:
            findings.extend(DEVICE_RULES[code].check_entry(traced))
    return findings, errors
