"""SPMD static verifier: the SMT11x sharding-aware rule pack.

The device pack (SMT10x) abstract-evals entry points on ONE device; the
class of defect that costs a *mesh* lives in what GSPMD does with the
program: large tensors silently resident fully-replicated across a
populated model axis (every fsdp blocker looks like this), conflicting
``with_sharding_constraint`` chains that force an implicit reshard on a
hot path, host fallbacks that are only reachable in the mesh
configuration (``use_device_bin`` required ``mesh is None`` for three
arcs — the binning searchsorted ran on host exactly when 8 chips were
waiting, until the device-side distributed binning change made the flag
mesh-capable), and mesh-vs-single-device traces that structurally
diverge where they should not (the bisection instrument
``test_sparse_mesh_matches_single_device`` needs).

This pack traces the canonical entry points under representative
``SpecLayout``s — (1, 1), (4, 2) feature-parallel, and a (1, 2, 2)
fsdp+tensor-parallel ONNX serving layout — and walks the jaxprs with
sharding awareness. Two rules additionally run as ordinary AST rules in the
default jax-free pass (SMT112's host-fallback-guard half and SMT114's
refusal-guard inventory), so the debt they enumerate cannot silently
grow even when no one pays for a trace.

Import discipline (enforced by ``tests/test_import_hygiene.py``): this
module is stdlib-only at import — jax is reached exclusively inside
:func:`run_spmd_pack` / the entry builders / :func:`trace_spmd_entry`,
so the default lint CLI and ``--list-rules`` stay jax-free; only
``--spmd`` pays for a trace.

Findings flow through the ordinary engine plumbing: codes register in
``engine.RULES``, findings anchor at the entry's defining ``file:line``
and are subject to the same ``LINT_ACKS.md`` waiver rows and the
zero-unwaived gate as every other pack. ``tools/spmd_diff.py`` exposes
the SMT113 differential (canonicalize + diff) as a standalone CLI.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
import sys
from typing import (Any, Callable, Dict, Iterable, List, Optional, Sequence,
                    Set, Tuple)

from .engine import Finding, Module, Rule, register, walk_scoped
from .rules_device import (_anchor_of, _gbdt_grow_inputs, _sub_jaxprs,
                           iter_eqns)

__all__ = [
    "SpmdEntry",
    "SpmdRule",
    "SPMD_RULES",
    "default_spmd_entries",
    "differential_entry_names",
    "trace_spmd_entry",
    "run_spmd_pack",
    "canonical_lines",
    "structural_diff",
]

# a tensor resident fully-replicated across a populated model axis above
# this footprint flags SMT110 (per-entry override for entries whose
# weights are legitimately small)
DEFAULT_REPLICATED_BYTES = 1 << 20


@dataclasses.dataclass
class SpmdEntry:
    """One entry point to trace under a representative ``SpecLayout``.

    ``build()`` runs under jax (lazily) and returns a dict with:

    - ``fn`` / ``args`` / ``kwargs``: the mesh-configured callable and its
      canonical example arguments (tracing only — arrays stay abstract);
    - optionally ``single_fn`` / ``single_args`` / ``single_kwargs``: the
      SAME computation in its single-device configuration — the
      differential twin SMT112's jaxpr half and SMT113 diff against;
    - optionally ``layout``: the ``SpecLayout`` the entry traced under
      (axis sizes gate SMT110 — a 1-wide model axis replicates nothing);
    - optionally ``placement_report``: the entry's own per-tensor
      residency decisions (``OnnxFunction.placement_report()``) so SMT110
      can name the tensor and the planner decision that replicated it;
    - optionally ``anchor`` / ``anchor_obj``: the findings anchor.
    """

    name: str
    build: Callable[[], Dict[str, Any]]
    mesh_axes: Tuple[str, ...] = ()
    replicated_bytes_limit: int = DEFAULT_REPLICATED_BYTES
    hot: bool = True


class TracedSpmdEntry:
    """An :class:`SpmdEntry` plus its traced jaxpr(s) and metadata."""

    def __init__(self, entry: SpmdEntry, closed, anchor: Tuple[str, int],
                 single=None, layout=None,
                 placement: Optional[Sequence[Dict[str, Any]]] = None):
        self.entry = entry
        self.closed = closed          # mesh-configuration ClosedJaxpr
        self.single = single          # single-device ClosedJaxpr or None
        self.anchor = anchor          # (path, line) findings anchor
        self.layout = layout          # SpecLayout or None
        self.placement = list(placement or [])

    @property
    def model_size(self) -> int:
        return int(getattr(self.layout, "model_size", 1) or 1)


# ---------------------------------------------------------------------------
# jaxpr canonicalization + structural diff (SMT113 / tools/spmd_diff.py)
# ---------------------------------------------------------------------------

# primitives that MUST differ between the mesh and single-device traces —
# collectives and placement pins only exist under a mesh; stripping them
# is what makes the remaining diff signal
_STRIP_PRIMS = frozenset({
    "psum", "pmax", "pmin", "pmean", "ppermute", "pbroadcast", "all_gather",
    "all_to_all", "reduce_scatter", "psum_scatter", "axis_index",
    "sharding_constraint",
})

# structural wrappers: descend into the sub-jaxpr without emitting a line
# (shard_map exists only mesh-side; pjit nesting is a staging artifact)
_TRANSPARENT_PRIMS = frozenset({
    "pjit", "jit", "closed_call", "core_call", "named_call", "shard_map",
    "custom_jvp_call", "custom_vjp_call", "custom_jvp_call_jaxpr",
    "custom_vjp_call_jaxpr", "remat", "remat2", "checkpoint",
})


def canonical_lines(closed) -> List[str]:
    """Render a ClosedJaxpr as a canonical line stream for diffing.

    One line per eqn, in trace order, recursing through control flow
    (scan/cond bodies are structure and stay; pjit/shard_map wrappers are
    transparent; collectives that must differ are stripped). Variable
    names never appear; dimension SIZES are alpha-renamed PER LINE in
    first-seen order (``d0, d1, ...``) so a 192-row single-device trace
    lines up with its 48-row-per-shard mesh twin when — and only when —
    the primitive structure matches. The renaming is line-local on
    purpose: a global mapping would let one extra mesh-side eqn near the
    head (the per-shard RNG fold) shift every later symbol and turn a
    4-line divergence into a whole-trace one.
    """
    lines: List[str] = []

    def rec(jaxpr) -> None:
        for eqn in jaxpr.eqns:
            prim = getattr(eqn.primitive, "name", "?")
            if prim in _STRIP_PRIMS:
                continue
            subs = [s for v in eqn.params.values() for s in _sub_jaxprs(v)]
            if prim in _TRANSPARENT_PRIMS:
                for s in subs:
                    rec(s)
                continue
            sym: Dict[Any, str] = {}

            def dim(s) -> str:
                try:
                    s = int(s)
                except (TypeError, ValueError):
                    return str(s)
                if s not in sym:
                    sym[s] = f"d{len(sym)}"
                return sym[s]

            def aval_str(v) -> str:
                aval = getattr(v, "aval", None)
                if aval is None:
                    return "?"
                dt = getattr(getattr(aval, "dtype", None), "name", "?")
                shape = getattr(aval, "shape", ())
                return f"{dt}[{','.join(dim(s) for s in shape)}]"

            ins = ",".join(aval_str(v) for v in eqn.invars)
            outs = ",".join(aval_str(v) for v in eqn.outvars)
            lines.append(f"{prim}({ins})->({outs})")
            for s in subs:
                rec(s)

    rec(closed.jaxpr)
    return lines


def structural_diff(mesh_lines: Sequence[str], single_lines: Sequence[str]
                    ) -> Optional[Dict[str, Any]]:
    """Structurally divergent regions between two canonical streams.

    A real LCS diff (``difflib``), not prefix/suffix trimming: the
    canonical mesh-side extra region (the per-shard RNG fold) sits at the
    very HEAD of the trace, where prefix matching would report the entire
    trace as divergent. Returns ``None`` when the streams are identical,
    else a dict naming the FIRST divergence — ``index`` (eqns shared
    before it), ``mesh_only`` / ``single_only`` line runs,
    ``common_suffix`` (eqns shared after the LAST divergence) — plus the
    full ``hunks`` list for the CLI.
    """
    import difflib

    a, b = list(mesh_lines), list(single_lines)
    sm = difflib.SequenceMatcher(None, a=a, b=b, autojunk=False)
    hunks = [{"mesh_index": i1, "single_index": j1,
              "mesh_only": a[i1:i2], "single_only": b[j1:j2]}
             for tag, i1, i2, j1, j2 in sm.get_opcodes() if tag != "equal"]
    if not hunks:
        return None
    first, last = hunks[0], hunks[-1]
    return {
        "index": first["mesh_index"],
        "common_suffix": len(a) - (last["mesh_index"]
                                   + len(last["mesh_only"])),
        "mesh_only": first["mesh_only"],
        "single_only": first["single_only"],
        "hunks": hunks,
    }


# ---------------------------------------------------------------------------
# the rules
# ---------------------------------------------------------------------------

SPMD_RULES: Dict[str, "SpmdRule"] = {}


def register_spmd(cls):
    """Register in BOTH the engine registry (``--select``/listing/waivers)
    and the spmd-pack registry (what :func:`run_spmd_pack` runs)."""
    register(cls)
    inst = SPMD_RULES[cls.code] = cls()
    return cls


class SpmdRule(Rule):
    """A rule over layout-parameterized traced entries. The AST hook is
    inert unless a subclass opts in (``ast_active = True``) — the engine
    uses the flag to decide which waiver rows a jax-free run may judge
    stale."""

    ast_active = False  # pure jaxpr rules produce nothing in AST runs

    def check(self, module: Module) -> Iterable[Finding]:
        return []

    def check_entry(self, traced: TracedSpmdEntry) -> Iterable[Finding]:
        raise NotImplementedError  # pragma: no cover

    def entry_finding(self, traced: TracedSpmdEntry, message: str) -> Finding:
        path, line = traced.anchor
        return Finding(path=path, line=line, col=1, code=self.code,
                       message=f"[{traced.entry.name}] {message}")


def _spec_axis_names(spec) -> Set[str]:
    """Axis names a PartitionSpec actually binds (entries are str, tuple
    of str, or None)."""
    names: Set[str] = set()
    for part in tuple(spec or ()):
        if isinstance(part, str):
            names.add(part)
        elif isinstance(part, (tuple, list)):
            names.update(p for p in part if isinstance(p, str))
    return names


@register_spmd
class ReplicatedResidency(SpmdRule):
    """SMT110 — a large tensor resident fully-replicated under a
    populated model axis.

    Sharding a model over ``model=m`` chips only buys HBM headroom for
    the tensors that actually shard; every tensor the planner silently
    replicates costs ``(m-1)/m`` of its bytes times ``m`` chips — and the
    ONNX tp planner replicates on ANY consumer-role conflict, indivisible
    dim, or non-float dtype without telling anyone. This rule makes each
    such decision a named finding (tensor, bytes, planner reason) so the
    fsdp work (ROADMAP item 4) starts from an inventory instead of a
    surprise OOM. Entries that expose a ``placement_report`` (the ONNX
    importer) get per-tensor attribution; for the rest, closure constants
    whose committed sharding leaves the model axis unused are flagged.
    """

    code = "SMT110"
    name = "replicated-residency"
    rationale = ("tensors resident fully-replicated across a populated "
                 "model axis forfeit the HBM headroom sharding exists "
                 "to buy")

    def check_entry(self, traced: TracedSpmdEntry) -> Iterable[Finding]:
        if traced.model_size <= 1:
            return []  # nothing to replicate ACROSS on a 1-wide model axis
        limit = traced.entry.replicated_bytes_limit
        layout = traced.layout
        model_axis = getattr(layout, "model_axis", None)
        findings: List[Finding] = []
        if traced.placement:
            # the entry planner knows tensor names and WHY it replicated:
            # report its decisions verbatim (the jaxpr consts below would
            # double-count the same arrays namelessly)
            for row in traced.placement:
                if row.get("decision") != "replicated":
                    continue
                nbytes = int(row.get("nbytes", 0) or 0)
                if nbytes <= limit:
                    continue
                findings.append(self.entry_finding(
                    traced,
                    f"tensor {row.get('tensor', '?')!r} "
                    f"(shape {row.get('shape', '?')}, "
                    f"{nbytes / 1024:.0f} KiB) is resident fully-replicated "
                    f"across the populated model axis "
                    f"({model_axis}={traced.model_size}); planner decision: "
                    f"{row.get('reason', 'unrecorded')}"))
            return findings
        for i, const in enumerate(getattr(traced.closed, "consts", ())):
            nbytes = int(getattr(const, "nbytes", 0) or 0)
            if nbytes <= limit:
                continue
            sharding = getattr(const, "sharding", None)
            spec = getattr(sharding, "spec", None)
            # numpy constants (no sharding) replicate onto every chip; a
            # NamedSharding whose spec never binds the model axis
            # replicates across it
            if sharding is not None and spec is None:
                continue  # opaque sharding: cannot judge, stay silent
            if spec is not None and model_axis in _spec_axis_names(spec):
                continue
            findings.append(self.entry_finding(
                traced,
                f"closure constant #{i} (shape "
                f"{getattr(const, 'shape', '?')}, {nbytes / 1024:.0f} KiB) "
                f"is resident fully-replicated across the populated model "
                f"axis ({model_axis}={traced.model_size}); shard it "
                f"(layout.col_weight/feature_blocks) or pass it as a "
                f"sharded argument"))
        return findings


@register_spmd
class ConstraintConflict(SpmdRule):
    """SMT111 — conflicting sharding constraints on one value chain.

    ``with_sharding_constraint`` is a promise to GSPMD; two different
    promises about the same value force the partitioner to materialize an
    implicit all-gather/reshard between them — bandwidth spent on a
    placement disagreement, invisible in the source because each
    constraint looks locally reasonable. Flags any value that is
    re-constrained to a different spec (directly chained or fanned out
    from the same producer). One chain is sanctioned: the fsdp
    all-gather-on-use re-pin (``layout.gather_for_use``), where the later
    spec is exactly the earlier spec with the layout's fsdp axis dropped
    — that reshard is the POINT (transient gathered copy, row-sharded
    residency), not a disagreement.
    """

    code = "SMT111"
    name = "sharding-constraint-conflict"
    rationale = ("re-constraining a value to a different spec forces "
                 "GSPMD to insert an implicit reshard on the hot path")

    @staticmethod
    def _constraint_spec(eqn) -> Optional[Any]:
        s = eqn.params.get("sharding")
        if s is None:
            return None
        return getattr(s, "spec", s)

    @staticmethod
    def _is_fsdp_repin(layout, a, b) -> bool:
        """True when one spec is the other's all-gathered *use* form under
        the layout's fsdp axis — the intentional stored→use re-pin (or the
        symmetric use→stored re-shard after an update step)."""
        use_spec = getattr(layout, "use_spec", None)
        if use_spec is None or getattr(layout, "fsdp_axis", None) is None:
            return False
        try:
            return use_spec(a) == b or use_spec(b) == a
        except Exception:
            return False

    def check_entry(self, traced: TracedSpmdEntry) -> Iterable[Finding]:
        if not traced.entry.hot:
            return []
        findings: List[Finding] = []
        committed: Dict[int, Any] = {}   # id(var) -> spec committed to it
        seen_pairs: Set[Tuple[str, str]] = set()
        for eqn in iter_eqns(traced.closed.jaxpr):
            prim = getattr(eqn.primitive, "name", "?")
            if prim != "sharding_constraint":
                continue
            spec = self._constraint_spec(eqn)
            if spec is None:
                continue
            key = str(spec)
            for var in eqn.invars:
                prev = committed.get(id(var))
                if prev is None:
                    continue
                pkey = str(prev)
                if pkey != key and (pkey, key) not in seen_pairs \
                        and not self._is_fsdp_repin(traced.layout,
                                                    prev, spec):
                    seen_pairs.add((pkey, key))
                    findings.append(self.entry_finding(
                        traced,
                        f"value constrained to {pkey} is re-constrained to "
                        f"{key} — GSPMD must insert an implicit "
                        f"all-gather/reshard between the two pins; agree on "
                        f"one spec per value"))
            for var in eqn.outvars:
                committed[id(var)] = spec
            # the constraint output carries the same value: a later
            # constraint on the INPUT var conflicts with this one too
            for var in eqn.invars:
                committed.setdefault(id(var), spec)
        return findings


_MESHISH_NAMES = ("mesh", "layout")
_CALLBACK_CALLS = ("pure_callback", "io_callback", "debug_callback")


def _compares_mesh_to_none(node: ast.AST, negated: bool) -> Optional[str]:
    """``<mesh> is None`` (negated=False) / ``is not None`` (True) —
    returns the compared name when the node is that comparison."""
    if not isinstance(node, ast.Compare) or len(node.ops) != 1:
        return None
    op = node.ops[0]
    if not isinstance(op, ast.IsNot if negated else ast.Is):
        return None
    if not (isinstance(node.comparators[0], ast.Constant)
            and node.comparators[0].value is None):
        return None
    left = node.left
    name = left.id if isinstance(left, ast.Name) else \
        left.attr if isinstance(left, ast.Attribute) else None
    if name and any(m in name.lower() for m in _MESHISH_NAMES):
        return name
    return None


@register_spmd
class HostFallbackUnderMesh(SpmdRule):
    """SMT112 — host fallback reachable only in the mesh configuration.

    The worst scaling bug is the one that only exists when the hardware
    shows up: a device-side fast path gated on ``mesh is None`` means the
    mesh configuration — the one with 8 chips waiting — does the work on
    the HOST (the ``use_device_bin`` searchsorted guard was the canonical
    true finding — mesh fits binned multi-million-row matrices in numpy
    for three arcs — until device-side distributed binning removed it).
    Two halves: an AST pass (jax-free, always on) flags device-path flags
    that require ``mesh is None`` and host callbacks lexically gated on
    ``mesh is not None``; the ``--spmd`` jaxpr pass flags host-callback
    primitives present in an entry's mesh trace but absent from its
    single-device twin.
    """

    code = "SMT112"
    name = "host-fallback-under-mesh"
    rationale = ("a device path gated on `mesh is None` means the mesh "
                 "configuration does the work on the host, serializing "
                 "every chip behind it")
    ast_active = True

    _DEVICEISH = re.compile(r"device|dev_bin|on_dev", re.IGNORECASE)

    def check(self, module: Module) -> Iterable[Finding]:
        findings: List[Finding] = []

        def visit(node: ast.AST, ctx) -> None:
            if isinstance(node, ast.Assign):
                targets = [t.id for t in node.targets
                           if isinstance(t, ast.Name)]
                if not any(self._DEVICEISH.search(t) for t in targets):
                    return
                for sub in ast.walk(node.value):
                    mesh_name = _compares_mesh_to_none(sub, negated=False)
                    if mesh_name:
                        findings.append(self.finding(
                            module, node,
                            f"device-path flag "
                            f"{[t for t in targets if self._DEVICEISH.search(t)][0]!r} "
                            f"requires '{mesh_name} is None' — the device "
                            f"path is unreachable under a mesh, so the mesh "
                            f"configuration falls back to the host; make "
                            f"the path mesh-capable or record the debt"))
                        return
            if isinstance(node, ast.If):
                gated_body: List[ast.stmt] = []
                for sub in ast.walk(node.test):
                    if _compares_mesh_to_none(sub, negated=True):
                        gated_body = node.body
                        break
                    if _compares_mesh_to_none(sub, negated=False):
                        gated_body = node.orelse
                        break
                for stmt in gated_body:
                    for sub in ast.walk(stmt):
                        if isinstance(sub, ast.Call):
                            callee = sub.func
                            cname = callee.attr if isinstance(
                                callee, ast.Attribute) else getattr(
                                callee, "id", None)
                            if cname in _CALLBACK_CALLS:
                                findings.append(self.finding(
                                    module, sub,
                                    f"host callback '{cname}' is reachable "
                                    f"only under a mesh — the distributed "
                                    f"configuration stalls every chip on a "
                                    f"host round-trip the single-device "
                                    f"path never pays"))

        walk_scoped(module.tree, visit)
        return findings

    _CALLBACK_PRIMS = frozenset({"pure_callback", "io_callback",
                                 "debug_callback", "outside_call",
                                 "host_callback_call"})

    def _callback_prims(self, closed) -> Set[str]:
        return {getattr(e.primitive, "name", "?")
                for e in iter_eqns(closed.jaxpr)
                if getattr(e.primitive, "name", "?") in self._CALLBACK_PRIMS}

    def check_entry(self, traced: TracedSpmdEntry) -> Iterable[Finding]:
        if traced.single is None:
            return []
        mesh_only = (self._callback_prims(traced.closed)
                     - self._callback_prims(traced.single))
        return [self.entry_finding(
            traced,
            f"host callback '{prim}' is staged in the mesh trace but "
            f"absent from the single-device trace — host fallback "
            f"reachable only in the mesh configuration")
            for prim in sorted(mesh_only)]


@register_spmd
class MeshDivergence(SpmdRule):
    """SMT113 — structural mesh-vs-single-device jaxpr divergence.

    The sparse mesh parity failure (``test_sparse_mesh_matches_single_
    device``) is a needle in a 400-eqn haystack; diffing the two traces
    after canonicalization (collectives stripped, names/dims
    alpha-renamed) names the FIRST structurally divergent region — the
    place a bisection starts. An entry whose twins should be structurally
    identical and are not is a finding; known-divergent entries carry a
    reasoned LINT_ACKS row that documents exactly which region is
    accepted. ``tools/spmd_diff.py`` prints the full region.
    """

    code = "SMT113"
    name = "mesh-divergence"
    rationale = ("a mesh trace that structurally diverges from its "
                 "single-device twin computes something different per "
                 "shard — the parity bug's hiding place")

    _HEAD = 2  # divergent-region lines quoted in the finding message

    def check_entry(self, traced: TracedSpmdEntry) -> Iterable[Finding]:
        if traced.single is None:
            return []
        mesh_lines = canonical_lines(traced.closed)
        single_lines = canonical_lines(traced.single)
        d = structural_diff(mesh_lines, single_lines)
        if d is None:
            return []
        mo, so = d["mesh_only"], d["single_only"]

        def head(lines: List[str]) -> str:
            shown = "; ".join(lines[:self._HEAD])
            more = len(lines) - self._HEAD
            return (shown + (f" (+{more} more)" if more > 0 else "")) \
                if lines else "<empty>"

        return [self.entry_finding(
            traced,
            f"mesh trace structurally diverges from the single-device "
            f"trace after {d['index']} shared eqns "
            f"({d['common_suffix']} shared after): mesh-only region "
            f"[{head(mo)}] vs single-only region [{head(so)}]; run "
            f"`python tools/spmd_diff.py --entry {traced.entry.name!r}` "
            f"for the full region")]


_REFUSAL_KEYWORDS = ("mesh", "sparse", "dart", "distributed")


@register
class RefusalGuardInventory(Rule):
    """SMT114 — mesh/sparse refusal-guard inventory (AST, always on).

    Every ``raise NotImplementedError`` whose message mentions
    mesh/sparse/dart/distributed is a piece of distributed-GBDT debt:
    a configuration the engine refuses instead of running. Refusing is
    the RIGHT call (a loud error beats silently-wrong trees), but the
    debt must be enumerable by machine — this rule makes each guard a
    finding, the matching ``LINT_ACKS.md`` row its tracked waiver, and
    ``docs/analysis.md``'s debt table its human ledger. Adding a new
    refusal without a reasoned waiver row fails the gate: the debt
    cannot silently grow.
    """

    code = "SMT114"
    name = "mesh-refusal-guard"
    rationale = ("NotImplementedError guards over mesh/sparse configs are "
                 "tracked debt — each needs a reasoned waiver row so the "
                 "inventory cannot silently grow")

    def check(self, module: Module) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Raise) or node.exc is None:
                continue
            exc = node.exc
            name = None
            if isinstance(exc, ast.Call):
                name = exc.func.id if isinstance(exc.func, ast.Name) else \
                    exc.func.attr if isinstance(exc.func, ast.Attribute) \
                    else None
            elif isinstance(exc, ast.Name):
                name = exc.id
            if name != "NotImplementedError":
                continue
            text = " ".join(
                s.value for s in ast.walk(exc)
                if isinstance(s, ast.Constant) and isinstance(s.value, str))
            low = text.lower()
            kws = sorted(k for k in _REFUSAL_KEYWORDS if k in low)
            if not kws:
                continue
            snippet = re.sub(r"\s+", " ", text).strip()
            if len(snippet) > 90:
                snippet = snippet[:87] + "..."
            findings.append(self.finding(
                module, node,
                f"refusal guard mentions {'/'.join(kws)}: \"{snippet}\" — "
                f"tracked distributed-GBDT debt; keep its LINT_ACKS.md row "
                f"and docs/analysis.md debt-table entry current"))
        return findings


# ---------------------------------------------------------------------------
# canonical entries: representative SpecLayouts over the hot paths
# ---------------------------------------------------------------------------

def _spmd_mlp_bytes():
    """The tp-serving stand-in model: the tiny MLP plus a TIED projection
    weight consumed in two roles (``MatMul`` rhs AND ``Gemm`` transB rhs —
    the tied-embedding pattern). Under a tp-only layout the planner
    replicates on the role conflict (SMT110's canonical finding); under
    an fsdp layout it stores the tied weight row-sharded and all-gathers
    at each consumer — the finding's resolution."""
    import numpy as np

    from ..onnx import builder
    from ..onnx.wire import serialize_model

    rng = np.random.default_rng(0)
    w1 = rng.normal(size=(64, 128)).astype(np.float32)
    b1 = rng.normal(size=(128,)).astype(np.float32)
    w_tied = rng.normal(size=(128, 128)).astype(np.float32)  # 64 KiB
    c0 = np.zeros((128,), np.float32)
    g = builder.make_graph(
        [builder.node("MatMul", ["x", "w1"], ["h0"]),
         builder.node("Add", ["h0", "b1"], ["h1"]),
         builder.node("Relu", ["h1"], ["h2"]),
         builder.node("MatMul", ["h2", "w_tied"], ["h3"]),
         builder.node("Gemm", ["h3", "w_tied", "c0"], ["y"], transB=1)],
        "mlp_tp",
        [builder.value_info("x", np.float32, [None, 64])],
        [builder.value_info("y", np.float32, [None, 128])],
        initializers={"w1": w1, "b1": b1, "w_tied": w_tied, "c0": c0})
    return serialize_model(builder.make_model(g))


def _build_onnx_fsdp_entry() -> Dict[str, Any]:
    """Beyond-HBM ONNX serving over the (1, 2, 2) layout: MatMul weights
    column-shard over ``model`` and are additionally STORED row-sharded
    over ``fsdp`` (all-gathered at each consumer). The tied weight — the
    planner's old replicate-on-conflict debt, SMT110's canonical finding
    — now stores over fsdp too, so the finding is resolved rather than
    waived; SMT111 sees the stored→use re-pin chain and must recognize it
    as the sanctioned fsdp gather. The no-layout twin gives SMT113 a
    structurally-identical baseline (constraints strip)."""
    import numpy as np

    from ..onnx.importer import OnnxFunction
    from ..runtime.layout import representative_layouts

    layout = representative_layouts()["(1,2,2)"]
    model = _spmd_mlp_bytes()
    of = OnnxFunction(model, dtype_policy="float32", layout=layout)
    single = OnnxFunction(model, dtype_policy="float32")
    x = np.zeros((8, 64), np.float32)
    return {"fn": of._run_positional, "args": (x,),
            "single_fn": single._run_positional, "single_args": (x,),
            "layout": layout, "placement_report": of.placement_report(),
            "anchor_obj": OnnxFunction._plan_const_specs}


def _build_gbdt_fp_entry(layout_key: str) -> Callable[[], Dict[str, Any]]:
    """2-D feature-parallel gbdt grow over a representative ``(data,
    model)`` mesh (degrading to what the host has) — the path ROADMAP
    item 2's device-side binning must feed."""

    def build() -> Dict[str, Any]:
        from ..gbdt import grow
        from ..runtime.layout import representative_layouts

        layout = representative_layouts()[layout_key]
        binned, g, h, w, fmask, TreeConfig, B = _gbdt_grow_inputs()
        cfg = TreeConfig(n_bins=B, num_leaves=4)
        dspec, rep = layout.batch(), layout.replicated()

        def body(b, gg, hh, ww, fm):
            return grow.grow_tree(b, gg, hh, ww, fm, cfg,
                                  axis_name=layout.data_axis,
                                  model_axis_name=layout.model_axis)

        fn = layout.shard_map(body,
                              in_specs=(dspec, dspec, dspec, dspec, rep),
                              out_specs=(rep, dspec), check=False)
        return {"fn": fn, "args": (binned, g, h, w, fmask),
                "layout": layout, "anchor_obj": grow.grow_tree}

    return build


def _build_gbdt_sparse_pair_entry() -> Dict[str, Any]:
    """The sparse grow step traced BOTH ways — the exact configuration
    ``test_sparse_mesh_matches_single_device`` fails on, exposed to
    SMT112/SMT113 and ``tools/spmd_diff.py`` as a differential pair."""
    from ..gbdt import boost

    mesh, single = boost.spmd_trace_pair()
    return {"fn": mesh["fn"], "args": mesh["args"],
            "single_fn": single["fn"], "single_args": single["args"],
            "layout": mesh["layout"], "anchor_obj": boost._build_step}


def _build_gbdt_device_bin_entry() -> Dict[str, Any]:
    """Shard-local device binning (the mesh ``use_device_bin`` path):
    raw f32 rows shard over ``data``, the packed edge/category tables
    replicate, and each shard runs the same vectorized binning kernel the
    single-device path uses — so the mesh trace must be STRUCTURALLY
    IDENTICAL to the single-device twin (any divergence here would break
    the bit-identical-trees parity the gbdt tests pin)."""
    from ..gbdt import device_predict
    from ..gbdt.binning import BinMapper
    from ..runtime.layout import representative_layouts

    import numpy as np

    layout = representative_layouts()["(4,2)-fp"]
    rng = np.random.default_rng(0)
    # 88 rows -> 22 per shard under data=4: no dimension of the
    # per-shard block aliases the packed-table width (max_bin) or the
    # feature count, so the canonical dim ids line up with the
    # single-device trace (64 rows gave 16/shard == max_bin and the
    # structural diff flagged a spurious broadcast hunk)
    x = rng.normal(size=(88, 6)).astype(np.float32)
    mapper = BinMapper(max_bin=16).fit(x.astype(np.float64))
    table, lens, cat_flags = device_predict.pack_feature_table(mapper)
    dspec, rep = layout.batch(), layout.replicated()

    def body(xb, t, ln):
        # cat_flags stays on host: static kernel-selection metadata
        return device_predict.device_bin_cat(xb, t, ln, cat_flags,
                                             mapper.missing_bin)

    fn = layout.shard_map(body, in_specs=(dspec, rep, rep),
                          out_specs=dspec, check=False)
    return {"fn": fn, "args": (x, table, lens),
            "single_fn": body, "single_args": (x, table, lens),
            "layout": layout, "anchor_obj": device_predict.device_bin_cat}


def default_spmd_entries() -> List[SpmdEntry]:
    """The canonical entries, one per representative layout: (1, 1)
    degenerate, (4, 2) feature-parallel, (1, 2, 2) fsdp+tensor-parallel
    serving, the sparse mesh-vs-single differential pair, and the
    shard-local device-binning pair the mesh ``use_device_bin`` path
    runs."""
    return [
        SpmdEntry("onnx.mlp[fsdp,(1,2,2)]", _build_onnx_fsdp_entry,
                  mesh_axes=("data", "fsdp", "model"),
                  replicated_bytes_limit=32 << 10),
        SpmdEntry("gbdt.grow[feature-parallel,(1,1)]",
                  _build_gbdt_fp_entry("(1,1)"),
                  mesh_axes=("data", "model")),
        SpmdEntry("gbdt.grow[feature-parallel,(4,2)]",
                  _build_gbdt_fp_entry("(4,2)-fp"),
                  mesh_axes=("data", "model")),
        SpmdEntry("gbdt.grow[sparse,mesh]", _build_gbdt_sparse_pair_entry,
                  mesh_axes=("data",)),
        SpmdEntry("gbdt.bin[device,mesh]", _build_gbdt_device_bin_entry,
                  mesh_axes=("data", "model")),
    ]


def differential_entry_names() -> List[str]:
    """Entries carrying a single-device twin (what ``tools/spmd_diff.py``
    can diff) — static so ``--list`` stays jax-free."""
    return ["gbdt.grow[sparse,mesh]", "gbdt.bin[device,mesh]",
            "onnx.mlp[fsdp,(1,2,2)]"]


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def _ensure_virtual_devices(n: int = 8) -> None:
    """Standalone CLI runs start jax with ONE cpu device — representative
    (4, 2)/(1, 2) layouts need more. Harmless when jax is already up (the
    flag is only read at first init) or when the caller set their own."""
    if "jax" in sys.modules:
        return
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" in flags:
        return
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n}").strip()


def trace_spmd_entry(entry: SpmdEntry, root: Optional[str] = None
                     ) -> TracedSpmdEntry:
    """Trace one entry's mesh configuration (and its single-device twin
    when the builder provides one) with ``jax.make_jaxpr`` — tracing
    only, no compile, no device execution."""
    import jax

    built = entry.build()
    closed = jax.make_jaxpr(built["fn"])(*built.get("args", ()),
                                         **built.get("kwargs", {}))
    single = None
    if built.get("single_fn") is not None:
        single = jax.make_jaxpr(built["single_fn"])(
            *built.get("single_args", ()), **built.get("single_kwargs", {}))
    return TracedSpmdEntry(entry, closed, _anchor_of(built, root),
                           single=single, layout=built.get("layout"),
                           placement=built.get("placement_report"))


def run_spmd_pack(entries: Optional[Sequence[SpmdEntry]] = None,
                  select: Optional[Sequence[str]] = None,
                  root: Optional[str] = None
                  ) -> Tuple[List[Finding], List[str]]:
    """Trace every entry under its representative layout and run the
    (selected) spmd rules over the jaxprs. Returns ``(findings, errors)``
    — an entry whose trace fails is an ERROR (the gate must see it),
    never a silent skip."""
    codes = [c for c in (select or sorted(SPMD_RULES)) if c in SPMD_RULES]
    if not codes:
        return [], []
    _ensure_virtual_devices()
    if entries is None:
        entries = default_spmd_entries()
    findings: List[Finding] = []
    errors: List[str] = []
    for entry in entries:
        try:
            traced = trace_spmd_entry(entry, root=root)
        except Exception as e:
            errors.append(f"spmd entry {entry.name!r} failed to trace: "
                          f"{type(e).__name__}: {e}")
            continue
        for code in codes:
            findings.extend(SPMD_RULES[code].check_entry(traced))
    return findings, errors
