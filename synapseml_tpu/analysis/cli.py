"""Lint CLI: ``python -m synapseml_tpu.analysis [paths...]``.

Output formats:

- ``text`` (default): ``path:line:col: CODE message`` per finding plus a
  summary line — the developer loop.
- ``json``: the full report (findings, waived, unused waivers, errors) —
  machine consumers.
- ``github``: ``::error file=...,line=...`` workflow annotations so CI
  failures are clickable at the offending line in the PR diff.
- ``sarif``: SARIF 2.1.0 — what GitHub code scanning ingests
  (``upload-sarif``), so findings land in the repo's Security tab with
  rule metadata attached.

``--device`` additionally runs the jaxpr-level device pack (SMT10x,
``rules_device``) over its canonical entry points, and ``--spmd`` the
sharding-aware SPMD pack (SMT11x, ``rules_spmd``) over its
layout-parameterized entries — the ONLY modes that import jax; the
default run stays jax-free (enforced by ``tests/test_import_hygiene.py``).

``--changed-only`` scopes per-file AST rules to ``git diff --name-only``
files (cross-module rules keep whole-repo scope) — the pre-commit loop.

Exit codes: 0 clean (waived findings allowed), 1 unwaived findings,
unparseable files, or — on a default full-repo run, where staleness is
judgeable — stale waiver rows; 2 configuration errors (unknown rule,
reasonless waiver, missing path).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Optional

from .engine import RULES, Finding, LintConfigError, analyze_paths

__all__ = ["main"]

DEFAULT_PATHS = ["synapseml_tpu", "tools", "bench.py"]


def _default_paths() -> List[str]:
    """The standard lint targets, resolved against the repo root derived
    from this package's location — so the bare CLI works from any cwd."""
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    paths = [os.path.join(root, p) for p in DEFAULT_PATHS]
    return [p for p in paths if os.path.exists(p)]


def _git_changed_files() -> Optional[List[str]]:
    """Repo-relative paths of modified + untracked files (``git diff
    --name-only HEAD`` ∪ ``git ls-files --others``), or None when git is
    unavailable — the ``--changed-only`` scope."""
    import subprocess

    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    out: List[str] = []
    for cmd in (["git", "-C", root, "diff", "--name-only", "HEAD"],
                ["git", "-C", root, "ls-files", "--others",
                 "--exclude-standard"]):
        try:
            r = subprocess.run(cmd, capture_output=True, text=True,
                               timeout=30)
        except (OSError, subprocess.TimeoutExpired):
            return None
        if r.returncode != 0:
            return None
        out.extend(line.strip() for line in r.stdout.splitlines()
                   if line.strip())
    return sorted(set(out))


def _rule_listing() -> str:
    from . import rules as _rules  # noqa: F401 — populate the registry
    from . import rules_device as _rd  # noqa: F401 — SMT10x codes
    from . import rules_spmd as _rs  # noqa: F401 — SMT11x codes

    lines = []
    for code in sorted(RULES):
        r = RULES[code]
        lines.append(f"{code}  {r.name}\n        {r.rationale}")
    return "\n".join(lines)


def _github_escape(s: str) -> str:
    # github workflow-command data escaping
    return (s.replace("%", "%25").replace("\r", "%0D").replace("\n", "%0A"))


def render_text(report: dict, out) -> None:
    for f in report["findings"]:
        print(f"{f.location}: {f.code} {f.message}", file=out)
    for w in report["unused_waivers"]:
        print(f"warning: unused waiver {w.rule} for {w.file!r} "
              f"(LINT_ACKS.md:{w.line}) — delete the stale row", file=out)
    for e in report["errors"]:
        print(f"error: {e}", file=out)
    n, w = len(report["findings"]), len(report["waived"])
    print(f"{report['n_files']} files checked, {n} finding"
          f"{'' if n == 1 else 's'}"
          + (f" ({w} waived)" if w else ""), file=out)


def render_json(report: dict, out) -> None:
    def enc(f: Finding) -> dict:
        return {"path": f.path, "line": f.line, "col": f.col,
                "code": f.code, "message": f.message}

    json.dump({
        "findings": [enc(f) for f in report["findings"]],
        "waived": [enc(f) for f in report["waived"]],
        "unused_waivers": [{"rule": w.rule, "file": w.file,
                            "match": w.match, "line": w.line}
                           for w in report["unused_waivers"]],
        "errors": report["errors"],
        "n_files": report["n_files"],
        "codes": report["codes"],
    }, out, indent=2)
    out.write("\n")


def render_github(report: dict, out) -> None:
    for f in report["findings"]:
        print(f"::error file={f.path},line={f.line},col={f.col},"
              f"title={f.code} {RULES[f.code].name}::"
              f"{_github_escape(f.message)}", file=out)
    for e in report["errors"]:
        print(f"::error::{_github_escape(e)}", file=out)


def render_sarif(report: dict, out) -> None:
    """SARIF 2.1.0 (the GitHub code-scanning upload schema): one run, one
    driver, one ``results`` entry per unwaived finding, waived findings
    carried as suppressed results so the security tab shows the reviewed
    decision instead of losing it."""
    rules = [{
        "id": code,
        "name": RULES[code].name,
        "shortDescription": {"text": RULES[code].name},
        "fullDescription": {"text": RULES[code].rationale},
        "defaultConfiguration": {"level": "error"},
    } for code in sorted({f.code for f in
                          report["findings"] + report["waived"]} |
                         set(report["codes"]))]

    def result(f: Finding, suppressed: bool) -> dict:
        r = {
            "ruleId": f.code,
            "level": "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path,
                                         "uriBaseId": "SRCROOT"},
                    "region": {"startLine": f.line, "startColumn": f.col},
                },
            }],
        }
        if suppressed:
            r["suppressions"] = [{"kind": "external",
                                  "justification": "waived in LINT_ACKS.md"}]
        return r

    json.dump({
        "$schema": "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                   "master/Schemata/sarif-schema-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "synapseml_tpu-lint",
                "informationUri":
                    "https://github.com/synapseml_tpu/docs/analysis.md",
                "rules": rules,
            }},
            "results": ([result(f, False) for f in report["findings"]]
                        + [result(f, True) for f in report["waived"]]),
            "invocations": [{
                "executionSuccessful": not report["errors"],
                "toolExecutionNotifications": [
                    {"level": "error", "message": {"text": e}}
                    for e in report["errors"]],
            }],
        }],
    }, out, indent=2)
    out.write("\n")


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m synapseml_tpu.analysis",
        description="Repo-invariant lint: the SMT rule pack.")
    ap.add_argument("paths", nargs="*", default=None,
                    help=f"files/directories to lint "
                         f"(default: {' '.join(DEFAULT_PATHS)})")
    ap.add_argument("--format", choices=["text", "json", "github", "sarif"],
                    default="text")
    ap.add_argument("--select", default=None,
                    help="comma-separated rule codes (default: all)")
    ap.add_argument("--device", action="store_true",
                    help="also run the jaxpr-level device pack (SMT10x) "
                         "over its canonical entry points; imports jax")
    ap.add_argument("--spmd", action="store_true",
                    help="also run the sharding-aware SPMD pack (SMT11x) "
                         "over representative SpecLayouts; imports jax")
    ap.add_argument("--changed-only", action="store_true",
                    help="scope per-file AST rules to `git diff "
                         "--name-only` files (cross-module rules stay "
                         "whole-repo); the pre-commit loop")
    ap.add_argument("--acks", default=None,
                    help="waiver file (default: LINT_ACKS.md found walking "
                         "up from the first path)")
    ap.add_argument("--no-acks", action="store_true",
                    help="ignore waivers (report every finding)")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        print(_rule_listing())
        return 0

    paths = args.paths or _default_paths()
    select = ([c.strip().upper() for c in args.select.split(",") if c.strip()]
              if args.select else None)
    changed_files = None
    if args.changed_only:
        changed_files = _git_changed_files()
        if changed_files is None:
            print("error: --changed-only needs a git checkout (git diff "
                  "--name-only failed)", file=sys.stderr)
            return 2
    t0 = time.perf_counter()
    try:
        report = analyze_paths(paths, select=select, acks_path=args.acks,
                               use_acks=not args.no_acks,
                               device=args.device, spmd=args.spmd,
                               changed_files=changed_files)
    except (LintConfigError, FileNotFoundError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    {"text": render_text, "json": render_json, "github": render_github,
     "sarif": render_sarif}[args.format](report, sys.stdout)
    if args.format == "text":
        print(f"({time.perf_counter() - t0:.2f}s)", file=sys.stderr)
    # stale waiver rows fail the gate ONLY on a default full-repo run —
    # the one invocation where every judged rule saw every file, so an
    # unused row really is stale rather than merely out of scope
    fail_stale = (not args.paths and not args.changed_only
                  and not args.no_acks and report["unused_waivers"])
    return 1 if (report["findings"] or report["errors"]
                 or fail_stale) else 0
