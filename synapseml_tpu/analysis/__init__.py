"""Static analysis: the repo-invariant lint engine and SMT rule pack.

Stdlib-only (``ast``); importable before jax exists, and covered by the
no-jax-at-import gate itself. See ``docs/analysis.md`` for the rule
catalog and the waiver workflow (``LINT_ACKS.md``).

Entry points: ``python -m synapseml_tpu.analysis`` / ``tools/lint.py``;
programmatic: :func:`analyze_paths`.
"""

from .engine import (  # noqa: F401
    RULES,
    Finding,
    LintConfigError,
    Module,
    Rule,
    Waiver,
    analyze_paths,
    apply_waivers,
    iter_python_files,
    load_waivers,
    register,
)
from . import rules  # noqa: F401  — populate RULES at import

__all__ = [
    "RULES",
    "Finding",
    "LintConfigError",
    "Module",
    "Rule",
    "Waiver",
    "analyze_paths",
    "apply_waivers",
    "iter_python_files",
    "load_waivers",
    "register",
]
