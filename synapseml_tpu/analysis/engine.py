"""AST lint engine: file collection, scoped traversal, rule registry, waivers.

The repo's load-bearing invariants (no jax at import, ``shard_map_compat``
only, one fixed histogram bucket layout, stages never overriding the
instrumented ``transform``/``fit``, lock discipline in the serving/metrics
hot paths) were enforced by convention, docs, and a few runtime subprocess
tests — and drift shipped silently. This engine makes every invariant a
named, ``file:line``-precise, CI-failing diagnostic (the same move the
reference makes with machine-readable ``Param`` metadata driving codegen:
structure you can *check* beats structure you can only describe).

Design constraints:

- **stdlib only** (``ast`` + ``os``): the linter runs in CI and developer
  loops before jax ever initializes, and is itself covered by the
  no-jax-at-import gate.
- **Single parse per file**, rules share the tree; a full-repo run must
  stay under seconds.
- **Waivers are reviewed decisions**: ``LINT_ACKS.md`` rows (mirroring
  ``BENCH_ACKS.md``) carry a mandatory reason; a bare waiver is a config
  error, not a pass.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "Finding",
    "Module",
    "Rule",
    "RULES",
    "register",
    "Ctx",
    "walk_scoped",
    "dotted_name",
    "iter_python_files",
    "Waiver",
    "load_waivers",
    "apply_waivers",
    "analyze_paths",
    "LintConfigError",
    "DEFAULT_ACKS_NAME",
]

DEFAULT_ACKS_NAME = "LINT_ACKS.md"


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    """One diagnostic, anchored to a file:line:col."""

    path: str  # repo-relative, posix separators
    line: int
    col: int
    code: str
    message: str

    @property
    def location(self) -> str:
        return f"{self.path}:{self.line}:{self.col}"


class Module:
    """One parsed source file handed to every rule."""

    def __init__(self, path: str, rel: str, source: str, tree: ast.Module):
        self.path = path          # absolute
        self.rel = rel            # repo-relative, posix
        self.source = source
        self.tree = tree

    @property
    def is_init(self) -> bool:
        return os.path.basename(self.path) == "__init__.py"

    @property
    def dirname(self) -> str:
        return os.path.dirname(self.path)

    @classmethod
    def parse(cls, path: str, rel: str) -> "Module":
        with open(path, encoding="utf-8") as f:
            source = f.read()
        return cls(path, rel, source, ast.parse(source, filename=path))


class Rule:
    """One named invariant. Subclasses set ``code``/``name``/``rationale``
    and implement :meth:`check` yielding findings for one module.

    Cross-module rules (e.g. SMT009 duplicate stage names) use the
    :meth:`begin`/:meth:`finalize` hooks: ``begin()`` resets per-run state
    before the file loop, ``check()`` accumulates, ``finalize()`` yields
    the findings that only exist relative to the whole scanned set."""

    code: str = ""
    name: str = ""
    rationale: str = ""

    def check(self, module: Module) -> Iterable[Finding]:  # pragma: no cover
        raise NotImplementedError

    def begin(self) -> None:
        """Reset cross-module state at the start of an analyze run."""

    def finalize(self) -> Iterable[Finding]:
        """Findings computable only after every module was seen."""
        return []

    def finding(self, module: Module, node: ast.AST, message: str) -> Finding:
        return Finding(path=module.rel,
                       line=getattr(node, "lineno", 1),
                       col=getattr(node, "col_offset", 0) + 1,
                       code=self.code, message=message)


RULES: Dict[str, Rule] = {}


def register(cls):
    """Class decorator adding a rule (by instance) to the global registry."""
    inst = cls()
    if not re.fullmatch(r"[A-Z]{2,8}\d{3}", inst.code):
        raise ValueError(f"rule code {inst.code!r} must look like SMT001")
    if inst.code in RULES:
        raise ValueError(f"duplicate rule code {inst.code}")
    RULES[inst.code] = inst
    return cls


# ---------------------------------------------------------------------------
# scoped traversal
# ---------------------------------------------------------------------------

_LOCKISH = ("lock", "mutex", "cond")


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _terminal_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Call):
        return _terminal_name(node.func)
    return None


def is_lock_expr(node: ast.AST) -> bool:
    """Heuristic: a ``with`` context expression that names a lock —
    ``self._lock``, ``outer._lock``, ``_pool_lock``, ``_key_lock(key)``."""
    name = _terminal_name(node)
    return bool(name) and any(p in name.lower() for p in _LOCKISH)


@dataclasses.dataclass(frozen=True)
class Ctx:
    """Traversal context: enclosing functions/classes and lock nesting."""

    funcs: Tuple[ast.AST, ...] = ()
    classes: Tuple[ast.AST, ...] = ()
    lock_depth: int = 0

    @property
    def in_lock(self) -> bool:
        return self.lock_depth > 0

    @property
    def in_function(self) -> bool:
        return bool(self.funcs)

    @property
    def in_constructor(self) -> bool:
        """Directly inside ``__init__``/``__new__`` (construction
        happens-before publication, so unlocked writes there are safe) —
        nested functions defined inside a constructor do NOT count: their
        bodies run later, from arbitrary threads."""
        return bool(self.funcs) and self.funcs[-1].name in ("__init__",
                                                           "__new__")


def walk_scoped(tree: ast.Module, visit: Callable[[ast.AST, Ctx], None]
                ) -> None:
    """Depth-first walk calling ``visit(node, ctx)`` for every node, with
    ``ctx`` tracking enclosing functions, classes, and with-lock regions.
    The lock region covers a ``with``'s *body* (not its context
    expressions)."""

    def rec(node: ast.AST, ctx: Ctx) -> None:
        visit(node, ctx)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested function's BODY runs later, when the enclosing
            # with-lock (if any) has been released — don't carry lock_depth
            # into it (a callback defined under a lock is not "under" it)
            inner = dataclasses.replace(ctx, funcs=ctx.funcs + (node,),
                                        lock_depth=0)
            for d in node.decorator_list:
                rec(d, ctx)
            for child in node.args.defaults + node.args.kw_defaults:
                if child is not None:
                    rec(child, ctx)
            for child in node.body:
                rec(child, inner)
            return
        if isinstance(node, ast.ClassDef):
            inner = dataclasses.replace(ctx, classes=ctx.classes + (node,))
            for d in node.decorator_list + node.bases:
                rec(d, ctx)
            for child in node.body:
                rec(child, inner)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            locked = any(is_lock_expr(item.context_expr)
                         for item in node.items)
            for item in node.items:
                rec(item.context_expr, ctx)
                if item.optional_vars is not None:
                    rec(item.optional_vars, ctx)
            body_ctx = (dataclasses.replace(ctx, lock_depth=ctx.lock_depth + 1)
                        if locked else ctx)
            for child in node.body:
                rec(child, body_ctx)
            return
        for child in ast.iter_child_nodes(node):
            rec(child, ctx)

    for stmt in tree.body:
        rec(stmt, Ctx())


# ---------------------------------------------------------------------------
# file collection
# ---------------------------------------------------------------------------

def iter_python_files(paths: Sequence[str], root: Optional[str] = None
                      ) -> List[Tuple[str, str]]:
    """Expand files/directories into sorted (abspath, relpath) pairs.
    Directories are walked recursively; ``__pycache__``, hidden and
    egg/build directories are skipped. ``root`` anchors the displayed
    relative paths (defaults to the common parent of ``paths``)."""
    out: List[Tuple[str, str]] = []
    abspaths = [os.path.abspath(p) for p in paths]
    if root is None:
        root = os.path.commonpath([p if os.path.isdir(p)
                                   else os.path.dirname(p) or "."
                                   for p in abspaths]) if abspaths else "."
    for p in abspaths:
        if os.path.isfile(p):
            out.append(p)
            continue
        if not os.path.isdir(p):
            raise FileNotFoundError(f"no such file or directory: {p}")
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__"
                                 and not d.startswith(".")
                                 and not d.endswith(".egg-info"))
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    uniq = sorted(set(out))
    return [(p, os.path.relpath(p, root).replace(os.sep, "/")) for p in uniq]


# ---------------------------------------------------------------------------
# waivers (LINT_ACKS.md)
# ---------------------------------------------------------------------------

class LintConfigError(ValueError):
    """The waiver file itself is malformed (e.g. a reasonless row)."""


@dataclasses.dataclass(frozen=True)
class Waiver:
    rule: str
    file: str
    match: str   # substring of the finding message; "" waives any message
    reason: str
    line: int    # line in LINT_ACKS.md, for unused-waiver reporting


def load_waivers(path: str) -> List[Waiver]:
    """Parse the ``| rule | file | match | reason |`` table rows of a
    ``LINT_ACKS.md`` (the ``BENCH_ACKS.md`` pattern). Every row must carry
    a non-empty reason — a bare waiver is a :class:`LintConfigError`, not
    a pass."""
    waivers: List[Waiver] = []
    with open(path, encoding="utf-8") as f:
        for lineno, raw in enumerate(f, 1):
            line = raw.strip()
            if not line.startswith("|"):
                continue
            cells = [c.strip() for c in line.strip("|").split("|")]
            if len(cells) < 4 or not re.fullmatch(r"[A-Z]{2,8}\d{3}",
                                                  cells[0]):
                continue  # header / separator / prose row
            rule, file_, match, reason = (cells[0], cells[1], cells[2],
                                          "|".join(cells[3:]).strip())
            match = "" if match in ("", "-", "*") else match
            if not reason or set(reason) <= {"-"}:
                raise LintConfigError(
                    f"{path}:{lineno}: waiver for {rule} on {file_!r} has "
                    f"no reason — waivers are reviewed decisions; add one")
            waivers.append(Waiver(rule=rule, file=file_.strip("`"),
                                  match=match.strip("`"), reason=reason,
                                  line=lineno))
    return waivers


def apply_waivers(findings: Sequence[Finding], waivers: Sequence[Waiver]
                  ) -> Tuple[List[Finding], List[Finding], List[Waiver]]:
    """Split findings into (unwaived, waived); also return waivers that
    matched nothing (stale rows worth deleting)."""
    used = [False] * len(waivers)
    unwaived: List[Finding] = []
    waived: List[Finding] = []
    for f in findings:
        hit = False
        for i, w in enumerate(waivers):
            if (w.rule == f.code and w.file == f.path
                    and (not w.match or w.match in f.message)):
                used[i] = True
                hit = True
        (waived if hit else unwaived).append(f)
    unused = [w for i, w in enumerate(waivers) if not used[i]]
    return unwaived, waived, unused


# ---------------------------------------------------------------------------
# top-level analysis
# ---------------------------------------------------------------------------

def default_acks_path(paths: Sequence[str]) -> Optional[str]:
    """Locate ``LINT_ACKS.md`` by walking up from the first scanned path
    (the repo root holds it, mirroring ``BENCH_ACKS.md``)."""
    start = os.path.abspath(paths[0]) if paths else os.getcwd()
    cur = start if os.path.isdir(start) else os.path.dirname(start)
    while True:
        cand = os.path.join(cur, DEFAULT_ACKS_NAME)
        if os.path.isfile(cand):
            return cand
        parent = os.path.dirname(cur)
        if parent == cur:
            return None
        cur = parent


def analyze_paths(paths: Sequence[str],
                  select: Optional[Sequence[str]] = None,
                  acks_path: Optional[str] = None,
                  use_acks: bool = True,
                  root: Optional[str] = None,
                  device: bool = False,
                  device_entries: Optional[Sequence[object]] = None,
                  spmd: bool = False,
                  spmd_entries: Optional[Sequence[object]] = None,
                  changed_files: Optional[Sequence[str]] = None
                  ) -> Dict[str, object]:
    """Run the (selected) rule pack over ``paths``.

    ``device=True`` additionally runs the jaxpr-level device pack
    (``rules_device``, SMT1xx) over its canonical entry points;
    ``spmd=True`` the sharding-aware SPMD pack (``rules_spmd``, SMT11x)
    over its layout-parameterized entries — the only modes that import
    jax; the default AST run never does.

    ``changed_files`` (repo-relative posix paths, e.g. from ``git diff
    --name-only``) scopes per-file AST rules to those files while
    cross-module rules (``finalize`` overridden) keep whole-repo scope —
    their findings only exist relative to the full scanned set. Scoped
    runs cannot judge waiver staleness, so ``unused_waivers`` is empty.

    Returns a report dict: ``findings`` (unwaived), ``waived``,
    ``unused_waivers``, ``errors`` (unparseable files), ``n_files``.
    """
    # rules register on import of the sibling modules; import here so the
    # engine is usable standalone in tests with a hand-built registry.
    # rules_device / rules_spmd register their SMT1xx codes (for
    # --select/--list-rules) but their trace rules stay inert — and
    # jax-free — unless device=True / spmd=True.
    from . import rules as _rules  # noqa: F401
    from . import rules_device as _rules_device  # noqa: F401
    from . import rules_spmd as _rules_spmd  # noqa: F401

    codes = sorted(RULES) if not select else sorted(select)
    unknown = [c for c in codes if c not in RULES]
    if unknown:
        raise LintConfigError(f"unknown rule code(s): {', '.join(unknown)}; "
                              f"known: {', '.join(sorted(RULES))}")

    def _ast_judgeable(code: str) -> bool:
        """Can this run produce findings for ``code``? Trace-only rules
        (inert AST hooks) need their pack flag; rules with a live AST
        half always can."""
        if getattr(RULES[code], "ast_active", True):
            return True
        if code in _rules_device.DEVICE_RULES:
            return device
        if code in _rules_spmd.SPMD_RULES:
            return spmd
        return True

    if select:
        # an explicitly selected trace-only rule can only fire under its
        # pack flag; running it without one would print "0 findings"
        # forever — a permanently-green gate is worse than a config error
        dead = [c for c in codes if not _ast_judgeable(c)]
        if dead and len(dead) == len(codes):
            raise LintConfigError(
                f"rule(s) {', '.join(dead)} are trace rules (jaxpr-level) "
                f"and require --device (SMT10x) or --spmd (SMT11x) to "
                f"run; without the flag this selection can never produce "
                f"a finding")
    if use_acks and acks_path is None:
        acks_path = default_acks_path(list(paths))
    if root is None and use_acks and acks_path is not None:
        # anchor displayed (and waiver-matched) paths at the repo root —
        # the directory holding LINT_ACKS.md — so `analysis synapseml_tpu`
        # and `analysis synapseml_tpu tools bench.py` report identical
        # paths and waiver rows match either way
        root = os.path.dirname(os.path.abspath(acks_path))
    findings: List[Finding] = []
    errors: List[str] = []
    files = iter_python_files(paths, root=root)
    changed: Optional[set] = None
    if changed_files is not None:
        changed = {str(p).replace(os.sep, "/") for p in changed_files}
        # cross-module rules stay whole-repo: their findings (duplicate
        # stage names, ...) only exist relative to the complete set, so
        # scoping them to the diff would silently blind the gate
        cross = {c for c in codes
                 if type(RULES[c]).finalize is not Rule.finalize}
    for code in codes:
        RULES[code].begin()
    for path, rel in files:
        if changed is not None and rel not in changed and not cross:
            continue
        try:
            module = Module.parse(path, rel)
        except (SyntaxError, UnicodeDecodeError) as e:
            errors.append(f"{rel}: {e.__class__.__name__}: {e}")
            continue
        run_codes = codes if changed is None or rel in changed else cross
        for code in run_codes:
            findings.extend(RULES[code].check(module))
    for code in codes:
        findings.extend(RULES[code].finalize())
    if device:
        dev_findings, dev_errors = _rules_device.run_device_pack(
            entries=device_entries, select=codes, root=root)
        findings.extend(dev_findings)
        errors.extend(dev_errors)
    if spmd:
        spmd_findings, spmd_errors = _rules_spmd.run_spmd_pack(
            entries=spmd_entries, select=codes, root=root)
        findings.extend(spmd_findings)
        errors.extend(spmd_errors)
    findings.sort()
    waivers: List[Waiver] = []
    if use_acks and acks_path is not None:
        waivers = load_waivers(acks_path)
    unwaived, waived, unused = apply_waivers(findings, waivers)
    # a waiver row is only STALE when this run could have produced the
    # finding it waives: a scoped run sees a slice of the repo, and a
    # trace-only rule's rows (SMT10x/SMT11x) are invisible to AST-only
    # runs — reporting those as unused would flag every reasoned spmd
    # waiver on every default run. Rows naming an unknown rule code are
    # always stale (the rule was deleted; the row must go too).
    unused = [w for w in unused
              if w.rule not in RULES
              or (changed is None and w.rule in codes
                  and _ast_judgeable(w.rule))]
    return {"findings": unwaived, "waived": waived,
            "unused_waivers": unused, "errors": errors,
            "n_files": len(files), "acks_path": acks_path,
            "codes": codes}
