"""``python -m synapseml_tpu.analysis`` — run the SMT lint rule pack."""

import sys

from .cli import main

if __name__ == "__main__":
    sys.exit(main())
