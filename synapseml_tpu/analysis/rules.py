"""The SMT rule pack: this repo's load-bearing invariants as lint rules.

Each rule names one invariant, says why it is load-bearing, and yields
``file:line`` findings. Heuristic rules (SMT006/SMT007) are tuned on the
real lock sites in ``observability/``, ``io/serving*.py`` and ``runtime/``;
anything they over-flag gets a reasoned ``LINT_ACKS.md`` row, never a
silent exemption. Fixture-level true-positive/true-negative coverage for
every rule lives in ``tests/test_lint_clean.py``.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .engine import (Ctx, Finding, Module, Rule, dotted_name, is_lock_expr,
                     register, walk_scoped)

__all__ = []  # rules are reached through engine.RULES


def _is_jax_module(name: Optional[str]) -> bool:
    return bool(name) and (name == "jax" or name.startswith("jax."))


def _imports_jax(node: ast.AST) -> bool:
    if isinstance(node, ast.Import):
        return any(_is_jax_module(a.name) for a in node.names)
    if isinstance(node, ast.ImportFrom):
        return _is_jax_module(node.module)
    return False


def _is_type_checking_if(node: ast.AST) -> bool:
    if not isinstance(node, ast.If):
        return False
    t = node.test
    return (isinstance(t, ast.Name) and t.id == "TYPE_CHECKING") or (
        isinstance(t, ast.Attribute) and t.attr == "TYPE_CHECKING")


@register
class ModuleLevelJaxImport(Rule):
    """SMT001 — jax imported at module import time.

    ``import synapseml_tpu`` (and every operational layer a serving worker
    or CLI tool touches at startup) must never import jax: initialization
    is slow, environment-sensitive, and grabs accelerator state. The
    subprocess gate in ``tests/test_import_hygiene.py`` stays the ground
    truth (it catches *transitive* imports this AST pass cannot); this
    rule adds the file:line diagnostic per offending statement, over every
    file instead of a curated module list. Fix: import inside the function
    that uses it, or use ``core.lazyimport.lazy_import``.
    """

    code = "SMT001"
    name = "module-level-jax-import"
    rationale = ("jax at import time breaks the no-jax-at-import contract "
                 "every worker/CLI startup relies on")

    def check(self, module: Module) -> Iterable[Finding]:
        findings: List[Finding] = []

        def rec(node: ast.AST) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return  # function bodies run post-import
            if _is_type_checking_if(node):
                return  # typing-only imports never execute
            if _imports_jax(node):
                what = (", ".join(a.name for a in node.names)
                        if isinstance(node, ast.Import) else node.module)
                findings.append(self.finding(
                    module, node,
                    f"module-level import of {what!r} runs at import time; "
                    f"import jax inside the using function or via "
                    f"core.lazyimport.lazy_import"))
                return
            for child in ast.iter_child_nodes(node):
                rec(child)

        for stmt in module.tree.body:
            rec(stmt)
        return findings


@register
class DirectShardMap(Rule):
    """SMT002 — ``shard_map`` imported/used directly instead of through
    ``runtime.topology.shard_map_compat``.

    jax moved ``shard_map`` between ``jax.experimental`` (0.4.x,
    ``check_rep=``) and top level (``check_vma=``); direct imports are
    exactly the drift that shipped 8 mesh-test ImportErrors in the seed.
    Every mesh-distributed call site goes through the compat wrapper, which
    picks the interpreter's spelling at call time.
    """

    code = "SMT002"
    name = "direct-shard-map"
    rationale = ("direct shard_map imports break across jax versions; "
                 "runtime.topology.shard_map_compat absorbs the drift")

    def check(self, module: Module) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                names = {a.name for a in node.names}
                if (mod == "jax.experimental.shard_map"
                        or (mod in ("jax", "jax.experimental")
                            and "shard_map" in names)):
                    findings.append(self.finding(
                        module, node,
                        f"direct shard_map import from {mod!r}; use "
                        f"runtime.topology.shard_map_compat"))
            elif isinstance(node, ast.Import):
                for a in node.names:
                    if a.name.startswith("jax.experimental.shard_map"):
                        findings.append(self.finding(
                            module, node,
                            f"direct import of {a.name!r}; use "
                            f"runtime.topology.shard_map_compat"))
            elif isinstance(node, ast.Attribute):
                dn = dotted_name(node)
                if dn in ("jax.shard_map",
                          "jax.experimental.shard_map",
                          "jax.experimental.shard_map.shard_map"):
                    findings.append(self.finding(
                        module, node,
                        f"direct use of {dn}; use "
                        f"runtime.topology.shard_map_compat"))
        return findings


def _is_wallclock_call(node: ast.AST, bare_time: bool) -> bool:
    """A ``time.time()`` call (or bare ``time()`` when imported that way)."""
    if not isinstance(node, ast.Call):
        return False
    dn = dotted_name(node.func)
    return dn == "time.time" or (bare_time and dn == "time")


@register
class WallClockDelta(Rule):
    """SMT003 — durations computed from ``time.time()`` deltas.

    Wall-clock deltas jump under NTP slew; every elapsed-time measurement
    uses ``time.perf_counter()`` / ``core.clock.StopWatch``. Timestamp-only
    uses of ``time.time()`` (event ``ts`` fields, exemplar ages) are fine —
    the rule only flags *subtractions* whose both operands trace back to
    wall-clock reads.
    """

    code = "SMT003"
    name = "wall-clock-delta"
    rationale = ("time.time() deltas jump under NTP slew; durations use "
                 "perf_counter / core.clock.StopWatch")

    def check(self, module: Module) -> Iterable[Finding]:
        bare_time = any(
            isinstance(n, ast.ImportFrom) and n.module == "time"
            and any(a.name == "time" for a in n.names)
            for n in ast.walk(module.tree))

        def taint_targets(stmt: ast.AST, names: Set[str],
                          attrs: Set[str]) -> None:
            if isinstance(stmt, ast.Assign) and _is_wallclock_call(
                    stmt.value, bare_time):
                for t in stmt.targets:
                    for el in (t.elts if isinstance(t, ast.Tuple) else [t]):
                        if isinstance(el, ast.Name):
                            names.add(el.id)
                        elif isinstance(el, ast.Attribute):
                            attrs.add(el.attr)

        # attribute taint is module-wide (self._start set in start(), read
        # in stop()); NAME taint is per function scope — a `t0` holding a
        # wall timestamp in one function must not poison a `t0` holding a
        # perf_counter in another
        attr_tainted: Set[str] = set()
        for node in ast.walk(module.tree):
            taint_targets(node, set(), attr_tainted)

        findings: List[Finding] = []

        def process_scope(body, inherited: Set[str]) -> None:
            tainted = set(inherited)
            nested: List[ast.AST] = []

            def rec(n: ast.AST, collect_only: bool) -> None:
                for child in ast.iter_child_nodes(n):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
                        if collect_only:
                            nested.append(child)
                        continue  # separate name scope (closure inherits)
                    if collect_only:
                        taint_targets(child, tainted, set())
                    elif (isinstance(child, ast.BinOp)
                            and isinstance(child.op, ast.Sub)
                            and wallclockish(child.left, tainted)
                            and wallclockish(child.right, tainted)):
                        findings.append(self.finding(
                            module, child,
                            "duration computed as a time.time() delta; use "
                            "time.perf_counter() or core.clock.StopWatch"))
                    rec(child, collect_only)

            holder = ast.Module(body=body, type_ignores=[])
            rec(holder, True)
            rec(holder, False)
            for fn in nested:
                process_scope(fn.body, tainted)

        def wallclockish(node: ast.AST, tainted: Set[str]) -> bool:
            if _is_wallclock_call(node, bare_time):
                return True
            if isinstance(node, ast.Name):
                return node.id in tainted
            if isinstance(node, ast.Attribute):
                return node.attr in attr_tainted
            return False

        process_scope(module.tree.body, set())
        return findings


@register
class NonDefaultHistogramBuckets(Rule):
    """SMT004 — ``Histogram``/``registry.histogram`` constructed with
    non-default buckets.

    Fleet quantiles come from *bucket-wise merged* worker histograms; the
    merge is exact only because every histogram in every process shares the
    single fixed ``DEFAULT_BUCKETS`` layout. One histogram with custom
    buckets silently breaks exact fleet merge for its family.
    """

    code = "SMT004"
    name = "non-default-histogram-buckets"
    rationale = ("per-worker histograms merge exactly only on the one fixed "
                 "DEFAULT_BUCKETS layout")

    def check(self, module: Module) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = None
            if isinstance(node.func, ast.Attribute):
                fname = node.func.attr
            elif isinstance(node.func, ast.Name):
                fname = node.func.id
            if fname not in ("histogram", "Histogram"):
                continue
            offending = None
            for kw in node.keywords:
                if kw.arg == "buckets":
                    v = kw.value
                    vn = dotted_name(v)
                    if not (vn and vn.split(".")[-1] == "DEFAULT_BUCKETS"):
                        offending = kw.value
            # positional buckets only exist on registry.histogram(name,
            # help, labelnames, buckets) — attribute calls; a bare-name
            # histogram() is the gbdt kernel, whose 4th arg is a weight
            if (offending is None and len(node.args) >= 4
                    and fname == "histogram"
                    and isinstance(node.func, ast.Attribute)):
                offending = node.args[3]
            if offending is not None:
                findings.append(self.finding(
                    module, offending,
                    "histogram constructed with non-default buckets; "
                    "per-worker merge is exact only on DEFAULT_BUCKETS"))
        return findings


_STAGE_BASES = {"PipelineStage", "Transformer", "Estimator", "Model",
                "UnaryTransformer", "PipelineModel"}
_STAGE_SUFFIXES = ("Transformer", "Estimator", "Model", "Stage")


def _registered_stage_classes(module: Module) -> List[ast.ClassDef]:
    """ClassDefs that would auto-register in ``STAGE_REGISTRY``: inherit a
    stage base (local subclass chains resolved, name-suffix heuristic for
    imported bases), not ``_``-prefixed, no ``_abstract_stage = True`` in
    their own body. Shared by SMT005 and SMT009 so the two rules cannot
    drift on what "registered" means."""
    local_bases: Dict[str, Set[str]] = {}
    classes = [n for n in ast.walk(module.tree)
               if isinstance(n, ast.ClassDef)]
    for cls in classes:
        local_bases[cls.name] = {
            dn.split(".")[-1] for dn in
            (dotted_name(b) for b in cls.bases) if dn}

    def is_stage_base(name: str, seen: Set[str]) -> bool:
        if name in _STAGE_BASES or name.endswith(_STAGE_SUFFIXES):
            return True
        if name in seen or name not in local_bases:
            return False
        seen.add(name)
        return any(is_stage_base(b, seen) for b in local_bases[name])

    out: List[ast.ClassDef] = []
    for cls in classes:
        if cls.name.startswith("_"):
            continue  # never registered (test/bench-local stages)
        abstract = any(
            isinstance(st, ast.Assign)
            and any(isinstance(t, ast.Name) and t.id == "_abstract_stage"
                    for t in st.targets)
            and isinstance(st.value, ast.Constant) and st.value.value
            for st in cls.body)
        if abstract:
            continue
        if any(is_stage_base(b, set()) for b in local_bases[cls.name]):
            out.append(cls)
    return out


@register
class StageOverridesInstrumentedMethod(Rule):
    """SMT005 — a registered ``PipelineStage`` subclass overrides base
    ``transform``/``fit``.

    Span instrumentation (wall time, row counts, cold/warm compile split,
    trace attachment) lives in the base ``Transformer.transform`` /
    ``Estimator.fit``; stages implement ``_transform``/``_fit``. An
    override silently drops the stage out of every ``/metrics`` and
    ``/traces`` view. Framework bases opt out with ``_abstract_stage =
    True`` in their own body; ``_``-prefixed classes are never registered.
    """

    code = "SMT005"
    name = "stage-overrides-instrumented-method"
    rationale = ("base transform/fit carry span instrumentation; stages "
                 "implement _transform/_fit")

    def check(self, module: Module) -> Iterable[Finding]:
        findings: List[Finding] = []
        for cls in _registered_stage_classes(module):
            for st in cls.body:
                if (isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef))
                        and st.name in ("transform", "fit")):
                    findings.append(self.finding(
                        module, st,
                        f"stage {cls.name} overrides instrumented base "
                        f"method {st.name}(); implement _{st.name}() — the "
                        f"base carries span instrumentation"))
        return findings


_MUTATORS = {"append", "appendleft", "extend", "extendleft", "insert",
             "remove", "pop", "popleft", "popitem", "clear", "update",
             "setdefault", "add", "discard", "rotate"}


def _mutations(node: ast.AST) -> List[Tuple[str, str, ast.AST]]:
    """Shared-state mutations in one AST node: ``[(kind, name, site)]``
    where kind is 'attr' (``X.name = / X.name[k] = / X.name.append()``)
    or 'name' (``NAME[k] = / NAME.append() / NAME = `` for globals)."""
    out: List[Tuple[str, str, ast.AST]] = []

    def target(t: ast.AST) -> None:
        if isinstance(t, (ast.Tuple, ast.List)):
            for el in t.elts:
                target(el)
        elif isinstance(t, ast.Starred):
            target(t.value)
        elif isinstance(t, ast.Attribute):
            out.append(("attr", t.attr, t))
        elif isinstance(t, ast.Subscript):
            if isinstance(t.value, ast.Attribute):
                out.append(("attr", t.value.attr, t))
            elif isinstance(t.value, ast.Name):
                out.append(("name", t.value.id, t))
        elif isinstance(t, ast.Name):
            out.append(("name", t.id, t))

    if isinstance(node, ast.Assign):
        for t in node.targets:
            target(t)
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        target(node.target)
    elif isinstance(node, ast.Delete):
        for t in node.targets:
            target(t)
    elif (isinstance(node, ast.Call)
          and isinstance(node.func, ast.Attribute)
          and node.func.attr in _MUTATORS):
        recv = node.func.value
        if isinstance(recv, ast.Attribute):
            out.append(("attr", recv.attr, node))
        elif isinstance(recv, ast.Name):
            out.append(("name", recv.id, node))
    return out


def _local_bindings(func: ast.AST) -> Set[str]:
    """Names a function binds locally (bare assignments / for targets /
    with-as, excluding nested function bodies): per Python scoping, such a
    name is local for the WHOLE function unless declared ``global``."""
    out: Set[str] = set()

    def names_of(t: ast.AST) -> None:
        if isinstance(t, ast.Name):
            out.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for el in t.elts:
                names_of(el)
        elif isinstance(t, ast.Starred):
            names_of(t.value)

    if isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
        a = func.args
        for arg in (a.posonlyargs + a.args + a.kwonlyargs
                    + ([a.vararg] if a.vararg else [])
                    + ([a.kwarg] if a.kwarg else [])):
            out.add(arg.arg)

    def rec(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                continue  # separate scope
            if isinstance(child, ast.Assign):
                for t in child.targets:
                    names_of(t)
            elif isinstance(child, (ast.AugAssign, ast.AnnAssign)):
                names_of(child.target)
            elif isinstance(child, (ast.For, ast.AsyncFor)):
                names_of(child.target)
            elif isinstance(child, (ast.With, ast.AsyncWith)):
                for item in child.items:
                    if item.optional_vars is not None:
                        names_of(item.optional_vars)
            rec(child)

    rec(func)
    return out


def _module_level_names(tree: ast.Module) -> Set[str]:
    names: Set[str] = set()
    for st in tree.body:
        if isinstance(st, ast.Assign):
            for t in st.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
        elif isinstance(st, (ast.AnnAssign, ast.AugAssign)):
            if isinstance(st.target, ast.Name):
                names.add(st.target.id)
    return names


@register
class UnlockedSharedWrite(Rule):
    """SMT006 — lock-protected state written outside the lock.

    Heuristic race check, tuned on the lock sites across ``observability/``,
    ``io/serving*.py`` and ``runtime/``: an attribute (or module global)
    that is *ever* mutated inside a ``with <lock>`` block is treated as
    lock-protected; any mutation of the same attribute outside a lock
    region is a finding. Constructor bodies (``__init__``/``__new__``) and
    module top level are exempt — construction happens-before publication.
    Unlocked *reads* are deliberately not flagged (lock-free fast-path
    reads are an intentional pattern here, e.g. double-checked
    ``shared_singleton``).
    """

    code = "SMT006"
    name = "unlocked-shared-write"
    rationale = ("state mutated under a lock in one place and without it in "
                 "another is a data race the GIL does not excuse")

    def check(self, module: Module) -> Iterable[Finding]:
        module_globals = _module_level_names(module.tree)
        protected_attrs: Set[str] = set()
        protected_globals: Set[str] = set()
        global_decls: Dict[int, Set[str]] = {}  # id(func) -> declared names
        locals_cache: Dict[int, Set[str]] = {}

        def _is_global_write(name: str, site: ast.AST, ctx: Ctx) -> bool:
            """A Name-rooted mutation counts as *shared* only when it can
            reach module state: a bare ``name = ...`` in a function binds a
            local unless declared ``global``, and a locally-bound name is
            local for the whole function scope; subscript/mutator-call
            sites mutate the object a module-level name refers to."""
            if not ctx.funcs:
                # module-level code runs at import (single-threaded)
                return not isinstance(site, ast.Name) and \
                    name in module_globals
            fn = ctx.funcs[-1]
            if name in global_decls.get(id(fn), ()):
                return True
            if isinstance(site, ast.Name):
                return False  # bare assign without global: binds a local
            key = id(fn)
            if key not in locals_cache:
                locals_cache[key] = _local_bindings(fn)
            if name in locals_cache[key]:
                return False  # shadowed: every use in this scope is local
            return name in module_globals

        def collect(node: ast.AST, ctx: Ctx) -> None:
            if isinstance(node, ast.Global) and ctx.funcs:
                global_decls.setdefault(
                    id(ctx.funcs[-1]), set()).update(node.names)
            if not ctx.in_lock:
                return
            for kind, name, site in _mutations(node):
                if kind == "attr":
                    protected_attrs.add(name)
                elif _is_global_write(name, site, ctx):
                    protected_globals.add(name)

        walk_scoped(module.tree, collect)
        if not protected_attrs and not protected_globals:
            return []

        findings: List[Finding] = []

        def flag(node: ast.AST, ctx: Ctx) -> None:
            if ctx.in_lock or not ctx.in_function or ctx.in_constructor:
                return
            for kind, name, site in _mutations(node):
                if kind == "attr" and name in protected_attrs:
                    findings.append(self.finding(
                        module, site,
                        f"attribute {name!r} is mutated under a lock "
                        f"elsewhere in this module but written here without "
                        f"one"))
                elif (kind == "name" and name in protected_globals
                        and _is_global_write(name, site, ctx)):
                    findings.append(self.finding(
                        module, site,
                        f"module global {name!r} is mutated under a lock "
                        f"elsewhere in this module but written here without "
                        f"one"))

        walk_scoped(module.tree, flag)
        return findings


_BLOCKING_DOTTED = {
    "time.sleep", "select.select", "subprocess.run", "subprocess.call",
    "subprocess.check_call", "subprocess.check_output", "subprocess.Popen",
    "urllib.request.urlopen", "socket.create_connection", "os.system",
    "requests.get", "requests.post", "requests.request",
}
_BLOCKING_ATTRS = {"recv", "accept", "connect", "sendall", "urlopen",
                   "wait", "result", "block_until_ready"}
_JAX_ROOTS = {"jax", "jnp", "lax"}


@register
class BlockingWorkUnderLock(Rule):
    """SMT007 — blocking I/O or jax dispatch while holding a lock.

    The family locks sit on the serving request hot path; a scrape or
    request that blocks on the network / a device computation while holding
    one turns every concurrent observation into queued p99. Flags known
    blocking calls (sleep, socket/subprocess/urllib, ``.wait()``/
    ``.result()``) and any jax dispatch (``jax.* / jnp.* / lax.*``,
    ``.block_until_ready()``) inside ``with <lock>`` bodies.
    """

    code = "SMT007"
    name = "blocking-work-under-lock"
    rationale = ("network / device / sleep work under a lock serializes "
                 "every concurrent hot-path observation behind it")

    def check(self, module: Module) -> Iterable[Finding]:
        findings: List[Finding] = []

        def visit(node: ast.AST, ctx: Ctx) -> None:
            if not ctx.in_lock or not isinstance(node, ast.Call):
                return
            dn = dotted_name(node.func)
            reason = None
            if dn is not None:
                root = dn.split(".")[0]
                if dn in _BLOCKING_DOTTED:
                    reason = f"blocking call {dn}()"
                elif root in _JAX_ROOTS:
                    reason = f"jax dispatch {dn}()"
            if (reason is None and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _BLOCKING_ATTRS
                    and not isinstance(node.func.value, ast.Constant)):
                reason = f"blocking call .{node.func.attr}()"
            if reason is not None:
                findings.append(self.finding(
                    module, node,
                    f"{reason} while holding a lock; move the blocking "
                    f"work outside the critical section"))

        walk_scoped(module.tree, visit)
        return findings


@register
class DuplicateStageName(Rule):
    """SMT009 — the same stage class name registered from two modules.

    ``STAGE_REGISTRY`` (and therefore ``load_stage``) is keyed by CLASS
    NAME: when two modules define a registered stage with the same name,
    whichever imports later silently wins, and a saved pipeline can load
    the WRONG class depending on import order. The runtime path only
    logged a warning (``core/stage.py register_stage``) — swallowed in
    production. This rule promotes it to a CI-failing finding: one
    diagnostic per defining site, each naming the other module(s).

    Detection reuses SMT005's registration heuristics: classes inheriting
    a stage base, not ``_``-prefixed, without ``_abstract_stage = True``
    in their own body.
    """

    code = "SMT009"
    name = "duplicate-stage-name"
    rationale = ("STAGE_REGISTRY is keyed by class name; a cross-module "
                 "collision makes load_stage resolve to whichever module "
                 "imported last")

    def __init__(self):
        # name -> [(module rel path, line, col)] — plain tuples only, so a
        # long-lived process does not pin every scanned module's AST
        self._sites: Dict[str, List[Tuple[str, int, int]]] = {}

    def begin(self) -> None:
        self._sites = {}

    def check(self, module: Module) -> Iterable[Finding]:
        for cls in _registered_stage_classes(module):
            self._sites.setdefault(cls.name, []).append(
                (module.rel, cls.lineno, cls.col_offset + 1))
        return []

    def finalize(self) -> Iterable[Finding]:
        findings: List[Finding] = []
        for name, sites in sorted(self._sites.items()):
            modules = sorted({rel for rel, _, _ in sites})
            if len(modules) < 2:
                continue
            for rel, line, col in sites:
                others = [m for m in modules if m != rel]
                findings.append(Finding(
                    path=rel, line=line, col=col, code=self.code,
                    message=f"stage class name {name!r} is also registered "
                            f"from {', '.join(others)}; load_stage resolves "
                            f"by NAME, so the later import silently shadows "
                            f"this one — rename one of the classes"))
        self._sites = {}
        return findings


@register
class UntimedNetworkCall(Rule):
    """SMT011 — ``urlopen`` / ``socket.create_connection`` without an
    explicit ``timeout=``.

    The fault-injection harness (``io/faultinject.py``) makes the failure
    mode concrete: under the wedged-socket plan an untimed call blocks
    FOREVER — a handler thread, a scrape, or a prober that never comes
    back. urllib's default is no timeout, so the only safe spelling is an
    explicit one at every call site. The timeout may be positional
    (``urlopen(url, data, t)`` / ``create_connection(addr, t)``) or a
    keyword.
    """

    code = "SMT011"
    name = "untimed-network-call"
    rationale = ("an untimed urlopen/socket connect wedges forever when "
                 "the peer stops answering; pass an explicit timeout=")

    # callable terminal name -> number of positional args that implies the
    # timeout was passed positionally
    _CALLS = {"urlopen": 3, "create_connection": 2}

    def check(self, module: Module) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            if isinstance(node.func, ast.Attribute):
                fname = node.func.attr
            elif isinstance(node.func, ast.Name):
                fname = node.func.id
            else:
                continue
            pos_needed = self._CALLS.get(fname)
            if pos_needed is None:
                continue
            if any(kw.arg == "timeout" for kw in node.keywords):
                continue
            if len(node.args) >= pos_needed:
                continue  # timeout passed positionally
            findings.append(self.finding(
                module, node,
                f"{fname}() without an explicit timeout= blocks forever "
                f"on a wedged peer; pass a timeout"))
        return findings


@register
class SilentExceptionSwallow(Rule):
    """SMT012 — silent exception swallowing in ``io/`` and
    ``observability/``.

    These packages are built from long-lived thread loops (dispatchers,
    probers, collectors, control loops). A bare ``except:`` — or a broad
    ``except Exception:`` whose body is only ``pass``/``continue`` inside
    a loop — makes such a loop eat its own death: the thread looks alive
    while serving nothing, which is the exact silent-failure mode the
    resilience layer exists to prevent. Swallowing deliberately is fine —
    say so by logging (or counting) what was swallowed; the handler then
    has a body and the rule passes. A bare ``except:`` that re-raises is
    also allowed (the narrow cleanup-then-reraise idiom).
    """

    code = "SMT012"
    name = "silent-exception-swallow"
    rationale = ("a swallowed exception in a serving/observability thread "
                 "loop turns a crash into a silent hang; log or count "
                 "what was swallowed")

    _SCOPES = (os.sep + os.path.join("synapseml_tpu", "io") + os.sep,
               os.sep + os.path.join("synapseml_tpu", "observability")
               + os.sep,
               # fixture paths: any io/ or observability/ directory
               os.sep + "io" + os.sep,
               os.sep + "observability" + os.sep)

    def _in_scope(self, module: Module) -> bool:
        path = os.path.abspath(module.path)
        return any(s in path for s in self._SCOPES)

    @staticmethod
    def _trivial_body(handler: ast.ExceptHandler) -> bool:
        return all(isinstance(s, (ast.Pass, ast.Continue))
                   for s in handler.body)

    @staticmethod
    def _reraises(handler: ast.ExceptHandler) -> bool:
        return any(isinstance(s, ast.Raise) for s in ast.walk(handler))

    def check(self, module: Module) -> Iterable[Finding]:
        if not self._in_scope(module):
            return []
        findings: List[Finding] = []

        class V(ast.NodeVisitor):
            def __init__(self):
                self.loops = 0

            def _loop(self, node):
                self.loops += 1
                self.generic_visit(node)
                self.loops -= 1

            visit_For = visit_While = _loop

            def visit_FunctionDef(self, node):
                # a handler inside a nested def is not "inside" the outer
                # loop — the function body runs whenever it is called
                saved, self.loops = self.loops, 0
                self.generic_visit(node)
                self.loops = saved

            visit_AsyncFunctionDef = visit_FunctionDef

            def visit_ExceptHandler(inner, node):
                bare = node.type is None
                broad = (isinstance(node.type, ast.Name)
                         and node.type.id in ("Exception", "BaseException"))
                if bare and not self._reraises(node):
                    findings.append(self.finding(
                        module, node,
                        "bare 'except:' swallows SystemExit/KeyboardInterrupt "
                        "too; catch Exception and log (or count) what was "
                        "swallowed"))
                elif broad and self._trivial_body(node) and inner.loops:
                    findings.append(self.finding(
                        module, node,
                        "'except Exception: pass' inside a loop lets a "
                        "thread loop eat its own death silently; log or "
                        "count the swallowed exception"))
                inner.generic_visit(node)

        V().visit(module.tree)
        return findings


@register
class AdHocMeshConstruction(Rule):
    """SMT013 — ad-hoc mesh construction outside the canonical layout.

    Every distributed path used to hand-roll its own 1-D
    ``jax.sharding.Mesh``, which is exactly how the repo ended up
    data-parallel-only (no model axis, no tensor-parallel serving, no
    feature-parallel histograms). Mesh construction now lives in ONE
    place — ``runtime/layout.py`` (``SpecLayout``) on top of
    ``runtime/topology.py`` (``make_mesh``) — so axis names, 2-D shapes
    and the (1, 1) degradation stay consistent across engines. Direct
    ``jax.sharding.Mesh(...)`` / ``make_mesh(...)`` calls anywhere else
    are findings (waiverable via ``LINT_ACKS.md`` for the rare
    deliberate exception).
    """

    code = "SMT013"
    name = "ad-hoc-mesh-construction"
    rationale = ("private meshes fragment sharding decisions and regress "
                 "to 1-D data parallelism; build layouts through "
                 "runtime.layout.SpecLayout")

    _ALLOWED_SUFFIXES = ("runtime/layout.py", "runtime/topology.py")

    def check(self, module: Module) -> Iterable[Finding]:
        rel = module.rel.replace(os.sep, "/")
        if any(rel.endswith(sfx) for sfx in self._ALLOWED_SUFFIXES):
            return []
        findings: List[Finding] = []
        mesh_aliases: Set[str] = set()   # names bound to the Mesh class
        module_aliases: Set[str] = set()  # names bound to the jax.sharding module
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ImportFrom):
                if node.module == "jax.sharding":
                    for a in node.names:
                        if a.name == "Mesh":
                            mesh_aliases.add(a.asname or a.name)
                elif node.module == "jax":
                    for a in node.names:
                        if a.name == "sharding":
                            module_aliases.add(a.asname or a.name)
            elif isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "jax.sharding" and a.asname:
                        module_aliases.add(a.asname)
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            dn = dotted_name(node.func)
            if dn is None:
                continue
            if dn in mesh_aliases or dn == "jax.sharding.Mesh" \
                    or dn.endswith(".sharding.Mesh") \
                    or any(dn == f"{m}.Mesh" for m in module_aliases):
                findings.append(self.finding(
                    module, node,
                    "ad-hoc jax.sharding.Mesh(...) construction; build the "
                    "mesh through runtime.layout.SpecLayout (canonical "
                    "axis names, 2-D shapes, (1,1) degradation)"))
            elif dn.split(".")[-1] == "make_mesh":
                findings.append(self.finding(
                    module, node,
                    "direct make_mesh(...) outside runtime/layout.py; use "
                    "runtime.layout.SpecLayout.build (or from_mesh) so "
                    "every engine shares one layout"))
        return findings


_WRONG_UNIT_SUFFIXES: Dict[str, str] = {
    # non-base-unit spellings -> the base unit Prometheus names use
    "_ms": "_seconds", "_millis": "_seconds", "_milliseconds": "_seconds",
    "_micros": "_seconds", "_us": "_seconds", "_nanos": "_seconds",
    "_ns": "_seconds", "_sec": "_seconds", "_secs": "_seconds",
    "_mins": "_seconds", "_minutes": "_seconds", "_hours": "_seconds",
    "_kb": "_bytes", "_mb": "_bytes", "_gb": "_bytes",
    "_kib": "_bytes", "_mib": "_bytes", "_gib": "_bytes",
}

# names that scream unbounded cardinality when they reach a label value:
# request/trace/span ids are unique per event, ports are unique per
# process incarnation — a label dict keyed by one grows the registry
# without bound and makes every scrape slower forever
_UNBOUNDED_LABEL_NAMES = {"rid", "request_id", "trace_id", "span_id",
                          "uuid", "request_uuid", "port"}


@register
class MetricNameDiscipline(Rule):
    """SMT014 — metric-name discipline on registry calls.

    Two invariants the whole exposition pipeline leans on:

    - **Unit-suffixed names.** Counters end ``_total`` (the OpenMetrics
      renderer strips it for family metadata — a counter without it
      produces spec-invalid OM and a failed scrape); nothing else ends
      ``_total``; timings/sizes use the base units ``_seconds``/``_bytes``
      (a ``_ms``/``_kb`` family breaks every recording rule and dashboard
      that assumes base units). Unitless gauges/histograms (ratios, MFU,
      batch sizes) are fine.
    - **Bounded label values.** ``labels(...)`` must never interpolate an
      unbounded value — a request id, trace id, span id, or port: one
      series per REQUEST is a memory leak wearing a label dict, and trace
      ids already have a first-class channel (exemplars). Detection is by
      value-expression name (``rid`` / ``request_id`` / ``trace_id`` /
      ``span_id`` / ``uuid`` / ``port``, bare or as an attribute or inside
      an f-string) and by direct ``uuid.*()`` calls. Bounded composite
      labels (``server_label = host:port`` retired on ``close()``) pass —
      the rule flags the raw signals, not every string containing digits.
    """

    code = "SMT014"
    name = "metric-name-discipline"
    rationale = ("non-base-unit or suffix-confused metric names break the "
                 "exposition contract; unbounded label values grow the "
                 "registry per request instead of per component")

    _CTORS = ("counter", "gauge", "histogram")

    def _name_findings(self, module: Module, node: ast.Call,
                       kind: str) -> Iterable[Finding]:
        if not (node.args and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            return  # dynamic name: the runtime schema check owns it
        mname = node.args[0].value
        if kind == "counter" and not mname.endswith("_total"):
            yield self.finding(
                module, node.args[0],
                f"counter {mname!r} must end in '_total' (the OpenMetrics "
                f"renderer names counter families by stripping it)")
        elif kind != "counter" and mname.endswith("_total"):
            yield self.finding(
                module, node.args[0],
                f"{kind} {mname!r} ends in '_total', the counter "
                f"convention; rename or make it a counter")
        for suf, base in _WRONG_UNIT_SUFFIXES.items():
            if mname.endswith(suf):
                yield self.finding(
                    module, node.args[0],
                    f"metric {mname!r} uses non-base unit {suf!r}; record "
                    f"base units ({base!r}) and let the dashboard scale")
                break

    @staticmethod
    def _unbounded_expr(expr: ast.AST) -> Optional[str]:
        """The offending name when ``expr`` is an unbounded-cardinality
        value (bare name, attribute, uuid call, or an f-string
        interpolating one); None when it looks bounded."""
        if isinstance(expr, ast.Name) and expr.id in _UNBOUNDED_LABEL_NAMES:
            return expr.id
        if isinstance(expr, ast.Attribute):
            if expr.attr in _UNBOUNDED_LABEL_NAMES:
                return expr.attr
            if isinstance(expr.value, (ast.Call, ast.Attribute)):
                # uuid.uuid4().hex and friends: the id hides one hop down
                return MetricNameDiscipline._unbounded_expr(expr.value)
        if isinstance(expr, ast.Call):
            dn = dotted_name(expr.func)
            if dn and (dn.startswith("uuid.")
                       or dn.split(".")[-1] in ("uuid4", "uuid1")):
                return dn
        if isinstance(expr, ast.JoinedStr):
            for v in expr.values:
                if isinstance(v, ast.FormattedValue):
                    got = MetricNameDiscipline._unbounded_expr(v.value)
                    if got is not None:
                        return got
        return None

    def check(self, module: Module) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            attr = node.func.attr
            if attr in self._CTORS:
                findings.extend(self._name_findings(module, node, attr))
            elif attr == "labels":
                values = list(node.args) + [kw.value for kw in node.keywords]
                for v in values:
                    bad = self._unbounded_expr(v)
                    if bad is not None:
                        findings.append(self.finding(
                            module, v,
                            f"unbounded value {bad!r} interpolated into a "
                            f"label: one series per request/trace/port "
                            f"incarnation grows the registry without "
                            f"bound — use a bounded label (trace ids "
                            f"belong in exemplars)"))
        return findings


# cache of "does this file use jax" verdicts, keyed by absolute path
_JAX_USING_CACHE: Dict[str, bool] = {}


def _file_uses_jax(path: str) -> bool:
    cached = _JAX_USING_CACHE.get(path)
    if cached is not None:
        return cached
    verdict = False
    try:
        with open(path, encoding="utf-8") as f:
            src = f.read()
        if "jax" in src:  # cheap pre-filter before parsing
            for node in ast.walk(ast.parse(src)):
                if _imports_jax(node):
                    verdict = True
                    break
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Name)
                        and node.func.id == "lazy_import"
                        and node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)
                        and _is_jax_module(node.args[0].value)):
                    verdict = True
                    break
    except (OSError, SyntaxError):
        verdict = False
    _JAX_USING_CACHE[path] = verdict
    return verdict


@register
class EagerJaxSubpackageInit(Rule):
    """SMT008 — a package ``__init__`` eagerly imports a jax-using
    submodule instead of exporting via ``core/lazyimport.py`` (PEP 562).

    ``import synapseml_tpu.gbdt`` must stay cheap and jax-free even though
    the trainer underneath uses jax everywhere: serving workers, scrapers
    and tools import packages at startup. The fix is
    ``lazy_module(__name__, {...})`` — attribute access imports the owning
    submodule on demand. Direct-submodule depth only (``from .boost import
    train``); the subprocess hygiene gate remains the transitive ground
    truth.
    """

    code = "SMT008"
    name = "eager-jax-subpackage-init"
    rationale = ("eager __init__ imports of jax-using submodules make "
                 "package import pay for the whole trainer")

    def check(self, module: Module) -> Iterable[Finding]:
        if not module.is_init:
            return []
        findings: List[Finding] = []
        for node in module.tree.body:
            targets: List[Tuple[str, str]] = []  # (display, abs path base)
            if isinstance(node, ast.ImportFrom) and node.level >= 1:
                base = module.dirname
                for _ in range(node.level - 1):
                    base = os.path.dirname(base)
                if node.module is None:
                    targets = [(a.name, os.path.join(
                        base, *a.name.split("."))) for a in node.names]
                else:
                    targets = [(node.module, os.path.join(
                        base, *node.module.split(".")))]
            elif isinstance(node, ast.ImportFrom) and node.module and \
                    node.module.startswith("synapseml_tpu."):
                # absolute self-import: find the package root on the
                # FILESYSTEM (walk up to the 'synapseml_tpu' directory) —
                # rel-path depth depends on where the scan was rooted
                top = node.module.split(".")[0]
                root = module.dirname
                while (os.path.basename(root) != top
                       and os.path.dirname(root) != root):
                    root = os.path.dirname(root)
                if os.path.basename(root) == top:
                    targets = [(node.module, os.path.join(
                        os.path.dirname(root), *node.module.split(".")))]
            for display, base in targets:
                for cand in (base + ".py", os.path.join(base, "__init__.py")):
                    if os.path.isfile(cand) and _file_uses_jax(cand):
                        findings.append(self.finding(
                            module, node,
                            f"eager import of jax-using submodule "
                            f"{display!r} in package __init__; export via "
                            f"core.lazyimport.lazy_module (PEP 562)"))
                        break
        return findings
