"""Train helpers: auto-featurizing wrappers + model statistics.

Reference: ``core/.../train/`` (1270 LoC) — ``TrainClassifier.scala:50`` /
``TrainRegressor`` (auto-featurize any columns, index labels, fit the wrapped
learner), ``ComputeModelStatistics.scala:59`` (confusion matrix, accuracy,
precision/recall/AUC for classifiers; MSE/RMSE/R2/MAE for regressors),
``ComputePerInstanceStatistics`` (per-row L1/L2 loss or log-loss).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..core import ComplexParam, Estimator, Model, Param, Table, Transformer
from ..featurize.stages import Featurize
from ..gbdt.boost import METRICS

__all__ = [
    "TrainClassifier", "TrainedClassifierModel",
    "TrainRegressor", "TrainedRegressorModel",
    "ComputeModelStatistics", "ComputePerInstanceStatistics",
]


class _TrainBase(Estimator):
    _abstract_stage = True

    model = ComplexParam("the learner estimator to train", object, default=None)
    label_col = Param("label column", str, default="label")
    features_col = Param("assembled features column", str, default="features")
    input_cols = Param("columns to featurize ([] = all non-label)", list, default=[])
    number_of_features = Param("hash space for high-cardinality columns", int,
                               default=262144)

    def _featurizer(self, table: Table) -> "Model":
        cols = list(self.input_cols) or [
            c for c in table.column_names if c != self.label_col
        ]
        return Featurize(input_cols=cols, output_col=self.features_col,
                         num_features=self.number_of_features).fit(table)


class TrainClassifier(_TrainBase):
    """Featurize + index labels + fit (reference ``TrainClassifier.scala:50``).
    Default learner: LightGBMClassifier."""

    def _fit(self, table: Table) -> "TrainedClassifierModel":
        self._validate_input(table, self.label_col)
        feat = self._featurizer(table)
        featurized = feat.transform(table)
        learner = self.model
        if learner is None:
            from ..gbdt import LightGBMClassifier

            learner = LightGBMClassifier()
        learner.set("features_col", self.features_col)
        learner.set("label_col", self.label_col)
        fitted = learner.fit(featurized)
        return TrainedClassifierModel(
            featurizer=feat, inner_model=fitted, label_col=self.label_col,
            features_col=self.features_col)


class TrainedClassifierModel(Model):
    featurizer = ComplexParam("fitted featurizer", object, default=None)
    inner_model = ComplexParam("fitted learner model", object, default=None)
    label_col = Param("label column", str, default="label")
    features_col = Param("features column", str, default="features")

    def _transform(self, table: Table) -> Table:
        return self.inner_model.transform(self.featurizer.transform(table))


class TrainRegressor(_TrainBase):
    """Reference ``TrainRegressor``. Default learner: LightGBMRegressor."""

    def _fit(self, table: Table) -> "TrainedRegressorModel":
        self._validate_input(table, self.label_col)
        feat = self._featurizer(table)
        featurized = feat.transform(table)
        learner = self.model
        if learner is None:
            from ..gbdt import LightGBMRegressor

            learner = LightGBMRegressor()
        learner.set("features_col", self.features_col)
        learner.set("label_col", self.label_col)
        fitted = learner.fit(featurized)
        return TrainedRegressorModel(
            featurizer=feat, inner_model=fitted, label_col=self.label_col,
            features_col=self.features_col)


class TrainedRegressorModel(Model):
    featurizer = ComplexParam("fitted featurizer", object, default=None)
    inner_model = ComplexParam("fitted learner model", object, default=None)
    label_col = Param("label column", str, default="label")
    features_col = Param("features column", str, default="features")

    def _transform(self, table: Table) -> Table:
        return self.inner_model.transform(self.featurizer.transform(table))


class ComputeModelStatistics(Transformer):
    """Scored table -> one-row metrics table
    (reference ``ComputeModelStatistics.scala:59``).

    ``evaluation_metric``: 'classification' | 'regression' | 'auto'."""

    label_col = Param("label column", str, default="label")
    scores_col = Param("prediction column", str, default="prediction")
    scored_labels_col = Param("alias of scores_col (reference name)", str,
                              default=None)
    probability_col = Param("probability column for AUC (classification)",
                            str, default="probability")
    evaluation_metric = Param("classification | regression | auto", str,
                              default="auto")

    def _transform(self, table: Table) -> Table:
        pred_col = self.scored_labels_col or self.scores_col
        self._validate_input(table, self.label_col, pred_col)
        y = table[self.label_col]
        pred = table[pred_col]
        mode = self.evaluation_metric
        if mode == "auto":
            numeric = (np.asarray(y).dtype != object
                       and len(np.unique(np.asarray(y))) > 10)
            mode = "regression" if numeric else "classification"
        if mode == "regression":
            yv = np.asarray(y, np.float64)
            pv = np.asarray(pred, np.float64)
            mse = float(np.mean((yv - pv) ** 2))
            ss_tot = float(np.sum((yv - yv.mean()) ** 2))
            stats = {
                "mean_squared_error": mse,
                "root_mean_squared_error": float(np.sqrt(mse)),
                "mean_absolute_error": float(np.mean(np.abs(yv - pv))),
                "R^2": 1.0 - float(np.sum((yv - pv) ** 2)) / ss_tot if ss_tot else 0.0,
            }
            return Table({k: np.array([v]) for k, v in stats.items()})
        # classification
        y_list = y.tolist()
        p_list = pred.tolist()
        classes = sorted({*y_list, *p_list}, key=str)
        lut = {c: i for i, c in enumerate(classes)}
        k = len(classes)
        cm = np.zeros((k, k), np.int64)
        for a, b in zip(y_list, p_list):
            cm[lut[a], lut[b]] += 1
        total = cm.sum()
        acc = float(np.trace(cm)) / total if total else 0.0
        with np.errstate(invalid="ignore", divide="ignore"):
            prec = np.diag(cm) / np.maximum(cm.sum(axis=0), 1)
            rec = np.diag(cm) / np.maximum(cm.sum(axis=1), 1)
        stats = {
            "accuracy": acc,
            "precision": float(np.mean(prec)),
            "recall": float(np.mean(rec)),
        }
        if k == 2 and self.probability_col in table:
            prob = np.asarray(table[self.probability_col])
            score = prob[:, 1] if prob.ndim == 2 else prob
            y_bin = np.array([lut[v] for v in y_list], np.float64)
            stats["AUC"] = METRICS["auc"][0](y_bin, score.astype(np.float64),
                                             np.ones(len(y_bin)))
        out = Table({k2: np.array([v]) for k2, v in stats.items()})
        out.meta["confusion_matrix"] = {"matrix": cm, "classes": classes}
        return out


class ComputePerInstanceStatistics(Transformer):
    """Per-row loss columns (reference ``ComputePerInstanceStatistics``)."""

    label_col = Param("label column", str, default="label")
    scores_col = Param("prediction column", str, default="prediction")
    probability_col = Param("probability column (classification)", str,
                            default="probability")
    evaluation_metric = Param("classification | regression | auto", str,
                              default="auto")

    def _transform(self, table: Table) -> Table:
        self._validate_input(table, self.label_col, self.scores_col)
        y = table[self.label_col]
        pred = table[self.scores_col]
        mode = self.evaluation_metric
        if mode == "auto":
            numeric = (np.asarray(y).dtype != object
                       and len(np.unique(np.asarray(y))) > 10)
            mode = "regression" if numeric else "classification"
        if mode == "regression":
            yv = np.asarray(y, np.float64)
            pv = np.asarray(pred, np.float64)
            return (table.with_column("L1_loss", np.abs(yv - pv))
                    .with_column("L2_loss", (yv - pv) ** 2))
        if self.probability_col in table:
            prob = np.asarray(table[self.probability_col], np.float64)
            classes = sorted({*y.tolist()}, key=str)
            lut = {c: i for i, c in enumerate(classes)}
            idx = np.array([lut.get(v, 0) for v in y.tolist()])
            if prob.ndim == 2 and prob.shape[1] >= len(classes):
                p_true = prob[np.arange(len(idx)), idx]
            else:
                p1 = prob if prob.ndim == 1 else prob[:, -1]
                p_true = np.where(idx == 1, p1, 1 - p1)
            ll = -np.log(np.clip(p_true, 1e-15, None))
            return table.with_column("log_loss", ll)
        correct = np.array([a == b for a, b in zip(y.tolist(), pred.tolist())],
                           np.float64)
        return table.with_column("0_1_loss", 1.0 - correct)
