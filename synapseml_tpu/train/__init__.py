"""Train helpers (reference ``core/.../train/``, SURVEY.md §2.3)."""

from .stages import (
    ComputeModelStatistics, ComputePerInstanceStatistics, TrainClassifier,
    TrainRegressor, TrainedClassifierModel, TrainedRegressorModel,
)

__all__ = [
    "TrainClassifier", "TrainedClassifierModel", "TrainRegressor",
    "TrainedRegressorModel", "ComputeModelStatistics",
    "ComputePerInstanceStatistics",
]
