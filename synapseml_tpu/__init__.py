"""synapseml_tpu — a TPU-native distributed ML pipeline framework.

A from-scratch rebuild of the capabilities of SynapseML (MMLSpark) designed for TPU
hardware: composable Estimator/Transformer pipelines over partitioned columnar tables,
a histogram-GBDT trainer whose feature-histogram allreduce runs as XLA collectives over
the ICI mesh, an online linear / contextual-bandit learner with collective weight
averaging, an ONNX importer executing via jit/pjit, image featurization, HTTP service
transformers, low-latency serving, and a library of distributed ML tools (explainers,
tuning, recommenders, KNN, data balance). See SURVEY.md at the repo root for the
structural analysis of the reference this rebuild targets.
"""

__version__ = "0.1.0"

from .core import (  # noqa: F401
    ComplexParam,
    Estimator,
    Model,
    Param,
    ParamValidators,
    Params,
    Pipeline,
    PipelineModel,
    PipelineStage,
    Table,
    Transformer,
    UnaryTransformer,
    concat_tables,
    load_stage,
    save_stage,
)
