"""sklearn-compatible wrapper GENERATION — the second executable surface.

Reference: the codegen layer does not stop at stubs — it emits RUNNABLE
wrapper classes for other surfaces from Param metadata
(``Wrappable.scala:394`` ``pyInitFunc``/``pyValueFuncs``, ``:515`` R
wrappers; ``CodeGen.scala:23-199`` walks the registry and writes the
wrapper packages), and auto-generates tests asserting cross-surface model
equality (``Fuzzing.scala:47`` ``PyTestFuzzing``). Here the second surface
is the sklearn estimator protocol: every registered
:class:`~synapseml_tpu.core.stage.Estimator` becomes a ``Sk<Name>`` class
with ``get_params``/``set_params`` (sklearn clone protocol), ``fit(X, y,
**columns)`` building the Table from arrays, and ``predict`` /
``predict_proba`` reading the model's output columns.

The generated module is COMMITTED (``synapseml_tpu/sklearn_api.py``) like
the reference's checked-in wrapper packages; ``tests/test_sklearn_api.py``
asserts (a) regeneration is drift-free against the committed text and
(b) wrapper <-> native equality per estimator — the PyTestFuzzing role.
"""

from __future__ import annotations

from typing import List

from ..core.params import ComplexParam
from ..core.stage import STAGE_REGISTRY, Estimator
from .generate import import_all_stage_modules

__all__ = ["generate_sklearn_module", "write_sklearn_module",
           "sklearn_estimator_names"]

_HEADER = '''"""sklearn-compatible estimator surface — GENERATED, do not edit.

Regenerate with ``python -m synapseml_tpu.codegen --sklearn``. Every
registered Estimator is wrapped in the sklearn protocol:

    from synapseml_tpu.sklearn_api import SkLightGBMClassifier
    clf = SkLightGBMClassifier(num_iterations=50).fit(X, y)
    proba = clf.predict_proba(X_test)

``fit(X, y=None, **columns)`` builds the native Table (``X`` -> the
estimator's features column, ``y`` -> its label column, extra arrays by
column name — e.g. ``group=`` for the ranker); ``predict`` returns the
model's prediction column, ``predict_proba`` the probability column where
one exists. ``get_params``/``set_params`` follow the sklearn clone
protocol, so these wrappers drop into sklearn model selection utilities.
"""

# fmt: off
# flake8: noqa

import numpy as np

try:  # BaseEstimator supplies __sklearn_tags__ etc. for sklearn >= 1.6
    from sklearn.base import BaseEstimator as _SkParent
except ImportError:  # sklearn absent: the protocol still works standalone
    class _SkParent:  # type: ignore[no-redef]
        pass


class _SkBase(_SkParent):
    """Shared sklearn-protocol plumbing over a native estimator class."""

    _native_module = None
    _native_class = None
    _features_col = None
    _label_col = None
    _prediction_col = None
    _probability_col = None

    def __init__(self, **params):
        self._validate(params)
        for name in self._param_names:
            if name in params:
                # user values stored UNMODIFIED: sklearn clone() checks
                # identity of constructor params
                value = params[name]
            else:
                value = self._param_defaults[name]
                if isinstance(value, (list, dict, set)):
                    # never alias the shared class-level mutable default
                    value = value.copy()
            setattr(self, name, value)
        self.model_ = None

    def _validate(self, params):
        unknown = set(params) - set(self._param_names)
        if unknown:
            raise TypeError(
                f"{type(self).__name__}: unknown params {sorted(unknown)}")
        for k, v in params.items():
            if v is None and self._param_defaults[k] is not None:
                # silently mapping None back to the default would make
                # get_params() disagree with the fitted native estimator
                raise TypeError(
                    f"{type(self).__name__}: {k}=None is not valid "
                    f"(omit it for the default {self._param_defaults[k]!r})")

    # -- sklearn clone protocol ------------------------------------------------

    def get_params(self, deep: bool = True):
        return {n: getattr(self, n) for n in self._param_names}

    def set_params(self, **params):
        self._validate(params)
        for k, v in params.items():
            setattr(self, k, v)  # as-is: sklearn set_params/clone semantics
        return self

    def __sklearn_tags__(self):
        tags = super().__sklearn_tags__()  # needs sklearn >= 1.6
        est_type = getattr(self, "_estimator_type", None)
        if est_type is not None:
            tags.estimator_type = est_type
        return tags

    def score(self, X, y, **columns):
        """Accuracy for classifiers, R^2 for regressors (the sklearn
        default-scoring contract model selection relies on)."""
        pred = self.predict(X, **columns)
        y = np.asarray(y)
        if getattr(self, "_estimator_type", None) == "classifier":
            return float((pred == y).mean())
        ss_res = float(((y - pred) ** 2).sum())
        ss_tot = float(((y - y.mean()) ** 2).sum())
        return 1.0 - ss_res / ss_tot if ss_tot else 0.0

    # -- native bridge ---------------------------------------------------------

    def _native(self):
        import importlib

        cls = getattr(importlib.import_module(self._native_module),
                      self._native_class)
        # None only ever means "the native default" here (_validate rejects
        # explicit None for non-None defaults), so omit those args
        kw = {n: getattr(self, n) for n in self._param_names
              if getattr(self, n) is not None}
        return cls(**kw)

    def _table(self, X, y=None, **columns):
        from synapseml_tpu.core import Table

        cols = {}
        if X is not None:
            cols[getattr(self, self._features_col)
                 if self._features_col else "features"] = np.asarray(X)
        if y is not None:
            cols[getattr(self, self._label_col)
                 if self._label_col else "label"] = np.asarray(y)
        for name, arr in columns.items():
            cols[name] = np.asarray(arr)
        return Table(cols)

    def fit(self, X, y=None, **columns):
        self.model_ = self._native().fit(self._table(X, y, **columns))
        if y is not None and \
                getattr(self, "_estimator_type", None) == "classifier":
            # sklearn scorers resolve predict_proba columns via classes_
            self.classes_ = np.unique(np.asarray(y))
        return self

    def _check_fitted(self):
        if self.model_ is None:
            raise RuntimeError(
                f"{type(self).__name__} is not fitted; call fit first")

    def transform(self, X, **columns):
        """The fitted model's full output Table (every output column)."""
        self._check_fitted()
        return self.model_.transform(self._table(X, **columns))

    def predict(self, X, **columns):
        self._check_fitted()
        out = self.transform(X, **columns)
        col = (getattr(self, self._prediction_col)
               if self._prediction_col else "prediction")
        return np.asarray(out[col])

    def predict_proba(self, X, **columns):
        if self._probability_col is None:
            raise AttributeError(
                f"{type(self).__name__} has no probability output")
        self._check_fitted()
        out = self.transform(X, **columns)
        return np.asarray(out[getattr(self, self._probability_col)])

    def __repr__(self):
        def differs(v, d):
            try:
                return bool(v != d)
            except Exception:  # e.g. numpy array vs list comparison
                return True

        changed = {n: v for n, v in self.get_params().items()
                   if differs(v, self._param_defaults[n])}
        args = ", ".join(f"{k}={v!r}" for k, v in sorted(changed.items()))
        return f"{type(self).__name__}({args})"

'''


def sklearn_estimator_names() -> List[str]:
    """Registered LIBRARY estimators that get wrappers (sorted; Pipeline
    excluded — its stage-list param is not a scalar sklearn param surface).
    Restricted to ``synapseml_tpu.*`` modules: the registry is global, so
    user/test-defined estimators registered earlier in a process must not
    leak into (or drift-fail) the committed generated surface."""
    import_all_stage_modules()
    return sorted(
        n for n, c in STAGE_REGISTRY.items()
        if issubclass(c, Estimator) and n != "Pipeline"
        and c.__module__.startswith("synapseml_tpu."))


def _wrapper_source(name: str) -> str:
    cls = STAGE_REGISTRY[name]
    simple = {n: p for n, p in sorted(cls._params.items())
              if not isinstance(p, ComplexParam)}
    defaults = {n: (p.default if p.has_default else None)
                for n, p in simple.items()}
    doc = (cls.__doc__ or "").strip().splitlines()
    first_doc = doc[0].replace('"""', "'''") if doc else name
    lines = [f"class Sk{name}(_SkBase):"]
    lines.append(f'    """{first_doc}"""')
    lines.append("")
    lines.append(f"    _native_module = {cls.__module__!r}")
    lines.append(f"    _native_class = {name!r}")
    for attr, pname in (("_features_col", "features_col"),
                        ("_label_col", "label_col"),
                        ("_prediction_col", "prediction_col"),
                        ("_probability_col", "probability_col")):
        if pname in cls._params:
            lines.append(f"    {attr} = {pname!r}")
    # classifier: has a probability output; regressor: supervised without
    # one — drives sklearn's is_classifier/stratified-CV + default scoring
    if "probability_col" in cls._params:
        lines.append("    _estimator_type = 'classifier'")
    elif "label_col" in cls._params and "prediction_col" in cls._params:
        lines.append("    _estimator_type = 'regressor'")
    lines.append(f"    _param_names = {tuple(simple)!r}")
    lines.append(f"    _param_defaults = {defaults!r}")
    lines.append("")
    return "\n".join(lines)


def generate_sklearn_module() -> str:
    """The full generated module source."""
    names = sklearn_estimator_names()
    parts = [_HEADER]
    for name in names:
        parts.append(_wrapper_source(name))
        parts.append("")
    all_line = ", ".join(f'"Sk{n}"' for n in names)
    parts.append(f"__all__ = [{all_line}]")
    parts.append("")
    return "\n".join(parts)


def write_sklearn_module(path: str) -> str:
    src = generate_sklearn_module()
    with open(path, "w") as f:
        f.write(src)
    return path
