"""CLI: ``python -m synapseml_tpu.codegen <out_dir>`` writes stubs + docs;
``--sklearn`` regenerates the committed sklearn wrapper surface
(reference: the sbt ``codegen`` task driving ``CodeGen.scala``)."""

import os
import sys

from .generate import generate_api_docs, generate_stubs
from .sklearn_gen import write_sklearn_module


def main(argv) -> int:
    if "--sklearn" in argv:
        target = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "sklearn_api.py")
        write_sklearn_module(target)
        print(f"regenerated {target}")
        return 0
    out = argv[1] if len(argv) > 1 else "generated"
    stubs = generate_stubs(f"{out}/stubs")  # stubs/<full module path>.pyi
    docs = generate_api_docs(f"{out}/docs")
    print(f"wrote {len(stubs)} stub files and {len(docs)} doc files to {out}/")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
