"""CLI: ``python -m synapseml_tpu.codegen <out_dir>`` writes stubs + docs
(reference: the sbt ``codegen`` task driving ``CodeGen.scala``)."""

import sys

from .generate import generate_api_docs, generate_stubs


def main(argv) -> int:
    out = argv[1] if len(argv) > 1 else "generated"
    stubs = generate_stubs(f"{out}/stubs")  # stubs/<full module path>.pyi
    docs = generate_api_docs(f"{out}/docs")
    print(f"wrote {len(stubs)} stub files and {len(docs)} doc files to {out}/")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
