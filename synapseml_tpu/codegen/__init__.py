"""Codegen: reflect stage Params into generated API surfaces.

Reference layer: ``core/.../codegen/`` — ``Wrappable.scala:68`` /
``CodeGen.scala:23-199`` walk every stage in the jar via
``JarLoadingUtils.instantiateServices`` and emit Python/R wrappers, setup
files, and docs from ``Params`` reflection. This framework is Python-native
(no wrapper language gap), so the same reflection emits what still has
value: typed ``.pyi`` stubs for IDEs/type-checkers and a markdown API
reference — from the live :data:`STAGE_REGISTRY`, so new stages are covered
the moment they register (same enforcement surface as the fuzzing
meta-test).
"""

from .generate import generate_api_docs, generate_stubs, registry_inventory

__all__ = ["generate_api_docs", "generate_stubs", "registry_inventory"]
