"""Static table schemas — the plan-time type system for pipelines.

The reference's stage contract is built on SparkML's ``transformSchema``:
every ``Estimator``/``Transformer`` statically declares how it maps an
input schema to an output schema (``PipelineStage.transformSchema``, used
by ``Pipeline.fit`` before any executor work is scheduled), so a mis-wired
pipeline fails in milliseconds at plan time. The rebuild's eager
``transform`` lost that: a missing or mistyped column only surfaced when
``_validate_input`` threw mid-``transform``, after upstream stages had
already burned device time.

This module restores the static half, deliberately coarser than numpy
dtypes (schemas must survive JSON, serving payloads, and "float32 vs
float64" irrelevancies):

- :class:`ColumnSpec` — a column's **dtype class** (``float`` / ``int`` /
  ``bool`` / ``object`` / ``any``) and **shape role** (``scalar`` — a 1-D
  column; ``vector`` — one vector per row; ``tensor`` — higher-rank per
  row; ``image`` — a tensor column carrying image semantics; ``any``).
- :class:`TableSchema` — ordered name -> :class:`ColumnSpec` mapping,
  derivable from a live :class:`~synapseml_tpu.core.table.Table`
  (:meth:`TableSchema.from_table`), JSON round-trippable (serving
  admission sends the expected schema back in 400 replies).
- :class:`SchemaError` — reports **all** missing columns at once with
  nearest-name suggestions (difflib), not just the first.

Stages declare their contract via ``input_schema()`` /
``transform_schema()`` / ``fit_schema()`` on ``PipelineStage``
(``core/stage.py``); ``Pipeline.validate`` threads a schema through every
stage **statically** — numpy only, no jax, no device work.
"""

from __future__ import annotations

import difflib
from typing import Any, Dict, Iterable, List, Mapping, Optional, Sequence, Tuple, Union

import numpy as np

__all__ = [
    "ColumnSpec",
    "TableSchema",
    "SchemaError",
    "PipelineSchemaError",
    "dtype_class_of",
    "nearest_name",
]

DTYPE_CLASSES = ("float", "int", "bool", "object", "any")
SHAPE_ROLES = ("scalar", "vector", "tensor", "image", "any")


def dtype_class_of(dtype) -> str:
    """Coarse class of a numpy dtype: float / int / bool / object."""
    kind = np.dtype(dtype).kind
    if kind == "f":
        return "float"
    if kind in ("i", "u"):
        return "int"
    if kind == "b":
        return "bool"
    return "object"  # O, U, S, V, M, ...


def nearest_name(name: str, candidates: Iterable[str]) -> Optional[str]:
    """Closest candidate to ``name`` (difflib), or None when nothing is
    plausibly a typo — the "did you mean" half of schema errors."""
    matches = difflib.get_close_matches(name, list(candidates), n=1,
                                        cutoff=0.6)
    return matches[0] if matches else None


class SchemaError(ValueError):
    """A static schema violation. ``missing`` lists every absent column
    (all at once, with suggestions already baked into the message);
    ``mismatched`` lists ``(column, expected, actual)`` spec conflicts."""

    def __init__(self, message: str,
                 missing: Sequence[str] = (),
                 mismatched: Sequence[Tuple[str, "ColumnSpec",
                                            "ColumnSpec"]] = ()):
        super().__init__(message)
        self.missing = list(missing)
        self.mismatched = list(mismatched)


class PipelineSchemaError(SchemaError):
    """A :class:`SchemaError` localized to one pipeline stage: carries the
    stage index and the offending stage so callers can report "stage 2
    (ValueIndexer...) ..." without re-parsing the message."""

    def __init__(self, message: str, stage_index: int, stage: Any,
                 cause: Optional[SchemaError] = None):
        super().__init__(message,
                         missing=cause.missing if cause else (),
                         mismatched=cause.mismatched if cause else ())
        self.stage_index = stage_index
        self.stage = stage


class ColumnSpec:
    """One column's (dtype class, shape role). ``any`` wildcards either
    axis; :meth:`accepts` is the compatibility relation consumers use
    (``float`` accepts ``int``/``bool`` inputs — upcast is lossless;
    ``tensor`` accepts ``image``/``vector`` — images and vectors *are*
    tensors)."""

    __slots__ = ("dtype_class", "role")

    def __init__(self, dtype_class: str = "any", role: str = "any"):
        if dtype_class not in DTYPE_CLASSES:
            raise ValueError(f"unknown dtype class {dtype_class!r}; "
                             f"one of {DTYPE_CLASSES}")
        if role not in SHAPE_ROLES:
            raise ValueError(f"unknown shape role {role!r}; "
                             f"one of {SHAPE_ROLES}")
        self.dtype_class = dtype_class
        self.role = role

    # -- construction ------------------------------------------------------

    @classmethod
    def parse(cls, spec: Union["ColumnSpec", str, Tuple[str, str]]
              ) -> "ColumnSpec":
        """Coerce ``"float"`` / ``"float:vector"`` / ``("float", "vector")``
        / a ColumnSpec into a ColumnSpec."""
        if isinstance(spec, ColumnSpec):
            return spec
        if isinstance(spec, tuple):
            return cls(*spec)
        if isinstance(spec, str):
            if ":" in spec:
                dc, _, role = spec.partition(":")
                return cls(dc, role)
            return cls(spec, "any")
        raise TypeError(f"cannot parse column spec from {spec!r}")

    @classmethod
    def from_column(cls, arr: np.ndarray,
                    meta: Optional[Dict[str, Any]] = None) -> "ColumnSpec":
        """Derive a spec from a live column array (+ its Table meta)."""
        sem = (meta or {}).get("type")
        if arr.dtype == object:
            first = next((v for v in arr if v is not None), None)
            if isinstance(first, np.ndarray):
                role = ("image" if sem == "image"
                        else "vector" if first.ndim == 1 else "tensor")
                return cls(dtype_class_of(first.dtype), role)
            if isinstance(first, tuple):  # sparse (indices, values) pairs
                return cls("object", "vector")
            return cls("object", "scalar")
        if arr.ndim > 1:
            role = ("image" if sem == "image"
                    else "vector" if arr.ndim == 2 else "tensor")
            return cls(dtype_class_of(arr.dtype), role)
        return cls(dtype_class_of(arr.dtype), "scalar")

    # -- relations ---------------------------------------------------------

    def accepts(self, other: "ColumnSpec") -> bool:
        """Would a consumer declaring ``self`` accept a column shaped like
        ``other``?"""
        dc_ok = (self.dtype_class == "any" or other.dtype_class == "any"
                 or self.dtype_class == other.dtype_class
                 or (self.dtype_class == "float"
                     and other.dtype_class in ("int", "bool")))
        role_ok = (self.role == "any" or other.role == "any"
                   or self.role == other.role
                   or (self.role == "tensor"
                       and other.role in ("image", "vector"))
                   or (self.role == "image" and other.role == "tensor"))
        return dc_ok and role_ok

    def __eq__(self, other) -> bool:
        return (isinstance(other, ColumnSpec)
                and self.dtype_class == other.dtype_class
                and self.role == other.role)

    def __hash__(self) -> int:
        return hash((self.dtype_class, self.role))

    def __repr__(self) -> str:
        return f"{self.dtype_class}:{self.role}"

    # -- JSON-value check (serving admission) ------------------------------

    def accepts_json_value(self, v: Any) -> bool:
        """Does a JSON-decoded value fit this spec? The serving admission
        check — a 400 at the front door instead of a worker 500. For
        vector/tensor/image roles the dtype class applies to the (nested)
        list's leaf elements."""
        if self.role in ("vector", "tensor", "image"):
            if not isinstance(v, list):
                return False
            leaves = v
            while leaves and isinstance(leaves[0], list):
                leaves = leaves[0]
            return all(self._leaf_ok(x) for x in leaves[:64])
        if self.role == "scalar" and isinstance(v, list):
            return False
        if self.role == "any" and isinstance(v, list):
            return True  # structure unknown: admit, the stage decides
        return self._leaf_ok(v)

    def _leaf_ok(self, v: Any) -> bool:
        if self.dtype_class == "bool":
            return isinstance(v, bool)
        if self.dtype_class == "int":
            return isinstance(v, int) and not isinstance(v, bool)
        if self.dtype_class == "float":
            return isinstance(v, (int, float)) and not isinstance(v, bool)
        return True  # object / any


class TableSchema:
    """Ordered name -> :class:`ColumnSpec` mapping.

    ``open=True`` marks a schema with **unknown additional columns** (the
    output of an undeclared stage): :meth:`require` then only checks the
    columns it knows about and never reports missing ones — static
    validation degrades gracefully instead of false-positive failing."""

    def __init__(self, columns: Mapping[str, Union[ColumnSpec, str,
                                                   Tuple[str, str]]] = (),
                 open: bool = False):
        self._cols: Dict[str, ColumnSpec] = {
            str(k): ColumnSpec.parse(v) for k, v in dict(columns).items()}
        self.open = bool(open)

    # -- construction ------------------------------------------------------

    @classmethod
    def from_table(cls, table) -> "TableSchema":
        """Derive the schema of a live Table (numpy only — no jax)."""
        return cls({name: ColumnSpec.from_column(table.column(name),
                                                 table.meta.get(name))
                    for name in table.column_names})

    @classmethod
    def open_schema(cls) -> "TableSchema":
        """The anything-goes schema an undeclared stage outputs."""
        return cls({}, open=True)

    @classmethod
    def from_dict(cls, d: Mapping[str, str]) -> "TableSchema":
        return cls({k: ColumnSpec.parse(v) for k, v in d.items()})

    def to_dict(self) -> Dict[str, str]:
        """JSON-ready ``{name: "dtype_class:role"}`` — what serving 400
        replies embed so the client sees the expected contract."""
        return {k: repr(v) for k, v in self._cols.items()}

    # -- accessors ---------------------------------------------------------

    @property
    def columns(self) -> List[str]:
        return list(self._cols)

    def __contains__(self, name: str) -> bool:
        return name in self._cols

    def __getitem__(self, name: str) -> ColumnSpec:
        return self._cols[name]

    def get(self, name: str,
            default: Optional[ColumnSpec] = None) -> Optional[ColumnSpec]:
        return self._cols.get(name, default)

    def __eq__(self, other) -> bool:
        return (isinstance(other, TableSchema) and self.open == other.open
                and self._cols == other._cols)

    def __repr__(self) -> str:
        inner = ", ".join(f"{k}: {v!r}" for k, v in self._cols.items())
        return f"TableSchema({{{inner}}}{', open' if self.open else ''})"

    def describe(self) -> str:
        """Compact human form for error messages: ``{a: float:scalar, ...}``."""
        return "{" + ", ".join(f"{k}: {v!r}" for k, v in self._cols.items()) \
            + ("*" if self.open else "") + "}"

    # -- functional updates ------------------------------------------------

    def with_column(self, name: str,
                    spec: Union[ColumnSpec, str, Tuple[str, str]]
                    ) -> "TableSchema":
        cols = dict(self._cols)
        cols[name] = ColumnSpec.parse(spec)
        return TableSchema(cols, open=self.open)

    def with_columns(self, new: Mapping[str, Any]) -> "TableSchema":
        out = self
        for k, v in new.items():
            out = out.with_column(k, v)
        return out

    def drop(self, *names: str) -> "TableSchema":
        return TableSchema({k: v for k, v in self._cols.items()
                            if k not in names}, open=self.open)

    def select(self, *names: str) -> "TableSchema":
        return TableSchema({n: self._cols[n] for n in names if n in
                            self._cols}, open=self.open)

    def rename(self, mapping: Mapping[str, str]) -> "TableSchema":
        return TableSchema({mapping.get(k, k): v
                            for k, v in self._cols.items()}, open=self.open)

    # -- validation --------------------------------------------------------

    def require(self, needed: Union["TableSchema", Mapping[str, Any],
                                    Sequence[str]],
                stage: Optional[str] = None) -> None:
        """Check this schema satisfies ``needed`` (a TableSchema, a
        name->spec mapping, or just column names). Raises ONE
        :class:`SchemaError` naming **every** missing column (with a
        nearest-name suggestion each) and every dtype/role mismatch.
        Missing columns are not reported when this schema is ``open``."""
        if isinstance(needed, TableSchema):
            need = dict(needed._cols)
        elif isinstance(needed, Mapping):
            need = {k: ColumnSpec.parse(v) for k, v in needed.items()}
        else:
            need = {str(c): ColumnSpec() for c in needed}
        missing: List[str] = []
        mismatched: List[Tuple[str, ColumnSpec, ColumnSpec]] = []
        for name, want in need.items():
            have = self._cols.get(name)
            if have is None:
                if not self.open:
                    missing.append(name)
            elif not want.accepts(have):
                mismatched.append((name, want, have))
        if not missing and not mismatched:
            return
        parts: List[str] = []
        if missing:
            descr = []
            for name in missing:
                sug = nearest_name(name, self._cols)
                descr.append(f"{name!r}"
                             + (f" (did you mean {sug!r}?)" if sug else ""))
            parts.append(f"missing column{'s' if len(missing) > 1 else ''} "
                         + ", ".join(descr)
                         + f"; available: {self.columns}")
        for name, want, have in mismatched:
            parts.append(f"column {name!r} has type {have!r}, "
                         f"expected {want!r}")
        prefix = f"{stage}: " if stage else ""
        raise SchemaError(prefix + "; ".join(parts),
                          missing=missing, mismatched=mismatched)

    def validate_json_payload(self, payload: Any,
                              max_errors: int = 16) -> List[str]:
        """Validate a JSON-decoded request body against this schema —
        the serving admission check. ``payload`` may be one row (object)
        or a list of rows. Returns human-readable error strings (empty =
        admitted); unknown extra fields are allowed."""
        rows = payload if isinstance(payload, list) else [payload]
        errors: List[str] = []
        for i, row in enumerate(rows):
            where = f"row {i}: " if isinstance(payload, list) else ""
            if not isinstance(row, Mapping):
                errors.append(f"{where}expected a JSON object with fields "
                              f"{self.columns}, got {type(row).__name__}")
            else:
                for name, spec in self._cols.items():
                    if name not in row:
                        sug = nearest_name(name, row)
                        errors.append(
                            f"{where}missing field {name!r} ({spec!r})"
                            + (f" — did you mean {sug!r}?" if sug else ""))
                    elif not spec.accepts_json_value(row[name]):
                        errors.append(
                            f"{where}field {name!r} should be {spec!r}, "
                            f"got {type(row[name]).__name__}")
            if len(errors) >= max_errors:
                errors.append("... (further errors truncated)")
                break
        return errors
