"""Lazy import machinery: PEP 562 package exports + lazy module proxies.

Two invariants of this repo meet here:

- **No jax at import**: ``import synapseml_tpu.<anything>`` must never pull
  in jax (worker processes, scrapers and CLI tools import the package at
  startup; jax initialization is slow and environment-sensitive). Enforced
  by ``tests/test_import_hygiene.py`` (subprocess ground truth) and lint
  rule SMT001 (file:line diagnostics).
- **Cheap subpackage imports**: a package ``__init__`` that eagerly imports
  jax-heavy submodules makes ``import synapseml_tpu.gbdt`` pay for the
  whole trainer even when the caller only wanted one estimator class.
  Lint rule SMT008 flags eager ``__init__`` imports of jax-using
  submodules; the fix is :func:`lazy_module`.

Tools:

- :func:`lazy_module` — PEP 562 exports for a package ``__init__``:
  attribute access imports the owning submodule on demand.
- :func:`lazy_import` — a module proxy for jax-heavy *leaf* modules
  (``jnp = lazy_import("jax.numpy")``): hundreds of call sites keep their
  ``jnp.foo`` spelling while the import happens on first attribute access.
- :func:`load_all` — force-import every lazy submodule of a package.
  Importing a module for its *side effects* (``STAGE_REGISTRY``
  registration) no longer happens implicitly for lazy packages, so code
  that needs it (``serving_worker --import-module``) calls this.
"""

from __future__ import annotations

import importlib
from typing import Callable, Dict, List, Sequence, Tuple

__all__ = ["lazy_module", "lazy_import", "load_all"]


def lazy_module(pkg_name: str, submod_attrs: Dict[str, Sequence[str]]
                ) -> Tuple[Callable, Callable, List[str]]:
    """PEP 562 lazy exports for a package ``__init__``.

    ``submod_attrs`` maps submodule name -> the attributes it provides.
    Returns ``(__getattr__, __dir__, __all__)`` for the caller to bind::

        __getattr__, __dir__, __all__ = lazy_module(__name__, {
            "flash": ["flash_attention", "dense_attention"],
            "ring": ["ring_attention"],
        })

    Unknown attributes fall back to a plain submodule import, so
    ``pkg.submodule`` access works for submodules that export nothing.
    """
    attr_to_mod: Dict[str, str] = {}
    for mod, attrs in submod_attrs.items():
        for a in attrs:
            attr_to_mod[a] = mod
    all_names = sorted(set(attr_to_mod) | set(submod_attrs))

    def __getattr__(name: str):
        owner = attr_to_mod.get(name)
        if owner is not None:
            value = getattr(
                importlib.import_module(f"{pkg_name}.{owner}"), name)
        else:
            try:
                value = importlib.import_module(f"{pkg_name}.{name}")
            except ModuleNotFoundError:
                raise AttributeError(
                    f"module {pkg_name!r} has no attribute {name!r}"
                ) from None
        # cache on the package so later accesses are plain dict lookups
        # (module __getattr__ is only consulted for missing names)
        import sys

        setattr(sys.modules[pkg_name], name, value)
        return value

    def __dir__():
        return list(all_names)

    # marker consumed by load_all(): which submodules this package defers
    __getattr__.lazy_submodules = tuple(sorted(submod_attrs))
    return __getattr__, __dir__, all_names


def load_all(module) -> List[str]:
    """Force-import every deferred submodule of a :func:`lazy_module`
    package (returns their names; [] for eager modules). This restores the
    registration side effects an eager ``__init__`` used to provide — e.g.
    ``PipelineStage`` subclasses entering ``STAGE_REGISTRY`` so
    ``load_stage`` can resolve them by class name."""
    getter = getattr(module, "__getattr__", None)
    subs = list(getattr(getter, "lazy_submodules", ()))
    for sub in subs:
        importlib.import_module(f"{module.__name__}.{sub}")
    return subs


class _LazyModuleProxy:
    """Attribute-forwarding stand-in for a module imported on first use."""

    __slots__ = ("_lazy_name", "_lazy_target")

    def __init__(self, name: str):
        object.__setattr__(self, "_lazy_name", name)
        object.__setattr__(self, "_lazy_target", None)

    def __getattr__(self, attr: str):
        target = object.__getattribute__(self, "_lazy_target")
        if target is None:
            target = importlib.import_module(
                object.__getattribute__(self, "_lazy_name"))
            object.__setattr__(self, "_lazy_target", target)
        return getattr(target, attr)

    def __repr__(self) -> str:
        name = object.__getattribute__(self, "_lazy_name")
        loaded = object.__getattribute__(self, "_lazy_target") is not None
        return f"<lazy module {name!r}{' (loaded)' if loaded else ''}>"


def lazy_import(name: str) -> _LazyModuleProxy:
    """A proxy that imports ``name`` on first attribute access.

    For jax-heavy leaf modules whose *call sites* should keep their
    natural spelling: ``jnp = lazy_import("jax.numpy")`` at module level
    is import-free, and ``jnp.add(...)`` inside a function resolves (and
    caches) the real module at call time. Do NOT touch proxy attributes at
    module level — that resolves the import eagerly and defeats the point
    (lint rule SMT001's subprocess ground truth still catches it).
    """
    return _LazyModuleProxy(name)
