"""Core substrate: params, stages, pipelines, columnar tables, persistence, telemetry."""

from .params import ComplexParam, Param, ParamValidators, Params
from .schema import (ColumnSpec, PipelineSchemaError, SchemaError,
                     TableSchema)
from .stage import (
    Estimator,
    Model,
    Pipeline,
    PipelineModel,
    PipelineStage,
    STAGE_REGISTRY,
    Transformer,
    UnaryTransformer,
    stage_class,
)
from .table import Table, concat_tables, features_matrix
from .serialization import load_stage, register_state_class, save_stage
from .clock import StopWatch, buffered_map
from .fault import retry_with_backoff, retry_with_timeout, using, using_many

__all__ = [
    "Param",
    "ComplexParam",
    "Params",
    "ParamValidators",
    "PipelineStage",
    "Transformer",
    "Estimator",
    "Model",
    "Pipeline",
    "PipelineModel",
    "UnaryTransformer",
    "STAGE_REGISTRY",
    "stage_class",
    "ColumnSpec",
    "TableSchema",
    "SchemaError",
    "PipelineSchemaError",
    "Table",
    "concat_tables",
    "features_matrix",
    "save_stage",
    "load_stage",
    "register_state_class",
    "StopWatch",
    "buffered_map",
    "retry_with_backoff",
    "retry_with_timeout",
    "using",
    "using_many",
]
