"""Pipeline stage abstractions: Transformer / Estimator / Model / Pipeline.

TPU-native rebuild of the SparkML stage contract the reference builds everything on
(``Estimator``/``Transformer``/``PipelineStage``; reference usage e.g.
``LightGBMBase.train`` at ``lightgbm/.../LightGBMBase.scala:43`` and every transformer in
``core/.../stages/``). Differences from the reference, by design:

- stages consume/produce :class:`~synapseml_tpu.core.table.Table` (columnar batches)
  instead of Spark DataFrames;
- there is no lazy query planner: ``transform`` is eager. XLA jit inside stages is the
  "planner" — stages are encouraged to implement vectorized whole-table computation and
  fall back to ``map_partitions`` only for IO / native-engine paths;
- every concrete stage auto-registers in :data:`STAGE_REGISTRY` (the analogue of
  ``JarLoadingUtils.instantiateServices`` classpath reflection,
  ``core/.../core/utils/JarLoadingUtils.scala:44-56``) which powers save/load,
  codegen and the fuzzing meta-test.
"""

from __future__ import annotations

import inspect
from typing import Any, Dict, List, Optional, Sequence

from ..observability.spans import stage_span
from .params import ComplexParam, Param, Params
from .table import Table
from .telemetry import log_stage_call

__all__ = [
    "PipelineStage",
    "Transformer",
    "Estimator",
    "Model",
    "Pipeline",
    "PipelineModel",
    "UnaryTransformer",
    "STAGE_REGISTRY",
    "register_stage",
    "stage_class",
]

# name -> class, for save/load + reflection tests (SURVEY.md §4 FuzzingTest).
STAGE_REGISTRY: Dict[str, type] = {}


def register_stage(cls):
    prev = STAGE_REGISTRY.get(cls.__name__)
    if prev is not None and prev.__module__ != cls.__module__:
        import logging

        logging.getLogger("synapseml_tpu").warning(
            "stage name collision: %s defined in both %s and %s; later wins for load_stage",
            cls.__name__, prev.__module__, cls.__module__,
        )
    STAGE_REGISTRY[cls.__name__] = cls
    return cls


def stage_class(name: str) -> type:
    try:
        return STAGE_REGISTRY[name]
    except KeyError:
        raise KeyError(f"Unknown stage class {name!r}. Registered: {sorted(STAGE_REGISTRY)}") from None


class PipelineStage(Params):
    """Common base: params + uid + save/load.

    Classes that are frameworks bases rather than loadable stages opt out of registry
    registration by declaring ``_abstract_stage = True`` in their own body.
    """

    _abstract_stage = True

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        is_abstract = cls.__dict__.get("_abstract_stage", False) or inspect.isabstract(cls)
        if not is_abstract and not cls.__name__.startswith("_"):
            register_stage(cls)

    # save/load implemented in serialization.py to keep this module dependency-light.
    def save(self, path: str) -> None:
        from .serialization import save_stage

        save_stage(self, path)

    @staticmethod
    def load(path: str) -> "PipelineStage":
        from .serialization import load_stage

        return load_stage(path)

    def _validate_input(self, table: Table, *needed_cols: str) -> None:
        for c in needed_cols:
            if c not in table:
                raise ValueError(
                    f"{type(self).__name__}({self.uid}): input is missing column {c!r}; "
                    f"available: {table.column_names}"
                )


class Transformer(PipelineStage):
    """Maps a Table to a Table (reference: SparkML ``Transformer``)."""

    _abstract_stage = True

    def transform(self, table: Table) -> Table:
        log_stage_call(self, "transform")
        with stage_span(self, "transform") as sp:
            out = self._transform(table)
            sp.set_rows(len(out) if isinstance(out, Table) else None)
        return out

    def _transform(self, table: Table) -> Table:  # pragma: no cover - abstract
        raise NotImplementedError

    def __call__(self, table: Table) -> Table:
        return self.transform(table)


class Estimator(PipelineStage):
    """Fits a Table, producing a :class:`Model` (reference: SparkML ``Estimator``)."""

    _abstract_stage = True

    def fit(self, table: Table) -> "Model":
        log_stage_call(self, "fit")
        with stage_span(self, "fit") as sp:
            sp.set_rows(len(table) if isinstance(table, Table) else None)
            model = self._fit(table)
        model.parent = self
        return model

    def _fit(self, table: Table) -> "Model":  # pragma: no cover - abstract
        raise NotImplementedError


class Model(Transformer):
    """A fitted transformer. ``parent`` points back at the estimator."""

    _abstract_stage = True
    parent: Optional[Estimator] = None


class UnaryTransformer(Transformer):
    """Convenience: input column -> output column transformers."""

    _abstract_stage = True

    input_col = Param("input column name", str, default="input")
    output_col = Param("output column name", str, default="output")

    def _transform(self, table: Table) -> Table:
        self._validate_input(table, self.input_col)
        out = self._transform_column(table.column(self.input_col), table)
        return table.with_column(self.output_col, out)

    def _transform_column(self, col, table: Table):  # pragma: no cover - abstract
        raise NotImplementedError


class Pipeline(Estimator):
    """Sequential composition of stages (reference: SparkML ``Pipeline``).

    ``fit`` threads the table through: estimators are fitted and replaced by their
    models (which then transform the running table); transformers transform directly.
    """

    stages = ComplexParam("list of pipeline stages", list, default=[])

    def __init__(self, stages: Optional[Sequence[PipelineStage]] = None, uid=None, **kw):
        super().__init__(uid=uid, **kw)
        if stages is not None:
            self.set("stages", list(stages))

    def _fit(self, table: Table) -> "PipelineModel":
        stages = list(self.stages)
        fitted: List[Transformer] = []
        cur = table
        for i, st in enumerate(stages):
            is_last = i == len(stages) - 1
            if isinstance(st, Estimator):
                m = st.fit(cur)
                if not is_last:  # skip the (possibly expensive) discarded final transform
                    cur = m.transform(cur)
                fitted.append(m)
            elif isinstance(st, Transformer):
                if not is_last:
                    cur = st.transform(cur)
                fitted.append(st)
            else:
                raise TypeError(f"Pipeline stage {st!r} is neither Estimator nor Transformer")
        return PipelineModel(stages=fitted)


class PipelineModel(Model):
    """Fitted pipeline: applies each fitted stage in order.

    Reference also constructs these directly from stage arrays
    (``NamespaceInjections.pipelineModel``, used at ``CognitiveServiceBase.scala:318``) —
    the constructor here serves the same purpose.
    """

    stages = ComplexParam("list of fitted transformer stages", list, default=[])

    def __init__(self, stages: Optional[Sequence[Transformer]] = None, uid=None, **kw):
        super().__init__(uid=uid, **kw)
        if stages is not None:
            self.set("stages", list(stages))

    def _transform(self, table: Table) -> Table:
        cur = table
        for st in self.stages:
            cur = st.transform(cur)
        return cur
