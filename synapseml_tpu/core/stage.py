"""Pipeline stage abstractions: Transformer / Estimator / Model / Pipeline.

TPU-native rebuild of the SparkML stage contract the reference builds everything on
(``Estimator``/``Transformer``/``PipelineStage``; reference usage e.g.
``LightGBMBase.train`` at ``lightgbm/.../LightGBMBase.scala:43`` and every transformer in
``core/.../stages/``). Differences from the reference, by design:

- stages consume/produce :class:`~synapseml_tpu.core.table.Table` (columnar batches)
  instead of Spark DataFrames;
- there is no lazy query planner: ``transform`` is eager. XLA jit inside stages is the
  "planner" — stages are encouraged to implement vectorized whole-table computation and
  fall back to ``map_partitions`` only for IO / native-engine paths;
- every concrete stage auto-registers in :data:`STAGE_REGISTRY` (the analogue of
  ``JarLoadingUtils.instantiateServices`` classpath reflection,
  ``core/.../core/utils/JarLoadingUtils.scala:44-56``) which powers save/load,
  codegen and the fuzzing meta-test.
"""

from __future__ import annotations

import inspect
from typing import Any, Dict, List, Optional, Sequence

from ..observability.spans import stage_span
from .params import ComplexParam, Param, Params
from .schema import ColumnSpec, PipelineSchemaError, SchemaError, TableSchema
from .table import Table
from .telemetry import log_stage_call

__all__ = [
    "PipelineStage",
    "Transformer",
    "Estimator",
    "Model",
    "Pipeline",
    "PipelineModel",
    "UnaryTransformer",
    "STAGE_REGISTRY",
    "STAGE_NAME_COLLISIONS",
    "register_stage",
    "stage_class",
]

# name -> class, for save/load + reflection tests (SURVEY.md §4 FuzzingTest).
STAGE_REGISTRY: Dict[str, type] = {}

# name -> sorted module list, recorded whenever two modules register the same
# class name. load_stage resolves by NAME, so the later registration shadows
# the earlier one — lint rule SMT009 fails CI on this; the runtime record is
# the introspection hook (and keeps the warning actionable).
STAGE_NAME_COLLISIONS: Dict[str, List[str]] = {}


def register_stage(cls):
    prev = STAGE_REGISTRY.get(cls.__name__)
    if prev is not None and prev.__module__ != cls.__module__:
        import logging

        mods = STAGE_NAME_COLLISIONS.setdefault(
            cls.__name__, [prev.__module__])
        if cls.__module__ not in mods:
            mods.append(cls.__module__)
        logging.getLogger("synapseml_tpu").warning(
            "stage name collision: %s defined in both %s and %s; later wins "
            "for load_stage (lint rule SMT009 fails CI on this)",
            cls.__name__, prev.__module__, cls.__module__,
        )
    STAGE_REGISTRY[cls.__name__] = cls
    return cls


def stage_class(name: str) -> type:
    try:
        return STAGE_REGISTRY[name]
    except KeyError:
        raise KeyError(f"Unknown stage class {name!r}. Registered: {sorted(STAGE_REGISTRY)}") from None


class PipelineStage(Params):
    """Common base: params + uid + save/load.

    Classes that are frameworks bases rather than loadable stages opt out of registry
    registration by declaring ``_abstract_stage = True`` in their own body.
    """

    _abstract_stage = True

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        is_abstract = cls.__dict__.get("_abstract_stage", False) or inspect.isabstract(cls)
        if not is_abstract and not cls.__name__.startswith("_"):
            register_stage(cls)

    # save/load implemented in serialization.py to keep this module dependency-light.
    def save(self, path: str) -> None:
        from .serialization import save_stage

        save_stage(self, path)

    @staticmethod
    def load(path: str) -> "PipelineStage":
        from .serialization import load_stage

        return load_stage(path)

    # -- static schema contract (SparkML transformSchema analogue) ----------

    def input_schema(self) -> Optional[TableSchema]:
        """The minimal input schema this stage's ``transform``/``fit``
        needs, or None when the stage does not declare one. Consumed by
        :meth:`transform_schema`, ``Pipeline.validate`` and the richer
        ``_validate_input`` error messages."""
        return None

    def request_schema(self) -> Optional[TableSchema]:
        """The JSON-BODY contract a serving request must satisfy, or None.

        Distinct from :meth:`input_schema` on purpose: serving engines
        feed pipelines a ``{id, request}`` table (the raw HTTP exchange),
        so a stage that parses request bodies declares its *table* needs
        as ``{request: object:scalar}`` and its *payload fields* here —
        admission (``io/serving_v2.py``) answers 400-with-diff from this
        schema before a request ever occupies a batch slot."""
        return None

    def transform_schema(self, schema: TableSchema) -> Optional[TableSchema]:
        """Statically map an input :class:`TableSchema` to the output
        schema this stage's ``transform`` would produce — no jax, no
        device work, milliseconds (SparkML ``transformSchema``).

        Raises :class:`SchemaError` when ``schema`` cannot feed this
        stage. Returns None when the OUTPUT is undeclared (validation
        degrades to an open schema downstream); the default implementation
        still checks :meth:`input_schema` requirements when declared."""
        ins = self.input_schema()
        if ins is not None:
            self._check_schema(schema, ins)
        return None

    def fit_schema(self, schema: TableSchema) -> Optional[TableSchema]:
        """Static schema of ``fit(table).transform(table)`` — what a
        pipeline position occupied by this estimator contributes. Defaults
        to :meth:`transform_schema` (estimators declare the fitted model's
        mapping there)."""
        return self.transform_schema(schema)

    def _check_schema(self, schema: TableSchema,
                      needed) -> None:
        """``schema.require(needed)`` with this stage's name attached."""
        schema.require(needed, stage=f"{type(self).__name__}({self.uid})")

    def _validate_input(self, table: Table, *needed_cols: str) -> None:
        missing = [c for c in needed_cols if c not in table]
        if not missing:
            return
        from .schema import nearest_name

        parts = []
        for c in missing:
            sug = nearest_name(c, table.column_names)
            parts.append(f"{c!r}" + (f" (did you mean {sug!r}?)" if sug
                                     else ""))
        msg = (f"{type(self).__name__}({self.uid}): input is missing "
               f"column{'s' if len(missing) > 1 else ''} "
               + ", ".join(parts)
               + f"; available: {table.column_names}")
        ins = self.input_schema()
        if ins is not None:
            msg += f"; declared input schema: {ins.describe()}"
        raise ValueError(msg)


class Transformer(PipelineStage):
    """Maps a Table to a Table (reference: SparkML ``Transformer``)."""

    _abstract_stage = True

    def transform(self, table: Table) -> Table:
        log_stage_call(self, "transform")
        with stage_span(self, "transform") as sp:
            out = self._transform(table)
            sp.set_rows(len(out) if isinstance(out, Table) else None)
        return out

    def _transform(self, table: Table) -> Table:  # pragma: no cover - abstract
        raise NotImplementedError

    def __call__(self, table: Table) -> Table:
        return self.transform(table)


class Estimator(PipelineStage):
    """Fits a Table, producing a :class:`Model` (reference: SparkML ``Estimator``)."""

    _abstract_stage = True

    def fit(self, table: Table) -> "Model":
        log_stage_call(self, "fit")
        with stage_span(self, "fit") as sp:
            sp.set_rows(len(table) if isinstance(table, Table) else None)
            model = self._fit(table)
        model.parent = self
        return model

    def _fit(self, table: Table) -> "Model":  # pragma: no cover - abstract
        raise NotImplementedError


class Model(Transformer):
    """A fitted transformer. ``parent`` points back at the estimator."""

    _abstract_stage = True
    parent: Optional[Estimator] = None


class UnaryTransformer(Transformer):
    """Convenience: input column -> output column transformers.

    The schema contract is DERIVED automatically: the input schema is
    ``{input_col: any}`` and ``transform_schema`` adds ``output_col`` with
    :meth:`_output_col_spec`'s spec (default wildcard — subclasses narrow
    it by overriding the method or the ``output_spec`` class attribute)."""

    _abstract_stage = True

    input_col = Param("input column name", str, default="input")
    output_col = Param("output column name", str, default="output")

    # subclasses may pin the produced column's spec ("float:vector", ...)
    output_spec: Any = None

    def input_schema(self) -> Optional[TableSchema]:
        return TableSchema({self.input_col: ColumnSpec()})

    def transform_schema(self, schema: TableSchema) -> Optional[TableSchema]:
        self._check_schema(schema, self.input_schema())
        spec = self._output_col_spec(schema.get(self.input_col))
        return schema.with_column(self.output_col, spec)

    def _output_col_spec(self, input_spec: Optional[ColumnSpec]) -> ColumnSpec:
        if self.output_spec is not None:
            return ColumnSpec.parse(self.output_spec)
        return ColumnSpec()

    def _transform(self, table: Table) -> Table:
        self._validate_input(table, self.input_col)
        out = self._transform_column(table.column(self.input_col), table)
        return table.with_column(self.output_col, out)

    def _transform_column(self, col, table: Table):  # pragma: no cover - abstract
        raise NotImplementedError


def _validate_stage_chain(owner, stages: Sequence[PipelineStage],
                          schema_or_table, fitting: bool) -> TableSchema:
    """Thread a schema through ``stages`` statically (no jax, no device
    work). Raises ONE :class:`PipelineSchemaError` naming the first broken
    stage; stages without a declaration turn the running schema into an
    open schema (downstream checks degrade gracefully instead of false-
    failing). Returns the final (possibly open) output schema."""
    if isinstance(schema_or_table, Table):
        schema = TableSchema.from_table(schema_or_table)
    elif isinstance(schema_or_table, TableSchema):
        schema = schema_or_table
    else:
        schema = TableSchema(schema_or_table)
    for i, st in enumerate(stages):
        mapper = (st.fit_schema if fitting and isinstance(st, Estimator)
                  else st.transform_schema)
        try:
            out = mapper(schema)
        except SchemaError as e:
            raise PipelineSchemaError(
                f"{type(owner).__name__}({owner.uid}) is statically invalid "
                f"at stage {i} ({type(st).__name__}({st.uid})): {e}",
                stage_index=i, stage=st, cause=e) from None
        schema = out if out is not None else TableSchema.open_schema()
    return schema


class Pipeline(Estimator):
    """Sequential composition of stages (reference: SparkML ``Pipeline``).

    ``fit`` threads the table through: estimators are fitted and replaced by their
    models (which then transform the running table); transformers transform directly.

    :meth:`validate` is the plan-time gate (SparkML ``transformSchema``
    threading): a mis-wired pipeline fails in milliseconds with the first
    broken stage named, before any stage burns device time.
    """

    stages = ComplexParam("list of pipeline stages", list, default=[])

    def __init__(self, stages: Optional[Sequence[PipelineStage]] = None, uid=None, **kw):
        super().__init__(uid=uid, **kw)
        if stages is not None:
            self.set("stages", list(stages))

    def input_schema(self) -> Optional[TableSchema]:
        st = list(self.stages)
        return st[0].input_schema() if st else None

    def request_schema(self) -> Optional[TableSchema]:
        st = list(self.stages)
        return st[0].request_schema() if st else None

    def validate(self, schema_or_table) -> TableSchema:
        """Statically thread a :class:`TableSchema` (or a live Table, or a
        plain ``{name: "dtype:role"}`` mapping) through every stage.
        Raises :class:`PipelineSchemaError` naming the first broken stage;
        returns the pipeline's declared output schema."""
        return _validate_stage_chain(self, list(self.stages),
                                     schema_or_table, fitting=True)

    def fit_schema(self, schema: TableSchema) -> Optional[TableSchema]:
        # nested pipelines validate like top-level ones
        return _validate_stage_chain(self, list(self.stages), schema,
                                     fitting=True)

    def _fit(self, table: Table) -> "PipelineModel":
        stages = list(self.stages)
        fitted: List[Transformer] = []
        cur = table
        for i, st in enumerate(stages):
            is_last = i == len(stages) - 1
            if isinstance(st, Estimator):
                m = st.fit(cur)
                if not is_last:  # skip the (possibly expensive) discarded final transform
                    cur = m.transform(cur)
                fitted.append(m)
            elif isinstance(st, Transformer):
                if not is_last:
                    cur = st.transform(cur)
                fitted.append(st)
            else:
                raise TypeError(f"Pipeline stage {st!r} is neither Estimator nor Transformer")
        return PipelineModel(stages=fitted)


class PipelineModel(Model):
    """Fitted pipeline: applies each fitted stage in order.

    Reference also constructs these directly from stage arrays
    (``NamespaceInjections.pipelineModel``, used at ``CognitiveServiceBase.scala:318``) —
    the constructor here serves the same purpose.
    """

    stages = ComplexParam("list of fitted transformer stages", list, default=[])

    def __init__(self, stages: Optional[Sequence[Transformer]] = None, uid=None, **kw):
        super().__init__(uid=uid, **kw)
        if stages is not None:
            self.set("stages", list(stages))

    def input_schema(self) -> Optional[TableSchema]:
        st = list(self.stages)
        return st[0].input_schema() if st else None

    def request_schema(self) -> Optional[TableSchema]:
        st = list(self.stages)
        return st[0].request_schema() if st else None

    def validate(self, schema_or_table) -> TableSchema:
        """Static schema threading over the FITTED stages (see
        ``Pipeline.validate``)."""
        return _validate_stage_chain(self, list(self.stages),
                                     schema_or_table, fitting=False)

    def transform_schema(self, schema: TableSchema) -> Optional[TableSchema]:
        return _validate_stage_chain(self, list(self.stages), schema,
                                     fitting=False)

    def _transform(self, table: Table) -> Table:
        cur = table
        for st in self.stages:
            cur = st.transform(cur)
        return cur
