"""Stage / pipeline persistence.

Rebuild of the reference's ``ComplexParamsWritable/Readable`` machinery
(``core/.../core/serialize/ComplexParamsSerializer.scala`` + the custom param classes
under ``org/apache/spark/ml/param/``): a stage saves to a *directory* containing

- ``metadata.json`` — class name, uid, framework version, all simple (JSON) params;
- one entry per set complex param, dispatched by value type:
  nested stages recurse into subdirectories, numpy arrays become ``.npy``, dicts of
  arrays ``.npz``, bytes ``.bin``; objects exposing the ``state_dict()`` /
  ``from_state_dict()`` protocol (e.g. fitted boosters) get a typed JSON+npz pair.

Round-tripping every stage through save/load is enforced by the serialization fuzzing
meta-test (reference: ``SerializationFuzzing``, ``core/src/test/.../Fuzzing.scala:222``).
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Any, Dict

import numpy as np

from .params import Params
from .telemetry import BUILD_VERSION

__all__ = ["save_stage", "load_stage", "register_state_class", "STATE_REGISTRY"]

# Classes implementing state_dict()/from_state_dict(), keyed by class name.
STATE_REGISTRY: Dict[str, type] = {}


def register_state_class(cls):
    """Class decorator registering a ``state_dict``-protocol type for persistence."""
    STATE_REGISTRY[cls.__name__] = cls
    return cls


def _is_stage(v) -> bool:
    from .stage import PipelineStage

    return isinstance(v, PipelineStage)


def _save_value(value, path: str) -> Dict[str, Any]:
    """Persist one complex value under ``path`` (a directory prefix, no extension).

    Returns a JSON descriptor recorded in metadata so load can dispatch."""
    from .stage import PipelineStage

    if isinstance(value, PipelineStage):
        save_stage(value, path + ".stage")
        return {"kind": "stage"}
    if isinstance(value, np.ndarray) or hasattr(value, "__array__"):
        value = np.asarray(value)  # covers jax.Array — device arrays persist as numpy
        np.save(path + ".npy", value, allow_pickle=value.dtype == object)
        return {"kind": "ndarray", "pickled": bool(value.dtype == object)}
    if isinstance(value, bytes):
        with open(path + ".bin", "wb") as f:
            f.write(value)
        return {"kind": "bytes"}
    if isinstance(value, (list, tuple)) and all(_is_stage(v) for v in value) and value:
        os.makedirs(path + ".stages", exist_ok=True)
        for i, st in enumerate(value):
            save_stage(st, os.path.join(path + ".stages", f"{i:04d}"))
        return {"kind": "stages", "n": len(value), "tuple": isinstance(value, tuple)}
    if type(value).__name__ in STATE_REGISTRY and hasattr(value, "state_dict"):
        state = value.state_dict()

        def _arrayish(v):
            return isinstance(v, np.ndarray) or hasattr(v, "__array__")

        arrays = {k: np.asarray(v) for k, v in state.items() if _arrayish(v)}
        scalars = {k: v for k, v in state.items() if not _arrayish(v)}
        np.savez(path + ".state.npz", **arrays)
        with open(path + ".state.json", "w") as f:
            json.dump({"class": type(value).__name__, "scalars": scalars}, f, default=_jsonable)
        return {"kind": "state"}
    if callable(value):
        # Closures (Lambda/UDFTransformer funcs) are not persistable; record the slot so
        # load yields None and the stage can warn (reference Lambda has the same caveat).
        return {"kind": "callable_dropped"}
    # Last resort: JSON-serializable python structures (lists/dicts of simple values).
    try:
        with open(path + ".json", "w") as f:
            json.dump(value, f, default=_jsonable)
        return {"kind": "json"}
    except TypeError:
        raise TypeError(
            f"Cannot serialize complex param value of type {type(value).__name__} at {path}. "
            f"Implement state_dict()/from_state_dict() and @register_state_class it."
        )


def _load_value(desc: Dict[str, Any], path: str):
    kind = desc["kind"]
    if kind == "stage":
        return load_stage(path + ".stage")
    if kind == "ndarray":
        return np.load(path + ".npy", allow_pickle=desc.get("pickled", False))
    if kind == "bytes":
        with open(path + ".bin", "rb") as f:
            return f.read()
    if kind == "stages":
        out = [
            load_stage(os.path.join(path + ".stages", f"{i:04d}")) for i in range(desc["n"])
        ]
        return tuple(out) if desc.get("tuple") else out
    if kind == "state":
        with open(path + ".state.json") as f:
            head = json.load(f)
        cls = STATE_REGISTRY[head["class"]]
        arrays = dict(np.load(path + ".state.npz", allow_pickle=False))
        return cls.from_state_dict({**head["scalars"], **arrays})
    if kind == "callable_dropped":
        import logging

        logging.getLogger("synapseml_tpu").warning(
            "loaded stage had a callable param at %s; callables don't persist — reset to None",
            path,
        )
        return None
    if kind == "json":
        with open(path + ".json") as f:
            return json.load(f)
    raise ValueError(f"Unknown complex value kind {kind!r}")


from .params import _json_default as _jsonable  # single JSON-coercion rule for the package


def save_stage(stage: Params, path: str) -> None:
    if os.path.exists(path):
        if not os.path.isdir(path):
            raise ValueError(f"save path {path!r} exists and is not a directory")
        # Only clobber directories we wrote (marked by metadata.json) or empty ones —
        # a typo'd path must not silently destroy unrelated files.
        if not (os.path.exists(os.path.join(path, "metadata.json")) or not os.listdir(path)):
            raise ValueError(
                f"save path {path!r} exists and does not look like a saved stage; refusing to overwrite"
            )
    # Write to a sibling temp dir and swap in only on success, so a mid-save failure
    # can't destroy a previously persisted model.
    tmp = path.rstrip("/") + ".saving.tmp"
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    try:
        complex_descs = {}
        for name, value in stage.complex_param_values().items():
            if value is None:
                complex_descs[name] = {"kind": "none"}
                continue
            complex_descs[name] = _save_value(value, os.path.join(tmp, name))
        meta = {
            "class": type(stage).__name__,
            # defining module: lets load_stage self-heal a registry miss
            # (PEP 562 lazy packages no longer register stages on bare
            # package import) by importing the module on demand
            "module": type(stage).__module__,
            "uid": stage.uid,
            "buildVersion": BUILD_VERSION,
            "params": stage.simple_param_values(),
            "complexParams": complex_descs,
        }
        with open(os.path.join(tmp, "metadata.json"), "w") as f:
            json.dump(meta, f, indent=2, sort_keys=True, default=_jsonable)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    if os.path.exists(path):
        shutil.rmtree(path)
    os.replace(tmp, path)


def load_stage(path: str):
    from .stage import stage_class

    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)
    try:
        cls = stage_class(meta["class"])
    except KeyError:
        # registry miss: the stage's package may be PEP 562 lazy (importing
        # it registers nothing until attribute access) — import the saved
        # defining module and retry; re-raise the registry error for old
        # artifacts without a module record
        if not meta.get("module"):
            raise
        import importlib

        importlib.import_module(meta["module"])
        cls = stage_class(meta["class"])
    stage = cls.__new__(cls)
    # Initialize Params plumbing without invoking subclass __init__ conventions.
    object.__setattr__(stage, "_param_values", {})
    stage.uid = meta["uid"]
    for k, v in meta["params"].items():
        param = cls.get_param(k)
        if param.dtype is tuple and isinstance(v, list):
            v = tuple(v)
        stage.set(k, v)
    for name, desc in meta["complexParams"].items():
        if desc["kind"] == "none":
            stage.set(name, None)
        else:
            stage.set(name, _load_value(desc, os.path.join(path, name)))
    if hasattr(stage, "_post_load"):
        stage._post_load()
    return stage
