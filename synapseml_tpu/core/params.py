"""Typed, validated, serializable parameter system.

This is the TPU-native rebuild's equivalent of SparkML ``Params`` as used throughout the
reference (``org.apache.spark.ml.param``; complex params at
``core/src/main/scala/com/microsoft/azure/synapse/ml/core/serialize/ComplexParam.scala``).
Params are *the* config system of the framework (SURVEY.md §5): they power

- typed validated configuration of every pipeline stage,
- JSON (de)serialization of stages and pipelines,
- reflection for binding codegen and the fuzzing meta-tests
  (reference: ``core/.../codegen/Wrappable.scala:68``, ``src/test/.../FuzzingTest.scala``).

Design: plain Python descriptors + an explicit per-class registry built by
``__init_subclass__`` — no metaclass magic, friendly to static analysis.
"""

from __future__ import annotations

import copy
import json
import uuid
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple, Type

__all__ = [
    "Param",
    "ComplexParam",
    "Params",
    "ParamValidators",
    "ParamMap",
]


class ParamValidators:
    """Factory of reusable validators (reference: ``ParamValidators`` in SparkML)."""

    @staticmethod
    def gt(low) -> Callable[[Any], bool]:
        return lambda v: v > low

    @staticmethod
    def gt_eq(low) -> Callable[[Any], bool]:
        return lambda v: v >= low

    @staticmethod
    def lt(high) -> Callable[[Any], bool]:
        return lambda v: v < high

    @staticmethod
    def lt_eq(high) -> Callable[[Any], bool]:
        return lambda v: v <= high

    @staticmethod
    def in_range(low, high, low_inclusive=True, high_inclusive=True) -> Callable[[Any], bool]:
        def check(v):
            ok_low = v >= low if low_inclusive else v > low
            ok_high = v <= high if high_inclusive else v < high
            return ok_low and ok_high

        return check

    @staticmethod
    def in_list(allowed) -> Callable[[Any], bool]:
        allowed = list(allowed)
        return lambda v: v in allowed

    @staticmethod
    def array_length_gt(n) -> Callable[[Any], bool]:
        return lambda v: len(v) > n

    @staticmethod
    def non_empty() -> Callable[[Any], bool]:
        return lambda v: len(v) > 0


class Param:
    """A typed parameter attached to a :class:`Params` class.

    Acts as a descriptor: ``stage.my_param`` returns the current value (set or default),
    ``stage.my_param = v`` validates and sets. ``dtype`` is advisory (used by codegen and
    the fuzzing meta-test to generate values); ``validator`` gates every set.
    """

    # Sentinel distinguishing "no default" from "default is None".
    _NO_DEFAULT = object()

    def __init__(
        self,
        doc: str,
        dtype: type = object,
        default: Any = _NO_DEFAULT,
        validator: Optional[Callable[[Any], bool]] = None,
        *,
        is_complex: bool = False,
    ):
        self.name: str = "<unbound>"
        self.owner: Optional[type] = None
        self.doc = doc
        self.dtype = dtype
        self.default = default
        self.validator = validator
        self.is_complex = is_complex

    @property
    def has_default(self) -> bool:
        return self.default is not Param._NO_DEFAULT

    def __set_name__(self, owner, name):
        self.name = name
        self.owner = owner

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        return obj.get_or_default(self.name)

    def __set__(self, obj, value):
        obj.set(self.name, value)

    def validate(self, value) -> None:
        if self.validator is not None and not self.validator(value):
            raise ValueError(
                f"Param {self.owner.__name__ if self.owner else '?'}.{self.name}: "
                f"value {value!r} failed validation ({self.doc})"
            )

    def __repr__(self):
        return f"Param({self.name}: {self.dtype.__name__}, doc={self.doc!r})"


class ComplexParam(Param):
    """Param holding a non-JSON value (arrays, fitted models, nested stages, callables).

    Reference: ``ComplexParam`` / the 21 custom param classes under
    ``core/src/main/scala/org/apache/spark/ml/param/`` (``ByteArrayParam``,
    ``TransformerParam``, ``EstimatorParam``, ``DataFrameParam``, ``UDFParam``,
    ``BallTreeParam``, ...). Serialized out-of-band by ``serialization.py`` rather than
    into the stage's JSON metadata.
    """

    def __init__(self, doc: str, dtype: type = object, default: Any = Param._NO_DEFAULT,
                 validator: Optional[Callable[[Any], bool]] = None):
        super().__init__(doc, dtype=dtype, default=default, validator=validator, is_complex=True)


ParamMap = Dict[str, Any]


class Params:
    """Base class for anything carrying :class:`Param` descriptors.

    Subclasses declare params as class attributes; ``__init_subclass__`` aggregates them
    (including inherited ones) into ``cls._params``. Constructor accepts ``**kwargs``
    addressing params by name, mirroring the generated-python-wrapper ergonomics of the
    reference (``codegen/Wrappable.scala:93``).
    """

    _params: Dict[str, Param] = {}

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        merged: Dict[str, Param] = {}
        for base in reversed(cls.__mro__):
            for k, v in vars(base).items():
                if isinstance(v, Param):
                    merged[k] = v
        cls._params = merged

    def __init__(self, uid: Optional[str] = None, **kwargs):
        # _param_values must exist before any set().
        object.__setattr__(self, "_param_values", {})
        self.uid = uid or f"{type(self).__name__}_{uuid.uuid4().hex[:12]}"
        self.set_params(**kwargs)

    # -- declaration / reflection ------------------------------------------------

    @classmethod
    def params(cls) -> Dict[str, Param]:
        return dict(cls._params)

    @classmethod
    def get_param(cls, name: str) -> Param:
        try:
            return cls._params[name]
        except KeyError:
            raise KeyError(f"{cls.__name__} has no param {name!r}") from None

    def has_param(self, name: str) -> bool:
        return name in self._params

    # -- get / set ---------------------------------------------------------------

    def set(self, name: str, value: Any) -> "Params":
        p = self.get_param(name)
        if value is not None:
            p.validate(value)
        self._param_values[name] = value
        return self

    def set_params(self, **kwargs) -> "Params":
        for k, v in kwargs.items():
            self.set(k, v)
        return self

    def get(self, name: str) -> Any:
        self.get_param(name)
        return self._param_values[name]

    def get_or_default(self, name: str) -> Any:
        p = self.get_param(name)
        if name in self._param_values:
            return self._param_values[name]
        if p.has_default:
            # Copy mutable defaults so stages can't alias each other's lists/dicts.
            d = p.default
            return copy.copy(d) if isinstance(d, (list, dict, set)) else d
        raise KeyError(f"Param {type(self).__name__}.{name} is not set and has no default")

    def is_set(self, name: str) -> bool:
        return name in self._param_values

    def is_defined(self, name: str) -> bool:
        return self.is_set(name) or self.get_param(name).has_default

    def clear(self, name: str) -> "Params":
        self._param_values.pop(name, None)
        return self

    # -- introspection -----------------------------------------------------------

    def extract_param_map(self) -> ParamMap:
        """All defined (set or defaulted) param values."""
        out: ParamMap = {}
        for name, p in self._params.items():
            if self.is_defined(name):
                out[name] = self.get_or_default(name)
        return out

    def explain_params(self) -> str:
        lines = []
        for name, p in sorted(self._params.items()):
            cur = repr(self.get_or_default(name)) if self.is_defined(name) else "undefined"
            lines.append(f"{name}: {p.doc} (current: {cur})")
        return "\n".join(lines)

    def copy(self, extra: Optional[ParamMap] = None) -> "Params":
        """Deep-ish copy: param values are shallow-copied, complex values shared."""
        other = copy.copy(self)
        object.__setattr__(other, "_param_values", dict(self._param_values))
        if extra:
            other.set_params(**extra)
        return other

    # -- (de)serialization of simple params --------------------------------------

    def simple_param_values(self) -> ParamMap:
        return {
            k: v for k, v in self._param_values.items() if not self._params[k].is_complex
        }

    def complex_param_values(self) -> ParamMap:
        return {k: v for k, v in self._param_values.items() if self._params[k].is_complex}

    def params_to_json(self) -> str:
        return json.dumps(self.simple_param_values(), sort_keys=True, default=_json_default)

    def __repr__(self):
        vals = ", ".join(f"{k}={v!r}" for k, v in sorted(self.simple_param_values().items()))
        return f"{type(self).__name__}(uid={self.uid}, {vals})"


def _json_default(o):
    # numpy scalars sneak into params frequently; coerce them.
    try:
        import numpy as np

        if isinstance(o, np.generic):
            return o.item()
        if isinstance(o, np.ndarray):
            return o.tolist()
    except ImportError:  # pragma: no cover
        pass
    if isinstance(o, tuple):
        return list(o)
    raise TypeError(f"Not JSON serializable: {type(o)}")
