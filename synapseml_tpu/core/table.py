"""Columnar, partitioned dataset substrate — the framework's DataFrame equivalent.

The reference operates on Spark DataFrames (row-oriented, partitioned, lazily planned).
A TPU-native framework wants *columnar, fixed-shape, batch-oriented* data so that stages
hand XLA large dense arrays instead of row streams (SURVEY.md §7 "Design stance": Arrow-
backed columnar batches). ``Table`` is that substrate:

- columns are numpy arrays: 1-D for scalars, N-D for fixed-shape tensor columns
  (vectors/images), ``object`` dtype for strings / ragged values;
- a table carries a logical partition count (``npartitions``); partition *i* is a
  contiguous row range. A "task" in the reference (one Spark partition) maps to one
  partition here — estimator/transformer code that is partition-parallel iterates
  ``partitions()`` (reference analogue: ``df.rdd.mapPartitions``);
- ``map_partitions`` is the execution primitive, mirroring the reference's ubiquitous
  ``mapPartitions`` (e.g. ``ONNXModel.scala:499-508``, ``VowpalWabbitBase.scala:337``).

Interop: ``from_pandas``/``to_pandas``, ``from_arrow``/``to_arrow`` when pyarrow is
available.
"""

from __future__ import annotations

import numpy as np
from typing import Any, Callable, Dict, Iterator, List, Mapping, Optional, Sequence, Tuple

__all__ = ["Table", "concat_tables"]


def _as_column(v) -> np.ndarray:
    if isinstance(v, np.ndarray):
        return v
    if hasattr(v, "__array__") and not isinstance(v, (list, tuple)):
        return np.asarray(v)
    arr = None
    if isinstance(v, (list, tuple)):
        first = v[0] if len(v) else None
        if isinstance(first, str) or first is None or isinstance(first, (dict, bytes)):
            arr = np.empty(len(v), dtype=object)
            arr[:] = v
        elif isinstance(first, (list, tuple, np.ndarray)):
            # Try to stack into a fixed-shape tensor column; fall back to ragged object.
            try:
                arr = np.asarray(v)
                if arr.dtype == object:
                    raise ValueError
            except ValueError:
                arr = np.empty(len(v), dtype=object)
                for i, x in enumerate(v):
                    arr[i] = np.asarray(x)
        else:
            arr = np.asarray(v)
    else:
        arr = np.asarray(v)
    return arr


class Table:
    """Immutable columnar table with logical partitioning."""

    def __init__(
        self,
        columns: Mapping[str, Any],
        npartitions: int = 1,
        meta: Optional[Dict[str, Dict[str, Any]]] = None,
    ):
        cols: Dict[str, np.ndarray] = {}
        n = None
        for k, v in columns.items():
            arr = _as_column(v)
            if n is None:
                n = len(arr)
            elif len(arr) != n:
                raise ValueError(
                    f"Column {k!r} has length {len(arr)}, expected {n}"
                )
            cols[k] = arr
        self._columns = cols
        self._num_rows = 0 if n is None else int(n)
        self.npartitions = max(1, min(int(npartitions), max(1, self._num_rows)))
        # Per-column metadata (semantic types: 'image', 'vector', ... + arbitrary keys).
        self.meta: Dict[str, Dict[str, Any]] = dict(meta or {})

    # -- construction ------------------------------------------------------------

    @staticmethod
    def from_pandas(df, npartitions: int = 1) -> "Table":
        cols = {}
        for c in df.columns:
            s = df[c]
            if s.dtype == object:
                arr = np.empty(len(s), dtype=object)
                arr[:] = list(s)
            else:
                arr = s.to_numpy()
            cols[str(c)] = arr
        return Table(cols, npartitions=npartitions)

    @staticmethod
    def from_rows(rows: Sequence[Mapping[str, Any]], npartitions: int = 1) -> "Table":
        if not rows:
            return Table({}, npartitions=npartitions)
        keys = list(rows[0].keys())
        return Table({k: [r[k] for r in rows] for k in keys}, npartitions=npartitions)

    def to_pandas(self):
        import pandas as pd

        data = {}
        for k, v in self._columns.items():
            if v.ndim > 1:
                col = np.empty(len(v), dtype=object)
                for i in range(len(v)):
                    col[i] = v[i]
                data[k] = col
            else:
                data[k] = v
        return pd.DataFrame(data)

    @staticmethod
    def from_arrow(tbl, npartitions: int = 1) -> "Table":
        return Table.from_pandas(tbl.to_pandas(), npartitions=npartitions)

    def to_arrow(self):
        import pyarrow as pa

        return pa.Table.from_pandas(self.to_pandas())

    # -- basic accessors ---------------------------------------------------------

    @property
    def num_rows(self) -> int:
        return self._num_rows

    def __len__(self) -> int:
        return self._num_rows

    @property
    def column_names(self) -> List[str]:
        return list(self._columns.keys())

    def __contains__(self, name: str) -> bool:
        return name in self._columns

    def column(self, name: str) -> np.ndarray:
        try:
            return self._columns[name]
        except KeyError:
            raise KeyError(
                f"No column {name!r}; available: {self.column_names}"
            ) from None

    __getitem__ = column

    def schema(self) -> Dict[str, str]:
        out = {}
        for k, v in self._columns.items():
            sem = self.meta.get(k, {}).get("type")
            if sem:
                out[k] = sem
            elif v.dtype == object:
                out[k] = "object"
            elif v.ndim > 1:
                out[k] = f"tensor{list(v.shape[1:])}:{v.dtype.name}"
            else:
                out[k] = v.dtype.name
        return out

    def row(self, i: int) -> Dict[str, Any]:
        return {k: v[i] for k, v in self._columns.items()}

    def rows(self) -> Iterator[Dict[str, Any]]:
        for i in range(self._num_rows):
            yield self.row(i)

    # -- column ops (all return new Tables) --------------------------------------

    def _like(self, columns: Mapping[str, Any], meta: Optional[Dict] = None) -> "Table":
        return Table(columns, npartitions=self.npartitions,
                     meta=meta if meta is not None else self.meta)

    def select(self, *names: str) -> "Table":
        return self._like({n: self.column(n) for n in names},
                          meta={k: v for k, v in self.meta.items() if k in names})

    def drop(self, *names: str) -> "Table":
        keep = [c for c in self.column_names if c not in names]
        return self.select(*keep)

    def with_column(self, name: str, values, meta: Optional[Dict[str, Any]] = None) -> "Table":
        cols = dict(self._columns)
        cols[name] = values
        m = dict(self.meta)
        if meta is not None:
            m[name] = meta
        t = self._like(cols, meta=m)
        return t

    def with_columns(self, new: Mapping[str, Any]) -> "Table":
        cols = dict(self._columns)
        cols.update(new)
        return self._like(cols)

    def rename(self, mapping: Mapping[str, str]) -> "Table":
        cols = {mapping.get(k, k): v for k, v in self._columns.items()}
        meta = {mapping.get(k, k): v for k, v in self.meta.items()}
        return self._like(cols, meta=meta)

    # -- row ops -----------------------------------------------------------------

    def slice(self, start: int, stop: int) -> "Table":
        cols = {k: v[start:stop] for k, v in self._columns.items()}
        return Table(cols, npartitions=1, meta=self.meta)

    def take(self, indices) -> "Table":
        idx = np.asarray(indices)
        cols = {k: v[idx] for k, v in self._columns.items()}
        return Table(cols, npartitions=self.npartitions, meta=self.meta)

    # -- fluent API (reference ``core/.../core/spark/FluentAPI.scala:14-20``:
    # ``df.mlTransform(stage, ...)`` / ``df.mlFit(estimator)``) ------------------

    def ml_transform(self, *stages) -> "Table":
        """Apply one or more transformers in sequence."""
        out = self
        for st in stages:
            out = st.transform(out)
        return out

    def ml_fit(self, estimator):
        """Fit an estimator on this table, returning its model."""
        return estimator.fit(self)

    def filter(self, mask) -> "Table":
        mask = np.asarray(mask, dtype=bool)
        return self.take(np.nonzero(mask)[0])

    def sample(self, frac: float, seed: int = 0, replace: bool = False) -> "Table":
        rng = np.random.default_rng(seed)
        k = int(round(frac * self._num_rows))
        idx = rng.choice(self._num_rows, size=k, replace=replace)
        return self.take(np.sort(idx))

    def shuffle(self, seed: int = 0) -> "Table":
        rng = np.random.default_rng(seed)
        return self.take(rng.permutation(self._num_rows))

    def random_split(self, fractions: Sequence[float], seed: int = 0) -> List["Table"]:
        """Reference analogue: ``df.randomSplit`` (used by TrainValidationSplit etc.)."""
        fracs = np.asarray(fractions, dtype=float)
        fracs = fracs / fracs.sum()
        rng = np.random.default_rng(seed)
        perm = rng.permutation(self._num_rows)
        bounds = np.floor(np.cumsum(fracs) * self._num_rows).astype(int)
        bounds[-1] = self._num_rows  # cumsum can float to <1.0; never drop tail rows
        out, start = [], 0
        for b in bounds:
            out.append(self.take(np.sort(perm[start:b])))
            start = b
        return out

    # -- partitioning ------------------------------------------------------------

    def repartition(self, n: int) -> "Table":
        t = Table(self._columns, npartitions=n, meta=self.meta)
        return t

    def partition_bounds(self) -> List[Tuple[int, int]]:
        """Even contiguous split of rows into ``npartitions`` ranges."""
        n, p = self._num_rows, self.npartitions
        cuts = [round(i * n / p) for i in range(p + 1)]
        return [(cuts[i], cuts[i + 1]) for i in range(p)]

    def partitions(self) -> Iterator["Table"]:
        for lo, hi in self.partition_bounds():
            yield self.slice(lo, hi)

    def map_partitions(
        self,
        fn: Callable[["Table", int], "Table"],
        parallel: bool = False,
        max_workers: Optional[int] = None,
    ) -> "Table":
        """Apply ``fn(partition_table, partition_index) -> Table`` per partition and
        concatenate results, preserving partition count. The reference's
        ``mapPartitions``; ``parallel=True`` runs partitions on a thread pool (native /
        IO-bound stages release the GIL; XLA stages should instead batch whole-table).
        """
        parts = list(self.partitions())
        if parallel and len(parts) > 1:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(max_workers=max_workers or len(parts)) as ex:
                results = list(ex.map(lambda t: fn(t[1], t[0]), enumerate(parts)))
        else:
            results = [fn(p, i) for i, p in enumerate(parts)]
        out = concat_tables(results)
        return Table(out._columns, npartitions=self.npartitions, meta={**self.meta, **out.meta})

    # -- misc --------------------------------------------------------------------

    def cache(self) -> "Table":
        return self  # eager substrate: no-op, kept for API parity (``Cacher`` stage)

    def __repr__(self):
        schema = ", ".join(f"{k}: {t}" for k, t in self.schema().items())
        return f"Table[{self._num_rows} rows x {len(self._columns)} cols, {self.npartitions} parts]({schema})"


def jsonable_value(v):
    """Coerce a table cell to a plain-JSON value (shared by the PowerBI and
    AzureSearch writers and any row-to-JSON path)."""
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray):
        return v.tolist()
    return v


def features_matrix(col: np.ndarray, dtype=np.float64) -> np.ndarray:
    """Coerce a features column (dense 2-D or object array of vectors) to an
    (n, d) float matrix — the one shared conversion every vector-consuming
    stage uses (GBDT/KNN/isolation forest/...)."""
    if col.dtype == object:
        return np.stack([np.asarray(v, dtype=dtype) for v in col])
    return np.asarray(col, dtype=dtype)


def concat_tables(tables: Sequence[Table]) -> Table:
    tables = [t for t in tables if t.num_rows > 0 or t.column_names]
    if not tables:
        return Table({})
    names = tables[0].column_names
    for i, t in enumerate(tables[1:], 1):
        if set(t.column_names) != set(names):
            raise ValueError(
                f"concat_tables: table {i} columns {sorted(t.column_names)} != "
                f"table 0 columns {sorted(names)}"
            )
    cols = {}
    for n in names:
        parts = [t.column(n) for t in tables]
        if any(p.dtype == object for p in parts):
            total = sum(len(p) for p in parts)
            arr = np.empty(total, dtype=object)
            i = 0
            for p in parts:
                arr[i : i + len(p)] = p
                i += len(p)
            cols[n] = arr
        else:
            cols[n] = np.concatenate(parts, axis=0)
    meta = {}
    for t in tables:
        meta.update(t.meta)
    return Table(cols, npartitions=max(t.npartitions for t in tables), meta=meta)
