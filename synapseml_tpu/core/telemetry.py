"""Structured per-stage telemetry.

Rebuild of the reference's ``BasicLogging`` trait
(``core/.../logging/BasicLogging.scala:26-71``): every stage method call emits one
structured JSON event ``{uid, className, method, buildVersion}`` so hosts can count
feature usage. Here events go to the ``synapseml_tpu.telemetry`` logger at DEBUG and to
an in-process ring buffer that tests/tools can inspect (``recent_events``).
"""

from __future__ import annotations

import collections
import json
import logging
import threading
import time
from typing import Any, Dict, List

__all__ = ["log_stage_call", "recent_events", "clear_events", "get_logger",
           "profile_trace", "BUILD_VERSION"]

BUILD_VERSION = "0.1.0"


def get_logger(name: str) -> logging.Logger:
    """Namespaced framework logger (``synapseml_tpu.<name>``)."""
    return logging.getLogger(f"synapseml_tpu.{name}")

_logger = logging.getLogger("synapseml_tpu.telemetry")
_events: "collections.deque[Dict[str, Any]]" = collections.deque(maxlen=4096)
_lock = threading.Lock()


def log_stage_call(stage, method: str, **extra) -> None:
    evt = {
        "uid": getattr(stage, "uid", "?"),
        "className": type(stage).__name__,
        "method": method,
        "buildVersion": BUILD_VERSION,
        "ts": time.time(),
        **extra,
    }
    with _lock:
        _events.append(evt)
    if _logger.isEnabledFor(logging.DEBUG):
        _logger.debug("metrics/ %s", json.dumps(evt, default=str))


def profile_trace(trace_dir: str):
    """Context manager capturing a ``jax.profiler`` trace into ``trace_dir``
    (SURVEY §5 prescription: the analogue of the reference's StopWatch/VW
    phase-timing diagnostics, but at XLA-op granularity — open the result
    with TensorBoard or ``tensorboard_plugin_profile``).

    The device trace shows per-HLO time, fusion boundaries, and HBM traffic
    — the data the engine's perf plateaus get debugged with. A telemetry
    event records the capture so traces are discoverable after the fact.

    >>> from synapseml_tpu.core.telemetry import profile_trace
    >>> with profile_trace("/tmp/trace"):   # doctest: +SKIP
    ...     model.transform(table)
    """
    import contextlib

    @contextlib.contextmanager
    def _ctx():
        import jax

        evt = {"method": "profile_trace", "trace_dir": trace_dir,
               "className": "profiler", "uid": "profiler",
               "buildVersion": BUILD_VERSION, "ts": time.time()}
        with _lock:
            _events.append(evt)
        with jax.profiler.trace(trace_dir):
            yield trace_dir

    return _ctx()


def recent_events() -> List[Dict[str, Any]]:
    with _lock:
        return list(_events)


def clear_events() -> None:
    with _lock:
        _events.clear()
