"""Structured per-stage telemetry.

Rebuild of the reference's ``BasicLogging`` trait
(``core/.../logging/BasicLogging.scala:26-71``): every stage method call emits one
structured JSON event ``{uid, className, method, buildVersion}`` so hosts can count
feature usage. Here events go to the ``synapseml_tpu.telemetry`` logger at DEBUG and to
an in-process ring buffer that tests/tools can inspect (``recent_events``).
"""

from __future__ import annotations

import collections
import json
import logging
import threading
import time
from typing import Any, Dict, List

__all__ = ["log_stage_call", "recent_events", "clear_events", "get_logger",
           "BUILD_VERSION"]

BUILD_VERSION = "0.1.0"


def get_logger(name: str) -> logging.Logger:
    """Namespaced framework logger (``synapseml_tpu.<name>``)."""
    return logging.getLogger(f"synapseml_tpu.{name}")

_logger = logging.getLogger("synapseml_tpu.telemetry")
_events: "collections.deque[Dict[str, Any]]" = collections.deque(maxlen=4096)
_lock = threading.Lock()


def log_stage_call(stage, method: str, **extra) -> None:
    evt = {
        "uid": getattr(stage, "uid", "?"),
        "className": type(stage).__name__,
        "method": method,
        "buildVersion": BUILD_VERSION,
        "ts": time.time(),
        **extra,
    }
    with _lock:
        _events.append(evt)
    if _logger.isEnabledFor(logging.DEBUG):
        _logger.debug("metrics/ %s", json.dumps(evt, default=str))


def recent_events() -> List[Dict[str, Any]]:
    with _lock:
        return list(_events)


def clear_events() -> None:
    with _lock:
        _events.clear()
