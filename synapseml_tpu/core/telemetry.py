"""Structured per-stage telemetry.

Rebuild of the reference's ``BasicLogging`` trait
(``core/.../logging/BasicLogging.scala:26-71``): every stage method call emits one
structured JSON event ``{uid, className, method, buildVersion}`` so hosts can count
feature usage. Here events go to the ``synapseml_tpu.telemetry`` logger at DEBUG and to
an in-process ring buffer that tests/tools can inspect (``recent_events``).
"""

from __future__ import annotations

import collections
import json
import logging
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = ["log_stage_call", "log_event", "recent_events", "clear_events",
           "drain_events", "get_logger", "set_event_capacity",
           "event_capacity", "profile_trace", "BUILD_VERSION"]

BUILD_VERSION = "0.1.0"


def get_logger(name: str) -> logging.Logger:
    """Namespaced framework logger (``synapseml_tpu.<name>``)."""
    return logging.getLogger(f"synapseml_tpu.{name}")

_logger = logging.getLogger("synapseml_tpu.telemetry")
_DEFAULT_CAPACITY = 4096
_events: "collections.deque[Dict[str, Any]]" = \
    collections.deque(maxlen=_DEFAULT_CAPACITY)
_lock = threading.Lock()


def set_event_capacity(n: int) -> None:
    """Resize the event ring buffer (keeps the newest events). Long-running
    serving hosts tune this instead of living with the old hardcoded 4096."""
    if n < 1:
        raise ValueError(f"event capacity must be >= 1, got {n}")
    global _events
    with _lock:
        _events = collections.deque(_events, maxlen=n)


def event_capacity() -> int:
    with _lock:
        return _events.maxlen


def _active_trace_id() -> Optional[str]:
    """Trace id of the active request trace, or None. Looked up through
    ``sys.modules`` rather than imported: core must not depend on the
    observability package, and if tracing was never imported there cannot
    be an active trace to report."""
    tr = sys.modules.get("synapseml_tpu.observability.tracing")
    if tr is None:
        return None
    try:
        return tr.current_trace_id() if tr.is_enabled() else None
    except Exception:
        return None


def log_stage_call(stage, method: str, **extra) -> None:
    """Record one structured stage-call event.

    ``ts`` is wall-clock (for cross-host correlation); any DURATION passed
    in ``extra`` must be measured with the monotonic clock
    (``core.clock.StopWatch``) — wall-clock deltas jump under NTP slew.
    Aggregate timings live in ``synapseml_tpu.observability`` spans; this
    event stream is the per-call view. Events emitted while a request
    trace is active carry its ``trace_id`` so the per-call view joins
    against ``/traces``.
    """
    log_event(method, className=type(stage).__name__,
              uid=getattr(stage, "uid", "?"), **extra)


def log_event(method: str, className: str = "event", uid: str = "?",
              **extra) -> None:
    """Record one structured event with the same schema as stage-call
    events — the hook for non-stage emitters (XLA compile accounting in
    ``observability.profiling``, profiler captures). ``pid`` is stamped
    live so multi-process event streams (a ``ProcessServingFleet``)
    stay attributable after they are pooled into one timeline."""
    evt = {
        "uid": uid,
        "className": className,
        "method": method,
        "buildVersion": BUILD_VERSION,
        "ts": time.time(),
        "pid": os.getpid(),
        **extra,
    }
    tid = _active_trace_id()
    if tid is not None:
        evt.setdefault("trace_id", tid)
    with _lock:
        _events.append(evt)
    if _logger.isEnabledFor(logging.DEBUG):
        _logger.debug("metrics/ %s", json.dumps(evt, default=str))


def profile_trace(trace_dir: str):
    """Context manager capturing a ``jax.profiler`` trace into ``trace_dir``
    (SURVEY §5 prescription: the analogue of the reference's StopWatch/VW
    phase-timing diagnostics, but at XLA-op granularity — open the result
    with TensorBoard or ``tensorboard_plugin_profile``).

    The device trace shows per-HLO time, fusion boundaries, and HBM traffic
    — the data the engine's perf plateaus get debugged with. A telemetry
    event records the capture so traces are discoverable after the fact;
    when a request trace is active (a traced serving path triggered the
    capture), the event AND a ``profile_trace`` span carry its trace id, so
    the XLA capture is discoverable straight from the ``/traces`` entry of
    the request that paid for it.

    >>> from synapseml_tpu.core.telemetry import profile_trace
    >>> with profile_trace("/tmp/trace"):   # doctest: +SKIP
    ...     model.transform(table)
    """
    import contextlib

    @contextlib.contextmanager
    def _ctx():
        import jax

        from .clock import StopWatch

        evt = {"method": "profile_trace", "trace_dir": trace_dir,
               "className": "profiler", "uid": "profiler",
               "buildVersion": BUILD_VERSION, "ts": time.time(),
               "pid": os.getpid()}
        tid = _active_trace_id()
        if tid is not None:
            evt["trace_id"] = tid
        with _lock:
            _events.append(evt)
        # duration via the MONOTONIC clock (wall-clock deltas jump under NTP
        # slew); ts above stays wall-clock for cross-host correlation
        sw = StopWatch()
        try:
            with sw.measure(), jax.profiler.trace(trace_dir):
                yield trace_dir
        finally:
            evt["duration_s"] = sw.elapsed_s
            if tid is not None:
                tr = sys.modules.get("synapseml_tpu.observability.tracing")
                if tr is not None:
                    try:
                        tr.get_tracer().record(
                            "profile_trace", duration_s=sw.elapsed_s,
                            attributes={"trace_dir": trace_dir})
                    except Exception:
                        pass  # tracing must never break a capture

    return _ctx()


def recent_events() -> List[Dict[str, Any]]:
    with _lock:
        return list(_events)


def drain_events() -> List[Dict[str, Any]]:
    """Atomic snapshot-and-clear: no event is ever seen twice or dropped
    between a ``recent_events()`` and a ``clear_events()`` racing with a
    concurrent ``log_stage_call``."""
    with _lock:
        out = list(_events)
        _events.clear()
    return out


def clear_events() -> None:
    with _lock:
        _events.clear()
