"""Timing utilities: StopWatch + async bounded-concurrency helpers.

Rebuild of ``core/.../core/utils/StopWatch.scala`` (phase timing used by VW training
diagnostics, ``VowpalWabbitBase.scala:292-327``) and ``AsyncUtils.bufferedAwait``
(``core/.../core/utils/AsyncUtils.scala`` — the backbone of the async HTTP client).
"""

from __future__ import annotations

import concurrent.futures
import time
from typing import Any, Callable, Iterable, Iterator, List, Optional, TypeVar

__all__ = ["StopWatch", "buffered_map"]

T = TypeVar("T")
R = TypeVar("R")


class StopWatch:
    """Accumulating nanosecond stopwatch usable as a context manager.

    >>> sw = StopWatch()
    >>> with sw.measure():
    ...     pass
    >>> sw.elapsed_ns >= 0
    True
    """

    def __init__(self):
        self.elapsed_ns = 0
        self._start: Optional[int] = None

    def start(self) -> None:
        self._start = time.perf_counter_ns()

    def stop(self) -> None:
        if self._start is None:
            raise RuntimeError("StopWatch not started")
        self.elapsed_ns += time.perf_counter_ns() - self._start
        self._start = None

    def restart(self) -> None:
        self.elapsed_ns = 0
        self.start()

    def measure(self):
        sw = self

        class _Ctx:
            def __enter__(self):
                sw.start()
                return sw

            def __exit__(self, *exc):
                sw.stop()
                return False

        return _Ctx()

    @property
    def elapsed_s(self) -> float:
        return self.elapsed_ns / 1e9


def buffered_map(
    fn: Callable[[T], R],
    items: Iterable[T],
    concurrency: int = 8,
    timeout_s: Optional[float] = None,
) -> Iterator[R]:
    """Apply ``fn`` over ``items`` with at most ``concurrency`` in flight, yielding
    results *in input order* as they complete (``AsyncUtils.bufferedAwait``).

    Unlike ``ThreadPoolExecutor.map``, submission is throttled: at most ``concurrency``
    futures exist at once, so an unbounded input stream doesn't queue unboundedly.
    """
    import collections

    it = iter(items)
    pending: "collections.deque[concurrent.futures.Future]" = collections.deque()
    with concurrent.futures.ThreadPoolExecutor(max_workers=concurrency) as ex:
        try:
            while True:
                while len(pending) < concurrency:
                    try:
                        pending.append(ex.submit(fn, next(it)))
                    except StopIteration:
                        break
                if not pending:
                    break
                yield pending.popleft().result(timeout=timeout_s)
        finally:
            for f in pending:
                f.cancel()
