"""Fault-tolerance utilities: retries, timeouts, RAII.

Rebuild of the reference's scattered resilience helpers (SURVEY.md §5):
``FaultToleranceUtils.retryWithTimeout`` (``core/.../core/utils/FaultToleranceUtils.scala:10-22``),
the exponential-backoff loop around native network init (``TrainUtils.scala:280-296``),
and ``StreamUtilities.using/usingMany`` (``core/.../core/env/StreamUtilities.scala``).
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time
from typing import Any, Callable, Iterable, Optional, Sequence, Tuple, Type

__all__ = ["retry_with_timeout", "retry_with_backoff", "using", "using_many", "run_with_timeout"]

_logger = logging.getLogger("synapseml_tpu.fault")


def run_with_timeout(fn: Callable[[], Any], timeout_s: float) -> Any:
    """Run ``fn`` on a daemon thread, raising TimeoutError after ``timeout_s``.

    On timeout the worker thread is truly abandoned (daemon=True, never joined) — a hung
    ``fn`` neither blocks the caller past the deadline nor prevents interpreter exit.
    """
    box: dict = {}
    done = threading.Event()

    def worker():
        try:
            box["value"] = fn()
        except BaseException as e:  # noqa: BLE001 - re-raised in caller
            box["error"] = e
        finally:
            done.set()

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    if not done.wait(timeout_s):
        raise TimeoutError(f"timed out after {timeout_s}s")
    if "error" in box:
        raise box["error"]
    return box["value"]


def retry_with_timeout(fn: Callable[[], Any], times: int = 3, timeout_s: float = 60.0) -> Any:
    """Retry ``fn`` up to ``times`` attempts, each bounded by ``timeout_s``."""
    times = max(1, times)  # always run at least once
    last: Optional[BaseException] = None
    for attempt in range(times):
        try:
            return run_with_timeout(fn, timeout_s)
        except Exception as e:  # noqa: BLE001 - deliberate catch-all retry
            last = e
            _logger.warning("attempt %d/%d failed: %s", attempt + 1, times, e)
    raise last  # type: ignore[misc]


def retry_with_backoff(
    fn: Callable[[], Any],
    retries: int = 5,
    initial_delay_s: float = 0.1,
    max_delay_s: float = 10.0,
    backoff: float = 2.0,
    retry_on: Tuple[Type[BaseException], ...] = (Exception,),
    sleep: Callable[[float], None] = time.sleep,
) -> Any:
    """Exponential-backoff retry (reference: LightGBM ``networkInit`` backoff loop)."""
    retries = max(1, retries)  # always run at least once
    delay = initial_delay_s
    last: Optional[BaseException] = None
    for attempt in range(retries):
        try:
            return fn()
        except retry_on as e:
            last = e
            if attempt == retries - 1:
                break
            _logger.warning("retrying after %.2fs (attempt %d/%d): %s", delay, attempt + 1, retries, e)
            sleep(delay)
            delay = min(delay * backoff, max_delay_s)
    raise last  # type: ignore[misc]


@contextlib.contextmanager
def using(resource):
    """RAII helper: closes the resource on exit (``StreamUtilities.using``)."""
    try:
        yield resource
    finally:
        close = getattr(resource, "close", None)
        if close is not None:
            with contextlib.suppress(Exception):
                close()


@contextlib.contextmanager
def using_many(resources: Sequence[Any]):
    try:
        yield resources
    finally:
        for r in resources:
            close = getattr(r, "close", None)
            if close is not None:
                with contextlib.suppress(Exception):
                    close()
