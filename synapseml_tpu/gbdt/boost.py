"""Boosting loop, objectives, and the serializable Booster.

Reference analogue: ``TrainUtils.trainCore`` (``lightgbm/.../TrainUtils.scala:92-160``,
iteration loop + eval/early-stop) and ``LightGBMBooster``
(``booster/LightGBMBooster.scala`` — predict normal/raw/leaf/contrib, save/load,
feature importance). The reference drives the LightGBM C++ core; here the whole
per-iteration step (objective grads -> bagging/GOSS weights -> tree growth -> score
update) is ONE jitted XLA program, vmapped over classes for multiclass and wrapped in
``shard_map`` over the mesh 'data' axis for distributed training (histogram ``psum``
replacing the reference's socket allreduce, ``TrainUtils.scala:280-296``).

Boosting modes (reference param ``boostingType`` gbdt|rf|dart|goss,
``LightGBMParams.scala``): gbdt, goss (top-|grad| keep + amplified subsample), dart
(tree dropout with 1/(k+1) normalization), rf (bagged trees, averaged, no shrinkage).
"""

from __future__ import annotations

import json
from functools import lru_cache
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.clock import StopWatch
from ..observability import get_registry
from ..observability.profiling import profiled_jit
from .binning import BinMapper
from .grow import GrownTree, TreeConfig, grow_tree

__all__ = ["GBDTBooster", "train", "OBJECTIVES", "METRICS"]


# ---------------------------------------------------------------------------------
# Objectives: name -> (init_score_fn(y, w) -> base, grad_fn(score, y, w) -> (g, h))
# score/raw margins; multiclass objectives see (n, C) scores. All jax-traceable.
# Reference param `objective` (LightGBMParams / LightGBMConstants).
# ---------------------------------------------------------------------------------

def _sigmoid(z):
    import jax.numpy as jnp

    return 1.0 / (1.0 + jnp.exp(-z))


def _obj_binary():
    def init(y, w):
        p = np.clip(np.average(y, weights=w), 1e-8, 1 - 1e-8)
        return float(np.log(p / (1 - p)))

    def grads(score, y, w):
        p = _sigmoid(score)
        return (p - y) * w, p * (1 - p) * w

    return init, grads


def _obj_l2():
    def init(y, w):
        return float(np.average(y, weights=w))

    def grads(score, y, w):
        return (score - y) * w, w

    return init, grads


def _obj_l1():
    def init(y, w):
        return float(np.median(y))

    def grads(score, y, w):
        import jax.numpy as jnp

        return jnp.sign(score - y) * w, w

    return init, grads


def _obj_huber(alpha=0.9):
    def init(y, w):
        return float(np.average(y, weights=w))

    def grads(score, y, w):
        import jax.numpy as jnp

        r = score - y
        return jnp.clip(r, -alpha, alpha) * w, w

    return init, grads


def _obj_poisson():
    def init(y, w):
        return float(np.log(max(np.average(y, weights=w), 1e-8)))

    def grads(score, y, w):
        import jax.numpy as jnp

        mu = jnp.exp(score)
        return (mu - y) * w, mu * w

    return init, grads


def _obj_quantile(alpha=0.5):
    def init(y, w):
        return float(np.quantile(y, alpha))

    def grads(score, y, w):
        import jax.numpy as jnp

        r = score - y
        g = jnp.where(r >= 0, 1.0 - alpha, -alpha)
        return g * w, w

    return init, grads


def _obj_tweedie(rho=1.5):
    def init(y, w):
        return float(np.log(max(np.average(y, weights=w), 1e-8)))

    def grads(score, y, w):
        import jax.numpy as jnp

        g = -y * jnp.exp((1 - rho) * score) + jnp.exp((2 - rho) * score)
        h = -y * (1 - rho) * jnp.exp((1 - rho) * score) + (2 - rho) * jnp.exp((2 - rho) * score)
        return g * w, jnp.maximum(h, 1e-16) * w

    return init, grads


def _obj_multiclass(num_class):
    def init(y, w):
        # per-class log prior (boost_from_average for softmax)
        pri = np.array([
            max(float(np.average(y == c, weights=w)), 1e-8) for c in range(num_class)
        ])
        return np.log(pri / pri.sum())

    def grads(score, y, w):
        import jax.numpy as jnp

        # score (n, C); y (n,) int
        p = jnp.exp(score - jnp.max(score, axis=1, keepdims=True))
        p = p / p.sum(axis=1, keepdims=True)
        onehot = (y[:, None] == jnp.arange(score.shape[1])).astype(p.dtype)
        g = (p - onehot) * w[:, None]
        h = p * (1 - p) * 2.0 * w[:, None]  # LightGBM multiplies softmax hess by 2
        return g, h

    return init, grads


def _group_tables(sizes: np.ndarray, G: int, base: int = 0):
    """(Q, G) row-index + validity tables for contiguous query groups whose
    rows start at ``base``."""
    Q = len(sizes)
    pad_idx = np.zeros((Q, G), dtype=np.int32)
    valid_np = np.zeros((Q, G), dtype=bool)
    start = base
    for q, sz in enumerate(sizes):
        pad_idx[q, :sz] = np.arange(start, start + sz)
        valid_np[q, :sz] = True
        start += sz
    return pad_idx, valid_np


def _lambda_grads(score, y, w, idx, valid, n: int, G: int,
                  truncation: int, sigma: float):
    """Pairwise LambdaRank grad/hess over (Q, G) group tables — dense
    fixed-shape (Q, G, G) device work, the TPU-friendly formulation of the
    reference's per-query C++ loops."""
    import jax.numpy as jnp

    s = jnp.where(valid, score[idx], -jnp.inf)  # (Q, G)
    lab = jnp.where(valid, y[idx], 0.0)
    # rank within group by current score, descending
    order = jnp.argsort(-s, axis=1)
    rank = jnp.argsort(order, axis=1)  # 0-based rank per doc
    gain = jnp.exp2(lab) - 1.0
    disc = jnp.where(valid, 1.0 / jnp.log2(2.0 + rank), 0.0)
    # ideal DCG at truncation from sorted labels
    ideal_gain = -jnp.sort(-jnp.where(valid, gain, 0.0), axis=1)
    ideal_rank = jnp.arange(G)
    trunc_mask = ideal_rank < truncation
    max_dcg = (ideal_gain * (1.0 / jnp.log2(2.0 + ideal_rank)) * trunc_mask).sum(1)
    max_dcg = jnp.maximum(max_dcg, 1e-12)[:, None, None]
    sdiff = s[:, :, None] - s[:, None, :]
    rho = 1.0 / (1.0 + jnp.exp(sigma * sdiff))  # sigmoid(-sigma * (s_i - s_j))
    delta = (
        jnp.abs(gain[:, :, None] - gain[:, None, :])
        * jnp.abs(disc[:, :, None] - disc[:, None, :])
        / max_dcg
    )
    in_trunc = (rank[:, :, None] < truncation) | (rank[:, None, :] < truncation)
    pair = (
        (lab[:, :, None] > lab[:, None, :])
        & valid[:, :, None] & valid[:, None, :] & in_trunc
    )
    lam = jnp.where(pair, sigma * rho * delta, 0.0)
    hpair = jnp.where(pair, sigma * sigma * rho * (1.0 - rho) * delta, 0.0)
    # winner i of pair (i, j): push score up (negative grad); loser j: down
    g_mat = -lam.sum(2) + lam.sum(1)
    h_mat = hpair.sum(2) + hpair.sum(1)
    g_flat = jnp.zeros(n, dtype=jnp.float32).at[idx.reshape(-1)].add(
        jnp.where(valid, g_mat, 0.0).reshape(-1))
    h_flat = jnp.zeros(n, dtype=jnp.float32).at[idx.reshape(-1)].add(
        jnp.where(valid, h_mat, 0.0).reshape(-1))
    return g_flat * w, jnp.maximum(h_flat, 1e-12) * w


def make_lambdarank(group_sizes: np.ndarray, truncation: int = 30, sigma: float = 1.0):
    """LambdaRank grad fn over contiguous query groups (reference objective
    ``lambdarank``, ``LightGBMRankerParams``). Rows MUST be ordered by group.

    Returns (init_fn, grad_fn); see :func:`_lambda_grads` for the math.
    """
    sizes = np.asarray(group_sizes, dtype=np.int64)
    n = int(sizes.sum())
    G = int(sizes.max())
    pad_idx, valid_np = _group_tables(sizes, G)

    def init(y, w):
        return 0.0

    def grads(score, y, w):
        import jax.numpy as jnp

        return _lambda_grads(score, y, w, jnp.asarray(pad_idx),
                             jnp.asarray(valid_np), n, G, truncation, sigma)

    return init, grads


def make_lambdarank_mesh(group_sizes: np.ndarray, n_shards: int, axis: str,
                         truncation: int = 30, sigma: float = 1.0):
    """Distributed LambdaRank via GROUP-ALIGNED sharding.

    The reference trains the ranker distributed by repartitioning on the
    group column so every query's rows land whole in one partition
    (``LightGBMRanker.scala:82-109``). TPU formulation: queries are assigned
    to shards by a deterministic greedy row-count balance, each shard's row
    block is padded to the widest shard with zero-weight rows, and the
    grad fn selects its shard's (Q, G) group tables by ``axis_index`` inside
    ``shard_map`` — per-query lambda computation stays entirely local; only
    the histogram psum crosses shards, exactly like every other objective.

    Returns ``(init_fn, grad_fn, order, w_mask, local)``:
    ``order`` (n_shards * local,) original-row id per padded-global slot
    (padding repeats row 0), ``w_mask`` zeroes the padding rows, ``local``
    the per-shard row count. Callers permute the uploaded arrays by
    ``order`` and multiply weights by ``w_mask``.
    """
    sizes = np.asarray(group_sizes, dtype=np.int64)
    n = int(sizes.sum())
    Q = len(sizes)
    G = int(sizes.max())
    starts = np.zeros(Q + 1, dtype=np.int64)
    np.cumsum(sizes, out=starts[1:])
    # deterministic contiguous assignment: a query goes to the shard its row
    # MIDPOINT falls in under an even n/n_shards split — monotone in q, so
    # chunks stay contiguous, and row counts balance to within one query
    target = n / n_shards
    mids = starts[:-1] + sizes / 2.0
    shard_of = np.minimum((mids / target).astype(np.int64), n_shards - 1)
    per_shard = [np.nonzero(shard_of == s)[0] for s in range(n_shards)]
    rows_per_shard = [int(sizes[qs].sum()) for qs in per_shard]
    local = max(max(rows_per_shard), 1)
    q_max = max(max(len(qs) for qs in per_shard), 1)

    order = np.zeros(n_shards * local, dtype=np.int64)
    w_mask = np.zeros(n_shards * local, dtype=np.float64)
    pad_idx = np.zeros((n_shards, q_max, G), dtype=np.int32)
    valid_np = np.zeros((n_shards, q_max, G), dtype=bool)
    for s, qs in enumerate(per_shard):
        pos = 0
        for qi, q in enumerate(qs):
            sz = int(sizes[q])
            order[s * local + pos: s * local + pos + sz] = \
                np.arange(starts[q], starts[q + 1])
            w_mask[s * local + pos: s * local + pos + sz] = 1.0
            pad_idx[s, qi, :sz] = np.arange(pos, pos + sz)  # LOCAL row ids
            valid_np[s, qi, :sz] = True
            pos += sz

    def init(y, w):
        return 0.0

    def grads(score, y, w):
        import jax
        import jax.numpy as jnp

        sidx = jax.lax.axis_index(axis)
        idx = jnp.take(jnp.asarray(pad_idx), sidx, axis=0)      # (Qmax, G)
        valid = jnp.take(jnp.asarray(valid_np), sidx, axis=0)
        return _lambda_grads(score, y, w, idx, valid, local, G,
                             truncation, sigma)

    return init, grads, order, w_mask, local


def _metric_ndcg(k: int = 10):
    def fn(y, score, w, group_sizes):
        total, start = 0.0, 0
        cnt = 0
        for sz in group_sizes:
            ys = y[start:start + sz]
            ss = score[start:start + sz]
            order = np.argsort(-ss, kind="stable")[:k]
            dcg = ((2.0 ** ys[order] - 1) / np.log2(2 + np.arange(len(order)))).sum()
            ideal = np.sort(ys)[::-1][:k]
            idcg = ((2.0 ** ideal - 1) / np.log2(2 + np.arange(len(ideal)))).sum()
            total += dcg / idcg if idcg > 0 else 0.0
            cnt += 1
            start += sz
        return total / max(cnt, 1)

    return fn


OBJECTIVES: Dict[str, Callable[..., Tuple[Callable, Callable]]] = {
    "binary": _obj_binary,
    "regression": _obj_l2,
    "l2": _obj_l2,
    "mean_squared_error": _obj_l2,
    "l1": _obj_l1,
    "mae": _obj_l1,
    "huber": _obj_huber,
    "poisson": _obj_poisson,
    "quantile": _obj_quantile,
    "tweedie": _obj_tweedie,
    "multiclass": _obj_multiclass,
    "softmax": _obj_multiclass,
}


# ---------------------------------------------------------------------------------
# Eval metrics (host-side numpy; eval sets are modest). name -> (fn, higher_better)
# ---------------------------------------------------------------------------------

def _metric_auc(y, score, w):
    order = np.argsort(score, kind="stable")
    y_s, w_s = y[order], w[order]
    ranks = np.cumsum(w_s) - w_s / 2.0  # midrank approximation for weighted AUC
    pos = y_s > 0
    sw_pos, sw_neg = w_s[pos].sum(), w_s[~pos].sum()
    if sw_pos == 0 or sw_neg == 0:
        return 0.5
    r_pos = (ranks[pos] * w_s[pos]).sum() / sw_pos
    r_neg = (ranks[~pos] * w_s[~pos]).sum() / sw_neg
    total = w_s.sum()
    return float(0.5 + (r_pos - r_neg) / total)


def _metric_binary_logloss(y, score, w):
    p = np.clip(1 / (1 + np.exp(-score)), 1e-15, 1 - 1e-15)
    return float(np.average(-(y * np.log(p) + (1 - y) * np.log(1 - p)), weights=w))


def _metric_l2(y, score, w):
    return float(np.average((y - score) ** 2, weights=w))


def _metric_rmse(y, score, w):
    return float(np.sqrt(_metric_l2(y, score, w)))


def _metric_l1(y, score, w):
    return float(np.average(np.abs(y - score), weights=w))


def _metric_multi_logloss(y, score, w):
    z = score - score.max(axis=1, keepdims=True)
    p = np.exp(z)
    p = p / p.sum(axis=1, keepdims=True)
    pi = np.clip(p[np.arange(len(y)), y.astype(int)], 1e-15, None)
    return float(np.average(-np.log(pi), weights=w))


def _metric_multi_error(y, score, w):
    return float(np.average(score.argmax(1) != y, weights=w))


METRICS: Dict[str, Tuple[Callable, bool]] = {
    "auc": (_metric_auc, True),
    "binary_logloss": (_metric_binary_logloss, False),
    "l2": (_metric_l2, False),
    "mse": (_metric_l2, False),
    "rmse": (_metric_rmse, False),
    "l1": (_metric_l1, False),
    "mae": (_metric_l1, False),
    "multi_logloss": (_metric_multi_logloss, False),
    "multi_error": (_metric_multi_error, False),
}

_DEFAULT_METRIC = {"binary": "binary_logloss", "multiclass": "multi_logloss",
                   "softmax": "multi_logloss", "l1": "l1", "mae": "l1",
                   "quantile": "l1"}


# Device (jnp) twins of METRICS so eval/early-stopping margins never leave
# the chip (the reference's per-iteration eval runs inside the C++ core;
# VERDICT r02 flagged the host replay loop as orders slower than training).
def _dev_metric(name: str):
    import jax.numpy as jnp

    def wavg(v, w):
        return jnp.sum(v * w) / jnp.maximum(jnp.sum(w), 1e-12)

    if name == "auc":
        def auc(y, score, w):
            order = jnp.argsort(score, stable=True)
            y_s, w_s = y[order], w[order]
            # normalize weights so all rank quantities are O(1): raw f32
            # ranks lose integer resolution past 2^24 rows (the host twin
            # runs in f64; TPU f32 needs the rescale)
            wn = w_s / jnp.maximum(jnp.sum(w_s), 1e-12)
            ranks = jnp.cumsum(wn) - wn / 2.0
            pos = (y_s > 0).astype(wn.dtype)
            sw_pos = jnp.sum(wn * pos)
            sw_neg = jnp.sum(wn * (1 - pos))
            r_pos = jnp.sum(ranks * wn * pos) / jnp.maximum(sw_pos, 1e-12)
            r_neg = jnp.sum(ranks * wn * (1 - pos)) / jnp.maximum(sw_neg, 1e-12)
            out = 0.5 + (r_pos - r_neg)
            return jnp.where((sw_pos == 0) | (sw_neg == 0), 0.5, out)
        return auc
    if name == "binary_logloss":
        def bll(y, score, w):
            p = jnp.clip(1 / (1 + jnp.exp(-score)), 1e-15, 1 - 1e-15)
            return wavg(-(y * jnp.log(p) + (1 - y) * jnp.log(1 - p)), w)
        return bll
    if name in ("l2", "mse"):
        return lambda y, s, w: wavg((y - s) ** 2, w)
    if name == "rmse":
        return lambda y, s, w: jnp.sqrt(wavg((y - s) ** 2, w))
    if name in ("l1", "mae"):
        return lambda y, s, w: wavg(jnp.abs(y - s), w)
    if name == "multi_logloss":
        def mll(y, score, w):
            z = score - score.max(axis=1, keepdims=True)
            p = jnp.exp(z)
            p = p / p.sum(axis=1, keepdims=True)
            pi = jnp.clip(p[jnp.arange(score.shape[0]), y.astype(jnp.int32)],
                          1e-15, None)
            return wavg(-jnp.log(pi), w)
        return mll
    if name == "multi_error":
        return lambda y, s, w: wavg((s.argmax(1) != y.astype(jnp.int32))
                                    .astype(jnp.float32), w)
    return None  # no device twin (e.g. ndcg) -> host eval path


# ---------------------------------------------------------------------------------
# Booster
# ---------------------------------------------------------------------------------

class GBDTBooster:
    """Serializable trained model: stacked tree arrays + bin mapper + metadata.

    Tree arrays have shape (T, C, ...): T iterations, C classes (C=1 unless
    multiclass). ``tree_scale`` (T,) carries shrinkage/DART/RF normalization.
    """

    def __init__(self, mapper: BinMapper, objective: str, num_class: int,
                 base_score: np.ndarray,
                 parent: np.ndarray, feature: np.ndarray, threshold: np.ndarray,
                 bin_: np.ndarray, gain: np.ndarray, leaf_value: np.ndarray,
                 leaf_hess: np.ndarray, tree_scale: np.ndarray,
                 boosting: str = "gbdt", best_iteration: Optional[int] = None,
                 feature_names: Optional[List[str]] = None,
                 cat_set: Optional[np.ndarray] = None):
        self.mapper = mapper
        self.objective = objective
        self.num_class = num_class
        self.base_score = np.atleast_1d(np.asarray(base_score, dtype=np.float64))
        self.parent = parent          # (T, C, L-1) int32
        self.feature = feature        # (T, C, L-1) int32
        self.threshold = threshold    # (T, C, L-1) f64 raw-value thresholds
        self.bin = bin_               # (T, C, L-1) int32
        self.gain = gain              # (T, C, L-1) f32
        self.leaf_value = leaf_value  # (T, C, L) f32 (unscaled)
        self.leaf_hess = leaf_hess    # (T, C, L) f32
        self.tree_scale = tree_scale  # (T,) f64
        self.boosting = boosting
        self.best_iteration = best_iteration
        self.feature_names = feature_names
        # (T, C, L-1, B) int8 category-membership sets for categorical splits
        # (split stores bin == -1); None when the model has no categorical splits
        self.cat_set = cat_set

    # -- prediction ----------------------------------------------------------------

    @property
    def num_trees(self) -> int:
        return self.parent.shape[0]

    def _used_trees(self, num_iteration: Optional[int]) -> int:
        t = self.best_iteration if num_iteration is None else num_iteration
        if t is None or t <= 0 or t > self.num_trees:
            t = self.num_trees
        return t

    def _binned(self, x: np.ndarray) -> np.ndarray:
        """Bin raw features; all split decisions happen on bins (bit-identical
        with training; NaN lands in the missing bin and follows the right
        branch, matching the float-threshold semantics)."""
        return self.mapper.transform(np.asarray(x, dtype=np.float64))

    def _csr_used_sub(self, csr, T: int):
        """Densify ONLY the features the first ``T`` trees reference.

        At hashed-text width the full (n, d) matrix is unbuildable, but
        trees touch at most T*(L-1) distinct features. Returns
        ``(sub, F, feats)``: the raw (n, |F|) float submatrix (implicit
        entries are true zeros), the ascending used-feature ids, and the
        tree feature arrays remapped into submatrix columns."""
        n, d = csr.shape
        if d != self.mapper.n_features:
            raise ValueError(f"expected {self.mapper.n_features} features, "
                             f"got {d}")
        F = np.unique(self.feature[:T]) if T else np.zeros(1, np.int64)
        order = csr.tocsc_order()
        cols_sorted = csr.indices[order]
        rows_sorted = csr.row_ids()[order]
        vals_sorted = csr.values[order]
        sub = np.zeros((n, len(F)), np.float64)
        lo = np.searchsorted(cols_sorted, F, side="left")
        hi = np.searchsorted(cols_sorted, F, side="right")
        for k in range(len(F)):
            sub[rows_sorted[lo[k]:hi[k]], k] = vals_sorted[lo[k]:hi[k]]
        feats = np.searchsorted(F, self.feature[:T]).astype(np.int32)
        return sub, F, feats

    def _bin_used_sub(self, sub: np.ndarray, F: np.ndarray) -> np.ndarray:
        """Bin a densified used-feature submatrix column by column."""
        binned = np.empty(sub.shape, dtype=np.int32)
        for k, j in enumerate(F):
            binned[:, k] = self.mapper.transform_column(int(j), sub[:, k])
        return binned

    def _csr_used_binned(self, csr, T: int):
        """Bin ONLY the features the first ``T`` trees reference — the CSR
        predict path (reference ``predictForCSR``,
        ``LightGBMBooster.scala:510``). Returns ``(binned, feats)``."""
        sub, F, feats = self._csr_used_sub(csr, T)
        return self._bin_used_sub(sub, F), feats

    def _leaf_of_binned(self, binned: np.ndarray, t: int, c: int,
                        feature: Optional[np.ndarray] = None) -> np.ndarray:
        node = np.zeros(binned.shape[0], dtype=np.int32)
        par, bins = self.parent[t, c], self.bin[t, c]
        feat = self.feature[t, c] if feature is None else feature[t, c]
        cat = self.cat_set[t, c] if self.cat_set is not None else None
        for s in range(par.shape[0]):
            p = par[s]
            if p < 0:
                continue
            col = binned[:, feat[s]]
            if bins[s] < 0:  # categorical split: left = in-set
                go_left = cat[s][col] > 0
            else:
                go_left = col <= bins[s]
            go_right = (node == p) & ~go_left
            node[go_right] = s + 1
        return node

    def _leaf_of(self, x: np.ndarray, t: int, c: int) -> np.ndarray:
        return self._leaf_of_binned(self._binned(x), t, c)

    def raw_predict(self, x: np.ndarray, num_iteration: Optional[int] = None,
                    backend: str = "auto") -> np.ndarray:
        """Raw margin, shape (n,) or (n, C) for multiclass.

        ``backend``: 'device' replays all trees in one jitted scan (the default
        for non-trivial batches — reference predict runs in the C++ core,
        ``LightGBMBooster.scala:510,529``), 'host' uses the numpy loop, 'auto'
        picks by batch size.
        """
        from .sparse import as_csr, is_sparse_input

        T = self._used_trees(num_iteration)
        if is_sparse_input(x):
            # reference predictForCSR: score sparse vectors directly
            csr = as_csr(x)
            n = csr.shape[0]
            binned, feats = self._csr_used_binned(csr, T)
        else:
            x = np.asarray(x, dtype=np.float64)
            n = x.shape[0]
            binned = self._binned(x)
            feats = None
        base = np.tile(self.base_score, (n, 1)).astype(np.float64)
        if T == 0:
            out = base
        elif backend == "device" or (backend == "auto" and n * T >= 2048):
            from .device_predict import device_raw_scores

            scores = device_raw_scores(
                binned, self.parent[:T],
                self.feature[:T] if feats is None else feats, self.bin[:T],
                self.leaf_value[:T], self.tree_scale[:T],
                self.cat_set[:T] if self.cat_set is not None else None)
            out = base + np.asarray(scores, np.float64)
        else:
            out = base.copy()
            for t in range(T):
                sc = self.tree_scale[t]
                for c in range(self.num_class):
                    leaf = self._leaf_of_binned(binned, t, c, feature=feats)
                    out[:, c] += self.leaf_value[t, c][leaf] * sc
        if self.boosting == "rf" and T > 0:
            out = np.tile(self.base_score, (n, 1)) + (out - base) / T
        return out[:, 0] if self.num_class == 1 else out

    def predict(self, x: np.ndarray, num_iteration: Optional[int] = None) -> np.ndarray:
        """Transformed prediction: probability for binary/multiclass, value otherwise.

        Reference: ``LightGBMBooster.score`` (``LightGBMBooster.scala:327``).
        """
        return self.activate(self.raw_predict(x, num_iteration))

    def activate(self, raw: np.ndarray) -> np.ndarray:
        """Objective link function over a raw margin (callers that already
        hold ``raw_predict`` output skip a full second scoring pass)."""
        if self.objective == "binary":
            return np.where(raw >= 0, 1 / (1 + np.exp(-np.abs(raw))),
                            np.exp(-np.abs(raw)) / (1 + np.exp(-np.abs(raw))))
        if self.objective in ("multiclass", "softmax"):
            z = raw - raw.max(axis=1, keepdims=True)
            p = np.exp(z)
            return p / p.sum(axis=1, keepdims=True)
        if self.objective in ("poisson", "tweedie"):
            return np.exp(raw)
        return raw

    def raw_predict_device(self, x, num_iteration: Optional[int] = None):
        """Fully on-device raw margin for a device-resident float feature array.

        Chains device binning (``device_predict.device_bin_cat``) into the
        jitted tree scan with NO host transfer — the path that keeps
        multi-stage pipelines (e.g. ViT featurizer -> GBDT, BASELINE config
        #5) resident on the chip. Categorical features bin on device too
        (exact-match category lookup). Returns a jax array (n, C).
        """
        import jax.numpy as jnp

        from .device_predict import (_score_kernel, device_bin_cat,
                                     pack_feature_table)

        T = self._used_trees(num_iteration)
        table, lens, cat_flags = pack_feature_table(self.mapper)
        # table/lens/cat_flags stay numpy: they are model constants, and
        # host arrays keep this whole method traceable under an outer jit
        # (a traced cat_flags broke BASELINE config #5 in r4)
        binned = device_bin_cat(x, table, lens, cat_flags,
                                self.mapper.missing_bin)
        if T == 0:
            return jnp.tile(jnp.asarray(self.base_score, jnp.float32),
                            (binned.shape[0], 1))
        has_cat = self.cat_set is not None
        k = _score_kernel(T, self.num_class, self.parent.shape[2], has_cat)
        cs = (self.cat_set[:T].astype(np.int8) if has_cat else
              np.zeros((T, self.num_class, self.parent.shape[2], 1), np.int8))
        scores = k(binned, self.parent[:T].astype(np.int32),
                   self.feature[:T].astype(np.int32),
                   self.bin[:T].astype(np.int32), cs,
                   self.leaf_value[:T].astype(np.float32),
                   np.asarray(self.tree_scale[:T], np.float64))
        out = scores + jnp.asarray(self.base_score, jnp.float32)[None, :]
        if self.boosting == "rf" and T > 0:
            out = jnp.asarray(self.base_score, jnp.float32)[None, :] + \
                (out - jnp.asarray(self.base_score, jnp.float32)[None, :]) / T
        return out

    def predict_device(self, x, num_iteration: Optional[int] = None):
        """On-device transformed prediction (sigmoid/softmax/exp per objective)."""
        import jax
        import jax.numpy as jnp

        raw = self.raw_predict_device(x, num_iteration)
        if self.objective == "binary":
            return jax.nn.sigmoid(raw[:, 0])
        if self.objective in ("multiclass", "softmax"):
            return jax.nn.softmax(raw, axis=1)
        if self.objective in ("poisson", "tweedie"):
            return jnp.exp(raw[:, 0] if self.num_class == 1 else raw)
        return raw[:, 0] if self.num_class == 1 else raw

    def predict_leaf(self, x: np.ndarray, num_iteration: Optional[int] = None,
                     backend: str = "auto") -> np.ndarray:
        """Leaf index per (row, tree*class) — reference ``predictLeaf``."""
        from .sparse import as_csr, is_sparse_input

        T = self._used_trees(num_iteration)
        if is_sparse_input(x):
            csr = as_csr(x)
            n = csr.shape[0]
            binned, feats = self._csr_used_binned(csr, T)
        else:
            x = np.asarray(x, dtype=np.float64)
            n = x.shape[0]
            binned = self._binned(x)
            feats = None
        if T and (backend == "device" or (backend == "auto" and n * T >= 2048)):
            from .device_predict import device_leaf_indices

            leaves = device_leaf_indices(
                binned, self.parent[:T],
                self.feature[:T] if feats is None else feats, self.bin[:T],
                self.cat_set[:T] if self.cat_set is not None else None)  # (T,C,n)
            return np.ascontiguousarray(
                np.transpose(leaves, (2, 0, 1)).reshape(n, T * self.num_class))
        out = np.empty((n, T * self.num_class), dtype=np.int32)
        k = 0
        for t in range(T):
            for c in range(self.num_class):
                out[:, k] = self._leaf_of_binned(binned, t, c, feature=feats)
                k += 1
        return out

    def predict_contrib(self, x: np.ndarray, num_iteration: Optional[int] = None,
                        approximate: bool = False):
        """Per-feature contributions + expected value (last column).

        Default is EXACT TreeSHAP (Lundberg's path algorithm, matching the
        reference's ``featuresShap`` / C++ TreeSHAP at
        ``LightGBMBooster.scala:510,529``); ``approximate=True`` selects the
        cheaper Saabas path attribution.

        Sparse input (reference ``predictForCSR`` contrib dispatch,
        ``LightGBMBooster.scala:397-419,510``): contributions are computed
        over the used-feature submatrix — a feature appearing in no tree has
        exactly zero SHAP value, so the result is returned as a
        :class:`~.sparse.CSRMatrix` of shape (n, d+1) whose stored columns
        are the trees' used features plus the expected-value column (a dense
        (n, d+1) panel at hashed-feature width would be terabytes). For
        multiclass a list of per-class CSRMatrix is returned; densified it
        matches the dense path bit-for-bit.
        """
        from .sparse import as_csr, is_sparse_input

        if is_sparse_input(x):
            return self._predict_contrib_sparse(as_csr(x), num_iteration,
                                                approximate)
        x = np.asarray(x, dtype=np.float64)
        n, d = x.shape
        if not approximate:
            out = self._contrib_shap_panel(self._binned(x), self.feature,
                                           n, d, num_iteration)
        else:
            out = self._contrib_saabas_panel(x, self.feature, self.threshold,
                                             n, d, num_iteration)
        C = self.num_class
        out[:, :, d] += self.base_score[:, None]
        return out[0] if C == 1 else out

    def _predict_contrib_sparse(self, csr, num_iteration, approximate):
        from .sparse import CSRMatrix

        T = self._used_trees(num_iteration)
        n, d = csr.shape
        C = self.num_class
        sub, F, feats = self._csr_used_sub(csr, T)
        dF = len(F)
        if not approximate:
            out = self._contrib_shap_panel(self._bin_used_sub(sub, F), feats,
                                           n, dF, num_iteration)
        else:
            # thresholds index by split slot, not feature — no remap needed
            out = self._contrib_saabas_panel(sub, feats, self.threshold[:T],
                                             n, dF, num_iteration)
        out[:, :, dF] += self.base_score[:, None]
        cols = np.concatenate([F.astype(np.int64), [d]])
        indptr = np.arange(0, n * (dF + 1) + 1, dF + 1, dtype=np.int64)
        results = [CSRMatrix(indptr, np.tile(cols, n).astype(np.int32),
                             out[c].reshape(-1), (n, d + 1))
                   for c in range(C)]
        return results[0] if C == 1 else results

    def _contrib_saabas_panel(self, xv, featmap, thrmap, n, d,
                              num_iteration) -> np.ndarray:
        """Saabas attributions, (C, n, d+1) WITHOUT base_score.

        ``xv`` (n, d) raw values; ``featmap`` (T, C, S) feature column per
        split (possibly remapped into a submatrix); ``thrmap`` (T, C, S)
        float thresholds. Numeric SET splits (``bin < 0`` with a finite
        threshold — imported default_left models) route missing left; true
        categorical splits (NaN threshold) have no raw-value walk and
        raise."""
        if self.cat_set is not None and bool(
                ((self.bin < 0) & ~np.isfinite(self.threshold)).any()):
            raise ValueError("approximate (Saabas) contributions don't support "
                             "categorical splits; use approximate=False")
        T = self._used_trees(num_iteration)
        C = self.num_class
        out = np.zeros((C, n, d + 1), dtype=np.float64)
        for t in range(T):
            sc = self.tree_scale[t] * (1.0 / T if self.boosting == "rf" else 1.0)
            for c in range(C):
                par = self.parent[t, c]
                feat = featmap[t, c]
                thr = thrmap[t, c]
                V = self.leaf_value[t, c].astype(np.float64).copy()
                Hs = np.maximum(self.leaf_hess[t, c].astype(np.float64), 1e-12).copy()
                L1 = par.shape[0]
                left_val = np.zeros(L1)
                right_val = np.zeros(L1)
                for s in range(L1 - 1, -1, -1):
                    p = par[s]
                    if p < 0:
                        continue
                    left_val[s], right_val[s] = V[p], V[s + 1]
                    tot = Hs[p] + Hs[s + 1]
                    V[p] = (V[p] * Hs[p] + V[s + 1] * Hs[s + 1]) / tot
                    Hs[p] = tot
                node = np.zeros(n, dtype=np.int32)
                cur = np.full(n, V[0])
                out[c, :, d] += V[0] * sc
                for s in range(L1):
                    p = par[s]
                    if p < 0:
                        continue
                    col = xv[:, feat[s]]
                    at_p = node == p
                    with np.errstate(invalid="ignore"):
                        if self.bin[t, c, s] < 0:
                            # default_left set split: NaN routes LEFT
                            # (NaN > thr compares False)
                            go_right = at_p & (col > thr[s])
                        else:
                            go_right = at_p & (np.isnan(col) | (col > thr[s]))
                    go_left = at_p & ~go_right
                    new = np.where(go_right, right_val[s], np.where(go_left, left_val[s], cur))
                    out[c, at_p, feat[s]] += (new[at_p] - cur[at_p]) * sc
                    node[go_right] = s + 1
                    cur = new
        return out

    def _contrib_shap_panel(self, binned, featmap, n, d,
                            num_iteration) -> np.ndarray:
        """Exact TreeSHAP, (C, n, d+1) WITHOUT base_score; additivity:
        row sum + base == raw_predict."""
        from .treeshap import build_explicit_tree, expected_value, tree_shap

        T = self._used_trees(num_iteration)
        C = self.num_class
        out = np.zeros((C, n, d + 1), dtype=np.float64)
        for t in range(T):
            sc = self.tree_scale[t] * (1.0 / T if self.boosting == "rf" else 1.0)
            for c in range(C):
                root = build_explicit_tree(
                    self.parent[t, c], featmap[t, c], self.bin[t, c],
                    self.leaf_value[t, c], self.leaf_hess[t, c],
                    self.cat_set[t, c] if self.cat_set is not None else None)
                out[c, :, :d] += sc * tree_shap(root, binned, d)
                out[c, :, d] += sc * expected_value(root)
        return out

    def feature_importance(self, importance_type: str = "split",
                           num_iteration: Optional[int] = None) -> np.ndarray:
        """'split' counts or 'gain' sums per feature — reference
        ``getFeatureImportances`` (``LightGBMBooster.scala:491``)."""
        T = self._used_trees(num_iteration)
        d = self.mapper.n_features
        out = np.zeros(d)
        used = self.parent[:T] >= 0
        feats = self.feature[:T][used]
        if importance_type == "split":
            np.add.at(out, feats, 1.0)
        elif importance_type == "gain":
            np.add.at(out, feats, self.gain[:T][used].astype(np.float64))
        else:
            raise ValueError(f"importance_type must be 'split'|'gain', got {importance_type!r}")
        return out

    # -- persistence ---------------------------------------------------------------

    def state_dict(self) -> Dict[str, Any]:
        """Persistence protocol for the stage serializer (core/serialization.py)."""
        return {
            "parent": self.parent, "feature": self.feature,
            "threshold": self.threshold, "bin": self.bin, "gain": self.gain,
            "leaf_value": self.leaf_value, "leaf_hess": self.leaf_hess,
            "tree_scale": self.tree_scale, "base_score": self.base_score,
            "objective": self.objective, "num_class": self.num_class,
            "boosting": self.boosting, "best_iteration": self.best_iteration,
            "feature_names": self.feature_names, "mapper": self.mapper.to_dict(),
            "cat_set": self.cat_set,
        }

    @staticmethod
    def from_state_dict(d: Dict[str, Any]) -> "GBDTBooster":
        mapper = d["mapper"]
        if not isinstance(mapper, dict):  # JSON round-trip may hand back a string
            mapper = json.loads(mapper)
        return GBDTBooster(
            mapper=BinMapper.from_dict(mapper),
            objective=d["objective"], num_class=int(d["num_class"]),
            base_score=np.asarray(d["base_score"]),
            parent=np.asarray(d["parent"], dtype=np.int32),
            feature=np.asarray(d["feature"], dtype=np.int32),
            threshold=np.asarray(d["threshold"], dtype=np.float64),
            bin_=np.asarray(d["bin"], dtype=np.int32),
            gain=np.asarray(d["gain"], dtype=np.float32),
            leaf_value=np.asarray(d["leaf_value"], dtype=np.float32),
            leaf_hess=np.asarray(d["leaf_hess"], dtype=np.float32),
            tree_scale=np.asarray(d["tree_scale"], dtype=np.float64),
            boosting=d.get("boosting", "gbdt"),
            best_iteration=d.get("best_iteration"),
            feature_names=list(d["feature_names"]) if d.get("feature_names") else None,
            cat_set=(np.asarray(d["cat_set"], dtype=np.int8)
                     if d.get("cat_set") is not None else None),
        )

    def save_native_model(self) -> str:
        """LightGBM text-model string a stock LightGBM can load
        (reference ``saveNativeModel``, ``LightGBMBooster.scala:454``)."""
        from .native_model import booster_to_native

        return booster_to_native(self)

    @staticmethod
    def from_native_model(model_str: str) -> "GBDTBooster":
        """Import a LightGBM text model (reference ``setModelString``) —
        existing LightGBM models get this engine's device predict path."""
        from .native_model import booster_from_native

        return booster_from_native(model_str)

    def to_json(self) -> str:
        """Model string — reference ``saveNativeModel``/``getNativeModel``
        (``LightGBMBooster.scala:454``)."""
        return json.dumps({
            "format": "synapseml_tpu.gbdt.v1",
            "objective": self.objective,
            "num_class": self.num_class,
            "boosting": self.boosting,
            "base_score": self.base_score.tolist(),
            "best_iteration": self.best_iteration,
            "feature_names": self.feature_names,
            "mapper": self.mapper.to_dict(),
            "tree_scale": self.tree_scale.tolist(),
            "arrays": {
                k: getattr(self, k).tolist()
                for k in ("parent", "feature", "threshold", "bin", "gain",
                          "leaf_value", "leaf_hess")
            },
            "cat_set": self.cat_set.tolist() if self.cat_set is not None else None,
        })

    @staticmethod
    def from_model_string(s: str) -> "GBDTBooster":
        """Load a model string in either supported format, sniffing which.

        Accepts this engine's JSON model string or LightGBM's text format
        (``tree\\nversion=v3...``) — mirroring the reference's
        ``setModelString`` (``TrainUtils.scala:30-32``), which accepts
        whatever ``saveNativeModel`` produced without the caller declaring
        the format."""
        head = s.lstrip()[:1]
        if head == "{":
            return GBDTBooster.from_json(s)
        return GBDTBooster.from_native_model(s)

    @staticmethod
    def from_json(s: str) -> "GBDTBooster":
        d = json.loads(s)
        if d.get("format") != "synapseml_tpu.gbdt.v1":
            raise ValueError(f"not a gbdt model string (format={d.get('format')!r})")
        a = d["arrays"]
        return GBDTBooster(
            mapper=BinMapper.from_dict(d["mapper"]),
            objective=d["objective"], num_class=d["num_class"],
            base_score=np.asarray(d["base_score"]),
            parent=np.asarray(a["parent"], dtype=np.int32),
            feature=np.asarray(a["feature"], dtype=np.int32),
            threshold=np.asarray(a["threshold"], dtype=np.float64),
            bin_=np.asarray(a["bin"], dtype=np.int32),
            gain=np.asarray(a["gain"], dtype=np.float32),
            leaf_value=np.asarray(a["leaf_value"], dtype=np.float32),
            leaf_hess=np.asarray(a["leaf_hess"], dtype=np.float32),
            tree_scale=np.asarray(d["tree_scale"], dtype=np.float64),
            boosting=d.get("boosting", "gbdt"),
            best_iteration=d.get("best_iteration"),
            feature_names=d.get("feature_names"),
            cat_set=(np.asarray(d["cat_set"], dtype=np.int8)
                     if d.get("cat_set") is not None else None),
        )


# ---------------------------------------------------------------------------------
# Training
# ---------------------------------------------------------------------------------

_DEFAULTS = dict(
    objective="regression", num_iterations=100, learning_rate=0.1, num_leaves=31,
    max_bin=255, lambda_l1=0.0, lambda_l2=0.0, min_data_in_leaf=20,
    min_sum_hessian_in_leaf=1e-3, min_gain_to_split=0.0, feature_fraction=1.0,
    bagging_fraction=1.0, bagging_freq=0, boosting="gbdt",
    max_depth=-1, max_delta_step=0.0, boost_from_average=True,
    pos_bagging_fraction=1.0, neg_bagging_fraction=1.0,  # binary class-aware bag
    bin_sample_count=200_000, max_bin_by_feature=None,
    top_rate=0.2, other_rate=0.1,         # goss
    drop_rate=0.1, max_drop=50, skip_drop=0.5,  # dart
    uniform_drop=False, xgboost_dart_mode=False,
    categorical_feature=None, cat_smooth=10.0, max_cat_threshold=32,
    parallelism="data_parallel", top_k=20,
    num_class=1, seed=0, bagging_seed=3, metric=None, early_stopping_round=0,
    early_stopping_min_delta=0.0, hist_method="auto", hist_chunk=1 << 20,
    # leaf-local gather histograms: ~7% end-to-end win at Adult scale on
    # v5e (r5, B=255) — opt-in because the vmapped multiclass path executes
    # every lax.switch buffer branch and small-n fits gain nothing
    leaf_local=False,
    alpha=0.9, tweedie_variance_power=1.5, verbose=0,
    lambdarank_truncation_level=30, sigmoid=1.0, ndcg_at=10,
)


# LightGBM parameter aliases (config.h alias table, the commonly-used rows)
_ALIASES = {
    "num_iterations": ("num_iteration", "num_tree", "num_trees", "num_round",
                       "num_rounds", "num_boost_round", "n_estimators",
                       "nrounds", "n_iter"),
    "learning_rate": ("shrinkage_rate", "eta"),
    "num_leaves": ("num_leaf", "max_leaves", "max_leaf", "max_leaf_nodes"),
    "min_data_in_leaf": ("min_data_per_leaf", "min_data",
                         "min_child_samples", "min_samples_leaf"),
    "min_sum_hessian_in_leaf": ("min_sum_hessian_per_leaf",
                                "min_sum_hessian", "min_hessian",
                                "min_child_weight"),
    "bagging_fraction": ("sub_row", "subsample", "bagging"),
    "bagging_freq": ("subsample_freq",),
    "feature_fraction": ("sub_feature", "colsample_bytree"),
    "lambda_l1": ("reg_alpha", "l1_regularization"),
    "lambda_l2": ("reg_lambda", "lambda", "l2_regularization"),
    "min_gain_to_split": ("min_split_gain",),
    "early_stopping_round": ("early_stopping_rounds", "early_stopping",
                             "n_iter_no_change"),
    "boosting": ("boosting_type", "boost"),
    "max_bin": ("max_bins",),
    "seed": ("random_state", "random_seed"),
    "bin_sample_count": ("bin_construct_sample_cnt", "subsample_for_bin"),
    "categorical_feature": ("cat_feature", "categorical_column",
                            "cat_column"),
    "verbose": ("verbosity", "verbose_eval"),
    "objective": ("objective_type", "app", "application", "loss"),
}
_ALIAS_OF = {a: k for k, al in _ALIASES.items() for a in al}
# accepted-but-inert LightGBM keys: threading/device selection belongs to
# XLA here, so these are dropped WITHOUT the typo warning
_INERT_PARAMS = frozenset({
    "num_threads", "num_thread", "n_jobs", "nthread", "nthreads",
    "device", "device_type", "gpu_device_id", "gpu_platform_id",
    "force_row_wise", "force_col_wise", "two_round", "is_enable_sparse",
    "enable_sparse", "sparse", "importance_type",
})


def _canonicalize_params(params):
    """Resolve LightGBM aliases and WARN on unknown keys.

    The reference engine accepts its full alias table and warns on
    unrecognized parameters (``Config::Set``); silently swallowing a typo'd
    key (``nmu_iterations``) instead trains a default model. Two different
    aliases of one canonical key warn when they conflict (LightGBM's
    '... will be overridden'); threading/device keys are accepted and
    dropped silently — they have no meaning under XLA."""
    import warnings

    params = dict(params or {})
    out = {}
    unknown = []
    for k, v in params.items():
        kc = _ALIAS_OF.get(k, k)
        if kc in _INERT_PARAMS:
            continue
        if kc not in _DEFAULTS and kc != "objective":
            unknown.append(k)
            continue
        if kc != k and kc in params:
            continue  # an explicit canonical key wins over its alias
        if kc in out and out[kc] != v:
            warnings.warn(
                f"parameter {kc!r} set via multiple aliases with different "
                f"values; {v!r} overrides {out[kc]!r}", stacklevel=3)
        out[kc] = v
    if unknown:
        warnings.warn(
            f"unknown train() parameters ignored: {sorted(unknown)} — check "
            "for typos (the known names are the _DEFAULTS keys plus the "
            "LightGBM aliases)", stacklevel=3)
    return out


def _resolve_objective(params):
    name = params["objective"]
    if name in ("multiclass", "softmax"):
        return OBJECTIVES[name](params["num_class"])
    if name == "huber":
        return OBJECTIVES[name](params["alpha"])
    if name == "quantile":
        return OBJECTIVES[name](params["alpha"])
    if name == "tweedie":
        return OBJECTIVES[name](params["tweedie_variance_power"])
    if name not in OBJECTIVES:
        raise ValueError(f"unknown objective {name!r}; available: {sorted(OBJECTIVES)}")
    return OBJECTIVES[name]()


def _renewed_leaf_values(node, yv, raw_col, weight, alpha: float, L: int):
    """Leaf outputs as the weighted ``alpha``-percentile of leaf residuals.

    LightGBM's ``RenewTreeOutput`` (``regression_objective.hpp`` — quantile
    and L1 objectives replace the gradient-ratio leaf value with the exact
    residual percentile; without it pinball/L1 loss converges far slower
    than reference engines — r4 crosscheck measured ~2x worse pinball
    against sklearn's quantile GBR). Jit-friendly: two argsorts group rows
    by (leaf, residual), then per-leaf weighted-percentile positions come
    from L vectorized ``searchsorted`` lookups — no data-dependent shapes.
    """
    import jax.numpy as jnp

    r = yv - raw_col
    order1 = jnp.argsort(r)
    leaf_o = jnp.take(node, order1)
    order2 = jnp.argsort(leaf_o, stable=True)
    perm = jnp.take(order1, order2)          # leaf-major, residual ascending
    node_s = jnp.take(node, perm)
    r_s = jnp.take(r, perm)
    w_s = jnp.take(weight, perm)
    cw = jnp.cumsum(w_s)
    leaves = jnp.arange(L)
    starts = jnp.searchsorted(node_s, leaves, side="left")
    ends = jnp.searchsorted(node_s, leaves, side="right")
    offset = jnp.where(starts > 0, jnp.take(cw, jnp.maximum(starts - 1, 0)),
                       0.0)
    total = jnp.where(ends > 0, jnp.take(cw, jnp.maximum(ends - 1, 0)),
                      0.0) - offset
    target = offset + alpha * total
    pos = jnp.searchsorted(cw, target, side="left")
    pos = jnp.clip(pos, starts, jnp.maximum(ends - 1, starts))
    vals = jnp.take(r_s, jnp.clip(pos, 0, r_s.shape[0] - 1))
    return jnp.where(total > 0, vals, 0.0).astype(jnp.float32)


def _preround(x, n_bound: int, axis_name):
    """Truncate gradients to a summation-exact f32 grid (deterministic
    histograms).

    Histogram cells are f32 sums whose order differs between the
    single-device pass and the per-shard-then-``psum`` mesh pass; on
    tie-heavy data a last-ulp difference flips a near-tied argmax split and
    the trees diverge (the real failure behind
    ``test_sparse_mesh_matches_single_device``). Rounding every gradient to
    a multiple of ``ulp(factor)`` with ``factor >= max|x| * n_bound`` makes
    every partial sum of up to ``n_bound`` terms exactly representable, so
    ANY summation order produces the bit-identical cell value (XGBoost's
    ``CreateRoundingFactor`` pre-rounding). ``max`` is order-independent, so
    the mesh's ``pmax`` of shard maxima equals the single-device max and
    both paths round on the same grid. Per-element error is bounded by
    ``ulp(factor)/2`` — at most ``max|x| * n_bound * 2**-24``.
    """
    import jax.numpy as jnp
    from jax import lax

    m = jnp.max(jnp.abs(x), axis=0)
    if axis_name is not None:
        m = lax.pmax(m, axis_name)
    delta = m * jnp.float32(n_bound)
    factor = jnp.exp2(jnp.ceil(jnp.log2(jnp.maximum(delta, jnp.float32(1e-35)))))
    return (x + factor) - factor


def _build_step(grad_fn=None, fobj=None, *, cfg, C, lr, boosting, d, cat_idx,
                ff, bf, bfreq, use_goss, top_rate, other_rate, mesh, axis,
                model_axis=None,
                pos_bf=1.0, neg_bf=1.0, sparse_meta=None, renew_alpha=None,
                scan_iters=None, eval_metric=None, n_eval=0, n_bound=None):
    """Build the jitted per-iteration training step.

    Module-level so :func:`_cached_step` can reuse compiled programs across
    ``train()`` calls — a per-call closure would make every fit re-trace and
    re-compile the full ``num_leaves``-step XLA program (tens of seconds),
    which dominated short runs and hyperparameter sweeps.

    ``scan_iters=k``: instead of a single step, return the WHOLE k-iteration
    training loop as one ``lax.scan`` program — one dispatch per fit instead
    of one per iteration (host dispatch latency dominates on tunneled/remote
    backends; per-iteration host work only exists for dart/eval/callbacks,
    which use the per-step form). RNG streams match the host loop exactly:
    carry key splits per iteration, bagging key folds by period."""
    import jax
    import jax.numpy as jnp

    axis_name = axis if mesh is not None else None
    # per-shard bagging streams only exist when a bagging/GOSS mask actually
    # consumes random bits; folding the key by axis_index unconditionally
    # would put a mesh-only RNG head in the traced program (SMT113) for
    # configs whose step touches no RNG at all
    bag_rng_live = use_goss or (bfreq > 0 and (bf < 1.0 or pos_bf < 1.0
                                               or neg_bf < 1.0))
    cat_mask_np = None
    if cat_idx:
        cat_mask_np = np.zeros(d, np.float32)
        cat_mask_np[list(cat_idx)] = 1.0

    def make_weights(key, grad_abs, yv, n_rows):
        """Bagging/GOSS row mask. Starts from ones: sample weights already live in
        the objective's grad/hess (multiplying again would square them)."""
        ones = jnp.ones(n_rows, jnp.float32)
        if use_goss:
            cut = jnp.quantile(grad_abs, 1.0 - top_rate)
            is_top = grad_abs >= cut
            keep_small = jax.random.uniform(key, grad_abs.shape) < (
                other_rate / max(1e-12, 1.0 - top_rate))
            amp = (1.0 - top_rate) / max(other_rate, 1e-12)
            return jnp.where(is_top, 1.0, jnp.where(keep_small, amp, 0.0))
        if (pos_bf < 1.0 or neg_bf < 1.0) and bfreq > 0:
            # class-aware bagging (LightGBM pos/negBaggingFraction): sample
            # positives and negatives independently
            frac = jnp.where(yv > 0, pos_bf, neg_bf)
            keep = jax.random.uniform(key, grad_abs.shape) < frac
            return keep.astype(jnp.float32)
        if bf < 1.0 and bfreq > 0:
            keep = jax.random.uniform(key, grad_abs.shape) < bf
            return keep.astype(jnp.float32)
        return ones

    def one_iter(binned, yv, wv, raw, key, fkey):
        """raw (n, C) -> per-class trees + new raw; runs fully on device."""
        if fobj is not None:
            g, h = fobj(raw[:, 0] if C == 1 else raw, yv, wv)
            g = jnp.reshape(jnp.asarray(g, jnp.float32), (-1, C) if C > 1 else (-1, 1))
            h = jnp.reshape(jnp.asarray(h, jnp.float32), (-1, C) if C > 1 else (-1, 1))
        elif C == 1:
            g, h = grad_fn(raw[:, 0], yv, wv)
            g, h = g[:, None], h[:, None]
        else:
            g, h = grad_fn(raw, yv, wv)
        g = g.astype(jnp.float32)
        h = h.astype(jnp.float32)
        if n_bound is not None:
            # deterministic histograms: single-device and mesh sums become
            # bit-identical regardless of accumulation order (see _preround)
            g = _preround(g, n_bound, axis_name)
            h = _preround(h, n_bound, axis_name)

        fmask = (jax.random.uniform(fkey, (d,)) < ff).astype(jnp.float32) if ff < 1.0 \
            else jnp.ones((d,), jnp.float32)
        # never mask every feature
        fmask = jnp.where(fmask.sum() == 0, jnp.ones((d,), jnp.float32), fmask)

        bw = make_weights(key, jnp.abs(g).sum(axis=1), yv, g.shape[0])
        # mesh PADDING rows are marked with weight NEGATIVE ZERO (-0.0) by
        # train()'s upload layouts: their g/h are zero via the weight, but
        # without this mask they would still count 1 in the histogram COUNT
        # channel, inflating min_data_in_leaf gating and breaking
        # mesh-vs-single-replica tree equality whenever n doesn't divide
        # the shard count (or under the lambdarank group layout). A USER's
        # +0.0 sample weight keeps its count — LightGBM counts zero-weight
        # rows too.
        bw = jnp.where(jnp.signbit(wv) & (wv == 0), 0.0, bw)

        cmask = (jnp.asarray(cat_mask_np) if cat_mask_np is not None else None)

        def grow_c(gc, hc):
            return grow_tree(binned, gc, hc, bw, fmask, cfg,
                             axis_name=axis_name, cat_mask=cmask,
                             model_axis_name=model_axis)

        if C == 1:
            tree, node = grow_c(g[:, 0], h[:, 0])
            if renew_alpha is not None:
                # LightGBM RenewTreeOutput: percentile leaf outputs for
                # quantile/L1 (weighted by sample weight x bagging mask)
                tree = tree._replace(leaf_value=_renewed_leaf_values(
                    node, yv, raw[:, 0], wv * bw, renew_alpha,
                    cfg.num_leaves))
            trees = jax.tree.map(lambda a: a[None], tree)  # add class dim
            delta = tree.leaf_value[node][:, None]
        else:
            trees, nodes = jax.vmap(grow_c, in_axes=(1, 1), out_axes=0)(g, h)
            delta = jnp.stack(
                [trees.leaf_value[c][nodes[c]] for c in range(C)], axis=1
            )
        if boosting == "rf":
            new_raw = raw  # rf: every tree fits the base-score residual; avg at predict
        else:
            new_raw = raw + lr * delta
        return trees, new_raw

    def scan_loop(binned, yv, wv, raw, key0, bkey):
        from jax import lax

        def body(carry, i):
            key, raw = carry
            key, k2 = jax.random.split(key)
            period = i if use_goss else i // max(bfreq, 1)
            k1 = jax.random.fold_in(bkey, period)
            if mesh is not None and bag_rng_live:
                k1 = jax.random.fold_in(k1, jax.lax.axis_index(axis))
            trees, raw = one_iter(binned, yv, wv, raw, k1, k2)
            return (key, raw), trees

        (_, raw), trees = lax.scan(body, (key0, raw),
                                   jnp.arange(scan_iters))
        return trees, raw

    def scan_loop_eval(binned, yv, wv, raw, key0, bkey, it0, base,
                       eval_data):
        """Training chunk with ON-DEVICE eval margins + metric per iteration
        (VERDICT r02: the host replay loop made realistic early-stopping runs
        orders slower than training). ``eval_data``: tuple per eval set of
        (binned, y, w, raw_margins). Returns the final carry key so chunks
        chain with the same RNG stream as the host loop."""
        from jax import lax

        from .grow import predict_binned

        metric = _dev_metric(eval_metric)

        def tree_delta(trees, eb):
            cols = []
            for c in range(C):
                tc = jax.tree.map(lambda a: a[c], trees)
                node = predict_binned(tc, eb)
                cols.append(tc.leaf_value[node])
            return jnp.stack(cols, axis=1)

        def body(carry, i):
            key, raw, eraws = carry
            key, k2 = jax.random.split(key)
            it = it0 + i
            period = it if use_goss else it // max(bfreq, 1)
            k1 = jax.random.fold_in(bkey, period)
            if mesh is not None and bag_rng_live:
                k1 = jax.random.fold_in(k1, jax.lax.axis_index(axis))
            trees, raw = one_iter(binned, yv, wv, raw, k1, k2)
            new_eraws, ms = [], []
            for (eb, ey, ew, _), eraw in zip(eval_data, eraws):
                eraw = eraw + lr * tree_delta(trees, eb)
                if boosting == "rf":  # rf averages trees instead of summing
                    esc = base[None, :] + (eraw - base[None, :]) / (it + 1.0)
                else:
                    esc = eraw
                score = esc[:, 0] if C == 1 else esc
                ms.append(metric(ey, score, ew))
                new_eraws.append(eraw)
            return (key, raw, tuple(new_eraws)), (trees, jnp.stack(ms))

        eraws0 = tuple(e[3] for e in eval_data)
        (key, raw, eraws), (trees, metrics) = lax.scan(
            body, (key0, raw, eraws0), jnp.arange(scan_iters))
        return trees, raw, eraws, metrics, key

    if mesh is not None:
        from ..runtime.layout import as_layout

        layout = as_layout(mesh, data_axis=axis)
        data_spec = layout.batch()
        rep = layout.replicated()
        if sparse_meta is not None:
            # SparseBinned pytree: the per-shard entry/cell-table arrays
            # shard on axis 0 (row blocks), the per-feature zero_bin
            # replicates; aux must match the arg's for the pytrees to line up
            from .sparse import SparseBinned

            d_s, B_s, n_local, max_run = sparse_meta
            binned_spec = SparseBinned(
                rows=data_spec, bins=data_spec, ends=data_spec,
                starts=data_spec, zero_bin=rep,
                d=d_s, n_bins=B_s, n=n_local, max_run=max_run)
        else:
            binned_spec = data_spec
        in_specs = (binned_spec, data_spec, data_spec, data_spec, rep, rep)
        out_specs = (rep, data_spec)
        # profiled jit entry points (observability/profiling.py): every
        # XLA compile of a training step is timed into
        # smt_compile_seconds{fn=...} with its recompile cause, and the
        # executable's cost_analysis FLOPs attribute achieved MFU to the
        # enclosing fit() span
        if scan_iters is not None and n_eval > 0:
            # mesh device-eval: eval sets REPLICATE (each shard scores the
            # full set against the replicated trees and computes the same
            # metric panel — no distributed AUC/rank machinery needed, and
            # the early-stop decision is shard-identical by construction);
            # only training rows stay sharded. it0/base are scalars.
            return profiled_jit(layout.shard_map(
                scan_loop_eval,
                in_specs=in_specs + (rep, rep, rep),
                out_specs=(rep, data_spec, rep, rep, rep),
                check=False), name="gbdt.scan_eval_sharded")
        if scan_iters is not None:
            return profiled_jit(layout.shard_map(scan_loop,
                                                 in_specs=in_specs,
                                                 out_specs=out_specs,
                                                 check=False),
                                name="gbdt.scan_sharded")

        def sharded_iter(binned, yv, wv, raw, key, fkey):
            if bag_rng_live:
                key = jax.random.fold_in(key, jax.lax.axis_index(axis))
            trees, new_raw = one_iter(binned, yv, wv, raw, key, fkey)
            return trees, new_raw

        return profiled_jit(layout.shard_map(
            sharded_iter,
            in_specs=in_specs,
            out_specs=out_specs,
            check=False,
        ), name="gbdt.iter_sharded")
    if scan_iters is not None and n_eval > 0:
        return profiled_jit(scan_loop_eval, name="gbdt.scan_eval")
    if scan_iters is not None:
        return profiled_jit(scan_loop, name="gbdt.scan")
    return profiled_jit(one_iter, name="gbdt.iter")


@lru_cache(maxsize=64)
def _cached_step(obj_key, *, cfg, C, lr, boosting, d, cat_idx, ff, bf, bfreq,
                 use_goss, top_rate, other_rate, mesh, axis, model_axis=None,
                 pos_bf=1.0, neg_bf=1.0, sparse_meta=None, renew_alpha=None,
                 scan_iters=None, eval_metric=None, n_eval=0, n_bound=None):
    """Compiled-step cache for built-in objectives (custom fobj / lambdarank
    close over data and stay uncached). Keyed on every static that shapes the
    traced program; jax's own jit cache then dedupes by input shape/dtype."""
    obj_name, num_class, alpha, tweedie, sigmoid = obj_key
    pp = dict(_DEFAULTS, objective=obj_name, num_class=num_class, alpha=alpha,
              tweedie_variance_power=tweedie, sigmoid=sigmoid)
    _, grad_fn = _resolve_objective(pp)
    return _build_step(grad_fn=grad_fn, cfg=cfg, C=C, lr=lr, boosting=boosting,
                       d=d, cat_idx=cat_idx, ff=ff, bf=bf, bfreq=bfreq,
                       use_goss=use_goss, top_rate=top_rate,
                       other_rate=other_rate, mesh=mesh, axis=axis,
                       model_axis=model_axis,
                       pos_bf=pos_bf, neg_bf=neg_bf, sparse_meta=sparse_meta,
                       renew_alpha=renew_alpha,
                       scan_iters=scan_iters, eval_metric=eval_metric,
                       n_eval=n_eval, n_bound=n_bound)


def spmd_trace_pair(n: int = 224, d: int = 24, shards: Optional[int] = None,
                    seed: int = 0):
    """The sparse training step in BOTH configurations, for differential
    static analysis — the shape ``test_sparse_mesh_matches_single_device``
    exercises, reduced to its traceable core. ``n`` deliberately avoids
    multiples of ``d`` so the row count can never alias the flattened
    ``d * n_bins`` cell-table length under the per-line dim renaming (at
    ``n=192=24*8`` the single-device trace accidentally fused the two dims
    and the diff reported a spurious scan-signature hunk).

    ``analysis/rules_spmd.py`` (SMT112/SMT113) and ``tools/spmd_diff.py``
    trace the two callables with ``jax.make_jaxpr`` and diff the
    canonicalized jaxprs: the first structurally divergent region is
    where a mesh-vs-single parity bisection starts. Returns
    ``(mesh, single)`` dicts — ``{"fn", "args"}`` plus the mesh side's
    ``"layout"`` — where ``fn`` is the UNWRAPPED step
    (``ProfiledJit._fn``: the shard_map-wrapped ``sharded_iter`` vs the
    bare ``one_iter``), so tracing never touches the AOT machinery.
    Tracing only — nothing here compiles or runs on devices.
    """
    import jax

    from ..runtime.layout import SpecLayout
    from .sparse import CSRMatrix, build_sparse_binned, shard_sparse_binned

    if shards is None:
        shards = min(4, len(jax.devices()))
    if n % shards:
        raise ValueError(f"n={n} must divide evenly over {shards} shards "
                         f"(wrapped padding would obscure the trace diff)")

    rng = np.random.default_rng(seed)
    mask = rng.random((n, d)) < 0.3
    dense = np.where(mask, rng.normal(size=(n, d)), 0.0)
    indptr = np.zeros(n + 1, np.int64)
    np.cumsum(mask.sum(axis=1), out=indptr[1:])
    csr = CSRMatrix(indptr, np.nonzero(mask)[1], dense[mask], (n, d))
    mapper = BinMapper(max_bin=16).fit_csr(csr)

    cfg = TreeConfig(n_bins=mapper.realized_n_bins, num_leaves=4)
    pp = dict(_DEFAULTS, objective="binary")
    _, grad_fn = _resolve_objective(pp)
    # ff/bf at 1.0: the step touches NO RNG on either side — the mesh step
    # only folds the bagging key per shard when a bagging/GOSS mask is
    # live, so the two traces must now be structurally identical (the gate
    # test + tools/spmd_diff.py golden pin exactly that). n_bound matches
    # train()'s for this shape (n divides shards, so padded == n).
    common = dict(grad_fn=grad_fn, cfg=cfg, C=1, lr=0.1, boosting="gbdt",
                  d=d, cat_idx=None, ff=1.0, bf=1.0, bfreq=0,
                  use_goss=False, top_rate=0.2, other_rate=0.1,
                  model_axis=None, n_bound=1 << max(n - 1, 1).bit_length())

    y = (rng.random(n) < 0.5).astype(np.float32)
    w = np.ones(n, np.float32)
    raw0 = np.zeros((n, 1), np.float32)
    key, fkey = jax.random.PRNGKey(0), jax.random.PRNGKey(1)

    layout = SpecLayout.build(data=shards, model_axis=None)
    sb_host, local = shard_sparse_binned(csr, mapper, shards, (-n) % shards)
    step_mesh = _build_step(mesh=layout, axis=layout.data_axis,
                            sparse_meta=(d, cfg.n_bins, local,
                                         sb_host.max_run), **common)
    step_single = _build_step(mesh=None, axis="data", sparse_meta=None,
                              **common)
    sb_single = build_sparse_binned(csr, mapper)
    mesh_side = {"fn": step_mesh._fn,
                 "args": (sb_host, y, w, raw0, key, fkey),
                 "layout": layout}
    single_side = {"fn": step_single._fn,
                   "args": (sb_single, y, w, raw0, key, fkey)}
    return mesh_side, single_side


def train(params: Dict[str, Any], x: np.ndarray, y: Optional[np.ndarray] = None,
          weight: Optional[np.ndarray] = None,
          eval_set: Optional[Sequence[Tuple[np.ndarray, np.ndarray]]] = None,
          group: Optional[np.ndarray] = None,
          eval_group: Optional[Sequence[np.ndarray]] = None,
          fobj: Optional[Callable] = None,
          mapper: Optional[BinMapper] = None,
          init_booster: Optional[GBDTBooster] = None,
          mesh=None, axis: str = "data",
          callbacks: Optional[Sequence[Callable]] = None,
          feature_names: Optional[List[str]] = None) -> GBDTBooster:
    """Train a booster. ``mesh`` shards rows over ``axis`` (histogram psum).

    ``mesh`` accepts a raw ``jax.sharding.Mesh`` (back-compat) or a
    :class:`~synapseml_tpu.runtime.layout.SpecLayout`. A layout with a
    populated ``model`` axis additionally engages FEATURE-PARALLEL
    histograms (dense, non-voting paths): each model-axis shard builds the
    histogram for its ``d / m`` feature block and stats are ``psum``'d per
    axis (``grow.grow_tree``), so histogram work parallelizes in 2-D.

    ``fobj(score, y, w) -> (grad, hess)`` is the custom-objective hook (reference
    ``FObjTrait``/``updateOneIterationCustom``). ``init_booster`` continues training
    (reference batch/continued training, ``LightGBMBase.scala:46-61``).
    """
    import jax
    import jax.numpy as jnp

    layout = None
    model_axis = None
    if mesh is not None:
        from ..runtime.layout import as_layout

        layout = as_layout(mesh, data_axis=axis)
        mesh, axis = layout.mesh, layout.data_axis
        if layout.model_size > 1:
            model_axis = layout.model_axis

    p = dict(_DEFAULTS)
    params_c = _canonicalize_params(params)
    p.update(params_c)
    obj_name = p["objective"]
    # per-boosting-iteration observability (docs/observability.md): the
    # host-synced loop (dart/eval/callbacks) observes every iteration; the
    # fused lax.scan paths observe whole chunks (one dispatch IS the unit of
    # work there) and count the iterations they contain
    _obs = get_registry()
    _m_iters = _obs.counter(
        "smt_gbdt_iterations_total", "boosting iterations trained",
        ("objective",)).labels(obj_name)
    _m_iter_s = _obs.histogram(
        "smt_gbdt_iteration_seconds",
        "wall time per boosting iteration (host-synced loop)",
        ("objective",)).labels(obj_name)
    _m_chunk_s = _obs.histogram(
        "smt_gbdt_scan_seconds",
        "wall time per fused lax.scan training chunk",
        ("objective",)).labels(obj_name)
    C = int(p["num_class"]) if obj_name in ("multiclass", "softmax") else 1
    from .dataset import GBDTDataset

    from .sparse import as_csr, is_sparse_input

    dataset = x if isinstance(x, GBDTDataset) else None
    if dataset is not None:
        x = dataset.x
        if feature_names is None:
            feature_names = dataset.feature_names
    dev_data = dataset is not None and dataset.is_device
    # sparse (CSR) features — reference treats these as first-class
    # (``DatasetAggregator.scala:84,143-148`` builds CSR native datasets;
    # ``LightGBMBooster.predictForCSR``): route through the sparse grower
    sparse_in = is_sparse_input(x)
    csr = as_csr(x) if sparse_in else None
    y_dev_in = y if isinstance(y, jnp.ndarray) else None
    if y is None:
        if dataset is None or dataset.label_np is None:
            raise ValueError("y is required unless a GBDTDataset carries a "
                             "label (GBDTDataset(x, label=y))")
        y = dataset.label_np
        # the dataset's cached device label serves host-built datasets too:
        # one upload across a whole hyperparameter sweep. Mesh fits keep it
        # only in the device-resident branch (which pads/reshards on
        # device); the host mesh branch pads y in numpy
        y_dev_in = (dataset.label_device()
                    if (mesh is None or dataset.is_device) else None)
    if dev_data:
        # device-resident dataset: the raw matrix never crosses to the host
        # (under a mesh the cached binned buffer reshards device-side);
        # continuation replays the init booster's margins on device (below)
        if mapper is not None and mapper is not dataset.mapper:
            raise ValueError("a device-resident GBDTDataset owns its binning; "
                             "an overriding mapper would need the raw matrix "
                             "on host")
        x_f32_in, x32, x = True, None, None
        n, d = dataset.x.shape
    elif sparse_in:
        x_f32_in, x32, x = False, None, None
        n, d = csr.shape
    else:
        x_f32_in = np.asarray(x).dtype == np.float32
        x32 = np.asarray(x) if x_f32_in else None  # skips a f64->f32 roundtrip
        x = np.asarray(x, dtype=np.float64)
        n, d = x.shape
    y = np.asarray(y, dtype=np.float64)
    w_dev_in = weight if isinstance(weight, jnp.ndarray) else None
    # + 0.0 normalizes a user's -0.0 weights to +0.0: NEGATIVE zero is the
    # in-band mesh-padding sentinel (one_iter zeroes those rows' histogram
    # count), and a user zero weight must keep its count like LightGBM's
    if w_dev_in is not None:
        w_dev_in = w_dev_in + 0.0
    w_np = np.ones(n) if weight is None else \
        np.asarray(weight, dtype=np.float64) + 0.0

    lr_layout = None  # (order, w_mask) group-aligned mesh layout, lambdarank only
    if obj_name == "lambdarank":
        if group is None:
            raise ValueError("objective='lambdarank' requires group (query sizes, "
                             "rows ordered by query)")
        if int(np.sum(group)) != n:
            raise ValueError(f"group sizes sum to {int(np.sum(group))}, expected {n}")
        if mesh is not None:
            # group-aligned sharding (reference repartition-by-group,
            # ``LightGBMRanker.scala:82-109``): whole queries per shard,
            # lambdas local, histograms psum'd like every other objective.
            # Sparse input reorders the CSR host-side before packing the
            # shard blocks; a device-resident dataset reorders ON device
            # (jnp.take by the group order, then reshard) — both below.
            init_fn, grad_fn, lr_order, lr_wmask, lr_local = make_lambdarank_mesh(
                group, int(mesh.shape[axis]), axis,
                truncation=int(p["lambdarank_truncation_level"]),
                sigma=float(p["sigmoid"]))
            lr_layout = (lr_order, lr_wmask)
        else:
            init_fn, grad_fn = make_lambdarank(
                group, truncation=int(p["lambdarank_truncation_level"]),
                sigma=float(p["sigmoid"]))
    else:
        init_fn, grad_fn = _resolve_objective(p)
    # Resolve names -> indices BEFORE sorting: the list may mix indices and
    # names (estimators concatenate categorical_slot_indexes +
    # categorical_slot_names, both settable simultaneously as in the
    # reference API), and sorted() over mixed str/int raises TypeError.
    cat_raw = list(p["categorical_feature"] or [])
    if any(not isinstance(c, (int, np.integer)) for c in cat_raw):
        if not feature_names:
            raise ValueError("categorical_feature names require feature_names")
        cat_raw = [feature_names.index(c) if isinstance(c, str) else int(c)
                   for c in cat_raw]
    cat_features = sorted({int(c) for c in cat_raw})
    if mapper is None:
        if init_booster is not None:
            mapper = init_booster.mapper
        elif dataset is not None:
            # Dataset semantics (LightGBM): the dataset owns binning and
            # overrides the call's max_bin/categorical params
            mapper = dataset.mapper
            import warnings

            if "max_bin" in params_c and \
                    int(params_c["max_bin"]) != dataset.max_bin:
                warnings.warn(
                    f"max_bin={params_c['max_bin']} ignored: the GBDTDataset "
                    f"was binned with max_bin={dataset.max_bin}",
                    stacklevel=2)
            for k, current in (("max_bin_by_feature",
                                mapper.max_bin_by_feature),
                               ("bin_sample_count", mapper.sample_cnt)):
                requested = params_c.get(k)
                if requested is not None and (requested or None) != \
                        (current or None):
                    # only on a real mismatch: estimators always pass their
                    # defaults, which must not warn
                    warnings.warn(
                        f"{k}={requested} ignored: the GBDTDataset owns "
                        "binning (pass binning params to GBDTDataset instead)",
                        stacklevel=2)
            if params_c.get("categorical_feature") and \
                    sorted(cat_features) != sorted(mapper.categorical_features):
                warnings.warn(
                    f"categorical_feature={cat_features} conflicts with the "
                    f"GBDTDataset's {sorted(mapper.categorical_features)}; "
                    "the dataset's binning wins (pass categorical_features "
                    "to GBDTDataset instead)", stacklevel=2)
        else:
            mapper = BinMapper(max_bin=int(p["max_bin"]), seed=int(p["seed"]),
                               sample_cnt=int(p["bin_sample_count"]),
                               max_bin_by_feature=p["max_bin_by_feature"],
                               categorical_features=cat_features)
            mapper = mapper.fit_csr(csr) if sparse_in else mapper.fit(x)
    has_cat = bool(mapper.categorical_features)
    reuse_dataset = dataset is not None and mapper is dataset.mapper
    # Bin on DEVICE when exact: features whose raw values are all
    # f32-representable bin identically via device_bin_cat's floored-f32
    # edges / exact category match (see pack_feature_table), and the
    # vectorized XLA binning replaces the host searchsorted pass — the
    # single largest fixed cost at multi-million-row scale. Under a mesh
    # the binning runs SHARD-LOCAL: raw rows upload under the data spec,
    # the packed edge/category tables replicate, and each shard bins its
    # own block (no host searchsorted exactly where the row count is
    # largest). f64-only values (incl. a PRE-FITTED mapper's non-f32
    # category values) keep the host path.
    from .device_predict import cats_f32_representable

    use_device_bin = (not sparse_in
                      and not reuse_dataset
                      and cats_f32_representable(mapper)
                      and (x_f32_in
                           or bool(np.all(x == x.astype(np.float32)))))
    if reuse_dataset:
        binned_np = dataset.binned_np
    elif sparse_in:
        binned_np = None
    else:
        binned_np = None if use_device_bin else mapper.transform(x)

    raw0_dev = None  # device-resident init margins (device-dataset continuation)
    if init_booster is not None:
        base = init_booster.base_score.copy()
        if dev_data:
            # continued training from a device-resident dataset: raw-margin
            # replay entirely ON DEVICE — the init booster's device binning
            # + jitted tree scan score the dataset's cached float matrix, so
            # the raw features still never cross to the host (reference
            # feeds batch N's model into N+1, ``LightGBMBase.scala:46-61``)
            raw0_dev = init_booster.raw_predict_device(dataset.x)
            raw0 = None
        else:
            raw0 = init_booster.raw_predict(csr if sparse_in else x)
            raw0 = raw0.reshape(n, C)
    else:
        base = np.atleast_1d(np.asarray(init_fn(y, w_np), dtype=np.float64))
        if not p["boost_from_average"]:
            base = np.zeros_like(base)  # LightGBM boost_from_average=false
        # host margin matrix only where it is actually consumed (mesh padding
        # / sharded upload); the non-mesh path builds raw_d on device
        raw0 = np.tile(base, (n, 1)) if mesh is not None else None

    boosting = p["boosting"]
    if boosting not in ("gbdt", "goss", "dart", "rf"):
        raise ValueError(f"boosting must be gbdt|goss|dart|rf, got {boosting!r}")
    if boosting == "dart" and int(p["early_stopping_round"]) > 0:
        # DART keeps rescaling earlier trees after best_iteration, so truncated
        # prediction can't reproduce the margins that early stopping evaluated;
        # LightGBM disallows the combination for the same reason. We train all
        # iterations and never set best_iteration (no truncation).
        import warnings
        warnings.warn("early_stopping_round is ignored with boosting='dart': "
                      "DART rescales earlier trees after the best iteration, so "
                      "truncating at best_iteration is not reproducible",
                      stacklevel=2)
    class_bagging = (float(p["pos_bagging_fraction"]) < 1.0
                     or float(p["neg_bagging_fraction"]) < 1.0)
    if class_bagging and obj_name != "binary":
        # LightGBM: pos/neg_bagging_fraction are binary-only (yv > 0 would
        # silently missample any other objective)
        raise ValueError("pos/neg_bagging_fraction require objective='binary'")
    if boosting == "rf" and not (
            (float(p["bagging_fraction"]) < 1.0 or class_bagging)
            and int(p["bagging_freq"]) > 0):
        # without bagging every rf tree sees identical gradients -> T copies of
        # one tree (LightGBM rejects this config the same way)
        raise ValueError("boosting='rf' requires bagging_fraction < 1.0 (or "
                         "class-aware pos/neg fractions) and bagging_freq > 0")
    lr = float(p["learning_rate"]) if boosting != "rf" else 1.0

    parallelism = p["parallelism"]
    if parallelism not in ("data_parallel", "data", "voting_parallel", "voting"):
        raise ValueError(f"parallelism must be data_parallel|voting_parallel, "
                         f"got {parallelism!r}")
    cfg = TreeConfig(
        # sparse trains in the COMPACT bin space (realized bins only): the
        # transient (d, B, 3) histograms at hashed-text width are sized by
        # what the data actually realizes, not by max_bin
        n_bins=mapper.realized_n_bins if sparse_in else mapper.n_bins,
        num_leaves=int(p["num_leaves"]),
        lambda_l1=float(p["lambda_l1"]), lambda_l2=float(p["lambda_l2"]),
        min_data_in_leaf=float(p["min_data_in_leaf"]),
        min_sum_hessian=float(p["min_sum_hessian_in_leaf"]),
        min_gain_to_split=float(p["min_gain_to_split"]),
        max_depth=int(p["max_depth"]),
        max_delta_step=float(p["max_delta_step"]),
        hist_method=p["hist_method"], hist_chunk=int(p["hist_chunk"]),
        cat_smooth=float(p["cat_smooth"]),
        max_cat_threshold=int(p["max_cat_threshold"]),
        parallelism="voting" if parallelism.startswith("voting") else "data",
        top_k=int(p["top_k"]),
        # multiclass vmaps grow_tree: a vmapped lax.cond/switch runs every
        # branch (~2 full histogram passes/step), so C > 1 keeps the fast
        # path off.  Sparse single-class growth routes through the
        # carried-histogram half-pass in _grow_tree_sparse instead.
        leaf_local=bool(p["leaf_local"]) and not (sparse_in and C > 1),
        leaf_buf_fixed=C > 1,
    )
    cat_mask_np = None
    if has_cat:
        cat_mask_np = np.zeros(d, np.float32)
        cat_mask_np[list(mapper.categorical_features)] = 1.0
    L = cfg.num_leaves
    ff = float(p["feature_fraction"])
    bf = float(p["bagging_fraction"])
    bfreq = int(p["bagging_freq"])
    use_goss = boosting == "goss"
    top_rate, other_rate = float(p["top_rate"]), float(p["other_rate"])

    # -- the jitted per-iteration step --------------------------------------------
    cat_idx = (tuple(sorted(mapper.categorical_features))
               if has_cat else None)
    sparse_meta = None
    sb_host = None
    if sparse_in and mesh is not None:
        # pack the mesh layout now: the in_specs pytree in _build_step must
        # carry the SAME static aux (incl. max_run) as the actual arrays
        from .sparse import shard_sparse_binned

        _ns = mesh.shape[axis]
        if lr_layout is not None:
            # distributed lambdarank over sparse rows: reorder the CSR into
            # the group-aligned layout before packing — lr_order already
            # pads every shard's block to equal length, so no row wrap
            sb_host, _local = shard_sparse_binned(
                csr.take_rows(np.asarray(lr_layout[0])), mapper, _ns, 0)
        else:
            sb_host, _local = shard_sparse_binned(csr, mapper, _ns, (-n) % _ns)
        sparse_meta = (d, cfg.n_bins, _local, sb_host.max_run)
    # percentile leaf renewal (LightGBM RenewTreeOutput): quantile targets
    # its alpha, L1 the median. Under a mesh the percentile would need a
    # global sort across shards; distributed fits keep gradient-ratio
    # leaves (documented behavior difference, matching the engine's
    # single-machine/parallel split)
    renew_alpha = None
    if mesh is None and C == 1 and fobj is None:
        renew_alpha = {"quantile": float(p["alpha"]),
                       "l1": 0.5, "mae": 0.5}.get(obj_name)
    if sparse_in or cfg.parallelism == "voting":
        # feature-parallel histograms need the dense (n, d) block slice and
        # compose with data-parallel growth only; these paths stay
        # data-parallel (model-axis shards replicate, still correct)
        model_axis = None
    # deterministic-histogram rounding bound (see _preround): next power of
    # two over the GLOBAL padded row count. Power-of-two shard counts never
    # push the padded total past the next power of two, so mesh and
    # single-device fits of the same data round on the same grid and grow
    # bit-identical trees.
    if mesh is None:
        _n_glob = n
    elif lr_layout is not None:
        _n_glob = int(lr_local) * int(mesh.shape[axis])
    else:
        _n_glob = n + ((-n) % layout.data_size)
    n_bound = 1 << max(int(_n_glob) - 1, 1).bit_length()
    step_args = dict(cfg=cfg, C=C, lr=lr, boosting=boosting, d=d,
                     cat_idx=cat_idx, ff=ff, bf=bf, bfreq=bfreq,
                     use_goss=use_goss, top_rate=top_rate,
                     other_rate=other_rate, mesh=mesh, axis=axis,
                     model_axis=model_axis,
                     pos_bf=float(p['pos_bagging_fraction']),
                     neg_bf=float(p['neg_bagging_fraction']),
                     sparse_meta=sparse_meta, renew_alpha=renew_alpha,
                     n_bound=n_bound)
    obj_key = (obj_name, C, float(p["alpha"]),
               float(p["tweedie_variance_power"]), float(p["sigmoid"]))
    step_cacheable = fobj is None and obj_name != "lambdarank"

    def make_step(scan_iters=None, eval_metric=None, n_eval=0):
        # Cacheable: the step is a pure function of these hashables, so a
        # second train() with the same config reuses the compiled XLA program
        # instead of re-tracing a fresh closure (compile dominates wall time
        # for short benchmark-style runs).
        if step_cacheable:
            return _cached_step(obj_key, scan_iters=scan_iters,
                                eval_metric=eval_metric, n_eval=n_eval,
                                **step_args)
        return _build_step(grad_fn=grad_fn, fobj=fobj, scan_iters=scan_iters,
                           eval_metric=eval_metric, n_eval=n_eval,
                           **step_args)

    # narrow binned storage: int8/int16 when bins fit — 4x/2x less transfer
    # and HBM traffic for the histogram reads (the engine's bandwidth bound)
    from .binning import bin_dtype as _bin_dtype

    bin_dtype = _bin_dtype(mapper.n_bins)

    if mesh is not None:
        n_shards = layout.data_size
        pad = (-n) % n_shards
        data_spec = layout.batch()
        dev_put = layout.put
        if dev_data:
            # device-resident dataset: RESHARD on device (device->device
            # collective placement, no host round-trip); padding rows wrap
            # to the front with zero weight
            def dpad(a, fill_first=True):
                # fill_first=False is the WEIGHT column: padding rows carry
                # -0.0, the sentinel one_iter uses to zero their histogram
                # count (a user's +0.0 weight still counts, like LightGBM)
                if pad:
                    a = jnp.concatenate(
                        [a, a[:pad] if fill_first else
                         jnp.full((pad,) + a.shape[1:], -0.0, a.dtype)],
                        axis=0)
                return a
            if lr_layout is not None:
                # distributed lambdarank from a device dataset: the group
                # reorder runs ON device (jnp.take by the group-aligned
                # order — the raw features never cross to the host); padding
                # slots get the -0.0 sentinel through the zeroed mask
                _lr_ord = jnp.asarray(lr_layout[0])
                _lr_msk = jnp.asarray(lr_layout[1], jnp.float32)

                def dpad(a, fill_first=True):
                    a = jnp.take(a, _lr_ord, axis=0)
                    if not fill_first:
                        a = jnp.where(_lr_msk == 0, jnp.float32(-0.0),
                                      a * _lr_msk)
                    return a
            binned_d = dev_put(dpad(dataset.device_binned()), data_spec)
            y_d = dev_put(dpad(
                y_dev_in.astype(jnp.float32) if y_dev_in is not None
                else jnp.asarray(y, jnp.float32)), data_spec)
            w_d = dev_put(dpad(
                jnp.ones(n, jnp.float32) if weight is None
                else (w_dev_in.astype(jnp.float32) if w_dev_in is not None
                      else jnp.asarray(w_np, jnp.float32)),
                fill_first=False), data_spec)
            raw_d = dev_put(dpad(
                raw0_dev.astype(jnp.float32) if raw0_dev is not None
                else jnp.zeros((n, C), jnp.float32)
                + jnp.asarray(base, jnp.float32)), data_spec)
        elif sparse_in:
            # equal row blocks, per-block entries packed and padded
            # (sparse.py layout, hoisted to sb_host above); padding rows wrap
            # to the front with zero weight, matching the dense convention
            from .sparse import SparseBinned

            sb = sb_host
            binned_d = SparseBinned(
                rows=dev_put(sb.rows, data_spec),
                bins=dev_put(sb.bins, data_spec),
                ends=dev_put(sb.ends, data_spec),
                starts=dev_put(sb.starts, data_spec),
                zero_bin=dev_put(sb.zero_bin, layout.replicated()),
                d=sb.d, n_bins=sb.n_bins, n=sb.n, max_run=sb.max_run)
            if lr_layout is not None:
                # group-aligned layout: the CSR was packed in lr_order above;
                # permute labels/weights/margins to match (padding slots get
                # the -0.0 sentinel via the zeroed mask)
                lr_order, lr_wmask = lr_layout
                y = y[lr_order]
                w_np = np.where(lr_wmask == 0, -0.0,
                                w_np[lr_order] * lr_wmask)
                raw0 = raw0[lr_order]
            elif pad:
                y = np.concatenate([y, y[:pad]])
                # -0.0: padding sentinel (zero weight AND zero hist count)
                w_np = np.concatenate([w_np, np.full(pad, -0.0)])
                raw0 = np.concatenate([raw0, raw0[:pad]], axis=0)
            y_d = dev_put(y.astype(np.float32), data_spec)
            w_d = dev_put(w_np.astype(np.float32), data_spec)
            raw_d = dev_put(raw0.astype(np.float32), data_spec)
        else:
            x_up = None
            if use_device_bin:
                # raw f32 rows go up instead of host-binned codes; the
                # padding/reorder below applies to whichever matrix ships
                x_up = np.ascontiguousarray(
                    x32 if x32 is not None else x.astype(np.float32))
            if lr_layout is not None:
                # lambdarank group-aligned layout: shard s's block holds its
                # whole queries (+ -0.0-weight padding); the grad fn's group
                # tables are in these LOCAL coordinates
                lr_order, lr_wmask = lr_layout
                if use_device_bin:
                    x_up = x_up[lr_order]
                else:
                    binned_np = binned_np[lr_order]
                y = y[lr_order]
                w_np = np.where(lr_wmask == 0, -0.0,
                                w_np[lr_order] * lr_wmask)
                raw0 = raw0[lr_order]
            elif pad:
                if use_device_bin:
                    x_up = np.concatenate([x_up, x_up[:pad]], axis=0)
                else:
                    binned_np = np.concatenate([binned_np, binned_np[:pad]],
                                               axis=0)
                y = np.concatenate([y, y[:pad]])
                # -0.0: padding sentinel (zero weight AND zero hist count)
                w_np = np.concatenate([w_np, np.full(pad, -0.0)])
                raw0 = np.concatenate([raw0, raw0[:pad]], axis=0)
            if use_device_bin:
                # device-side distributed binning: rows shard over ``data``,
                # the packed edge/category tables replicate, and each shard
                # bins its own block through the same vectorized XLA kernel
                # as the single-device path — so mesh and host-bin fits see
                # identical bin codes (the parity tests pin the trees
                # bit-identical).
                # The packed edge tables stay REPLICATED even on an fsdp
                # layout (no store-over-fsdp): every shard reads every
                # feature's edges every binning step (rows x all features),
                # so a row-sharded table would all-gather per step and save
                # nothing between steps — the table is (d, max_bins+1) f32,
                # orders of magnitude under the weight tensors the fsdp
                # axis exists for, and binning is one-shot per fit anyway.
                from .device_predict import device_bin_cat, pack_feature_table

                table, lens, cat_flags = pack_feature_table(mapper)
                rep_spec = layout.replicated()
                # cat_flags stays on HOST: it is static kernel-selection
                # metadata (device_bin_cat specializes on it), not data
                bin_shard = layout.shard_map(
                    lambda xb, t, ln: device_bin_cat(
                        xb, t, ln, cat_flags,
                        mapper.missing_bin).astype(bin_dtype),
                    in_specs=(data_spec, rep_spec, rep_spec),
                    out_specs=data_spec, check=False)
                binned_d = bin_shard(dev_put(x_up, data_spec),
                                     dev_put(table, rep_spec),
                                     dev_put(lens, rep_spec))
            else:
                binned_d = dev_put(binned_np.astype(bin_dtype), data_spec)
            y_d = dev_put(y.astype(np.float32), data_spec)
            w_d = dev_put(w_np.astype(np.float32), data_spec)
            raw_d = dev_put(raw0.astype(np.float32), data_spec)
    else:
        if sparse_in:
            from .sparse import build_sparse_binned

            binned_d = (dataset.device_binned() if reuse_dataset
                        else build_sparse_binned(csr, mapper))
        elif reuse_dataset:
            binned_d = dataset.device_binned()  # uploaded once, reused
        elif use_device_bin:
            from .device_predict import device_bin_cat, pack_feature_table

            table, lens, cat_flags = pack_feature_table(mapper)
            xb = jnp.asarray(np.ascontiguousarray(
                x32 if x32 is not None else x.astype(np.float32)))
            binned_d = device_bin_cat(
                xb, table, lens, cat_flags,
                mapper.missing_bin).astype(bin_dtype)
        else:
            binned_d = jnp.asarray(binned_np.astype(bin_dtype))
        # y that arrived as a device array stays put; unit weights and the
        # constant base margin are constructed ON device (at multi-million
        # rows these uploads otherwise rival the feature matrix itself)
        y_d = (y_dev_in.astype(jnp.float32) if y_dev_in is not None
               else jnp.asarray(y, dtype=jnp.float32))
        w_d = (jnp.ones(n, jnp.float32) if weight is None
               else w_dev_in.astype(jnp.float32) if w_dev_in is not None
               else jnp.asarray(w_np, dtype=jnp.float32))
        if init_booster is None:
            raw_d = (jnp.zeros((n, C), jnp.float32)
                     + jnp.asarray(base, jnp.float32))
        elif raw0_dev is not None:
            raw_d = raw0_dev.astype(jnp.float32)
        else:
            raw_d = jnp.asarray(raw0, dtype=jnp.float32)

    # -- eval / early stopping state ----------------------------------------------
    if obj_name == "lambdarank":
        metric_name = f"ndcg@{int(p['ndcg_at'])}"
        ndcg_fn = _metric_ndcg(int(p["ndcg_at"]))
        metric_fn = None
        higher_better = True
        if eval_set and (eval_group is None or len(eval_group) != len(eval_set)):
            raise ValueError("lambdarank eval_set requires matching eval_group")
    else:
        metric_name = p["metric"] or _DEFAULT_METRIC.get(obj_name, "l2")
        if metric_name not in METRICS:
            raise ValueError(f"unknown metric {metric_name!r}; "
                             f"available: {sorted(METRICS)}")
        metric_fn, higher_better = METRICS[metric_name]
    evals: List[Dict[str, Any]] = []
    eval_binned = []
    if eval_set:
        for ex, ey in eval_set:
            if isinstance(ex, GBDTDataset):
                ex = ex.x  # symmetric with the x handling above
            if is_sparse_input(ex):
                from .sparse import build_sparse_binned

                if not sparse_in:
                    # compact eval bins against dense-space tree thresholds
                    # would misroute missing values
                    raise ValueError("sparse eval_set requires sparse "
                                     "training features")
                ecsr = as_csr(ex)
                e_n = ecsr.shape[0]
                if init_booster is not None:
                    eraw0 = init_booster.raw_predict(ecsr).reshape(
                        e_n, C).astype(np.float64)
                else:
                    eraw0 = np.tile(base, (e_n, 1))
                eval_binned.append((build_sparse_binned(ecsr, mapper),
                                    np.asarray(ey, dtype=np.float64), eraw0))
                continue
            ex = np.asarray(ex, dtype=np.float64)
            if init_booster is not None:  # continued training: seed with prior trees
                eraw0 = init_booster.raw_predict(ex).reshape(len(ex), C).astype(np.float64)
            else:
                eraw0 = np.tile(base, (len(ex), 1))
            eval_binned.append((mapper.transform(ex), np.asarray(ey, dtype=np.float64),
                               eraw0))
    best_metric = -np.inf if higher_better else np.inf
    best_iter = 0
    patience = 0 if boosting == "dart" else int(p["early_stopping_round"])
    min_delta = float(p["early_stopping_min_delta"])

    def check_early_stop(it, rec):
        """Shared stop bookkeeping for the device-eval and host loops; returns
        True when training should stop after iteration ``it``."""
        nonlocal best_metric, best_iter, stopped_early
        m = rec[f"eval0_{metric_name}"]
        improved = (m > best_metric + min_delta) if higher_better \
            else (m < best_metric - min_delta)
        if improved:
            best_metric, best_iter = m, it + 1
        elif patience and it + 1 - best_iter >= patience:
            stopped_early = True
        return stopped_early

    # dart state
    rng = np.random.default_rng(int(p["seed"]))
    dart_drop_rate = float(p["drop_rate"])
    dart_max_drop = int(p["max_drop"])
    dart_skip = float(p["skip_drop"])
    dart_uniform = bool(p["uniform_drop"])
    dart_xgb_mode = bool(p["xgboost_dart_mode"])

    trees_host: List[Any] = []
    tree_scales: List[float] = []

    def host_binned():
        """Host copy of the binned matrix, pulled lazily — only dart's
        drop/re-add bookkeeping replays trees host-side."""
        nonlocal binned_np
        if binned_np is None:
            binned_np = np.asarray(binned_d, dtype=np.int32)
        return binned_np

    def predict_tree_binned(tr, binned_mat, c):
        if not isinstance(binned_mat, np.ndarray):
            # sparse eval_set under the host loop (callbacks / mesh / dart /
            # host-only metric): the eval matrix is a SparseBinned — replay
            # the tree on DEVICE over the binned triple (tree bins and the
            # triple share the compact bin space; no dense host matrix ever
            # materializes), same path replay_tree uses for training rows
            from .grow import GrownTree, predict_binned as _pb

            gt = GrownTree(tr.parent[c], tr.feature[c], tr.bin[c],
                           tr.gain[c], tr.leaf_value[c], tr.leaf_hess[c],
                           tr.cat_set[c])
            node = np.asarray(_pb(gt, binned_mat))
            return tr.leaf_value[c][node]
        node = np.zeros(binned_mat.shape[0], dtype=np.int32)
        par, feat, bins = tr.parent[c], tr.feature[c], tr.bin[c]
        cat = tr.cat_set[c]
        for s in range(par.shape[0]):
            if par[s] < 0:
                continue
            col = binned_mat[:, feat[s]]
            go_left = cat[s][col] > 0 if bins[s] < 0 else col <= bins[s]
            go_right = (node == par[s]) & ~go_left
            node[go_right] = s + 1
        return tr.leaf_value[c][node]

    _sparse_replay_mesh = None

    def replay_tree(tr, c):
        """(n,) leaf values of one stored tree — dart's drop/re-add replay.

        Dense: numpy replay over the host binned matrix. Sparse: device
        replay straight over the binned triple (``predict_binned`` gathers
        each split's column from the SparseBinned — tree bins and the triple
        share the compact bin space, so no host matrix ever materializes).
        Under a mesh the triple's row ids are LOCAL to each shard block, so
        the replay runs under ``shard_map`` (tree replicated, nodes come
        back row-sharded over ``data`` at the padded global length)."""
        if not sparse_in:
            return predict_tree_binned(tr, host_binned(), c)
        from .grow import GrownTree, predict_binned as _pb

        gt = GrownTree(tr.parent[c], tr.feature[c], tr.bin[c], tr.gain[c],
                       tr.leaf_value[c], tr.leaf_hess[c], tr.cat_set[c])
        if mesh is not None:
            nonlocal _sparse_replay_mesh
            if _sparse_replay_mesh is None:
                from .sparse import SparseBinned

                sb = binned_d
                rep = layout.replicated()
                sb_spec = SparseBinned(
                    rows=data_spec, bins=data_spec, ends=data_spec,
                    starts=data_spec, zero_bin=rep,
                    d=sb.d, n_bins=sb.n_bins, n=sb.n, max_run=sb.max_run)
                # jit for the call cache: every dropped tree replays through
                # the ONE compiled program instead of re-tracing per tree
                _sparse_replay_mesh = jax.jit(layout.shard_map(
                    _pb, in_specs=(rep, sb_spec), out_specs=data_spec,
                    check=False))
            node = np.asarray(_sparse_replay_mesh(gt, binned_d))
        else:
            node = np.asarray(_pb(gt, binned_d))
        return tr.leaf_value[c][node]

    key = jax.random.PRNGKey(int(p["seed"]))
    bkey = jax.random.PRNGKey(int(p["bagging_seed"]))  # separate bagging stream
    num_iter = int(p["num_iterations"])
    stopped_early = False

    # Only dart bookkeeping, per-iteration eval, and user callbacks need the
    # tree on the HOST mid-loop. Without them the ENTIRE loop runs as one
    # lax.scan program — a single dispatch instead of one per iteration (the
    # host round-trip dominates wall time on tunneled/remote backends).
    sync_each_iter = bool(eval_binned) or boosting == "dart" or bool(callbacks)

    # Eval/early-stopping WITHOUT dart/callbacks: run chunked device scans —
    # margins and metrics stay on device; only a (chunk, n_eval) metric panel
    # crosses to host for the early-stop decisions between chunks. Under a
    # mesh the eval sets replicate (see the scan_eval_sharded wrap): mesh
    # training with an eval_set no longer round-trips predictions through
    # the host every iteration.
    use_device_eval = (bool(eval_binned) and boosting != "dart"
                       and not callbacks
                       and metric_fn is not None
                       and _dev_metric(metric_name) is not None)
    if use_device_eval and num_iter > 0:
        if mesh is not None:
            _rep = layout.replicated()

            def _eput(a):
                return dev_put(a, _rep)
        else:
            def _eput(a):
                return a
        eval_dev = [(_eput(eb) if sparse_in
                     else _eput(jnp.asarray(eb.astype(bin_dtype))),
                     _eput(jnp.asarray(ey, jnp.float32)),
                     _eput(jnp.ones(len(ey), jnp.float32)),
                     _eput(jnp.asarray(eraw0, jnp.float32)))
                    for eb, ey, eraw0 in eval_binned]
        base_d = jnp.asarray(base, jnp.float32)
        # small fixed chunk: the whole chunk is trained before stop decisions
        # apply, so chunk size only bounds the (truncated) overshoot
        chunk = num_iter if patience == 0 else min(num_iter, 32)
        # at most two programs: the full chunk and one tail remainder
        loop_full = make_step(scan_iters=chunk, eval_metric=metric_name,
                              n_eval=len(eval_dev))
        it0 = 0
        while it0 < num_iter and not stopped_early:
            k_iters = min(chunk, num_iter - it0)
            loop_fn = (loop_full if k_iters == chunk else
                       make_step(scan_iters=k_iters, eval_metric=metric_name,
                                 n_eval=len(eval_dev)))
            _sw = StopWatch()
            _sw.start()
            trees_stacked, raw_d, eraws, mseries, key = loop_fn(
                binned_d, y_d, w_d, raw_d, key, bkey, jnp.int32(it0),
                base_d, tuple(eval_dev))
            eval_dev = [(eb, ey, ew, eraw)
                        for (eb, ey, ew, _), eraw in zip(eval_dev, eraws)]
            stacked_np = jax.device_get(trees_stacked)
            _sw.stop()  # device_get is the completion barrier
            _m_chunk_s.observe(_sw.elapsed_s)
            _m_iters.inc(k_iters)
            trees_host += [jax.tree.map(lambda a, i=i: a[i], stacked_np)
                           for i in range(k_iters)]
            mnp = np.asarray(mseries)  # (k_iters, n_eval)
            for j in range(k_iters):
                it = it0 + j
                rec = {"iteration": it}
                for ei in range(len(eval_dev)):
                    rec[f"eval{ei}_{metric_name}"] = float(mnp[j, ei])
                evals.append(rec)
                if check_early_stop(it, rec):
                    # truncate the overshoot so the booster matches the host
                    # loop's stop point exactly
                    trees_host = trees_host[: it + 1]
                    evals = evals[: it + 1]
                    break
            it0 += k_iters
        tree_scales = [1.0] * len(trees_host)
        num_iter = 0  # host loop below is skipped

    if not sync_each_iter and num_iter > 0:
        loop_fn = make_step(scan_iters=num_iter)
        _sw = StopWatch()
        _sw.start()
        trees_stacked, raw_d = loop_fn(binned_d, y_d, w_d, raw_d, key, bkey)
        stacked_np = jax.device_get(trees_stacked)  # each field (T, C, ...)
        _sw.stop()  # device_get is the completion barrier
        _m_chunk_s.observe(_sw.elapsed_s)
        _m_iters.inc(num_iter)
        trees_host = [jax.tree.map(lambda a, i=i: a[i], stacked_np)
                      for i in range(num_iter)]
        tree_scales = [1.0] * num_iter
        num_iter = 0  # host loop below is skipped

    step = make_step() if num_iter > 0 else None
    for it in range(num_iter):
        key, k2 = jax.random.split(key)
        # LightGBM re-bags every bagging_freq iterations and reuses the bag
        # in between; GOSS resamples every iteration
        period = it if use_goss else (it // max(bfreq, 1))
        k1 = jax.random.fold_in(bkey, period)

        dart_dropped: List[int] = []
        if boosting == "dart" and trees_host and rng.random() >= dart_skip:
            u = rng.random(len(trees_host))
            if dart_uniform:
                mask = u < dart_drop_rate
            else:
                # LightGBM default: drop probability proportional to tree
                # weight (heavier trees drop more often), expected count
                # matching drop_rate (dart.cpp DroppingTrees)
                w = np.asarray(tree_scales, np.float64)
                inv_avg = len(w) / max(w.sum(), 1e-12)
                mask = u < dart_drop_rate * w * inv_avg
            dart_dropped = list(np.nonzero(mask)[0][:dart_max_drop])
            if dart_dropped:
                # remove dropped trees from raw score before fitting the new tree
                raw_np = np.array(raw_d)
                for t in dart_dropped:
                    for c in range(C):
                        raw_np[:, c] -= lr * tree_scales[t] * replay_tree(
                            trees_host[t], c)
                raw_d = _reput(raw_np, raw_d)

        _sw = StopWatch()
        _sw.start()
        trees, raw_d = step(binned_d, y_d, w_d, raw_d, k1, k2)
        # the no-sync case runs the scan fast-path above; this loop only
        # exists for dart/eval/callbacks, which all need host trees
        tree_np = jax.tree.map(np.asarray, trees)
        trees_host.append(tree_np)
        _sw.stop()  # the np.asarray pull is the completion barrier
        _m_iter_s.observe(_sw.elapsed_s)
        _m_iters.inc()

        scale = 1.0
        if boosting == "dart" and dart_dropped:
            k_d = len(dart_dropped)
            if dart_xgb_mode:
                # xgboost normalization: new tree lr/(k+lr), dropped k/(k+lr)
                scale = 1.0 / (k_d + lr)
                factor = k_d / (k_d + lr)
            else:
                scale = 1.0 / (k_d + 1)
                factor = k_d / (k_d + 1.0)
            # normalize: dropped trees keep ``factor`` of their weight
            raw_np = np.array(raw_d)
            for c in range(C):
                raw_np[:, c] -= (1.0 - scale) * lr * replay_tree(tree_np, c)
            for t in dart_dropped:
                old = tree_scales[t]
                tree_scales[t] = old * factor
                for c in range(C):
                    raw_np[:, c] += lr * old * factor * replay_tree(
                        trees_host[t], c)
                    # keep eval margins in sync with the rescaled trees
                    for eb, _ey, eraw in eval_binned:
                        eraw[:, c] += lr * old * (factor - 1.0) * predict_tree_binned(
                            trees_host[t], eb, c)
            raw_d = _reput(raw_np, raw_d)
        tree_scales.append(scale)

        # eval + early stopping
        if eval_binned:
            rec = {"iteration": it}
            for ei, (eb, ey, eraw) in enumerate(eval_binned):
                for c in range(C):
                    eraw[:, c] += lr * scale * predict_tree_binned(tree_np, eb, c)
                if boosting == "rf":  # rf averages trees instead of summing
                    eavg = np.tile(base, (len(ey), 1)) + (eraw - base) / (it + 1)
                    escore = eavg[:, 0] if C == 1 else eavg
                else:
                    escore = eraw[:, 0] if C == 1 else eraw
                ew = np.ones(len(ey))
                if metric_fn is None:  # ndcg needs query groups
                    rec[f"eval{ei}_{metric_name}"] = ndcg_fn(ey, escore, ew,
                                                            eval_group[ei])
                else:
                    rec[f"eval{ei}_{metric_name}"] = metric_fn(ey, escore, ew)
            evals.append(rec)
            check_early_stop(it, rec)
        if callbacks:
            # a truthy callback return requests a stop AFTER this iteration
            # (the tuning scheduler's rung-demotion hook): the booster keeps
            # every tree trained so far, exactly like early stopping
            stop_requested = False
            for cb in callbacks:
                if cb({"iteration": it, "evals": evals[-1] if evals else None}):
                    stop_requested = True
            if stop_requested:
                break
        if stopped_early:
            break

    # -- assemble host model --------------------------------------------------------
    # (the scan fast-path already pulled trees to host in one batched
    # device_get; the host loop pulls per iteration for dart/eval/callbacks)
    T = len(trees_host)
    parent = np.stack([t.parent for t in trees_host]) if T else np.zeros((0, C, L - 1), np.int32)
    feature = np.stack([t.feature for t in trees_host]) if T else np.zeros((0, C, L - 1), np.int32)
    bins = np.stack([t.bin for t in trees_host]) if T else np.zeros((0, C, L - 1), np.int32)
    gain = np.stack([t.gain for t in trees_host]) if T else np.zeros((0, C, L - 1), np.float32)
    leaf_value = np.stack([t.leaf_value for t in trees_host]) if T else np.zeros((0, C, L), np.float32)
    leaf_hess = np.stack([t.leaf_hess for t in trees_host]) if T else np.zeros((0, C, L), np.float32)
    cat_stack = None
    if has_cat:
        cat_stack = (np.stack([t.cat_set for t in trees_host]).astype(np.int8)
                     if T else np.zeros((0, C, L - 1, mapper.n_bins), np.int8))
        if cat_stack.shape[-1] < mapper.n_bins:
            # sparse trees grow in the COMPACT bin space; the booster predicts
            # from full-space codes (category codes coincide in both spaces,
            # only the missing bin is remapped) — pad the set rows and move
            # the compact missing bin's membership to the full missing bin
            Bc = cat_stack.shape[-1]
            padded = np.zeros(cat_stack.shape[:-1] + (mapper.n_bins,), np.int8)
            padded[..., : Bc - 1] = cat_stack[..., : Bc - 1]
            padded[..., mapper.missing_bin] = cat_stack[..., Bc - 1]
            cat_stack = padded
    threshold = np.zeros(parent.shape, dtype=np.float64)
    for t in range(T):
        for c in range(C):
            for s in range(L - 1):
                if parent[t, c, s] >= 0:
                    threshold[t, c, s] = mapper.bin_upper_value(
                        int(feature[t, c, s]), bins[t, c, s])

    scales = np.asarray(tree_scales, dtype=np.float64) * (lr if boosting != "rf" else 1.0)
    booster = GBDTBooster(
        mapper=mapper, objective=obj_name, num_class=C, base_score=base,
        parent=parent, feature=feature, threshold=threshold, bin_=bins, gain=gain,
        leaf_value=leaf_value, leaf_hess=leaf_hess, tree_scale=scales,
        boosting=boosting,
        best_iteration=best_iter if (patience and eval_binned) else None,
        feature_names=list(feature_names) if feature_names else None,
        cat_set=cat_stack,
    )
    if init_booster is not None and init_booster.num_trees:
        booster = _merge_boosters(init_booster, booster)
    booster.evals_result = evals  # type: ignore[attr-defined]
    return booster


from ..core.serialization import register_state_class

register_state_class(GBDTBooster)


def _reput(raw_np, raw_d):
    import jax

    sharding = getattr(raw_d, "sharding", None)
    if sharding is not None:
        return jax.device_put(raw_np.astype(np.float32), sharding)
    import jax.numpy as jnp

    return jnp.asarray(raw_np, dtype=jnp.float32)


def _merge_boosters(a: GBDTBooster, b: GBDTBooster) -> GBDTBooster:
    """Concatenate tree lists — reference ``mergeBooster``/continued training."""
    if a.num_class != b.num_class or a.objective != b.objective:
        raise ValueError("cannot merge boosters with different objective/num_class")
    return GBDTBooster(
        mapper=b.mapper, objective=b.objective, num_class=b.num_class,
        base_score=a.base_score,
        parent=np.concatenate([a.parent, b.parent]),
        feature=np.concatenate([a.feature, b.feature]),
        threshold=np.concatenate([a.threshold, b.threshold]),
        bin_=np.concatenate([a.bin, b.bin]),
        gain=np.concatenate([a.gain, b.gain]),
        leaf_value=np.concatenate([a.leaf_value, b.leaf_value]),
        leaf_hess=np.concatenate([a.leaf_hess, b.leaf_hess]),
        tree_scale=np.concatenate([a.tree_scale, b.tree_scale]),
        boosting=b.boosting, best_iteration=None, feature_names=b.feature_names,
        cat_set=_merge_cat_sets(a, b),
    )


def _merge_cat_sets(a: GBDTBooster, b: GBDTBooster):
    if a.cat_set is None and b.cat_set is None:
        return None

    def expand(x: GBDTBooster):
        if x.cat_set is not None:
            return x.cat_set
        other = a.cat_set if x is b else b.cat_set
        shape = (x.parent.shape[0],) + other.shape[1:]
        return np.zeros(shape, dtype=np.int8)

    return np.concatenate([expand(a), expand(b)])
