"""Device-resident batched booster inference.

Reference analogue: ``LightGBMBooster.predictForMat/score`` dispatching into the
C++ predictor (``LightGBMBooster.scala:510,529``). TPU design: the trained
model is a stack of replay-list trees (T, C, S) — prediction replays every
split of every tree with vectorized gathers, scanning over trees so the raw
score accumulates in a fixed (n, C) buffer. One jit per (T, C, S, n-bucket)
shape; rows are padded to the next power-of-two bucket to bound recompiles.

All decisions happen on BINNED features (int comparisons + category-set
lookups), exactly matching training — so device and host predictions are
bit-identical, and categorical splits need no float thresholds.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Optional, Tuple

import numpy as np

__all__ = ["device_leaf_indices", "device_raw_scores"]


@lru_cache(maxsize=64)
def _leaf_kernel(T: int, C: int, S: int, has_cat: bool):
    import jax
    import jax.numpy as jnp
    from jax import lax

    def one_tree(binned, par, feat, bins, cat_set):
        # par/feat/bins (S,); cat_set (S, B) int8 or (S, 1) dummy
        n = binned.shape[0]

        def step(node, s):
            p = par[s]
            col = jnp.take(binned, feat[s], axis=1)
            if has_cat:
                in_set = jnp.take(cat_set[s], col) > 0
                is_cat = bins[s] < 0
                go_left = jnp.where(is_cat, in_set, col <= bins[s])
            else:
                go_left = col <= bins[s]
            go_right = (node == p) & (p >= 0) & ~go_left
            return jnp.where(go_right, s + 1, node), None

        node, _ = lax.scan(step, jnp.zeros(n, jnp.int32), jnp.arange(S))
        return node

    @jax.jit
    def kernel(binned, parent, feature, bins, cat_set):
        # parent (T,C,S) ... -> leaf index (T, C, n)
        per_class = jax.vmap(jax.vmap(
            lambda p, f, b, cs: one_tree(binned, p, f, b, cs)))
        return per_class(parent, feature, bins, cat_set)

    return kernel


@lru_cache(maxsize=64)
def _score_kernel(T: int, C: int, S: int, has_cat: bool):
    import jax
    import jax.numpy as jnp
    from jax import lax

    def one_tree(binned, par, feat, bins, cat_set, leaf_value):
        n = binned.shape[0]

        def step(node, s):
            p = par[s]
            col = jnp.take(binned, feat[s], axis=1)
            if has_cat:
                in_set = jnp.take(cat_set[s], col) > 0
                is_cat = bins[s] < 0
                go_left = jnp.where(is_cat, in_set, col <= bins[s])
            else:
                go_left = col <= bins[s]
            go_right = (node == p) & (p >= 0) & ~go_left
            return jnp.where(go_right, s + 1, node), None

        node, _ = lax.scan(step, jnp.zeros(n, jnp.int32), jnp.arange(S))
        return jnp.take(leaf_value, node)  # (n,)

    @jax.jit
    def kernel(binned, parent, feature, bins, cat_set, leaf_value, scale):
        # scan over trees: acc (n, C) += scale_t * leaf_values
        n = binned.shape[0]

        def body(acc, xs):
            par, feat, bins_t, cs, lv, sc = xs
            vals = jax.vmap(lambda p, f, b, c, v: one_tree(binned, p, f, b, c, v))(
                par, feat, bins_t, cs, lv)        # (C, n)
            return acc + sc * vals.T, None

        acc, _ = lax.scan(
            body, jnp.zeros((n, C), jnp.float32),
            (parent, feature, bins, cat_set, leaf_value,
             scale.astype(jnp.float32)))
        return acc

    return kernel


def _pad_rows(binned: np.ndarray) -> Tuple[np.ndarray, int]:
    """Pad row count up to a power-of-two bucket (>=256) to bound recompiles."""
    n = binned.shape[0]
    bucket = 256
    while bucket < n:
        bucket *= 2
    if bucket == n:
        return binned, n
    pad = np.zeros((bucket - n, binned.shape[1]), dtype=binned.dtype)
    return np.concatenate([binned, pad], axis=0), n


def _cat_or_dummy(cat_set: Optional[np.ndarray], T: int, C: int, S: int):
    if cat_set is None:
        return np.zeros((T, C, S, 1), dtype=np.int8), False
    return cat_set, True


def device_leaf_indices(binned: np.ndarray, parent: np.ndarray,
                        feature: np.ndarray, bins: np.ndarray,
                        cat_set: Optional[np.ndarray] = None) -> np.ndarray:
    """(n, d) binned -> (T, C, n) leaf index, computed on device."""
    T, C, S = parent.shape
    cs, has_cat = _cat_or_dummy(cat_set, T, C, S)
    padded, n = _pad_rows(np.ascontiguousarray(binned, dtype=np.int32))
    k = _leaf_kernel(T, C, S, has_cat)
    out = k(padded, parent.astype(np.int32), feature.astype(np.int32),
            bins.astype(np.int32), cs.astype(np.int8))
    return np.asarray(out)[:, :, :n]


def device_raw_scores(binned: np.ndarray, parent: np.ndarray,
                      feature: np.ndarray, bins: np.ndarray,
                      leaf_value: np.ndarray, scale: np.ndarray,
                      cat_set: Optional[np.ndarray] = None) -> np.ndarray:
    """(n, d) binned -> (n, C) sum over trees of scale_t * leaf_value."""
    T, C, S = parent.shape
    cs, has_cat = _cat_or_dummy(cat_set, T, C, S)
    padded, n = _pad_rows(np.ascontiguousarray(binned, dtype=np.int32))
    k = _score_kernel(T, C, S, has_cat)
    out = k(padded, parent.astype(np.int32), feature.astype(np.int32),
            bins.astype(np.int32), cs.astype(np.int8),
            leaf_value.astype(np.float32), np.asarray(scale, np.float64))
    return np.asarray(out)[:n]


def cats_f32_representable(mapper) -> bool:
    """True when every category value survives an f32 round-trip — the
    precondition for device categorical binning (host fallback otherwise)."""
    for vals in mapper.cat_values.values():
        v64 = np.asarray(vals, dtype=np.float64)
        if not np.array_equal(v64.astype(np.float32).astype(np.float64), v64):
            return False
    return True


def pack_feature_table(mapper) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Per-feature bin tables -> padded (d, Emax) f32 matrix + (d,) lengths
    + (d,) categorical flags. Numeric rows hold upper edges; categorical
    rows hold the SORTED category values (exact-match lookup on device).
    Padding is +inf, which never compares below a finite value, so the
    device bin computation needs no per-feature masking.

    BinMapper's edges are float64; the device path compares in float32, so
    each edge is rounded DOWN to the nearest f32 (never up). The host bin is
    the count of f64 edges strictly below ``v``; for f32-representable ``v``,
    ``floor_f32(e64) < v  ⟺  e64 < v``: if ``e64 < v`` then
    ``floor_f32(e64) ≤ e64 < v``; if ``v ≤ e64`` then ``v``, being an f32
    no greater than ``e64``, satisfies ``v ≤ floor_f32(e64)``. Rounding up
    would break the second case when the rounded edge lands exactly on a
    data value (e.g. midpoint edges between adjacent f32 values).

    Category values must be exactly f32-representable (integer codes are);
    a lossy value would break the device equality test, so it raises —
    callers that can fall back to host binning should gate on
    :func:`cats_f32_representable` first."""
    edges = mapper.upper_edges
    sizes = [len(mapper.cat_values[j]) if j in mapper.cat_values else len(e)
             for j, e in enumerate(edges)]
    emax = max(max(sizes), 1)
    out = np.full((len(edges), emax), np.inf, dtype=np.float32)
    lens = np.empty(len(edges), dtype=np.int32)
    cat_flags = np.zeros(len(edges), dtype=np.int8)
    for j, e in enumerate(edges):
        if j in mapper.cat_values:
            vals = np.asarray(mapper.cat_values[j], dtype=np.float64)
            v32 = vals.astype(np.float32)
            if not np.array_equal(v32.astype(np.float64), vals):
                raise ValueError(
                    f"categorical feature {j} has values not exactly "
                    "f32-representable; device binning would mis-code them")
            out[j, : len(vals)] = v32
            lens[j] = len(vals)
            cat_flags[j] = 1
            continue
        e64 = np.asarray(e, dtype=np.float64)
        e32 = e64.astype(np.float32)
        floored = np.where(e32.astype(np.float64) > e64,
                           np.nextafter(e32, np.float32(-np.inf)), e32)
        out[j, : len(e)] = floored
        lens[j] = len(e)
    return out, lens, cat_flags


def device_bin_cat(x, table, lens, cat_flags, missing_bin: int):
    """(n, d) float features -> (n, d) int32 bins, entirely on device.

    Matches ``BinMapper.transform`` for f32-representable raw values (see
    the rounding note on :func:`pack_feature_table`). Numeric features:
    count of edges strictly below ``v``, clamped to the last bin.
    Categorical: the code is the position of the EXACT match among the
    sorted category values — ``count(vals < v) != count(vals <= v)``
    detects membership without a gather — unseen values and NaN land in the
    missing bin (and therefore follow the right branch, matching
    ``BinMapper.transform_column``). The kernel specializes on whether any
    categorical feature exists: the ``<=`` reduction is a second full pass
    over (n, d, E) and must not tax purely-numeric multi-million-row
    ingest.

    ``cat_flags`` is STATIC model metadata (it selects which kernel to
    build) and must be a host array — never a traced value. Keeping it on
    host is what lets the whole function run under an outer ``jax.jit``
    (e.g. a fused featurizer->GBDT pipeline step): only ``x`` may be a
    tracer."""
    import jax
    import jax.numpy as jnp

    if isinstance(cat_flags, jax.core.Tracer):
        raise TypeError(
            "device_bin_cat: cat_flags is static model metadata and must be "
            "a host (numpy) array, not a traced jax value — pass the numpy "
            "cat_flags from pack_feature_table directly")
    cat_flags_np = np.asarray(cat_flags)
    has_cat = bool(cat_flags_np.any())
    kern = _device_bin_cat_kernel(int(missing_bin), has_cat)
    if has_cat:
        return kern(jnp.asarray(x), jnp.asarray(table), jnp.asarray(lens),
                    jnp.asarray(cat_flags_np))
    return kern(jnp.asarray(x), jnp.asarray(table), jnp.asarray(lens))


@lru_cache(maxsize=16)
def _device_bin_cat_kernel(missing_bin: int, has_cat: bool):
    # jitted: run eagerly, the (n, d, E) broadcast compares materialize in
    # HBM op-by-op (tens of GB and tens of seconds at multi-million rows);
    # under jit XLA fuses them into the reductions
    import jax
    import jax.numpy as jnp

    @jax.jit
    def run_cat(x, table, lens, cat_flags):
        lt = (table[None, :, :] < x[:, :, None]).sum(-1).astype(jnp.int32)
        le = (table[None, :, :] <= x[:, :, None]).sum(-1).astype(jnp.int32)
        num_bins = jnp.minimum(lt, lens[None, :] - 1)
        cat_bins = jnp.where(lt != le, lt, missing_bin)
        bins = jnp.where(cat_flags[None, :] > 0, cat_bins, num_bins)
        return jnp.where(jnp.isfinite(x), bins, missing_bin).astype(jnp.int32)

    @jax.jit
    def run_num(x, table, lens):
        lt = (table[None, :, :] < x[:, :, None]).sum(-1).astype(jnp.int32)
        bins = jnp.minimum(lt, lens[None, :] - 1)
        return jnp.where(jnp.isfinite(x), bins, missing_bin).astype(jnp.int32)

    return run_cat if has_cat else run_num
