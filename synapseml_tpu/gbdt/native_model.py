"""LightGBM native text-model interop.

Reference: ``saveNativeModel``/``setModelString``
(``lightgbm/.../LightGBMBooster.scala:454``, ``LightGBMModelMethods.scala``) —
the reference round-trips boosters through LightGBM's text model format. Here
the format is implemented directly, which buys two-way interop:

- :func:`booster_to_native` exports a trained :class:`GBDTBooster` as
  LightGBM text a stock LightGBM install can load and predict with;
- :func:`booster_from_native` imports a real LightGBM text model into a
  :class:`GBDTBooster`, so existing LightGBM models get this framework's
  device-resident prediction/serving path.

Structure mapping: this engine's trees are replay lists (split ``s`` turns
leaf-slot ``parent[s]`` into slots ``(parent[s], s+1)``); LightGBM's are
pointer trees (``left_child``/``right_child``, negative = ~leaf). The two are
interconvertible for any binary tree by replaying splits parent-first. Split
semantics match exactly: numerical ``value <= threshold`` goes left, NaN
follows the right branch (``missing_type=NaN``, ``default_left=False``).
Import builds a synthetic :class:`BinMapper` whose per-feature edges are the
model's own thresholds — ``value <= t`` ⇔ ``bin(value) <= bin(t)`` holds
exactly, so the binned replay path (device predict included) reproduces the
pointer-tree decisions bit-for-bit.

Categorical splits round-trip too (r4): export writes LightGBM's bitset
encoding — ``decision_type`` bit 0 set, the split's ``threshold`` is an
index into ``cat_boundaries``/``cat_threshold`` uint32 words whose bits are
the LEFT-going category values — and import decodes it back into this
engine's per-split ``cat_set`` membership rows.

``default_left`` (r5): a numeric split that routes missing LEFT is encoded
as a per-split SET over the feature's bin ids — ``{bins <= threshold} ∪
{missing bin}`` — reusing the categorical ``cat_set`` machinery (``bin ==
-1`` + membership row), with the float threshold kept so export writes the
split back as ``threshold`` + the ``default_left`` decision bit. Every
predict path (host, device, TreeSHAP) already dispatches per-split on
``bin < 0``, so real-world LightGBM models trained on data with missing
values load and predict bit-for-bit.

``zero_as_missing`` models (missing_type=Zero) import exactly too (r5):
features carrying such splits get a dedicated ZERO-BAND bin — synthetic
edges at ``(nextafter(-1e-35), +1e-35]`` reproduce LightGBM's
``|v| <= kZeroThreshold`` test in bin space — and the band (plus NaN,
which the native predictor converts to 0.0 first) routes by the split's
``default_left`` bit via the same set encoding. One caveat: RE-exporting a
zero_as_missing import writes the NaN-missing ``default_left`` form, so
the re-exported text predicts zeros by threshold under stock LightGBM;
this engine's own predictions stay exact.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from .binning import BinMapper

__all__ = ["booster_to_native", "booster_from_native"]

# LightGBM decision_type bit field: bit0 categorical, bit1 default_left,
# bits 2-3 missing_type (0 none, 1 zero, 2 NaN)
_DT_CATEGORICAL = 1
_DT_DEFAULT_LEFT = 2
_DT_MISSING_ZERO = 1 << 2
_DT_MISSING_NAN = 2 << 2
_DT_MISSING_MASK = 3 << 2


def _fmt(v: float) -> str:
    return repr(float(v))


# ---------------------------------------------------------------------------------
# export
# ---------------------------------------------------------------------------------

def _replay_to_pointer(parent, feature, threshold, gain, leaf_value,
                       leaf_hess, bins=None, cat_set=None, cat_values=None):
    """One replay-list tree -> LightGBM pointer arrays (leaves re-indexed
    densely in slot order).

    ``bins``/``cat_set``/``cat_values``: when the tree has categorical
    splits (``bins[s] == -1``), each becomes a bitset threshold — the
    split's ``threshold`` is its index into ``cat_boundaries`` and the
    uint32 ``cat_threshold`` words carry the LEFT-going category VALUES
    (``cat_set`` is over bin ids; ``cat_values[feature]`` maps them back to
    raw categories, which must be non-negative integers as LightGBM
    requires)."""
    steps = [s for s in range(parent.shape[0]) if parent[s] >= 0]
    if not steps:  # stump: single leaf
        return dict(num_leaves=1, split_feature=[], split_gain=[],
                    threshold=[], decision_type=[], left_child=[],
                    right_child=[], leaf_value=[float(leaf_value[0])],
                    leaf_weight=[float(leaf_hess[0])],
                    num_cat=0, cat_boundaries=[0], cat_threshold=[])
    # internal node ids = positions in `steps`; slots -> current tree attach
    # point: (internal id, 'l'|'r') whose child pointer tracks the slot
    internal_of_step = {s: i for i, s in enumerate(steps)}
    left = [0] * len(steps)
    right = [0] * len(steps)
    link: Dict[int, tuple] = {}  # slot -> (internal id, side)
    for i, s in enumerate(steps):
        p = int(parent[s])
        if p in link:
            j, side = link[p]
            if side == "l":
                left[j] = i
            else:
                right[j] = i
        link[p] = (i, "l")
        link[s + 1] = (i, "r")
    # remaining links are leaves; dense leaf ids in slot order
    slots = sorted(link)
    leaf_id = {slot: n for n, slot in enumerate(slots)}
    for slot, (j, side) in link.items():
        enc = ~leaf_id[slot]  # LightGBM: negative child = ~leaf index
        if side == "l":
            left[j] = enc
        else:
            right[j] = enc
    thresholds: List[float] = []
    decision_types: List[int] = []
    cat_boundaries = [0]
    cat_threshold: List[int] = []
    for s in steps:
        if bins is not None and int(bins[s]) < 0 and \
                np.isfinite(threshold[s]):
            # numeric set-split (an imported missing-direction split):
            # write back as threshold + the direction bit read from the
            # set's MISSING-bin membership (the last bin), so default-right
            # zero_as_missing imports don't flip their NaN routing
            thresholds.append(float(threshold[s]))
            left_bit = _DT_DEFAULT_LEFT if cat_set[s][-1] else 0
            decision_types.append(_DT_MISSING_NAN | left_bit)
            continue
        if bins is not None and int(bins[s]) < 0:  # categorical split
            f = int(feature[s])
            vals = cat_values.get(f)
            if vals is None:
                raise ValueError(f"split on feature {f} is categorical but "
                                 "the mapper has no category values for it")
            vals = np.asarray(vals)
            if not np.array_equal(vals, np.round(vals)) or vals.min() < 0:
                raise ValueError(
                    f"categorical feature {f} has non-integer or negative "
                    "category values; LightGBM bitsets need codes >= 0 "
                    "(use to_json for arbitrary categories)")
            if cat_set[s][-1]:
                # only the MISSING bin (last) is observable at predict time
                # among the beyond-code bins — the grower's rank-prefix can
                # park it on the left side, which LightGBM bitsets cannot
                # express: NaN/unseen will route right in the exported model
                # (LightGBM's own not-in-bitset behavior). Zero-mass bins in
                # (len(vals), missing) are unreachable and need no warning.
                import warnings

                warnings.warn(
                    f"categorical split on feature {f}: missing/unseen "
                    "values routed left in training but LightGBM bitsets "
                    "route them right; exported model differs on such rows",
                    stacklevel=3)
            left_vals = vals[np.flatnonzero(
                cat_set[s][: len(vals)])].astype(np.int64)
            n_words = (int(vals.max()) // 32) + 1 if len(vals) else 1
            words = [0] * n_words
            for v in left_vals:
                words[v // 32] |= 1 << (v % 32)
            thresholds.append(float(len(cat_boundaries) - 1))
            decision_types.append(_DT_CATEGORICAL | _DT_MISSING_NAN)
            cat_threshold.extend(words)
            cat_boundaries.append(len(cat_threshold))
        else:
            thresholds.append(float(threshold[s]))
            decision_types.append(_DT_MISSING_NAN)
    return dict(
        num_leaves=len(slots),
        split_feature=[int(feature[s]) for s in steps],
        split_gain=[float(gain[s]) for s in steps],
        threshold=thresholds,
        decision_type=decision_types,
        left_child=left, right_child=right,
        leaf_value=[float(leaf_value[slot]) for slot in slots],
        leaf_weight=[float(leaf_hess[slot]) for slot in slots],
        num_cat=len(cat_boundaries) - 1,
        cat_boundaries=cat_boundaries, cat_threshold=cat_threshold,
    )


def booster_to_native(booster) -> str:
    """Serialize a :class:`GBDTBooster` as a LightGBM text model."""
    T, C = booster.parent.shape[:2]
    d = booster.mapper.n_features or (int(booster.feature.max()) + 1
                                      if booster.feature.size else 1)
    names = booster.feature_names or [f"Column_{j}" for j in range(d)]
    obj = {"binary": "binary sigmoid:1",
           "multiclass": "multiclass num_class:%d" % booster.num_class,
           "softmax": "multiclass num_class:%d" % booster.num_class,
           "regression": "regression",
           }.get(booster.objective, booster.objective)
    rf = booster.boosting == "rf"
    lines = [
        "tree",
        "version=v3",
        f"num_class={booster.num_class}",
        f"num_tree_per_iteration={booster.num_class}",
        "label_index=0",
        f"max_feature_idx={d - 1}",
        f"objective={obj}",
        "feature_names=" + " ".join(names),
        "feature_infos=" + " ".join(["[-inf:inf]"] * d),
    ]
    if rf:
        lines.append("average_output")
    lines.append("")

    for t in range(booster.num_trees):
        for c in range(C):
            tree = _replay_to_pointer(
                booster.parent[t, c], booster.feature[t, c],
                booster.threshold[t, c], booster.gain[t, c],
                booster.leaf_value[t, c], booster.leaf_hess[t, c],
                bins=(booster.bin[t, c]
                      if booster.cat_set is not None else None),
                cat_set=(booster.cat_set[t, c]
                         if booster.cat_set is not None else None),
                cat_values=booster.mapper.cat_values)
            # fold shrinkage/dart scale into leaf values; fold base_score in
            # (first tree per class normally; EVERY tree under rf averaging)
            sc = float(booster.tree_scale[t])
            add = float(booster.base_score[c]) if (t == 0 or rf) else 0.0
            vals = [v * sc + add for v in tree["leaf_value"]]
            lines += [
                f"Tree={t * C + c}",
                f"num_leaves={tree['num_leaves']}",
                f"num_cat={tree['num_cat']}",
                "split_feature=" + " ".join(map(str, tree["split_feature"])),
                "split_gain=" + " ".join(map(_fmt, tree["split_gain"])),
                "threshold=" + " ".join(map(_fmt, tree["threshold"])),
                "decision_type=" + " ".join(map(str, tree["decision_type"])),
                "left_child=" + " ".join(map(str, tree["left_child"])),
                "right_child=" + " ".join(map(str, tree["right_child"])),
                "leaf_value=" + " ".join(map(_fmt, vals)),
                "leaf_weight=" + " ".join(map(_fmt, tree["leaf_weight"])),
            ]
            if tree["num_cat"]:
                lines += [
                    "cat_boundaries=" + " ".join(
                        map(str, tree["cat_boundaries"])),
                    "cat_threshold=" + " ".join(
                        map(str, tree["cat_threshold"])),
                ]
            lines += ["shrinkage=1", ""]
    lines += ["end of trees", ""]
    return "\n".join(lines)


# ---------------------------------------------------------------------------------
# import
# ---------------------------------------------------------------------------------

def _parse_kv(block: List[str]) -> Dict[str, str]:
    out = {}
    for line in block:
        if "=" in line:
            k, _, v = line.partition("=")
            out[k.strip()] = v.strip()
        elif line.strip():
            out[line.strip()] = ""
    return out


def _pointer_to_replay(num_leaves, split_feature, threshold, split_gain,
                       left_child, right_child, leaf_value, leaf_weight,
                       max_leaves):
    """Pointer tree -> replay arrays sized to ``max_leaves`` slots.

    Also returns ``node_of_step`` (the pointer-tree internal node each
    replay step came from) so callers can look up per-node side tables —
    the categorical bitset decode needs it."""
    L1 = max_leaves - 1
    parent = np.full(L1, -1, np.int32)
    feat = np.zeros(L1, np.int32)
    thr = np.zeros(L1, np.float64)
    gain = np.zeros(L1, np.float32)
    lv = np.zeros(max_leaves, np.float32)
    lh = np.zeros(max_leaves, np.float32)
    node_of_step = np.full(L1, -1, np.int32)
    if num_leaves == 1:
        lv[0] = leaf_value[0]
        lh[0] = leaf_weight[0] if leaf_weight is not None else 0.0
        return parent, feat, thr, gain, lv, lh, node_of_step
    # replay order: walk internal nodes parent-first (BFS from root node 0);
    # slot bookkeeping inverts the export mapping
    slot_of_node = {0: 0}  # internal node -> slot it currently splits
    order: List[int] = []
    queue = [0]
    while queue:
        nd = queue.pop(0)
        order.append(nd)
        s = len(order) - 1  # replay step index
        p_slot = slot_of_node[nd]
        parent[s] = p_slot
        feat[s] = split_feature[nd]
        thr[s] = threshold[nd]
        gain[s] = split_gain[nd] if split_gain is not None else 0.0
        node_of_step[s] = nd
        for child, child_slot in ((left_child[nd], p_slot),
                                  (right_child[nd], s + 1)):
            if child >= 0:
                slot_of_node[child] = child_slot
                queue.append(child)
            else:
                leaf = ~child if child < 0 else child
                lv[child_slot] = leaf_value[leaf]
                if leaf_weight is not None:
                    lh[child_slot] = leaf_weight[leaf]
    return parent, feat, thr, gain, lv, lh, node_of_step


def booster_from_native(model_str: str):
    """Parse a LightGBM text model into a :class:`GBDTBooster`."""
    from .boost import GBDTBooster

    text = model_str.replace("\r\n", "\n")
    if not text.lstrip().startswith("tree"):
        raise ValueError("not a LightGBM text model (missing 'tree' header)")
    body = text.split("end of trees")[0]
    chunks = body.split("Tree=")
    header = _parse_kv(chunks[0].splitlines())
    num_class = int(header.get("num_class", 1))
    per_iter = int(header.get("num_tree_per_iteration", num_class))
    d = int(header["max_feature_idx"]) + 1
    obj_field = header.get("objective", "regression").split()
    objective = {"binary": "binary", "multiclass": "multiclass",
                 "multiclassova": "multiclass",
                 "regression_l1": "l1"}.get(obj_field[0], obj_field[0])
    average_output = "average_output" in header
    feature_names = (header.get("feature_names") or "").split() or None

    trees = []
    for chunk in chunks[1:]:
        kv = _parse_kv(chunk.splitlines())
        nl = int(kv["num_leaves"])
        ints = lambda key: [int(x) for x in kv.get(key, "").split()]
        flts = lambda key: ([float(x) for x in kv.get(key, "").split()]
                            or None)
        dts = ints("decision_type")
        trees.append(dict(
            num_leaves=nl, split_feature=ints("split_feature"),
            threshold=flts("threshold") or [],
            split_gain=flts("split_gain"),
            left_child=ints("left_child"), right_child=ints("right_child"),
            leaf_value=flts("leaf_value") or [0.0],
            leaf_weight=flts("leaf_weight"),
            decision_type=dts,
            cat_boundaries=ints("cat_boundaries") or [0],
            cat_threshold=ints("cat_threshold")))
    if not trees:
        raise ValueError("model has no trees")
    if len(trees) % per_iter:
        raise ValueError(f"{len(trees)} trees not divisible by "
                         f"num_tree_per_iteration={per_iter}")

    def _is_cat_split(tr, node: int) -> bool:
        dts = tr["decision_type"]
        return bool(dts and node < len(dts) and dts[node] & _DT_CATEGORICAL)

    def _bitset_values(tr, cat_idx: int) -> List[int]:
        lo = tr["cat_boundaries"][cat_idx]
        hi = tr["cat_boundaries"][cat_idx + 1]
        vals = []
        for wi, w in enumerate(tr["cat_threshold"][lo:hi]):
            b = 0
            while w:
                if w & 1:
                    vals.append(wi * 32 + b)
                w >>= 1
                b += 1
        return vals

    # synthetic BinMapper: per-feature edges = the model's own thresholds,
    # so 'value <= t' == 'bin(value) <= bin(t)' exactly; categorical
    # features get their category codes from the union of the model's own
    # bitsets (unseen values -> missing bin -> right branch, the LightGBM
    # not-in-bitset behavior)
    thr_by_feat: List[set] = [set() for _ in range(d)]
    cat_vals_by_feat: Dict[int, set] = {}
    zero_feats: set = set()  # features with any missing_type=Zero split
    for tr in trees:
        for node, (f, t) in enumerate(zip(tr["split_feature"],
                                          tr["threshold"])):
            if _is_cat_split(tr, node):
                cat_vals_by_feat.setdefault(f, set()).update(
                    _bitset_values(tr, int(t)))
            else:
                thr_by_feat[f].add(float(t))
                dts = tr["decision_type"]
                if node < len(dts) and \
                        (dts[node] & _DT_MISSING_MASK) == _DT_MISSING_ZERO:
                    zero_feats.add(f)
    # zero_as_missing features get a dedicated ZERO-BAND bin: edges at
    # (nextafter(-kZeroThreshold, -inf), +kZeroThreshold] reproduce
    # LightGBM's |v| <= 1e-35 zero test exactly in bin space, so the
    # band can be routed per split like the missing bin
    _KZERO = 1e-35
    for f in zero_feats:
        thr_by_feat[f].add(float(np.nextafter(-_KZERO, -np.inf)))
        thr_by_feat[f].add(_KZERO)
    max_cat = max((len(v) for v in cat_vals_by_feat.values()), default=0)
    mapper = BinMapper(
        max_bin=max(2, max((len(s) + 1) for s in thr_by_feat), max_cat),
        categorical_features=sorted(cat_vals_by_feat))
    mapper.upper_edges = [
        (np.array([np.inf]) if j in cat_vals_by_feat else
         np.concatenate([np.sort(np.array(sorted(s), np.float64)), [np.inf]]))
        for j, s in enumerate(thr_by_feat)]
    mapper.cat_values = {
        f: np.array(sorted(v), np.float64)
        for f, v in cat_vals_by_feat.items()}
    mapper.n_features = d

    T = len(trees) // per_iter
    C = per_iter
    max_leaves = max(tr["num_leaves"] for tr in trees)
    max_leaves = max(max_leaves, 2)
    shape1 = (T, C, max_leaves - 1)
    parent = np.full(shape1, -1, np.int32)
    feature = np.zeros(shape1, np.int32)
    threshold = np.zeros(shape1, np.float64)
    bin_ = np.zeros(shape1, np.int32)
    gain = np.zeros(shape1, np.float32)
    leaf_value = np.zeros((T, C, max_leaves), np.float32)
    leaf_hess = np.zeros((T, C, max_leaves), np.float32)
    B = mapper.n_bins

    def _needs_set_split(dt: int, thr: float) -> bool:
        """True when the split routes some bin against its threshold order
        and therefore needs the bin-set encoding."""
        if dt & _DT_CATEGORICAL:
            return False  # LightGBM cat splits route NaN/unseen right
        mt = dt & _DT_MISSING_MASK
        if mt == _DT_MISSING_ZERO:
            return True  # the zero band routes by default_left, not by t
        if mt == _DT_MISSING_NAN:
            return bool(dt & _DT_DEFAULT_LEFT)
        # missing_type=None: NaN converts to 0.0 before the compare
        return 0.0 <= thr

    any_set_split = any(
        _needs_set_split(dt, thr)
        for tr in trees
        for dt, thr in zip(tr["decision_type"], tr["threshold"]))
    cat_set = (np.zeros(shape1 + (B,), np.int8)
               if cat_vals_by_feat or any_set_split else None)
    for idx, tr in enumerate(trees):
        t, c = divmod(idx, C)
        (parent[t, c], feature[t, c], threshold[t, c], gain[t, c],
         leaf_value[t, c], leaf_hess[t, c], node_of_step) = \
            _pointer_to_replay(
                tr["num_leaves"], tr["split_feature"], tr["threshold"],
                tr["split_gain"], tr["left_child"], tr["right_child"],
                tr["leaf_value"], tr["leaf_weight"], max_leaves)
        for s in range(max_leaves - 1):
            nd = int(node_of_step[s])
            if nd < 0:
                continue
            f = int(feature[t, c, s])
            dt = (tr["decision_type"][nd]
                  if nd < len(tr["decision_type"]) else _DT_MISSING_NAN)
            if _is_cat_split(tr, nd):
                # LightGBM categorical splits route NaN/unseen RIGHT
                # regardless of default_left (not-in-bitset rule)
                vals = mapper.cat_values[f]
                left = _bitset_values(tr, int(tr["threshold"][nd]))
                codes = np.searchsorted(vals, np.asarray(left, np.float64))
                cat_set[t, c, s, codes] = 1
                bin_[t, c, s] = -1
                threshold[t, c, s] = np.nan
                continue
            # bin = position of the threshold in the feature's edges
            b = int(np.searchsorted(mapper.upper_edges[f],
                                    threshold[t, c, s]))
            if not _needs_set_split(dt, threshold[t, c, s]):
                bin_[t, c, s] = b
                continue
            # set encoding over the feature's bins; threshold kept for
            # re-export (NaN-missing default_left form; a re-exported
            # zero_as_missing model keeps OUR predictions exact, but its
            # zeros route by threshold under stock LightGBM)
            cat_set[t, c, s, : b + 1] = 1
            if (dt & _DT_MISSING_MASK) == _DT_MISSING_ZERO:
                # EVERY bin inside [-kZero, +kZero] (and NaN, which the
                # native predictor converts to 0.0) routes by default_left
                # regardless of the threshold order. A model threshold can
                # fall inside the band (LightGBM emits -kZero as a bin
                # upper bound under zero_as_missing), fragmenting it into
                # several bins — mark the whole [first, last] band range.
                go_left = bool(dt & _DT_DEFAULT_LEFT)
                edges = mapper.upper_edges[f]
                zb_lo = int(np.searchsorted(edges, -_KZERO))
                zb_hi = int(np.searchsorted(edges, _KZERO))
                cat_set[t, c, s, zb_lo: zb_hi + 1] = 1 if go_left else 0
                cat_set[t, c, s, mapper.missing_bin] = 1 if go_left else 0
            else:
                # NaN-missing (default_left) or None (NaN -> 0.0 <= t)
                cat_set[t, c, s, mapper.missing_bin] = 1
            bin_[t, c, s] = -1
    return GBDTBooster(
        mapper=mapper, objective=objective, num_class=num_class,
        base_score=np.zeros(num_class),
        parent=parent, feature=feature, threshold=threshold, bin_=bin_,
        gain=gain, leaf_value=leaf_value, leaf_hess=leaf_hess,
        tree_scale=np.ones(T, np.float64),
        boosting="rf" if average_output else "gbdt",
        feature_names=feature_names,
        cat_set=cat_set,
    )
