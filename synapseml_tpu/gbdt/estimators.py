"""LightGBM-style estimator stages over Tables.

Rebuild of ``lightgbm/src/main/scala/.../lightgbm/``:
- ``LightGBMClassifier`` (``LightGBMClassifier.scala:26``) — binary/multiclass with
  probability / rawPrediction / leafPrediction / featuresShap output columns;
- ``LightGBMRegressor`` (``LightGBMRegressor.scala:38``) — regression objectives
  incl. quantile/huber/poisson/tweedie;
- ``LightGBMRanker`` (``LightGBMRanker.scala:25``) — lambdarank over a group column.

Params keep the reference names (snake_case): the shared surface of
``params/LightGBMParams.scala`` — boosting_type, num_iterations, learning_rate,
num_leaves, max_bin, bagging/feature fractions, lambdas, early stopping, etc.
``use_barrier_execution_mode`` is accepted for API parity (SPMD shard_map is
gang-scheduled by construction); distribution is the ``mesh`` param (rows shard
over the mesh 'data' axis). ``parallelism='data_parallel'`` allreduces full
histograms (psum replacing the reference's socket ring); ``'voting_parallel'``
runs the PV-tree vote + candidate-only reduce (reference
``LightGBMParams.scala:16-30``) — see ``grow.py``.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..core import (ColumnSpec, ComplexParam, Estimator, Model, Param, Table,
                    TableSchema)
from ..core.params import ParamValidators
from .boost import GBDTBooster, train

__all__ = [
    "LightGBMClassifier", "LightGBMClassificationModel",
    "LightGBMRegressor", "LightGBMRegressionModel",
    "LightGBMRanker", "LightGBMRankerModel",
]


def _features_matrix(table: Table, col: str, num_bits: int = 18):
    """Dense (n, d) matrix — or a :class:`CSRMatrix` when the column is a
    sparse (indices, values) column (the VW featurizer's output, marked with
    ``vw_sparse`` meta). The reference's ``matrixType=auto`` plays the same
    role: sparse vectors stay sparse into the native dataset
    (``DatasetAggregator.scala:84``)."""
    from ..core.table import features_matrix

    arr = table.column(col)
    if arr.dtype == object:
        meta = table.meta.get(col, {})
        first = next((v for v in arr if v is not None), None)
        if meta.get("type") == "vw_sparse" or (
                isinstance(first, tuple) and len(first) == 2
                and isinstance(first[0], np.ndarray)):
            from .sparse import CSRMatrix

            return CSRMatrix.from_pairs(arr, num_bits=num_bits)
    return features_matrix(arr)


class _LightGBMBase(Estimator):
    """Shared params (reference ``LightGBMParams.scala``) + fit plumbing
    (``LightGBMBase.train:43`` / ``innerTrain:447``)."""

    _abstract_stage = True

    features_col = Param("features column (vector)", str, default="features")
    label_col = Param("label column", str, default="label")
    prediction_col = Param("prediction output column", str, default="prediction")
    weight_col = Param("optional sample-weight column", str, default=None)
    validation_indicator_col = Param(
        "optional bool column marking validation rows (reference "
        "validationIndicatorCol)", str, default=None)
    init_score_col = Param("optional initial raw-score column", str, default=None)
    leaf_prediction_col = Param("optional leaf-index output column", str, default=None)
    features_shap_col = Param("optional per-feature contribution output column",
                              str, default=None)
    sparse_num_bits = Param("hash-mask bits for sparse (indices, values) "
                            "feature columns (the VW featurizer's output): "
                            "d = 2^b", int, default=18)

    boosting_type = Param("gbdt | rf | dart | goss", str, default="gbdt",
                          validator=ParamValidators.in_list(["gbdt", "rf", "dart", "goss"]))
    num_iterations = Param("boosting iterations", int, default=100,
                           validator=ParamValidators.gt_eq(0))
    learning_rate = Param("shrinkage rate", float, default=0.1,
                          validator=ParamValidators.gt(0))
    num_leaves = Param("max leaves per tree", int, default=31,
                       validator=ParamValidators.gt(1))
    max_depth = Param("max tree depth, <= 0 unlimited (reference maxDepth)",
                      int, default=-1)
    max_delta_step = Param("clamp leaf outputs, 0 = off (reference "
                           "maxDeltaStep)", float, default=0.0)
    boost_from_average = Param("start from the label average (reference "
                               "boostFromAverage)", bool, default=True)
    max_bin = Param("max histogram bins per feature", int, default=255,
                    validator=ParamValidators.gt(1))
    max_bin_by_feature = Param("per-feature max_bin overrides (reference "
                               "maxBinByFeature; empty = max_bin)", list,
                               default=[])
    bin_sample_count = Param("rows sampled for bin-edge estimation (reference "
                             "binSampleCount)", int, default=200_000,
                             validator=ParamValidators.gt(0))
    bagging_fraction = Param("row subsample fraction", float, default=1.0)
    pos_bagging_fraction = Param("positive-row subsample fraction (reference "
                                 "posBaggingFraction)", float, default=1.0)
    neg_bagging_fraction = Param("negative-row subsample fraction (reference "
                                 "negBaggingFraction)", float, default=1.0)
    bagging_freq = Param("bag every k iterations (0 = off)", int, default=0)
    bagging_seed = Param("bagging seed", int, default=3)
    feature_fraction = Param("feature subsample fraction per tree", float, default=1.0)
    lambda_l1 = Param("L1 regularization", float, default=0.0)
    lambda_l2 = Param("L2 regularization", float, default=0.0)
    min_sum_hessian_in_leaf = Param("min hessian mass per leaf", float, default=1e-3)
    min_data_in_leaf = Param("min rows per leaf", int, default=20)
    min_gain_to_split = Param("min split gain", float, default=0.0)
    early_stopping_round = Param("stop after k rounds without improvement (0 = off)",
                                 int, default=0)
    improvement_tolerance = Param("min metric delta counted as improvement "
                                  "(reference improvementTolerance)", float, default=0.0)
    top_rate = Param("goss: top-gradient keep fraction", float, default=0.2)
    other_rate = Param("goss: small-gradient sample fraction", float, default=0.1)
    drop_rate = Param("dart: tree dropout rate", float, default=0.1)
    max_drop = Param("dart: max trees dropped per iteration", int, default=50)
    skip_drop = Param("dart: probability of skipping dropout", float, default=0.5)
    uniform_drop = Param("dart: drop uniformly instead of weight-proportional "
                         "(reference uniformDrop)", bool, default=False)
    xgboost_dart_mode = Param("dart: xgboost normalization lr/(k+lr) "
                              "(reference xgboostDartMode)", bool, default=False)
    metric = Param("eval metric name ('' = objective default)", str, default="")
    parallelism = Param("data_parallel (full histogram allreduce) | "
                        "voting_parallel (PV-tree: top-k feature vote + "
                        "candidate-only reduce)", str, default="data_parallel",
                        validator=ParamValidators.in_list(
                            ["data_parallel", "voting_parallel"]))
    top_k = Param("voting_parallel: local vote size (global select 2k; "
                  "reference topK)", int, default=20,
                  validator=ParamValidators.gt(0))
    categorical_slot_names = Param("feature names treated as categorical "
                                   "(reference categoricalSlotNames)", list,
                                   default=[])
    categorical_slot_indexes = Param("feature indices treated as categorical "
                                     "(reference categoricalSlotIndexes)", list,
                                     default=[])
    cat_smooth = Param("categorical split smoothing (reference catSmooth)",
                       float, default=10.0)
    max_cat_threshold = Param("max categories in the left set of a categorical "
                              "split (reference maxCatThreshold)", int, default=32)
    use_barrier_execution_mode = Param("accepted for API parity (gang scheduling is "
                                       "implicit in SPMD)", bool, default=False)
    num_batches = Param("split training into k sequential batches with model "
                        "continuation (reference numBatches)", int, default=0)
    seed = Param("random seed", int, default=0)
    verbosity = Param("verbosity", int, default=-1)
    mesh = ComplexParam("optional jax Mesh for distributed training", object,
                        default=None)

    _objective_default = "regression"

    objective = Param("training objective", str, default="regression")

    # -- static schema (SparkML transformSchema analogue) -------------------

    # features: a dense vector column OR a sparse (indices, values) object
    # column (the VW featurizer's output) — dtype class stays open
    _FEATURES_SPEC = ColumnSpec("any", "vector")

    def input_schema(self) -> TableSchema:
        cols = {self.features_col: self._FEATURES_SPEC,
                self.label_col: ColumnSpec("float", "scalar")}
        if self.weight_col:
            cols[self.weight_col] = ColumnSpec("float", "scalar")
        if self.validation_indicator_col:
            cols[self.validation_indicator_col] = ColumnSpec("any", "scalar")
        if self.init_score_col:
            cols[self.init_score_col] = ColumnSpec("float", "any")
        return TableSchema(cols)

    def _prediction_schema(self, schema: TableSchema) -> TableSchema:
        """Columns every fitted model appends (subclasses add theirs)."""
        out = schema.with_column(self.prediction_col,
                                 ColumnSpec("float", "scalar"))
        if self.leaf_prediction_col:
            out = out.with_column(self.leaf_prediction_col,
                                  ColumnSpec("float", "vector"))
        if self.features_shap_col:
            out = out.with_column(self.features_shap_col,
                                  ColumnSpec("any", "any"))
        return out

    def transform_schema(self, schema: TableSchema) -> TableSchema:
        self._check_schema(schema, self.input_schema())
        return self._prediction_schema(schema)

    def _train_params(self) -> dict:
        return {
            "objective": self.objective,
            "boosting": self.boosting_type,
            "num_iterations": self.num_iterations,
            "learning_rate": self.learning_rate,
            "num_leaves": self.num_leaves,
            "max_depth": self.max_depth,
            "max_delta_step": self.max_delta_step,
            "boost_from_average": self.boost_from_average,
            "max_bin": self.max_bin,
            "max_bin_by_feature": list(self.max_bin_by_feature) or None,
            "bin_sample_count": self.bin_sample_count,
            "bagging_fraction": self.bagging_fraction,
            "pos_bagging_fraction": self.pos_bagging_fraction,
            "neg_bagging_fraction": self.neg_bagging_fraction,
            "bagging_freq": self.bagging_freq,
            "feature_fraction": self.feature_fraction,
            "lambda_l1": self.lambda_l1,
            "lambda_l2": self.lambda_l2,
            "min_sum_hessian_in_leaf": self.min_sum_hessian_in_leaf,
            "min_data_in_leaf": self.min_data_in_leaf,
            "min_gain_to_split": self.min_gain_to_split,
            "early_stopping_round": self.early_stopping_round,
            "early_stopping_min_delta": self.improvement_tolerance,
            "top_rate": self.top_rate, "other_rate": self.other_rate,
            "drop_rate": self.drop_rate, "max_drop": self.max_drop,
            "skip_drop": self.skip_drop,
            "uniform_drop": self.uniform_drop,
            "xgboost_dart_mode": self.xgboost_dart_mode,
            "metric": self.metric or None,
            "seed": self.seed,
            "bagging_seed": self.bagging_seed,
            "parallelism": self.parallelism,
            "top_k": self.top_k,
            "categorical_feature": (list(self.categorical_slot_indexes)
                                    + list(self.categorical_slot_names)) or None,
            "cat_smooth": self.cat_smooth,
            "max_cat_threshold": self.max_cat_threshold,
        }

    def _split_validation(self, table: Table):
        vcol = self.validation_indicator_col
        if vcol:
            self._validate_input(table, vcol)
            mask = np.asarray(table[vcol], dtype=bool)
            return table.filter(~mask), table.filter(mask)
        return table, None

    # Instance-level seam for the tuning subsystem (synapseml_tpu/tuning):
    # a study sets ``est._tuning_overrides`` so every trial trains from ONE
    # shared pre-binned GBDTDataset (binning happens once per study, not
    # once per trial) under the scheduler's iteration budget and rung
    # callbacks. Never set on user-facing estimators outside a study.
    _tuning_overrides = None

    def _fit_booster_tuned(self, table: Table, ov: dict,
                           extra_params: Optional[dict] = None) -> GBDTBooster:
        self._validate_input(table, self.label_col)
        y = np.asarray(table[self.label_col], dtype=np.float64)
        w = (np.asarray(table[self.weight_col], dtype=np.float64)
             if self.weight_col else None)
        params = self._train_params()
        params.update(extra_params or {})
        params.update(ov.get("params") or {})
        return train(params, ov["dataset"], y=y, weight=w,
                     eval_set=ov.get("eval_set"),
                     init_booster=ov.get("init_booster"),
                     callbacks=ov.get("callbacks"), mesh=self.mesh)

    def _fit_booster(self, table: Table, extra_params: Optional[dict] = None,
                     group=None, eval_group_from=None) -> GBDTBooster:
        ov = self._tuning_overrides
        if ov is not None:
            return self._fit_booster_tuned(table, ov, extra_params)
        self._validate_input(table, self.features_col, self.label_col)
        tr, val = self._split_validation(table)
        x = _features_matrix(tr, self.features_col, self.sparse_num_bits)
        y = np.asarray(tr[self.label_col], dtype=np.float64)
        w = (np.asarray(tr[self.weight_col], dtype=np.float64)
             if self.weight_col else None)
        params = self._train_params()
        params.update(extra_params or {})
        eval_set = eval_groups = None
        if val is not None and val.num_rows:
            eval_set = [(
                _features_matrix(val, self.features_col, self.sparse_num_bits),
                np.asarray(val[self.label_col], dtype=np.float64),
            )]
            if eval_group_from is not None:
                eval_groups = [eval_group_from(val)]
        kw = {}
        if group is not None:
            kw["group"] = group(tr) if callable(group) else group
        if eval_groups is not None:
            kw["eval_group"] = eval_groups
        # Resolve categorical_slot_names against the features column's
        # slot-name metadata (the reference reads SparkML vector attribute
        # metadata for the same purpose).
        slot_names = table.meta.get(self.features_col, {}).get("slot_names")
        if slot_names is not None:
            kw["feature_names"] = list(slot_names)
        elif self.categorical_slot_names:
            raise ValueError(
                "categorical_slot_names requires slot-name metadata on the "
                f"features column: Table(meta={{{self.features_col!r}: "
                "{'slot_names': [...]}})")

        n_batches = int(self.num_batches)
        if n_batches > 1 and group is not None:
            raise NotImplementedError(
                "num_batches > 1 is not supported for the ranker: row-slice "
                "batches would split query groups")
        if n_batches and n_batches > 1:
            # reference batch training: model of batch k seeds batch k+1
            # (``LightGBMBase.scala:46-61``)
            total = int(params["num_iterations"])
            base_per, rem = divmod(total, n_batches)
            booster = None
            for b in range(n_batches):
                per = base_per + (1 if b < rem else 0)
                if per == 0:
                    continue
                lo = b * len(x) // n_batches
                hi = (b + 1) * len(x) // n_batches
                params_b = dict(params, num_iterations=per)
                booster = train(params_b, x[lo:hi], y[lo:hi],
                                weight=None if w is None else w[lo:hi],
                                eval_set=eval_set, init_booster=booster,
                                mesh=self.mesh, **kw)
            return booster
        return train(params, x, y, weight=w, eval_set=eval_set,
                     mesh=self.mesh, **kw)


class _LightGBMModelBase(Model):
    """Shared transform: features -> prediction (+ optional leaf/shap columns).

    Reference model methods: ``LightGBMModelMethods.scala:18-116``."""

    _abstract_stage = True

    features_col = Param("features column", str, default="features")
    prediction_col = Param("prediction output column", str, default="prediction")
    leaf_prediction_col = Param("optional leaf-index output column", str, default=None)
    features_shap_col = Param("optional contribution output column", str, default=None)
    sparse_num_bits = Param("hash-mask bits for sparse feature columns",
                            int, default=18)
    booster = ComplexParam("trained GBDTBooster", object, default=None)

    def input_schema(self) -> TableSchema:
        return TableSchema({self.features_col:
                            _LightGBMBase._FEATURES_SPEC})

    def _prediction_schema(self, schema: TableSchema,
                           prediction_spec=("float", "scalar")
                           ) -> TableSchema:
        out = schema.with_column(self.prediction_col,
                                 ColumnSpec(*prediction_spec))
        if self.leaf_prediction_col:
            out = out.with_column(self.leaf_prediction_col,
                                  ColumnSpec("float", "vector"))
        if self.features_shap_col:
            out = out.with_column(self.features_shap_col,
                                  ColumnSpec("any", "any"))
        return out

    def transform_schema(self, schema: TableSchema) -> TableSchema:
        self._check_schema(schema, self.input_schema())
        return self._prediction_schema(schema)

    def _extra_outputs(self, out: Table, x: np.ndarray) -> Table:
        if self.leaf_prediction_col:
            out = out.with_column(self.leaf_prediction_col,
                                  self.booster.predict_leaf(x).astype(np.float64))
        if self.features_shap_col:
            from .sparse import CSRMatrix

            contrib = self.booster.predict_contrib(x)
            if isinstance(contrib, (CSRMatrix, list)):
                # sparse input -> sparse contributions: store per-row
                # (indices, values) pairs, the same convention sparse
                # feature columns use (a dense (n, d+1) panel at hashed
                # width is the thing predict_contrib avoided). Multiclass
                # offsets class c's columns by c*(d+1), matching the dense
                # class-major flatten below.
                mats = contrib if isinstance(contrib, list) else [contrib]
                col = np.empty(mats[0].shape[0], dtype=object)
                for i in range(len(col)):
                    idx_parts, val_parts = [], []
                    for ci, m in enumerate(mats):
                        a, b = int(m.indptr[i]), int(m.indptr[i + 1])
                        idx_parts.append(m.indices[a:b].astype(np.int64)
                                         + ci * m.shape[1])
                        val_parts.append(m.values[a:b])
                    col[i] = (np.concatenate(idx_parts),
                              np.concatenate(val_parts))
                return out.with_column(self.features_shap_col, col)
            if contrib.ndim == 3:  # multiclass: flatten class-major like the reference
                contrib = np.concatenate(list(contrib), axis=1)
            out = out.with_column(self.features_shap_col, contrib)
        return out

    def save_native_model(self, path: str, fmt: str = "lightgbm") -> None:
        """Reference ``saveNativeModel`` (``LightGBMModelMethods``).

        ``fmt='lightgbm'`` writes LightGBM's text model format (loadable by a
        stock LightGBM); ``'json'`` writes this engine's JSON model string."""
        if fmt not in ("lightgbm", "json"):
            raise ValueError(f"fmt must be lightgbm|json, got {fmt!r}")
        with open(path, "w") as f:
            f.write(self.booster.save_native_model() if fmt == "lightgbm"
                    else self.booster.to_json())

    @classmethod
    def load_native_model(cls, path: str, **params):
        """Build a model stage from a saved model file — LightGBM text or
        this engine's JSON, sniffed (reference ``setModelString`` ingestion
        path accepts whatever ``saveNativeModel`` wrote)."""
        from .boost import GBDTBooster

        with open(path) as f:
            text = f.read()
        return cls(booster=GBDTBooster.from_model_string(text), **params)

    def get_feature_importances(self, importance_type: str = "split") -> np.ndarray:
        return self.booster.feature_importance(importance_type)


class LightGBMClassifier(_LightGBMBase):
    """Reference: ``LightGBMClassifier.scala:26``. Auto-selects binary vs multiclass
    from label cardinality unless ``objective`` is set explicitly."""

    objective = Param("binary | multiclass (auto from labels if unset)", str,
                      default="")
    probability_col = Param("probability output column", str, default="probability")
    raw_prediction_col = Param("raw margin output column", str, default="rawPrediction")
    is_unbalance = Param("rescale grad of minority class (reference isUnbalance)",
                         bool, default=False)

    def input_schema(self) -> TableSchema:
        # classifier labels may be strings/anything unique-able
        base = super().input_schema()
        return base.with_column(self.label_col, ColumnSpec("any", "scalar"))

    def _prediction_schema(self, schema: TableSchema) -> TableSchema:
        out = super()._prediction_schema(schema)
        # predictions carry the ORIGINAL label values (possibly strings)
        out = out.with_column(self.prediction_col, ColumnSpec("any", "scalar"))
        out = out.with_column(self.raw_prediction_col,
                              ColumnSpec("float", "vector"))
        return out.with_column(self.probability_col,
                               ColumnSpec("float", "vector"))

    def _fit(self, table: Table) -> "LightGBMClassificationModel":
        self._validate_input(table, self.features_col, self.label_col)
        y_raw = table[self.label_col]
        classes, y_idx = np.unique(np.asarray(y_raw), return_inverse=True)
        n_class = len(classes)
        if n_class < 2:
            raise ValueError(f"need >= 2 classes, label column has {n_class}")
        obj = self.objective
        if not obj:
            obj = "binary" if n_class == 2 else "multiclass"
        extra = {"objective": obj}
        if obj in ("multiclass", "softmax"):
            extra["num_class"] = n_class
        tbl = table.with_column(self.label_col, y_idx.astype(np.float64))
        if self.is_unbalance and n_class == 2 and not self.weight_col:
            # weight positives by neg/pos ratio (reference isUnbalance semantics)
            pos = max(int((y_idx == 1).sum()), 1)
            neg = int((y_idx == 0).sum())
            wcol = np.where(y_idx == 1, neg / pos, 1.0)
            tbl = tbl.with_column("__unbalance_weight__", wcol)
            old_w = self.weight_col
            self.set("weight_col", "__unbalance_weight__")
            try:
                booster = self._fit_booster(tbl, extra)
            finally:
                self.set("weight_col", old_w)
        else:
            booster = self._fit_booster(tbl, extra)
        return LightGBMClassificationModel(
            booster=booster, labels=classes.astype(np.float64)
            if np.issubdtype(classes.dtype, np.number) else classes,
            features_col=self.features_col, prediction_col=self.prediction_col,
            probability_col=self.probability_col,
            raw_prediction_col=self.raw_prediction_col,
            leaf_prediction_col=self.leaf_prediction_col,
            features_shap_col=self.features_shap_col,
            sparse_num_bits=self.sparse_num_bits,
        )


class LightGBMClassificationModel(_LightGBMModelBase):
    probability_col = Param("probability output column", str, default="probability")
    raw_prediction_col = Param("raw margin output column", str, default="rawPrediction")
    labels = ComplexParam("class label values in index order", object, default=None)

    def transform_schema(self, schema: TableSchema) -> TableSchema:
        self._check_schema(schema, self.input_schema())
        out = self._prediction_schema(schema,
                                      prediction_spec=("any", "scalar"))
        out = out.with_column(self.raw_prediction_col,
                              ColumnSpec("float", "vector"))
        return out.with_column(self.probability_col,
                               ColumnSpec("float", "vector"))

    def _transform(self, table: Table) -> Table:
        self._validate_input(table, self.features_col)
        x = _features_matrix(table, self.features_col, self.sparse_num_bits)
        b: GBDTBooster = self.booster
        raw = b.raw_predict(x)
        prob = b.activate(raw)  # one scoring pass feeds both output columns
        if b.num_class == 1:  # binary: emit 2-class vectors like the reference
            raw2 = np.stack([-raw, raw], axis=1)
            prob2 = np.stack([1 - prob, prob], axis=1)
            idx = (prob >= 0.5).astype(np.int64)
        else:
            raw2, prob2 = raw, prob
            idx = prob.argmax(axis=1)
        labels = self.labels
        pred = np.asarray(labels)[idx] if labels is not None else idx.astype(np.float64)
        out = table.with_column(self.raw_prediction_col, raw2.astype(np.float32))
        out = out.with_column(self.probability_col, prob2.astype(np.float32))
        out = out.with_column(self.prediction_col, pred)
        return self._extra_outputs(out, x)


class LightGBMRegressor(_LightGBMBase):
    """Reference: ``LightGBMRegressor.scala:38`` (objectives regression/l1/huber/
    quantile/poisson/tweedie/...)."""

    objective = Param("regression objective", str, default="regression")
    alpha = Param("huber/quantile alpha", float, default=0.9)
    tweedie_variance_power = Param("tweedie variance power in [1, 2)", float,
                                   default=1.5)

    def _fit(self, table: Table) -> "LightGBMRegressionModel":
        booster = self._fit_booster(table, {
            "alpha": self.alpha,
            "tweedie_variance_power": self.tweedie_variance_power,
        })
        return LightGBMRegressionModel(
            booster=booster, features_col=self.features_col,
            prediction_col=self.prediction_col,
            leaf_prediction_col=self.leaf_prediction_col,
            features_shap_col=self.features_shap_col,
            sparse_num_bits=self.sparse_num_bits,
        )


class LightGBMRegressionModel(_LightGBMModelBase):
    def _transform(self, table: Table) -> Table:
        self._validate_input(table, self.features_col)
        x = _features_matrix(table, self.features_col, self.sparse_num_bits)
        out = table.with_column(self.prediction_col,
                                self.booster.predict(x).astype(np.float64))
        return self._extra_outputs(out, x)


class LightGBMRanker(_LightGBMBase):
    """Reference: ``LightGBMRanker.scala:25`` — lambdarank over ``group_col``."""

    objective = Param("ranking objective", str, default="lambdarank")
    group_col = Param("query/group id column", str, default="group")

    def input_schema(self) -> TableSchema:
        return super().input_schema().with_column(
            self.group_col, ColumnSpec("any", "scalar"))

    ndcg_at = Param("NDCG truncation for eval", int, default=10)
    lambdarank_truncation_level = Param("pairs beyond this rank are ignored",
                                        int, default=30)
    max_position = Param("accepted for API parity (maxPosition)", int, default=20)

    def _fit(self, table: Table) -> "LightGBMRankerModel":
        self._validate_input(table, self.group_col)
        # rows must be contiguous per group: stable-sort by group id
        gid = np.asarray(table[self.group_col])
        order = np.argsort(gid, kind="stable")
        sorted_tbl = table.take(order)

        def sizes_of(t: Table) -> np.ndarray:
            g = np.asarray(t[self.group_col])
            _, counts = np.unique(g, return_counts=True)
            # np.unique sorts; rows are group-sorted, so counts align
            return counts

        booster = self._fit_booster(
            sorted_tbl,
            {"lambdarank_truncation_level": self.lambdarank_truncation_level,
             "ndcg_at": self.ndcg_at},
            group=sizes_of, eval_group_from=sizes_of,
        )
        return LightGBMRankerModel(
            booster=booster, features_col=self.features_col,
            prediction_col=self.prediction_col,
            leaf_prediction_col=self.leaf_prediction_col,
            features_shap_col=self.features_shap_col,
            sparse_num_bits=self.sparse_num_bits,
        )


class LightGBMRankerModel(_LightGBMModelBase):
    def _transform(self, table: Table) -> Table:
        self._validate_input(table, self.features_col)
        x = _features_matrix(table, self.features_col, self.sparse_num_bits)
        out = table.with_column(self.prediction_col,
                                self.booster.predict(x).astype(np.float64))
        return self._extra_outputs(out, x)
