"""Exact TreeSHAP feature contributions.

Reference: the C++ TreeSHAP behind ``LGBM_BoosterPredictForMat`` with
``C_API_PREDICT_CONTRIB`` (surfaced at ``LightGBMBooster.scala:510,529`` as
``featuresShap``). This is Lundberg & Lee's polynomial-time path algorithm
(Algorithm 2 of the TreeSHAP paper), vectorized across instances: the tree is
walked once, path state arrays carry a batch dimension, and every EXTEND /
UNWIND is a numpy vector op over all rows.

Covers (the p(S) weights) use the training hessian mass per leaf
(``leaf_hess``), the same weighting the engine's leaf values are computed with.
Split decisions replay on BINNED features, identical to prediction.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

__all__ = ["tree_shap", "build_explicit_tree"]


class _Node:
    __slots__ = ("feature", "bin", "cat", "left", "right", "cover", "value", "leaf")

    def __init__(self):
        self.feature = -1
        self.bin = -1
        self.cat: Optional[np.ndarray] = None
        self.left: Optional["_Node"] = None
        self.right: Optional["_Node"] = None
        self.cover = 0.0
        self.value = 0.0
        self.leaf = -1


def build_explicit_tree(parent: np.ndarray, feature: np.ndarray, bins: np.ndarray,
                        leaf_value: np.ndarray, leaf_hess: np.ndarray,
                        cat_set: Optional[np.ndarray] = None) -> _Node:
    """Replay-list arrays (one tree) -> explicit binary tree with covers.

    Split ``s`` converts current leaf ``parent[s]`` into an internal node whose
    left child keeps leaf id ``parent[s]`` and right child is leaf id ``s+1``.
    """
    root = _Node()
    root.leaf = 0
    cur = {0: root}
    for s in range(parent.shape[0]):
        p = int(parent[s])
        if p < 0:
            continue
        node = cur[p]
        node.leaf = -1
        node.feature = int(feature[s])
        node.bin = int(bins[s])
        if node.bin < 0 and cat_set is not None:
            node.cat = cat_set[s]
        left, right = _Node(), _Node()
        left.leaf, right.leaf = p, s + 1
        node.left, node.right = left, right
        cur[p], cur[s + 1] = left, right

    def finish(n: "_Node") -> float:
        if n.left is None:
            n.value = float(leaf_value[n.leaf])
            n.cover = max(float(leaf_hess[n.leaf]), 1e-12)
        else:
            n.cover = finish(n.left) + finish(n.right)
        return n.cover

    finish(root)
    return root


def _extend(pw: np.ndarray, zf: List[float], of: List[np.ndarray],
            pz: float, po: np.ndarray, depth: int) -> np.ndarray:
    """EXTEND: grow the path-weight table by one fraction pair.

    ``pw`` (n, depth) -> (n, depth+1); ``zf``/``of`` are appended by the caller.
    """
    n = pw.shape[0]
    out = np.zeros((n, depth + 1), dtype=np.float64)
    out[:, 1:] = pw * po[:, None] * (np.arange(1, depth + 1) / (depth + 1))
    out[:, :-1] += pw * pz * ((depth - np.arange(depth)) / (depth + 1))
    if depth == 0:
        out[:, 0] = 1.0
    return out


def _unwound_sum(pw: np.ndarray, zf: List[float], of: List[np.ndarray],
                 i: int) -> np.ndarray:
    """Sum of the path weights with entry ``i`` unwound (UNWIND + sum), (n,)."""
    n, depth1 = pw.shape
    depth = depth1 - 1
    o, z = of[i], zf[i]
    total = np.zeros(n)
    nxt = pw[:, depth].copy()
    o_safe = np.where(o == 0.0, 1.0, o)
    for j in range(depth - 1, -1, -1):
        # where o != 0: tmp = nxt*(depth+1)/((j+1)*o); total += tmp; nxt = pw[j] - tmp*z*(depth-j)/(depth+1)
        tmp = nxt * (depth + 1) / ((j + 1) * o_safe)
        with_o = tmp
        without_o = pw[:, j] * (depth + 1) / (z * (depth - j)) if z * (depth - j) != 0 \
            else np.zeros(n)
        use_o = o != 0.0
        contrib = np.where(use_o, with_o, without_o)
        total += contrib
        nxt = np.where(use_o, pw[:, j] - tmp * z * (depth - j) / (depth + 1), nxt)
    return total


def tree_shap(root: _Node, binned: np.ndarray, n_features: int) -> np.ndarray:
    """phi (n, n_features); sum(phi) + E[f] == f(x) per row (additivity)."""
    n = binned.shape[0]
    phi = np.zeros((n, n_features), dtype=np.float64)

    def go_left_mask(node: "_Node") -> np.ndarray:
        col = binned[:, node.feature]
        if node.bin < 0:
            return node.cat[col] > 0
        return col <= node.bin

    def recurse(node: "_Node", pw: np.ndarray, zf: List[float],
                of: List[np.ndarray], feats: List[int]):
        depth = len(zf)
        if node.left is None:
            # leaf: attribute to every feature on the path
            for i in range(1, depth):
                w = _unwound_sum(pw, zf, of, i)
                phi[:, feats[i]] += w * (of[i] - zf[i]) * node.value
            return

        hot_left = go_left_mask(node)
        hot, cold = node.left, node.right
        # per-row hot child differs; process both children, with one_fraction
        # masked per row. zero fraction = child cover / node cover.
        try:
            i_dup = feats.index(node.feature, 1)
        except ValueError:
            i_dup = -1

        for child, is_left in ((node.left, True), (node.right, False)):
            iz = child.cover / node.cover
            io = hot_left.astype(np.float64) if is_left else (~hot_left).astype(np.float64)
            cpw, czf, cof, cfeats = pw, list(zf), list(of), list(feats)
            if i_dup >= 0:
                # feature already on path: unwind it, fold its fractions in
                iz = iz * czf[i_dup]
                io = io * cof[i_dup]
                cpw = _unwind(cpw, czf, cof, i_dup)
                del czf[i_dup], cof[i_dup], cfeats[i_dup]
            d = len(czf)
            npw = _extend(cpw, czf, cof, iz, io, d)
            czf.append(iz)
            cof.append(io)
            cfeats.append(node.feature)
            recurse(child, npw, czf, cof, cfeats)

    def _unwind(pw: np.ndarray, zf: List[float], of: List[np.ndarray],
                i: int) -> np.ndarray:
        n_, depth1 = pw.shape
        depth = depth1 - 1
        o, z = of[i], zf[i]
        out = np.zeros((n_, depth), dtype=np.float64)
        nxt = pw[:, depth].copy()
        o_safe = np.where(o == 0.0, 1.0, o)
        use_o = o != 0.0
        for j in range(depth - 1, -1, -1):
            tmp = nxt * (depth + 1) / ((j + 1) * o_safe)
            with_o = tmp
            nxt_with = pw[:, j] - tmp * z * (depth - j) / (depth + 1)
            if z * (depth - j) != 0:
                without_o = pw[:, j] * (depth + 1) / (z * (depth - j))
            else:
                without_o = np.zeros(n_)
            out[:, j] = np.where(use_o, with_o, without_o)
            nxt = np.where(use_o, nxt_with, nxt)
        return out

    # root: path starts with the sentinel (1, 1) entry
    pw0 = np.ones((n, 1), dtype=np.float64)
    recurse(root, pw0, [1.0], [np.ones(n)], [-1])
    return phi


def expected_value(root: _Node) -> float:
    """Cover-weighted mean prediction E[f] (the SHAP base value)."""
    if root.left is None:
        return root.value
    wl = root.left.cover / root.cover
    return wl * expected_value(root.left) + (1 - wl) * expected_value(root.right)
