"""Gradient/hessian histograms — the engine's hot kernel.

Reference analogue: LightGBM's C++ ``ConstructHistograms`` (per-thread bin scans over
row blocks), whose results are allreduced over the socket ring in ``data_parallel``
mode. TPU design instead:

- **one-hot matmul**: for a row chunk, build the (chunk, d, B) one-hot of bin ids and
  contract the chunk axis against the (chunk, 3) [grad, hess, count] panel — an MXU
  matmul. Chunks stream through ``lax.scan`` so the one-hot never exceeds
  ``chunk * d * B`` elements of VMEM-friendly working set.
- **scatter fallback** for CPU/debug: ``zeros.at[flat_idx].add(values)``.

Both paths take a per-row ``weight`` so callers express leaf masks / bagging /GOSS
amplification as weights (no dynamic shapes). Distributed reduction is the caller's
``psum`` — histograms are dense (d, B, 3) tensors, the natural XLA collective.
"""

from __future__ import annotations

import numpy as np

__all__ = ["histogram", "HIST_CHANNELS"]

HIST_CHANNELS = 3  # grad, hess, count


def _hist_scatter(binned, ghc, n_bins):
    import jax.numpy as jnp

    n, d = binned.shape
    binned = binned.astype(jnp.int32)  # narrow storage dtypes overflow f*B+bin
    # flat index per (row, feature): f * B + bin
    flat = binned + jnp.arange(d, dtype=binned.dtype)[None, :] * n_bins  # (n, d)
    out = jnp.zeros((d * n_bins, HIST_CHANNELS), dtype=jnp.float32)
    # every feature column of a row gets the same row panel
    vals = jnp.broadcast_to(ghc[:, None, :], (n, d, HIST_CHANNELS))
    out = out.at[flat.reshape(-1)].add(vals.reshape(-1, HIST_CHANNELS))
    return out.reshape(d, n_bins, HIST_CHANNELS)


def _hist_onehot(binned, ghc, n_bins, chunk):
    """One-hot contraction histogram.

    The one-hot (chunk, d, B) compare is a broadcast operand of the
    dot_general, so XLA fuses it into the contraction loop — it is never
    materialized in HBM. Chunks are LARGE (default 2^20 rows): the scan
    exists only as an HBM-materialization bound; small chunks turn the
    histogram into thousands of sequential micro-steps whose per-step
    overhead dominates the whole GBDT engine (measured ~4x end-to-end).
    Everything stays f32 so per-row gradients aren't quantized and split
    gains match the f32 scatter path — TPU and CPU grow identical trees.
    """
    import jax
    import jax.numpy as jnp

    n, d = binned.shape
    chunk = min(chunk, max(n, 1))
    bins = jnp.arange(n_bins, dtype=binned.dtype)

    def contract(b, g):
        onehot = (b[:, :, None] == bins).astype(jnp.float32)  # (rows, d, B)
        # (d*B, rows) @ (rows, 3) on the MXU, f32 accumulation
        return jax.lax.dot_general(
            onehot, g,
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (d, B, 3)

    if n <= chunk:
        return contract(binned, ghc)

    pad = (-n) % chunk
    if pad:
        binned = jnp.pad(binned, ((0, pad), (0, 0)))
        ghc = jnp.pad(ghc, ((0, pad), (0, 0)))  # zero weight: padding contributes 0
    nc = (n + pad) // chunk
    binned = binned.reshape(nc, chunk, d)
    ghc = ghc.reshape(nc, chunk, HIST_CHANNELS)

    def body(acc, xs):
        b, g = xs
        return acc + contract(b, g), None

    init = jnp.zeros((d, n_bins, HIST_CHANNELS), dtype=jnp.float32)
    acc, _ = jax.lax.scan(body, init, (binned, ghc))
    return acc


def histogram_panel(binned, ghc, n_bins: int, method: str = "auto",
                    chunk: int = 2048):
    """(d, B, 3) histogram of a prebuilt (n, 3) [grad, hess, count] panel."""
    import jax

    if method == "auto":
        # any non-cpu backend gets the MXU one-hot path: a tunneled TPU can
        # register under a plugin backend name (e.g. 'axon'), not 'tpu' —
        # matching on == "tpu" silently fell back to scatter there
        method = "onehot" if jax.default_backend() != "cpu" else "scatter"
    if method == "onehot":
        return _hist_onehot(binned, ghc, n_bins, chunk)
    if method == "scatter":
        return _hist_scatter(binned, ghc, n_bins)
    raise ValueError(f"unknown histogram method {method!r}")


def histogram(binned, grad, hess, weight, n_bins: int, method: str = "auto",
              chunk: int = 2048):
    """(d, B, 3) histogram of [grad, hess, count], each scaled by ``weight``.

    ``binned``: (n, d) int bins; ``grad``/``hess``/``weight``: (n,) f32.
    ``method``: 'onehot' (MXU), 'scatter', or 'auto' (onehot on TPU else scatter).
    """
    import jax.numpy as jnp

    ghc = jnp.stack([grad * weight, hess * weight, weight], axis=-1)
    return histogram_panel(binned, ghc, n_bins, method=method, chunk=chunk)


def histogram_np(binned: np.ndarray, grad, hess, weight, n_bins: int) -> np.ndarray:
    """Plain-numpy reference for tests."""
    n, d = binned.shape
    out = np.zeros((d, n_bins, HIST_CHANNELS), dtype=np.float64)
    g = np.asarray(grad) * weight
    h = np.asarray(hess) * weight
    w = np.asarray(weight)
    for j in range(d):
        np.add.at(out[j, :, 0], binned[:, j], g)
        np.add.at(out[j, :, 1], binned[:, j], h)
        np.add.at(out[j, :, 2], binned[:, j], w)
    return out.astype(np.float32)
