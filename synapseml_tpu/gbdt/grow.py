"""Leaf-wise tree growth, fully jit-compiled.

Reference analogue: the C++ ``SerialTreeLearner``/``DataParallelTreeLearner``/
``VotingParallelTreeLearner`` driven per-iteration from ``TrainUtils.trainCore``
(``TrainUtils.scala:92-160``; parallelism modes ``LightGBMParams.scala:16-30``).
TPU design:

- fixed shapes everywhere: ``num_leaves`` slots, ``lax.fori_loop`` over the
  ``num_leaves - 1`` split steps; an inert step (gain <= min_gain) records parent -1;
- the tree is a *replay list* of splits (parent leaf, feature, bin), not a pointer
  tree: prediction replays the splits in order with vectorized gathers — no
  data-dependent control flow, so it jits and vmaps (multiclass) cleanly;
- leaf-wise like LightGBM: each step splits the best-gain leaf anywhere in the tree;
- parent-subtract: each step computes ONE masked histogram (the new right child) and
  derives the left side by subtraction — same trick as LightGBM's sibling subtract;
- distributed ``parallelism='data'``: every histogram is ``psum``-reduced over the
  mesh axis, so all shards take identical split decisions (the reference ships
  histogram buffers over its TCP ring for the same purpose);
- distributed ``parallelism='voting'`` (LightGBM PV-tree): histograms stay LOCAL;
  each shard votes for its top-k features per leaf, votes are psum'd, and only the
  globally top-2k features' histograms are allreduced — comm volume drops from
  (L,d,B,3) to (L,2k,B,3) per step;
- categorical splits (LightGBM many-vs-many): a categorical feature's bins are
  sorted by grad/hess ratio and the best sorted-prefix becomes the left-going
  category SET, stored as a (B,) membership row (``cat_set``); the replay list
  marks such splits with ``bin == -1``.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import numpy as np

from .histogram import histogram

__all__ = ["TreeConfig", "GrownTree", "grow_tree", "predict_binned"]


class TreeConfig(NamedTuple):
    """Static (compile-time) growth hyperparameters."""

    n_bins: int
    num_leaves: int = 31
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_data_in_leaf: float = 20.0
    min_sum_hessian: float = 1e-3
    min_gain_to_split: float = 0.0
    hist_method: str = "auto"
    hist_chunk: int = 1 << 20
    cat_smooth: float = 10.0
    max_cat_threshold: int = 32
    max_depth: int = -1          # <= 0: unlimited (LightGBM maxDepth)
    max_delta_step: float = 0.0  # > 0: clamp leaf outputs (LightGBM maxDeltaStep)
    parallelism: str = "data"   # 'data' | 'voting'
    top_k: int = 20             # voting: local vote size (global select = 2k)
    # Leaf-local histograms (LightGBM's ConstructHistograms scans only the
    # split leaf): gather the SMALLER child's rows into a static power-of-2
    # buffer picked by lax.switch, histogram the buffer, and derive the other
    # side by parent subtraction — work per split is proportional to the
    # split leaf, not to n.
    leaf_local: bool = False
    leaf_buf_min: int = 1024    # smallest gather buffer (rows)
    # Under vmap (multiclass) a vmapped lax.switch executes EVERY buffer
    # branch (~2n per step, worse than the full scan). leaf_buf_fixed
    # drops the ladder for ONE static buffer covering the largest possible
    # child (~n/2 rows): still roughly half the full-data scan plus the
    # parent subtract, and branch-free so it vmaps cleanly.
    leaf_buf_fixed: bool = False


class GrownTree(NamedTuple):
    """Replay-list tree: split ``s`` turns leaf ``parent[s]`` into (parent[s], s+1).

    ``bin[s] >= 0``: numeric split 'bin <= b goes left'. ``bin[s] == -1``:
    categorical split; row goes left iff ``cat_set[s, row_bin] == 1``.
    """

    parent: "np.ndarray"  # (L-1,) int32; -1 = inert step
    feature: "np.ndarray"  # (L-1,) int32
    bin: "np.ndarray"  # (L-1,) int32
    gain: "np.ndarray"  # (L-1,) f32
    leaf_value: "np.ndarray"  # (L,) f32  (unshrunk; learning rate applied by caller)
    leaf_hess: "np.ndarray"  # (L,) f32 — leaf hessian mass (cover), for contribs
    cat_set: "np.ndarray"  # (L-1, B) int8 — left-going category membership


def _thresh_l1(g, l1):
    import jax.numpy as jnp

    return jnp.sign(g) * jnp.maximum(jnp.abs(g) - l1, 0.0)


def _prefix_bins(h):
    """Inclusive prefix sum along the BIN axis (-2) of (..., B, c) via a
    lower-triangular MXU matmul.

    XLA lowers ``cumsum`` on TPU to an O(B^2) reduce-window on the VPU —
    at Adult scale (B=256) that single op was ~30% of per-split device time
    (r5 trace: 263 us). The triangular dot does the same O(B^2) flops on
    the MXU in single-digit microseconds. Summation order differs from the
    sequential scan only in fp rounding; split-gain ties are resolved the
    same way on every backend since the formulation is used everywhere."""
    import jax
    import jax.numpy as jnp

    B = h.shape[-2]
    tri = jnp.tril(jnp.ones((B, B), jnp.float32))
    # HIGHEST: default TPU matmul precision truncates operands to bf16 —
    # fine for the one-hot histogram (0/1 and raw per-row values are
    # bf16-exact) but NOT for these already-accumulated per-bin sums
    return jnp.einsum("ij,...jc->...ic", tri, h,
                      precision=jax.lax.Precision.HIGHEST,
                      preferred_element_type=jnp.float32)


def grow_tree(binned, grad, hess, row_weight, feature_mask, cfg: TreeConfig,
              axis_name: Optional[str] = None, cat_mask=None,
              model_axis_name: Optional[str] = None):
    """Grow one tree. Returns (GrownTree of device arrays, node_of_row (n,) int32).

    ``binned`` (n, d) int32 — or a :class:`~.sparse.SparseBinned`, which
    routes to the summary-based sparse grower (wide hashed features);
    ``grad``/``hess``/``row_weight`` (n,) f32;
    ``feature_mask`` (d,) f32 in {0,1} (feature_fraction sampling);
    ``cat_mask`` (d,) f32 in {0,1} — categorical features (None = all numeric).

    ``model_axis_name`` (2-D ``SpecLayout`` meshes, ``runtime/layout.py``)
    turns on FEATURE-PARALLEL histograms: rows stay sharded over
    ``axis_name`` and each ``model``-axis shard histograms only its
    ``d / m`` feature block; one ``psum`` over BOTH axes reassembles the
    full (d, B, 3) panel on every shard (the blocks are disjoint, so the
    cross-model sum just concatenates them). Work per device drops from
    ``n_local * d`` to ``n_local * d / m`` — the 2-D analogue of
    LightGBM's data+feature hybrid — while split selection and row
    routing stay replicated (cheap, and ``binned`` is already resident).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from .sparse import SparseBinned

    if isinstance(binned, SparseBinned):
        if model_axis_name is not None:
            raise NotImplementedError(
                "feature-parallel histograms need the dense (n, d) layout; "
                "sparse input trains data-parallel (model axis size 1)")
        return _grow_tree_sparse(binned, grad, hess, row_weight,
                                 feature_mask, cfg, axis_name,
                                 cat_mask=cat_mask)

    n, d = binned.shape
    L, B = cfg.num_leaves, cfg.n_bins
    l1, l2 = cfg.lambda_l1, cfg.lambda_l2
    has_cat = cat_mask is not None
    voting = cfg.parallelism == "voting" and axis_name is not None
    if voting:
        if model_axis_name is not None:
            raise ValueError(
                "parallelism='voting' keeps histograms local by design; "
                "it composes with a data axis only (model axis size 1)")
        k_local = min(cfg.top_k, d)
        k_global = min(2 * cfg.top_k, d)
    if model_axis_name is not None and axis_name is None:
        raise ValueError("model_axis_name requires axis_name (2-D layout "
                         "meshes always carry the data axis)")

    def hist_of(weight):
        if model_axis_name is not None:
            # feature-parallel block: this shard histograms features
            # [j*blk, (j+1)*blk); the two-axis psum both reduces row
            # shards AND reassembles the disjoint blocks (other shards
            # contribute exact zeros outside their block)
            m = lax.psum(1, model_axis_name)  # static: the axis size
            j = lax.axis_index(model_axis_name)
            blk = -(-d // m)
            pad = m * blk - d
            bp = jnp.pad(binned, ((0, 0), (0, pad))) if pad else binned
            hb = histogram(lax.dynamic_slice_in_dim(bp, j * blk, blk, axis=1),
                           grad, hess, weight, B,
                           method=cfg.hist_method, chunk=cfg.hist_chunk)
            h = lax.dynamic_update_slice_in_dim(
                jnp.zeros((m * blk, B, 3), jnp.float32), hb, j * blk, axis=0)
            return lax.psum(h[:d], (axis_name, model_axis_name))
        h = histogram(binned, grad, hess, weight, B,
                      method=cfg.hist_method, chunk=cfg.hist_chunk)
        if axis_name is not None and not voting:
            h = lax.psum(h, axis_name)
        return h

    # -- leaf-local gather histograms (LightGBM ConstructHistograms analogue) --
    # (the gather ladder scans full-width rows; under a model axis the
    # feature-parallel block path above is the histogram work-splitter)
    use_leaf_local = (cfg.leaf_local and n > 2 * cfg.leaf_buf_min
                      and model_axis_name is None)
    if use_leaf_local:
        from .histogram import histogram_panel

        ghc_full = jnp.stack(
            [grad * row_weight, hess * row_weight, row_weight], axis=-1)
        # pad row n: zero weight, bin 0 — gathered padding contributes nothing
        binned_pad = jnp.concatenate(
            [binned, jnp.zeros((1, d), binned.dtype)], axis=0)
        ghc_pad = jnp.concatenate(
            [ghc_full, jnp.zeros((1, 3), ghc_full.dtype)], axis=0)
        # Single-host the smaller child is <= ceil(n/2); under data-parallel
        # shard_map the side is chosen by GLOBAL counts, so one shard's local
        # membership can be up to n — the ladder must cover it or the compact
        # scatter silently drops rows.
        buf_max = (n if (axis_name is not None and not voting)
                   else (n + 1) // 2)
        sizes = []
        sz = cfg.leaf_buf_min
        while sz < buf_max:
            sizes.append(sz)
            sz *= 2
        sizes.append(sz)
        sizes_arr = jnp.asarray(sizes, jnp.int32)
        row_ids = jnp.arange(n, dtype=jnp.int32)

        def leaf_hist_local(mask, cnt):
            """Histogram of the masked rows via a static-size gather buffer.

            ``lax.switch`` picks the smallest power-of-2 buffer >= cnt; rows
            compact into it with a cumsum scatter (out-of-buffer writes drop).
            No collectives inside the branches, so shards may take different
            branches under shard_map."""
            pos = jnp.cumsum(mask) - 1  # compacted position per member row

            def make_branch(size):
                def br(_):
                    tgt = jnp.where(mask, pos, size).astype(jnp.int32)
                    idx = jnp.full((size,), n, jnp.int32).at[tgt].set(
                        row_ids, mode="drop")
                    rows = jnp.take(binned_pad, idx, axis=0)
                    panel = jnp.take(ghc_pad, idx, axis=0)
                    return histogram_panel(rows, panel, B,
                                           method=cfg.hist_method,
                                           chunk=cfg.hist_chunk)
                return br

            if cfg.leaf_buf_fixed:
                # branch-free single buffer (multiclass/vmap mode): the
                # covering size always fits, so the switch — which a vmap
                # would execute in EVERY branch — is simply not built
                return make_branch(sizes[-1])(None)
            branch = jnp.minimum((cnt > sizes_arr).sum(), len(sizes) - 1)
            return lax.switch(branch, [make_branch(s) for s in sizes], None)

    def gain_term(G, H):
        return _thresh_l1(G, l1) ** 2 / (H + l2)

    def gain_table(hists, fmask_sel):
        """(..., d_sel, B, 3) histograms -> (..., d_sel, B) split-gain table.

        For numeric features entry b is the 'bin <= b' threshold split; for
        categorical features entry b is the sorted-prefix of length b+1.
        """
        G, H, C = hists[..., 0], hists[..., 1], hists[..., 2]
        GT = G.sum(-1, keepdims=True)
        HT = H.sum(-1, keepdims=True)
        CT = C.sum(-1, keepdims=True)
        pos = jnp.arange(B)

        def split_gain(GL, HL, CL, extra_valid):
            GR, HR, CR = GT - GL, HT - HL, CT - CL
            g = gain_term(GL, HL) + gain_term(GR, HR) - gain_term(GT, HT)
            valid = (
                (pos < B - 1)
                & (CL >= cfg.min_data_in_leaf)
                & (CR >= cfg.min_data_in_leaf)
                & (HL >= cfg.min_sum_hessian)
                & (HR >= cfg.min_sum_hessian)
                & extra_valid
                & (fmask_sel[..., None] > 0)
            )
            return jnp.where(valid, g, -jnp.inf)

        cum = _prefix_bins(hists)
        gain_num = split_gain(cum[..., 0], cum[..., 1], cum[..., 2], True)
        if not has_cat:
            return gain_num
        ratio = G / (H + cfg.cat_smooth)
        order = jnp.argsort(-ratio, axis=-1)
        hs = jnp.take_along_axis(hists, order[..., None], axis=-2)
        cums = _prefix_bins(hs)
        gain_cat = split_gain(cums[..., 0], cums[..., 1], cums[..., 2],
                              pos + 1 <= cfg.max_cat_threshold)
        return gain_num, gain_cat

    def combined_gain(hists, fmask_sel, cmask_sel):
        g = gain_table(hists, fmask_sel)
        if not has_cat:
            return g
        gain_num, gain_cat = g
        return jnp.where(cmask_sel[..., None] > 0, gain_cat, gain_num)

    def best_splits(hists, n_active):
        """Best (gain, feature, bin) per leaf. (L,) each.

        ``hists`` (L, d, B, 3) — fully reduced in 'data' mode, local in
        'voting' mode (reduction of candidates happens here).
        """
        if not voting:
            gain = combined_gain(hists, feature_mask,
                                 cat_mask if has_cat else None)   # (L, d, B)
            flat = gain.reshape(L, d * B)
            idx = jnp.argmax(flat, axis=-1)
            best_gain = jnp.take_along_axis(flat, idx[:, None], axis=-1)[:, 0]
            active = jnp.arange(L) < n_active
            return (jnp.where(active, best_gain, -jnp.inf),
                    idx // B, idx % B)

        # -- voting-parallel (PV-tree): vote -> select -> reduce candidates ----
        local_gain = combined_gain(hists, feature_mask,
                                   cat_mask if has_cat else None)  # (L, d, B)
        per_feat = local_gain.max(-1)                              # (L, d)
        topk_idx = lax.top_k(per_feat, k_local)[1]                 # (L, k)
        # dtype pinned: a bare zeros() is f64 under x64 and the psum /
        # top_k chain inherits it (device lint SMT101)
        votes = jnp.zeros((L, d), jnp.float32).at[
            jnp.arange(L)[:, None], topk_idx].add(1.0)
        votes = lax.psum(votes, axis_name)
        # deterministic global selection on every shard
        sel = lax.top_k(votes, k_global)[1]                        # (L, 2k)
        cand = jnp.take_along_axis(
            hists, sel[:, :, None, None], axis=1)                  # (L, 2k, B, 3)
        cand = lax.psum(cand, axis_name)
        fmask_sel = jnp.take(feature_mask, sel)                    # (L, 2k)
        cmask_sel = jnp.take(cat_mask, sel) if has_cat else None
        gain = combined_gain(cand, fmask_sel, cmask_sel)           # (L, 2k, B)
        flat = gain.reshape(L, k_global * B)
        idx = jnp.argmax(flat, axis=-1)
        best_gain = jnp.take_along_axis(flat, idx[:, None], axis=-1)[:, 0]
        feat = jnp.take_along_axis(sel, (idx // B)[:, None], axis=1)[:, 0]
        active = jnp.arange(L) < n_active
        return jnp.where(active, best_gain, -jnp.inf), feat, idx % B

    def split_detail(hists, l, f_sel, b_sel):
        """Left-membership over bins for the chosen split (B,) bool, plus the
        categorical flag. Uses the REDUCED histogram row so every shard derives
        the same category set."""
        row = jnp.take(jnp.take(hists, l, axis=0), f_sel, axis=0)  # (B, 3)
        if voting:
            row = lax.psum(row, axis_name)
        if has_cat:
            is_cat = jnp.take(cat_mask, f_sel) > 0
            ratio = row[:, 0] / (row[:, 1] + cfg.cat_smooth)
            rank = jnp.argsort(jnp.argsort(-ratio))
            # zero-mass bins (no rows in this leaf; incl. the missing bin on
            # NaN-free data) stay OUT of the left set: their placement is
            # gain-neutral for training but decides where unseen categories
            # route at predict time — LightGBM sends not-in-bitset right,
            # and native-model export can only express that
            in_set_cat = (rank <= b_sel) & (row[:, 2] > 0)
            in_set_num = jnp.arange(B) <= b_sel
            return jnp.where(is_cat, in_set_cat, in_set_num), is_cat
        return jnp.arange(B) <= b_sel, jnp.zeros((), jnp.bool_)

    def step(s, state):
        node, hists, parent, feat, bin_, gains, cat_sets, depth = state
        leaf_gain, leaf_f, leaf_b = best_splits(hists, s + 1)
        if cfg.max_depth > 0:
            # leaves at the depth cap cannot split (LightGBM leaf-wise
            # growth under maxDepth)
            leaf_gain = jnp.where(depth < cfg.max_depth, leaf_gain, -jnp.inf)
        l = jnp.argmax(leaf_gain)
        g_best = leaf_gain[l]
        # both operands are static config floats: a host-side max keeps the
        # threshold out of the traced program (a traced jnp.maximum of two
        # python floats is an f64 op under x64 — device lint SMT101)
        ok = g_best > max(cfg.min_gain_to_split, 0.0)
        f_sel = leaf_f[l]
        b_sel = leaf_b[l]
        in_set, is_cat = split_detail(hists, l, f_sel, b_sel)
        # binned may be stored int8/int16 (HBM + transfer savings); gather
        # indices must widen
        col = jnp.take(binned, f_sel, axis=1).astype(jnp.int32)
        go_left = jnp.take(in_set, col)
        went_right = (node == l) & ~go_left & ok
        node = jnp.where(went_right, s + 1, node)
        if use_leaf_local:
            # histogram only the SMALLER child's rows; derive the other side
            # by parent subtraction (LightGBM's sibling subtract, but with the
            # scan itself leaf-local instead of full-data)
            # node is already updated: rows still in l are exactly the
            # original members that went left
            went_left = (node == l) & ok
            cnt_r = went_right.sum().astype(jnp.int32)
            cnt_l = went_left.sum().astype(jnp.int32)
            if axis_name is not None and not voting:
                # data mode psums h_small: every shard must pick the same side
                cnt_r = lax.psum(cnt_r, axis_name)
                cnt_l = lax.psum(cnt_l, axis_name)
            smaller_right = cnt_r <= cnt_l
            mask_small = jnp.where(smaller_right, went_right, went_left)
            cnt_small = jnp.minimum(cnt_r, cnt_l)
            if axis_name is not None and not voting:
                # local buffer sizing: the local member count is what must fit
                local_cnt = mask_small.sum().astype(jnp.int32)
            else:
                local_cnt = cnt_small
            h_small = leaf_hist_local(mask_small, local_cnt)
            if axis_name is not None and not voting:
                h_small = lax.psum(h_small, axis_name)
            child = jnp.where(smaller_right, h_small, hists[l] - h_small)
        else:
            child = hist_of(row_weight * went_right.astype(jnp.float32))
        hists = jnp.where(
            ok,
            hists.at[s + 1].set(child).at[l].add(-child),
            hists,
        )
        parent = parent.at[s].set(jnp.where(ok, l, -1).astype(jnp.int32))
        feat = feat.at[s].set(f_sel.astype(jnp.int32))
        bin_ = bin_.at[s].set(
            jnp.where(is_cat, -1, b_sel).astype(jnp.int32))
        gains = gains.at[s].set(jnp.where(ok, g_best, 0.0).astype(jnp.float32))
        cat_sets = cat_sets.at[s].set(
            (in_set & is_cat & ok).astype(jnp.int8))
        child_depth = jnp.where(ok, depth[l] + 1, depth[l]).astype(jnp.int32)
        depth = jnp.where(ok, depth.at[s + 1].set(child_depth)
                          .at[l].set(child_depth), depth)
        return node, hists, parent, feat, bin_, gains, cat_sets, depth

    root_hist = hist_of(row_weight)
    hists0 = jnp.zeros((L, d, B, 3), dtype=jnp.float32).at[0].set(root_hist)
    state0 = (
        jnp.zeros(n, dtype=jnp.int32),
        hists0,
        jnp.full(L - 1, -1, dtype=jnp.int32),
        jnp.zeros(L - 1, dtype=jnp.int32),
        jnp.zeros(L - 1, dtype=jnp.int32),
        jnp.zeros(L - 1, dtype=jnp.float32),
        jnp.zeros((L - 1, B), dtype=jnp.int8),
        jnp.zeros(L, dtype=jnp.int32),  # per-leaf depth
    )
    node, hists, parent, feat, bin_, gains, cat_sets, _depth = lax.fori_loop(
        0, L - 1, step, state0)

    # leaf totals: sum over bins of any one feature covers every row exactly once
    G_leaf = hists[:, 0, :, 0].sum(-1)
    H_leaf = hists[:, 0, :, 1].sum(-1)
    if voting:
        G_leaf = lax.psum(G_leaf, axis_name)
        H_leaf = lax.psum(H_leaf, axis_name)
    leaf_value = -_thresh_l1(G_leaf, l1) / (H_leaf + l2)
    leaf_value = jnp.where(H_leaf > 0, leaf_value, 0.0)
    if cfg.max_delta_step > 0:
        leaf_value = jnp.clip(leaf_value, -cfg.max_delta_step,
                              cfg.max_delta_step)
    return GrownTree(parent, feat, bin_, gains, leaf_value, H_leaf, cat_sets), node


def _grow_tree_sparse(sb, grad, hess, row_weight, feature_mask,
                      cfg: TreeConfig, axis_name: Optional[str],
                      cat_mask=None):
    """Summary-based leaf-wise growth over a :class:`SparseBinned` matrix.

    The dense grower keeps every leaf's full (d, B, 3) histogram resident so
    each step can re-evaluate all leaves — impossible at hashed-text width
    (L * d * B * 3 floats at d = 2^18 is gigabytes). This variant keeps only
    per-leaf best-split SUMMARIES (gain, feature, bin) plus G/H totals, and
    rebuilds the two child histograms of the split leaf transiently each step
    with one scatter-free pass (``sparse_histogram_split``) — the same
    economy as LightGBM's bounded histogram pool + per-leaf ``SplitInfo``
    cache (``serial_tree_learner``'s ``best_split_per_leaf_``).
    Parallelism 'data' psums the transient child histograms, 'voting'
    (PV-tree) exchanges per-child votes + the elected candidates.

    Categorical splits (``cat_mask``): the gain table sorts each categorical
    feature's bins by grad/hess ratio exactly like the dense grower; because
    this grower keeps no resident histograms, applying a categorical split
    recomputes the ONE (B, 3) feature histogram of the split leaf (an
    O(max_run) bounded gather + tiny scatter, psum'd under a mesh) to derive
    the left-going category set.
    """
    import jax.numpy as jnp
    from jax import lax

    from .sparse import (sparse_column, sparse_histogram_side,
                         sparse_histogram_split)

    n = grad.shape[0]
    d, B = sb.d, sb.n_bins
    L = cfg.num_leaves
    l1, l2 = cfg.lambda_l1, cfg.lambda_l2
    has_cat = cat_mask is not None
    voting = cfg.parallelism == "voting" and axis_name is not None
    # Leaf-local half pass (the sparse analogue of the dense gather
    # ladder): leaf-wise growth usually splits a leaf the PREVIOUS step
    # just materialized, so its full (d, B, 3) histogram is still in hand
    # — carry the last step's two child panels, histogram only the
    # SMALLER child of the current split (a 3-channel pass instead of the
    # 6-channel both-sides pass) and derive the sibling by parent
    # subtraction. Opt-in: the carry keeps one (2, d, B, 3) panel
    # resident for the whole loop, a real cost at hashed-text width.
    # Voting mode is excluded — PV-tree's election works off LOCAL
    # histograms and reduces only elected candidates, so a carried
    # REDUCED parent panel has nothing to subtract from.
    use_ll = bool(cfg.leaf_local) and not voting
    if voting:
        k_local = min(cfg.top_k, d)
        k_global = min(2 * cfg.top_k, d)

    pos = jnp.arange(B)

    def gain_term(G, H):
        return _thresh_l1(G, l1) ** 2 / (H + l2)

    def _split_gain_parts(G, H, C, GL, HL, CL, fmask_sel, extra_valid):
        GT = G.sum(-1, keepdims=True)
        HT = H.sum(-1, keepdims=True)
        CT = C.sum(-1, keepdims=True)
        GR, HR, CR = GT - GL, HT - HL, CT - CL
        g = gain_term(GL, HL) + gain_term(GR, HR) - gain_term(GT, HT)
        valid = (
            (pos < B - 1)
            & (CL >= cfg.min_data_in_leaf)
            & (CR >= cfg.min_data_in_leaf)
            & (HL >= cfg.min_sum_hessian)
            & (HR >= cfg.min_sum_hessian)
            & extra_valid
            & (fmask_sel[..., None] > 0)
        )
        return jnp.where(valid, g, -jnp.inf)

    def numeric_gain(h, fmask_sel, cmask_sel=None):
        """(..., d_sel, B, 3) hists -> (..., d_sel, B) split gains.

        Numeric entry b = 'bin <= b' threshold; categorical entry b =
        best sorted-prefix of length b+1 (dense ``gain_table`` semantics)."""
        G, H, C = h[..., 0], h[..., 1], h[..., 2]
        cum = _prefix_bins(h)
        g_num = _split_gain_parts(G, H, C, cum[..., 0], cum[..., 1],
                                  cum[..., 2], fmask_sel, True)
        if not has_cat:
            return g_num
        ratio = G / (H + cfg.cat_smooth)
        order = jnp.argsort(-ratio, axis=-1)
        hs = jnp.take_along_axis(h, order[..., None], axis=-2)
        cums = _prefix_bins(hs)
        g_cat = _split_gain_parts(G, H, C, cums[..., 0], cums[..., 1],
                                  cums[..., 2], fmask_sel,
                                  pos + 1 <= cfg.max_cat_threshold)
        cm = cat_mask if cmask_sel is None else cmask_sel
        return jnp.where(cm[..., None] > 0, g_cat, g_num)

    def best_of_children(h2):
        """(2, d, B, 3) child hists -> per-child (gain, feat, bin).

        'data' mode: ``h2`` arrives fully psum'd, evaluate directly.
        'voting' mode: ``h2`` is local — vote top-k features per child, psum
        votes, reduce only the elected 2k candidates (PV-tree)."""
        if not voting:
            gain = numeric_gain(h2, feature_mask)          # (2, d, B)
            flat = gain.reshape(2, d * B)
            idx = jnp.argmax(flat, axis=-1)
            bg = jnp.take_along_axis(flat, idx[:, None], axis=-1)[:, 0]
            return bg, (idx // B).astype(jnp.int32), (idx % B).astype(jnp.int32)
        local_gain = numeric_gain(h2, feature_mask)        # (2, d, B)
        per_feat = local_gain.max(-1)                      # (2, d)
        topk_idx = lax.top_k(per_feat, k_local)[1]         # (2, k)
        votes = jnp.zeros((2, d), jnp.float32).at[
            jnp.arange(2)[:, None], topk_idx].add(1.0)  # SMT101: pin dtype
        votes = lax.psum(votes, axis_name)
        sel = lax.top_k(votes, k_global)[1]                # (2, 2k)
        cand = jnp.take_along_axis(h2, sel[:, :, None, None], axis=1)
        cand = lax.psum(cand, axis_name)                   # (2, 2k, B, 3)
        fmask_sel = jnp.take(feature_mask, sel)            # (2, 2k)
        cmask_sel = jnp.take(cat_mask, sel) if has_cat else None
        gain = numeric_gain(cand, fmask_sel, cmask_sel)    # (2, 2k, B)
        flat = gain.reshape(2, k_global * B)
        idx = jnp.argmax(flat, axis=-1)
        bg = jnp.take_along_axis(flat, idx[:, None], axis=-1)[:, 0]
        feat = jnp.take_along_axis(sel, (idx // B)[:, None], axis=1)[:, 0]
        return bg, feat.astype(jnp.int32), (idx % B).astype(jnp.int32)

    ghc_all = jnp.stack([grad * row_weight, hess * row_weight, row_weight],
                        axis=-1)

    def split_and_summarize(side):
        """side (n,) {0 left, 1 right, 2 inactive} -> child summaries +
        totals + the (possibly reduced) child histograms themselves."""
        h2, totals = sparse_histogram_split(sb, ghc_all, side)
        if axis_name is not None:
            totals = lax.psum(totals, axis_name)
            if not voting:
                h2 = lax.psum(h2, axis_name)
        bg, bf, bb = best_of_children(h2)
        return bg, bf, bb, totals, h2

    nnz_pad = sb.rows.shape[0]

    def leaf_feature_hist(f, member):
        """(B, 3) [G, H, count] histogram of ONE feature over one leaf's
        rows — O(max_run) bounded gather plus a B-cell scatter; the
        implicit-zero residual lands in the feature's zero bin. Used only to
        derive a categorical split's left set at apply time (this grower
        keeps no resident histograms to reorder)."""
        ghc = jnp.stack([grad * row_weight, hess * row_weight, row_weight],
                        axis=-1) * member.astype(jnp.float32)[:, None]
        ghc_pad = jnp.concatenate([ghc, jnp.zeros((1, 3), jnp.float32)],
                                  axis=0)
        start = jnp.take(sb.starts, f).astype(jnp.int32)
        cnt = jnp.take(sb.starts, f + 1).astype(jnp.int32) - start
        j = jnp.arange(sb.max_run, dtype=jnp.int32)
        valid = j < cnt
        pidx = jnp.clip(start + j, 0, max(nnz_pad - 1, 0))
        rows_f = jnp.where(valid, jnp.take(sb.rows, pidx), n)
        bins_f = jnp.where(valid, jnp.take(sb.bins, pidx), 0)
        panel = jnp.take(ghc_pad, rows_f, axis=0)   # pad/non-member rows -> 0
        hist = jnp.zeros((B, 3), jnp.float32).at[bins_f].add(panel)
        tot = ghc.sum(0)
        return hist.at[jnp.take(sb.zero_bin, f)].add(tot - hist.sum(0))

    def step(s, state):
        if use_ll:
            (node, best_gain, best_feat, best_bin, G_leaf, H_leaf,
             parent, feat, bin_, gains, cat_sets, depth,
             carry_h2, carry_ids) = state
        else:
            (node, best_gain, best_feat, best_bin, G_leaf, H_leaf,
             parent, feat, bin_, gains, cat_sets, depth) = state
        leaf_gain = best_gain
        if cfg.max_depth > 0:
            leaf_gain = jnp.where(depth < cfg.max_depth, leaf_gain, -jnp.inf)
        l = jnp.argmax(leaf_gain)
        g_best = leaf_gain[l]
        # both operands are static config floats: a host-side max keeps the
        # threshold out of the traced program (a traced jnp.maximum of two
        # python floats is an f64 op under x64 — device lint SMT101)
        ok = g_best > max(cfg.min_gain_to_split, 0.0)
        f_sel = best_feat[l]
        b_sel = best_bin[l]
        col = sparse_column(sb, f_sel, n)
        member = node == l
        if has_cat:
            is_cat = jnp.take(cat_mask, f_sel) > 0
            # the O(max_run) gather only pays on categorical splits (every
            # shard picks the same f_sel from the reduced decision, so the
            # branch is uniform); the psum stays OUTSIDE the cond so the
            # collective schedule is shard-independent
            row = lax.cond(
                is_cat, lambda: leaf_feature_hist(f_sel, member),
                lambda: jnp.zeros((B, 3), jnp.float32))
            if axis_name is not None:
                row = lax.psum(row, axis_name)
            ratio = row[:, 0] / (row[:, 1] + cfg.cat_smooth)
            rank = jnp.argsort(jnp.argsort(-ratio))
            # zero-mass bins stay OUT of the left set (dense split_detail:
            # unseen categories route right, matching LightGBM bitsets)
            in_set = (rank <= b_sel) & (row[:, 2] > 0)
            go_left = jnp.where(is_cat, jnp.take(in_set, col), col <= b_sel)
        else:
            is_cat = jnp.zeros((), jnp.bool_)
            in_set = jnp.zeros((B,), jnp.bool_)
            go_left = col <= b_sel
        went_right = member & ~go_left & ok
        node = jnp.where(went_right, s + 1, node)
        side = jnp.where(member & ok,
                         jnp.where(go_left, 0, 1), 2).astype(jnp.int32)
        if use_ll:
            # leaf-local half-pass: when the leaf being split is one of the
            # two children produced by the PREVIOUS step, its reduced
            # histogram is already in the carry — histogram only the smaller
            # child and derive the sibling as parent - small.  ``l`` (and so
            # ``hit``) comes from the REDUCED summaries, uniform across
            # shards, and no collective sits inside either cond branch; the
            # psum happens once, outside.
            cnt_l = (side == 0).sum().astype(jnp.int32)
            cnt_r = (side == 1).sum().astype(jnp.int32)
            if axis_name is not None:
                cnt_l = lax.psum(cnt_l, axis_name)
                cnt_r = lax.psum(cnt_r, axis_name)
            smaller_right = cnt_r <= cnt_l
            hit = (l == carry_ids[0]) | (l == carry_ids[1])
            parent_h = jnp.where(l == carry_ids[0], carry_h2[0], carry_h2[1])
            mask_small = jnp.where(smaller_right, side == 1, side == 0)

            def _half(_):
                h_small, _t = sparse_histogram_side(sb, ghc_all, mask_small)
                return jnp.stack([h_small, h_small])

            def _full(_):
                h2_loc, _t = sparse_histogram_split(sb, ghc_all, side)
                return h2_loc

            h2 = lax.cond(hit, _half, _full, None)
            if axis_name is not None:
                h2 = lax.psum(h2, axis_name)
            small = h2[0]
            h2_hit = jnp.where(smaller_right,
                               jnp.stack([parent_h - small, small]),
                               jnp.stack([small, parent_h - small]))
            h2 = jnp.where(hit, h2_hit, h2)
            # totals from masked panel sums directly — bitwise identical to
            # the full pass's ghc6 channel sums, so leaf values never depend
            # on which histogram path ran
            totals = jnp.stack(
                [(ghc_all * (side == 0).astype(jnp.float32)[:, None]).sum(0),
                 (ghc_all * (side == 1).astype(jnp.float32)[:, None]).sum(0)])
            if axis_name is not None:
                totals = lax.psum(totals, axis_name)
            c_gain, c_feat, c_bin = best_of_children(h2)
            carry_h2 = jnp.where(ok, h2, carry_h2)
            new_ids = jnp.stack([l.astype(jnp.int32),
                                 jnp.asarray(s + 1, jnp.int32)])
            carry_ids = jnp.where(ok, new_ids, carry_ids)
        else:
            (c_gain, c_feat, c_bin), totals = (lambda r: (r[:3], r[3]))(
                split_and_summarize(side))
        upd = lambda a, v0, v1: a.at[l].set(v0).at[s + 1].set(v1)
        best_gain = jnp.where(ok, upd(best_gain, c_gain[0], c_gain[1]),
                              best_gain)
        best_feat = jnp.where(ok, upd(best_feat, c_feat[0], c_feat[1]),
                              best_feat)
        best_bin = jnp.where(ok, upd(best_bin, c_bin[0], c_bin[1]), best_bin)
        G_leaf = jnp.where(ok, upd(G_leaf, totals[0, 0], totals[1, 0]), G_leaf)
        H_leaf = jnp.where(ok, upd(H_leaf, totals[0, 1], totals[1, 1]), H_leaf)
        parent = parent.at[s].set(jnp.where(ok, l, -1).astype(jnp.int32))
        feat = feat.at[s].set(f_sel.astype(jnp.int32))
        bin_ = bin_.at[s].set(
            jnp.where(is_cat, -1, b_sel).astype(jnp.int32))
        gains = gains.at[s].set(jnp.where(ok, g_best, 0.0).astype(jnp.float32))
        cat_sets = cat_sets.at[s].set(
            (in_set & is_cat & ok).astype(jnp.int8))
        child_depth = jnp.where(ok, depth[l] + 1, depth[l]).astype(jnp.int32)
        depth = jnp.where(ok, depth.at[s + 1].set(child_depth)
                          .at[l].set(child_depth), depth)
        out = (node, best_gain, best_feat, best_bin, G_leaf, H_leaf,
               parent, feat, bin_, gains, cat_sets, depth)
        if use_ll:
            out = out + (carry_h2, carry_ids)
        return out

    # root: everything on side 0
    root_side = jnp.zeros(n, jnp.int32)
    r_gain, r_feat, r_bin, r_tot, r_h2 = split_and_summarize(root_side)
    neg = jnp.full(L, -jnp.inf, jnp.float32)
    state0 = (
        jnp.zeros(n, dtype=jnp.int32),
        neg.at[0].set(r_gain[0].astype(jnp.float32)),
        jnp.zeros(L, jnp.int32).at[0].set(r_feat[0]),
        jnp.zeros(L, jnp.int32).at[0].set(r_bin[0]),
        jnp.zeros(L, jnp.float32).at[0].set(r_tot[0, 0]),
        jnp.zeros(L, jnp.float32).at[0].set(r_tot[0, 1]),
        jnp.full(L - 1, -1, dtype=jnp.int32),
        jnp.zeros(L - 1, dtype=jnp.int32),
        jnp.zeros(L - 1, dtype=jnp.int32),
        jnp.zeros(L - 1, dtype=jnp.float32),
        jnp.zeros((L - 1, B), dtype=jnp.int8),
        jnp.zeros(L, dtype=jnp.int32),
    )
    if use_ll:
        # the root split put EVERY row on side 0, so r_h2[0] is the full
        # root histogram: seeding slot 0 with it (slot 1 dead at -1) makes
        # step 0's split of leaf 0 a carry hit with parent = root
        state0 = state0 + (jnp.stack([r_h2[0], jnp.zeros_like(r_h2[0])]),
                           jnp.asarray([0, -1], jnp.int32))
        (node, _bg, _bf, _bb, G_leaf, H_leaf, parent, feat, bin_, gains,
         cat_sets, _depth, _ch, _ci) = lax.fori_loop(0, L - 1, step, state0)
    else:
        (node, _bg, _bf, _bb, G_leaf, H_leaf, parent, feat, bin_, gains,
         cat_sets, _depth) = lax.fori_loop(0, L - 1, step, state0)

    leaf_value = -_thresh_l1(G_leaf, l1) / (H_leaf + l2)
    leaf_value = jnp.where(H_leaf > 0, leaf_value, 0.0)
    if cfg.max_delta_step > 0:
        leaf_value = jnp.clip(leaf_value, -cfg.max_delta_step,
                              cfg.max_delta_step)
    return (GrownTree(parent, feat, bin_, gains, leaf_value, H_leaf,
                      cat_sets), node)


def predict_binned(tree: GrownTree, binned):
    """Replay splits over a binned matrix -> leaf index per row (device or host).

    ``binned``: (n, d) int matrix or a :class:`SparseBinned` (column gathers
    go through the bounded per-feature gather path)."""
    import jax.numpy as jnp

    from .sparse import SparseBinned, sparse_column

    sparse = isinstance(binned, SparseBinned)
    n = binned.n if sparse else binned.shape[0]
    node = jnp.zeros(n, dtype=jnp.int32)
    L1 = tree.parent.shape[0]
    for s in range(L1):
        p = tree.parent[s]
        if sparse:
            col = sparse_column(binned, tree.feature[s], n)
        else:
            col = jnp.take(binned, tree.feature[s], axis=1).astype(jnp.int32)
        is_cat = tree.bin[s] < 0
        go_left_cat = jnp.take(tree.cat_set[s], col) > 0
        go_left = jnp.where(is_cat, go_left_cat, col <= tree.bin[s])
        go_right = (node == p) & ~go_left & (p >= 0)
        node = jnp.where(go_right, s + 1, node)
    return node
