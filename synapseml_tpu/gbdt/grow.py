"""Leaf-wise tree growth, fully jit-compiled.

Reference analogue: the C++ ``SerialTreeLearner``/``DataParallelTreeLearner`` driven
per-iteration from ``TrainUtils.trainCore`` (``TrainUtils.scala:92-160``). TPU design:

- fixed shapes everywhere: ``num_leaves`` slots, ``lax.fori_loop`` over the
  ``num_leaves - 1`` split steps; an inert step (gain <= min_gain) records parent -1;
- the tree is a *replay list* of splits (parent leaf, feature, bin), not a pointer
  tree: prediction replays the splits in order with vectorized gathers — no
  data-dependent control flow, so it jits and vmaps (multiclass) cleanly;
- leaf-wise like LightGBM: each step splits the best-gain leaf anywhere in the tree;
- parent-subtract: each step computes ONE masked histogram (the new right child) and
  derives the left side by subtraction — same trick as LightGBM's sibling subtract;
- distributed: pass ``axis_name`` and every histogram is ``psum``-reduced over that
  mesh axis, so all shards take identical split decisions (the reference ships
  histogram buffers over its TCP ring for the same purpose).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import numpy as np

from .histogram import histogram

__all__ = ["TreeConfig", "GrownTree", "grow_tree", "predict_binned"]


class TreeConfig(NamedTuple):
    """Static (compile-time) growth hyperparameters."""

    n_bins: int
    num_leaves: int = 31
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_data_in_leaf: float = 20.0
    min_sum_hessian: float = 1e-3
    min_gain_to_split: float = 0.0
    hist_method: str = "auto"
    hist_chunk: int = 2048


class GrownTree(NamedTuple):
    """Replay-list tree: split ``s`` turns leaf ``parent[s]`` into (parent[s], s+1)."""

    parent: "np.ndarray"  # (L-1,) int32; -1 = inert step
    feature: "np.ndarray"  # (L-1,) int32
    bin: "np.ndarray"  # (L-1,) int32 — split is 'bin <= b goes left'
    gain: "np.ndarray"  # (L-1,) f32
    leaf_value: "np.ndarray"  # (L,) f32  (unshrunk; learning rate applied by caller)
    leaf_hess: "np.ndarray"  # (L,) f32 — leaf hessian mass (cover), for contribs


def _thresh_l1(g, l1):
    import jax.numpy as jnp

    return jnp.sign(g) * jnp.maximum(jnp.abs(g) - l1, 0.0)


def grow_tree(binned, grad, hess, row_weight, feature_mask, cfg: TreeConfig,
              axis_name: Optional[str] = None):
    """Grow one tree. Returns (GrownTree of device arrays, node_of_row (n,) int32).

    ``binned`` (n, d) int32; ``grad``/``hess``/``row_weight`` (n,) f32;
    ``feature_mask`` (d,) f32 in {0,1} (feature_fraction sampling).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    n, d = binned.shape
    L, B = cfg.num_leaves, cfg.n_bins
    l1, l2 = cfg.lambda_l1, cfg.lambda_l2

    def hist_of(weight):
        h = histogram(binned, grad, hess, weight, B,
                      method=cfg.hist_method, chunk=cfg.hist_chunk)
        if axis_name is not None:
            h = lax.psum(h, axis_name)
        return h

    def gain_term(G, H):
        return _thresh_l1(G, l1) ** 2 / (H + l2)

    def best_splits(hists, n_active):
        """Best (gain, feature, bin) per leaf from its histogram. (L,) each."""
        G = hists[..., 0]  # (L, d, B)
        H = hists[..., 1]
        C = hists[..., 2]
        GL = jnp.cumsum(G, axis=-1)
        HL = jnp.cumsum(H, axis=-1)
        CL = jnp.cumsum(C, axis=-1)
        GT = GL[..., -1:]
        HT = HL[..., -1:]
        CT = CL[..., -1:]
        GR, HR, CR = GT - GL, HT - HL, CT - CL
        gain = gain_term(GL, HL) + gain_term(GR, HR) - gain_term(GT, HT)
        valid = (
            (jnp.arange(B) < B - 1)  # split point must leave a non-empty right range
            & (CL >= cfg.min_data_in_leaf)
            & (CR >= cfg.min_data_in_leaf)
            & (HL >= cfg.min_sum_hessian)
            & (HR >= cfg.min_sum_hessian)
            & (feature_mask[None, :, None] > 0)
        )
        gain = jnp.where(valid, gain, -jnp.inf)
        flat = gain.reshape(L, d * B)
        idx = jnp.argmax(flat, axis=-1)
        best_gain = jnp.take_along_axis(flat, idx[:, None], axis=-1)[:, 0]
        active = jnp.arange(L) < n_active
        return jnp.where(active, best_gain, -jnp.inf), idx // B, idx % B

    def step(s, state):
        node, hists, parent, feat, bin_, gains = state
        leaf_gain, leaf_f, leaf_b = best_splits(hists, s + 1)
        l = jnp.argmax(leaf_gain)
        g_best = leaf_gain[l]
        ok = g_best > jnp.maximum(cfg.min_gain_to_split, 0.0)
        f_sel = leaf_f[l]
        b_sel = leaf_b[l]
        col = jnp.take(binned, f_sel, axis=1)
        went_right = (node == l) & (col > b_sel) & ok
        node = jnp.where(went_right, s + 1, node)
        child = hist_of(row_weight * went_right.astype(jnp.float32))
        hists = jnp.where(
            ok,
            hists.at[s + 1].set(child).at[l].add(-child),
            hists,
        )
        parent = parent.at[s].set(jnp.where(ok, l, -1).astype(jnp.int32))
        feat = feat.at[s].set(f_sel.astype(jnp.int32))
        bin_ = bin_.at[s].set(b_sel.astype(jnp.int32))
        gains = gains.at[s].set(jnp.where(ok, g_best, 0.0).astype(jnp.float32))
        return node, hists, parent, feat, bin_, gains

    root_hist = hist_of(row_weight)
    hists0 = jnp.zeros((L, d, B, 3), dtype=jnp.float32).at[0].set(root_hist)
    state0 = (
        jnp.zeros(n, dtype=jnp.int32),
        hists0,
        jnp.full(L - 1, -1, dtype=jnp.int32),
        jnp.zeros(L - 1, dtype=jnp.int32),
        jnp.zeros(L - 1, dtype=jnp.int32),
        jnp.zeros(L - 1, dtype=jnp.float32),
    )
    node, hists, parent, feat, bin_, gains = lax.fori_loop(0, L - 1, step, state0)

    # leaf totals: sum over bins of any one feature covers every row exactly once
    G_leaf = hists[:, 0, :, 0].sum(-1)
    H_leaf = hists[:, 0, :, 1].sum(-1)
    leaf_value = -_thresh_l1(G_leaf, l1) / (H_leaf + l2)
    leaf_value = jnp.where(H_leaf > 0, leaf_value, 0.0)
    return GrownTree(parent, feat, bin_, gains, leaf_value, H_leaf), node


def predict_binned(tree: GrownTree, binned):
    """Replay splits over a binned matrix -> leaf index per row (device or host)."""
    import jax.numpy as jnp

    n = binned.shape[0]
    node = jnp.zeros(n, dtype=jnp.int32)
    L1 = tree.parent.shape[0]
    for s in range(L1):
        p = tree.parent[s]
        col = jnp.take(binned, tree.feature[s], axis=1)
        go_right = (node == p) & (col > tree.bin[s]) & (p >= 0)
        node = jnp.where(go_right, s + 1, node)
    return node
