"""Quantile feature binning — the host-side ``Dataset`` construction step.

Reference analogue: LightGBM's ``BinMapper``/``Dataset`` built through
``LGBM_DatasetCreateFromMat`` after the chunked marshalling in
``lightgbm/.../dataset/DatasetAggregator.scala``. Binning runs once on the host in
numpy (data prep, not MXU work); the binned int matrix is what ships to the TPU.

Bin layout (per feature): bins ``0..n_bins-1`` cover finite values by quantile
ranges; missing values (NaN) map to the LAST bin (LightGBM's ``use_missing`` default
puts NaN in its own bin). Split "value <= upper_edge[b]" == "bin <= b"; NaN compares
false so missing rows follow the right/greater branch.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

__all__ = ["BinMapper"]


class BinMapper:
    """Fit per-feature quantile bin edges; transform float matrices to int8/16 bins.

    ``categorical_features`` lists column indices treated as categories: each
    distinct value (by descending count, up to ``max_bin``) gets its own bin,
    unseen values and NaN map to the missing bin, and the grower uses
    sorted-set splits instead of threshold splits for them (reference:
    LightGBM categorical handling exercised by ``VerifyLightGBMClassifier``
    "categorical handling").
    """

    def __init__(self, max_bin: int = 255, sample_cnt: int = 200_000, seed: int = 0,
                 categorical_features: Optional[List[int]] = None,
                 max_bin_by_feature: Optional[List[int]] = None):
        if max_bin < 2:
            raise ValueError(f"max_bin must be >= 2, got {max_bin}")
        if sample_cnt < 1:
            # an empty sample fits [inf]-only edges for every feature and the
            # model silently degenerates (LightGBM rejects
            # bin_construct_sample_cnt <= 0 the same way)
            raise ValueError(f"sample_cnt must be >= 1, got {sample_cnt}")
        self.max_bin = int(max_bin)
        self.sample_cnt = int(sample_cnt)
        self.seed = seed
        self.categorical_features = sorted(set(categorical_features or []))
        # per-feature override of max_bin (LightGBM maxBinByFeature); entries
        # <= 0 fall back to max_bin
        self.max_bin_by_feature = ([int(b) for b in max_bin_by_feature]
                                   if max_bin_by_feature else None)
        if self.max_bin_by_feature and any(
                0 < b < 2 for b in self.max_bin_by_feature):
            raise ValueError("max_bin_by_feature entries must be >= 2 (or <= 0 "
                             "for the max_bin default)")
        self.upper_edges: Optional[List[np.ndarray]] = None  # per-feature ascending edges
        self.cat_values: dict = {}  # feature -> ascending array of category values
        self.n_features: Optional[int] = None

    def _feature_max_bin(self, j: int) -> int:
        mbf = self.max_bin_by_feature
        if mbf and j < len(mbf) and mbf[j] > 0:
            return mbf[j]
        return self.max_bin

    @property
    def _effective_max_bin(self) -> int:
        if self.max_bin_by_feature:
            return max(self.max_bin, *[b for b in self.max_bin_by_feature
                                       if b > 0] or [self.max_bin])
        return self.max_bin

    @property
    def n_bins(self) -> int:
        """Total bins per feature including the reserved missing bin."""
        return self._effective_max_bin + 1

    @property
    def missing_bin(self) -> int:
        return self._effective_max_bin

    def sample_indices(self, n: int) -> Optional[np.ndarray]:
        """Row indices ``fit`` would subsample for edge estimation (None =
        all rows). The single source of truth — GBDTDataset's device path
        pulls exactly these rows so both construction paths fit identical
        edges."""
        if n <= self.sample_cnt:
            return None
        rng = np.random.default_rng(self.seed)
        return rng.choice(n, size=self.sample_cnt, replace=False)

    def fit(self, x: np.ndarray) -> "BinMapper":
        x = np.asarray(x, dtype=np.float64)
        n, d = x.shape
        if self.max_bin_by_feature and len(self.max_bin_by_feature) != d:
            # a typo'd list would silently inflate n_bins (and every
            # histogram buffer) via _effective_max_bin
            raise ValueError(
                f"max_bin_by_feature has {len(self.max_bin_by_feature)} "
                f"entries for {d} features")
        idx = self.sample_indices(n)
        sample = x if idx is None else x[idx]
        edges: List[np.ndarray] = []
        self.cat_values = {}
        for j in range(d):
            col = sample[:, j]
            col = col[np.isfinite(col)]
            if j in self.categorical_features:
                vals, counts = np.unique(col, return_counts=True)
                fmb = self._feature_max_bin(j)
                if len(vals) > fmb:  # keep the most frequent categories
                    keep = np.argsort(-counts, kind="stable")[: fmb]
                    vals = vals[keep]
                self.cat_values[j] = np.sort(vals)
                edges.append(np.array([np.inf]))  # placeholder, unused for cat
                continue
            if col.size == 0:
                edges.append(np.array([np.inf]))
                continue
            uniq = np.unique(col)
            fmb = self._feature_max_bin(j)
            if len(uniq) <= fmb:
                # exact: one bin per distinct value; upper edge = midpoint to next
                ue = np.empty(len(uniq))
                ue[:-1] = (uniq[:-1] + uniq[1:]) / 2
                ue[-1] = np.inf
                edges.append(ue)
            else:
                qs = np.quantile(col, np.linspace(0, 1, fmb + 1)[1:-1])
                ue = np.unique(qs)
                edges.append(np.concatenate([ue, [np.inf]]))
        self.upper_edges = edges
        self.n_features = d
        return self

    def transform(self, x: np.ndarray) -> np.ndarray:
        """Float matrix -> int32 bin matrix (NaN -> missing bin)."""
        if self.upper_edges is None:
            raise RuntimeError("BinMapper.transform called before fit")
        x = np.asarray(x, dtype=np.float64)
        n, d = x.shape
        if d != self.n_features:
            raise ValueError(f"expected {self.n_features} features, got {d}")
        out = np.empty((n, d), dtype=np.int32)
        for j in range(d):
            col = x[:, j]
            if j in self.cat_values:
                vals = self.cat_values[j]
                idx = np.searchsorted(vals, col)
                idx = np.clip(idx, 0, max(len(vals) - 1, 0))
                known = np.isfinite(col) & (len(vals) > 0)
                if len(vals):
                    known &= vals[idx] == col
                out[:, j] = np.where(known, idx, self.missing_bin)
                continue
            out[:, j] = np.searchsorted(self.upper_edges[j], col, side="left")
            miss = ~np.isfinite(col)
            # +inf searches past the last edge; clamp, then stamp NaN into its bin
            np.clip(out[:, j], 0, len(self.upper_edges[j]) - 1, out=out[:, j])
            if miss.any():
                out[miss, j] = self.missing_bin
        return out

    def fit_transform(self, x: np.ndarray) -> np.ndarray:
        return self.fit(x).transform(x)

    def bin_upper_value(self, feature: int, b: np.ndarray) -> np.ndarray:
        """Raw-value threshold for split 'bin <= b' (used by tree predict on raw x).

        NaN for categorical features (their splits are set-based, not threshold)."""
        if feature in self.cat_values:
            return np.full(np.shape(b), np.nan) if np.ndim(b) else np.nan
        ue = self.upper_edges[feature]
        return ue[np.clip(b, 0, len(ue) - 1)]

    def to_dict(self) -> dict:
        return {
            "max_bin": self.max_bin,
            "max_bin_by_feature": self.max_bin_by_feature,
            "sample_cnt": self.sample_cnt,
            "seed": self.seed,
            "upper_edges": [e.tolist() for e in (self.upper_edges or [])],
            "categorical_features": self.categorical_features,
            "cat_values": {str(k): v.tolist() for k, v in self.cat_values.items()},
        }

    @staticmethod
    def from_dict(d: dict) -> "BinMapper":
        m = BinMapper(max_bin=d["max_bin"], sample_cnt=d["sample_cnt"], seed=d["seed"],
                      categorical_features=d.get("categorical_features"),
                      max_bin_by_feature=d.get("max_bin_by_feature"))
        if d.get("upper_edges"):
            m.upper_edges = [np.asarray(e) for e in d["upper_edges"]]
            m.n_features = len(m.upper_edges)
        m.cat_values = {int(k): np.asarray(v)
                        for k, v in (d.get("cat_values") or {}).items()}
        return m


def bin_dtype(n_bins: int):
    """Narrowest integer dtype holding bin ids (shared by the trainer's
    transfer path and GBDTDataset's cached device buffer — they must agree
    or jitted steps retrace on dtype)."""
    if n_bins <= 127:
        return np.int8
    if n_bins <= 32767:
        return np.int16
    return np.int32
